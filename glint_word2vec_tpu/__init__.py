"""glint_word2vec_tpu — a TPU-native framework for very-large-vocabulary word2vec.

A ground-up JAX/XLA/Pallas/pjit redesign of the capabilities of glint-word2vec
(Spark + Glint parameter servers, see /root/reference): skip-gram negative
sampling (SGNS) and CBOW trained fully in-core on a TPU mesh.

Architecture (vs. the reference, cited as file:line into the reference repo):

- The async parameter-server ``dotprod``/``adjust`` round-trips
  (mllib/feature/ServerSideGlintWord2Vec.scala:417-429) collapse into a single
  synchronous ``jax.jit`` SGNS step (:mod:`glint_word2vec_tpu.ops.sgns`).
- The PS-sharded input/output embedding matrices (``BigWord2VecMatrix``,
  README.md:69) become GSPMD-sharded ``jax.Array`` pairs over an ICI mesh
  (:mod:`glint_word2vec_tpu.parallel`).
- The server-resident unigram negative-sampling table (unigramTableSize,
  mllib:81,234-244) becomes an O(vocab) on-device alias table sampled with
  ``jax.random`` (:mod:`glint_word2vec_tpu.ops.sampler`).
- The Spark RDD subsample/window pipeline (mllib:371-390) becomes a vectorized
  NumPy host pipeline emitting fixed-shape padded batches
  (:mod:`glint_word2vec_tpu.data.pipeline`).
- Model ops — transform, sentence averaging, findSynonyms/analogy, norms,
  matvec (mllib:460-669, ml:322-497) — are jitted gathers/reductions on the
  sharded arrays (:mod:`glint_word2vec_tpu.models`).
- Persistence keeps the reference's on-disk contract: matrix shards + a
  ``words`` one-word-per-line sidecar + params metadata (mllib:493-498,714-715).

Module map: ``data/`` (vocab + host pipeline), ``ops/`` (SGNS/CBOW steps, sampler,
pallas kernels), ``parallel/`` (mesh + sharding), ``models/`` (model & estimator API),
``train/`` (trainer, checkpoint).
"""

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.vocab import Vocabulary, build_vocab
from glint_word2vec_tpu.models import (
    ServerSideGlintWord2Vec,
    ServerSideGlintWord2VecModel,
    Word2Vec,
    Word2VecModel,
)

__version__ = "0.1.0"

__all__ = [
    "Word2VecConfig",
    "Vocabulary",
    "build_vocab",
    "Word2Vec",
    "Word2VecModel",
    "ServerSideGlintWord2Vec",
    "ServerSideGlintWord2VecModel",
    "__version__",
]
