"""R8 bad trainer half: three dispatch-only refusals — one with no config
twin at all (cbow x use_pallas), one 'covered' only by a single-knob range
check (cbow x negative_pool), which is not coverage, and one on a NEW
stabilizer knob (use_pallas x max_row_norm) whose range check in config is
likewise not combination coverage."""


class Trainer:
    def _build_step(self):
        cfg = self.config
        if cfg.use_pallas:
            if cfg.cbow:
                raise ValueError("use_pallas is SGNS-only")
            if cfg.max_row_norm:
                raise ValueError("stabilizers are XLA-path only")
        if cfg.cbow:
            if cfg.negative_pool == 0:
                raise ValueError("cbow needs the shared pool here")
        return None
