"""Multi-host (multi-process) scaffolding — the G1/G8 replacement at pod scale.

The reference bootstraps a parameter-server cluster across Spark executors
(``Client.runOnSpark``, mllib:354-360,718) and moves everything over Akka RPC. Here a
multi-host run is N identical JAX processes (one per TPU host) joined into ONE global
device mesh: ``jax.distributed.initialize`` wires the coordination service, training
collectives ride ICI/DCN inside the jitted step (GSPMD), and only the per-host input
feed crosses the host boundary.

Input-feed strategy: by default (``config.shard_input=True``) each process generates
only its own 1/N of the sentence stream — ``epoch_batches(shard=process_index,
num_shards=process_count)``, the repartition analog (mllib:345) — and one
``process_allgather`` per dispatch round assembles the identical global batch on every
process (``Trainer._fit_sharded``: the gather rides the device interconnect; word-clock
deltas travel with it so every process computes identical alphas, and per-process alive
flags give deadlock-free lockstep when streams end unevenly). Host pipeline work
therefore scales 1/N with hosts. ``shard_input=False`` selects the zero-coordination
fallback: every process regenerates the full stream and :func:`put_global` carves out
its devices' rows — redundant host work, no collectives outside the step.

Launch contract (one command per host, mirroring ``jax.distributed`` conventions):

    GLINT_COORDINATOR=host0:12355 GLINT_NUM_PROCESSES=16 GLINT_PROCESS_ID=$i \
        python train.py ...

or pass the same values to :func:`initialize` explicitly. On Cloud TPU VMs with the
standard metadata, plain ``initialize()`` auto-detects everything.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, Optional

import jax
import numpy as np

logger = logging.getLogger("glint_word2vec_tpu")

_ENV_COORD = "GLINT_COORDINATOR"
_ENV_NPROC = "GLINT_NUM_PROCESSES"
_ENV_PID = "GLINT_PROCESS_ID"


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids=None,
) -> None:
    """Join this process to the global mesh. Call before any other JAX use.

    Resolution order: explicit args → ``GLINT_*`` env vars → JAX auto-detection
    (Cloud TPU metadata). A plain single-process run (no args, no env) is a no-op, so
    library code can call this unconditionally.
    """
    coordinator_address = coordinator_address or os.environ.get(_ENV_COORD)
    if num_processes is None and _ENV_NPROC in os.environ:
        num_processes = int(os.environ[_ENV_NPROC])
    if process_id is None and _ENV_PID in os.environ:
        process_id = int(os.environ[_ENV_PID])
    if coordinator_address is None and num_processes is None:
        logger.debug("distributed.initialize: single-process run, nothing to do")
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
    logger.info("distributed: process %d/%d, %d local + %d global devices",
                jax.process_index(), jax.process_count(),
                jax.local_device_count(), jax.device_count())


def is_multiprocess() -> bool:
    return jax.process_count() > 1


_GATHER_JIT = None


def _gather_plumbing():
    """(mesh sharding for per-process slices, replicated-output identity jit) of
    the cross-process gather — built once; shapes recompile per feed geometry,
    which is constant over a run."""
    global _GATHER_JIT
    if _GATHER_JIT is None:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        devices = np.array(jax.devices()).reshape(
            jax.process_count(), jax.local_device_count())
        mesh = Mesh(devices, ("processes", "local_devices"))
        _GATHER_JIT = (
            NamedSharding(mesh, P("processes")),
            jax.jit(lambda x: x, out_shardings=NamedSharding(mesh, P())),
        )
    return _GATHER_JIT


def allgather_start(host_tree):
    """Split-phase ``multihost_utils.process_allgather(tiled=False)``: launch
    the gather program for a pytree of per-process host arrays and return the
    (async) global jax.Arrays; :func:`allgather_fetch` blocks for the stacked
    numpy result (leading [process_count] axis, exactly the process_allgather
    layout).

    Why split: the one-round-ahead feed stager (trainer._one_ahead_iter) must
    LAUNCH the next round's gather at a pinned point in the cross-host
    program-launch order — before the current round's step dispatch — and only
    later block for its bytes, so the gather's wire transfer and the host-side
    decode overlap device compute instead of serializing after it.
    Single-process: no program at all, the "handle" is the stacked numpy array
    (makes the staged code path testable without a pod)."""
    if not is_multiprocess():
        return jax.tree.map(
            lambda x: np.expand_dims(np.asarray(x), 0), host_tree)
    sharding, ident = _gather_plumbing()

    def start(x):
        h = np.expand_dims(np.asarray(x), 0)
        bufs = [jax.device_put(h, d) for d in jax.local_devices()]
        garr = jax.make_array_from_single_device_arrays(
            (jax.process_count(),) + h.shape[1:], sharding, bufs)
        return ident(garr)

    return jax.tree.map(start, host_tree)


def allgather_fetch(handles):
    """Block for and decode the result of :func:`allgather_start`."""
    if not is_multiprocess():
        return handles
    return jax.tree.map(
        lambda a: np.asarray(a.addressable_data(0)), handles)


def local_sgd_delta_merge(start, local, axis: str, num_shards: int):
    """The local-SGD delta-merge collective (config.sync_every, docs/
    sharding.md §Local-SGD): reconcile ``num_shards`` diverged per-shard
    replicas with ONE psum over the named mesh ``axis``::

        merged = start + psum(local − start, axis) · (1 / num_shards)

    i.e. the mean of the per-shard deltas applied to the common window-start
    state. Call INSIDE a shard_map body (per-device view, named-axis psum) at
    the end of a ``sync_every=k`` owner-local window. Properties the callers
    rely on:

    - **Deterministic and replica-consistent.** The all-reduce delivers the
      bitwise-identical sum to every participant, and ``start`` is replicated
      across the axis, so the merged replicas are bit-identical — the data
      axis leaves the window exactly replicated again (the out_spec contract
      of the window program).
    - **Exact mean at power-of-2 shard counts.** ``1/num_shards`` is exact in
      binary for every mesh this repo ships (1/2/4/8 data shards), so the f64
      oracle tests can demand ~1e-11 agreement, not "close".
    - **Stabilizer-aware by construction.** Per-row clamps (max_row_norm)
      hold under the merge: each shard's rows satisfy ‖row‖ ≤ c, and the
      merged row is a convex combination of rows each within the ball, so
      ‖merged row‖ ≤ c — no post-merge re-clamp pass needed.
    - **One collective program at a time.** The psum rides inside the jitted
      window program that produced ``local`` — never a separate dispatch —
      so the XLA:CPU rendezvous-serialization rule the trainer enforces
      (trainer._sync_collectives) is preserved: the merge cannot race another
      program's collectives.

    ``num_shards == 1`` returns ``local`` unchanged (no collective compiled).
    The delta/psum/scale run in the params' own dtype — the same class of
    reduction the GSPMD backward's data-axis all-reduce performs per step,
    paid here once per k steps.
    """
    if num_shards == 1:
        return local
    import jax.numpy as jnp
    scale = 1.0 / float(num_shards)

    def merge(s, loc):
        delta = jax.lax.psum(loc - s, axis)
        return s + delta * jnp.asarray(scale, loc.dtype)

    return jax.tree.map(merge, start, local)


def put_global(sharding, host_arrays: Dict[str, np.ndarray]):
    """Place a dict of full (global-shape) host arrays onto sharding(s) that may span
    processes. ``sharding`` is either one sharding for every array or a dict keyed
    like ``host_arrays`` (arrays of different ranks need different specs).

    Single-process: plain ``device_put``. Multi-process: every process holds the same
    full host array (see module docstring) and ``make_array_from_callback`` carves out
    exactly the shards its local devices own — the ``make_array_from_process_local_data``
    pattern specialized to the replicated-pipeline feed.
    """
    def spec(k):
        return sharding[k] if isinstance(sharding, dict) else sharding

    if not is_multiprocess():
        return {k: jax.device_put(v, spec(k)) for k, v in host_arrays.items()}
    out = {}
    for k, v in host_arrays.items():
        arr = np.asarray(v)
        out[k] = jax.make_array_from_callback(
            arr.shape, spec(k), lambda idx, a=arr: a[idx])
    return out
