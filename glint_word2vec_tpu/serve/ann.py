"""IVF approximate-nearest-neighbor index over the trained embedding matrix.

The serving tier's fast arm (ROADMAP item 1): exact ``find_synonyms`` is a
full [V, D] matvec + top-k per batch — the right oracle, the wrong steady
state for millions-of-users traffic. This index buys a tunable
compute-vs-recall trade the classic IVF way:

- **build** (at load/checkpoint-publish time): unit-normalize the rows
  (cosine == dot on the unit sphere; zero-norm sharding-padding rows stay
  zero and can never enter a top-k), k-means a sampled subset into
  ``num_centroids`` coarse cells (seeded Lloyd iterations — deterministic:
  same matrix + seed → the same index), then assign every row to its
  nearest centroid, stored as one CSR-style inverted-list layout
  (``offsets [C+1]`` + ``rows [V]``);
- **search**: score the query against the C centroids, visit only the
  ``nprobe`` nearest cells, and rank the candidate rows — the scanned
  fraction is ~``nprobe / C`` of the vocabulary instead of 1.0;
- **recall is measured, not assumed**: the build samples rows as queries
  and scores the index against the EXACT full-scan oracle on the same
  normalized matrix; ``stats["recall_at_10"]`` travels with the index, so
  a geometry that breaks IVF's clustering assumption (e.g. a post-blowup
  matrix) is visible at publish time — and tools/eval_quality.py records
  the same number into EVAL_RUNS rows. Quantized builds are additionally
  GATED: a build whose measured recall falls below its resolved floor
  raises :class:`RecallFloorError` instead of publishing a silently
  degraded index (docs/serving.md §6).

Storage is pluggable (ISSUE 18, ROADMAP 1(c)): the inverted lists live in
one of three cell-contiguous layouts behind ``quant=``:

- ``"f32"`` — one float32 normalized copy in the packed-cell layout (the
  original arm; 4·D bytes/row, exact cosine scores);
- ``"int8"`` — per-row-scaled int8 codes (serve/quant.py), ~D bytes/row:
  a probed cell is one contiguous int8 block converted in-cache and
  scanned by a BLAS matvec, so DRAM traffic per candidate drops ~4×
  (the packed scan is bandwidth-bound — PERF.md §6);
- ``"pq"`` — product-quantized codes + per-subspace codebooks
  (Jégou et al., PAMI 2011), ~2·m bytes/row, scanned via per-query ADC
  lookup tables, with the top ``rerank`` candidates re-ranked against
  exact float rows fetched lazily from the index's row source.

Host-resident by design: search is numpy (BLAS matmuls over small
candidate sets) — it deliberately does not touch the device, so ANN
queries never contend with the exact arm's device dispatches or a
co-located trainer's collectives. The exact sharded top-k
(models/word2vec.py) remains the ground-truth oracle.
"""

from __future__ import annotations

import logging
import math
import time
from typing import Callable, Dict, Iterator, Optional, Tuple

import numpy as np

logger = logging.getLogger("glint_word2vec_tpu")

# chunk sizes bounding host scratch: assignment [chunk, C] and the exact-
# oracle [Q, chunk] score blocks stay under ~256 MB each
_ASSIGN_BLOCK_BYTES = 256 << 20
_ORACLE_BLOCK_BYTES = 256 << 20

# documented per-arm recall@10 floors the AUTO (-1) ``recall_floor``
# resolves to, measured on production-scale clustered embedding geometry
# (V >= 400k, tools/servebench.py — SERVEBENCH_r03): both quantized arms
# rely on their exact re-rank stage to hold these (int8's rescaled dots
# carry ~1e-2 relative error; PQ's ADC ordering scrambles inside dense
# clusters) — disabling re-rank (rerank=-1) forfeits the floor. f32 is
# never auto-gated — its recall is governed by the nprobe choice, and
# gating it would refuse every legitimately small-nprobe deployment.
# Toy-scale builds (chaos drills, unit tests) pass an explicit floor
# (0.0 disables) because IVF probe loss at tiny V dominates any
# quantization effect.
RECALL_FLOORS: Dict[str, float] = {"f32": 0.0, "int8": 0.99, "pq": 0.95}

QUANT_MODES = ("f32", "int8", "pq")


class RecallFloorError(RuntimeError):
    """A quantized index build measured recall below its resolved floor
    and refused to publish (docs/serving.md §6). Carries the measured
    value and the floor so callers (hot-reload, benches) can report both."""

    def __init__(self, quant: str, measured: float, floor: float):
        self.quant = quant
        self.measured = measured
        self.floor = floor
        super().__init__(
            f"{quant} index build refused: measured recall@10 "
            f"{measured:.4f} < floor {floor:.4f} — the matrix geometry "
            f"does not support this quantization arm at this nprobe; "
            f"raise nprobe/rerank, use a weaker arm (int8/f32), or pass "
            f"an explicit recall_floor to override")


def resolve_recall_floor(recall_floor: float, quant: str) -> float:
    """-1 = AUTO (the documented per-arm floor above); >= 0 explicit
    (0.0 disables the gate)."""
    if recall_floor is None or recall_floor < 0:
        return RECALL_FLOORS[quant]
    return float(recall_floor)


def _normalize_rows(m: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(unit rows, norms); zero-norm rows stay zero (cosine 0 everywhere —
    the same masking rule as the exact path's zero-norm handling)."""
    m = np.ascontiguousarray(m, dtype=np.float32)
    norms = np.linalg.norm(m, axis=1)
    out = m / np.maximum(norms, 1e-12)[:, None]
    return out, norms


def _argmax_rows(x: np.ndarray, centroids: np.ndarray) -> np.ndarray:
    """Nearest-centroid id per row of ``x`` (both unit-normalized), with the
    [chunk, C] score block bounded."""
    C = centroids.shape[0]
    chunk = max(1, _ASSIGN_BLOCK_BYTES // max(C * 4, 1))
    out = np.empty(x.shape[0], np.int32)
    for lo in range(0, x.shape[0], chunk):
        out[lo:lo + chunk] = np.argmax(
            x[lo:lo + chunk] @ centroids.T, axis=1).astype(np.int32)
    return out


def _topk_desc(scores: np.ndarray, k: int) -> np.ndarray:
    """Indices of the k largest entries, sorted descending by score (ties:
    ascending index — stable across runs)."""
    n = scores.shape[0]
    if k >= n:
        cand = np.arange(n)
    else:
        cand = np.argpartition(scores, n - k)[n - k:]
    return cand[np.lexsort((cand, -scores[cand]))][:k]


def _kmeans_unit(X: np.ndarray, C: int, rng, iters: int) -> np.ndarray:
    """Seeded Lloyd over unit rows (cosine assignment, re-normalized
    means, dead-cell repair from random training rows — deterministic:
    same X + rng state → the same centroids)."""
    centroids = X[rng.choice(X.shape[0], size=C, replace=False)].copy()
    for _ in range(max(iters, 1)):
        assign = _argmax_rows(X, centroids)
        sums = np.zeros_like(centroids)
        np.add.at(sums, assign, X)
        counts = np.bincount(assign, minlength=C)
        live = counts > 0
        sums[live] /= counts[live, None]
        dead = np.flatnonzero(~live)
        if dead.size:
            # re-seed empty cells from random training rows so every
            # cell stays live (classic Lloyd repair, deterministic)
            sums[dead] = X[rng.choice(X.shape[0], size=dead.size)]
        centroids, _ = _normalize_rows(sums)
    return centroids


class F32Storage:
    """The original packed-cell storage: one contiguous float32 normalized
    copy in inverted-list order. Scores are exact cosines."""

    kind = "f32"

    def __init__(self, packed: np.ndarray):
        self._packed = packed            # [V, D] unit rows, list order

    @property
    def nbytes(self) -> int:
        return int(self._packed.nbytes)

    def scanner(self, q: np.ndarray) -> Callable[[int, int], np.ndarray]:
        packed = self._packed

        def scan(lo: int, hi: int) -> np.ndarray:
            # one contiguous matvec per probed cell (packed layout)
            return packed[lo:hi] @ q

        return scan

    def reconstruct(self, pos) -> np.ndarray:
        return self._packed[pos]

    def block(self, lo: int, hi: int) -> np.ndarray:
        """Exact normalized rows [lo:hi) in PACKED order (oracle scans)."""
        return self._packed[lo:hi]


class MatrixRowFetch:
    """Lazy exact-row source over a borrowed in-memory matrix: rows are
    normalized per fetch, nothing beyond the caller's own matrix is held.
    The quantized arms' re-rank/oracle source for in-memory builds — the
    model already holds its matrix, so borrowing it costs no extra copy
    (``index_bytes`` counts only what the index OWNS; docs/serving.md §6).
    """

    kind = "borrowed-matrix"

    def __init__(self, matrix: np.ndarray):
        self._matrix = matrix

    def __call__(self, ids: np.ndarray) -> np.ndarray:
        return _normalize_rows(self._matrix[np.asarray(ids)])[0]


class IvfIndex:
    """Built inverted-file index; see :func:`build_ivf`.

    Storage is the PACKED layout: rows are reordered so each inverted list
    is one contiguous block (``storage`` rows ``offsets[c]:offsets[c+1]``
    are cell ``c``). Probing a cell is then a sequential scan over its
    block — the naive gather of ~nprobe/C·V scattered rows is
    DRAM-latency-bound and measured 5-10x slower at V ≥ 400k on this host
    class. ``_ids`` maps packed positions back to original row ids;
    ``_row_pos`` is the inverse (for :meth:`vector`).

    ``row_fetch`` (optional) is the exact-row source: ``fetch(ids) ->
    normalized f32 rows``. Quantized arms use it for the PQ re-rank stage,
    for exact word-query vectors, and as the :meth:`measure_recall`
    oracle; without one, :meth:`vector` falls back to dequantized codes
    and ``measure_recall`` is unavailable after build."""

    def __init__(self, centroids: np.ndarray, offsets: np.ndarray,
                 storage, ids: np.ndarray, row_pos: np.ndarray,
                 nprobe: int, stats: Dict, rerank: int = 0,
                 row_fetch: Optional[Callable[[np.ndarray], np.ndarray]]
                 = None):
        self._centroids = centroids      # [C, D] unit rows
        self._offsets = offsets          # [C + 1] int64
        self._storage = storage          # cell-contiguous code/row store
        self._ids = ids                  # [V] int32: packed pos -> row id
        self._row_pos = row_pos          # [V] int64: row id -> packed pos
        self.nprobe = int(nprobe)
        self.stats = stats
        self._rerank = int(rerank)       # 0 = no re-rank stage
        self._row_fetch = row_fetch

    @property
    def quant(self) -> str:
        return self._storage.kind

    @property
    def num_centroids(self) -> int:
        return int(self._centroids.shape[0])

    @property
    def num_rows(self) -> int:
        return int(self._ids.shape[0])

    @property
    def index_bytes(self) -> int:
        """Bytes the index OWNS: codes/rows + centroids + list structure
        (+ codebooks/scales). A borrowed re-rank row source is NOT counted
        — it is the model's own matrix (in-memory builds) or mmap'd
        checkpoint shards (shard-native builds), alive either way."""
        return int(self._storage.nbytes + self._centroids.nbytes
                   + self._offsets.nbytes + self._ids.nbytes
                   + self._row_pos.nbytes)

    def vector(self, row: int) -> np.ndarray:
        """The indexed (unit-normalized) vector of one row — lets word
        queries reuse the host copy instead of a device gather. Exact when
        a row source exists (f32 storage IS one); dequantized otherwise."""
        if self._storage.kind == "f32":
            return self._storage.reconstruct(self._row_pos[row])
        if self._row_fetch is not None:
            return self._row_fetch(np.asarray([row]))[0]
        return self._storage.reconstruct(self._row_pos[row])

    def _resolved_rerank(self, k: int) -> int:
        """The re-rank candidate count for one top-``k`` search: >0
        explicit, -1 explicitly off, 0 = AUTO — pq widens to max(100,
        40k) (ADC's fine ordering scrambles inside dense clusters, where
        top-10 score gaps are smaller than the reconstruction error, so
        the shortlist must out-span the cluster); int8's rescaled dots
        are much tighter, max(32, 4k) heals the ordering. f32 never
        re-ranks (its scores are already exact)."""
        if self._row_fetch is None or self._rerank < 0:
            return 0
        if self._rerank > 0:
            return self._rerank
        if self._storage.kind == "pq":
            return max(100, 40 * k)
        if self._storage.kind == "int8":
            return max(32, 4 * k)
        return 0

    def search(self, queries: np.ndarray, k: int,
               nprobe: Optional[int] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Top-``k`` cosine rows per query over the probed cells.

        Returns ``(scores [Q, k], row_ids [Q, k])``; slots past the
        candidate count (possible only at tiny nprobe on tiny lists) carry
        ``(-inf, -1)`` — identical fill semantics across all three storage
        arms. ``nprobe`` overrides the index default; clamped to the
        centroid count (``nprobe >= C`` degrades to an exact scan and is
        the recall-1.0 reference point for f32; quantized arms add their
        code error). f32 scores are exact cosines; int8 scores are
        rescaled int8 dots (~1e-2 relative error); pq results are ADC-
        shortlisted then re-ranked against exact rows, so the RETURNED
        top-k scores are exact cosines again."""
        q, _ = _normalize_rows(np.atleast_2d(np.asarray(queries, np.float32)))
        C = self.num_centroids
        npr = min(int(nprobe) if nprobe else self.nprobe, C)
        npr = max(npr, 1)
        cscore = q @ self._centroids.T                       # [Q, C]
        Q = q.shape[0]
        off = self._offsets
        scores = np.full((Q, k), -np.inf, np.float32)
        idx = np.full((Q, k), -1, np.int64)
        rerank_n = self._resolved_rerank(k)
        for r in range(Q):
            # probe cells best-first, and past the nprobe budget KEEP
            # probing until the candidate pool covers k (a tiny/uneven cell
            # must not starve the result below the requested top-k — the
            # serve-reload chaos phase caught exactly that at toy vocab)
            order = np.argsort(-cscore[r], kind="stable")
            scan = self._storage.scanner(q[r])
            parts, pos_parts, got = [], [], 0
            for j, c in enumerate(order):
                if j >= npr and got >= k:
                    break
                lo, hi = off[c], off[c + 1]
                if hi == lo:
                    continue
                parts.append(scan(lo, hi))
                pos_parts.append(np.arange(lo, hi))
                got += hi - lo
            if not parts:
                continue
            s = np.concatenate(parts)
            pos = np.concatenate(pos_parts)
            if rerank_n:
                # ADC/quantized shortlist -> exact re-rank: fetch the top
                # rerank_n candidates' float rows lazily and rank those by
                # true cosine (asymmetric distance discipline, PAMI 2011)
                short = _topk_desc(s, min(rerank_n, s.size))
                cand_ids = self._ids[pos[short]]
                exact = self._row_fetch(cand_ids) @ q[r]
                top = _topk_desc(exact, min(k, exact.size))
                scores[r, :top.size] = exact[top]
                idx[r, :top.size] = cand_ids[top]
            else:
                top = _topk_desc(s, min(k, s.size))
                scores[r, :top.size] = s[top]
                idx[r, :top.size] = self._ids[pos[top]]
        return scores, idx

    # -- exact oracle ------------------------------------------------------------------

    def _oracle_blocks(self, chunk: int
                       ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """(exact normalized rows, their row ids) in bounded blocks — the
        full-scan oracle's source: f32 storage serves its own packed copy;
        quantized storages stream through the row source."""
        V = self.num_rows
        if self._storage.kind == "f32":
            for lo in range(0, V, chunk):
                hi = min(lo + chunk, V)
                yield self._storage.block(lo, hi), self._ids[lo:hi]
        elif self._row_fetch is not None:
            for lo in range(0, V, chunk):
                ids = np.arange(lo, min(lo + chunk, V))
                yield self._row_fetch(ids), ids
        else:
            raise RuntimeError(
                "exact-oracle recall needs a row source; this quantized "
                "index was built with keep_rows=False (recall was still "
                "measured at build — see index.stats)")

    def _query_rows(self, query_rows: np.ndarray) -> np.ndarray:
        if self._storage.kind == "f32":
            return self._storage.reconstruct(self._row_pos[query_rows])
        if self._row_fetch is not None:
            return self._row_fetch(query_rows)
        return np.stack([self._storage.reconstruct(self._row_pos[r])
                         for r in query_rows])

    def measure_recall(self, query_rows: np.ndarray, k: int = 10,
                       nprobe: Optional[int] = None) -> float:
        """recall@k of this index vs the EXACT full-scan oracle on the same
        normalized matrix, querying by row id (self excluded on both arms —
        the serving semantics). Quantized arms stream the oracle through
        their row source in bounded blocks, so the measurement never
        materializes a dense [V, D] copy either."""
        query_rows = np.asarray(query_rows)
        q = self._query_rows(query_rows)
        _, ann_i = self.search(q, k + 1, nprobe)
        Q = q.shape[0]
        kk = k + 1
        chunk = max(kk, _ORACLE_BLOCK_BYTES // max(Q * 4, 1))
        best_s = np.full((Q, kk), -np.inf, np.float32)
        best_i = np.full((Q, kk), -1, np.int64)
        for rows, ids in self._oracle_blocks(chunk):
            s = q @ rows.T                                   # [Q, block]
            cat_s = np.concatenate([best_s, s], axis=1)
            cat_i = np.concatenate(
                [best_i, np.broadcast_to(ids, (Q, ids.shape[0]))], axis=1)
            sel = np.argpartition(cat_s, cat_s.shape[1] - kk,
                                  axis=1)[:, -kk:]
            best_s = np.take_along_axis(cat_s, sel, axis=1)
            best_i = np.take_along_axis(cat_i, sel, axis=1)
        hits, total = 0, 0
        for r in range(Q):
            qi = int(query_rows[r])
            order = _topk_desc(best_s[r], kk)
            exact = [int(best_i[r][p]) for p in order
                     if best_i[r][p] >= 0 and best_i[r][p] != qi][:k]
            ann = [i for i in ann_i[r] if i >= 0 and i != qi][:k]
            hits += len(set(exact) & set(ann))
            total += len(exact)
        return hits / max(total, 1)


def auto_centroids(num_rows: int) -> int:
    """The AUTO cell count: ~4·sqrt(V), clamped so every cell averages ≥ 8
    rows and the centroid scan stays tiny next to the scan it replaces."""
    return max(1, min(int(round(4 * math.sqrt(max(num_rows, 1)))),
                      max(num_rows // 8, 1), 4096))


def auto_nprobe(num_centroids: int) -> int:
    """The AUTO probe width: ~1/12 of the cells (≈8% of the vocabulary
    scanned) — the measured recall ≥ 0.95 operating point on clustered
    embedding geometry (tools/servebench.py); tune per deployment."""
    return max(1, -(-num_centroids // 12))


def _gate_recall(index: IvfIndex, rng, nonzero: np.ndarray,
                 recall_queries: int, recall_k: int, floor: float) -> None:
    """Measure recall vs the exact oracle (EVERY build that can measure
    does) and refuse a quantized build below its floor."""
    probes = rng.choice(nonzero, size=min(recall_queries, nonzero.size),
                        replace=False)
    key = ("recall_at_10" if recall_k == 10
           else f"recall_at_{recall_k}")
    measured = round(index.measure_recall(probes, k=recall_k), 4)
    index.stats[key] = measured
    index.stats["recall_queries"] = int(probes.size)
    if measured < floor:
        raise RecallFloorError(index.quant, measured, floor)


def build_ivf(
    matrix: np.ndarray,
    num_centroids: int = 0,
    nprobe: int = 0,
    seed: int = 0,
    kmeans_iters: int = 4,
    train_sample: int = 65536,
    recall_queries: int = 256,
    recall_k: int = 10,
    measure_recall: bool = True,
    quant: str = "f32",
    pq_m: int = 0,
    rerank: int = 0,
    recall_floor: float = -1.0,
    keep_rows: bool = True,
) -> IvfIndex:
    """Build an :class:`IvfIndex` from a [V, D] embedding matrix (pass the
    UNPADDED ``model.syn0``; sharding padding would only add zero rows).

    ``num_centroids``/``nprobe`` 0 = AUTO (:func:`auto_centroids` /
    :func:`auto_nprobe` — the ``serve_ann_centroids``/``serve_ann_nprobe``
    config knobs carry the same 0-is-AUTO convention). ``measure_recall``
    scores the built index against the exact oracle on ``recall_queries``
    sampled rows; the result rides ``index.stats`` (and, from there,
    servebench JSON lines and EVAL_RUNS rows).

    Quantization (docs/serving.md §6): ``quant`` picks the storage arm
    (``f32``/``int8``/``pq``); ``pq_m`` is the PQ subspace count (0 = AUTO,
    serve/quant.py); ``rerank`` the exact-re-rank shortlist (0 = AUTO:
    max(32, 4k) for pq, off for int8); ``recall_floor`` the refusal gate
    (-1 = AUTO per-arm documented floor, 0 disables) — a measured-recall
    build below floor raises :class:`RecallFloorError`. Quantized arms
    BORROW the input matrix as their lazy exact-row source (re-rank,
    word-query vectors, oracle); ``keep_rows=False`` drops it after the
    build-time recall measurement, leaving a codes-only index."""
    t0 = time.perf_counter()
    if quant not in QUANT_MODES:
        raise ValueError(f"quant must be one of {QUANT_MODES}, got {quant!r}")
    src = np.asarray(matrix)
    normed, norms = _normalize_rows(src)
    V = normed.shape[0]
    nonzero = np.flatnonzero(norms > 0)
    C = int(num_centroids) if num_centroids else auto_centroids(V)
    C = max(1, min(C, max(nonzero.size, 1)))
    rng = np.random.default_rng(seed)

    if nonzero.size:
        if nonzero.size > train_sample:
            train = rng.choice(nonzero, size=train_sample, replace=False)
        else:
            train = nonzero
        X = normed[train]
        centroids = _kmeans_unit(X, C, rng, kmeans_iters)
    else:
        # degenerate all-zero matrix: one empty-ish cell, exact fallback
        centroids = np.zeros((1, normed.shape[1]), np.float32)
        C = 1
        X = normed[:0]

    assign_all = _argmax_rows(normed, centroids)
    counts = np.bincount(assign_all, minlength=C)
    offsets = np.zeros(C + 1, np.int64)
    np.cumsum(counts, out=offsets[1:])
    ids = np.argsort(assign_all, kind="stable").astype(np.int32)
    row_pos = np.empty(V, np.int64)
    row_pos[ids] = np.arange(V)

    row_fetch = None
    if quant == "f32":
        storage = F32Storage(
            np.ascontiguousarray(normed[ids]))   # list-contiguous layout
    else:
        from glint_word2vec_tpu.serve.quant import make_quant_storage
        storage = make_quant_storage(
            quant, train_rows=X, seed=seed, pq_m=pq_m,
            encode_blocks=((normed[ids[lo:lo + 262144]],
                            np.arange(lo, min(lo + 262144, V)))
                           for lo in range(0, V, 262144)),
            num_rows=V, dim=normed.shape[1])
        row_fetch = MatrixRowFetch(src)

    npr = int(nprobe) if nprobe else auto_nprobe(C)
    floor = resolve_recall_floor(recall_floor, quant)
    stats: Dict = {
        "quant": quant,
        "centroids": C,
        "nprobe": min(npr, C),
        "rows": V,
        "mean_list_len": round(float(counts.mean()), 2) if C else 0.0,
        "max_list_len": int(counts.max()) if C else 0,
        "recall_floor": floor,
    }
    index = IvfIndex(centroids, offsets, storage, ids, row_pos,
                     min(npr, C), stats, rerank=rerank, row_fetch=row_fetch)
    _finish_stats(index, t0)
    if measure_recall and nonzero.size > recall_k:
        _gate_recall(index, rng, nonzero, recall_queries, recall_k, floor)
    stats["build_seconds"] = round(time.perf_counter() - t0, 3)
    if not keep_rows and quant != "f32":
        index._row_fetch = None
    logger.info("IVF index built: V=%d C=%d nprobe=%d quant=%s recall@%d=%s "
                "bytes/vec=%s in %.2fs",
                V, C, stats["nprobe"], quant, recall_k,
                stats.get(f"recall_at_{recall_k}"),
                stats["bytes_per_vector"], stats["build_seconds"])
    return index


def _finish_stats(index: IvfIndex, t0: float) -> None:
    """Footprint observability (ISSUE 18 satellite): every build reports
    what it OWNS — statusd renders these as ``glint_serve_index_bytes`` /
    ``glint_serve_ann_bytes_per_vector``."""
    stats = index.stats
    stats["index_bytes"] = index.index_bytes
    stats["bytes_per_vector"] = (
        round(index.index_bytes / max(index.num_rows, 1), 2))
    if index._storage.kind == "pq":
        stats["pq_m"] = index._storage.m
    if index._storage.kind in ("pq", "int8"):
        stats["rerank"] = index._resolved_rerank(10)
