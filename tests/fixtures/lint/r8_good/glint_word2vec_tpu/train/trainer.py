"""R8 good trainer half: same dispatch guards (including the __init__ one
and the sync_every cadence guard); config carries every twin."""


class Trainer:
    def __init__(self, config):
        self.config = config
        if config.device_pairgen:
            if config.cbow:
                raise ValueError("device feed is skip-gram only")

    def _build_step(self):
        cfg = self.config
        if cfg.use_pallas:
            if cfg.cbow:
                raise ValueError("use_pallas is SGNS-only")
            if cfg.max_row_norm:
                raise ValueError("stabilizers are XLA-path only")
        if cfg.cbow:
            if cfg.negative_pool == 0:
                raise ValueError("cbow needs the shared pool here")
        if cfg.sync_every > 1:
            if cfg.step_lowering != "shard_map":
                raise ValueError("sync_every needs the shard_map lowering")
        return None
