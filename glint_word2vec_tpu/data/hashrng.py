"""Position-keyed host-side hash RNG — the contract shared by the numpy pipeline and
the native C++ pair generator (``native/pairgen.cpp``).

Every random decision in the pair stream (subsample keep/drop, per-position window
shrink) is a pure function of ``(seed, stream, iteration, shard, token_ordinal)`` using
the same murmur3-finalizer lattice as the device sampler (:mod:`..ops.prng`). This buys
three properties the previous sequential ``numpy.random.Generator`` scheme could not:

- **backend equivalence**: the numpy path and the multithreaded C++ path produce
  bit-identical pair streams (asserted by tests), so enabling the native generator
  never changes training results;
- **parallelism**: no sequential RNG state — any thread can draw for any position;
- **block-size independence**: the stream depends only on the token's global ordinal
  within (iteration, shard), not on how the pipeline batches sentences into blocks.

The reference's analog is the per-partition XORShift reseed
``seed ^ ((idx+1)<<16) ^ ((-k-1)<<8)`` (mllib:372,382) — deterministic per partition
but sequential within it.

Keep-probability comparison happens in float32 on both sides: ``(bits >> 8)`` is ≤ 2^24
(exact in f32) and the 2^-24 scale is a power of two, so the u01 values are exactly
representable and the comparison is bit-identical across implementations.
"""

from __future__ import annotations

import numpy as np

GOLDEN = np.uint32(0x9E3779B9)

# stream constants (must match native/pairgen.cpp)
STREAM_SUBSAMPLE = 101
STREAM_WINDOW = 102


def mix32(x: np.ndarray) -> np.ndarray:
    """murmur3 fmix32 finalizer on uint32 arrays (wraps, as unsigned arithmetic does;
    the errstate guard silences numpy's overflow warning for 0-d scalar inputs)."""
    x = np.asarray(x, dtype=np.uint32)
    with np.errstate(over="ignore"):
        x = (x ^ (x >> np.uint32(16))) * np.uint32(0x85EBCA6B)
        x = (x ^ (x >> np.uint32(13))) * np.uint32(0xC2B2AE35)
    return x ^ (x >> np.uint32(16))


def stream_base(seed: int, stream: int, iteration: int, shard: int) -> np.uint32:
    """The per-(seed, stream, iteration, shard) base the per-ordinal mix folds in."""
    s = np.uint32((seed & 0xFFFFFFFF) * 0x9E3779B9 & 0xFFFFFFFF)
    t = np.uint32((stream * 0x7FEB352D + 0x68E31DA4) & 0xFFFFFFFF)
    c = np.uint32((iteration * 0x85EBCA6B + shard * 0xC2B2AE35) & 0xFFFFFFFF)
    return mix32(c ^ mix32(s ^ t))[()]


def hash_bits_at(base: np.uint32, ordinals: np.ndarray) -> np.ndarray:
    """uint32 bits for 64-bit token ordinals under a precomputed stream base."""
    o = np.asarray(ordinals, dtype=np.uint64)
    lo = (o & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (o >> np.uint64(32)).astype(np.uint32)
    return mix32(lo ^ mix32(hi ^ np.uint32(0xDEADBEEF)) ^ base)


def hash_u01_at(base: np.uint32, ordinals: np.ndarray) -> np.ndarray:
    """float32 uniforms in [0, 1) with 24 bits of mantissa entropy, position-keyed."""
    bits = hash_bits_at(base, ordinals)
    return (bits >> np.uint32(8)).astype(np.float32) * np.float32(2.0 ** -24)


def hash_mod_at(base: np.uint32, ordinals: np.ndarray, bound: int) -> np.ndarray:
    """int64 draws in [0, bound), position-keyed (modulo bias ≤ bound/2^32)."""
    bits = hash_bits_at(base, ordinals)
    return (bits % np.uint32(bound)).astype(np.int64)
