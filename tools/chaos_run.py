#!/usr/bin/env python
"""Chaos runner: drive a toy-corpus run through a scripted fault schedule
end-to-end and verify the fault-tolerance layer holds (docs/robustness.md).

Every fault is deterministic (train/faults.py) — no sleep/kill-timing races:

1. **crash-resume** — a subprocess trains with periodic checkpointing and is
   SIGKILLed *inside* the second checkpoint's swap window (the torn state: old
   checkpoint renamed aside, replacement not yet in place). The parent recovers
   via ``load_latest_valid`` (which reclaims the staging debris and restores
   the renamed-aside previous checkpoint), resumes training from it, and
   verifies the finished checkpoint's digests.
2. **corrupt-fallback** — a newer checkpoint is saved with scripted bit-flips;
   ``load_latest_valid`` must reject it on digest mismatch and fall back to the
   older clean one.
3. **nan-rollback / nan-halt** — NaN is injected into the params carry at a
   scripted step; under ``nonfinite_policy="rollback"`` the run finishes with
   finite embeddings, under ``"halt"`` it fails fast with a diagnostic.
4. **norm-blowup** — the params carry is scaled by 1e6 at a scripted step: a
   FINITE blowup (the measured 1.6M-vocab collapse signature, ROADMAP item 2).
   ``nonfinite_policy`` alone must stay silent, ``norm_watch="warn"`` must
   record firings and finish, ``norm_watch="halt"`` must fail fast.
5. **norm-recover** — the full detect→mitigate→recover ladder
   (docs/robustness.md): the same finite blowup under
   ``norm_watch="recover"`` (beside ``nonfinite_policy="halt"`` — the
   snapshot ring must arm for the watchdog even though nonfinite rollback
   never does) must roll back, back the lr off, engage the row-norm clamp,
   and FINISH with finite params and ``recoveries_performed >= 1``; a
   repeatedly-reblowing run past ``max_recoveries`` must degrade to the
   halt contract (NormBlowupError).
6. **blackbox** — chaos-proven forensics (docs/observability.md): a
   SIGTERM'd telemetry-on subprocess (``crash_at_step`` +
   ``crash_signal=TERM`` — the preemption first-warning surface) and an
   injected finite blowup under ``norm_watch="halt"`` must each leave a
   schema-valid ``<telemetry_path>.blackbox.json`` flight-recorder dump
   carrying ≥ 1 heartbeat and the terminal cause (signal / exception).
7. **serve-reload** — the serving tier under publish chaos (ISSUE 10,
   docs/serving.md): a trainer thread publishes checkpoints every few steps
   while a query storm runs against an EmbeddingService watching the same
   path — zero failed/refused queries, ≥ 3 observed hot-reloads, and every
   superseded model's buffers released once its in-flight leases drained.
   The epilogue drives the cross-publish V-GREW case (ISSUE 11): the
   checkpoint is vocabulary-extended mid-storm, the service must hot-reload
   at the new V (index rebuilt, ``vocab_change_reloads`` counted) and answer
   a query for a word that did not exist one publish earlier.
8. **continual-drift** — the closed continual loop (ISSUE 11,
   docs/continual.md): base fit → corpus append with unseen words → a
   SIGTERM'd mid-increment driver subprocess must leave a resumable
   published checkpoint and an unconsumed cursor → the retried increment
   grows V with the fingerprint lineage recorded → a live serve replica
   hot-reloads the grown model, answers a query for a NEW word, and an old
   word's neighbors stay inside its co-occurrence cluster.
9. **fleet-kill** — the serving FLEET under replica death (ISSUE 12,
   docs/serving.md §5): N replica subprocesses behind a FleetRouter, one
   SIGKILL'd mid-query-storm → its circuit breaker opens, ZERO client
   queries fail (retries land on the survivors), the ReplicaSet restarts
   it, and the breaker recovers through the half-open trial to closed;
   then a 3-publish rolling-reload storm keeps >= N-1 replicas serving
   with every reload issued only to a drained replica.
10. **flaky-ingest** — the first N ingest I/O attempts raise; the bounded
    exponential-backoff wrapper in ``data/`` must absorb them.
11. **train-preempt / train-stall / train-crashloop** — the training
    SUPERVISOR under scripted faults (ISSUE 16, docs/robustness.md
    §supervisor, delegating to tools/train_run.py): a SIGTERM'd fit
    emergency-checkpoints within its preemption deadline and resumes to
    match an uninterrupted twin's purity gate; an injected in-step hang
    is detected within 2x the stall horizon, diagnosed (flight-recorder
    dump), killed, and resumed; a deterministic every-attempt crash walks
    the escalation ladder and is quarantined with a machine-readable
    verdict in bounded attempts.

Usage::

    python tools/chaos_run.py           # moderate sizes
    python tools/chaos_run.py --smoke   # small + fast (wired into tier-1 tests)
    python tools/chaos_run.py --only serve-reload   # one phase (CI serving job)
    python tools/chaos_run.py --list    # print available phase names

Exit code 0 iff every phase passed.
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def toy_sentences(n_sentences: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    return [[f"w{i}" for i in rng.integers(0, 30, 20)]
            for _ in range(n_sentences)]


def toy_config(policy: str = "halt", **kw):
    from glint_word2vec_tpu.config import Word2VecConfig
    return Word2VecConfig(
        vector_size=8, pairs_per_batch=128, window=3, num_iterations=2,
        steps_per_dispatch=2, heartbeat_every_steps=2, subsample_ratio=0.0,
        prefetch_chunks=0, seed=1, nonfinite_policy=policy, **kw)


def _fit(sentences, cfg, **kw):
    from glint_word2vec_tpu.data.pipeline import encode_sentences
    from glint_word2vec_tpu.data.vocab import build_vocab
    from glint_word2vec_tpu.train.trainer import Trainer
    vocab = build_vocab(sentences, min_count=1)
    enc = encode_sentences(sentences, vocab, 1000)
    trainer = Trainer(cfg, vocab)
    trainer.fit(enc, **kw)
    return trainer


def worker_crash(workdir: str, n_sentences: int) -> None:
    """The crashing training leg — launched as a subprocess with
    GLINT_FAULT_CRASH_POINT=save:swap@2 in its env, so the first periodic save
    completes and the second dies mid-swap. Never returns normally."""
    _fit(toy_sentences(n_sentences), toy_config(),
         checkpoint_path=os.path.join(workdir, "ck"),
         checkpoint_every_steps=2)
    print("WORKER SURVIVED (fault did not fire)", flush=True)
    sys.exit(3)


def worker_blackbox(workdir: str, n_sentences: int) -> None:
    """The SIGTERM'd telemetry-on leg of the blackbox phase — launched with
    GLINT_FAULT_CRASH_AT_STEP + GLINT_FAULT_CRASH_SIGNAL=TERM in its env,
    so the trainer's SIGTERM hook (obs/blackbox.py) must dump the flight
    recorder before the process dies. Never returns normally."""
    _fit(toy_sentences(n_sentences), toy_config(
        telemetry_path=os.path.join(workdir, "run.jsonl")))
    print("WORKER SURVIVED (fault did not fire)", flush=True)
    sys.exit(3)


def phase_crash_resume(workdir: str, n_sentences: int) -> str:
    from glint_word2vec_tpu.models.estimator import Word2Vec
    from glint_word2vec_tpu.train.checkpoint import (
        load_latest_valid, verify_checkpoint)

    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               GLINT_FAULT_CRASH_POINT="save:swap@2")
    rc = subprocess.call(
        [sys.executable, os.path.abspath(__file__), "--worker", "crash",
         "--workdir", workdir, "--sentences", str(n_sentences)],
        env=env)
    if rc not in (-9, 137):
        return f"worker exited {rc}, expected SIGKILL (-9/137)"
    entries = sorted(os.listdir(workdir))
    if not any(".old-" in e or ".tmp-" in e for e in entries):
        return f"no interrupted-save debris found ({entries}) — fault missed"
    ck = load_latest_valid(workdir)
    meta = verify_checkpoint(ck)
    step = meta["train_state"]["global_step"]
    if meta["train_state"]["finished"] or step <= 0:
        return f"recovered checkpoint is not a mid-run state (step {step})"
    model = Word2Vec.resume(ck, toy_sentences(n_sentences),
                            checkpoint_every_steps=2)
    if not model.train_state.finished:
        return "resumed run did not finish"
    verify_checkpoint(ck)  # the finished save must verify too
    if not np.isfinite(np.asarray(model.syn0)).all():
        return "resumed run produced non-finite embeddings"
    return ""


def phase_corrupt_fallback(workdir: str) -> str:
    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.train import faults
    from glint_word2vec_tpu.train.checkpoint import (
        TrainState, load_latest_valid, save_model)

    words = ["a", "b", "c"]
    counts = np.array([3, 2, 1])
    syn0 = np.random.default_rng(0).normal(size=(3, 8)).astype(np.float32)
    cfg = Word2VecConfig(vector_size=8)
    save_model(os.path.join(workdir, "ck-a"), words, counts, syn0, -syn0,
               cfg, TrainState(global_step=10))
    faults.configure(corrupt_checkpoint_bytes=3)
    try:
        save_model(os.path.join(workdir, "ck-b"), words, counts, syn0, -syn0,
                   cfg, TrainState(global_step=20))
    finally:
        faults.reset()
    got = load_latest_valid(workdir)
    if os.path.basename(got) != "ck-a":
        return f"picked {got!r}; expected the older clean ck-a (ck-b is corrupt)"
    return ""


def phase_nan(policy: str) -> str:
    from glint_word2vec_tpu.train import faults
    from glint_word2vec_tpu.train.faults import NonFiniteParamsError

    faults.configure(nan_at_step=8)
    try:
        trainer = _fit(toy_sentences(200, seed=2), toy_config(policy))
    except NonFiniteParamsError as e:
        faults.reset()
        if policy == "halt":
            return "" if "non-finite parameters" in str(e) else \
                f"halt diagnostic unclear: {e}"
        return f"rollback run raised instead of recovering: {e}"
    finally:
        faults.reset()
    if policy == "halt":
        return "halt run finished instead of raising"
    if not np.isfinite(np.asarray(trainer.params.syn0)).all():
        return "rollback run ended with non-finite params"
    if trainer.rollbacks_performed < 1:
        return "rollback run never rolled back (fault missed)"
    return ""


def phase_norm_blowup() -> str:
    """The finite-blowup watchdog (ISSUE 6 / ROADMAP item 2): scale the params
    carry by 1e6 mid-run — a FINITE norm blowup, the measured 1.6M-vocab
    collapse signature. The non-finite guardrail alone must stay silent (no
    NaN ever appears — exactly the round-5 blindness), norm_watch='warn' must
    record firings and finish, norm_watch='halt' must fail fast."""
    from glint_word2vec_tpu.train import faults
    from glint_word2vec_tpu.train.faults import NormBlowupError

    # 1. nonfinite halt alone: silent (the blowup is finite)
    faults.configure(scale_params_at_step=8)
    try:
        trainer = _fit(toy_sentences(200, seed=2), toy_config("halt"))
    except Exception as e:  # noqa: BLE001 — any raise here is the failure
        return f"nonfinite_policy='halt' fired on a FINITE blowup: {e}"
    finally:
        faults.reset()
    if not np.isfinite(np.asarray(trainer.params.syn0)).all():
        return "scaled params went non-finite — injection no longer finite"
    if trainer.norm_watchdog.fires:
        return "watchdog fired with norm_watch='off'"

    # 2. warn: fires, training continues to completion
    faults.configure(scale_params_at_step=8)
    try:
        trainer = _fit(toy_sentences(200, seed=2),
                       toy_config("halt", norm_watch="warn"))
    finally:
        faults.reset()
    if trainer.norm_watchdog.fires < 1:
        return "norm_watch='warn' never fired on the injected blowup"

    # 3. halt: fail fast with the diagnostic
    faults.configure(scale_params_at_step=8)
    try:
        _fit(toy_sentences(200, seed=2),
             toy_config("halt", norm_watch="halt"))
    except NormBlowupError as e:
        return "" if "finite norm blowup" in str(e) else \
            f"halt diagnostic unclear: {e}"
    finally:
        faults.reset()
    return "norm_watch='halt' finished instead of raising"


def phase_norm_recover() -> str:
    """Close the loop (ISSUE 7): the injected finite blowup must drive the
    full warn→recover→resume→finish ladder — watchdog fires, the run rolls
    back to a ring snapshot, lr backs off, the row-norm clamp engages, and
    fit() COMPLETES with finite params; and a run that re-blows past its
    recovery budget must degrade to the fail-fast halt contract."""
    from glint_word2vec_tpu.train import faults
    from glint_word2vec_tpu.train.faults import NormBlowupError

    # 1. recover: blowup mid-run -> rollback + mitigation -> finish.
    #    nonfinite_policy stays 'halt' on purpose: the ring must arm for the
    #    WATCHDOG consumer (the pre-round-12 arming bug left it empty here).
    faults.configure(scale_params_at_step=8)
    try:
        trainer = _fit(toy_sentences(200, seed=2),
                       toy_config("halt", norm_watch="recover"))
    except Exception as e:  # noqa: BLE001 — a recover run must not raise
        return f"norm_watch='recover' raised instead of recovering: {e}"
    finally:
        faults.reset()
    if trainer.recoveries_performed < 1:
        return "recover run finished but never recovered (fault missed?)"
    if trainer.norm_watchdog.fires < 1:
        return "recover run finished without a watchdog firing"
    if not np.isfinite(np.asarray(trainer.params.syn0)).all():
        return "recovered run ended with non-finite params"
    norms = np.linalg.norm(
        np.asarray(trainer.params.syn0, np.float64), axis=1)
    if norms.max() > trainer.config.norm_watch_threshold * 1.001:
        return (f"recovered run still carries blown rows "
                f"(max norm {norms.max():.3g}) — mitigation not engaged?")
    if trainer._lr_scale >= 1.0:
        return "recovery did not back the learning rate off"
    if not trainer._stabilizers.max_row_norm:
        return "recovery did not engage max_row_norm"

    # 2. budget exhaustion: the blowup re-fires every round (times=99), so
    #    after max_recoveries the ladder must degrade to halt, fail-fast
    faults.configure(scale_params_at_step=8, scale_params_times=99)
    try:
        _fit(toy_sentences(200, seed=2),
             toy_config("halt", norm_watch="recover", max_recoveries=2))
    except NormBlowupError as e:
        return "" if "budget exhausted" in str(e) else \
            f"exhaustion diagnostic unclear: {e}"
    except Exception as e:  # noqa: BLE001
        return f"budget exhaustion raised the wrong error: {e}"
    finally:
        faults.reset()
    return "budget-exhaustion run finished instead of halting"


def phase_blackbox(workdir: str, n_sentences: int) -> str:
    """Chaos-proven forensics (ISSUE 9): an injected crash (SIGTERM'd
    subprocess — the preemption first-warning surface) and an injected
    finite blowup (NormBlowupError through the abort path) must each leave
    a SCHEMA-VALID ``<telemetry_path>.blackbox.json`` carrying the ring
    contents (>= 1 heartbeat) and the terminal cause record."""
    import json
    from glint_word2vec_tpu.obs.schema import validate_blackbox_file
    from glint_word2vec_tpu.train import faults
    from glint_word2vec_tpu.train.faults import NormBlowupError

    # 1. injected crash: SIGTERM at a scripted step, in a real subprocess —
    #    the dump must be written by the signal hook before the process dies
    crash_dir = os.path.join(workdir, "crash")
    os.makedirs(crash_dir, exist_ok=True)
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               GLINT_FAULT_CRASH_AT_STEP="8",
               GLINT_FAULT_CRASH_SIGNAL="TERM")
    rc = subprocess.call(
        [sys.executable, os.path.abspath(__file__), "--worker", "blackbox",
         "--workdir", crash_dir, "--sentences", str(n_sentences)],
        env=env)
    if rc not in (-15, 143):
        return f"worker exited {rc}, expected SIGTERM (-15/143)"
    dump = os.path.join(crash_dir, "run.jsonl.blackbox.json")
    if not os.path.exists(dump):
        return "SIGTERM'd run left no blackbox dump"
    v = validate_blackbox_file(dump)
    if not v["ok"]:
        return f"crash dump not schema-valid: {v['errors'][:3]}"
    with open(dump) as f:
        doc = json.load(f)
    if doc["cause"] != {"kind": "signal", "signal": "SIGTERM", "signum": 15}:
        return f"crash dump cause wrong: {doc['cause']}"
    if len(doc["heartbeats"]) < 1:
        return "crash dump carries no heartbeats"
    if not doc["dispatches"]:
        return "crash dump carries no dispatch records"

    # 2. injected finite blowup: NormBlowupError rides the abort path and
    #    must dump with the exception as the terminal cause (and the
    #    watchdog record in the event ring — the record-before-raise
    #    contract made durable)
    blow_dir = os.path.join(workdir, "blowup")
    os.makedirs(blow_dir, exist_ok=True)
    run_log = os.path.join(blow_dir, "run.jsonl")
    faults.configure(scale_params_at_step=8)
    try:
        _fit(toy_sentences(n_sentences, seed=2),
             toy_config("halt", norm_watch="halt", telemetry_path=run_log))
        return "norm_watch='halt' finished instead of raising"
    except NormBlowupError:
        pass
    except Exception as e:  # noqa: BLE001
        return f"blowup raised the wrong error: {e}"
    finally:
        faults.reset()
    dump = run_log + ".blackbox.json"
    if not os.path.exists(dump):
        return "blowup run left no blackbox dump"
    v = validate_blackbox_file(dump)
    if not v["ok"]:
        return f"blowup dump not schema-valid: {v['errors'][:3]}"
    with open(dump) as f:
        doc = json.load(f)
    cause = doc["cause"]
    if cause.get("kind") != "exception" or cause.get("type") != "NormBlowupError":
        return f"blowup dump cause wrong: {cause}"
    if len(doc["heartbeats"]) < 1:
        return "blowup dump carries no heartbeats"
    kinds = [e["kind"] for e in doc["events"]]
    if "watchdog" not in kinds:
        return f"blowup dump events missing the watchdog record ({kinds})"
    if "run_end" not in kinds:
        return f"blowup dump events missing the terminal run_end ({kinds})"
    return ""


def phase_serve_reload(workdir: str, n_sentences: int) -> str:
    """Serving-tier chaos (ISSUE 10): the trainer publishes checkpoints
    mid-query-storm. The service must (a) answer every query — no errors,
    no refusals, no torn reads across the atomic swap; (b) observe >= 3
    hot-reloads through the publish-signal watcher; (c) release every
    superseded model's buffers once its in-flight leases drain."""
    import threading
    import time

    from glint_word2vec_tpu.data.pipeline import encode_sentences
    from glint_word2vec_tpu.data.vocab import build_vocab
    from glint_word2vec_tpu.serve import EmbeddingService
    from glint_word2vec_tpu.train.trainer import Trainer

    sents = toy_sentences(n_sentences, seed=4)
    vocab = build_vocab(sents, min_count=1)
    cfg = toy_config()
    enc = encode_sentences(sents, vocab, cfg.max_sentence_length)
    trainer = Trainer(cfg, vocab)
    ck = os.path.join(workdir, "ck")
    trainer.save_checkpoint(ck)  # the service needs a first publish to boot

    service = EmbeddingService(
        checkpoint=ck, ann=True, watch=True, reload_poll_s=0.02,
        max_batch=16, max_delay_ms=1.0)
    fit_err, query_errs = [], []
    queries = [0]

    def fit():
        try:
            # checkpoint every 4 global steps: many publishes race the
            # watcher's reloads and the storm below
            trainer.fit(enc, checkpoint_path=ck, checkpoint_every_steps=4)
            trainer.save_checkpoint(ck)
        except Exception as e:  # noqa: BLE001 — re-raised via fit_err
            fit_err.append(e)

    t = threading.Thread(target=fit)
    words = {f"w{i}" for i in range(30)}
    storm_on = threading.Event()
    storm_on.set()

    def storm(ci: int):
        i = 0
        while storm_on.is_set() or i == 0:
            i += 1
            try:
                res = service.synonyms(f"w{(ci * 7 + i) % 30}", 5)
                if len(res) != 5 or not all(
                        w in words and np.isfinite(s) for w, s in res):
                    query_errs.append(f"bad result: {res}")
            except Exception as e:  # noqa: BLE001 — ANY raise is the failure
                query_errs.append(f"{type(e).__name__}: {e}")
            queries[0] += 1

    clients = [threading.Thread(target=storm, args=(c,)) for c in range(3)]
    t.start()
    for c in clients:
        c.start()
    t.join()
    # the acceptance needs >= 3 OBSERVED publishes. Training publishes
    # plenty, but on a loaded host a reload cycle (load + index build) can
    # outlast the whole toy fit — so keep the storm up and keep PUBLISHING
    # until the watcher has demonstrably observed three, bounded by a
    # deadline (a watcher that never observes them is the failure)
    deadline = time.monotonic() + 60
    while service.stats()["reloads"] < 3 and time.monotonic() < deadline:
        trainer.save_checkpoint(ck)
        settle = time.monotonic() + 2
        while (service.stats()["reloads"] < 3
               and time.monotonic() < min(settle, deadline)):
            time.sleep(0.05)
    storm_on.clear()
    for c in clients:
        c.join()
    try:
        if fit_err:
            return f"trainer died under the storm: {fit_err[0]}"
        if query_errs:
            return (f"{len(query_errs)} failed queries during publishes "
                    f"(first: {query_errs[0]})")
        stats = service.stats()
        if stats["refused"]:
            return f"{stats['refused']} queries refused (queue never fills here)"
        if stats["reloads"] < 3:
            return (f"only {stats['reloads']} hot-reloads observed across "
                    f"the publish storm (need >= 3)")
        if stats["models_released"] != stats["reloads"]:
            return (f"buffer leak: {stats['reloads']} reloads but only "
                    f"{stats['models_released']} old models released")
        if queries[0] < 50:
            return f"storm too thin ({queries[0]} queries) to prove overlap"

        # cross-publish V-GREW epilogue (ISSUE 11): extend the vocabulary
        # between publishes; the watcher must hot-reload at the new V with
        # a freshly built index and serve the brand-new word
        from glint_word2vec_tpu.continual import extend_checkpoint
        rep = extend_checkpoint(
            ck, {"brandnew0": 50, "brandnew1": 40}, min_count=1)
        deadline = time.monotonic() + 30
        while (service.info()["num_words"] != rep["new_vocab_size"]
               and time.monotonic() < deadline):
            time.sleep(0.05)
        info = service.info()
        if info["num_words"] != rep["new_vocab_size"]:
            return (f"service never reloaded the V-grew publish "
                    f"(serving {info['num_words']} words, want "
                    f"{rep['new_vocab_size']})")
        if service.stats()["vocab_change_reloads"] < 1:
            return "V-grew reload not counted as a vocab change"
        res = service.synonyms("brandnew0", 3)
        if not res or not all(np.isfinite(s) for _, s in res):
            return f"new-vocab word query failed after the V-grew reload: {res}"

        # QUANTIZED V-grew epilogue (ISSUE 18): a second service pinned to
        # the int8 arm rides the same checkpoint; another vocabulary
        # extension must hot-reload it at the SAME quant mode with recall
        # re-measured at the new V (floor 0: toy-vocab probe loss is about
        # the scale, not the quantizer — docs/serving.md §6), and the
        # brand-new word must serve through the quantized index
        qsvc = EmbeddingService(
            checkpoint=ck, ann=True, watch=True, reload_poll_s=0.02,
            max_batch=16, max_delay_ms=1.0,
            ann_quant="int8", ann_recall_floor=0.0)
        try:
            before = qsvc.info()["ann"]
            if before.get("quant") != "int8":
                return f"quantized service built arm {before.get('quant')!r}"
            rep2 = extend_checkpoint(ck, {"brandnew2": 30}, min_count=1)
            deadline = time.monotonic() + 30
            while (qsvc.info()["num_words"] != rep2["new_vocab_size"]
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            qinfo = qsvc.info()
            if qinfo["num_words"] != rep2["new_vocab_size"]:
                return (f"quantized service never reloaded the V-grew "
                        f"publish (serving {qinfo['num_words']} words, "
                        f"want {rep2['new_vocab_size']})")
            after = qinfo["ann"]
            if after.get("quant") != "int8":
                return (f"V-grew reload changed the quant arm: "
                        f"{before.get('quant')!r} -> {after.get('quant')!r}")
            if after.get("rows") != rep2["new_vocab_size"]:
                return (f"quantized index not rebuilt at the new V "
                        f"(index rows {after.get('rows')})")
            if not isinstance(after.get("recall_at_10"), float):
                return ("quantized V-grew rebuild did not re-measure "
                        f"recall: {after.get('recall_at_10')!r}")
            qres = qsvc.synonyms("brandnew2", 3)
            if not qres or not all(np.isfinite(s) for _, s in qres):
                return (f"new-vocab word query failed through the "
                        f"quantized index: {qres}")
        finally:
            qsvc.close()
    finally:
        service.close()
    return ""


def phase_continual_drift(workdir: str, n_sentences: int) -> str:
    """The closed continual loop under fault injection (ISSUE 11,
    docs/continual.md): base fit -> corpus append with unseen words -> a
    SIGTERM'd mid-increment driver must leave a RESUMABLE published
    checkpoint and an unconsumed cursor -> the retried increment grows V
    (lineage recorded, carried rows verified by the extension itself) -> a
    live serve replica hot-reloads the grown model and answers a query for
    a NEW word, with an old word's neighbors still in its cluster."""
    import json as _json
    import time

    from glint_word2vec_tpu.continual import ContinualRunner, StreamCursor
    from glint_word2vec_tpu.serve import EmbeddingService
    from glint_word2vec_tpu.train.checkpoint import (
        load_latest_valid, load_model_header, verify_checkpoint)
    from tools.continual_run import (
        _CLUSTER_A, _NEW_WORDS, _write_cluster_segment)

    corpus_dir = os.path.join(workdir, "corpus")
    work_dir = os.path.join(workdir, "work")
    ck = os.path.join(workdir, "publish", "ck")
    os.makedirs(corpus_dir, exist_ok=True)
    _write_cluster_segment(
        os.path.join(corpus_dir, "seg-000.txt"), n_sentences, seed=1)
    overrides = dict(
        vector_size=16, min_count=2, window=3, num_iterations=2,
        pairs_per_batch=128, subsample_ratio=0.0, seed=1,
        prefetch_chunks=0, steps_per_dispatch=2, heartbeat_every_steps=2)
    runner = ContinualRunner(ck, corpus_dir, work_dir,
                             config_overrides=overrides,
                             checkpoint_every_steps=4)
    base = runner.ensure_base()
    v_base = base["vocab_size"]
    _write_cluster_segment(
        os.path.join(corpus_dir, "seg-001.txt"), n_sentences, seed=2,
        extra_a_words=_NEW_WORDS)

    # 1. SIGTERM mid-increment: the subprocess driver extends + starts the
    #    incremental fit, then dies at a scripted step. crash_at_step fires
    #    on global_step, which CONTINUES from the base checkpoint — 1 is
    #    already exceeded, so the first fit round of the increment dies.
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               GLINT_FAULT_CRASH_AT_STEP="1",
               GLINT_FAULT_CRASH_SIGNAL="TERM")
    rc = subprocess.call(
        [sys.executable,
         os.path.join(_REPO, "tools", "continual_run.py"),
         "--checkpoint", ck, "--corpus-dir", corpus_dir,
         "--work-dir", work_dir, "--max-increments", "1",
         "--idle-polls", "1"],
        env=env, stdout=subprocess.DEVNULL)
    if rc not in (-15, 143):
        return f"driver exited {rc}, expected SIGTERM (-15/143)"
    # resumable: the publish path (or its swap debris) verifies, and the
    # cursor did NOT consume the tail — the increment will retry
    try:
        recovered = load_latest_valid(os.path.dirname(ck))
        verify_checkpoint(recovered)
    except Exception as e:  # noqa: BLE001 — unrecoverable = the failure
        return f"no resumable checkpoint after mid-increment SIGTERM: {e}"
    cursor = StreamCursor(work_dir)
    if "seg-001.txt" in cursor.consumed:
        return "SIGTERM'd increment was marked consumed (not resumable)"

    # 2. retry the increment in-process, with a live serve replica watching
    service = EmbeddingService(
        checkpoint=ck, ann=True, watch=True, reload_poll_s=0.05,
        max_batch=16, max_delay_ms=1.0)
    try:
        runner2 = ContinualRunner(ck, corpus_dir, work_dir,
                                  config_overrides=overrides,
                                  checkpoint_every_steps=4)
        rep = runner2.run_once()
        if rep["action"] != "increment":
            return f"retried increment did not run: {rep}"
        header = load_model_header(ck)
        if header["vocab_size"] <= v_base:
            return (f"vocab did not grow across the increment "
                    f"({v_base} -> {header['vocab_size']})")
        lineage = header["vocab_lineage"]
        if not lineage or lineage[0].get("remap") != "identity-prefix":
            return f"fingerprint lineage missing/wrong: {lineage}"
        deadline = time.monotonic() + 30
        while (service.info()["num_words"] != header["vocab_size"]
               and time.monotonic() < deadline):
            time.sleep(0.05)
        if service.info()["num_words"] != header["vocab_size"]:
            return "serve replica never hot-reloaded the grown model"
        res = service.synonyms(_NEW_WORDS[0], 4)
        if not res or not all(np.isfinite(s) for _, s in res):
            return f"new-word query failed on the grown model: {res}"
        old = service.synonyms(_CLUSTER_A[0], 4)
        a_like = set(_CLUSTER_A) | set(_NEW_WORDS)
        if sum(1 for w, _ in old if w in a_like) < 2:
            return (f"old word {_CLUSTER_A[0]!r} lost its cluster after "
                    f"the increment: {old}")
        if service.stats()["refused"]:
            return "queries refused during the continual publishes"
        # the cursor JSON round-trips (the next driver run starts clean)
        with open(os.path.join(work_dir, "cursor.json")) as f:
            doc = _json.load(f)
        if "seg-001.txt" not in doc.get("consumed", {}):
            return "completed increment did not consume its segment"
    finally:
        service.close()
        runner.close()
    return ""


def phase_fleet_kill(workdir: str, n_sentences: int) -> str:
    """Serving-fleet chaos (ISSUE 12, docs/serving.md §5): SIGKILL a
    replica subprocess mid-query-storm — its circuit breaker must open,
    ZERO client queries may fail (retries land elsewhere), the ReplicaSet
    must restart it, and the breaker must recover through the half-open
    trial probe to closed; then a 3-publish rolling-reload storm must keep
    >= N-1 replicas serving at all times with every reload issued only
    after that replica's in-flight count drained (lease-drain per
    replica). Delegates to the fleet driver's drill (tools/fleet_run.py
    run_smoke — the same assertions CI's fleet job runs standalone)."""
    from tools.fleet_run import run_smoke
    try:
        rep = run_smoke(workdir, n_sentences, replicas=3)
    except AssertionError as e:
        return str(e)
    except Exception as e:  # noqa: BLE001 — any raise is the failure
        return f"{type(e).__name__}: {e}"
    if rep.get("failed_queries") != 0:
        return f"failed queries: {rep}"
    return ""


def _phase_supervisor(drill, workdir: str, n_sentences: int) -> str:
    """Shared wrapper for the three supervisor drills (ISSUE 16,
    docs/robustness.md §supervisor) — each delegates to the training
    driver's drill (tools/train_run.py, the same assertions CI's
    supervisor job runs standalone) and reports its first broken
    invariant."""
    os.makedirs(workdir, exist_ok=True)
    try:
        drill(workdir, n_sentences)
    except AssertionError as e:
        return str(e)
    except Exception as e:  # noqa: BLE001 — any raise is the failure
        return f"{type(e).__name__}: {e}"
    return ""


def phase_train_preempt(workdir: str, n_sentences: int) -> str:
    """A SIGTERM'd supervised fit must emergency-checkpoint within its
    preemption deadline (losing at most one dispatch chunk), get restarted
    from the verified save, and finish matching an uninterrupted twin."""
    from tools.train_run import run_preempt_drill
    return _phase_supervisor(run_preempt_drill, workdir, n_sentences)


def phase_train_stall(workdir: str, n_sentences: int) -> str:
    """An injected in-step hang must be detected within 2x the stall
    horizon, diagnosed (SIGTERM flight-recorder dump, then SIGKILL), and
    the run resumed to completion."""
    from tools.train_run import run_stall_drill
    return _phase_supervisor(run_stall_drill, workdir, n_sentences)


def phase_train_crashloop(workdir: str, n_sentences: int) -> str:
    """A deterministic every-attempt crash must walk the escalation ladder
    and quarantine with a machine-readable verdict in bounded attempts."""
    from tools.train_run import run_crashloop_drill
    return _phase_supervisor(run_crashloop_drill, workdir, n_sentences)


def phase_flaky_ingest(workdir: str) -> str:
    from glint_word2vec_tpu.data.corpus import encode_corpus
    from glint_word2vec_tpu.data.vocab import build_vocab
    from glint_word2vec_tpu.train import faults

    sents = toy_sentences(50, seed=3)
    vocab = build_vocab(sents, min_count=1)
    faults.configure(fail_ingest_first_n=2)
    try:
        enc = encode_corpus(sents, vocab, os.path.join(workdir, "enc"))
    except OSError as e:
        return f"retry wrapper did not absorb 2 injected faults: {e}"
    finally:
        faults.reset()
    if len(enc) != len(sents):
        return f"encoded {len(enc)} sentences, expected {len(sents)}"
    return ""


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--smoke", action="store_true",
                    help="small corpus / fast phases (tier-1 smoke)")
    ap.add_argument("--workdir", default="",
                    help="working directory (default: a fresh temp dir)")
    ap.add_argument("--worker", choices=["crash", "blackbox"],
                    help="internal: run a fault-target worker leg")
    ap.add_argument("--sentences", type=int, default=0)
    ap.add_argument("--only", default="",
                    help="comma-separated phase names to run (default: all) "
                         "— the CI serving job runs --only serve-reload")
    ap.add_argument("--list", action="store_true",
                    help="print available phase names and exit")
    args = ap.parse_args()

    n_sentences = args.sentences or (300 if args.smoke else 1500)
    if args.worker == "crash":
        worker_crash(args.workdir, n_sentences)
        return 3  # unreachable
    if args.worker == "blackbox":
        worker_blackbox(args.workdir, n_sentences)
        return 3  # unreachable

    workdir = args.workdir or tempfile.mkdtemp(prefix="glint_chaos_")
    os.makedirs(workdir, exist_ok=True)
    phases = [
        ("crash-resume",
         lambda: phase_crash_resume(os.path.join(workdir, "p1"), n_sentences)),
        ("corrupt-fallback",
         lambda: phase_corrupt_fallback(os.path.join(workdir, "p2"))),
        ("nan-rollback", lambda: phase_nan("rollback")),
        ("nan-halt", lambda: phase_nan("halt")),
        ("norm-blowup", phase_norm_blowup),
        ("norm-recover", phase_norm_recover),
        ("blackbox",
         lambda: phase_blackbox(os.path.join(workdir, "p5"), n_sentences)),
        ("serve-reload",
         lambda: phase_serve_reload(os.path.join(workdir, "p6"), n_sentences)),
        ("continual-drift",
         lambda: phase_continual_drift(os.path.join(workdir, "p7"),
                                       min(n_sentences, 400))),
        ("fleet-kill",
         lambda: phase_fleet_kill(os.path.join(workdir, "p8"),
                                  min(n_sentences, 300))),
        ("flaky-ingest",
         lambda: phase_flaky_ingest(os.path.join(workdir, "p4"))),
        ("train-preempt",
         lambda: phase_train_preempt(os.path.join(workdir, "p9"),
                                     min(n_sentences, 200))),
        ("train-stall",
         lambda: phase_train_stall(os.path.join(workdir, "p10"),
                                   min(n_sentences, 200))),
        ("train-crashloop",
         lambda: phase_train_crashloop(os.path.join(workdir, "p11"),
                                       min(n_sentences, 200))),
    ]
    if args.list:
        for name, _ in phases:
            print(name)
        return 0
    if args.only:
        want = {p.strip() for p in args.only.split(",") if p.strip()}
        names = [name for name, _ in phases]
        unknown = want - set(names)
        if unknown:
            print(f"[chaos] unknown phase(s): {sorted(unknown)} — "
                  f"available: {', '.join(names)}", flush=True)
            return 2
        phases = [(name, fn) for name, fn in phases if name in want]
    failures = 0
    for name, fn in phases:
        for sub in ("p1", "p2", "p4", "p6", "p8"):
            os.makedirs(os.path.join(workdir, sub), exist_ok=True)
        err = fn()
        status = "PASS" if not err else f"FAIL: {err}"
        print(f"[chaos] {name:18s} {status}", flush=True)
        failures += bool(err)
    if not args.workdir:
        shutil.rmtree(workdir, ignore_errors=True)
    print(f"[chaos] {'OK' if not failures else 'FAILED'} "
          f"({len(phases) - failures}/{len(phases)} phases passed)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
