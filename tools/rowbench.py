"""Row-traffic primitive benchmark: where does the SGNS step's bandwidth go?

The round-3 verdict computed that the B=64k f32 step moves ~604 MB of row traffic in
6.46 ms ≈ 93 GB/s against ~819 GB/s of v5e HBM — ~11% of roofline — and asked for a
component-level accounting. This tool times the step's constituent memory primitives
in isolation with the slope method (tools/microbench.py — the only trustworthy timing
through the remote-TPU tunnel):

    gather        — out = mat[idx]                      (read B rows)
    scatter-add   — mat.at[idx].add(upd)                (RMW B rows)
    dedup-scatter — sort idx, segment_sum rows, scatter unique rows only
    full permute  — upd[order]                          (read+write B rows)

each × {unique-shuffled, zipf} indices × {f32, bf16}, plus a copy bandwidth anchor
(mat + 1) to calibrate what "roofline" means for this chip through this runtime.

Run: python tools/rowbench.py [--quick]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

V, D, B = 200_000, 384, 65_536


def zipf_counts(v: int) -> np.ndarray:
    return np.maximum(1e9 / (np.arange(v) + 10.0) ** 1.07, 5.0)


def make_indices(kind: str, rng: np.random.Generator, n: int) -> np.ndarray:
    if kind == "unique":
        # B distinct rows, shuffled — no duplicate serialization possible
        return rng.choice(V, size=n, replace=False)
    if kind == "zipf":
        c = zipf_counts(V)
        return rng.choice(V, size=n, p=c / c.sum())
    if kind == "zipf_sorted":
        c = zipf_counts(V)
        return np.sort(rng.choice(V, size=n, p=c / c.sum()))
    raise ValueError(kind)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from microbench import time_chunked

    dev = jax.devices()[0]
    print(f"device: {dev}", file=sys.stderr)
    K = 8 if args.quick else 16

    rng = np.random.default_rng(0)

    def report(name, spc, bytes_moved):
        ms = spc / K * 1e3
        gbs = bytes_moved / (spc / K) / 1e9
        print(f"{name:42s} {ms:8.3f} ms  {gbs:8.1f} GB/s", file=sys.stderr)
        return ms, gbs

    results = {}
    for dt_name, dt in (("f32", jnp.float32), ("bf16", jnp.bfloat16)):
        itemsize = 4 if dt_name == "f32" else 2
        row_bytes = D * itemsize
        mat0 = jnp.asarray(rng.normal(0, 0.05, (V, D)), dt)
        upd0 = jnp.asarray(rng.normal(0, 1e-4, (B, D)), dt)

        # ---- copy anchor: read V rows + write V rows -------------------------
        def copy_chunk(m, _):
            def body(c, _x):
                return c * jnp.asarray(1.0001, dt), ()
            out, _ = jax.lax.scan(body, m, None, length=K)
            return out, out[0, 0]

        f = jax.jit(copy_chunk, donate_argnums=(0,))
        spc = time_chunked(f, lambda: mat0 + 0, lambda i: ((),),
                           n_lo=2, n_hi=8, fetch=lambda c, o: o)
        results[f"copy_{dt_name}"] = report(
            f"copy mat*c [{dt_name}] (2x{V}x{D})", spc, 2 * V * D * itemsize)

        idx_sets = {k: jnp.asarray(
            np.stack([make_indices(k, np.random.default_rng(100 + j), B)
                      for j in range(K)]), jnp.int32)
            for k in ("unique", "zipf", "zipf_sorted")}

        # ---- gather ----------------------------------------------------------
        for kind in ("unique", "zipf"):
            def gather_chunk2(c, m, idxs):
                def body(cc, ix):
                    g = m[ix]
                    return cc + g.astype(jnp.float32).sum(), ()
                out, _ = jax.lax.scan(body, c, idxs)
                return out, out

            f = jax.jit(gather_chunk2)
            spc = time_chunked(f, lambda: jnp.float32(0.0),
                               lambda i: (mat0, idx_sets[kind]),
                               n_lo=2, n_hi=8, fetch=lambda c, o: o)
            results[f"gather_{kind}_{dt_name}"] = report(
                f"gather B rows [{kind} {dt_name}]", spc, B * row_bytes)

        # ---- scatter-add -----------------------------------------------------
        for kind in ("unique", "zipf", "zipf_sorted"):
            def scat_chunk(m, u, idxs):
                def body(c, ix):
                    return c.at[ix].add(u), ()
                out, _ = jax.lax.scan(body, m, idxs)
                return out, out[0, 0]

            f = jax.jit(scat_chunk, donate_argnums=(0,))
            spc = time_chunked(f, lambda: mat0 + 0,
                               lambda i: (upd0, idx_sets[kind]),
                               n_lo=2, n_hi=8, fetch=lambda c, o: o)
            # RMW of ~B rows: B read + B write (upper bound; duplicates make it less)
            results[f"scatter_{kind}_{dt_name}"] = report(
                f"scatter-add B rows [{kind} {dt_name}]", spc, 2 * B * row_bytes)

        # ---- scatter-add with XLA's sorted/unique fast-path flags ------------
        for kind, flags in (("zipf_sorted", dict(indices_are_sorted=True)),
                            ("unique", dict(unique_indices=True)),):
            def scat_flag_chunk(m, u, idxs):
                def body(c, ix):
                    return c.at[ix].add(u, **flags), ()
                out, _ = jax.lax.scan(body, m, idxs)
                return out, out[0, 0]

            f = jax.jit(scat_flag_chunk, donate_argnums=(0,))
            spc = time_chunked(f, lambda: mat0 + 0,
                               lambda i: (upd0, idx_sets[kind]),
                               n_lo=2, n_hi=8, fetch=lambda c, o: o)
            fl = "+".join(k for k in flags)
            results[f"scatter_{kind}_{fl}_{dt_name}"] = report(
                f"scatter-add [{kind} {fl} {dt_name}]", spc, 2 * B * row_bytes)

        # unique AND sorted with both flags — the theoretical XLA fast path
        uniq_sorted = jnp.sort(idx_sets["unique"], axis=-1)

        def scat_us_chunk(m, u, idxs):
            def body(c, ix):
                return c.at[ix].add(u, indices_are_sorted=True,
                                    unique_indices=True), ()
            out, _ = jax.lax.scan(body, m, idxs)
            return out, out[0, 0]

        f = jax.jit(scat_us_chunk, donate_argnums=(0,))
        spc = time_chunked(f, lambda: mat0 + 0, lambda i: (upd0, uniq_sorted),
                           n_lo=2, n_hi=8, fetch=lambda c, o: o)
        results[f"scatter_uniqsorted_bothflags_{dt_name}"] = report(
            f"scatter-add [unique sorted both-flags {dt_name}]", spc,
            2 * B * row_bytes)

        # ---- scatter-add with half the rows dropped (OOB index) --------------
        drop_idx = np.stack([make_indices("zipf", np.random.default_rng(300 + j), B)
                             for j in range(K)])
        dmask = np.random.default_rng(9).random((K, B)) < 0.5
        drop_idx = np.where(dmask, V, drop_idx)  # OOB -> dropped by XLA scatter

        def scat_drop_chunk(m, u, idxs):
            def body(c, ix):
                return c.at[ix].add(u, mode="drop"), ()
            out, _ = jax.lax.scan(body, m, idxs)
            return out, out[0, 0]

        f = jax.jit(scat_drop_chunk, donate_argnums=(0,))
        spc = time_chunked(f, lambda: mat0 + 0,
                           lambda i: (upd0, jnp.asarray(drop_idx, jnp.int32)),
                           n_lo=2, n_hi=8, fetch=lambda c, o: o)
        results[f"scatter_half_dropped_{dt_name}"] = report(
            f"scatter-add [zipf 50% OOB-dropped {dt_name}]", spc, B * row_bytes)

        # ---- hot-row accumulate via one-hot matmul (MXU path) ----------------
        for H in (1024, 2048):
            def onehot_chunk(m, u, idxs):
                def body(c, ix):
                    oh = (ix[:, None] == jnp.arange(H)[None, :]).astype(dt)
                    hot = (oh.T @ u.astype(dt)).astype(dt)       # [H, D] on MXU
                    return c.at[jnp.arange(H)].add(hot), ()
                out, _ = jax.lax.scan(body, m, idxs)
                return out, out[0, 0]

            f = jax.jit(onehot_chunk, donate_argnums=(0,))
            spc = time_chunked(f, lambda: mat0 + 0,
                               lambda i: (upd0, idx_sets["zipf"]),
                               n_lo=2, n_hi=8, fetch=lambda c, o: o)
            results[f"onehot_H{H}_{dt_name}"] = report(
                f"one-hot matmul accum H={H} [{dt_name}]", spc,
                B * row_bytes + 2 * H * row_bytes)

        # ---- cumsum over [B, D] (sorted-segment-sum building block) ----------
        def cumsum_chunk(c, u, idxs):
            def body(cc, ix):
                s = jnp.cumsum(u.astype(jnp.float32), axis=0)
                return cc + s[-1, 0], ()
            out, _ = jax.lax.scan(body, c, idxs)
            return out, out

        f = jax.jit(cumsum_chunk)
        spc = time_chunked(f, lambda: jnp.float32(0.0),
                           lambda i: (upd0, idx_sets["zipf"]),
                           n_lo=2, n_hi=8, fetch=lambda c, o: o)
        results[f"cumsum_{dt_name}"] = report(
            f"cumsum [B,D] [{dt_name}]", spc, 2 * B * row_bytes)

        # ---- dedup scatter-add (sort + segment_sum + unique-row scatter) -----
        for kind in ("unique", "zipf"):
            def dedup_chunk(m, u, idxs):
                def body(c, ix):
                    order = jnp.argsort(ix)
                    sidx = ix[order]
                    supd = u[order]
                    seg_start = jnp.concatenate(
                        [jnp.ones((1,), jnp.int32),
                         (sidx[1:] != sidx[:-1]).astype(jnp.int32)])
                    seg_id = jnp.cumsum(seg_start) - 1
                    sums = jax.ops.segment_sum(supd, seg_id, num_segments=B)
                    seg_row = jnp.full((B,), V, jnp.int32).at[seg_id].min(sidx)
                    return c.at[seg_row].add(sums.astype(dt)), ()
                out, _ = jax.lax.scan(body, m, idxs)
                return out, out[0, 0]

            f = jax.jit(dedup_chunk, donate_argnums=(0,))
            spc = time_chunked(f, lambda: mat0 + 0,
                               lambda i: (upd0, idx_sets[kind]),
                               n_lo=2, n_hi=8, fetch=lambda c, o: o)
            results[f"dedup_{kind}_{dt_name}"] = report(
                f"dedup scatter-add [{kind} {dt_name}]", spc, 2 * B * row_bytes)

        # ---- dedup, pre-sorted indices (host sorts; no permute gather) -------
        def dedup_sorted_chunk(m, u, idxs):
            def body(c, ix):
                seg_start = jnp.concatenate(
                    [jnp.ones((1,), jnp.int32),
                     (ix[1:] != ix[:-1]).astype(jnp.int32)])
                seg_id = jnp.cumsum(seg_start) - 1
                sums = jax.ops.segment_sum(u, seg_id, num_segments=B)
                seg_row = jnp.full((B,), V, jnp.int32).at[seg_id].min(ix)
                return c.at[seg_row].add(sums.astype(dt)), ()
            out, _ = jax.lax.scan(body, m, idxs)
            return out, out[0, 0]

        f = jax.jit(dedup_sorted_chunk, donate_argnums=(0,))
        spc = time_chunked(f, lambda: mat0 + 0,
                           lambda i: (upd0, idx_sets["zipf_sorted"]),
                           n_lo=2, n_hi=8, fetch=lambda c, o: o)
        results[f"dedup_presorted_{dt_name}"] = report(
            f"dedup scatter-add [presorted zipf {dt_name}]", spc, 2 * B * row_bytes)

        # ---- row permute (cost of reordering a [B,D] update) -----------------
        perm = jnp.asarray(np.stack([np.random.default_rng(7 + j).permutation(B)
                                     for j in range(K)]), jnp.int32)

        def perm_chunk(c, u, perms):
            def body(cc, pr):
                return cc + u[pr].astype(jnp.float32).sum(), ()
            out, _ = jax.lax.scan(body, c, perms)
            return out, out

        f = jax.jit(perm_chunk)
        spc = time_chunked(f, lambda: jnp.float32(0.0), lambda i: (upd0, perm),
                           n_lo=2, n_hi=8, fetch=lambda c, o: o)
        results[f"permute_{dt_name}"] = report(
            f"permute B update rows [{dt_name}]", spc, B * row_bytes)

        # ---- argsort cost ----------------------------------------------------
        def sort_chunk(c, idxs):
            def body(cc, ix):
                return cc + jnp.argsort(ix)[0], ()
            out, _ = jax.lax.scan(body, c, idxs)
            return out, out

        f = jax.jit(sort_chunk)
        spc = time_chunked(f, lambda: jnp.int32(0), lambda i: (idx_sets["zipf"],),
                           n_lo=2, n_hi=8, fetch=lambda c, o: o)
        results[f"argsort_{dt_name}"] = report(
            f"argsort B int32 [{dt_name} run]", spc, 2 * B * 4)

    print("\nsummary ms/op:", file=sys.stderr)
    for k, (ms, gbs) in results.items():
        print(f"  {k:28s} {ms:8.3f} ms {gbs:8.1f} GB/s", file=sys.stderr)


if __name__ == "__main__":
    main()
