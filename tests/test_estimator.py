"""End-to-end estimator tests on a small synthetic corpus: fit → model ops → save/load →
resume; compat layer surface; CBOW path; trainer heartbeats."""

import warnings

import numpy as np
import pytest

from glint_word2vec_tpu import (
    ServerSideGlintWord2Vec,
    ServerSideGlintWord2VecModel,
    Word2Vec,
    Word2VecConfig,
)
from glint_word2vec_tpu.train.checkpoint import load_model


def two_topic_corpus(n=300, seed=0):
    """Two disjoint co-occurrence clusters: {a,b,c} and {x,y,z}."""
    rng = np.random.default_rng(seed)
    sents = []
    for _ in range(n):
        ws = ["a", "b", "c"] if rng.integers(0, 2) == 0 else ["x", "y", "z"]
        sents.append([ws[i] for i in rng.integers(0, 3, 10)])
    return sents


CFG = dict(vector_size=16, window=3, negatives=5, min_count=1, num_iterations=3,
           learning_rate=0.025, pairs_per_batch=128, subsample_ratio=0.0, seed=1)


@pytest.fixture(scope="module")
def fitted():
    sents = two_topic_corpus()
    model = Word2Vec(**CFG).fit(sents)
    return model, sents


def test_fit_produces_valid_model(fitted):
    # NOTE: semantic-quality gates live in test_integration_toy.py on the real corpus —
    # micro-vocab synthetic corpora do not yield separated cosine geometry even for
    # textbook sequential word2vec (verified against a numpy reference implementation).
    model, _ = fitted
    assert model.num_words == 6
    mat = np.asarray(model.syn0)
    assert np.all(np.isfinite(mat)) and np.abs(mat).sum() > 0
    syns = model.find_synonyms("a", 5)
    assert len(syns) == 5 and all(np.isfinite(s) for _, s in syns)


def test_fit_deterministic_per_seed():
    sents = two_topic_corpus(50)
    m1 = Word2Vec(**CFG).fit(sents)
    m2 = Word2Vec(**CFG).fit(sents)
    np.testing.assert_array_equal(np.asarray(m1.syn0), np.asarray(m2.syn0))
    cfg3 = dict(CFG); cfg3["seed"] = 9
    m3 = Word2Vec(**cfg3).fit(sents)
    assert not np.array_equal(np.asarray(m1.syn0), np.asarray(m3.syn0))


def test_heartbeats_recorded(fitted):
    model, _ = fitted
    # alpha decays over training (reference schedule mllib:405-413)
    assert model.train_state.finished
    assert model.train_state.words_processed > 0


def test_heartbeats_sample_real_loss_despite_fast_twin():
    """The trainer dispatches a metrics-elided step twin for chunks no
    heartbeat samples (PERF.md §4). Heartbeat rows must still carry the REAL
    loss — a 0.0 loss in a heartbeat means the elision prediction missed."""
    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.data.pipeline import encode_sentences
    from glint_word2vec_tpu.data.vocab import build_vocab
    from glint_word2vec_tpu.train.trainer import Trainer

    sents = two_topic_corpus(400)
    vocab = build_vocab(sents, 1)
    cfg = Word2VecConfig(vector_size=16, window=3, negatives=3, min_count=1,
                         num_iterations=4, pairs_per_batch=128, negative_pool=16,
                         steps_per_dispatch=2, heartbeat_every_steps=8,
                         subsample_ratio=0.0, seed=1)
    t = Trainer(cfg, vocab)
    assert t._step_fn_fast is not t._step_fn  # shared-pool path builds the twin
    # count twin usage: the elision must actually run (a regression that always
    # picks the full twin would otherwise pass every assertion below)
    used = {"fast": 0, "full": 0}
    fast, full = t._step_fn_fast, t._step_fn

    def fast_counting(*a, **kw):
        used["fast"] += 1
        return fast(*a, **kw)

    def full_counting(*a, **kw):
        used["full"] += 1
        return full(*a, **kw)

    t._step_fn_fast, t._step_fn = fast_counting, full_counting
    t.fit(encode_sentences(sents, vocab, cfg.max_sentence_length))
    assert t.heartbeats, "cadence 8 over hundreds of steps must fire"
    assert all(np.isfinite(h.loss) and h.loss > 0.0 for h in t.heartbeats)
    assert used["fast"] > 0 and used["full"] > 0, used

    # and the twins really are interchangeable: the same fit with elision
    # disabled (fast twin never used) lands on bit-identical params
    t2 = Trainer(cfg, vocab)
    t2._step_fn_fast = t2._step_fn
    t2.fit(encode_sentences(sents, vocab, cfg.max_sentence_length))
    np.testing.assert_array_equal(np.asarray(t.params.syn0),
                                  np.asarray(t2.params.syn0))
    np.testing.assert_array_equal(np.asarray(t.params.syn1),
                                  np.asarray(t2.params.syn1))


def test_save_load_resume(tmp_path, fitted):
    model, sents = fitted
    path = str(tmp_path / "m")
    model.save(path)
    data = load_model(path)
    assert data["train_state"].finished
    loaded = ServerSideGlintWord2VecModel.load(path)
    np.testing.assert_allclose(
        loaded.inner.transform("a"), model.transform("a"), rtol=1e-6)


def test_mid_training_checkpoint_and_resume(tmp_path):
    sents = two_topic_corpus(100)
    path = str(tmp_path / "ckpt")
    cfg = dict(CFG)
    cfg["num_iterations"] = 2
    Word2Vec(**cfg).fit(sents, checkpoint_path=path, checkpoint_every_steps=2)
    data = load_model(path)
    assert data["syn1"] is not None  # trainable state present
    resumed = Word2Vec.resume(path, sents)
    assert resumed.train_state.finished


def test_compat_builder_surface():
    sents = two_topic_corpus(150)
    w2v = (ServerSideGlintWord2Vec()
           .setVectorSize(12)
           .setLearningRate(0.05)
           .setNumIterations(2)
           .setWindowSize(3)
           .setMinCount(1)
           .setSubsampleRatio(1.0)
           .setBatchSize(50)
           .setN(5)
           .setSeed(3)
           .setNumParameterServers(2)
           .setMaxSentenceLength(100)
           .setUnigramTableSize(10 ** 6)
           .setNumPartitions(1))
    model = w2v.fit(sents)
    vecs = model.getVectors()
    assert set(vecs) == {"a", "b", "c", "x", "y", "z"}
    assert vecs["a"].shape == (12,)
    # single word transform (mllib path) and sentence transform (ml path)
    assert model.transform("a").shape == (12,)
    out = model.transform([["a", "b"], ["x"]])
    assert out.shape == (2, 12)
    arr = model.findSynonymsArray("a", 2)
    assert len(arr) == 2
    words, mat = model.toLocal()
    assert len(words) == 6 and mat.shape == (6, 12)
    model.stop(terminateOtherClients=True)


def test_compat_ps_knobs_warn():
    w2v = ServerSideGlintWord2Vec()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        w2v.setParameterServerHost("10.0.0.1")
        w2v.setParameterServerConfig({"glint.master.port": 13380})
        w2v.setBatchSize(100).setN(20).setWindowSize(10)  # 20000 > 10000 budget
    msgs = " ".join(str(r.message) for r in rec)
    assert "no parameter servers" in msgs
    assert "Akka" in msgs


def test_compat_dict_rows():
    sents = two_topic_corpus(100)
    rows = [{"sentence": s, "id": i} for i, s in enumerate(sents[:20])]
    w2v = (ServerSideGlintWord2Vec().setVectorSize(8).setMinCount(1)
           .setSubsampleRatio(1.0).setSeed(0))
    model = w2v.fit(rows)
    out = model.transform(rows[:3])
    # transform preserves extra columns and appends the output col (it spec:260-288)
    assert set(out[0]) == {"sentence", "id", "vector"}
    assert out[0]["vector"].shape == (8,)


def test_cbow_end_to_end():
    sents = two_topic_corpus(300)
    cfg = dict(CFG)
    cfg["cbow"] = True
    model = Word2Vec(**cfg).fit(sents)
    mat = np.asarray(model.syn0)
    assert np.all(np.isfinite(mat)) and np.abs(mat).sum() > 0


def test_config_object_plus_overrides():
    cfg = Word2VecConfig(vector_size=8)
    est = Word2Vec(cfg, window=2)
    assert est.config.vector_size == 8 and est.config.window == 2


def test_negative_pool_and_lane_padding_end_to_end():
    sents = two_topic_corpus(100)
    cfg = dict(CFG)
    cfg.update(negative_pool=16, vector_size=20)  # pads to 128 internally
    model = Word2Vec(**cfg).fit(sents)
    # exports are sliced back to the logical vector size
    assert model.transform("a").shape == (20,)
    words, mat = model.to_local()
    assert mat.shape == (6, 20)
    assert np.all(np.isfinite(mat))


def test_lane_padding_columns_stay_zero():
    from glint_word2vec_tpu.data.pipeline import encode_sentences
    from glint_word2vec_tpu.data.vocab import build_vocab
    from glint_word2vec_tpu.train.trainer import Trainer
    from glint_word2vec_tpu.config import Word2VecConfig

    sents = two_topic_corpus(50)
    vocab = build_vocab(sents, 1)
    cfg = Word2VecConfig(vector_size=20, min_count=1, pairs_per_batch=64,
                         num_iterations=1)
    tr = Trainer(cfg, vocab)
    assert tr.padded_dim == 128
    tr.fit(encode_sentences(sents, vocab))
    full = np.asarray(tr.params.syn0)
    assert full.shape[1] == 128
    np.testing.assert_array_equal(full[:, 20:], 0.0)


def test_compat_batch_size_maps_to_device_batch():
    """setBatchSize/setNumPartitions map to pairs_per_batch (their product, the
    reference's concurrent-pair count, mllib:417-429) — with a perf warning for tiny
    batches. Untouched knobs keep the TPU-efficient config default."""
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        cfg = (ServerSideGlintWord2Vec()
               .setBatchSize(50).setNumPartitions(4).to_config())
    assert cfg.pairs_per_batch == 200
    assert any("pairs_per_batch" in str(r.message) for r in rec)

    default_cfg = ServerSideGlintWord2Vec().to_config()
    from glint_word2vec_tpu.config import Word2VecConfig
    assert default_cfg.pairs_per_batch == Word2VecConfig().pairs_per_batch


def test_negative_and_64bit_seeds_train():
    """Any Python-int seed must work: negative and >=2**31 seeds masked to uint32
    previously crashed at trace time via int32 canonicalization (ADVICE r2)."""
    sents = two_topic_corpus(30)
    for seed in (-123, 2 ** 31 + 7, 2 ** 40 + 1):
        cfg = dict(CFG)
        cfg.update(seed=seed, num_iterations=1)
        model = Word2Vec(**cfg).fit(sents)
        assert np.all(np.isfinite(np.asarray(model.syn0)))


def test_global_step_persisted_across_resume(tmp_path):
    """The hash-PRNG counter continues after resume: the resumed trainer must not
    restart the (seed, counter) negative-sample lattice at 0 (ADVICE r2)."""
    from glint_word2vec_tpu.train.checkpoint import load_model as _load

    sents = two_topic_corpus(100)
    path = str(tmp_path / "ckpt")
    cfg = dict(CFG)
    cfg["num_iterations"] = 2
    Word2Vec(**cfg).fit(sents, checkpoint_path=path, checkpoint_every_steps=2)
    state = _load(path)["train_state"]
    assert state.global_step > 0
    from glint_word2vec_tpu.train.trainer import Trainer
    from glint_word2vec_tpu.data.vocab import Vocabulary
    from glint_word2vec_tpu.ops.sgns import EmbeddingPair
    import jax.numpy as jnp

    data = _load(path)
    vocab = Vocabulary.from_words_and_counts(data["words"], data["counts"])
    tr = Trainer(data["config"], vocab,
                 params=EmbeddingPair(jnp.asarray(data["syn0"]),
                                      jnp.asarray(data["syn1"])),
                 train_state=state)
    assert tr.global_step == state.global_step


def test_exact_step_resume_matches_uninterrupted(tmp_path):
    """Interrupt mid-iteration (via checkpoint), resume, and match the uninterrupted
    run's final params bit-for-bit (VERDICT r2 #8). Checkpoint cadence aligned to
    steps_per_dispatch so the PRNG dispatch boundaries replay identically."""
    sents = two_topic_corpus(200, seed=4)
    cfg = dict(CFG)
    cfg.update(num_iterations=2, steps_per_dispatch=4, pairs_per_batch=64)

    baseline = Word2Vec(**cfg).fit(sents)

    path = str(tmp_path / "ckpt")
    from glint_word2vec_tpu.train.checkpoint import load_model as _load

    class StopTraining(Exception):
        pass

    # run until the first mid-iteration checkpoint exists, then abort the process
    # the blunt way a crash would
    from glint_word2vec_tpu.data.pipeline import encode_sentences
    from glint_word2vec_tpu.data.vocab import build_vocab
    from glint_word2vec_tpu.train.trainer import Trainer

    vocab = build_vocab(sents, 1)
    enc = encode_sentences(sents, vocab, 1000)
    tr = Trainer(Word2VecConfig(**cfg), vocab)
    n_dispatches = [0]
    orig_fn = tr._step_fn

    def counting(*a, **kw):
        n_dispatches[0] += 1
        if n_dispatches[0] == 3:  # partway through iteration 1, after 2 dispatches
            # save BEFORE dispatching: the step donates (and thus deletes) the input
            # params, so the consistent snapshot is the pre-dispatch state
            tr.save_checkpoint(path)
            raise StopTraining()
        return orig_fn(*a, **kw)

    # patch BOTH twins: _dispatch_step_fn may hand out the metrics-elided twin
    # for chunks no heartbeat samples
    tr._step_fn = tr._step_fn_fast = counting
    try:
        tr.fit(enc)
    except StopTraining:
        pass
    state = _load(path)["train_state"]
    assert not state.finished and state.batches_done > 0

    resumed = Word2Vec.resume(path, sents)
    np.testing.assert_array_equal(
        np.asarray(resumed.syn0), np.asarray(baseline.syn0))


def test_profile_dir_captures_trace(tmp_path):
    """config.profile_dir wraps fit() in a jax.profiler trace (SURVEY §5: the
    reference has no profiling at all; this plus the host-wait/dispatch split is
    the observability story)."""
    import os

    from glint_word2vec_tpu import Word2Vec

    rng = np.random.default_rng(0)
    words = [f"w{i}" for i in range(30)]
    sents = [[words[j] for j in rng.integers(0, 30, 8)] for _ in range(40)]
    prof = str(tmp_path / "prof")
    Word2Vec(vector_size=8, min_count=1, pairs_per_batch=64, num_iterations=1,
             window=2, negatives=2, negative_pool=8, steps_per_dispatch=2,
             seed=3, profile_dir=prof).fit(sents)
    found = [os.path.join(r, f) for r, _, fs in os.walk(prof) for f in fs]
    assert found, "profiler trace directory is empty"


def test_stability_warnings_fire(caplog):
    """The trainer warns on the three measured divergence regimes (EVAL.md): pool
    overload, duplicate overload, and the compounding band that NaN'd at 60M words
    while passing both individual thresholds. Since round 5 the duplicate channel
    REFUSES at construction (tests/test_stability_gates.py); the warn-only
    behavior asserted here rides the allow_unstable override."""
    import logging

    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.data.vocab import Vocabulary
    from glint_word2vec_tpu.train.trainer import Trainer

    # Zipfy counts: top word ~0.4% of the (unsubsampled) stream
    counts = np.maximum(2_000_000 / (np.arange(5000) + 10.0) ** 1.05, 5).astype(int)
    vocab = Vocabulary.from_words_and_counts(
        [f"w{i}" for i in range(5000)], counts)

    def warns(**kw):
        cfg = Word2VecConfig(vector_size=16, min_count=1, **kw)
        with caplog.at_level(logging.WARNING, logger="glint_word2vec_tpu"):
            caplog.clear()
            Trainer(cfg, vocab)
        return [r.message for r in caplog.records]

    # pool overload: load 5120
    assert any("pool" in m for m in warns(
        pairs_per_batch=65536, negatives=5, negative_pool=64,
        subsample_ratio=1e-4))
    # duplicate overload: no subsampling, top word >300 dups per 64k batch
    assert any("duplicates" in m for m in warns(
        pairs_per_batch=65536, negatives=5, negative_pool=1024,
        subsample_ratio=0.0, allow_unstable=True))
    # compounding band: both below individual thresholds, warned jointly
    msgs = warns(pairs_per_batch=65536, negatives=5, negative_pool=256,
                 subsample_ratio=1e-4)
    assert any("compound" in m for m in msgs), msgs
    # the duplicate channel is warned on the per-pair path too (negative_pool=0)
    assert any("duplicates" in m for m in warns(
        pairs_per_batch=65536, negatives=5, negative_pool=0,
        subsample_ratio=0.0, allow_unstable=True))
    # a safe config stays quiet
    assert not warns(pairs_per_batch=16384, negatives=5, negative_pool=64,
                     subsample_ratio=1e-4)


def test_auto_negative_pool_scales_with_batch():
    """The default (negative_pool=-1) resolves so pool load B*n/P stays <= 600 —
    the measured 60M-word stability rule (EVAL.md) — rounded to the 128 lane tile."""
    from glint_word2vec_tpu.config import Word2VecConfig

    cfg = Word2VecConfig(pairs_per_batch=65536)
    assert cfg.negative_pool >= 512
    assert cfg.negative_pool % 128 == 0
    assert 65536 * cfg.negatives / cfg.negative_pool <= 600
    small = Word2VecConfig(pairs_per_batch=8192)
    assert small.negative_pool == 128
    # below the MXU-amortization scale auto keeps the per-pair exact path:
    # shared negatives measurably cost quality on small corpora (toy bf16 gate)
    assert Word2VecConfig(pairs_per_batch=256).negative_pool == 0
    assert Word2VecConfig(pairs_per_batch=4096).negative_pool == 128
    # the pallas step requires a shared pool — auto never strands it at 0
    assert Word2VecConfig(pairs_per_batch=256, use_pallas=True).negative_pool == 128
    # explicit choices pass through untouched; 0 keeps the per-pair path
    assert Word2VecConfig(negative_pool=256).negative_pool == 256
    assert Word2VecConfig(negative_pool=0).negative_pool == 0
    # the compat layer pins the reference's exact per-pair semantics
    from glint_word2vec_tpu.models.compat import ServerSideGlintWord2Vec
    assert ServerSideGlintWord2Vec().to_config().negative_pool == 0
