"""Zero-downtime hot-reload: swap-window-safe loading, a lease-counted
serving handle, and the checkpoint-publish watcher.

The trainer's atomic save protocol (train/checkpoint.py) already gives
serving a clean publish signal: a completed save replaces the checkpoint
directory in two renames (``path`` → ``path.old-<pid>``, staged
``.tmp-*`` → ``path``), so ``<path>/metadata.json`` changes identity
exactly once per publish — and is briefly ABSENT inside the sub-second
swap window. This module owns the serving side of that protocol:

- :func:`load_with_retry` — THE single owner of the swap-window retry
  logic (extracted from tools/serve_checkpoint.py, which now calls this):
  transient mid-swap failures (missing path, half-written JSON, a
  metadata/words pair read across the two renames) retry over the window;
  permanent problems (bad mesh for the shard layout, corrupt arrays)
  surface immediately.
- :class:`ServingHandle` — the atomically swappable ``(model, index)``
  pair with lease counting: a dispatch takes a lease for the whole batch,
  ``swap()`` installs the new pair instantly for FUTURE batches, and the
  old model's device buffers are released only when its last in-flight
  lease drains — no query ever observes a stopped model, no buffer ever
  leaks past the drain.
- :class:`CheckpointWatcher` — a poll thread (graftlint R1 sanctioned
  owner: read-only on params, it only stats a file and invokes the
  service's reload callback) that detects the publish signal and triggers
  the background load + index build + swap.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Iterator, Optional, Tuple

import numpy as np
from glint_word2vec_tpu.lockcheck import make_lock

logger = logging.getLogger("glint_word2vec_tpu")


def decorrelated_jitter(base: float, cap: float, rng) -> Iterator[float]:
    """AWS-style decorrelated-jitter backoff delays: each delay is drawn
    ``uniform(base, 3 × previous)``, capped at ``cap``.

    Why not the old fixed interval: N fleet replicas watching ONE publish
    path all hit the same swap window at the same poll tick; fixed-interval
    retry keeps them phase-locked — every retry round lands N simultaneous
    directory scans + digest reads on the same files (the thundering herd).
    Decorrelation spreads the rounds apart while keeping the expected delay
    near the base; the cap bounds the tail so a budgeted retry loop still
    has a predictable worst case.

    ``rng`` is an explicitly seeded ``np.random.Generator`` (the R2
    determinism contract — tests pin the exact sequence per seed; production
    callers seed per process so replicas genuinely decorrelate)."""
    prev = base
    while True:
        prev = min(cap, float(rng.uniform(base, max(base, prev * 3))))
        yield prev


def load_with_retry(path: str, plan=None, attempts: int = 8,
                    delay: float = 0.25, max_delay: float = 2.0,
                    rng=None):
    """Load a checkpoint, absorbing the trainer's atomic-swap window.

    The swap has a sub-second window where the checkpoint path is
    mid-rename / the old dir is being removed; a load landing inside it
    sees FileNotFoundError or a half-listed directory. Retry over the
    window instead of bouncing the error to the caller. Only the transient
    swap-window failures retry: a missing path, half-written JSON, or a
    metadata/words pair read across the two renames (surfaces as the
    loader's vocab_size-mismatch ValueError). A digest-mismatch
    CheckpointCorruptError also retries: under rapid publishing a load can
    read publish N's metadata and publish N+1's arrays (two ATOMIC saves,
    one straddling reader — observed live in the serve-reload chaos
    phase), indistinguishable from bit rot on one attempt but healed on
    retry; REAL corruption keeps failing and still raises once the budget
    is spent. Permanent problems (bad mesh for the shard layout) surface
    immediately.

    Continual publishes make the cross-publish torn read CONCRETE: when the
    vocabulary grew between publish N and N+1 (continual/extend.py), a
    straddling reader sees publish N+1's metadata (vocab_size = V_new) with
    publish N's words sidecar or arrays (V_old entries) — exactly the
    loader's vocab_size/words-mismatch ValueErrors retried below. A load
    that SUCCEEDED is therefore always one self-consistent publish; the
    V-grew case is driven in the serve-reload and continual-drift chaos
    phases.

    Backoff between attempts is DECORRELATED JITTER over
    ``[delay, max_delay]`` (:func:`decorrelated_jitter`): a fleet of
    replicas retrying the same publish path must not synchronize into a
    thundering herd — the pre-fleet fixed interval phase-locked them. Pass
    a seeded ``rng`` to pin the sequence (tests); the default seeds from
    the pid + clock so each replica PROCESS draws a different sequence."""
    from glint_word2vec_tpu.models.word2vec import Word2VecModel
    from glint_word2vec_tpu.train.checkpoint import CheckpointCorruptError
    if rng is None:
        # seeded Generator (R2): decorrelation across processes is the
        # point, so the seed folds in process identity + time
        rng = np.random.default_rng((os.getpid(), time.monotonic_ns()))
    delays = decorrelated_jitter(delay, max_delay, rng)
    last: Optional[BaseException] = None
    for i in range(attempts):
        try:
            return Word2VecModel.load(path, plan=plan)
        except (FileNotFoundError, json.JSONDecodeError,
                CheckpointCorruptError) as e:
            last = e
        except ValueError as e:
            if "vocab_size" not in str(e) and "words" not in str(e):
                raise
            last = e
        if i == attempts - 1:
            raise last
        time.sleep(next(delays))


def publish_signature(checkpoint_path: str) -> Optional[Tuple[int, int, int]]:
    """The checkpoint's current publish identity (``metadata.json``
    mtime/inode/size), or None while absent / mid-swap. Capture this
    BEFORE loading and record it as served AFTER the load succeeds — a
    publish landing during a slow load/index build then still differs
    from the recorded signature and re-fires (capturing after the load
    would permanently swallow it)."""
    try:
        st = os.stat(os.path.join(checkpoint_path, "metadata.json"))
    except OSError:
        return None
    return (st.st_mtime_ns, st.st_ino, st.st_size)


def publish_signature_str(sig: Optional[Tuple[int, int, int]]
                          ) -> Optional[str]:
    """The signature's stable wire/telemetry form (``mtime_ns-inode-size``),
    or None while unknown (in-memory model, or captured mid-swap). ONE
    owner: the replica protocol's ``stats`` reply, the router's staleness
    compare, the trainer's ``publish`` record, and every ``publish_sig``
    telemetry field all format through here — the collector joins publish
    chains by string equality, so a second formatter would silently break
    the join."""
    return None if sig is None else "-".join(str(x) for x in sig)


class _Slot:
    """One (model, index) generation plus its lease count. ``refs`` starts
    at 1 — the handle's own reference; ``swap`` drops it."""

    __slots__ = ("model", "index", "refs")

    def __init__(self, model, index):
        self.model = model
        self.index = index
        self.refs = 1


class ServingHandle:
    """Atomically swappable (model, index) with lease-counted release."""

    def __init__(self, model, index=None):
        self._lock = make_lock("serve.handle")
        self._current: Optional[_Slot] = _Slot(model, index)
        self.models_released = 0
        self.swaps = 0

    @contextlib.contextmanager
    def lease(self) -> Iterator[Tuple[Any, Any]]:
        """Pin the CURRENT generation for the duration of one batch: the
        yielded pair stays alive (buffers un-released) until the context
        exits, even if a swap lands mid-batch."""
        with self._lock:
            slot = self._current
            if slot is None:
                raise RuntimeError("serving handle is stopped")
            slot.refs += 1
        try:
            yield slot.model, slot.index
        finally:
            self._release(slot)

    def _release(self, slot: _Slot) -> None:
        with self._lock:
            slot.refs -= 1
            drained = slot.refs == 0
            if drained:
                self.models_released += 1
        if drained:
            # outside the lock: stop() deletes device buffers
            try:
                slot.model.stop()
            except Exception:  # noqa: BLE001 — release is best-effort
                logger.warning("old serving model release failed",
                               exc_info=True)

    def swap(self, model, index=None) -> None:
        """Install a new generation. Future leases see the new pair
        immediately; the old generation is released when its in-flight
        leases drain (possibly right here, if none are in flight)."""
        new = _Slot(model, index)
        with self._lock:
            old = self._current
            if old is None:
                raise RuntimeError("serving handle is stopped")
            self._current = new
            self.swaps += 1
        self._release(old)  # drop the handle's own reference

    def stop(self) -> None:
        """Release the current generation (after in-flight leases drain)
        and refuse further leases. Idempotent."""
        with self._lock:
            old = self._current
            self._current = None
        if old is not None:
            self._release(old)

    def detach(self) -> None:
        """Refuse further leases WITHOUT releasing the current model — for
        callers that own the model's lifecycle themselves (a service built
        over an in-memory ``model=`` keeps the caller's buffers alive; the
        bench reuses one matrix across service arms)."""
        with self._lock:
            self._current = None


class CheckpointWatcher:
    """Publish-signal poller: fires ``on_publish()`` when the checkpoint's
    ``metadata.json`` changes identity (mtime/inode/size), i.e. once per
    completed trainer save. The mid-swap ABSENT state is not a signal —
    the next poll after the swap completes sees the new identity."""

    def __init__(self, checkpoint_path: str,
                 on_publish: Callable[[], None],
                 poll_s: float = 0.5,
                 loaded_signature: Optional[Tuple[int, int, int]] = None,
                 name: str = "glint-serve-watcher"):
        """``loaded_signature`` is the :func:`publish_signature` captured
        BEFORE the caller loaded the model it is now serving — a publish
        that landed during that load then differs and fires on the first
        poll. None (nothing served yet) makes the first poll fire on any
        existing checkpoint."""
        if poll_s <= 0:
            raise ValueError(f"poll_s must be positive but got {poll_s}")
        self._path = checkpoint_path
        self._on_publish = on_publish
        self._poll_s = float(poll_s)
        self._name = name
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._loaded_sig = loaded_signature

    def _signature(self) -> Optional[Tuple[int, int, int]]:
        return publish_signature(self._path)

    def mark_loaded(self, signature: Optional[Tuple[int, int, int]]) -> None:
        """Record ``signature`` (captured BEFORE the explicit reload that
        just succeeded — see :func:`publish_signature`) as served, so the
        watcher does not re-fire on it."""
        self._loaded_sig = signature

    def start(self) -> "CheckpointWatcher":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, name=self._name, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> int:
        """Returns the number of leaked threads (0/1)."""
        self._stop.set()
        leaked = 0
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=30)
            if t.is_alive():
                leaked = 1
                logger.warning("checkpoint watcher thread leaked "
                               "(join timeout)")
        return leaked

    def _run(self) -> None:
        while not self._stop.wait(self._poll_s):
            sig = self._signature()
            if sig is None or sig == self._loaded_sig:
                continue
            try:
                self._on_publish()
            except Exception:  # noqa: BLE001 — a failed reload must not
                # kill serving; the CURRENT model keeps answering and the
                # next poll retries (a newer publish may fix it)
                logger.warning("hot-reload failed; still serving the "
                               "previous model", exc_info=True)
                continue
            # record the signature captured BEFORE the load: if the trainer
            # published again mid-load, the next poll re-fires
            self._loaded_sig = sig
