"""Two-process distributed training test — the scaled-down analog of a multi-host
TPU pod run (G1/G8 replacement; reference boots its PS cluster across executors,
mllib:354-360).

Spawns 2 coordinated JAX processes, each with 4 virtual CPU devices, builds ONE global
(2, 4) mesh spanning both, and trains end-to-end through the Trainer with the
replicated-pipeline input feed (parallel/distributed.py). Both processes must finish in
lockstep and agree bit-for-bit on the final (replicated-checksummed) parameters.
"""

import os
import socket
import subprocess
import sys

import pytest

WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")

from glint_word2vec_tpu.parallel.distributed import initialize, is_multiprocess
pid = int(sys.argv[1]); port = sys.argv[2]
initialize(coordinator_address="127.0.0.1:" + port, num_processes=2, process_id=pid)
assert is_multiprocess()
assert jax.device_count() == 8 and jax.local_device_count() == 4

import numpy as np
from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.pipeline import encode_sentences
from glint_word2vec_tpu.data.vocab import build_vocab
from glint_word2vec_tpu.parallel.mesh import make_mesh
from glint_word2vec_tpu.train.trainer import Trainer

rng = np.random.default_rng(0)
words = [f"w{i}" for i in range(64)]
sentences = [[words[j] for j in rng.integers(0, 64, 12)] for _ in range(200)]
vocab = build_vocab(sentences, min_count=1)
cfg = Word2VecConfig(vector_size=16, min_count=1, pairs_per_batch=128,
                     num_iterations=2, window=3, negatives=3, negative_pool=16,
                     steps_per_dispatch=2, seed=7)
plan = make_mesh(2, 4)   # spans both processes: 8 global devices
trainer = Trainer(cfg, vocab, plan=plan)
assert trainer.params.syn0.sharding.is_equivalent_to(plan.embedding, 2)
encoded = encode_sentences(sentences, vocab, cfg.max_sentence_length)
trainer.fit(encoded)

import jax.numpy as jnp
checksum = float(jax.jit(lambda p: jnp.sum(p.syn0) + 1000.0 * jnp.sum(p.syn1))(
    trainer.params))
assert np.isfinite(checksum)
print(f"CHECKSUM {checksum:.10e} steps {trainer.global_step} "
      f"pairs {trainer.pairs_trained:.0f}", flush=True)
"""


@pytest.mark.slow
def test_two_process_training(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), port],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=420)
        assert p.returncode == 0, f"worker failed:\nstdout:{out}\nstderr:{err[-3000:]}"
        outs.append(out)
    lines = [next(ln for ln in o.splitlines() if ln.startswith("CHECKSUM"))
             for o in outs]
    assert lines[0] == lines[1], f"processes disagree: {lines}"
