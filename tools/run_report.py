#!/usr/bin/env python
"""Fold a telemetry JSONL run log into ONE summary JSON line.

The machine half of post-run inspection (docs/observability.md): where
``telemetry_tail.py`` renders for a human, this tool reduces a whole run
log — plus optionally the matching ``.blackbox.json`` dump and EVAL_RUNS
rows — into one machine-readable line a driver/CI can archive, diff, and
gate on. Prints exactly ONE JSON line on stdout (graftlint R7); all chatter
goes to stderr.

Summary fields: the run bracket (run_id/status/steps/pairs), throughput
distribution over the heartbeat windows (median/p10/p90/last pairs/s), the
host-wait/dispatch totals AND the per-phase time-attribution rollup (from
run_end, falling back to summing heartbeat windows for a truncated log —
exactly the crash case the blackbox exists for), recovery/watchdog state,
and the norm-channel trajectory (first/last/max of syn0+syn1 max_norm).

Usage::

    # one run log (rotated segments are more positional paths)
    python tools/run_report.py run.jsonl [run.jsonl.1 ...]
        [--blackbox run.jsonl.blackbox.json]
        [--eval-runs EVAL_RUNS.jsonl] [--eval-last N]

    # a FLEET run: N per-process sinks (router + replicas + trainer),
    # one --log each — reports per-process status plus the merged rollup
    python tools/run_report.py --log fleet.jsonl --log replica-0.jsonl \
        --log replica-1.jsonl --log trainer.jsonl

Exit code 0 iff every log parsed and (when the run ended) ended "ok"; a
truncated log (no run_end / serve_end / fleet_end bracket) reports
``"status": "truncated"`` and exits 1 — a remote driver can alarm on
exactly that. A deadline-checkpointed preemption reports
``"status": "preempted"`` (distinct from both: the run was ASKED to die
and closed its bracket first) with a ``"preempt"`` block carrying
steps-saved vs steps-lost; it still exits 1 — resuming is the
supervisor's job, not a clean end. In ``--log`` mode each log's ``<log>.blackbox.json`` dump is
folded in automatically when present (a dump next to a truncated serving
log is the expected SIGTERM shape, not an error).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _quantile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[i]


def _merge_phase_windows(windows: List[dict]) -> dict:
    """Sum per-heartbeat phase rollups into one run-level rollup (the
    fallback when run_end — which carries the exact cumulative — is
    missing). Bucketed quantiles re-derive from the merged sparse hists."""
    from glint_word2vec_tpu.obs.phases import (
        HIST_BUCKETS, PhaseAccumulator)
    out: dict = {}
    for w in windows:
        for name, ph in (w or {}).items():
            acc = out.setdefault(name, {"count": 0, "total_s": 0.0,
                                        "hist": [0] * HIST_BUCKETS})
            acc["count"] += int(ph.get("count", 0))
            acc["total_s"] += float(ph.get("total_s", 0.0))
            for idx, c in (ph.get("hist") or {}).items():
                i = int(idx)
                if 0 <= i < HIST_BUCKETS:
                    acc["hist"][i] += int(c)
    return {name: PhaseAccumulator._summarize(
                acc["count"], acc["total_s"], acc["hist"])
            for name, acc in out.items()}


def summarize(paths: List[str], blackbox: str = "",
              eval_runs: str = "", eval_last: int = 1,
              tolerate_torn_tail: bool = False) -> dict:
    from glint_word2vec_tpu.obs.schema import (
        validate_blackbox_file, validate_file)
    kinds: dict = {}
    heartbeats: List[dict] = []
    run_start: Optional[dict] = None
    run_end: Optional[dict] = None
    watchdog = 0
    recoveries: List[dict] = []
    preempt: Optional[dict] = None
    schema_ok = True
    schema_errors: List[str] = []
    for path in paths:
        v = validate_file(path, tolerate_torn_tail=tolerate_torn_tail)
        schema_ok = schema_ok and v["ok"]
        schema_errors.extend(v["errors"][:5])
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    r = json.loads(line)
                except json.JSONDecodeError:
                    continue  # counted via the validator above
                kind = r.get("kind", "?")
                kinds[kind] = kinds.get(kind, 0) + 1
                if kind == "heartbeat":
                    heartbeats.append(r)
                # bracket-aware across tiers: a fleet run's sinks are
                # serve_*/fleet_* logs — their end bracket is what "the
                # process exited cleanly" means there (serve_end /
                # fleet_end carry no status field; presence IS "ok")
                elif kind in ("run_start", "serve_start", "fleet_start"):
                    run_start = r
                elif kind in ("run_end", "serve_end", "fleet_end"):
                    run_end = r
                elif kind == "watchdog":
                    watchdog += 1
                elif kind == "recovery":
                    recoveries.append(r)
                elif kind == "preempt":
                    preempt = r

    pps = sorted(float(h["pairs_per_sec"]) for h in heartbeats
                 if h.get("pairs_per_sec"))
    status = (run_end.get("status", "ok") if run_end else "truncated")
    phases = (run_end or {}).get("phases")
    if not phases:
        phases = _merge_phase_windows(
            [h.get("phases") for h in heartbeats if h.get("phases")])

    def _norm_track(matrix: str) -> dict:
        vals = [(h["norms"][matrix]["max_norm"]) for h in heartbeats
                if (h.get("norms") or {}).get(matrix, {}).get("max_norm")
                is not None]
        if not vals:
            return {}
        return {"first": vals[0], "last": vals[-1], "max": max(vals)}

    report = {
        "ok": bool(schema_ok and status == "ok"),
        "paths": paths,
        "schema_valid": schema_ok,
        "schema_errors": schema_errors[:5],
        "run_id": (run_end or run_start or {}).get("run_id"),
        "status": status,
        "kinds": kinds,
        "steps": (run_end or {}).get("steps",
                                     heartbeats[-1]["step"] if heartbeats
                                     else 0),
        "pairs_trained": (run_end or {}).get("pairs_trained"),
        "wall_s": (round(run_end["t"] - run_start["t"], 3)
                   if run_end and run_start else None),
        "heartbeats": len(heartbeats),
        "pairs_per_sec": {
            "median": round(_quantile(pps, 0.5), 1),
            "p10": round(_quantile(pps, 0.10), 1),
            "p90": round(_quantile(pps, 0.90), 1),
            "last": round(pps[-1], 1) if pps else 0.0,
        } if pps else None,
        "host_wait_s_total": (run_end or {}).get("host_wait_s_total"),
        "dispatch_s_total": (run_end or {}).get("dispatch_s_total"),
        "phases": phases,
        "watchdog_fires": watchdog if not run_end
        else run_end.get("watchdog_fires", watchdog),
        "recoveries": len(recoveries) if not run_end
        else run_end.get("recoveries", len(recoveries)),
        "lr_scale_final": (run_end or {}).get(
            "lr_scale", heartbeats[-1].get("lr_scale") if heartbeats
            else None),
        "norms": {m: t for m in ("syn0", "syn1")
                  if (t := _norm_track(m))} or None,
    }
    if status == "preempted" and preempt is not None:
        # a deadline-checkpointed preemption (config.checkpoint_on_preempt,
        # docs/robustness.md §supervisor): distinct from "truncated" (died
        # with no end bracket) and "error" (failed) — the run was ASKED to
        # die and published what it could first. steps_lost is what the
        # supervisor re-trains after resume: 0 when the emergency save made
        # the deadline, else the gap back to the last periodic checkpoint.
        lost = 0 if preempt.get("saved") else int(
            preempt.get("steps_since_save") or 0)
        report["preempt"] = {
            "saved": bool(preempt.get("saved")),
            "step": preempt.get("step"),
            "steps_saved": int(preempt.get("step") or 0) - lost,
            "steps_lost": lost,
            "checkpoint": preempt.get("checkpoint"),
        }
    if blackbox:
        bb = validate_blackbox_file(blackbox)
        report["blackbox"] = {"path": blackbox, "valid": bb["ok"],
                              "kinds": bb["kinds"],
                              "errors": bb["errors"][:3]}
        if bb["ok"]:
            with open(blackbox, "r", encoding="utf-8") as f:
                report["blackbox"]["cause"] = json.load(f)["cause"]
    if eval_runs:
        rows = []
        try:
            with open(eval_runs, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if line:
                        rows.append(json.loads(line))
        except (OSError, json.JSONDecodeError) as e:
            report["eval"] = {"error": str(e)}
        else:
            keep = ("purity", "analogy_acc1", "emb_abs_max", "row_norm_max",
                    "row_norm_p99", "rows_norm_over_100", "vocab_size",
                    "words", "gen_version", "stab_ab_arm", "diverged")
            report["eval"] = [
                {k: r[k] for k in keep if k in r}
                for r in rows[-max(eval_last, 1):]]
    return report


def summarize_fleet(logs: List[str]) -> dict:
    """Per-process reports + the merged rollup for a fleet run's N sinks
    (one ``--log`` per process). Each log's ``<log>.blackbox.json`` folds
    in automatically when present; the merged status is "ok" only when
    EVERY process's is."""
    processes = {}
    for path in logs:
        name = os.path.splitext(os.path.basename(path))[0]
        if name in processes:
            # two hosts' sinks may share a basename (nodeA/serve.jsonl,
            # nodeB/serve.jsonl) — a silent overwrite could mask a failing
            # log behind a healthy same-named twin
            name = path
        bb = path + ".blackbox.json"
        # fleet teardown is SIGKILL — a half-written final sink line is the
        # expected torn tail, not schema corruption
        rep = summarize([path],
                        blackbox=bb if os.path.exists(bb) else "",
                        tolerate_torn_tail=True)
        # a process that died WITH a dump told its story — the alarm is a
        # truncated log with no forensics at all
        rep["dumped"] = "blackbox" in rep
        processes[name] = rep
    merged_kinds: dict = {}
    for rep in processes.values():
        for k, n in rep["kinds"].items():
            merged_kinds[k] = merged_kinds.get(k, 0) + n
    # fleet verdict: every sink parsed schema-valid with records, and no
    # process that DID write its end bracket ended "error". "truncated" is
    # not gated: replicas exit by teardown SIGKILL (ReplicaSet.close), so a
    # missing end bracket is a serving log's normal shape

    def _proc_ok(r: dict) -> bool:
        return (r["schema_valid"] and sum(r["kinds"].values()) > 0
                and r["status"] != "error")

    return {
        "ok": all(_proc_ok(r) for r in processes.values()),
        "mode": "fleet",
        "processes": {n: {
            "ok": _proc_ok(r),
            "status": r["status"], "records": sum(
                r["kinds"].values()), "schema_valid": r["schema_valid"],
            "dumped": r["dumped"],
            **({"cause": r["blackbox"].get("cause", {}).get("kind")}
               if r.get("blackbox") else {}),
        } for n, r in processes.items()},
        "merged": {
            "logs": len(processes),
            "statuses": sorted({r["status"]
                                for r in processes.values()}),
            "schema_valid": all(r["schema_valid"]
                                for r in processes.values()),
            "kinds": merged_kinds,
            "dumps": sum(1 for r in processes.values() if r["dumped"]),
        },
        "detail": processes,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("paths", nargs="*",
                    help="sink JSONL file(s), oldest rotated segment first "
                         "(ONE run's segments — use --log for fleet runs)")
    ap.add_argument("--log", action="append", default=[],
                    help="one per-process sink of a FLEET run; repeatable — "
                         "reports per-process + merged status")
    ap.add_argument("--blackbox", default="",
                    help="also validate + fold in a .blackbox.json dump")
    ap.add_argument("--eval-runs", default="",
                    help="append the last EVAL_RUNS rows (quality metrics)")
    ap.add_argument("--eval-last", type=int, default=1,
                    help="how many trailing EVAL_RUNS rows to include")
    args = ap.parse_args()
    if bool(args.paths) == bool(args.log):
        ap.error("pass either positional segment paths (one run) or "
                 "--log per process (a fleet run), not both/neither")
    if args.log:
        report = summarize_fleet(args.log)
    else:
        report = summarize(args.paths, blackbox=args.blackbox,
                           eval_runs=args.eval_runs,
                           eval_last=args.eval_last)
    print(json.dumps(report, allow_nan=False))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
