"""CLI: ``python -m tools.graftcheck [--smoke] [--json-out F]``.

Prints exactly ONE JSON line on stdout (graftlint R7); progress on stderr.
Exit 1 on any unexplained violation, baseline/registry drift, or
undocumented knob."""

from __future__ import annotations

import argparse
import json
import os
import sys

# self-provision a CPU backend BEFORE jax initializes (the probe builds real
# Trainers; the session image may pin a remote-TPU plugin otherwise)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, _REPO)


def main(argv=None) -> int:
    """Parses args, runs the sweep, and emits via ``_run``'s single JSON
    print — exactly ONE line on stdout on every exit path (graftlint R7)."""
    payload, rc = _run(argv)
    print(json.dumps(payload))
    return rc


def _run(argv) -> tuple:
    from tools.graftcheck import checker

    ap = argparse.ArgumentParser(
        prog="graftcheck", description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="thinned lattice (the tier-1 wiring); the full "
                         "sweep (>= 1000 executed configs) runs in CI")
    ap.add_argument("--json-out", default="",
                    help="also write the JSON report to this path")
    ap.add_argument("--baseline", default="",
                    help="baseline file (default: the committed "
                         "tools/graftcheck/baseline.json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the committed baseline from this "
                         "(reviewed) run instead of gating against it")
    ap.add_argument("--root", default=_REPO)
    args = ap.parse_args(argv)

    mode = "smoke" if args.smoke else "full"
    if args.write_baseline and mode != "full":
        checker.log("graftcheck: refusing to write a baseline from a smoke "
                    "run — the full sweep is the inventory")
        return ({"tool": "graftcheck", "ok": False,
                 "error": "write-baseline requires the full sweep"}, 2)
    checker.log(f"graftcheck: enumerating the {mode} lattice ...")
    report = checker.run_sweep(mode)
    if args.write_baseline:
        path = checker.write_baseline(report, args.baseline)
        checker.log(f"graftcheck: baseline written to {path}")
    report = checker.apply_gates(report, args.root, args.baseline)

    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1)
    for v in report["violations"]:
        checker.log(f"  VIOLATION[{'baselined' if v['baselined'] else 'NEW'}]"
                    f" {v['key'][:100]}  counterexample="
                    f"{v['counterexample']}")
    for d in report["baseline_drift"]:
        checker.log(f"  DRIFT {d}")
    for d in report["registry_drift"]:
        checker.log(f"  REGISTRY {d}")
    if report["docs_missing"]:
        checker.log(f"  DOCS missing knob rows: {report['docs_missing']}")
    checker.log(
        f"graftcheck: {report['configs_executed']} configs executed "
        f"({report['accepted']} accepted, {report['refused_construction']} "
        f"refused), {report['probes_run']} dispatch probes, "
        f"{len(report['refusal_signatures'])} refusal signatures, "
        f"{report['unexplained_violations']} unexplained violation(s) -> "
        f"{'ok' if report['ok'] else 'FAIL'}")
    return (report, 0 if report["ok"] else 1)


if __name__ == "__main__":
    raise SystemExit(main())
