"""Run-telemetry subsystem (docs/observability.md).

Seven layers, each usable alone, all off by default and zero-cost when off:

- :mod:`.probe` — the fused on-device health reduction over the params carry
  (finiteness + per-matrix row-norm channels), the instrumentation ROADMAP
  item 2 names as the first step against the measured finite norm blowup.
- :mod:`.watch` — the finite-blowup watchdog (``config.norm_watch``) that
  fires on the probe channels where the non-finite guardrail stays silent.
- :mod:`.sink` + :mod:`.schema` — the schema-versioned JSONL run log
  (rotating file, never stdout — graftlint R7).
- :mod:`.spans` — thread-safe host trace spans exported as Chrome-trace JSON
  (Perfetto-loadable).
- :mod:`.phases` — host-side per-phase log2 duration histograms (producer
  wait / stage / dispatch / device block), the "where did the time go"
  attribution without a trace viewer.
- :mod:`.blackbox` — the flight recorder: bounded rings of recent telemetry
  dumped atomically to ``<telemetry_path>.blackbox.json`` on fit death.
- :mod:`.statusd` — the read-only live-inspection HTTP endpoint
  (``config.status_port``): JSON + Prometheus gauges for a running fit;
  the serving tier reuses it with the ``glint_serve_*`` renderer
  (:func:`.statusd.serve_prometheus_text`, docs/serving.md).

Plus the FLEET plane above them (ISSUE 13, docs/observability.md §9):

- :mod:`.trace` — cross-process trace propagation: one ``trace_id`` per
  fleet query, span children across the router/replica boundary, the
  per-process clock anchor, and the publish-side correlation record.
- :mod:`.slo` — availability/latency objectives with multi-window burn
  rates over the router's per-query samples (``glint_serve_fleet_slo_*``).
- :mod:`.collect` — the offline collector: N per-process sinks + blackbox
  dumps merged into one causally ordered fleet timeline
  (``tools/obs_collect.py``; Perfetto export + slowest-K exemplars +
  offline SLO recompute).
"""

from glint_word2vec_tpu.obs.blackbox import FlightRecorder
from glint_word2vec_tpu.obs.phases import PhaseAccumulator
from glint_word2vec_tpu.obs.probe import HealthStats, make_health_probe
from glint_word2vec_tpu.obs.schema import (
    SCHEMA_VERSION,
    validate_blackbox,
    validate_blackbox_file,
    validate_file,
    validate_record,
)
from glint_word2vec_tpu.obs.sink import TelemetrySink
from glint_word2vec_tpu.obs.slo import SloObjectives, SloTracker
from glint_word2vec_tpu.obs.spans import Tracer, default_tracer
from glint_word2vec_tpu.obs.trace import SpanEmitter, clock_anchor
from glint_word2vec_tpu.obs.statusd import (
    StatusServer,
    prometheus_text,
    serve_prometheus_text,
)
from glint_word2vec_tpu.obs.watch import NormWatchdog

__all__ = [
    "HealthStats", "make_health_probe",
    "SCHEMA_VERSION", "validate_file", "validate_record",
    "validate_blackbox", "validate_blackbox_file",
    "TelemetrySink", "Tracer", "default_tracer", "NormWatchdog",
    "FlightRecorder", "PhaseAccumulator", "StatusServer", "prometheus_text",
    "serve_prometheus_text",
    "SpanEmitter", "clock_anchor", "SloObjectives", "SloTracker",
]
