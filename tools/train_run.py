#!/usr/bin/env python
"""Supervised-training driver CLI (docs/robustness.md §supervisor): run a
fit under the TrainingSupervisor's die→diagnose→resume loop — preemption-
deadline checkpointing, decorrelated-jitter restarts, hang detection,
crash-loop quarantine, peer-death gang restarts.

Stdout carries exactly ONE JSON line (graftlint R7 — the driver contract);
human progress goes to stderr.

Usage::

    # supervise an arbitrary training command (gang: repeat --cmd/--log)
    python tools/train_run.py --cmd "python my_fit.py" --log run.jsonl \
        --checkpoint-dir ckpts [--max-restarts N] [--stall-s S]
        [--loop-window W] [--workdir DIR]

    # the self-contained supervisor drills (tier-1 + CI): a SIGTERM'd fit
    # emergency-checkpoints within its deadline and resumes to match an
    # uninterrupted twin's purity gate; an injected in-step stall is
    # detected and killed+resumed; a deterministic crash loop is
    # quarantined with a machine-readable verdict in bounded attempts
    python tools/train_run.py --smoke
    python tools/train_run.py --drill preempt|stall|crashloop

Exit code 0 iff the supervised run ended "ok" (or the drill's every
assertion passed).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# -- drill corpus / config ------------------------------------------------

# two co-occurrence clusters that NEVER share a sentence: the purity gate
# below only needs nearest neighbors to stay inside their own cluster — a
# structure even a two-iteration toy fit learns, and one that a resumed
# run that lost real progress (or re-trained the wrong batches) breaks
_CLUSTER_A = [f"a{i}" for i in range(15)]
_CLUSTER_B = [f"b{i}" for i in range(15)]


def cluster_sentences(n_sentences: int, seed: int = 0):
    import numpy as np
    rng = np.random.default_rng(seed)
    out = []
    for s in range(n_sentences):
        pool = _CLUSTER_A if s % 2 == 0 else _CLUSTER_B
        out.append([pool[j] for j in rng.integers(0, len(pool), 20)])
    return out


def drill_config(**kw):
    from glint_word2vec_tpu.config import Word2VecConfig
    return Word2VecConfig(
        vector_size=16, pairs_per_batch=128, window=3, num_iterations=2,
        steps_per_dispatch=2, heartbeat_every_steps=2, subsample_ratio=0.0,
        prefetch_chunks=0, seed=1, min_count=1, **kw)


def _cluster_purity(words, syn0) -> float:
    """Mean fraction of each probe word's top-4 cosine neighbors that sit
    in its own cluster (the continual-drift phase's neighbor rule, as a
    scalar both arms of the preempt drill must clear)."""
    import numpy as np
    idx = {w: i for i, w in enumerate(words)}
    emb = np.asarray(syn0, np.float64)
    emb = emb / np.maximum(np.linalg.norm(emb, axis=1, keepdims=True), 1e-12)
    fracs = []
    for cluster in (_CLUSTER_A, _CLUSTER_B):
        for probe in cluster[:3]:
            i = idx[probe]
            sims = emb @ emb[i]
            sims[i] = -np.inf
            top = np.argsort(-sims)[:4]
            same = sum(1 for j in top if words[j] in cluster)
            fracs.append(same / 4.0)
    return float(np.mean(fracs))


# -- the worker leg -------------------------------------------------------

def worker_fit(workdir: str, n_sentences: int) -> int:
    """One supervised fit attempt: resume from the newest verified
    checkpoint under <workdir>/ckpt when one exists, else fit fresh —
    exactly the ``load_latest_valid`` resume contract the supervisor
    restarts around. Honors the supervisor's mitigation ladder
    (GLINT_SUPERVISOR_MITIGATE=1 engages the trainer's existing
    norm_watch="recover" stabilizer/lr-backoff arm) and exits
    PEER_ABORT_EXIT on a peer-death abort so the supervisor can tell the
    victim from the cause."""
    from glint_word2vec_tpu.models.estimator import Word2Vec
    from glint_word2vec_tpu.train.checkpoint import load_latest_valid
    from glint_word2vec_tpu.train.supervisor import (
        MITIGATE_ENV, PEER_ABORT_EXIT, PeerDeathError)

    ckdir = os.path.join(workdir, "ckpt")
    ck = os.path.join(ckdir, "model")
    os.makedirs(ckdir, exist_ok=True)
    sentences = cluster_sentences(n_sentences, seed=3)
    mitigate = os.environ.get(MITIGATE_ENV) == "1"
    overrides = {"norm_watch": "recover"} if mitigate else {}
    try:
        existing = load_latest_valid(ckdir)
    except FileNotFoundError:
        existing = None
    try:
        if existing is not None:
            log(f"[worker] resuming from {existing}"
                + (" (mitigations engaged)" if mitigate else ""))
            Word2Vec.resume(existing, sentences, checkpoint_every_steps=4,
                            config_overrides=overrides or None)
        else:
            log("[worker] fresh fit"
                + (" (mitigations engaged)" if mitigate else ""))
            cfg = drill_config(
                telemetry_path=os.path.join(workdir, "run.jsonl"),
                checkpoint_on_preempt=True, **overrides)
            Word2Vec(cfg).fit(sentences, checkpoint_path=ck,
                              checkpoint_every_steps=4)
    except PeerDeathError as e:
        log(f"[worker] peer death: {e}")
        return PEER_ABORT_EXIT
    return 0


# -- supervision plumbing shared by the drills ----------------------------

def _drill_supervisor(workdir: str, n_sentences: int, telemetry,
                      **kw):
    from glint_word2vec_tpu.train.supervisor import TrainingSupervisor
    cmd = [sys.executable, os.path.abspath(__file__), "--worker", "fit",
           "--workdir", workdir, "--sentences", str(n_sentences)]
    return TrainingSupervisor(
        [cmd], workdir, child_logs=[os.path.join(workdir, "run.jsonl")],
        checkpoint_dir=os.path.join(workdir, "ckpt"),
        telemetry=telemetry, poll_s=0.1, term_grace_s=2.0,
        backoff_base_s=0.02, backoff_cap_s=0.2, seed=7, **kw)


def _final_header(workdir: str):
    from glint_word2vec_tpu.train.checkpoint import (
        load_latest_valid, load_model_header)
    return load_model_header(
        load_latest_valid(os.path.join(workdir, "ckpt")))


# -- drills ---------------------------------------------------------------

def run_preempt_drill(workdir: str, n_sentences: int = 200) -> dict:
    """train-preempt: SIGTERM mid-fit (scripted crash_at_step — the
    handler defers it into the preemption-deadline path) → emergency
    checkpoint published + verified with ≤ one dispatch chunk lost →
    supervisor resumes → the final model reaches the uninterrupted twin's
    exact final step and passes the same purity gate."""
    from glint_word2vec_tpu.data.pipeline import encode_sentences
    from glint_word2vec_tpu.data.vocab import build_vocab
    from glint_word2vec_tpu.obs.sink import TelemetrySink
    from glint_word2vec_tpu.train.checkpoint import load_model
    from glint_word2vec_tpu.train.trainer import Trainer

    # the uninterrupted twin, in-process: same corpus, same config seed —
    # its final step and purity are the bar the supervised arm must meet
    sentences = cluster_sentences(n_sentences, seed=3)
    vocab = build_vocab(sentences, min_count=1)
    twin = Trainer(drill_config(), vocab)
    twin.fit(encode_sentences(sentences, vocab, 1000))
    twin_step = int(twin.global_step)
    twin_purity = _cluster_purity(vocab.words,
                                  twin.unpadded_params().syn0)
    log(f"[preempt] twin finished: step={twin_step} "
        f"purity={twin_purity:.3f}")
    assert twin_purity >= 0.75, \
        f"twin purity {twin_purity:.3f} too weak to gate on"

    def fault_env(attempt: int) -> dict:
        if attempt == 0:
            # deterministic preemption: the scripted self-SIGTERM fires in
            # _finish_round, the fit-scoped handler defers it, and the SAME
            # round's tail drains the emergency save — no timing races
            return {"GLINT_FAULT_CRASH_AT_STEP": "6",
                    "GLINT_FAULT_CRASH_SIGNAL": "TERM"}
        return {"GLINT_FAULT_CRASH_AT_STEP": ""}

    sink = TelemetrySink(os.path.join(workdir, "supervisor.jsonl"))
    try:
        sup = _drill_supervisor(workdir, n_sentences, sink,
                                max_restarts=3, stall_s=60.0,
                                env_for_attempt=fault_env)
        verdict = sup.run()
    finally:
        sink.close()
    assert verdict.status == "ok", f"supervised run failed: {verdict}"
    assert verdict.attempts == 2, \
        f"expected exactly 2 attempts (preempt + resume), got {verdict}"
    first = verdict.history[0]
    assert first["cls"] == "preempt", \
        f"first attempt classified {first['cls']!r}, want preempt: {verdict}"
    # the trainer's own preempt record: emergency save made the deadline
    pre = None
    with open(os.path.join(workdir, "run.jsonl"), encoding="utf-8") as f:
        for line in f:
            r = json.loads(line)
            if r.get("kind") == "preempt":
                pre = r
    assert pre is not None, "no preempt record in the worker sink"
    assert pre["saved"], f"emergency checkpoint missed the deadline: {pre}"
    # ≤ one dispatch chunk (steps_per_dispatch=2) of progress at risk
    assert pre["steps_since_save"] <= 2, f"lost too much progress: {pre}"
    header = _final_header(workdir)
    ts = header["train_state"]
    assert ts.finished, f"final checkpoint not finished: {ts}"
    assert int(ts.global_step) == twin_step, \
        f"resumed final step {ts.global_step} != twin {twin_step}"
    data = load_model(os.path.join(workdir, "ckpt", "model"))
    purity = _cluster_purity(data["words"], data["syn0"])
    gate = min(0.75, twin_purity)
    assert purity >= gate, \
        f"resumed purity {purity:.3f} under the twin's gate {gate:.3f}"
    log(f"[preempt] PASS: resumed to step {ts.global_step}, "
        f"purity {purity:.3f} (twin {twin_purity:.3f})")
    return {"ok": True, "twin_step": twin_step,
            "final_step": int(ts.global_step),
            "purity": round(purity, 4), "twin_purity": round(twin_purity, 4),
            "preempt": {k: pre[k] for k in
                        ("step", "saved", "steps_since_save")},
            "attempts": verdict.attempts}


def run_stall_drill(workdir: str, n_sentences: int = 200) -> dict:
    """train-stall: an injected in-step stall (faults.stall_at_step) wedges
    the fit; the supervisor's hang watchdog must detect the silence within
    2×stall_s, capture a diagnostic (SIGTERM → flight-recorder dump, then
    SIGKILL), count it as a failure, and resume to completion."""
    from glint_word2vec_tpu.obs.sink import TelemetrySink

    def fault_env(attempt: int) -> dict:
        if attempt == 0:
            return {"GLINT_FAULT_STALL_AT_STEP": "6",
                    "GLINT_FAULT_STALL_S": "120"}
        return {"GLINT_FAULT_STALL_AT_STEP": ""}

    stall_s = 2.0
    sink = TelemetrySink(os.path.join(workdir, "supervisor.jsonl"))
    try:
        sup = _drill_supervisor(workdir, n_sentences, sink,
                                max_restarts=3, stall_s=stall_s,
                                env_for_attempt=fault_env)
        verdict = sup.run()
    finally:
        sink.close()
    assert verdict.status == "ok", f"supervised run failed: {verdict}"
    assert verdict.attempts == 2, \
        f"expected exactly 2 attempts (stall + resume), got {verdict}"
    first = verdict.history[0]
    assert first["cls"] == "stall", \
        f"first attempt classified {first['cls']!r}, want stall: {verdict}"
    # detection bound + diagnostic: the supervisor_stall record and the
    # dump the TERM-first kill requested from the wedged child
    stall_rec = None
    with open(os.path.join(workdir, "supervisor.jsonl"),
              encoding="utf-8") as f:
        for line in f:
            r = json.loads(line)
            if r.get("kind") == "supervisor_stall":
                stall_rec = r
    assert stall_rec is not None, "no supervisor_stall record"
    assert stall_rec["stalled_s"] <= 2 * stall_s + 1.0, \
        f"stall detected too late: {stall_rec}"
    dump = os.path.join(workdir, "run.jsonl.blackbox.json")
    assert os.path.exists(dump), \
        "stalled child left no flight-recorder dump (TERM diagnostic lost)"
    header = _final_header(workdir)
    assert header["train_state"].finished, "resumed run did not finish"
    log(f"[stall] PASS: detected after {stall_rec['stalled_s']:.1f}s at "
        f"step {stall_rec['last_step']}, resumed to completion")
    return {"ok": True, "stalled_s": stall_rec["stalled_s"],
            "last_step": stall_rec["last_step"],
            "final_step": int(header["train_state"].global_step),
            "attempts": verdict.attempts}


def run_crashloop_drill(workdir: str, n_sentences: int = 200) -> dict:
    """train-crashloop: the same deterministic crash (SIGKILL at a scripted
    step) on EVERY attempt — the supervisor must classify the repeated
    (step, cause) signature as a deterministic loop, walk the escalation
    ladder (stage 1 mitigations, stage 2 halt), and quarantine with a
    machine-readable verdict in bounded attempts — never an unbounded
    restart loop."""
    from glint_word2vec_tpu.obs.sink import TelemetrySink

    env = {"GLINT_FAULT_CRASH_AT_STEP": "6",
           "GLINT_FAULT_CRASH_SIGNAL": "KILL"}
    max_restarts = 6
    sink = TelemetrySink(os.path.join(workdir, "supervisor.jsonl"))
    try:
        sup = _drill_supervisor(workdir, n_sentences, sink,
                                max_restarts=max_restarts, stall_s=60.0,
                                loop_window=2, env=env)
        verdict = sup.run()
    finally:
        sink.close()
    assert verdict.status == "quarantined", \
        f"deterministic loop not quarantined: {verdict}"
    assert verdict.classification == "deterministic-crash-loop", \
        f"wrong classification: {verdict}"
    assert verdict.attempts <= max_restarts, \
        f"quarantine took {verdict.attempts} attempts (> {max_restarts})"
    stages = [l["stage"] for l in verdict.ladder]
    assert stages == [1, 2], \
        f"escalation ladder did not walk 1→2: {verdict.ladder}"
    vpath = os.path.join(workdir, "verdict.json")
    assert os.path.exists(vpath), "no machine-readable verdict.json"
    with open(vpath, encoding="utf-8") as f:
        doc = json.load(f)
    assert doc["status"] == "quarantined" and doc["signature"], \
        f"verdict.json incomplete: {doc}"
    log(f"[crashloop] PASS: quarantined {doc['signature']!r} after "
        f"{verdict.attempts} attempts")
    return {"ok": True, "attempts": verdict.attempts,
            "signature": doc["signature"], "ladder": stages}


def run_smoke(workdir: str, n_sentences: int = 200) -> dict:
    """All three supervisor drills, one report (the CI supervisor job's
    single artifact)."""
    report = {}
    for name, fn in (("preempt", run_preempt_drill),
                     ("stall", run_stall_drill),
                     ("crashloop", run_crashloop_drill)):
        sub = os.path.join(workdir, name)
        os.makedirs(sub, exist_ok=True)
        log(f"[smoke] --- {name} drill ---")
        report[name] = fn(sub, n_sentences)
    # the supervisor sinks must be schema-valid end to end (the new
    # supervisor_* kinds are registered, not grandfathered)
    from glint_word2vec_tpu.obs.schema import validate_file
    for name in ("preempt", "stall", "crashloop"):
        v = validate_file(os.path.join(workdir, name, "supervisor.jsonl"))
        assert v["ok"], f"{name} supervisor sink schema-invalid: " \
                        f"{v['errors'][:3]}"
    report["ok"] = all(r.get("ok") for r in report.values())
    return report


# -- generic supervised-run mode ------------------------------------------

def run_supervised(args) -> dict:
    from glint_word2vec_tpu.obs.sink import TelemetrySink
    from glint_word2vec_tpu.train.supervisor import TrainingSupervisor
    workdir = args.workdir or tempfile.mkdtemp(prefix="glint_train_run_")
    os.makedirs(workdir, exist_ok=True)
    commands = [c.split() if isinstance(c, str) else c for c in args.cmd]
    sink = None
    if args.telemetry:
        sink = TelemetrySink(args.telemetry)
    try:
        sup = TrainingSupervisor(
            commands, workdir, child_logs=args.log,
            checkpoint_dir=args.checkpoint_dir, telemetry=sink,
            max_restarts=args.max_restarts, stall_s=args.stall_s,
            loop_window=args.loop_window, seed=args.seed)
        if args.status_port:
            from glint_word2vec_tpu.obs.statusd import (
                StatusServer, supervisor_prometheus_text)
            statusd = StatusServer(
                args.status_port, sup.status_snapshot,
                metrics_fn=supervisor_prometheus_text).start()
        else:
            statusd = None
        try:
            verdict = sup.run()
        finally:
            if statusd is not None:
                statusd.stop()
    finally:
        if sink is not None:
            sink.close()
    return {"ok": verdict.status == "ok", "mode": "supervise",
            **verdict.to_dict()}


def main() -> int:
    from glint_word2vec_tpu.config import Word2VecConfig
    defaults = Word2VecConfig()
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--cmd", action="append", default=[],
                    help="training command to supervise (repeat for a "
                         "multi-process gang)")
    ap.add_argument("--log", action="append", default=[],
                    help="telemetry sink path the matching --cmd writes "
                         "(the supervisor's progress/classification window)")
    ap.add_argument("--checkpoint-dir", default="",
                    help="directory load_latest_valid resumes from "
                         "(and the restart audit verifies)")
    ap.add_argument("--telemetry", default="",
                    help="write supervisor_* telemetry records here")
    ap.add_argument("--status-port", type=int, default=0,
                    help="> 0: serve glint_supervisor_* gauges on "
                         "127.0.0.1:<port>")
    ap.add_argument("--max-restarts", type=int,
                    default=defaults.supervisor_max_restarts)
    ap.add_argument("--stall-s", type=float,
                    default=defaults.supervisor_stall_s)
    ap.add_argument("--loop-window", type=int,
                    default=defaults.supervisor_loop_window)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--smoke", action="store_true",
                    help="run the three supervisor drills (tier-1/CI) "
                         "in a temp dir")
    ap.add_argument("--drill", choices=["preempt", "stall", "crashloop"],
                    help="run ONE drill (the chaos phases call these)")
    ap.add_argument("--worker", choices=["fit"],
                    help="internal: one supervised fit attempt")
    ap.add_argument("--workdir", default="")
    ap.add_argument("--sentences", type=int, default=200)
    args = ap.parse_args()

    if args.worker == "fit":
        return worker_fit(args.workdir, args.sentences)

    if args.smoke or args.drill:
        workdir = args.workdir or tempfile.mkdtemp(prefix="glint_sup_")
        os.makedirs(workdir, exist_ok=True)
        try:
            if args.drill:
                fn = {"preempt": run_preempt_drill,
                      "stall": run_stall_drill,
                      "crashloop": run_crashloop_drill}[args.drill]
                out = fn(workdir, args.sentences)
            else:
                out = run_smoke(workdir, args.sentences)
        except AssertionError as e:
            out = {"ok": False, "error": str(e)}
        finally:
            if not args.workdir:
                shutil.rmtree(workdir, ignore_errors=True)
        print(json.dumps(out))
        return 0 if out.get("ok") else 1

    if not args.cmd:
        ap.error("pass --cmd (with --log per command) to supervise a run, "
                 "or --smoke / --drill for the self-contained drills")
    if len(args.log) != len(args.cmd):
        ap.error("need exactly one --log per --cmd")
    out = run_supervised(args)
    print(json.dumps(out))
    return 0 if out.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
