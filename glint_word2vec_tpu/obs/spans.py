"""Host trace spans: a small thread-safe span API + Chrome-trace export.

The trainer already records two aggregate timers (``host_wait_time`` /
``dispatch_time``), but PR 3/PR 4 added a multi-threaded producer, a
one-round-ahead stager, and parallel checkpoint I/O — and no artifact shows
where wall-clock actually goes across those threads, which is exactly what
the first pod session (ROADMAP items 3–4) needs to attribute step time. A
span is one timed region on one thread; the export is the Chrome trace event
format (``chrome://tracing`` / Perfetto / ``about:tracing`` all load it), so
nesting and cross-thread overlap render without any custom viewer.

Design constraints:

- zero-cost when disabled: ``span()`` returns a shared no-op context manager
  (no allocation, no clock read) — every fit path can instrument
  unconditionally;
- thread-safe and bounded: events land in a ring (oldest dropped past
  ``max_events``) under one lock held only for the append — producer/stager
  threads never serialize against each other's timed regions;
- no ad-hoc threads (graftlint R1): this module only OBSERVES threads.

One process-wide default tracer exists so layers with no Trainer handle
(checkpoint save/load) can record spans; the Trainer enables/clears it per
run when telemetry is on.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional
from glint_word2vec_tpu.lockcheck import make_rlock


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


_NOOP = _NoopSpan()

# span name → time-attribution phase (obs/phases.py): every recorded span
# whose name maps here also lands in the accumulator attached for the run,
# so the phase histograms need no second clock read at the span sites
_PHASE_OF = {
    "producer_wait": "producer_wait",
    "stage_put": "stage",
    "allgather_fetch": "stage",
    "dispatch": "dispatch",
    "health_probe": "device_block",
    "device_block": "device_block",
}


class _Span:
    __slots__ = ("_tracer", "name", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: Optional[dict]):
        self._tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._tracer._record(self.name, self._t0, t1 - self._t0, self.args)
        return None


class Tracer:
    """Collects complete ("X") spans; exports the Chrome trace event format."""

    def __init__(self, enabled: bool = False, max_events: int = 200_000):
        from collections import deque
        self.enabled = enabled
        self.max_events = int(max_events)
        # RLock: the flight recorder's SIGTERM dump (main thread) reads
        # span_summary() — a plain Lock held by the interrupted thread's
        # own _record() would deadlock the handler (obs/blackbox.py)
        self._lock = make_rlock("obs.spans")
        # deque(maxlen): appending past capacity drops the OLDEST in O(1) —
        # the tail of a long run is what a hang/slowdown investigation needs
        self._events: "deque" = deque(maxlen=self.max_events)
        self._dropped = 0
        self._epoch = time.perf_counter()
        self._phases = None  # PhaseAccumulator of the running trainer, or None

    def configure(self, enabled: bool) -> None:
        self.enabled = enabled

    def attach_phases(self, acc) -> None:
        """Attach (or detach with None) the run's PhaseAccumulator — recorded
        spans whose names map to a phase tee their duration into it."""
        self._phases = acc

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._dropped = 0
            self._epoch = time.perf_counter()

    def span(self, name: str, **args):
        """Context manager timing one region on the calling thread."""
        if not self.enabled:
            return _NOOP
        return _Span(self, name, args or None)

    def wrap_iter(self, name: str, it):
        """Wrap an iterator so each ``next()`` is a span ON THE CONSUMING
        THREAD — handed to a producer-thread iterator (``_threaded_iter``),
        this times production where it happens. Always wraps: ``span()``
        re-checks ``enabled`` per item (feed iterators are built before the
        run bookkeeping arms the tracer), and the per-chunk no-op cost is
        nothing next to chunk assembly."""

        def gen():
            src = iter(it)
            while True:
                with self.span(name):
                    try:
                        item = next(src)
                    except StopIteration:
                        return
                yield item

        return gen()

    def _record(self, name: str, t0: float, dur: float,
                args: Optional[dict]) -> None:
        if self._phases is not None:
            phase = _PHASE_OF.get(name)
            if phase is not None:
                self._phases.add(phase, dur)
        ev = (name, threading.get_ident(), threading.current_thread().name,
              t0 - self._epoch, dur, args)
        with self._lock:
            if len(self._events) == self.max_events:
                self._dropped += 1
            self._events.append(ev)

    # -- introspection / export -------------------------------------------------

    def events(self) -> List[dict]:
        with self._lock:
            evs = list(self._events)
        return [{"name": n, "tid": tid, "thread": tname,
                 "ts_s": ts, "dur_s": dur, **({"args": a} if a else {})}
                for n, tid, tname, ts, dur, a in evs]

    def span_summary(self) -> Dict[str, dict]:
        """Per-span-name {count, total_s, max_s} — the run_end digest."""
        out: Dict[str, dict] = {}
        for ev in self.events():
            s = out.setdefault(ev["name"],
                               {"count": 0, "total_s": 0.0, "max_s": 0.0})
            s["count"] += 1
            s["total_s"] = round(s["total_s"] + ev["dur_s"], 6)
            s["max_s"] = round(max(s["max_s"], ev["dur_s"]), 6)
        return out

    def export_chrome_trace(self, path: str) -> int:
        """Write the collected spans as a Chrome-trace JSON file; returns the
        event count. Thread ids are remapped to small ints in first-seen
        order, with metadata events naming each thread."""
        with self._lock:
            evs = list(self._events)
            dropped = self._dropped
        tid_map: Dict[int, int] = {}
        names: Dict[int, str] = {}
        trace = []
        for n, tid, tname, ts, dur, a in evs:
            small = tid_map.setdefault(tid, len(tid_map))
            names.setdefault(small, tname)
            ev = {"ph": "X", "name": n, "pid": 0, "tid": small,
                  "ts": round(ts * 1e6, 1), "dur": round(dur * 1e6, 1)}
            if a:
                ev["args"] = a
            trace.append(ev)
        meta = [{"ph": "M", "name": "thread_name", "pid": 0, "tid": small,
                 "args": {"name": tname}} for small, tname in names.items()]
        doc = {"traceEvents": meta + trace, "displayTimeUnit": "ms",
               "otherData": {"dropped_events": dropped}}
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        return len(trace)


_default = Tracer()


def default_tracer() -> Tracer:
    """The process-wide tracer (disabled until a telemetry-on run enables it)."""
    return _default
