"""Serving-tier tests (glint_word2vec_tpu/serve/, docs/serving.md):

- the micro-batcher: coalescing, deadline flush, bounded-queue backpressure
  (ServerOverloaded), per-request error isolation, drain-on-stop;
- the IVF ANN index: deterministic build, full-probe == exact oracle,
  recall@10 on clustered geometry, candidate-coverage expansion at tiny
  cells, zero-norm padding exclusion;
- the model's ANN entry (attach_ann + find_synonyms_batch(ann=True));
- the lease-counted serving handle: in-flight batches finish on the old
  model across a swap, buffers release exactly when leases drain;
- the assembled EmbeddingService: exact arm parity with the model, hot
  reload (explicit + watcher), schema-valid serve_* telemetry, and the
  glint_serve_* Prometheus rendering.
"""

import json
import os
import shutil
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from glint_word2vec_tpu.data.vocab import Vocabulary, build_vocab
from glint_word2vec_tpu.models.word2vec import Word2VecModel
from glint_word2vec_tpu.obs.schema import validate_file, validate_record
from glint_word2vec_tpu.obs.statusd import serve_prometheus_text
from glint_word2vec_tpu.serve import (
    BatchingScheduler,
    EmbeddingService,
    ServerOverloaded,
    ServiceClosed,
    ServingHandle,
    build_ivf,
    decorrelated_jitter,
    load_with_retry,
)


def clustered_matrix(v=3000, d=32, clusters=40, seed=0, noise=0.35):
    """The serving bench's synthetic geometry: tight unit-centroid cells
    (trained embeddings are clustered — the eval ladder measures topic
    purity ~1.0 on healthy runs)."""
    rng = np.random.default_rng(seed)
    cents = rng.standard_normal((clusters, d)).astype(np.float32)
    cents /= np.linalg.norm(cents, axis=1, keepdims=True)
    return (cents[rng.integers(0, clusters, v)]
            + noise * rng.standard_normal((v, d)).astype(np.float32)
            / np.sqrt(d))


def make_model(v=3000, d=32, seed=0):
    m = clustered_matrix(v, d, seed=seed)
    vocab = Vocabulary.from_words_and_counts(
        [f"w{i}" for i in range(v)], np.ones(v, np.int64))
    return Word2VecModel(vocab, jnp.asarray(m))


# -- batcher ---------------------------------------------------------------------------


def test_batcher_coalesces_concurrent_submits():
    sizes = []

    def handler(batch):
        sizes.append(len(batch))
        time.sleep(0.005)  # hold the worker so submitters pile up
        return [x * 2 for x in batch]

    b = BatchingScheduler(handler, max_batch=16, max_delay_ms=5.0,
                          max_queue=128).start()
    try:
        results = {}

        def client(i):
            results[i] = b.submit(i)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(48)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == {i: i * 2 for i in range(48)}
        assert sum(sizes) == 48
        assert max(sizes) > 1, f"no coalescing happened ({sizes})"
        st = b.stats()
        assert st["submitted"] == st["completed"] == 48
        assert st["errors"] == st["refused"] == 0
        assert st["batches"] == len(sizes)
        assert st["latency_ms"]["n"] == 48
    finally:
        b.stop()


def test_batcher_deadline_flushes_lone_request():
    b = BatchingScheduler(lambda batch: [len(batch)], max_batch=1024,
                          max_delay_ms=20.0, max_queue=8).start()
    try:
        t0 = time.monotonic()
        assert b.submit("x") == 1  # a lone request must not wait forever
        assert time.monotonic() - t0 < 5.0
    finally:
        b.stop()


def test_batcher_backpressure_refuses_fast():
    gate = threading.Event()

    def handler(batch):
        gate.wait(30)
        return batch

    b = BatchingScheduler(handler, max_batch=1, max_delay_ms=0.0,
                          max_queue=4).start()
    try:
        threads = []
        # 1 in-flight inside the handler + 4 filling the queue
        for i in range(5):
            t = threading.Thread(target=lambda: b.submit(1))
            t.start()
            threads.append(t)
        deadline = time.monotonic() + 5
        while b.stats()["queue_depth"] < 4 and time.monotonic() < deadline:
            time.sleep(0.005)
        t0 = time.monotonic()
        with pytest.raises(ServerOverloaded):
            b.submit(2)
        assert time.monotonic() - t0 < 1.0, "refusal was not fast"
        assert b.stats()["refused"] == 1
        gate.set()
        for t in threads:
            t.join(timeout=30)
    finally:
        gate.set()
        b.stop()


def test_batcher_per_request_errors_do_not_fail_the_batch():
    def handler(batch):
        return [ValueError(f"bad {x}") if x < 0 else x for x in batch]

    b = BatchingScheduler(handler, max_batch=8, max_delay_ms=2.0,
                          max_queue=32).start()
    try:
        assert b.submit(7) == 7
        with pytest.raises(ValueError, match="bad -3"):
            b.submit(-3)
        assert b.submit(9) == 9
        st = b.stats()
        assert st["errors"] == 1 and st["completed"] == 2
    finally:
        b.stop()


def test_batcher_handler_exception_reaches_every_caller():
    def handler(batch):
        raise RuntimeError("kaboom")

    b = BatchingScheduler(handler, max_batch=4, max_delay_ms=1.0,
                          max_queue=8).start()
    try:
        with pytest.raises(RuntimeError, match="kaboom"):
            b.submit(1)
    finally:
        b.stop()
    with pytest.raises(RuntimeError):
        b.submit(2)  # stopped scheduler refuses new work


def test_batcher_submit_during_and_after_shutdown_raises_typed():
    """ISSUE-12 satellite: a submit racing stop() gets the typed
    ServiceClosed (subclassing RuntimeError for old callers), during the
    drain AND after it — never whatever the dead worker queue produces."""
    gate = threading.Event()

    def handler(batch):
        gate.wait(30)
        return batch

    b = BatchingScheduler(handler, max_batch=1, max_delay_ms=0.0,
                          max_queue=8).start()
    admitted = b.submit_async(1)  # in flight when stop() lands
    stopper = threading.Thread(target=b.stop)
    stopper.start()
    try:
        deadline = time.monotonic() + 5
        while not b._stopping and time.monotonic() < deadline:
            time.sleep(0.005)
        # submit DURING shutdown (worker still draining the admitted one)
        with pytest.raises(ServiceClosed):
            b.submit(2)
        gate.set()
        stopper.join(timeout=30)
        # submit AFTER shutdown
        with pytest.raises(ServiceClosed):
            b.submit(3)
        # the admitted request was still served (drain-and-stop contract)
        assert b.wait(admitted, timeout=5) == 1
    finally:
        gate.set()
        stopper.join(timeout=5)


def test_overload_carries_retry_after_hint():
    """ISSUE-12 satellite: ServerOverloaded carries retry_after_s = queued
    batches x the observed (EWMA) batch service time — None before the
    first batch ever completed (no honest estimate exists yet)."""
    gate = threading.Event()
    first_done = threading.Event()

    def handler(batch):
        if first_done.is_set():
            gate.wait(30)
        else:
            time.sleep(0.05)  # a measured first batch: EWMA ~= 50 ms
            first_done.set()
        return batch

    b = BatchingScheduler(handler, max_batch=1, max_delay_ms=0.0,
                          max_queue=2).start()
    try:
        assert b.submit(0) == 0  # establishes the EWMA
        assert abs(b.stats()["batch_service_s"] - 0.05) < 0.04
        threads = [threading.Thread(target=lambda: b.submit(1))
                   for _ in range(3)]  # 1 in handler + 2 filling the queue
        for t in threads:
            t.start()
        deadline = time.monotonic() + 5
        while b.stats()["queue_depth"] < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        with pytest.raises(ServerOverloaded) as ei:
            b.submit(9)
        hint = ei.value.retry_after_s
        assert hint is not None and hint > 0, \
            "refusal after a measured batch must carry the drain hint"
        # 2 queued batches x ~50 ms EWMA, loose upper bound for CI noise
        assert hint < 2.0, f"hint implausibly large: {hint}"
        gate.set()
        for t in threads:
            t.join(timeout=30)
    finally:
        gate.set()
        b.stop()


def test_overload_hint_is_none_before_first_batch():
    gate = threading.Event()
    b = BatchingScheduler(lambda batch: gate.wait(30) or batch,
                          max_batch=1, max_delay_ms=0.0, max_queue=1).start()
    try:
        t = threading.Thread(target=lambda: b.submit(1))
        t.start()
        t2 = threading.Thread(target=lambda: b.submit(2))
        t2.start()
        deadline = time.monotonic() + 5
        while b.stats()["queue_depth"] < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        with pytest.raises(ServerOverloaded) as ei:
            b.submit(3)
        assert ei.value.retry_after_s is None  # no measured batch yet
        gate.set()
        t.join(timeout=30)
        t2.join(timeout=30)
    finally:
        gate.set()
        b.stop()


# -- decorrelated-jitter backoff (ISSUE-12 satellite) ----------------------------------


def test_decorrelated_jitter_seeded_sequence():
    """Same seed -> the exact same delay sequence; different seeds ->
    decorrelated sequences (the anti-thundering-herd property N fleet
    replicas retrying one publish path rely on); every delay in
    [base, cap]."""
    a_gen = decorrelated_jitter(0.25, 2.0, np.random.default_rng(3))
    a = [next(a_gen) for _ in range(6)]
    b_gen = decorrelated_jitter(0.25, 2.0, np.random.default_rng(3))
    b = [next(b_gen) for _ in range(6)]
    assert a == b, "seeded jitter must be reproducible"
    c_gen = decorrelated_jitter(0.25, 2.0, np.random.default_rng(4))
    c = [next(c_gen) for _ in range(6)]
    assert a != c, "different seeds must decorrelate"
    for d in a + c:
        assert 0.25 <= d <= 2.0
    assert len(set(a)) > 1, "fixed-interval retry is the bug this removes"


def test_load_with_retry_backoff_uses_seeded_jitter(tmp_path, monkeypatch):
    """The retry loop's sleeps are exactly the decorrelated-jitter
    sequence of the rng passed in (unit-tested with a seeded RNG, per the
    ISSUE) — not the old synchronized fixed interval."""
    slept = []
    monkeypatch.setattr(
        "glint_word2vec_tpu.serve.reload.time.sleep", slept.append)
    with pytest.raises(FileNotFoundError):
        load_with_retry(str(tmp_path / "never-published"), attempts=5,
                        delay=0.25, max_delay=2.0,
                        rng=np.random.default_rng(11))
    want_gen = decorrelated_jitter(0.25, 2.0, np.random.default_rng(11))
    want = [next(want_gen) for _ in range(4)]  # attempts-1 sleeps
    assert slept == want
    assert len(set(slept)) > 1


# -- ANN index -------------------------------------------------------------------------


def test_ivf_build_is_deterministic():
    m = clustered_matrix()
    a = build_ivf(m, seed=3, measure_recall=False)
    b = build_ivf(m, seed=3, measure_recall=False)
    np.testing.assert_array_equal(a._centroids, b._centroids)
    np.testing.assert_array_equal(a._ids, b._ids)
    c = build_ivf(m, seed=4, measure_recall=False)
    assert not np.array_equal(a._centroids, c._centroids)


def test_ivf_full_probe_matches_exact_oracle():
    m = clustered_matrix(v=800, d=16)
    idx = build_ivf(m, seed=0, measure_recall=False)
    normed = m / np.maximum(
        np.linalg.norm(m, axis=1, keepdims=True), 1e-12)
    q = normed[:8]
    s, ids = idx.search(q, 5, nprobe=idx.num_centroids)
    exact = q @ normed.T
    for r in range(8):
        want = np.argsort(-exact[r], kind="stable")[:5]
        assert set(ids[r]) == set(want), "full probe must equal exact scan"


def test_ivf_recall_on_clustered_geometry():
    idx = build_ivf(clustered_matrix(v=5000, d=32), seed=0)
    assert idx.stats["recall_at_10"] >= 0.95  # the serving acceptance bar
    # recall is monotone toward 1.0 as nprobe grows to C
    probes = np.arange(64)
    full = idx.measure_recall(probes, k=10, nprobe=idx.num_centroids)
    assert full == 1.0


def test_ivf_small_cells_still_fill_topk():
    """The serve-reload chaos finding: at toy vocab the nprobe budget can
    land on cells with fewer than k rows — probing must expand until the
    candidate pool covers k, never return a short result."""
    m = clustered_matrix(v=30, d=8, clusters=5)
    idx = build_ivf(m, seed=0, measure_recall=False)
    s, ids = idx.search(m[:4], 6, nprobe=1)
    assert (ids >= 0).all(), f"short result at tiny cells: {ids}"


def test_ivf_zero_norm_rows_never_surface():
    m = clustered_matrix(v=200, d=16)
    m[50] = 0.0  # a sharding-padding-style zero row
    idx = build_ivf(m, seed=0, measure_recall=False)
    _, ids = idx.search(m[:16], 10, nprobe=idx.num_centroids)
    assert 50 not in set(ids.ravel().tolist())


# -- model ANN entry -------------------------------------------------------------------


def test_model_ann_routing_and_parity():
    model = make_model()
    with pytest.raises(RuntimeError, match="no index attached"):
        model.find_synonyms_batch(["w0"], 5, ann=True)
    index = build_ivf(np.asarray(model.syn0), seed=0)
    model.attach_ann(index)
    assert model.ann is index
    exact = model.find_synonyms_batch(["w0", "w7"], 8)
    ann_full = model.find_synonyms_batch(
        ["w0", "w7"], 8, ann=True, nprobe=index.num_centroids)
    # full probe: identical neighbors, identical self-exclusion semantics
    assert [[w for w, _ in row] for row in ann_full] == \
           [[w for w, _ in row] for row in exact]
    for row_a, row_e in zip(ann_full, exact):
        np.testing.assert_allclose([s for _, s in row_a],
                                   [s for _, s in row_e], rtol=1e-5)
    ann = model.find_synonyms_batch(["w0"], 10, ann=True)
    assert len(ann[0]) == 10 and "w0" not in [w for w, _ in ann[0]]
    model.stop()
    assert model.ann is None


# -- serving handle --------------------------------------------------------------------


def test_handle_swap_drains_leases_before_release():
    old, new = make_model(v=100, d=8, seed=1), make_model(v=100, d=8, seed=2)
    h = ServingHandle(old)
    with h.lease() as (m, _):
        assert m is old
        h.swap(new)
        # the in-flight lease still serves the OLD model, un-released
        assert m.num_words == 100 and not m._stopped
        assert h.models_released == 0
        with h.lease() as (m2, _):
            assert m2 is new  # future leases see the new generation
    # lease drained -> old released exactly once
    assert h.models_released == 1 and old._stopped and not new._stopped
    h.stop()
    assert new._stopped and h.models_released == 2
    with pytest.raises(RuntimeError):
        with h.lease():
            pass


# -- the assembled service -------------------------------------------------------------


def _train_tiny(tmp_path, seed=9, n=120):
    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.data.pipeline import encode_sentences
    from glint_word2vec_tpu.train.trainer import Trainer
    rng = np.random.default_rng(seed)
    sents = [[f"w{j}" for j in rng.integers(0, 40, 12)] for _ in range(n)]
    vocab = build_vocab(sents, min_count=1)
    cfg = Word2VecConfig(vector_size=16, min_count=1, pairs_per_batch=128,
                         num_iterations=1, window=2, negatives=3,
                         negative_pool=8, steps_per_dispatch=2, seed=seed)
    trainer = Trainer(cfg, vocab)
    trainer.fit(encode_sentences(sents, vocab, cfg.max_sentence_length))
    ck = str(tmp_path / "model")
    trainer.save_checkpoint(ck)
    return trainer, vocab, ck, sents


def test_service_exact_arm_matches_model(tmp_path):
    trainer, vocab, ck, _ = _train_tiny(tmp_path)
    local = Word2VecModel.load(ck)
    want = local.find_synonyms("w0", 5)
    svc = EmbeddingService(checkpoint=ck, ann=False)
    try:
        got = svc.synonyms("w0", 5)
        assert [w for w, _ in got] == [w for w, _ in want]
        np.testing.assert_allclose([s for _, s in got],
                                   [s for _, s in want], rtol=1e-5)
        np.testing.assert_allclose(svc.vector("w1"), local.transform("w1"),
                                   rtol=1e-6)
        batch = svc.synonyms_batch(["w0", "w1", "w2"], 5)
        assert len(batch) == 3 and all(len(r) == 5 for r in batch)
        with pytest.raises(KeyError, match="not in vocabulary"):
            svc.synonyms("nope", 5)
        info = svc.info()
        assert info["num_words"] == vocab.size and info["finished"]
    finally:
        svc.close()
    local.stop()


def test_service_reload_and_telemetry(tmp_path):
    trainer, vocab, ck, sents = _train_tiny(tmp_path)
    log = str(tmp_path / "serve.jsonl")
    svc = EmbeddingService(checkpoint=ck, ann=True, telemetry_path=log)
    try:
        r1 = svc.synonyms("w0", 5)
        assert len(r1) == 5
        # the trainer publishes a newer checkpoint; explicit reload swaps
        from glint_word2vec_tpu.data.pipeline import encode_sentences
        trainer.fit(encode_sentences(sents, vocab, 1000))
        trainer.save_checkpoint(ck)
        model = svc.reload_now()
        assert model.num_words == vocab.size
        assert svc.stats()["reloads"] == 1
        assert svc.stats()["models_released"] == 1  # old buffers gone
        assert len(svc.synonyms("w0", 5)) == 5
        svc.emit_stats()
    finally:
        svc.close()
    summary = validate_file(log)
    assert summary["ok"], summary["errors"][:3]
    kinds = summary["kinds"]
    assert kinds.get("serve_start") == 1
    assert kinds.get("serve_reload") == 1
    assert kinds.get("serve_stats") == 1
    assert kinds.get("serve_end") == 1
    with open(log) as f:
        recs = [json.loads(line) for line in f]
    start = next(r for r in recs if r["kind"] == "serve_start")
    assert start["ann"]["centroids"] >= 1  # index stats ride the record


def test_service_watcher_hot_reloads(tmp_path):
    trainer, vocab, ck, sents = _train_tiny(tmp_path, seed=11)
    svc = EmbeddingService(checkpoint=ck, ann=True, watch=True,
                           reload_poll_s=0.05)
    try:
        from glint_word2vec_tpu.data.pipeline import encode_sentences
        trainer.fit(encode_sentences(sents, vocab, 1000))
        trainer.save_checkpoint(ck)  # the publish signal
        deadline = time.monotonic() + 10
        while svc.stats()["reloads"] < 1 and time.monotonic() < deadline:
            assert len(svc.synonyms("w0", 5)) == 5  # serving never stops
            time.sleep(0.02)
        assert svc.stats()["reloads"] >= 1, "watcher never saw the publish"
        assert svc.stats()["models_released"] >= 1
    finally:
        svc.close()


def test_watcher_sees_publish_landing_during_boot_load(tmp_path, monkeypatch):
    """Review finding: the publish signature must be captured BEFORE the
    (slow) initial load + index build — a trainer publish landing inside
    that window must still fire the watcher, not be recorded as served."""
    trainer, vocab, ck, sents = _train_tiny(tmp_path, seed=13)
    import glint_word2vec_tpu.serve.service as service_mod
    real_load = service_mod.load_with_retry

    def slow_load_with_publish(path, plan=None, **kw):
        model = real_load(path, plan=plan, **kw)
        # the trainer publishes AGAIN while the boot load is in flight
        trainer.save_checkpoint(ck)
        return model

    monkeypatch.setattr(service_mod, "load_with_retry",
                        slow_load_with_publish)
    svc = EmbeddingService(checkpoint=ck, ann=False, watch=True,
                           reload_poll_s=0.05)
    monkeypatch.setattr(service_mod, "load_with_retry", real_load)
    try:
        deadline = time.monotonic() + 10
        while svc.stats()["reloads"] < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert svc.stats()["reloads"] >= 1, \
            "publish during the boot load was swallowed"
    finally:
        svc.close()


def test_watcher_survives_delete_then_recreate(tmp_path):
    """ISSUE-12 satellite: the publish path deleted mid-watch (operator
    mistake, retention sweep) must not crash or wedge the watcher — the
    ABSENT state is not a signal, the current model keeps serving, and a
    later re-publish at the same path fires a normal reload."""
    trainer, vocab, ck, sents = _train_tiny(tmp_path, seed=17)
    svc = EmbeddingService(checkpoint=ck, ann=False, watch=True,
                           reload_poll_s=0.05)
    try:
        assert len(svc.synonyms("w0", 5)) == 5
        shutil.rmtree(ck)  # the publish path vanishes mid-watch
        time.sleep(0.3)  # several polls over the absent path
        assert len(svc.synonyms("w0", 5)) == 5  # still serving, no crash
        assert svc.stats()["reloads"] == 0
        trainer.save_checkpoint(ck)  # recreated: a fresh publish identity
        deadline = time.monotonic() + 15
        while svc.stats()["reloads"] < 1 and time.monotonic() < deadline:
            assert len(svc.synonyms("w0", 5)) == 5
            time.sleep(0.02)
        assert svc.stats()["reloads"] >= 1, \
            "recreated publish path never fired the watcher"
        assert len(svc.synonyms("w0", 5)) == 5
    finally:
        svc.close()


def test_watcher_survives_torn_publish_metadata_before_arrays(tmp_path):
    """ISSUE-12 satellite: metadata.json appearing BEFORE its arrays (the
    torn-publish window a non-atomic copy/rsync produces) must end in a
    served model, never a crash — the watcher fires on the metadata
    identity, load_with_retry absorbs the missing-arrays window, and a
    failed round leaves the old model serving with the next poll
    retrying."""
    trainer, vocab, ck, sents = _train_tiny(tmp_path, seed=19)
    staging = str(tmp_path / "staged")
    shutil.copytree(ck, staging)  # a complete publish to tear apart
    svc = EmbeddingService(checkpoint=ck, ann=False, watch=True,
                           reload_poll_s=0.05)
    try:
        assert len(svc.synonyms("w0", 5)) == 5
        # the torn window: re-publish metadata/words/counts, arrays ABSENT
        shutil.rmtree(ck)
        os.makedirs(ck)
        for f in ("metadata.json", "words", "counts.npy"):
            shutil.copy2(os.path.join(staging, f), os.path.join(ck, f))
        time.sleep(0.4)  # the watcher fires into the torn window
        assert len(svc.synonyms("w0", 5)) == 5  # old model still serving
        # the arrays land; the in-flight retry (or the next poll) heals
        for f in ("syn0.npy", "syn1.npy"):
            shutil.copy2(os.path.join(staging, f), os.path.join(ck, f))
        deadline = time.monotonic() + 30
        while svc.stats()["reloads"] < 1 and time.monotonic() < deadline:
            assert len(svc.synonyms("w0", 5)) == 5
            time.sleep(0.02)
        assert svc.stats()["reloads"] >= 1, \
            "torn publish never healed into a served model"
        assert len(svc.synonyms("w0", 5)) == 5
    finally:
        svc.close()


def test_stats_carry_served_publish_generation(tmp_path):
    """The fleet staleness channel: stats()['publish_sig'] is the served
    publish identity — None for in-memory models, refreshed by reload."""
    trainer, vocab, ck, sents = _train_tiny(tmp_path, seed=23)
    svc = EmbeddingService(checkpoint=ck, ann=False)
    try:
        sig0 = svc.stats()["publish_sig"]
        assert sig0, "checkpoint-backed service must report its generation"
        trainer.save_checkpoint(ck)
        svc.reload_now()
        sig1 = svc.stats()["publish_sig"]
        assert sig1 and sig1 != sig0, "reload must advance the generation"
    finally:
        svc.close()
    mem = EmbeddingService(model=make_model(v=50, d=8), ann=False)
    try:
        assert mem.stats()["publish_sig"] is None
    finally:
        mem.close()


def test_failed_init_does_not_leak_threads_or_model():
    """Review finding: a failed __init__ (here: status port already bound)
    must stop the already-started batcher thread and leave a caller-owned
    model untouched."""
    import socket
    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    port = blocker.getsockname()[1]
    model = make_model(v=100, d=8)
    try:
        with pytest.raises(OSError):
            EmbeddingService(model=model, ann=False, status_port=port)
        deadline = time.monotonic() + 5
        while (any(t.name == "glint-serve-batcher"
                   for t in threading.enumerate())
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert not any(t.name == "glint-serve-batcher"
                       for t in threading.enumerate()), \
            "batcher thread leaked past the failed init"
        assert not model._stopped  # caller-owned model stays alive
        # the pure-validation errors raise before ANY resource exists
        with pytest.raises(ValueError, match="watch=True needs"):
            EmbeddingService(model=model, watch=True)
    finally:
        blocker.close()
        model.stop()


def test_serve_record_kinds_validate():
    base = {"schema": 1, "t": 0.0}
    ok = [
        {**base, "kind": "serve_start", "checkpoint": "/ck",
         "vocab_size": 10, "vector_size": 4, "ann": {"centroids": 2}},
        {**base, "kind": "serve_reload", "vocab_size": 10, "reloads": 1,
         "load_seconds": 0.5},
        {**base, "kind": "serve_stats", "submitted": 5, "refused": 0,
         "batches": 2, "queue_depth": 0, "reloads": 1,
         "latency_ms": {"p50": 1.0}, "occupancy_mean": 2.5},
        {**base, "kind": "serve_end", "submitted": 5, "refused": 0,
         "reloads": 1},
    ]
    for rec in ok:
        assert validate_record(rec) == [], rec["kind"]
    bad = {**base, "kind": "serve_stats", "submitted": 5}
    assert validate_record(bad), "missing required fields must fail"
    wrong = {**base, "kind": "serve_start", "checkpoint": "/ck",
             "vocab_size": 10, "vector_size": 4, "ann": "not-a-dict"}
    assert validate_record(wrong), "optional field with wrong type must fail"


def test_serve_prometheus_rendering():
    snap = {"status": "serving", "submitted": 12, "refused": 1,
            "completed": 11, "errors": 0, "batches": 4, "queue_depth": 2,
            "occupancy_mean": 3.0, "reloads": 2, "models_released": 2,
            "vocab_size": 1000, "load_seconds": 0.4,
            "latency_ms": {"p50": 1.5, "p95": 3.0, "p99": 4.5, "n": 11},
            "ann": {"recall_at_10": 0.99, "nprobe": 8, "centroids": 64,
                    "build_seconds": 0.2}}
    text = serve_prometheus_text(snap)
    for needle in ("glint_serve_up 1", "glint_serve_submitted_total 12",
                   "glint_serve_refused_total 1",
                   "glint_serve_queue_depth 2",
                   'glint_serve_latency_ms{quantile="p99"} 4.5',
                   "glint_serve_ann_recall_at_10 0.99",
                   "glint_serve_reloads_total 2"):
        assert needle in text, f"{needle!r} missing from:\n{text}"
    assert "glint_serve_up 0" in serve_prometheus_text({"status": "closed"})
