"""Hyperparameter surface.

Mirrors the reference's 16-knob surface (mllib/feature/ServerSideGlintWord2Vec.scala:67-244,
ml/feature/ServerSideGlintWord2Vec.scala:40-222) with the same semantics and defaults, plus
TPU-native knobs the reference had no analog for (mesh shape, pair-batch size, dtype, pallas).

Reference defaults (mllib:67-81,251): vectorSize 100, learningRate 0.01875, numPartitions 1,
numIterations 1, minCount 5, maxSentenceLength 1000, window 5, batchSize 50, n 5,
subsampleRatio 1e-6, numParameterServers 5, parameterServerHost "", unigramTableSize 1e8,
seed random.

Knobs that existed only to work around the reference's Akka transport — the
``batchSize * n * window <= 10000`` payload constraint (mllib:83-85,154-188) and
``parameterServerHost``/``parameterServerConfig`` (mllib:196-231) — are accepted by the
compat layer (:mod:`glint_word2vec_tpu.models.compat`) for drop-in familiarity but have no
effect here: there is no RPC.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass
class Word2VecConfig:
    """Configuration for TPU-native word2vec training.

    Attributes whose names differ from the reference keep a comment mapping them back.
    """

    # --- core hyperparameters (reference-parity; defaults mllib:67-81,251) ---
    vector_size: int = 100          # vectorSize (mllib:67)
    learning_rate: float = 0.01875  # stepSize/learningRate (mllib:68)
    num_partitions: int = 1         # numPartitions (mllib:69) — scales the lr-decay clock
                                    # (mllib:406-410); on TPU it is the data-parallel degree
    num_iterations: int = 1         # numIterations (mllib:70)
    min_count: int = 5              # minCount (mllib:76)
    max_sentence_length: int = 1000  # maxSentenceLength (mllib:73,88-97)
    window: int = 5                 # window (mllib:251)
    batch_size: int = 50            # batchSize (mllib:74) — reference centers-per-minibatch;
                                    # kept for decay/compat; device batching uses pairs_per_batch
    negatives: int = 5              # n (mllib:75)
    subsample_ratio: float = -1.0   # subsampleRatio (mllib:77,190-194). 0 disables.
                                    # -1 (default) = AUTO: resolves to 1e-3 at
                                    # construction, and the Trainer may LOWER it
                                    # further when the corpus + batch geometry would
                                    # exceed the measured duplicate-overload
                                    # divergence boundary (expected top-word
                                    # duplicates per batch > 300 trains to NaN —
                                    # EVAL.md round-4 addendum). An explicit value is
                                    # never silently changed: explicit unstable
                                    # configs are refused unless allow_unstable=True.
                                    # Why 1e-3 (word2vec.c's/gensim's default):
                                    # bounds EVAL.md's duplicate-overload channel — a
                                    # frequent word's summed scatter updates in one
                                    # large batch diverge with subsampling OFF — while
                                    # staying sane on small corpora (1e-4 starves a
                                    # 161k-word corpus below the reference's own
                                    # semantic gates; 1e-3 passes them AND holds
                                    # purity 1.0 at 17M words in EVAL_RUNS — though
                                    # the same 17M rows measure analogy acc@1 0.71 at
                                    # 1e-3 vs 0.99 at 1e-4, so tune per corpus.
                                    # HARD boundary, measured: 1e-3 with B=64k
                                    # diverges at 60M words (duplicate channel, 336
                                    # expected dups > the 300 threshold — the
                                    # construction-time warning names exactly this);
                                    # large-batch long runs want ~1e-4, which is also
                                    # the best relational quality at scale.
                                    # NOTE: the reference's default is 1e-6, but its
                                    # formula divides Int/Long (mllib:374-376) so its
                                    # subsampling is a silent no-op — the compat layer
                                    # pins 0.0 to mirror that observed behavior. Setting
                                    # >0 uses the intended float formula (pipeline.py).
    seed: int = 0                   # seed (mllib:71; random by default there, fixed here for
                                    # reproducibility — sync training makes runs deterministic)

    # --- sharding / deployment (replaces numParameterServers & PS plumbing) ---
    num_model_shards: int = 1       # ≈ numParameterServers (mllib:78,204-212): how many ways
                                    # the embedding rows are sharded over the mesh 'model' axis
    num_data_shards: int = 1        # data-parallel degree over the mesh 'data' axis
    embedding_partition: str = "rows"  # "rows" (production: V/N rows per device) or
                                       # "cols" (CIKM'16: D/N columns per device,
                                       # partial dots + psum — the reference PS
                                       # layout, G2/SURVEY §7.4). Identical math
                                       # (cross-layout loss check in the dryrun).
                                       # "cols" is EXPERIMENTAL, single-host only:
                                       # the design verdict (PERF.md §7) is that
                                       # rows divides the per-update-row scatter
                                       # bound by N and enables row-shards
                                       # checkpoints, while cols only wins
                                       # collective bytes below pool ≈ 2·D (its
                                       # blowout case, per-pair sampling, is the
                                       # reference's thin-network regime, not ICI)
    mesh_shape: Optional[Tuple[int, int]] = None  # explicit (data, model) mesh; default derives
                                                  # from num_data_shards × num_model_shards
    step_lowering: str = "gspmd"    # how the sharded SGNS step lowers onto the mesh:
                                    # "gspmd" (default): one jitted program, GSPMD
                                    # inserts whatever collectives it derives from the
                                    # sharding constraints — the pre-round-9 behavior,
                                    # bit-identical to it.
                                    # "shard_map": the hand-lowered explicit schedule
                                    # (ops/sgns_shard.py, docs/sharding.md) — each
                                    # model shard gathers rows it owns (index −
                                    # row_offset, OOB-masked) with ONE psum over the
                                    # model axis assembling e_in/e_pos/pool rows, the
                                    # backward applies OWNER-LOCAL scatters only (zero
                                    # update bytes cross the model axis — the TPU
                                    # analog of the reference's ship-indices-and-
                                    # scalars collective schedule, CIKM'16), and the
                                    # data axis exchanges the per-shard update payload
                                    # with one all-gather. HLO-audited collective
                                    # bytes: tools/collectives.py; mesh-shape A/B:
                                    # tools/shard_ab.py. Identical math (f64 ~1e-12
                                    # equivalence tested at every 8-device mesh
                                    # shape); each lowering is run-to-run
                                    # deterministic, but the two lowerings are NOT
                                    # bit-identical to each other (different FP
                                    # reduction orders). Shared-pool skip-gram rows
                                    # layout only (pool > 0, no cbow/pallas/
                                    # duplicate_scaling/cols — refused at
                                    # construction). GSPMD stays the default until a
                                    # hardware A/B lands (the audited collective
                                    # profile is the evidence so far, PERF.md §7)
    sync_every: int = 1             # local-SGD merge cadence (docs/sharding.md
                                    # §Local-SGD): 1 (default) = fully synchronous,
                                    # bit-identical to the pre-knob step. k > 1 = each
                                    # data shard runs k OWNER-LOCAL steps against its
                                    # own params replica (the shard_map schedule's
                                    # owner-local gather/scatter machinery, so zero
                                    # update bytes cross the model axis AND zero bytes
                                    # cross the data axis inside the window), then ONE
                                    # delta-merge collective reconciles the data axis:
                                    # merged = start + psum(local − start, data)/nd —
                                    # the reference's Hogwild-across-partitions
                                    # network-thrift discipline (PAPER.md §0, CIKM'16)
                                    # in its deterministic periodic-averaging form.
                                    # Per-shard negative lattices are DISJOINT, so a
                                    # merged run is deterministic per (seed, mesh, k).
                                    # shard_map lowering only (the owner-local window
                                    # doesn't exist under GSPMD — refused at
                                    # construction); must divide steps_per_dispatch so
                                    # every dispatch boundary is a merge boundary
                                    # (snapshot/rollback/preemption-save never see an
                                    # unmerged shard). Priced: tools/collectives.py
                                    # --sync-every; quality-gated: tools/eval_quality
                                    # --localsgd-ab

    # --- negative-sampling table (G7; mllib:81,234-244) ---
    unigram_table_size: int = 100_000_000  # kept for compat; the alias sampler is O(2·vocab)
                                           # and exact, so this only sizes the optional
                                           # table-based sampler used in parity tests
    sample_power: float = 0.75      # classic word2vec counts^0.75 (fork-side in the reference)

    # --- TPU-native knobs (no reference analog) ---
    pairs_per_batch: int = 8192     # (center, context) pairs per device step; the reference's
                                    # RPC-bound batchSize*window pairs/minibatch becomes one
                                    # large fixed-shape jit step. Sized for realistic
                                    # corpora (millions of words up); on toy corpora use
                                    # a small batch (~256) — a 161k-word corpus at 8192
                                    # pairs/step gets only ~20 coarse updates per epoch,
                                    # too few for sharp analogy geometry (the toy
                                    # integration suite's settings)
    sigmoid_mode: str = "exact"     # "exact" = jax.nn.sigmoid; "clipped" mirrors the reference
                                    # LUT clipping at |f| > 6 (mllib:246-248,292-302)
    allow_unstable: bool = False    # override the construction-time REFUSAL of configs
                                    # inside a measured divergence region (today: the
                                    # duplicate-overload channel — explicit
                                    # subsample_ratio whose expected top-word
                                    # duplicates per batch exceed 300, the boundary
                                    # EVAL measured training to NaN at 60M words).
                                    # With the override the trainer only warns, for
                                    # boundary research and short runs
    duplicate_scaling: bool = False  # opt-in stabilizer: average (not sum) a row's updates
                                     # over its in-batch multiplicity. Off by default —
                                     # textbook word2vec semantics; realistic vocabs have
                                     # low duplicate density after subsampling. Turn on for
                                     # tiny-vocab/large-batch regimes where summed
                                     # duplicates would diverge (slows differentiation;
                                     # see ops/sgns.py)
    negative_pool: int = -1         # >0: share one pool of this many negatives across the
                                    # whole batch (reweighted by negatives/pool to keep the
                                    # expected gradient) — turns the dominant negative row
                                    # traffic into MXU matmuls, ~2-3x step speedup. 0 = the
                                    # reference's exact per-pair sampling (G3 semantics;
                                    # the compat layer pins this). -1 (default) = AUTO:
                                    # resolved at construction to the smallest multiple of
                                    # 128 keeping the pool-row load pairs_per_batch *
                                    # negatives / pool <= 600 — the measured 60M-word
                                    # stability rule (EVAL.md; a fixed small pool under a
                                    # large batch provably diverges, e.g. B=64k/P=64).
                                    # That 600 band was CALIBRATED AT 90k VOCAB; at
                                    # large vocabularies a pool row is re-corrected
                                    # orders of magnitude less often and the measured
                                    # safe band tightens to load <= 160 (EVAL.md
                                    # round-5: load 640 collapsed purity 0.99 -> 0.14
                                    # at 1.6M vocab, load 160 fixed it at the same
                                    # lr). Config cannot see the vocabulary, so the
                                    # Trainer re-resolves a STILL-AUTO pool upward at
                                    # construction once vocab.size > 500k
                                    # (trainer._resolve_vocab_scaled_pool; explicit
                                    # pools are never changed, only warned about) —
                                    # except batches < 4096 pairs, which resolve to 0:
                                    # per-pair is fast enough there and shared negatives
                                    # cost quality on small corpora (toy bf16 gate)
    pad_vector_to_lanes: bool = True  # pad the embedding minor dim to a multiple of 128
                                      # (TPU lane width) — D=300 rows are misaligned and
                                      # measurably slower than padded 384; exports are
                                      # sliced back to vector_size
    param_dtype: str = "float32"    # embedding storage dtype
    compute_dtype: str = "float32"  # dot-product dtype ("bfloat16" rides the MXU)
    logits_dtype: str = "float32"   # dtype of the [B, pool] negative-logit chain on
                                    # the shared-pool paths (f_neg → sigmoid → g_neg).
                                    # f32 matches the reference's client-side math
                                    # (mllib:421-425); "bfloat16" halves what is, at
                                    # pool >= 512, several full passes over a [B, pool]
                                    # array (PERF.md §4) — coefficients are O(lr·n/pool)
                                    # and tolerate the ~0.4% relative noise
    # --- step restructurings (ISSUE 14, PERF.md §11 — the emitter-ceiling
    # levers; all off by default, and OFF ELIDES THE NEW OPS: the default
    # step is bit-identical to the pre-restructure release, tested) ---
    fused_logits: bool = False      # fuse the negative-logit coefficient chain
                                    # (ops/sgns.py shared_pool_coeffs): validity
                                    # + batch mask fold into ONE select and the
                                    # alpha·negatives/pool reweight into one
                                    # precomputed scalar, so the [B, pool] (or
                                    # per-pair [B, n]) chain materializes only
                                    # the dot output and the coefficient array
                                    # instead of also a float validity array
                                    # and its mask/alpha/reweight passes.
                                    # Identical math (f64-oracle tested), not
                                    # bit-identical (multiply association
                                    # changes). SGNS paths only (per-pair,
                                    # shared-pool GSPMD + shard_map); refused
                                    # beside cbow/use_pallas/duplicate_scaling
    bf16_chain: bool = False        # end-to-end reduced-precision update chain:
                                    # the logit dots accumulate in
                                    # promote(compute, f32) via
                                    # preferred_element_type instead of a
                                    # multiply + convert + reduce, so bf16 mode
                                    # materializes NO dense f32 [B, D]
                                    # intermediate (stepaudit dtype-contract
                                    # row pins this) while keeping the R4 f32
                                    # accumulation discipline. Requires
                                    # compute_dtype='bfloat16' (with f32
                                    # compute there is no chain to narrow) and,
                                    # on the shared-pool paths, logits_dtype=
                                    # 'bfloat16'. SGNS paths only; refused
                                    # beside cbow/use_pallas
    hot_rows: int = 0               # > 0: cross-step hot-row accumulation
                                    # (ops/sgns.py hot_* helpers) — updates to
                                    # the K most frequent rows (the vocabulary
                                    # index prefix, by the sorted-by-frequency
                                    # contract) accumulate in a float32 [K, D]
                                    # slab across the steps of a dispatch chunk
                                    # and flush as ONE dense block add per
                                    # hot_flush_every steps, cutting the
                                    # [V, D] scatter-emitter rows per step by
                                    # the Zipf mass of the hot set. Reads stay
                                    # exact (gathers add the pending deltas
                                    # back), so this changes FP rounding order
                                    # only — but that IS a semantic change at
                                    # reduced precision, so it ships default-
                                    # off behind the --hotrow-ab EVAL parity
                                    # gate (tools/eval_quality.py). The
                                    # trainer clamps K to the real vocabulary.
                                    # Single-device SGNS XLA paths only:
                                    # refused beside cbow/use_pallas/
                                    # duplicate_scaling/shard_map/cols/multi-
                                    # shard meshes/stabilizers (the post-
                                    # scatter clamp would measure rows missing
                                    # their pending deltas) and norm_watch=
                                    # 'recover' (auto-engages the clamp)
    hot_flush_every: int = 0        # hot_rows flush cadence in steps. 0
                                    # (default) = AUTO: once per dispatch
                                    # chunk (steps_per_dispatch). An explicit
                                    # value must divide steps_per_dispatch —
                                    # the slab lives in the chunk's scan
                                    # carry, and every chunk flushes
                                    # unconditionally at its end so the
                                    # params carry leaving a dispatch is
                                    # always complete (checkpoints/probes
                                    # never see a pending slab). Inert when
                                    # hot_rows=0
    use_pallas: bool = False        # fused Pallas SGNS kernel for the hot step
    sharded_checkpoint: bool = False  # row-shards save (each process writes its own
                                      # rows, no host gather — G9 analog); forced on
                                      # for multi-process runs
    cbow: bool = False              # CBOW variant (context-mean → center) instead of skip-gram
    cbow_update: str = "scatter"    # CBOW step formulation (cbow=True only):
                                    # "scatter" (default): grouped [B, 2·window]
                                    # context batches, gather/scatter B·C syn0
                                    # rows per step (ops/sgns.cbow_step_*). The
                                    # reference formulation; required for
                                    # duplicate_scaling=True and the only one
                                    # multi-feed-agnostic (host pair feed).
                                    # "banded": sentence-contiguous token-block
                                    # feed + prefix-sum interval accumulation
                                    # (ops/cbow_banded.py) — ~B context rows
                                    # instead of B·C, projected ≥2× examples/s
                                    # at the headline geometry (PERF.md §9).
                                    # Identical update math (float64-equivalence
                                    # tested); needs the shared-pool estimator
                                    # (negative_pool > 0), window ≥ 2, and no
                                    # duplicate_scaling — unsupported combos
                                    # are refused at construction, never
                                    # silently downgraded. Stays opt-in until
                                    # EVAL evidence lands (acceptance rule)
    shuffle: bool = True            # shuffle sentence order each iteration (reference order is
                                    # whatever repartition() produced, i.e. arbitrary; mllib:345)

    # --- lr decay semantics (mllib:405-413) ---
    min_alpha_factor: float = 1e-4  # floor alpha at learning_rate * 1e-4 (mllib:410)
    decay_interval_words: int = 10_000  # reference alpha cadence (mllib:404) — here alpha
                                        # updates every batch (host-side, free); kept for
                                        # compat surface
    steps_per_dispatch: int = 16    # train steps scanned inside one device dispatch;
                                    # amortizes host->device dispatch/transfer latency
                                    # (dominant through a remote-TPU tunnel, still real
                                    # on-pod); the last chunk of an epoch is padded with
                                    # masked batches
    heartbeat_every_steps: int = 100  # telemetry cadence. The reference logs every 10k
                                      # words (one 50-pair minibatch era); fetching device
                                      # metrics forces a host sync, so at 8k-pair batches a
                                      # word-based cadence would sync nearly every step and
                                      # halve throughput
    prefetch_chunks: int = 8        # dispatch chunks buffered by the background batch
                                    # producer thread: host pair-generation overlaps device
                                    # compute (the reference pipelines one minibatch deep
                                    # for the same reason, mllib:428-429). 0 = synchronous
                                    # (producer thread off; debugging aid)
    profile_dir: str = ""           # non-empty: capture a jax.profiler trace of every
                                    # fit() into this directory (view with TensorBoard
                                    # or xprof; complements the host-wait/dispatch
                                    # split the trainer always records)
    feed_consistency_check: bool = False  # debug: on multi-process runs, fingerprint
                                    # every assembled global batch and compare across
                                    # processes (one tiny extra allgather per round) —
                                    # catches SPMD feed divergence (nondeterministic
                                    # host pipelines, clock drift) at the round it
                                    # happens instead of as silent training divergence.
                                    # The aux-subsystem analog of race detection: the
                                    # reference ACCEPTED data races by design
                                    # (Hogwild, SURVEY §5); the synchronous design can
                                    # verify its no-divergence contract instead
    shard_input: bool = True        # multi-process runs: each process generates only its
                                    # own sentence shard (the repartition analog,
                                    # mllib:345) and per-round allgathers assemble the
                                    # global batch — host pipeline work scales 1/N with
                                    # hosts. False = every process regenerates the full
                                    # stream (zero-coordination fallback). Both skip-gram
                                    # (packed pairs) and CBOW (centers/contexts/counts)
                                    # feeds ride the same protocol.
    device_pairgen: bool = False    # generate training pairs ON DEVICE (ops/pairgen.py):
                                    # the host subsamples and ships kept-token blocks
                                    # (~1 byte/pair on the wire vs 4 for packed pairs)
                                    # and the jitted step derives window draws from the
                                    # same position-keyed hash lattice as the host
                                    # pipeline. The stream is deterministic per seed but
                                    # NOT bit-identical to the host feed's (windows are
                                    # keyed by kept-token ordinals and blocks cut at the
                                    # token budget — statistically identical; contract
                                    # + tests in ops/pairgen.py). Use when the
                                    # host→device feed link is the bottleneck (thin
                                    # PCIe/DCN/tunnel links). Skip-gram only (CBOW
                                    # batches are grouped windows the device generator
                                    # does not produce). Multi-process: combine with
                                    # shard_input=True — each process packs token
                                    # blocks for its own data segments and the
                                    # iteration-barrier allgather keeps training
                                    # bit-identical to single-process
                                    # (trainer._fit_device_feed_sharded)
    tokens_per_step: int = 0        # device_pairgen: raw token slots per step; 0 sizes
                                    # automatically from pairs_per_batch, window, and the
                                    # subsample keep ratio (targeting ~93% pair-slot fill;
                                    # overflow pairs are dropped and counted)

    # --- parallel host data plane (PERF.md §10; no reference analog — the
    # reference gets host parallelism from Spark partitions, mllib:345,428) ---
    producer_workers: int = 1       # feed-producer thread pool width. 1 (default) =
                                    # the serial producer, bit-identical to every
                                    # prior release. >1 fans the per-slab pair/token
                                    # generation (and, on multi-segment device
                                    # feeds, the per-segment block streams) across
                                    # this many threads — numpy releases the GIL in
                                    # the hot loops, so production genuinely
                                    # parallelizes. The stream is position-keyed
                                    # (hashrng), so ANY worker count produces the
                                    # bit-identical stream (tested); the knob only
                                    # changes throughput. Sized to the host: ~4 on
                                    # an 8-core host feeding a co-located device
                                    # (PERF.md §5: the serial producer tops out at
                                    # 9.5M pairs/s against a 12.4-13.2M pairs/s step)
    io_workers: int = 1             # checkpoint/export I/O thread pool width. 1
                                    # (default) = serial writes/reads. >1 fans
                                    # independent file writes, shard reads, digest
                                    # verification, and export block formatting
                                    # across this many threads (train/checkpoint.py,
                                    # models/word2vec.py) and parallelizes the
                                    # cold-start builds (vocab counting slabs,
                                    # alias-table partitions). Outputs are
                                    # byte-identical at ANY worker count — the knob
                                    # only changes wall clock. Hashing always
                                    # happens in the same pass as the write
                                    # (single-pass digests; this is unconditional,
                                    # it needs no workers). One CROSS-RELEASE
                                    # caveat, worker-independent: round 8's
                                    # vectorized alias builder (ops/sampler.py)
                                    # produces a DIFFERENT (equally exact,
                                    # deterministic) table than rounds <= 7 at any
                                    # worker count, so the realized negative-sample
                                    # stream differs from prior releases —
                                    # distribution unchanged (tested), PERF.md §10.
                                    # SCOPE caveat for the vocab-counting slab fan-out:
                                    # counting PYTHON string tokens under the GIL is
                                    # pure contention — MEASURED 0.66x at workers=4
                                    # (hostbench, PERF.md §10; Counter.update never
                                    # releases the lock) — so build_vocab engages the
                                    # slab pool only when data.vocab.
                                    # parallel_counting_profitable() says the runtime
                                    # can profit (free-threaded CPython). A session on
                                    # a free-threaded host flips it by re-measuring
                                    # there, not by editing a guess: the helper + its
                                    # evidence live in one place (data/vocab.py)
    sharded_prefetch: bool = True   # multi-process device-feed runs: stage each
                                    # round's allgather + assembly + device put one
                                    # round ahead on a background thread so the
                                    # wire transfer overlaps device compute (the
                                    # single-process _stage_to_device analog). The
                                    # stager and the main loop alternate under a
                                    # strict ticket handshake, so every process
                                    # keeps ONE deterministic program-launch order
                                    # (allgather_r, touch_r, dispatch_r, ...) — the
                                    # invariant that makes cross-host collectives
                                    # deadlock-free (see trainer._one_ahead_iter).
                                    # False = the pre-round-8 consumer-thread put.
                                    # No effect single-process or at
                                    # prefetch_chunks=0

    # --- fault tolerance (docs/robustness.md; no reference analog — the
    # reference leans on Spark task re-execution, SURVEY §5) ---
    nonfinite_policy: str = "halt"  # what the trainer does when the params carry goes
                                    # non-finite (bf16 blowup, divergence). Probed at
                                    # heartbeat/checkpoint cadence on the params the
                                    # heartbeat fetch already syncs on, so the fast
                                    # metrics-elided step twin stays elided.
                                    # "halt" (default): raise NonFiniteParamsError with
                                    # a diagnostic instead of burning accelerator-hours
                                    # training NaNs or overwriting a good checkpoint;
                                    # "rollback": restore the newest in-memory good
                                    # snapshot and jump the negative-sample counter
                                    # lattice so the retried stretch draws a different
                                    # sample path; "none": pre-round-6 behavior (no
                                    # probe, NaNs train on silently)
    rollback_history: int = 2       # nonfinite_policy="rollback": how many good param
                                    # snapshots the in-memory ring holds. A rollback
                                    # pops the newest; repeated blowups before the next
                                    # finite probe step back through the older entries.
                                    # Each snapshot is a device-resident copy of the
                                    # padded [V, D] syn0+syn1 pair — budget HBM
                                    # accordingly
    max_rollbacks: int = 8          # nonfinite_policy="rollback": give up (raise) after
                                    # this many rollbacks in one fit() — a run that
                                    # keeps diverging needs a config change
                                    # (lr/pool/subsample), not infinite retries

    # --- run telemetry (docs/observability.md; no reference analog — its only
    # observability is the every-10k-words driver log line, mllib:411-412) ---
    telemetry_path: str = ""        # non-empty: write the schema-versioned JSONL
                                    # run log here (obs/sink.py — rotating file,
                                    # NEVER stdout: the driver tools' one-JSON-
                                    # line contract, graftlint R7, must survive a
                                    # telemetry-on trainer inside any of them).
                                    # Carries run_start/run_end, extended
                                    # heartbeats (norm channels, per-phase host
                                    # timings), and watchdog records; the host
                                    # trace spans export beside it as
                                    # <telemetry_path>.trace.json (Chrome trace
                                    # format — Perfetto-loadable). Empty
                                    # (default) = telemetry off, zero cost
    telemetry_rotate_bytes: int = 64 << 20  # rotate the run log past this size
                                    # (<path>.1..<path>.3 kept) so long-run
                                    # telemetry is disk-bounded
    heartbeat_ring: int = 512       # in-memory Trainer.heartbeats capacity (a
                                    # bounded ring — pre-round-11 this list grew
                                    # one record per heartbeat forever, ~weeks-
                                    # long runs leaked). The full history
                                    # persists in the telemetry sink file
    norm_watch: str = "off"         # finite-blowup watchdog over the fused
                                    # health probe's row-norm channels
                                    # (obs/probe.py, heartbeat cadence — the
                                    # guardrail for the measured 1.6M-vocab
                                    # FINITE collapse where nonfinite_policy
                                    # never fires, EVAL.md round-5 / ROADMAP 2).
                                    # "off" (default): probe channels still
                                    # recorded when telemetry is on, nothing
                                    # fires. "warn": log + telemetry record per
                                    # firing probe, training continues.
                                    # "recover": the self-stabilizing ladder
                                    # (docs/robustness.md) — emit a telemetry
                                    # recovery record, roll back to the newest
                                    # snapshot-ring entry (the ring arms for
                                    # ANY consumer, not only nonfinite
                                    # rollback), re-seed the negative-sample
                                    # counter lattice, back the lr off by
                                    # recover_lr_backoff, engage max_row_norm
                                    # (at norm_watch_threshold) if it was off,
                                    # and continue — up to max_recoveries per
                                    # fit, then degrade to "halt". "halt":
                                    # raise NormBlowupError (fail-fast, the
                                    # nonfinite_policy="halt" contract)
    norm_watch_threshold: float = 100.0  # row-L2-norm boundary of the
                                    # frac_over channel. Provenance: the EVAL
                                    # harness's blown-row heuristic (rows with
                                    # |emb| > 100, tools/eval_quality.py) —
                                    # healthy trained rows sit at norm ~1-15
                                    # across every EVAL_RUNS config; collapsed
                                    # 1.6M-vocab rows measured orders of
                                    # magnitude past 100 (docs/observability.md)
    norm_watch_frac: float = 0.01   # watchdog fires when this fraction of a
                                    # matrix's rows exceed the threshold — the
                                    # collapse shows in a hot-row subset first
    norm_watch_max: float = 1000.0  # hard ceiling on any single row norm —
                                    # catches a lone runaway row the fraction
                                    # channel dilutes at large vocabularies
    # --- in-step stabilizers + watchdog auto-recovery (docs/robustness.md
    # escalation ladder; the mitigation half of the ROADMAP-2 finite-blowup
    # response — the knobs the watchdog diagnostic used to recommend by hand).
    # ALL off by default: the 0.0 defaults elide every stabilizer op from the
    # compiled step, so the default step is bit-identical to pre-stabilizer
    # releases (tested). Implemented on every XLA step path (per-pair, shared
    # pool, both CBOW formulations, both sharded lowerings); refused beside
    # use_pallas (the fused kernel owns its own update math).
    max_row_norm: float = 0.0       # > 0: per-TOUCHED-row L2 clamp applied on
                                    # the update path after each step's
                                    # scatter (touched rows only — NEVER a
                                    # dense [V, D] renorm pass; ops/sgns.py
                                    # stabilize_rows). The direct counter to
                                    # the measured finite blowup: healthy
                                    # trained rows sit at norm ~1-15 across
                                    # every EVAL_RUNS config, the round-5
                                    # collapse runs orders of magnitude past
                                    # 100 — a clamp anywhere in [15, 100]
                                    # bounds the channel without touching
                                    # healthy geometry. norm_watch="recover"
                                    # engages this at norm_watch_threshold
                                    # when it was off
    update_clip: float = 0.0        # > 0: per-row L2 ceiling on each pair's/
                                    # example's update contribution (the
                                    # d_in/d_pos SGNS rows, d_hidden/d_out
                                    # CBOW rows), applied before the scatter-
                                    # add. Pool-row deltas are deliberately
                                    # exempt — under shard_map each data
                                    # shard holds only a partial pool delta,
                                    # so clipping there would make the
                                    # lowerings drift; pool rows are bounded
                                    # by the n/P reweight + max_row_norm
                                    # (ops/sgns.py Stabilizers)
    row_l2: float = 0.0             # > 0: L2 weight decay on touched rows —
                                    # each touched row scales by
                                    # (1 − alpha·row_l2) once per step
                                    # regardless of in-batch multiplicity.
                                    # Decay pressure scales with how often a
                                    # row trains, exactly matching the hot-
                                    # row mechanism of the blowup channel
    recover_lr_backoff: float = 0.5  # norm_watch="recover": multiply the
                                    # effective learning rate by this factor
                                    # at each recovery (compounding across
                                    # recoveries; applied to the dispatched
                                    # alphas, so no step recompile). Lowering
                                    # lr is the third measured mitigation in
                                    # the watchdog diagnostic
    max_recoveries: int = 4         # norm_watch="recover": recovery budget
                                    # per fit(); exhaustion degrades to the
                                    # "halt" contract (NormBlowupError with
                                    # the full diagnostic) — a run that keeps
                                    # blowing through recoveries needs a
                                    # config change, not infinite retries
    profile_steps: int = 0          # with profile_dir set: stop the jax.profiler
                                    # trace once this many steps complete after
                                    # fit() starts (0 = trace the whole fit, the
                                    # pre-round-11 behavior). A bounded window
                                    # keeps pod traces loadable — whole-fit
                                    # traces at production step counts are
                                    # multi-GB
    status_port: int = 0            # > 0: serve a read-only live-inspection
                                    # HTTP endpoint on 127.0.0.1:<port> for
                                    # the duration of each fit
                                    # (obs/statusd.py): /status.json (the
                                    # gauge snapshot as JSON), /metrics
                                    # (Prometheus text format, glint_*
                                    # gauges), /healthz. 0 (default) = off
                                    # with ZERO cost — no thread is created
                                    # and no socket bound (tested). The
                                    # endpoint only READS trainer state; it
                                    # can never interleave device work into
                                    # the dispatch pipeline
    blackbox_ring: int = 256        # flight-recorder capacity (obs/
                                    # blackbox.py): how many per-dispatch
                                    # metadata records the in-memory ring
                                    # holds (recent heartbeats and watchdog/
                                    # recovery events keep a quarter of this
                                    # each). The ring dumps atomically to
                                    # <telemetry_path>.blackbox.json when a
                                    # fit dies (exception, NormBlowupError,
                                    # SIGTERM), so a remote death leaves a
                                    # diagnosis artifact instead of a
                                    # truncated JSONL. Only active when
                                    # telemetry_path is set (the dump path
                                    # derives from it)

    # --- preemption + training supervisor (docs/robustness.md §supervisor;
    # train/supervisor.py, tools/train_run.py). checkpoint_on_preempt /
    # preempt_deadline_s / peer_beacon_s are read by the trainer's SIGNAL
    # and round-bookkeeping paths only (host-side, after the dispatch is
    # staged); the supervisor_* knobs are read by the SUPERVISOR process,
    # never by the trainer — all dispatch-inert ---
    checkpoint_on_preempt: bool = False  # True: a SIGTERM during fit() no
                                    # longer kills the run on the spot —
                                    # the handler records a deadline and
                                    # the trainer finishes the in-flight
                                    # dispatch, drains the carry, runs the
                                    # nonfinite/norm guard, and writes an
                                    # EMERGENCY checkpoint through the
                                    # normal digest-verified atomic save
                                    # path, then exits resumable (run_end
                                    # status "preempted", rc = -SIGTERM).
                                    # Past the deadline (or if the guard
                                    # refuses the carry) it degrades to
                                    # the blackbox-only dump — never a
                                    # torn or unverified save. False
                                    # (default): dump-and-die, the
                                    # pre-supervisor behavior
    preempt_deadline_s: float = 30.0  # budget between the first SIGTERM
                                    # and the forced exit: the emergency
                                    # save only STARTS while inside it
                                    # (a TPU preemption sends SIGKILL
                                    # ~30s after the warning; k8s default
                                    # grace is 30s)
    peer_beacon_s: float = 0.0      # > 0 (multi-process sharded fits):
                                    # each process touches a liveness
                                    # beacon beside the checkpoint dir
                                    # this often and checks its peers'
                                    # before each allgather round. A peer
                                    # stale past 6x this aborts the fit
                                    # cleanly (PeerDeathError) instead of
                                    # hanging in the collective rendezvous
                                    # forever; a process WEDGED inside the
                                    # collective hard-exits (rc 43) from
                                    # the beacon watcher thread at 12x.
                                    # 0 (default) = off, zero cost
    supervisor_stall_s: float = 300.0  # hang watchdog: no step advance
                                    # observed (telemetry tail /
                                    # status.json) within this many
                                    # seconds => the supervisor captures a
                                    # diagnostic (SIGTERM = blackbox dump,
                                    # then SIGKILL), counts a failure, and
                                    # resumes from the last valid
                                    # checkpoint
    supervisor_max_restarts: int = 8  # total restart budget per
                                    # TrainingSupervisor.run(); exhaustion
                                    # halts with a machine-readable
                                    # verdict — never an unbounded
                                    # restart loop
    supervisor_loop_window: int = 3  # crash-loop quarantine rule: this
                                    # many CONSECUTIVE failures with the
                                    # same signature (exception/signal
                                    # type + same step, +- one dispatch
                                    # chunk) classify a deterministic
                                    # crash-loop — escalate per the
                                    # documented ladder (engage
                                    # stabilizers / lr backoff, then halt
                                    # quarantined) instead of restarting
                                    # forever

    # --- serving tier (docs/serving.md; serve/ — read by the SERVING
    # process, never by the trainer: dispatch-inert by construction. The
    # knobs travel with the checkpoint like every other field, so a
    # deployment's serving geometry is pinned beside the model it serves;
    # EmbeddingService constructor arguments override per process) ---
    serve_max_batch: int = 64       # micro-batcher coalescing cap: concurrent
                                    # queries batch up to this many per device
                                    # dispatch (the 13-16 ms batched path vs
                                    # 230-375 ms per-query, PERF.md §6)
    serve_max_delay_ms: float = 2.0  # batching deadline: a batch dispatches at
                                    # most this long after its FIRST request
                                    # arrived (bounds added latency; 0 =
                                    # dispatch immediately, batch only what is
                                    # already queued)
    serve_queue_depth: int = 256    # bounded admission queue; a full queue
                                    # refuses new requests FAST
                                    # (ServerOverloaded, the 429 analog) —
                                    # never unbounded buffering into latency
                                    # collapse
    serve_ann_centroids: int = 0    # IVF coarse cells. 0 = AUTO ~4·sqrt(V)
                                    # (serve/ann.py auto_centroids: clamped so
                                    # cells average >= 8 rows, ceiling 4096)
    serve_ann_nprobe: int = 0       # cells probed per query. 0 = AUTO
                                    # ~centroids/12 (~8% of the vocabulary
                                    # scanned — the measured recall >= 0.95
                                    # operating point on clustered embedding
                                    # geometry, tools/servebench.py)
    serve_ann_quant: str = "f32"    # index storage arm (docs/serving.md §6):
                                    # "f32" one normalized float copy (exact
                                    # scores), "int8" per-row-scaled int8
                                    # codes (~4x smaller, bandwidth-bound
                                    # scan speedup), "pq" product-quantized
                                    # codes + ADC scan (~16-32x smaller,
                                    # exact re-rank restores recall)
    serve_ann_pq_m: int = 0         # PQ subspaces (x256 centroids each).
                                    # 0 = AUTO ~D/8 (serve/quant.py
                                    # auto_pq_m; pq arm only)
    serve_ann_rerank: int = 0       # exact-re-rank shortlist for quantized
                                    # arms: top-N by quantized score re-
                                    # scored against lazily fetched float
                                    # rows. 0 = AUTO (pq: max(100, 40k),
                                    # int8: max(32, 4k)), -1 = off
                                    # (forfeits the recall floor)
    serve_ann_recall_floor: float = -1.0  # measured-recall@10 refusal gate
                                    # per build: below floor raises
                                    # RecallFloorError instead of serving a
                                    # silently degraded index. -1 = AUTO
                                    # (documented per-arm floors: int8 0.99,
                                    # pq 0.95, f32 ungated), 0 = disabled
    serve_ann_max_densify_bytes: int = 8 << 30  # refuse an in-memory index
                                    # build whose dense normalized [V, D]
                                    # f32 copy exceeds this many bytes —
                                    # the error names the shard-native
                                    # build as the migration. 0 = unlimited
    serve_reload_poll_s: float = 0.5  # hot-reload watcher poll cadence over
                                    # the checkpoint publish signal
                                    # (metadata.json identity; serve/reload.py)
    # --- serving fleet (docs/serving.md §5; serve/fleet.py — read by the
    # fleet ROUTER process (FleetRouter / tools/fleet_run.py), never by the
    # trainer or a single replica: dispatch-inert by construction, same
    # contract as the serve_* tier above. The knobs travel with the
    # checkpoint so a deployment's fleet policy is pinned beside the model
    # it serves; FleetRouter constructor arguments override per process. ---
    serve_fleet_replicas: int = 3   # replica processes behind the router;
                                    # the rolling-reload capacity floor is
                                    # N-1, so N >= 2 is where the fleet
                                    # starts buying anything (N = 1 is the
                                    # single-service deployment with router
                                    # overhead — allowed, benched as the
                                    # baseline arm in servebench --fleet)
    serve_fleet_probe_s: float = 0.5  # health-probe cadence: the router's
                                    # prober sends each replica a cheap
                                    # stats op this often (liveness +
                                    # publish-generation staleness; an
                                    # OPEN breaker's half-open trial rides
                                    # the same tick, so recovery costs
                                    # zero client queries)
    serve_fleet_breaker_failures: int = 3  # consecutive failures/timeouts
                                    # that open a replica's circuit
                                    # breaker (closed -> open); client
                                    # traffic routes only to CLOSED
                                    # breakers
    serve_fleet_breaker_reset_s: float = 2.0  # open-breaker cooldown before
                                    # the half-open trial probe; trial
                                    # success closes the breaker, failure
                                    # reopens it and re-arms the cooldown
    serve_fleet_hedge_ms: float = -1.0  # tail-latency hedging delay: a
                                    # single query unanswered past this
                                    # many ms goes to a SECOND replica,
                                    # first response wins (the loser's
                                    # reply is discarded). -1 (default) =
                                    # AUTO: derive from the router's own
                                    # measured p99 (re-derived every 64
                                    # samples, floored at 2 ms — hedges
                                    # stay rare by construction). 0 = off.
                                    # Cheap because the CIKM'16 discipline
                                    # keeps per-request payloads tiny
                                    # (PAPER.md §0)
    serve_fleet_retry_deadline_s: float = 10.0  # per-request retry budget:
                                    # failed attempts retry on OTHER
                                    # replicas (decorrelated-jitter
                                    # backoff once all were tried) until
                                    # this deadline, then the request
                                    # fails with NoHealthyReplicas.
                                    # ServerOverloaded replies don't burn
                                    # backoff — they mark the replica
                                    # saturated and move on immediately

    # --- continual training (docs/continual.md; continual/ — read by the
    # continual DRIVER (ContinualRunner / tools/continual_run.py), never by
    # trainer construction or dispatch: dispatch-inert by construction, like
    # the serve_* tier. The knobs travel with the checkpoint so a
    # deployment's increment policy is pinned beside the model it grows.) ---
    continual_min_new_words: int = 1  # vocab-extension trigger: grow
                                    # syn0/syn1 only when at least this many
                                    # NEW words pass min_count in the corpus
                                    # tail; below it the increment trains
                                    # under the existing vocabulary (counts
                                    # still merge, alias table still rebuilt)
    continual_lr_rewarm: float = 1.0  # learning-rate re-warm per increment:
                                    # each incremental fit starts at
                                    # learning_rate * this and decays over
                                    # the increment's own word clock (the
                                    # reference decays alpha over ONE corpus
                                    # pass; a continual deployment needs the
                                    # clock re-armed per increment). Applied
                                    # through the trainer's dispatch-time lr
                                    # scale (the recovery ladder's staging
                                    # point), NEVER by rewriting
                                    # learning_rate — the config persists
                                    # into every publish, and a rewritten lr
                                    # would compound to rewarm^k after k
                                    # increments
    continual_iterations: int = 1   # epochs per incremental fit over the
                                    # new corpus tail (+ replay segments)
    continual_replay_segments: int = 0  # how many of the most recent
                                    # already-consumed segments to re-train
                                    # alongside each new tail — the
                                    # forgetting mitigation (the
                                    # eval_quality --continual-ab gate
                                    # measures what 0 costs); replayed
                                    # segments reuse their cached encodes
    continual_poll_s: float = 2.0   # driver poll cadence over the
                                    # append-only corpus directory between
                                    # increments (continual/loop.py)

    def __post_init__(self) -> None:
        if self.embedding_partition not in ("rows", "cols"):
            raise ValueError(
                f"embedding_partition must be 'rows' or 'cols', "
                f"got {self.embedding_partition!r}")
        if self.vector_size <= 0:
            raise ValueError(f"vector_size must be positive but got {self.vector_size}")
        if self.learning_rate <= 0:
            raise ValueError(f"learning_rate must be positive but got {self.learning_rate}")
        if self.num_partitions <= 0:
            raise ValueError(f"num_partitions must be positive but got {self.num_partitions}")
        if self.num_iterations < 0:
            raise ValueError(
                f"num_iterations must be nonnegative but got {self.num_iterations}")
        if self.min_count < 0:
            raise ValueError(f"min_count must be nonnegative but got {self.min_count}")
        if self.max_sentence_length <= 0:
            raise ValueError(
                f"max_sentence_length must be positive but got {self.max_sentence_length}")
        if self.window <= 0:
            raise ValueError(f"window must be positive but got {self.window}")
        if self.window > 127:
            # CBOW context counts ship as uint8 (2*window slots) and the reference
            # caps useful windows far below this anyway (default 5, mllib:251)
            raise ValueError(f"window must be <= 127 but got {self.window}")
        if self.batch_size <= 0:
            raise ValueError(f"batch_size must be positive but got {self.batch_size}")
        if self.negatives <= 0:
            raise ValueError(f"negatives must be positive but got {self.negatives}")
        # remembered so the Trainer may auto-lower an AUTO ratio into the measured
        # stability region (explicit values are refused instead, see trainer.py)
        self._auto_subsample = self.subsample_ratio == -1.0
        if self._auto_subsample:
            self.subsample_ratio = 1e-3
        if not (0 <= self.subsample_ratio <= 1):
            raise ValueError(
                f"subsample_ratio must be in [0, 1] (or -1 for auto) "
                f"but got {self.subsample_ratio}")
        if self.unigram_table_size <= 0:
            raise ValueError(
                f"unigram_table_size must be positive but got {self.unigram_table_size}")
        if self.pairs_per_batch <= 0:
            raise ValueError(
                f"pairs_per_batch must be positive but got {self.pairs_per_batch}")
        if self.sigmoid_mode not in ("exact", "clipped"):
            raise ValueError(
                f"sigmoid_mode must be 'exact' or 'clipped' but got {self.sigmoid_mode!r}")
        if self.num_model_shards <= 0:
            raise ValueError(
                f"num_model_shards must be positive but got {self.num_model_shards}")
        # --- CBOW update-path selection matrix (trainer._build_step has the
        # dispatch-side twin of this table). Every unsupported combination is
        # an ERROR here, not a silent fallback:
        #   banded  × duplicate_scaling → refuse (mean semantics are
        #       per-materialized-context-set; only the scatter path has them)
        #   banded  × cbow=False        → refuse (knob is meaningless)
        #   banded  × negative_pool=0   → refuse (banded is built on the
        #       shared-pool estimator; per-example pools would re-create the
        #       [B, n, D] row traffic the path exists to remove)
        #   banded  × use_pallas        → refuse (pallas step is SGNS-only)
        #   banded  × tokens_per_step   → refuse (banded derives its block size
        #       from pairs_per_batch + window; the knob is device_pairgen's)
        #   banded  × window=1          → refuse (legacy window b=nextInt(1)=0
        #       yields no contexts at all — same rule as device_pairgen)
        #   scatter × duplicate_scaling → per-example negatives
        #       (explicit negative_pool>0 alongside it is refused below;
        #       an AUTO pool resolves to 0)
        if self.cbow_update not in ("scatter", "banded"):
            raise ValueError(
                f"cbow_update must be 'scatter' or 'banded' "
                f"but got {self.cbow_update!r}")
        if self.cbow_update == "banded":
            if not self.cbow:
                raise ValueError(
                    "cbow_update='banded' requires cbow=True — the knob "
                    "selects the CBOW step formulation")
            if self.duplicate_scaling:
                raise ValueError(
                    "cbow_update='banded' does not support "
                    "duplicate_scaling=True: mean-update semantics are only "
                    "implemented on the scatter path (its per-context-set "
                    "occurrence counts have no banded form) — use "
                    "cbow_update='scatter'")
            if self.use_pallas:
                raise ValueError(
                    "cbow_update='banded' is an XLA path; use_pallas=True "
                    "(the fused SGNS kernel) does not apply to CBOW")
            if self.negative_pool == 0:
                raise ValueError(
                    "cbow_update='banded' requires the shared-pool estimator "
                    "(negative_pool > 0, or -1 for auto); per-example "
                    "negatives (negative_pool=0) are scatter-path only")
            if self.tokens_per_step:
                raise ValueError(
                    "cbow_update='banded' derives its token-block size from "
                    "pairs_per_batch + window; tokens_per_step is the "
                    "device_pairgen knob — leave it 0")
            if self.window < 2:
                raise ValueError(
                    "cbow_update='banded' with window=1 emits no contexts at "
                    "all under the reference's legacy asymmetric window "
                    "(b = nextInt(1) = 0 always) — use window >= 2")
        # --- pallas selection matrix (graftlint R8 refusal-matrix parity:
        # trainer._build_step carries the dispatch-side twin of these two
        # refusals; every combination refused there must be refused at
        # construction too, so no checkpoint can ever store knobs the
        # dispatch will later reject). Multi-device×pallas stays dispatch-
        # only — it depends on the mesh plan, which config cannot see.
        if self.use_pallas:
            if self.cbow:
                raise ValueError(
                    "use_pallas=True is not implemented for CBOW — the fused "
                    "kernel is SGNS-only; use the XLA CBOW paths "
                    "(cbow_update='scatter'/'banded')")
            if self.duplicate_scaling:
                raise ValueError(
                    "duplicate_scaling is not implemented for use_pallas=True "
                    "— the fused kernel applies sum semantics only; use the "
                    "XLA path or bound the row loads via "
                    "negative_pool/subsample_ratio instead")
            if self.max_row_norm or self.update_clip or self.row_l2:
                raise ValueError(
                    "the in-step stabilizers (max_row_norm/update_clip/"
                    "row_l2) are not implemented for use_pallas=True — the "
                    "fused kernel owns its own update math; use the XLA "
                    "paths, which compile the stabilizers into every "
                    "lowering (ops/sgns.py)")
            if self.norm_watch == "recover":
                raise ValueError(
                    "norm_watch='recover' auto-engages max_row_norm, which "
                    "the fused pallas kernel does not implement — use "
                    "norm_watch='warn'/'halt' with use_pallas=True, or the "
                    "XLA paths for auto-recovery")
        if (self.cbow and self.duplicate_scaling and self.negative_pool > 0):
            raise ValueError(
                "CBOW with duplicate_scaling=True implements mean semantics "
                "per-example only; an explicit negative_pool > 0 would be "
                "silently ignored — set negative_pool=0 (or -1 for auto, "
                "which resolves to 0 here)")
        # remembered so replace() re-derives the pool when the batch geometry
        # changes (a resolved auto pool must not stick to a new pairs_per_batch)
        self._auto_pool = self.negative_pool == -1
        if self.negative_pool == -1:
            if self.cbow and self.duplicate_scaling:
                # mean semantics exist only on the per-example scatter path
                self.negative_pool = 0
            elif (self.pairs_per_batch < 4096 and not self.use_pallas
                    and self.cbow_update != "banded"
                    and self.step_lowering != "shard_map"):
                # Small batches take the per-pair exact path (the reference's G3
                # semantics): the shared pool's matmul amortization buys nothing at
                # this scale, and shared negatives measurably cost quality on small
                # corpora (the bf16 toy-corpus gate fails at B=256/P=128 but passes
                # per-pair — tests/test_integration_toy.py). The pallas step needs a
                # pool, so use_pallas keeps the load-rule resolution below. NB the
                # per-pair path always runs its logit chain in f32 (trainer.py);
                # logits_dtype applies to the shared-pool paths.
                self.negative_pool = 0
            else:
                # AUTO: scale the shared pool with the batch so the per-row load
                # stays inside the measured 60M-word stability boundary
                # (load <= 600, EVAL.md), rounded up to the 128-lane MXU tile
                p_min = -(-self.pairs_per_batch * self.negatives // 600)
                self.negative_pool = max(128, 128 * (-(-p_min // 128)))
        if self.negative_pool < 0:
            raise ValueError(
                f"negative_pool must be nonnegative (or -1 for auto) "
                f"but got {self.negative_pool}")
        # --- step-restructuring selection matrix (ISSUE 14 / PERF.md §11;
        # trainer._build_step carries the dispatch-side twins — graftlint R8
        # refusal parity, graftcheck executes the empirical sweep). Every
        # unsupported combination is an ERROR here, never a silent fallback:
        #   fused_logits × use_pallas          → refuse (pallas owns the step)
        #   fused_logits × cbow                → refuse (SGNS chains only; the
        #       CBOW chain keeps the classic form until its own EVAL evidence)
        #   fused_logits × duplicate_scaling   → refuse (mean semantics read
        #       the per-pair coefficient arrays the fusion eliminates)
        #   bf16_chain   × use_pallas/cbow     → refuse (as above)
        #   bf16_chain   × compute f32         → refuse (no chain to narrow)
        #   bf16_chain   × pool>0 + logits f32 → refuse (the [B, pool] chain
        #       would silently stay f32 — exactly the half-applied state the
        #       _build_step logits warning exists to avoid; per-pair pool=0
        #       has no logits_dtype surface and is exempt)
        #   hot_rows     × use_pallas/cbow/duplicate_scaling → refuse
        #   hot_rows     × shard_map/cols      → refuse (the hot slab is the
        #       GLOBAL index prefix [0, K); under the rows layout it lives
        #       entirely on model shard 0 — owner-local accumulation would
        #       serialize every hot update onto one shard. Documented initial
        #       refusal, docs/sharding.md)
        #   hot_rows     × multi-shard mesh    → refuse (single-chip path
        #       initially; the trainer also refuses a multi-device plan)
        #   hot_rows     × stabilizers/recover → refuse (the post-scatter
        #       clamp would measure rows missing their pending slab deltas)
        #   hot_flush_every (explicit)         → must divide steps_per_dispatch
        if self.fused_logits:
            if self.use_pallas:
                raise ValueError(
                    "fused_logits=True is an XLA-chain restructuring; "
                    "use_pallas=True owns the whole step — drop one")
            if self.cbow:
                raise ValueError(
                    "fused_logits=True is implemented for the SGNS logit "
                    "chains only (per-pair and shared-pool); CBOW keeps the "
                    "classic chain — set fused_logits=False")
            if self.duplicate_scaling:
                raise ValueError(
                    "fused_logits=True does not support duplicate_scaling="
                    "True: mean-update semantics read the per-pair "
                    "coefficient arrays the fused chain eliminates — use "
                    "the classic chain")
        if self.bf16_chain:
            if self.use_pallas:
                raise ValueError(
                    "bf16_chain=True is an XLA-chain restructuring; "
                    "use_pallas=True owns the whole step — drop one")
            if self.cbow:
                raise ValueError(
                    "bf16_chain=True is implemented for the SGNS paths "
                    "only; CBOW keeps the classic chain — set "
                    "bf16_chain=False")
            if self.compute_dtype != "bfloat16":
                raise ValueError(
                    "bf16_chain=True requires compute_dtype='bfloat16' — "
                    "with float32 compute there is no reduced-precision "
                    "chain to carry end-to-end")
            if self.negative_pool != 0 and self.logits_dtype != "bfloat16":
                raise ValueError(
                    "bf16_chain=True with a shared negative pool requires "
                    "logits_dtype='bfloat16': a float32 [B, pool] logit "
                    "chain would silently keep the dense traffic the knob "
                    "exists to remove")
        if self.hot_rows < 0:
            raise ValueError(
                f"hot_rows must be nonnegative (0 = off) "
                f"but got {self.hot_rows}")
        if self.hot_flush_every < 0:
            raise ValueError(
                f"hot_flush_every must be nonnegative (0 = auto: once per "
                f"dispatch chunk) but got {self.hot_flush_every}")
        if self.hot_rows:
            if self.use_pallas:
                raise ValueError(
                    "hot_rows is not implemented for use_pallas=True — the "
                    "fused kernel owns its own update math; use the XLA "
                    "SGNS paths")
            if self.cbow:
                raise ValueError(
                    "hot_rows is implemented for the SGNS paths only; CBOW "
                    "keeps the classic per-step scatters — set hot_rows=0")
            if self.duplicate_scaling:
                raise ValueError(
                    "hot_rows does not support duplicate_scaling=True: "
                    "mean-update scaling and cross-step slab accumulation "
                    "compose into semantics nothing has EVAL evidence for — "
                    "use one or the other")
            if self.step_lowering == "shard_map":
                raise ValueError(
                    "hot_rows has no shard_map form: the hot slab is the "
                    "global index prefix [0, K), which under the rows "
                    "layout lives entirely on model shard 0 — owner-local "
                    "accumulation would serialize every hot update onto one "
                    "shard (documented refusal, docs/sharding.md); use "
                    "step_lowering='gspmd' on a single device")
            if self.embedding_partition == "cols":
                raise ValueError(
                    "hot_rows requires the rows layout (the slab is a "
                    "whole-row prefix block); embedding_partition='cols' "
                    "owns columns — use 'rows'")
            if self.num_model_shards > 1 or self.num_data_shards > 1:
                raise ValueError(
                    "hot_rows is the single-chip step restructuring "
                    "(PERF.md §11); multi-shard meshes keep the classic "
                    "scatters — set hot_rows=0 or use a 1x1 mesh")
            if self.mesh_shape is not None and tuple(self.mesh_shape) != (1, 1):
                raise ValueError(
                    "hot_rows is the single-chip step restructuring "
                    f"(PERF.md §11); mesh_shape={self.mesh_shape} keeps the "
                    "classic scatters — set hot_rows=0 or use (1, 1)")
            if self.max_row_norm or self.update_clip or self.row_l2:
                raise ValueError(
                    "hot_rows is incompatible with the in-step stabilizers "
                    "(max_row_norm/update_clip/row_l2): the post-scatter "
                    "touched-row pass would measure hot rows missing their "
                    "pending slab deltas — clamping a partial row is the "
                    "silent-distortion class the stabilizers exist to "
                    "prevent; use one or the other")
            if self.norm_watch == "recover":
                raise ValueError(
                    "hot_rows is incompatible with norm_watch='recover' "
                    "(the recovery ladder auto-engages max_row_norm, which "
                    "has no hot-row form); use norm_watch='warn'/'halt' or "
                    "hot_rows=0")
            if self.hot_flush_every and (
                    self.hot_flush_every > self.steps_per_dispatch
                    or self.steps_per_dispatch % self.hot_flush_every):
                raise ValueError(
                    f"hot_flush_every={self.hot_flush_every} must divide "
                    f"steps_per_dispatch={self.steps_per_dispatch}: the hot "
                    f"slab lives in the dispatch chunk's scan carry and "
                    f"every chunk flushes at its end, so the cadence cannot "
                    f"exceed or straddle the chunk (0 = auto: once per "
                    f"chunk)")
        # --- step_lowering selection matrix (trainer._build_step dispatches on
        # it; every unsupported combination is an ERROR here, never a silent
        # fallback — same discipline as the CBOW matrix above):
        #   shard_map × cbow              → refuse (the explicit schedule is the
        #       shared-pool SGNS step only; CBOW keeps GSPMD)
        #   shard_map × use_pallas        → refuse (pallas owns the whole step)
        #   shard_map × duplicate_scaling → refuse (mean semantics need global
        #       in-batch occurrence counts — a [V]-sized cross-shard psum the
        #       schedule exists to avoid)
        #   shard_map × negative_pool=0   → refuse (per-pair negatives re-create
        #       the [B, n, D] row traffic; the schedule assembles ONE pool)
        #   shard_map × cols              → refuse (owner-local row scatters are
        #       the rows layout's property; cols owns columns, not rows)
        if self.step_lowering not in ("gspmd", "shard_map"):
            raise ValueError(
                f"step_lowering must be 'gspmd' or 'shard_map' "
                f"but got {self.step_lowering!r}")
        if self.step_lowering == "shard_map":
            if self.cbow:
                raise ValueError(
                    "step_lowering='shard_map' is implemented for the "
                    "shared-pool skip-gram step only; CBOW runs under GSPMD "
                    "(step_lowering='gspmd')")
            if self.use_pallas:
                raise ValueError(
                    "step_lowering='shard_map' and use_pallas=True both claim "
                    "the step lowering; the pallas kernel is single-device "
                    "only — drop one")
            if self.duplicate_scaling:
                raise ValueError(
                    "step_lowering='shard_map' does not support "
                    "duplicate_scaling=True: mean-update semantics need global "
                    "in-batch occurrence counts, a [V]-sized cross-shard psum "
                    "the explicit schedule exists to avoid — use 'gspmd'")
            if self.negative_pool == 0:
                raise ValueError(
                    "step_lowering='shard_map' requires the shared-pool "
                    "estimator (negative_pool > 0, or -1 for auto at "
                    "pairs_per_batch >= 4096); per-pair negatives "
                    "(negative_pool=0) are GSPMD-path only")
            if self.embedding_partition != "rows":
                raise ValueError(
                    "step_lowering='shard_map' is the rows-layout schedule "
                    "(owner-local row scatters); embedding_partition="
                    f"{self.embedding_partition!r} keeps GSPMD")
        # --- sync_every (local-SGD) selection matrix (docs/sharding.md
        # §Local-SGD; trainer._build_step keeps the dispatch-side twin —
        # graftlint R8 refusal parity):
        #   sync_every>1 × gspmd lowering  → refuse (the owner-local window is
        #       the shard_map schedule's property; GSPMD has no owner-local
        #       k-step form — and with it no CBOW either, since CBOW keeps
        #       GSPMD)
        #   sync_every>1 × device_pairgen  → refuse (the windowed chunk is the
        #       host packed-pair feed; the device generator's token blocks
        #       would need their own window plumbing)
        #   sync_every ∤ steps_per_dispatch → refuse (the window lives inside
        #       the dispatch chunk's scan; a merge must land on every dispatch
        #       boundary so recovery never resurrects an unmerged shard)
        if self.sync_every <= 0:
            raise ValueError(
                f"sync_every must be positive (1 = synchronous) "
                f"but got {self.sync_every}")
        if self.sync_every > 1:
            if self.step_lowering != "shard_map":
                raise ValueError(
                    f"sync_every={self.sync_every} (local-SGD) requires "
                    f"step_lowering='shard_map': the k owner-local steps "
                    f"reuse the explicit schedule's owner-local gather/"
                    f"scatter machinery, which has no GSPMD form (and no "
                    f"CBOW form — CBOW runs under GSPMD); got "
                    f"step_lowering={self.step_lowering!r}")
            if self.device_pairgen:
                raise ValueError(
                    f"sync_every={self.sync_every} (local-SGD) supports the "
                    f"host packed-pair feed only; device_pairgen's token-"
                    f"block chunks have no windowed form")
            if self.steps_per_dispatch % self.sync_every:
                raise ValueError(
                    f"sync_every={self.sync_every} must divide "
                    f"steps_per_dispatch={self.steps_per_dispatch}: the "
                    f"local-SGD window lives inside the dispatch chunk's "
                    f"scan and every chunk ends merged, so the merge cadence "
                    f"cannot exceed or straddle the chunk (snapshot-ring/"
                    f"rollback/preemption saves land on merge boundaries "
                    f"only)")
        # --- device_pairgen selection matrix (graftcheck first-run findings,
        # tools/graftcheck/ — these four refusals lived only in
        # Trainer.__init__, so a config could be constructed/serialized that
        # every Trainer would later reject; same parity discipline as the
        # CBOW/pallas/step_lowering matrices above):
        #   device_pairgen × cbow          → refuse (CBOW batches are grouped
        #       windows the device generator does not produce)
        #   device_pairgen × use_pallas    → refuse (pallas owns the step)
        #   device_pairgen × window=1      → refuse (legacy asymmetric window
        #       b = nextInt(1) = 0 emits no pairs at all)
        #   device_pairgen × explicit tokens_per_step × window past the
        #       2^24 exact-f32 prefix-sum bound → refuse (ops/pairgen
        #       _cumsum_i32 exactness; an AUTO tokens_per_step=0 is sized by
        #       the Trainer, which re-checks the derived value at dispatch)
        if self.device_pairgen:
            if self.cbow:
                raise ValueError(
                    "device_pairgen is skip-gram only (CBOW batches are "
                    "grouped windows the device generator does not produce)")
            if self.use_pallas:
                raise ValueError(
                    "device_pairgen is not supported with use_pallas — the "
                    "fused kernel owns the whole step and consumes host "
                    "pairs; drop one")
            if self.window == 1:
                raise ValueError(
                    "device_pairgen with window=1 emits no pairs at all "
                    "under the reference's legacy asymmetric window "
                    "(b = nextInt(1) = 0 always, and the right bound is "
                    "exclusive) — use window >= 2")
            if (self.tokens_per_step > 0
                    and self.tokens_per_step * (2 * self.window - 1) >= 1 << 24):
                raise ValueError(
                    f"tokens_per_step={self.tokens_per_step} with window="
                    f"{self.window} overflows the device generator's "
                    f"exact-f32 prefix-sum bound (T * (2*window - 1) must "
                    f"stay below 2^24); lower tokens_per_step or split the "
                    f"batch")
        # cols × sharded_checkpoint: row-shards checkpoints need each process
        # to own whole ROWS — the cols layout owns columns (design rationale:
        # PERF.md §7). Trainer.__init__ keeps the runtime twin (cols ×
        # multi-process), which depends on jax.process_count().
        if self.embedding_partition == "cols" and self.sharded_checkpoint:
            raise ValueError(
                "embedding_partition='cols' does not support "
                "sharded_checkpoint=True: row-shards checkpoints need each "
                "process to own whole rows (design rationale: PERF.md §7); "
                "use 'rows'")
        if self.num_data_shards <= 0:
            raise ValueError(
                f"num_data_shards must be positive but got {self.num_data_shards}")
        # dtype strings validated HERE, not first at jnp.dtype() inside
        # _build_step: a typo'd dtype used to construct (and serialize)
        # cleanly and then crash dispatch with a TypeError — the exact
        # construction/dispatch gap class graftcheck's probe executes for
        if self.param_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"param_dtype must be 'float32' or 'bfloat16' "
                f"but got {self.param_dtype!r}")
        if self.compute_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"compute_dtype must be 'float32' or 'bfloat16' "
                f"but got {self.compute_dtype!r}")
        if self.logits_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"logits_dtype must be 'float32' or 'bfloat16' "
                f"but got {self.logits_dtype!r}")
        # dispatch-geometry range checks (graftcheck registry audit): these
        # three used to be unvalidated — steps_per_dispatch=0 or
        # heartbeat_every_steps=0 constructed cleanly and died at fit() with
        # a ZeroDivisionError, past every refusal surface
        if self.steps_per_dispatch <= 0:
            raise ValueError(
                f"steps_per_dispatch must be positive "
                f"but got {self.steps_per_dispatch}")
        if self.heartbeat_every_steps <= 0:
            raise ValueError(
                f"heartbeat_every_steps must be positive "
                f"but got {self.heartbeat_every_steps}")
        if self.prefetch_chunks < 0:
            raise ValueError(
                f"prefetch_chunks must be nonnegative (0 = synchronous) "
                f"but got {self.prefetch_chunks}")
        if self.tokens_per_step < 0:
            raise ValueError(
                f"tokens_per_step must be nonnegative but got {self.tokens_per_step}")
        if self.producer_workers < 1:
            raise ValueError(
                f"producer_workers must be >= 1 (1 = serial producer) "
                f"but got {self.producer_workers}")
        if self.io_workers < 1:
            raise ValueError(
                f"io_workers must be >= 1 (1 = serial I/O) "
                f"but got {self.io_workers}")
        if self.nonfinite_policy not in ("halt", "rollback", "none"):
            raise ValueError(
                f"nonfinite_policy must be 'halt', 'rollback', or 'none' "
                f"but got {self.nonfinite_policy!r}")
        if self.rollback_history <= 0:
            raise ValueError(
                f"rollback_history must be positive but got {self.rollback_history}")
        if self.max_rollbacks < 0:
            raise ValueError(
                f"max_rollbacks must be nonnegative but got {self.max_rollbacks}")
        if self.norm_watch not in ("off", "warn", "recover", "halt"):
            raise ValueError(
                f"norm_watch must be 'off', 'warn', 'recover', or 'halt' "
                f"but got {self.norm_watch!r}")
        if self.max_row_norm < 0:
            raise ValueError(
                f"max_row_norm must be nonnegative (0 = off) "
                f"but got {self.max_row_norm}")
        if self.update_clip < 0:
            raise ValueError(
                f"update_clip must be nonnegative (0 = off) "
                f"but got {self.update_clip}")
        if not (0 <= self.row_l2 < 1):
            # (1 − alpha·row_l2) must stay a contraction for any alpha <= 1;
            # realistic decay sits orders of magnitude below 1 anyway
            raise ValueError(
                f"row_l2 must be in [0, 1) (0 = off) but got {self.row_l2}")
        if not (0 < self.recover_lr_backoff <= 1):
            raise ValueError(
                f"recover_lr_backoff must be in (0, 1] "
                f"but got {self.recover_lr_backoff}")
        if self.max_recoveries < 0:
            raise ValueError(
                f"max_recoveries must be nonnegative "
                f"but got {self.max_recoveries}")
        if self.norm_watch_threshold <= 0:
            raise ValueError(
                f"norm_watch_threshold must be positive "
                f"but got {self.norm_watch_threshold}")
        if self.norm_watch_max <= 0:
            raise ValueError(
                f"norm_watch_max must be positive but got {self.norm_watch_max}")
        if not (0 < self.norm_watch_frac <= 1):
            raise ValueError(
                f"norm_watch_frac must be in (0, 1] but got {self.norm_watch_frac}")
        if self.heartbeat_ring <= 0:
            raise ValueError(
                f"heartbeat_ring must be positive but got {self.heartbeat_ring}")
        if self.telemetry_rotate_bytes <= 0:
            raise ValueError(
                f"telemetry_rotate_bytes must be positive "
                f"but got {self.telemetry_rotate_bytes}")
        if self.profile_steps < 0:
            raise ValueError(
                f"profile_steps must be nonnegative but got {self.profile_steps}")
        if not (0 <= self.status_port <= 65535):
            raise ValueError(
                f"status_port must be in [0, 65535] (0 = off) "
                f"but got {self.status_port}")
        if self.blackbox_ring <= 0:
            raise ValueError(
                f"blackbox_ring must be positive but got {self.blackbox_ring}")
        if self.preempt_deadline_s <= 0:
            raise ValueError(
                f"preempt_deadline_s must be positive "
                f"but got {self.preempt_deadline_s}")
        if self.peer_beacon_s < 0:
            raise ValueError(
                f"peer_beacon_s must be nonnegative (0 = off) "
                f"but got {self.peer_beacon_s}")
        if self.supervisor_stall_s <= 0:
            raise ValueError(
                f"supervisor_stall_s must be positive "
                f"but got {self.supervisor_stall_s}")
        if self.supervisor_max_restarts < 0:
            raise ValueError(
                f"supervisor_max_restarts must be nonnegative "
                f"but got {self.supervisor_max_restarts}")
        if self.supervisor_loop_window < 2:
            # 1 would classify every SECOND failure as a deterministic loop
            # (a single repeat proves nothing about determinism)
            raise ValueError(
                f"supervisor_loop_window must be >= 2 "
                f"but got {self.supervisor_loop_window}")
        if self.serve_max_batch <= 0:
            raise ValueError(
                f"serve_max_batch must be positive "
                f"but got {self.serve_max_batch}")
        if self.serve_max_delay_ms < 0:
            raise ValueError(
                f"serve_max_delay_ms must be nonnegative (0 = dispatch "
                f"immediately) but got {self.serve_max_delay_ms}")
        if self.serve_queue_depth <= 0:
            raise ValueError(
                f"serve_queue_depth must be positive "
                f"but got {self.serve_queue_depth}")
        if self.serve_ann_centroids < 0:
            raise ValueError(
                f"serve_ann_centroids must be nonnegative (0 = auto) "
                f"but got {self.serve_ann_centroids}")
        if self.serve_ann_nprobe < 0:
            raise ValueError(
                f"serve_ann_nprobe must be nonnegative (0 = auto) "
                f"but got {self.serve_ann_nprobe}")
        if self.serve_ann_quant not in ("f32", "int8", "pq"):
            raise ValueError(
                f"serve_ann_quant must be one of 'f32', 'int8', 'pq' "
                f"but got {self.serve_ann_quant!r}")
        if self.serve_ann_pq_m < 0:
            raise ValueError(
                f"serve_ann_pq_m must be nonnegative (0 = auto ~D/8) "
                f"but got {self.serve_ann_pq_m}")
        if self.serve_ann_rerank < -1:
            raise ValueError(
                f"serve_ann_rerank must be -1 (off), 0 (auto), or a "
                f"positive shortlist size but got {self.serve_ann_rerank}")
        if not (self.serve_ann_recall_floor == -1.0
                or 0.0 <= self.serve_ann_recall_floor <= 1.0):
            raise ValueError(
                f"serve_ann_recall_floor must be -1 (auto per-arm floor) "
                f"or in [0, 1] (0 = disabled) "
                f"but got {self.serve_ann_recall_floor}")
        if self.serve_ann_max_densify_bytes < 0:
            raise ValueError(
                f"serve_ann_max_densify_bytes must be nonnegative "
                f"(0 = unlimited) but got {self.serve_ann_max_densify_bytes}")
        if self.serve_reload_poll_s <= 0:
            raise ValueError(
                f"serve_reload_poll_s must be positive "
                f"but got {self.serve_reload_poll_s}")
        if self.serve_fleet_replicas <= 0:
            raise ValueError(
                f"serve_fleet_replicas must be positive "
                f"but got {self.serve_fleet_replicas}")
        if self.serve_fleet_probe_s <= 0:
            raise ValueError(
                f"serve_fleet_probe_s must be positive "
                f"but got {self.serve_fleet_probe_s}")
        if self.serve_fleet_breaker_failures <= 0:
            raise ValueError(
                f"serve_fleet_breaker_failures must be positive "
                f"but got {self.serve_fleet_breaker_failures}")
        if self.serve_fleet_breaker_reset_s <= 0:
            raise ValueError(
                f"serve_fleet_breaker_reset_s must be positive "
                f"but got {self.serve_fleet_breaker_reset_s}")
        if self.serve_fleet_hedge_ms < 0 and self.serve_fleet_hedge_ms != -1.0:
            raise ValueError(
                f"serve_fleet_hedge_ms must be -1 (auto: p99-derived), "
                f"0 (off), or a positive delay in ms "
                f"but got {self.serve_fleet_hedge_ms}")
        if self.serve_fleet_retry_deadline_s <= 0:
            raise ValueError(
                f"serve_fleet_retry_deadline_s must be positive "
                f"but got {self.serve_fleet_retry_deadline_s}")
        if self.continual_min_new_words <= 0:
            # 0 would make every increment a (pointless) zero-growth
            # extension pass; "never grow" is not a policy this knob
            # expresses (drop the driver instead)
            raise ValueError(
                f"continual_min_new_words must be positive "
                f"but got {self.continual_min_new_words}")
        if self.continual_lr_rewarm <= 0:
            raise ValueError(
                f"continual_lr_rewarm must be positive "
                f"but got {self.continual_lr_rewarm}")
        if self.continual_iterations <= 0:
            raise ValueError(
                f"continual_iterations must be positive "
                f"but got {self.continual_iterations}")
        if self.continual_replay_segments < 0:
            raise ValueError(
                f"continual_replay_segments must be nonnegative "
                f"but got {self.continual_replay_segments}")
        if self.continual_poll_s <= 0:
            raise ValueError(
                f"continual_poll_s must be positive "
                f"but got {self.continual_poll_s}")

    def replace(self, **kwargs) -> "Word2VecConfig":
        if (getattr(self, "_auto_pool", False)
                and "negative_pool" not in kwargs):
            # the pool was auto-derived — re-derive it on the new config
            # instead of freezing the resolved value. Pre-graftcheck this
            # re-derived only when the flipped knob changed the AUTO rule's
            # geometry/path inputs; any OTHER flip (seed, telemetry, ...)
            # froze the resolved pool, which then read as EXPLICIT on the
            # derived config — to_dict(auto_markers=True) stored it, and the
            # Trainer's vocab-scaled re-resolution (load <= 160 past 500k
            # vocab) silently skipped it. Re-resolution is deterministic in
            # the geometry/path knobs, so under an unchanged geometry the
            # value is unchanged too — only the AUTO-ness is (now correctly)
            # preserved. graftcheck property (c) holds replace() to exactly
            # this: equivalent to fresh construction from the auto-marker
            # dict with the flip applied.
            kwargs["negative_pool"] = -1
        if (getattr(self, "_auto_subsample", False)
                and "subsample_ratio" not in kwargs):
            # keep auto-ness: the Trainer's stability auto-lowering must still
            # apply to the derived config (a frozen 1e-3 would read as explicit)
            kwargs["subsample_ratio"] = -1.0
        return dataclasses.replace(self, **kwargs)

    def to_dict(self, auto_markers: bool = True) -> dict:
        d = dataclasses.asdict(self)
        if auto_markers and getattr(self, "_auto_subsample", False):
            # preserve AUTO-ness across serialization (symmetric with replace()):
            # a pre-resolution config shipped to a worker must auto-lower there,
            # not read as an explicitly chosen 1e-3 and be refused.
            # auto_markers=False (checkpoints) stores the RESOLVED value instead:
            # a trained model's metadata must pin the semantics it trained with,
            # and format-version-1 readers reject a -1.0 sentinel
            d["subsample_ratio"] = -1.0
        if auto_markers and getattr(self, "_auto_pool", False):
            # same rule for the pool: a round-tripped AUTO pool must stay AUTO
            # so the Trainer's vocab-scaled re-resolution (load <= 160 past
            # 500k vocab) still applies on the receiving side — a frozen
            # resolved value would read as explicit and skip the safety rule
            d["negative_pool"] = -1
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Word2VecConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        clean = {k: v for k, v in d.items() if k in fields}
        if "mesh_shape" in clean and clean["mesh_shape"] is not None:
            clean["mesh_shape"] = tuple(clean["mesh_shape"])
        if (clean.get("cbow") and clean.get("duplicate_scaling")
                # > 0: only RESOLVED stored pools need the normalization; a
                # -1 AUTO marker (to_dict round-trip) resolves itself to 0
                # beside cbow+duplicate_scaling and must stay AUTO
                and clean.get("negative_pool", 0) > 0
                and clean.get("cbow_update", "scatter") == "scatter"):
            # pre-selection-matrix checkpoints stored a resolved auto pool next
            # to cbow+duplicate_scaling; the old trainer IGNORED that pool
            # (warn-only, per-example negatives), so normalizing to 0 preserves
            # the exact trained semantics — refusing would brick the checkpoint
            clean["negative_pool"] = 0
        return cls(**clean)
