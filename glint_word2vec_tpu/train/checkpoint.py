"""Model persistence (reference components G9/C13) with mid-training checkpointing.

On-disk layout keeps the reference's composite-model contract (mllib:493-498,714-715,
ml:504-507) while replacing HDFS matrix shards with array files:

    path/
      words          one word per line, line order == embedding row order (exact parity
                     with the reference's sidecar, mllib:495-496)
      counts.npy     per-word corpus counts (needed to rebuild the negative-sampling
                     table on resume; the reference re-broadcasts vocabCns instead)
      syn0.npy       input embeddings [V, D] float32            (dense layout)
      syn1.npy       output embeddings [V, D] float32 (present iff trainable state saved)
      syn0.shards/rows-<start>-<stop>.npy                       (row-shards layout)
      syn1.shards/rows-<start>-<stop>.npy
      metadata.json  config + format version + train_state — the analog of the ML layer's
                     DefaultParamsWriter metadata (ml:504-507)

Two matrix layouts behind one directory contract:

- **dense** — host numpy arrays, one ``.npy`` per matrix. Fine up to a few GB.
- **row-shards** — the G9 analog of the reference's PS-side shard write
  (``matrix.save``, mllib:493-497): every process writes only the row ranges its own
  devices hold (``Array.addressable_shards``), so nothing is ever gathered to one host
  — at the 10M×300 north star each of 16 hosts writes ~0.75 GB instead of one host
  materializing 12 GB per matrix. Shards are written PADDED (as sharded in HBM) with
  the real (vocab_size, vector_size) recorded in metadata; readers slice.

``load_model`` reads either layout into host arrays; :func:`load_params_into_plan`
streams row-shards straight into a (possibly different) target mesh through
``make_array_from_callback`` + memory-mapped shard files — load never needs a full host
copy either (the "retarget a different PS topology" load path, mllib:696-725).

Improvement over the reference: ``train_state`` records (iteration, words_processed), so a
``numIterations`` run is resumable mid-way — the reference is all-or-nothing (SURVEY §5).

Integrity (docs/robustness.md): both writers record a per-file SHA-256 digest map in
``metadata.json`` (additive — older readers ignore it, so no format bump); readers
verify what they read, :func:`verify_checkpoint` audits a checkpoint without loading
the matrices into device memory, and :func:`load_latest_valid` scans a directory of
checkpoints, reclaims interrupted-save debris, and returns the newest one that
verifies — the recovery entry point after a crash or preemption.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import shutil
from typing import Any, Dict, List, Optional

import numpy as np

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.train import faults


def _traced(name: str):
    """Record this function as a host trace span on the process-wide tracer
    (obs/spans.py) — a no-op until a telemetry-on run enables it. Imported
    lazily at CALL time: this module sits on the train package's import path
    and obs pulls train.faults back in."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            from glint_word2vec_tpu.obs.spans import default_tracer
            with default_tracer().span(name):
                return fn(*args, **kwargs)
        return wrapped
    return deco

logger = logging.getLogger("glint_word2vec_tpu")

# Per-layout format stamps: the dense .npy layout is unchanged since round 1 and stays
# at 1 (readers pinned to 1 keep working); the row-shards layout introduced 2; a
# checkpoint whose TrainState carries shard_progress (mid-run, sharded-input feed)
# stamps 3 so that older readers — whose TrainState.from_dict would silently DROP the
# field and mis-position the resume — refuse it instead.
DENSE_FORMAT_VERSION = 1
SHARDED_FORMAT_VERSION = 2
SHARD_PROGRESS_FORMAT_VERSION = 3
_READABLE_VERSIONS = (1, 2, 3)


class CheckpointCorruptError(ValueError):
    """A checkpoint failed integrity verification: missing/unparseable
    metadata, a file named in the digest map absent, or content whose SHA-256
    does not match the digest recorded at save time."""


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


class _HashingWriter:
    """File-object proxy that SHA-256-hashes every byte as it is written —
    the single-pass digest path (PERF.md §10): writers used to write each
    file and then RE-READ it through :func:`_sha256_file`, one full extra
    I/O pass over multi-GB matrices."""

    def __init__(self, f):
        self._f = f
        self.sha = hashlib.sha256()

    def write(self, data) -> int:
        self.sha.update(data)
        return self._f.write(data)

    def flush(self) -> None:
        self._f.flush()

    def tell(self) -> int:
        return self._f.tell()


def _save_npy_hashed(path: str, arr: np.ndarray) -> str:
    """``np.save`` through a hashing proxy: returns the file's SHA-256 from
    the same pass that wrote it."""
    with open(path, "wb") as f:
        w = _HashingWriter(f)
        np.save(w, arr)
    return w.sha.hexdigest()


def _save_words_hashed(path: str, words: List[str]) -> str:
    with open(path, "wb") as f:
        w = _HashingWriter(f)
        for word in words:
            w.write((word + "\n").encode("utf-8"))
    return w.sha.hexdigest()


def _run_io(tasks, workers: int) -> list:
    """Run independent no-arg I/O callables, returning their results in task
    order — a thin eager adapter over the feed plane's
    :func:`..data.pipeline.ordered_pool_map` (ONE pool primitive to
    maintain). ``workers <= 1`` runs them serially on the calling thread;
    outputs never depend on the worker count, only wall clock does
    (config.io_workers)."""
    from glint_word2vec_tpu.data.pipeline import ordered_pool_map
    tasks = list(tasks)
    return list(ordered_pool_map(
        lambda t: t(), tasks, min(workers, len(tasks))))


def _format_version(base: int, train_state: Optional["TrainState"]) -> int:
    if train_state is not None and train_state.shard_progress is not None:
        return SHARD_PROGRESS_FORMAT_VERSION
    return base


# keys the checkpoint writers own; extra_metadata may not shadow them — a
# caller-supplied "digests" or "config" would silently corrupt the contract
_RESERVED_META_KEYS = frozenset({
    "format_version", "framework", "layout", "vocab_size", "vector_size",
    "padded_vocab", "padded_dim", "config", "train_state", "digests"})


def _merge_extra_metadata(meta: Dict[str, Any],
                          extra: Optional[Dict[str, Any]]) -> None:
    if not extra:
        return
    clash = sorted(_RESERVED_META_KEYS & set(extra))
    if clash:
        raise ValueError(
            f"extra_metadata may not shadow writer-owned metadata keys "
            f"{clash}; pick different names")
    meta.update(extra)


@dataclasses.dataclass
class TrainState:
    """Mid-training progress: which iteration we are in and how many (subsampled) words
    the lr-decay clock has consumed (mllib:405-413 semantics).

    ``global_step`` is the hash-PRNG counter (ops/prng.py): persisting it keeps the
    (seed, counter) negative-sample lattice from repeating across a checkpoint resume.
    ``batches_done`` is the number of batches of the *current* iteration already trained —
    the deterministic batch-stream position that makes resume exact-step (the stream is a
    pure function of (seed, iteration, shard), so skipping ``batches_done`` batches
    reproduces the interrupted run's position).

    ``shard_progress`` records sharded stream positions; what an entry indexes depends
    on ``shard_feed``:

    - ``"pairs"`` (host-feed sharded runs, _fit_sharded): per-PROCESS
      ``[[iteration, local pair-batches done], ...]`` indexed by process id — resume
      needs the same process count.
    - ``"tokens"`` (device-feed runs): per-SEGMENT
      ``[[iteration, blocks consumed], ...]`` indexed by data segment. Segments are
      deterministic and process-independent, so resume is ELASTIC: any process count
      dividing the mesh data degree (including 1) can pick the positions up.
      Single-process device-feed checkpoints carry these alongside their own exact
      ``batches_done``.

    None on replicated-feed and host-feed single-process runs.
    """

    iteration: int = 1
    words_processed: int = 0
    finished: bool = False
    global_step: int = 0
    batches_done: int = 0
    shard_progress: Optional[List[List[int]]] = None
    # which stream shard_progress positions index: "pairs" (_fit_sharded's
    # per-process pair-batch streams) or "tokens" (per-SEGMENT device-feed
    # block positions — written by EVERY device-feed run, single-process
    # included, for elastic resume). The two count different things, so
    # resuming one with the other would silently mis-position; None on
    # host-feed single-process checkpoints and on pre-round-4 sharded ones
    # (accepted as "pairs", the only kind then)
    shard_feed: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TrainState":
        return cls(**{k: d[k]
                      for k in ("iteration", "words_processed", "finished",
                                "global_step", "batches_done", "shard_progress",
                                "shard_feed")
                      if k in d})


@_traced("checkpoint_save")
def save_model(
    path: str,
    words: List[str],
    counts: np.ndarray,
    syn0: np.ndarray,
    syn1: Optional[np.ndarray],
    config: Word2VecConfig,
    train_state: Optional[TrainState] = None,
    extra_metadata: Optional[Dict[str, Any]] = None,
) -> None:
    """Atomic save: everything is written to a sibling temp directory first and swapped
    into place, so a crash mid-save never corrupts an existing checkpoint (the whole point
    of ``checkpoint_every_steps``-style periodic saves). Every data file's SHA-256 rides
    in ``metadata.json["digests"]`` so readers (and :func:`load_latest_valid`) can tell
    a torn or bit-rotted checkpoint from a good one.

    I/O plane (PERF.md §10): digests are computed IN the write pass
    (:class:`_HashingWriter` — one sequential pass per file, not write + re-
    read), and the four independent file writes fan out over
    ``config.io_workers`` threads. The bytes on disk and the digest map are
    identical at any worker count.

    ``extra_metadata``: additive keys merged into ``metadata.json`` (readers
    ignore unknown keys — no format bump). The continual subsystem rides
    this for the ``vocab_lineage`` chain (continual/extend.py); reserved
    keys (anything :func:`load_model_header` already reads) are refused."""
    bad = [w for w in words if (not w) or ("\n" in w)]
    if bad:
        raise ValueError(
            f"cannot save vocabulary: {len(bad)} token(s) are empty or contain newlines "
            f"(first: {bad[0]!r}); the words sidecar is newline-delimited")
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = os.path.join(parent, f".{os.path.basename(path)}.tmp-{os.getpid()}")
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        def stage(name: str) -> str:
            return os.path.join(tmp, name)

        syn0 = np.asarray(syn0, dtype=np.float32)
        tasks = [
            lambda: _save_words_hashed(stage("words"), words),
            lambda: _save_npy_hashed(stage("counts.npy"),
                                     np.asarray(counts, dtype=np.int64)),
            lambda: _save_npy_hashed(stage("syn0.npy"), syn0),
        ]
        names = ["words", "counts.npy", "syn0.npy"]
        if syn1 is not None:
            tasks.append(lambda: _save_npy_hashed(
                stage("syn1.npy"), np.asarray(syn1, dtype=np.float32)))
            names.append("syn1.npy")
        digests: Dict[str, str] = dict(
            zip(names, _run_io(tasks, getattr(config, "io_workers", 1))))
        faults.crash_point("save:arrays-written")
        meta = {
            "format_version": _format_version(DENSE_FORMAT_VERSION, train_state),
            "framework": "glint_word2vec_tpu",
            "vocab_size": int(syn0.shape[0]),
            "vector_size": int(syn0.shape[1]),
            "config": config.to_dict(auto_markers=False),
            "train_state": (train_state or TrainState(finished=True)).to_dict(),
            "digests": digests,
        }
        _merge_extra_metadata(meta, extra_metadata)
        with open(stage("metadata.json"), "w", encoding="utf-8") as f:
            json.dump(meta, f, indent=2)
        faults.crash_point("save:staged")
        old = None
        if os.path.exists(path):
            old = path + f".old-{os.getpid()}"
            os.rename(path, old)
        faults.crash_point("save:swap")  # the torn window: path absent, old+tmp live
        os.rename(tmp, path)
        if old is not None:
            shutil.rmtree(old)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    faults.corrupt_checkpoint(path)


def _write_array_shards(dirpath: str, arr, workers: int = 1) -> Dict[str, str]:
    """Write the row ranges THIS process owns (replica 0 only) as individual .npy
    files. ``arr`` is a (possibly multi-process) row-sharded jax.Array; no full-array
    host materialization happens — each shard's ``.data`` is device-local. The
    filenames carry the row ranges; readers list the directory (no manifest).
    Returns {checkpoint-relative path: sha256} for the files this process wrote.

    Each shard hashes in its own write pass (:class:`_HashingWriter`) and the
    independent shard writes — device→host fetch included — fan out over
    ``workers`` threads; the digest map is assembled in shard order, so bytes
    and metadata are identical at any worker count."""
    os.makedirs(dirpath, exist_ok=True)
    jobs = []  # (relative name, task) in shard order
    for sh in arr.addressable_shards:
        if sh.replica_id != 0:
            continue  # rows replicated over the data axis: first replica writes
        rows = sh.index[0]
        start = rows.start or 0
        stop = rows.stop if rows.stop is not None else arr.shape[0]
        cols = sh.index[1] if len(sh.index) > 1 else slice(None)
        if (cols.start or 0) != 0 or (cols.stop not in (None, arr.shape[1])):
            raise ValueError(
                "row-shards layout requires row sharding (full rows per shard); got "
                f"column slice {cols} — use the dense layout for other shardings")
        fname = f"rows-{start:010d}-{stop:010d}.npy"

        def task(sh=sh, fname=fname):
            return _save_npy_hashed(os.path.join(dirpath, fname),
                                    np.asarray(sh.data))

        jobs.append((f"{os.path.basename(dirpath)}/{fname}", task))
    return dict(zip([rel for rel, _ in jobs],
                    _run_io([t for _, t in jobs], workers)))


@_traced("checkpoint_save_sharded")
def save_model_sharded(
    path: str,
    words: List[str],
    counts: np.ndarray,
    syn0,
    syn1,
    config: Word2VecConfig,
    train_state: Optional[TrainState] = None,
    vocab_size: Optional[int] = None,
    vector_size: Optional[int] = None,
    extra_metadata: Optional[Dict[str, Any]] = None,
) -> None:
    """Row-shards save: every process writes its own rows, process 0 writes metadata
    and swaps the directory into place after a cross-process barrier. Single-process
    runs degenerate to the same protocol with one writer.

    ``syn0``/``syn1`` are the PADDED sharded jax.Arrays exactly as trained;
    ``vocab_size``/``vector_size`` record the real extents for readers.

    Failure model (shared fate, like every barrier in a SPMD program): if any process
    raises between the barriers, the survivors block in ``sync_global_devices`` until the
    JAX coordination service detects the dead process and fails the whole job — there is
    no per-process timeout here by design, because a partial save must never be swapped
    into place. Garbage left in ``.tmp-sharded`` by a failed attempt is reclaimed by the
    next save: process 0 rmtree's the staging dir before the first barrier. The atomic
    ``os.rename`` swap means an existing checkpoint at ``path`` is never corrupted by a
    mid-save crash.
    """
    import jax

    bad = [w for w in words if (not w) or ("\n" in w)]
    if bad:
        raise ValueError(
            f"cannot save vocabulary: {len(bad)} token(s) are empty or contain "
            f"newlines (first: {bad[0]!r}); the words sidecar is newline-delimited")
    multi = jax.process_count() > 1
    if multi:
        from jax.experimental import multihost_utils
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    # deterministic tmp name: all processes write into the SAME staging dir (shared
    # filesystem contract, like the reference's HDFS target)
    tmp = os.path.join(parent, f".{os.path.basename(path)}.tmp-sharded")
    if jax.process_index() == 0:
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
    if multi:
        multihost_utils.sync_global_devices("glint-ckpt-staged")
    io_workers = getattr(config, "io_workers", 1)
    try:
        # shard lists are NOT collected into metadata: readers list the directory, and
        # the filenames carry the row ranges (a cross-process reduce would buy nothing)
        digests = _write_array_shards(os.path.join(tmp, "syn0.shards"), syn0,
                                      workers=io_workers)
        if syn1 is not None:
            digests.update(
                _write_array_shards(os.path.join(tmp, "syn1.shards"), syn1,
                                    workers=io_workers))
        # per-process digest sidecars ride the shared filesystem (the same
        # contract the shard files themselves rely on); process 0 merges them
        # into metadata after the write barrier — cheaper and simpler than
        # allgathering variable-length digest maps through the device mesh
        sidecar = os.path.join(tmp, f".digests-{jax.process_index()}.json")
        with open(sidecar, "w", encoding="utf-8") as f:
            json.dump(digests, f)
        faults.crash_point("save:arrays-written")
        if multi:
            multihost_utils.sync_global_devices("glint-ckpt-written")
        if jax.process_index() == 0:
            for name in sorted(os.listdir(tmp)):
                if name.startswith(".digests-"):
                    with open(os.path.join(tmp, name), encoding="utf-8") as f:
                        digests.update(json.load(f))
                    os.unlink(os.path.join(tmp, name))
            digests["words"] = _save_words_hashed(
                os.path.join(tmp, "words"), words)
            digests["counts.npy"] = _save_npy_hashed(
                os.path.join(tmp, "counts.npy"),
                np.asarray(counts, dtype=np.int64))
            meta = {
                "format_version": _format_version(SHARDED_FORMAT_VERSION,
                                                  train_state),
                "framework": "glint_word2vec_tpu",
                "layout": "row-shards",
                "vocab_size": int(vocab_size if vocab_size is not None
                                  else syn0.shape[0]),
                "vector_size": int(vector_size if vector_size is not None
                                   else syn0.shape[1]),
                "padded_vocab": int(syn0.shape[0]),
                "padded_dim": int(syn0.shape[1]),
                "config": config.to_dict(auto_markers=False),
                "train_state": (train_state or TrainState(finished=True)).to_dict(),
                "digests": digests,
            }
            _merge_extra_metadata(meta, extra_metadata)
            with open(os.path.join(tmp, "metadata.json"), "w", encoding="utf-8") as f:
                json.dump(meta, f, indent=2)
            faults.crash_point("save:staged")
            old = None
            if os.path.exists(path):
                old = path + ".old-swap"
                if os.path.exists(old):
                    shutil.rmtree(old)
                os.rename(path, old)
            faults.crash_point("save:swap")
            os.rename(tmp, path)
            if old is not None:
                shutil.rmtree(old)
        if multi:
            multihost_utils.sync_global_devices("glint-ckpt-done")
    except BaseException:
        if jax.process_index() == 0:
            shutil.rmtree(tmp, ignore_errors=True)
        raise
    if jax.process_index() == 0:
        faults.corrupt_checkpoint(path)


class ShardedMatrixReader:
    """Memory-mapped reader over a ``*.shards/`` directory: row-range reads without
    assembling the full matrix."""

    # np.save writes bfloat16 (an ml_dtypes type numpy has no descr for) as raw
    # 2-byte void '|V2', and np.load hands the void dtype back — assignments and
    # math on it then fail with "No cast function available". The bf16 trainer
    # is the only 2-byte-void producer in this codebase, so reads re-view the
    # bytes as bfloat16. (The dense layout is unaffected: save_model converts
    # to float32 on write.)
    _VOID2 = np.dtype("V2")

    @classmethod
    def _undo_void(cls, arr: np.ndarray) -> np.ndarray:
        if arr.dtype == cls._VOID2:
            import ml_dtypes
            return arr.view(ml_dtypes.bfloat16)
        return arr

    def __init__(self, dirpath: str):
        self.dirpath = dirpath
        self._mmap_cache: Optional[List[tuple]] = None
        self._spans: List[tuple] = []
        for fname in sorted(os.listdir(dirpath)):
            if not fname.startswith("rows-"):
                continue
            stem = fname[len("rows-"):-len(".npy")]
            start, stop = (int(x) for x in stem.split("-"))
            self._spans.append((start, stop, fname))
        if not self._spans:
            raise FileNotFoundError(f"no shard files under {dirpath!r}")
        self._spans.sort()
        self.rows = self._spans[-1][1]
        probe = self._undo_void(
            np.load(os.path.join(dirpath, self._spans[0][2]), mmap_mode="r"))
        self.cols = probe.shape[1]
        self.dtype = probe.dtype
        prev = 0
        for start, stop, _ in self._spans:
            if start != prev:
                raise ValueError(
                    f"shard gap/overlap at row {prev} (next shard starts {start}) "
                    f"under {dirpath!r}")
            prev = stop

    def read(self, start: int, stop: int, workers: int = 1) -> np.ndarray:
        """Rows [start, stop) assembled from the overlapping shard files (mmap-backed,
        so only the requested pages are touched). ``workers > 1`` copies the
        per-shard row ranges concurrently (disjoint destination slices, so the
        result is identical at any worker count)."""
        out = np.empty((stop - start, self.cols), dtype=self.dtype)

        def copy_span(span):
            s, e, fname = span
            lo, hi = max(start, s), min(stop, e)
            if lo >= hi:
                return
            m = self._undo_void(
                np.load(os.path.join(self.dirpath, fname), mmap_mode="r"))
            out[lo - start:hi - start] = m[lo - s:hi - s]

        _run_io([lambda sp=sp: copy_span(sp) for sp in self._spans], workers)
        return out

    def read_all(self, workers: int = 1) -> np.ndarray:
        return self.read(0, self.rows, workers=workers)

    def gather(self, ids: np.ndarray) -> np.ndarray:
        """Scattered rows by id, in ``ids`` order, gathered through
        cached per-shard mmap handles — ``read()`` reopens every shard
        file per call, which is fine for block streaming but dominates
        when the serving tier's re-rank stage fetches a few hundred
        scattered rows per query (serve/quant.py). Only the requested
        rows' pages are touched."""
        ids = np.asarray(ids)
        if self._mmap_cache is None:
            self._mmap_cache = [
                (s, e, self._undo_void(np.load(
                    os.path.join(self.dirpath, fname), mmap_mode="r")))
                for s, e, fname in self._spans]
        out = np.empty((ids.size, self.cols), dtype=self.dtype)
        for s, e, m in self._mmap_cache:
            mask = (ids >= s) & (ids < e)
            if mask.any():
                out[mask] = m[ids[mask] - s]
        return out


@_traced("checkpoint_load_plan")
def load_params_into_plan(path: str, plan, padded_vocab: int, padded_dim: int,
                          dtype=np.float32, verify: bool = False,
                          io_workers: Optional[int] = None):
    """Stream a row-shards checkpoint straight onto a target mesh (which may differ
    from the one that wrote it — the reference's load-onto-new-PS-topology path,
    mllib:696-725): each device's row block is read from the mmap'd shard files by a
    ``make_array_from_callback`` callback, zero-padded to the target padded shape.
    Returns (syn0, syn1) as global jax.Arrays; syn1 is None if not saved.

    ``verify=True`` checks the recorded shard digests first — one extra
    sequential read of every shard file, so it is off by default on this
    streaming path (the 10M-row north star); recovery flows that just survived
    a crash should pass True or call :func:`verify_checkpoint` themselves."""
    import jax

    meta_path = os.path.join(path, "metadata.json")
    with open(meta_path, "r", encoding="utf-8") as f:
        meta = json.load(f)
    if meta.get("layout") != "row-shards":
        raise ValueError(f"{path!r} is not a row-shards checkpoint")
    if io_workers is None:
        # fallback only — the RESUMING run's live config should set this (the
        # saved value reflects the writing host, not the loading one)
        io_workers = int(meta.get("config", {}).get("io_workers", 1))
    if verify:
        _verify_digests(path, meta, workers=io_workers)
    V, Dr = meta["vocab_size"], meta["vector_size"]

    def make(name: str):
        dirpath = os.path.join(path, f"{name}.shards")
        if not os.path.isdir(dirpath):
            return None
        reader = ShardedMatrixReader(dirpath)

        def cb(idx):
            rows = idx[0]
            start = rows.start or 0
            stop = rows.stop if rows.stop is not None else padded_vocab
            block = np.zeros((stop - start, padded_dim), dtype=dtype)
            lo, hi = start, min(stop, V)  # rows beyond the real vocab stay zero
            if lo < hi:
                src = reader.read(lo, hi, workers=io_workers)
                block[:hi - lo, :min(Dr, padded_dim)] = \
                    src[:, :min(Dr, padded_dim)]
            cols = idx[1] if len(idx) > 1 else slice(None)
            return block[:, cols]

        return jax.make_array_from_callback(
            (padded_vocab, padded_dim), plan.embedding, cb)

    return make("syn0"), make("syn1")


def _verify_digests(path: str, meta: Dict[str, Any],
                    workers: int = 1) -> None:
    """Check every recorded SHA-256 digest against the on-disk bytes.
    Checkpoints written before the digest map existed pass vacuously.
    ``workers > 1`` hashes the files concurrently (config.io_workers);
    failures are reported in sorted-name order either way."""
    digests = meta.get("digests") or {}
    items = sorted(digests.items())
    for rel, _ in items:
        if not os.path.exists(os.path.join(path, rel.replace("/", os.sep))):
            raise CheckpointCorruptError(
                f"checkpoint {path!r}: {rel!r} is recorded in the digest map "
                f"but missing on disk — torn or partially deleted checkpoint")
    got_all = _run_io(
        [lambda rel=rel: _sha256_file(
            os.path.join(path, rel.replace("/", os.sep)))
         for rel, _ in items], workers)
    for (rel, want), got in zip(items, got_all):
        if got != want:
            raise CheckpointCorruptError(
                f"checkpoint {path!r}: {rel!r} content digest {got[:12]}… does "
                f"not match the recorded {want[:12]}… — corrupt (bit rot, torn "
                f"write, or hand-edited); refusing to load it")


def verify_checkpoint(path: str, io_workers: int = 1) -> Dict[str, Any]:
    """Integrity audit of one checkpoint directory without loading matrices
    into device memory: metadata parses, the format version is readable, every
    required data file for the layout exists, shard spans are gapless, and all
    recorded digests match the bytes on disk. Returns the parsed metadata.
    Raises :class:`CheckpointCorruptError` (or ``FileNotFoundError`` when no
    metadata exists at all). ``io_workers > 1`` hashes files concurrently."""
    meta_path = os.path.join(path, "metadata.json")
    if not os.path.exists(meta_path):
        raise FileNotFoundError(f"no metadata.json under {path!r}")
    try:
        with open(meta_path, "r", encoding="utf-8") as f:
            meta = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise CheckpointCorruptError(
            f"checkpoint {path!r}: metadata.json unreadable ({e})") from e
    version = meta.get("format_version")
    if version not in _READABLE_VERSIONS:
        raise CheckpointCorruptError(
            f"checkpoint {path!r}: unsupported format_version {version}")
    required = ["words", "counts.npy"]
    if meta.get("layout") == "row-shards":
        shard_dirs = ["syn0.shards"]
        if os.path.isdir(os.path.join(path, "syn1.shards")):
            shard_dirs.append("syn1.shards")
        for dirname in shard_dirs:
            try:
                ShardedMatrixReader(os.path.join(path, dirname))
            except (OSError, ValueError) as e:
                raise CheckpointCorruptError(
                    f"checkpoint {path!r}: {dirname} unreadable ({e})") from e
    else:
        required.append("syn0.npy")
    for name in required:
        if not os.path.exists(os.path.join(path, name)):
            raise CheckpointCorruptError(
                f"checkpoint {path!r}: required file {name!r} missing — "
                f"partial or torn checkpoint")
    _verify_digests(path, meta, workers=io_workers)
    return meta


def load_latest_valid(directory: str, reclaim: bool = True) -> str:
    """Recovery entry point: scan ``directory`` for checkpoint directories and
    return the path of the newest one that passes :func:`verify_checkpoint`.

    "Newest" orders by the recorded train progress (global_step, then
    words_processed), falling back to mtime — progress is what a resume cares
    about, and mtimes lie across filesystems and restores.

    Interrupted-save debris is reclaimed along the way (``reclaim=True``):

    - ``.\\*.tmp-\\*`` staging directories (never swapped into place) are deleted
      outright — even a complete one was never committed.
    - ``\\*.old-\\*`` directories (the previous checkpoint, renamed aside during
      the swap window) are *candidates*: if one is the newest verifiable state
      — the SIGKILL-between-renames case, where the live path vanished — it is
      renamed back into place and its path returned; superseded or corrupt
      ones are deleted.

    With ``reclaim=True`` this is a RECOVERY operation for a dead writer: it
    deletes staging directories and renames swap debris, so it must NOT race a
    live saver (it would destroy an in-flight save). Readers that may overlap
    a running trainer — a serving process polling the directory — pass
    ``reclaim=False``: nothing is touched, and a winning ``*.old-*`` candidate
    is returned at its debris path instead of being renamed back.

    Raises ``FileNotFoundError`` when nothing under ``directory`` verifies."""
    try:
        entries = sorted(os.listdir(directory))
    except OSError as e:
        raise FileNotFoundError(
            f"cannot scan checkpoint directory {directory!r}: {e}") from e
    candidates: List[tuple] = []  # (kind, name, path)
    for name in entries:
        p = os.path.join(directory, name)
        if not os.path.isdir(p):
            continue
        if ".tmp-" in name:
            if reclaim:
                logger.info("reclaiming interrupted-save staging dir %s", p)
                shutil.rmtree(p, ignore_errors=True)
            continue
        kind = "old" if ".old-" in name else "normal"
        candidates.append((kind, name, p))
    best = None  # (sort_key, kind, name, path)
    for kind, name, p in candidates:
        try:
            meta = verify_checkpoint(p)
        except (FileNotFoundError, CheckpointCorruptError, ValueError) as e:
            logger.warning("skipping unverifiable checkpoint %s: %s", p, e)
            continue
        ts = meta.get("train_state") or {}
        key = (int(ts.get("global_step") or 0),
               int(ts.get("words_processed") or 0),
               1 if kind == "normal" else 0,
               os.path.getmtime(p))
        if best is None or key > best[0]:
            best = (key, kind, name, p)
    if best is None:
        raise FileNotFoundError(
            f"no verifiable checkpoint under {directory!r} "
            f"({len(candidates)} candidate(s) scanned)")
    _, kind, name, p = best
    if not reclaim:
        return p
    if kind == "old":
        # the swap was interrupted after the previous checkpoint was renamed
        # aside: restore it to its base name so resume paths see a normal
        # checkpoint (anything sitting at the base name failed verification,
        # or it would have outranked this debris)
        base = os.path.join(directory, name.split(".old-")[0])
        if os.path.exists(base):
            shutil.rmtree(base)
        os.rename(p, base)
        logger.warning("recovered checkpoint %s from interrupted-save "
                       "debris %s", base, name)
        p = base
    for kind2, _, p2 in candidates:
        if kind2 == "old" and p2 != best[3] and os.path.exists(p2):
            logger.info("reclaiming superseded swap debris %s", p2)
            shutil.rmtree(p2, ignore_errors=True)
    return p


def load_model_header(path: str) -> Dict[str, Any]:
    """Read everything EXCEPT the matrices: metadata, words sidecar, counts. This is
    the cheap half of the reference's load contract (the ``/words`` read + params
    metadata, mllib:714-715, ml:514-519) — used by the sharded model-load path so the
    [V, D] matrices never materialize on one host."""
    meta_path = os.path.join(path, "metadata.json")
    if not os.path.exists(meta_path):
        raise FileNotFoundError(f"no metadata.json under {path!r}")
    with open(meta_path, "r", encoding="utf-8") as f:
        meta = json.load(f)
    version = meta.get("format_version")
    if version not in _READABLE_VERSIONS:
        raise ValueError(f"unsupported checkpoint format_version {version}")
    with open(os.path.join(path, "words"), "r", encoding="utf-8") as f:
        words = [line.rstrip("\n") for line in f if line.rstrip("\n")]
    counts = np.load(os.path.join(path, "counts.npy"))
    declared = meta.get("vocab_size")
    if declared is not None and declared != len(words):
        raise ValueError(
            f"words sidecar has {len(words)} entries but metadata declares "
            f"vocab_size {declared} — corrupt or hand-edited checkpoint")
    return {
        "words": words,
        "counts": counts,
        "layout": meta.get("layout", "dense"),
        "vocab_size": meta.get("vocab_size", len(words)),
        "vector_size": meta.get("vector_size"),
        "config": Word2VecConfig.from_dict(meta["config"]),
        "train_state": TrainState.from_dict(meta.get("train_state", {})),
        # continual-training provenance (continual/extend.py): the chain of
        # vocabulary migrations this checkpoint descends from; [] on
        # checkpoints that never grew
        "vocab_lineage": meta.get("vocab_lineage", []),
    }


@_traced("checkpoint_load")
def load_model(path: str, header: Optional[Dict[str, Any]] = None,
               verify: bool = True,
               io_workers: Optional[int] = None) -> Dict[str, Any]:
    """Read a saved model directory. Returns dict with words, counts, syn0, syn1 (may be
    None), config, train_state. Mirrors the reference's load contract (mllib:710-725:
    read /words in row order, load matrix shards, rebuild model).

    ``header``: a prior :func:`load_model_header` result to reuse — callers that
    already read it (to check the layout) pass it through so the words sidecar and
    counts are not parsed twice.

    ``verify`` (default True): check every file against the SHA-256 digests the
    writer recorded — a bit-flipped or torn checkpoint raises
    :class:`CheckpointCorruptError` instead of silently loading garbage rows.
    Costs one extra sequential read of the files; this full-materialization
    path is host-RAM-bound anyway (pre-digest checkpoints pass vacuously).

    ``io_workers`` (default: the saved config's ``io_workers``) fans digest
    hashing, per-shard reads, and the syn0/syn1 loads across a thread pool —
    the loaded arrays are identical at any worker count."""
    if header is None:
        header = load_model_header(path)
    if io_workers is None:
        io_workers = getattr(header["config"], "io_workers", 1)
    if verify:
        meta_path = os.path.join(path, "metadata.json")
        with open(meta_path, "r", encoding="utf-8") as f:
            _verify_digests(path, json.load(f), workers=io_workers)
    words = header["words"]
    if header["layout"] == "row-shards":
        V, Dr = header["vocab_size"], header["vector_size"]
        s1dir = os.path.join(path, "syn1.shards")
        # split the worker budget across the two matrices, each of which fans
        # its own per-shard copies (disjoint destination slices)
        per = max(1, io_workers // 2)
        syn0, syn1 = _run_io(
            [lambda: ShardedMatrixReader(
                os.path.join(path, "syn0.shards")).read(
                    0, V, workers=per)[:, :Dr],
             lambda: (ShardedMatrixReader(s1dir).read(
                 0, V, workers=per)[:, :Dr]
                      if os.path.isdir(s1dir) else None)],
            io_workers)
    else:
        syn1_path = os.path.join(path, "syn1.npy")
        syn0, syn1 = _run_io(
            [lambda: np.load(os.path.join(path, "syn0.npy")),
             lambda: (np.load(syn1_path) if os.path.exists(syn1_path)
                      else None)],
            io_workers)
    if syn0.shape[0] != len(words):
        raise ValueError(
            f"words sidecar has {len(words)} entries but syn0 has {syn0.shape[0]} rows")
    return {
        "words": words,
        "counts": header["counts"],
        "syn0": syn0,
        "syn1": syn1,
        "config": header["config"],
        "train_state": header["train_state"],
    }
