"""R8 bad trainer half: four dispatch-only refusals — one with no config
twin at all (cbow x use_pallas), one 'covered' only by a single-knob range
check (cbow x negative_pool), which is not coverage, one on a NEW
stabilizer knob (use_pallas x max_row_norm) whose range check in config is
likewise not combination coverage, and one living in __init__ path
selection rather than _build_step (the device_pairgen class graftcheck's
first run caught in the real tree)."""


class Trainer:
    def __init__(self, config):
        self.config = config
        if config.device_pairgen:
            if config.cbow:
                raise ValueError("device feed is skip-gram only")

    def _build_step(self):
        cfg = self.config
        if cfg.use_pallas:
            if cfg.cbow:
                raise ValueError("use_pallas is SGNS-only")
            if cfg.max_row_norm:
                raise ValueError("stabilizers are XLA-path only")
        if cfg.cbow:
            if cfg.negative_pool == 0:
                raise ValueError("cbow needs the shared pool here")
        return None
