"""The explicit shard_map scale-out step (ops/sgns_shard.py, ISSUE 4).

Three contracts, each tested at every 8-device mesh shape (1x8, 2x4, 4x2,
8x1 — the conftest forces the 8-device CPU mesh):

1. EQUIVALENCE — shard_map ≡ GSPMD ≡ single-device step at float64 to ~1e-12
   (params; the loss side-channel reassociates its f32 sums across shards and
   gets a correspondingly looser bound), plus the rows/cols cross-layout loss
   check against the shard_map step.
2. DETERMINISM — ``step_lowering`` changes wall clock only: params are
   bit-identical across repeated runs per lowering, and the two lowerings
   agree to f32 reassociation noise (bit-identity ACROSS lowerings is
   impossible by construction: different reduction orders).
3. SCHEDULE — the compiled shard_map HLO moves ZERO update bytes over the
   model axis (its only model-axis collective is the forward row-assembly
   psum) and fewer total collective bytes than GSPMD on every mesh with a
   data axis — asserted through the real auditor (tools/collectives.py), so
   a regression that re-introduces a dense all-gather/all-reduce into the
   compiled step fails HERE, not on a hardware run. tools/shard_ab.py --smoke
   runs as a subprocess for the same reason (the harness cannot rot).
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.pipeline import encode_sentences
from glint_word2vec_tpu.data.vocab import build_vocab
from glint_word2vec_tpu.ops.sgns import EmbeddingPair, sgns_step_shared_core
from glint_word2vec_tpu.ops.sgns_shard import make_shard_map_sgns_step
from glint_word2vec_tpu.parallel.mesh import classify_replica_groups, make_mesh
from glint_word2vec_tpu.train.trainer import Trainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MESHES = [(1, 8), (2, 4), (4, 2), (8, 1)]
NEG = 3


def _f64_inputs(v=64, d=16, b=32, pool=8, seed=0):
    rng = np.random.default_rng(seed)
    params = EmbeddingPair(
        jnp.asarray(rng.standard_normal((v, d)), jnp.float64),
        jnp.asarray(rng.standard_normal((v, d)) * 0.1, jnp.float64))
    batch = {
        "centers": jnp.asarray(rng.integers(0, v, b), jnp.int32),
        "contexts": jnp.asarray(rng.integers(0, v, b), jnp.int32),
        # some padded pairs, so masking semantics are exercised
        "mask": jnp.asarray(rng.random(b) < 0.9, jnp.float32),
    }
    negs = jnp.asarray(rng.integers(0, v, pool), jnp.int32)
    return params, batch, negs, jnp.float64(0.025)


@pytest.mark.parametrize("shape", MESHES)
def test_equivalence_f64_all_mesh_shapes(shape):
    """shard_map ≡ GSPMD ≡ single-device at f64 ~1e-12, per mesh shape."""
    from jax.experimental import enable_x64

    with enable_x64():
        params, batch, negs, alpha = _f64_inputs()
        ref, mref = sgns_step_shared_core(
            params, batch["centers"], batch["contexts"], batch["mask"],
            negs, alpha, NEG, "exact", jnp.float64, False, jnp.float64, True)

        plan = make_mesh(*shape)
        sharded = EmbeddingPair(
            jax.device_put(params.syn0, plan.embedding),
            jax.device_put(params.syn1, plan.embedding))

        # GSPMD lowering on this mesh
        def gspmd(p, b_, n_, a_):
            new_p, m = sgns_step_shared_core(
                p, b_["centers"], b_["contexts"], b_["mask"], n_, a_,
                NEG, "exact", jnp.float64, False, jnp.float64, True)
            return jax.lax.with_sharding_constraint(
                new_p, EmbeddingPair(plan.embedding, plan.embedding)), m

        g_out, g_m = jax.jit(gspmd)(sharded, batch, negs, alpha)
        # explicit shard_map lowering on this mesh
        step = make_shard_map_sgns_step(
            plan.mesh, NEG, "exact", jnp.float64, jnp.float64, True)
        s_out, s_m = jax.jit(step)(sharded, batch, negs, alpha)
        assert s_out.syn0.sharding.is_equivalent_to(plan.embedding, 2)

        for out, m, name in ((g_out, g_m, "gspmd"), (s_out, s_m, "shard_map")):
            np.testing.assert_allclose(
                np.asarray(out.syn0), np.asarray(ref.syn0),
                rtol=0, atol=1e-12, err_msg=f"{name} syn0 @ {shape}")
            np.testing.assert_allclose(
                np.asarray(out.syn1), np.asarray(ref.syn1),
                rtol=0, atol=1e-12, err_msg=f"{name} syn1 @ {shape}")
            assert float(m.pairs) == float(mref.pairs)
            # the loss numerators are f32 by production choice
            # (shared_pool_coeffs casts f_pos to f32), so cross-shard
            # reassociation bounds the side-channel at f32 resolution
            assert abs(float(m.loss) - float(mref.loss)) < 1e-5


def test_cross_layout_loss_rows_vs_cols():
    """The CIKM'16 column layout (GSPMD, embedding_partition='cols') and the
    explicit rows schedule compute the same loss — the dryrun's cross-layout
    check extended to the shard_map step (f64)."""
    from jax.experimental import enable_x64

    with enable_x64():
        params, batch, negs, alpha = _f64_inputs(v=64, d=32, b=32, pool=8)
        plan = make_mesh(2, 4)
        rows_p = EmbeddingPair(
            jax.device_put(params.syn0, plan.embedding),
            jax.device_put(params.syn1, plan.embedding))
        cols_p = EmbeddingPair(
            jax.device_put(params.syn0, plan.embedding_cols),
            jax.device_put(params.syn1, plan.embedding_cols))

        step = make_shard_map_sgns_step(
            plan.mesh, NEG, "exact", jnp.float64, jnp.float64, True)
        _, m_rows = jax.jit(step)(rows_p, batch, negs, alpha)

        def cols(p, b_, n_, a_):
            new_p, m = sgns_step_shared_core(
                p, b_["centers"], b_["contexts"], b_["mask"], n_, a_,
                NEG, "exact", jnp.float64, False, jnp.float64, True)
            return jax.lax.with_sharding_constraint(
                new_p, EmbeddingPair(plan.embedding_cols,
                                     plan.embedding_cols)), m

        _, m_cols = jax.jit(cols)(cols_p, batch, negs, alpha)
        assert abs(float(m_rows.loss) - float(m_cols.loss)) < 1e-5


def _fit(lowering, shape, vocab, sents, seed=3):
    cfg = Word2VecConfig(vector_size=16, min_count=1, pairs_per_batch=64,
                         num_iterations=1, window=2, negatives=NEG,
                         negative_pool=16, steps_per_dispatch=2, seed=seed,
                         step_lowering=lowering)
    tr = Trainer(cfg, vocab, plan=make_mesh(*shape))
    tr.fit(encode_sentences(sents, vocab, cfg.max_sentence_length))
    return np.asarray(tr.params.syn0), np.asarray(tr.params.syn1)


def test_step_lowering_wall_clock_only():
    """Repeated runs are bit-identical PER lowering; the two lowerings agree
    to f32 reassociation noise (different reduction orders — cross-lowering
    bit-identity is not claimed, docs/sharding.md)."""
    rng = np.random.default_rng(0)
    words = [f"w{i}" for i in range(40)]
    sents = [[words[j] for j in rng.integers(0, 40, 10)] for _ in range(80)]
    vocab = build_vocab(sents, min_count=1)

    runs = {low: [_fit(low, (2, 4), vocab, sents) for _ in range(2)]
            for low in ("gspmd", "shard_map")}
    for low, ((a0, a1), (b0, b1)) in runs.items():
        assert np.array_equal(a0, b0) and np.array_equal(a1, b1), (
            f"{low} lowering is not run-to-run deterministic")
    np.testing.assert_allclose(runs["gspmd"][0][0], runs["shard_map"][0][0],
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(runs["gspmd"][0][1], runs["shard_map"][0][1],
                               rtol=1e-4, atol=1e-6)


def test_trainer_shard_map_trains_and_stays_sharded():
    rng = np.random.default_rng(1)
    words = [f"w{i}" for i in range(50)]
    sents = [[words[j] for j in rng.integers(0, 50, 12)] for _ in range(60)]
    vocab = build_vocab(sents, min_count=1)
    plan = make_mesh(2, 4)
    cfg = Word2VecConfig(vector_size=16, min_count=1, pairs_per_batch=64,
                         num_iterations=2, window=3, negative_pool=16,
                         step_lowering="shard_map")
    tr = Trainer(cfg, vocab, plan=plan)
    tr.fit(encode_sentences(sents, vocab))
    assert tr.params.syn0.sharding.is_equivalent_to(plan.embedding, 2)
    assert np.all(np.isfinite(np.asarray(tr.unpadded_params().syn0)))


def test_shard_map_device_pairgen_smoke():
    """The shard_map inner composes with the on-device pair generator feed."""
    rng = np.random.default_rng(2)
    words = [f"w{i}" for i in range(50)]
    sents = [[words[j] for j in rng.integers(0, 50, 12)] for _ in range(60)]
    vocab = build_vocab(sents, min_count=1)
    cfg = Word2VecConfig(vector_size=16, min_count=1, pairs_per_batch=64,
                         num_iterations=1, window=3, negative_pool=16,
                         device_pairgen=True, step_lowering="shard_map")
    tr = Trainer(cfg, vocab, plan=make_mesh(2, 4))
    tr.fit(encode_sentences(sents, vocab))
    assert np.all(np.isfinite(np.asarray(tr.unpadded_params().syn0)))


# -- config selection matrix ---------------------------------------------------------


def test_config_refusals():
    for kw in (dict(cbow=True), dict(use_pallas=True),
               dict(duplicate_scaling=True), dict(negative_pool=0),
               dict(embedding_partition="cols")):
        with pytest.raises(ValueError, match="shard_map|lowering"):
            Word2VecConfig(step_lowering="shard_map", **kw)
    with pytest.raises(ValueError, match="step_lowering"):
        Word2VecConfig(step_lowering="banana")
    # AUTO pool resolves to a real pool (not 0) under shard_map even at
    # small batches — the schedule requires the shared-pool estimator
    cfg = Word2VecConfig(step_lowering="shard_map", pairs_per_batch=256)
    assert cfg.negative_pool > 0


def test_trainer_refuses_indivisible_batch():
    sents = [["a", "b", "c"]] * 10
    vocab = build_vocab(sents, min_count=1)
    cfg = Word2VecConfig(vector_size=16, min_count=1, pairs_per_batch=65,
                         negative_pool=16, step_lowering="shard_map")
    with pytest.raises(ValueError, match="divisible"):
        Trainer(cfg, vocab, plan=make_mesh(2, 4))


# -- replica-group classifier (the audit's mesh bridge) ------------------------------


def test_classify_replica_groups():
    assert classify_replica_groups(2, 4, [[0, 1, 2, 3], [4, 5, 6, 7]]) == "model"
    assert classify_replica_groups(
        2, 4, [[0, 4], [1, 5], [2, 6], [3, 7]]) == "data"
    assert classify_replica_groups(2, 4, [range(8)]) == "all"
    assert classify_replica_groups(2, 4, [[0, 1], [2, 3], [4, 5], [6, 7]]) == "other"
    # order inside a group must not matter (XLA orders ids arbitrarily)
    assert classify_replica_groups(2, 4, [[3, 1, 0, 2], [7, 5, 6, 4]]) == "model"
    assert classify_replica_groups(4, 2, [[0, 1], [2, 3], [4, 5], [6, 7]]) == "model"
    assert classify_replica_groups(4, 2, [[0, 2, 4, 6], [1, 3, 5, 7]]) == "data"


# -- the audited schedule + the A/B harness cannot rot -------------------------------


def test_collective_audit_smoke_schedule_holds():
    """Compile both lowerings at the smoke geometry on every mesh shape and
    assert the shard_map schedule facts from the HLO: zero model-axis update
    bytes, and (on every mesh with a data axis) fewer total bytes than
    GSPMD. This is the regression tripwire the ISSUE asks for: a change that
    re-introduces a dense all-gather/all-reduce into the compiled step fails
    this test, not a hardware run."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "collectives.py"),
         "--smoke", "--mesh", "all"],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert len(result["meshes"]) == 4
    for mesh in result["meshes"]:
        nd, nm = mesh["mesh"]
        sm = mesh["shard_map"]
        assert sm["model_axis_update_bytes"] == 0, (nd, nm, sm)
        if nm > 1:
            # the one forward-assembly psum was found and matched
            assert sm["forward_assembly_bytes"] > 0, (nd, nm, sm)
        assert "other" not in sm["bytes_by_axis"], sm
        if nd > 1:
            # with a data axis, GSPMD pays the dense [Vs, D] delta psum;
            # the explicit schedule must move strictly fewer bytes
            assert sm["total_bytes"] < mesh["gspmd"]["total_bytes"], (nd, nm)
        # the local-SGD window (config.sync_every — ISSUE 17): still zero
        # model-axis update bytes, all k per-step assembly psums visible to
        # the text audit (the Python-unrolled-loop contract), and per-window
        # data bytes within the priced bound of the k=1 GSPMD schedule
        ls = mesh.get("localsgd")
        assert ls is not None and ls["sync_every"] > 1, mesh.keys()
        assert ls["model_axis_update_bytes"] == 0, (nd, nm, ls)
        if nm > 1:
            assert ls["forward_assembly_count"] == ls["sync_every"], ls
        if nd > 1:
            assert ls["window_data_over_gspmd_k1_schedule"] <= 0.2, (nd, nm, ls)


def test_shard_ab_smoke_tier():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "shard_ab.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert len(result["meshes"]) == 4
    for mesh in result["meshes"]:
        assert mesh["gspmd_ms"] > 0 and mesh["shard_map_ms"] > 0
        # f32 agreement: reassociation noise only, relative to param scale
        assert mesh["max_abs_diff"] <= 1e-4 * max(mesh["param_abs_max"], 1e-3)
    # the sync_every interleaved arm (ISSUE 17): every arm timed, and the
    # sync_every=1 arm is the synchronous baseline — zero divergence from
    # itself, positive divergence recorded (not asserted — it IS the
    # staleness measurement) for the local arms
    for mesh in result["localsgd_meshes"]:
        arms = mesh["arms"]
        assert "1" in arms and len(arms) >= 2
        for a in arms.values():
            assert a["ms_per_step"] > 0
        assert arms["1"]["max_abs_diff_vs_sync"] == 0.0
