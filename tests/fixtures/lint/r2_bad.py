"""R2 bad: stdlib random + unseeded module-level numpy RNG."""
import random

import numpy as np


def draw(n):
    return [random.random() for _ in range(n)], np.random.rand(n)
