"""Tests for model ops (C8/C12 analogs): transform, sentence averaging, synonyms,
analogy, norms, multiply, exports, stop."""

import numpy as np
import pytest

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.vocab import Vocabulary
from glint_word2vec_tpu.models.word2vec import Word2VecModel

WORDS = ["alpha", "beta", "gamma", "delta", "epsilon"]


@pytest.fixture(scope="module")
def model():
    rng = np.random.default_rng(0)
    vocab = Vocabulary.from_words_and_counts(WORDS, [50, 40, 30, 20, 10])
    syn0 = rng.normal(size=(5, 8)).astype(np.float32)
    # make beta nearly parallel to alpha so synonyms are predictable
    syn0[1] = syn0[0] * 2.0 + rng.normal(size=8).astype(np.float32) * 1e-3
    return Word2VecModel(vocab, syn0, syn1=np.zeros_like(syn0),
                         config=Word2VecConfig(vector_size=8)), syn0


def test_transform_word(model):
    m, syn0 = model
    np.testing.assert_allclose(m.transform("alpha"), syn0[0], rtol=1e-6)
    with pytest.raises(KeyError, match="not in vocabulary"):
        m.transform("zzz")


def test_transform_words_batched(model):
    m, syn0 = model
    out = list(m.transform_words(["gamma", "alpha", "gamma"], batch_size=2))
    np.testing.assert_allclose(out[0], syn0[2], rtol=1e-6)
    np.testing.assert_allclose(out[1], syn0[0], rtol=1e-6)
    np.testing.assert_allclose(out[2], syn0[2], rtol=1e-6)
    with pytest.raises(KeyError):
        list(m.transform_words(["alpha", "zzz"]))


def test_transform_sentences_average_and_oov(model):
    m, syn0 = model
    out = m.transform_sentences([
        ["alpha", "beta"],          # mean of two vectors
        ["alpha", "zzz", "alpha"],  # OOV dropped, duplicates count (ml:451-452)
        ["zzz"],                    # no in-vocab words → zero vector
        [],
    ])
    np.testing.assert_allclose(out[0], (syn0[0] + syn0[1]) / 2, rtol=1e-5)
    np.testing.assert_allclose(out[1], syn0[0], rtol=1e-5)
    np.testing.assert_array_equal(out[2], np.zeros(8))
    np.testing.assert_array_equal(out[3], np.zeros(8))


def test_transform_sentences_batch_boundary(model):
    m, syn0 = model
    sents = [["alpha"]] * 7
    out = m.transform_sentences(sents, batch_size=3)  # 3+3+1 flushes
    for row in out:
        np.testing.assert_allclose(row, syn0[0], rtol=1e-5)


def test_pull_and_multiply(model):
    m, syn0 = model
    np.testing.assert_allclose(m.pull([2, 0]), syn0[[2, 0]], rtol=1e-6)
    v = np.arange(8, dtype=np.float32)
    np.testing.assert_allclose(m.multiply(v), syn0 @ v, rtol=1e-4)


def test_norms(model):
    m, syn0 = model
    np.testing.assert_allclose(
        np.asarray(m.norms), np.linalg.norm(syn0, axis=1), rtol=1e-5)


def test_find_synonyms_word_query_excludes_self(model):
    m, _ = model
    res = m.find_synonyms("alpha", 2)
    words = [w for w, _ in res]
    assert "alpha" not in words
    assert words[0] == "beta"          # nearly parallel by construction
    assert res[0][1] > 0.999
    # scores sorted descending
    assert res[0][1] >= res[1][1]


def test_find_synonyms_vector_query(model):
    m, syn0 = model
    res = m.find_synonyms(syn0[0], 1)
    assert res[0][0] in ("alpha", "beta")  # self allowed for vector queries (mllib:621)


def test_find_synonyms_num_larger_than_vocab(model):
    m, _ = model
    res = m.find_synonyms("alpha", 50)
    assert len(res) == 4  # vocab minus query word


def test_find_synonyms_batch_matches_per_query(model):
    """find_synonyms_batch = find_synonyms per row, in one device dispatch per
    chunk: word and vector queries mix, word queries exclude themselves, and a
    chunk smaller than the query list exercises the chunking path."""
    m, syn0 = model
    queries = ["alpha", syn0[0], "gamma", "beta", syn0[3]]
    batched = m.find_synonyms_batch(queries, 2, chunk=2)
    assert len(batched) == len(queries)
    for q, got in zip(queries, batched):
        want = m.find_synonyms(q, 2)
        assert [w for w, _ in got] == [w for w, _ in want]
        # scores agree to matmul-association tolerance ([Q,V] vs [V] paths)
        np.testing.assert_allclose([s for _, s in got], [s for _, s in want],
                                   atol=1e-5)
    with pytest.raises(KeyError, match="not in vocabulary"):
        m.find_synonyms_batch(["alpha", "zzz"], 2)


def test_analogy_excludes_queries(model):
    m, _ = model
    res = m.analogy("alpha", "beta", "gamma", num=2)
    for w, _ in res:
        assert w not in ("alpha", "beta", "gamma")


def test_get_vectors_and_iter(model):
    m, syn0 = model
    vecs = m.get_vectors()
    assert set(vecs) == set(WORDS)
    np.testing.assert_allclose(vecs["delta"], syn0[3], rtol=1e-6)
    streamed = dict(m.iter_vectors(batch_size=2))
    for w in WORDS:
        np.testing.assert_allclose(streamed[w], vecs[w], rtol=1e-6)


def test_to_local(model):
    m, syn0 = model
    words, mat = m.to_local()
    assert words == WORDS
    np.testing.assert_allclose(mat, syn0, rtol=1e-6)


def _read_word2vec_format(path, binary):
    """Reference reader for the classic word2vec format — parses exactly the way
    gensim's KeyedVectors.load_word2vec_format / word2vec.c's distance tool do:
    header "<vocab> <dim>", then per word either space-joined decimals + newline
    (text) or <dim> little-endian float32s + newline (binary, word ends at ' ')."""
    with open(path, "rb") as f:
        header = f.readline().split()
        v, d = int(header[0]), int(header[1])
        words, vecs = [], np.empty((v, d), np.float32)
        for i in range(v):
            if binary:
                w = bytearray()
                while True:
                    ch = f.read(1)
                    if ch == b" ":
                        break
                    w.extend(ch)
                words.append(w.decode())
                vecs[i] = np.frombuffer(f.read(4 * d), dtype="<f4")
                assert f.read(1) == b"\n"
            else:
                parts = f.readline().split()
                words.append(parts[0].decode())
                vecs[i] = [float(x) for x in parts[1:]]
    return words, vecs


@pytest.mark.parametrize("binary", [False, True])
def test_export_word2vec_round_trip(model, tmp_path, binary):
    """export_word2vec writes the exact classic format (the reference's toLocal
    ecosystem hand-off, mllib:651-662): a gensim-style parser reads back identical
    words and float32-identical vectors."""
    m, syn0 = model
    path = str(tmp_path / ("vecs.bin" if binary else "vecs.txt"))
    m.export_word2vec(path, binary=binary, batch_size=2)  # exercise block seams
    words, vecs = _read_word2vec_format(path, binary)
    assert words == WORDS
    np.testing.assert_array_equal(vecs, syn0.astype(np.float32))


def test_vocab_size_mismatch_raises():
    vocab = Vocabulary.from_words_and_counts(["a"], [1])
    with pytest.raises(ValueError, match="rows"):
        Word2VecModel(vocab, np.zeros((2, 4), np.float32))


def test_stop_releases():
    vocab = Vocabulary.from_words_and_counts(["a", "b"], [2, 1])
    m = Word2VecModel(vocab, np.zeros((2, 4), np.float32))
    m.stop()
    m.stop()  # idempotent
    with pytest.raises(RuntimeError, match="stopped"):
        m.transform("a")
