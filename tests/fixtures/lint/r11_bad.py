"""R11 bad fixture: the PR 12 latency-ring race, verbatim shape. The worker
thread appends to the deque lock-free while stats() sorts it — deque
iteration raises RuntimeError on concurrent mutation, so BOTH sites are
findings (a lock-free append plus a locked read still races)."""
import collections
import threading


class LatencyRing:
    def __init__(self):
        self._lock = threading.Lock()
        self._latencies = collections.deque(maxlen=512)
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        while True:
            self._latencies.append(0.0)

    def stats(self):
        return sorted(self._latencies)
