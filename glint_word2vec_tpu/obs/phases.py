"""Per-phase host time attribution: log2 histograms over per-chunk durations.

The trainer has carried exactly two aggregate timers since round 1
(``host_wait_time`` / ``dispatch_time``) — enough to say "the host starved
the device", not enough to say *which* stage did, or whether the tail of a
distribution (one slow chunk every N) is what ate the run. This module is
the host-side twin of the probe's quarter-octave log2 histogram trick
(obs/probe.py: a bucketed quantile is exact to one bucket, ratio ≤ 2^0.25,
with no sort) applied to wall-clock durations, so "where did the time go"
is answerable per-run from the telemetry JSONL alone — no Perfetto trace
load needed.

Phases (one histogram each, docs/observability.md):

- ``producer_wait`` — fit() blocked on the next chunk/round (the host-wait
  sites of all four fit paths);
- ``stage``         — feed device-put + transfer-forcing touch
  (``stage_put``) and the sharded handshake's ``allgather_fetch``;
- ``dispatch``      — per-round step dispatch (incl. meta staging);
- ``device_block``  — explicit device syncs: the fused health probe, the
  heartbeat metrics fetch, and the CPU-mesh collective-serialization drain.

Durations arrive two ways: the span tracer tees every span whose name maps
to a phase (``spans._PHASE_OF``) into the accumulator attached for the run,
and the trainer adds the non-span waits directly. Buckets cover 2^-20 s
(~1 µs) to 2^6 s (64 s) at 4 buckets/octave — 104 buckets; durations
outside clamp to the edge buckets. Thread-safe: producer/stager threads add
concurrently with the main loop under one lock (an add is an int increment
+ two float adds — never contended for longer than that).

Disabled accumulators (telemetry and statusd both off) cost one attribute
check per add — and the span tee skips even that when no accumulator is
attached, so the telemetry-off fit path is unchanged.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional
from glint_word2vec_tpu.lockcheck import make_rlock

# quarter-octave log2 buckets over 2^-20 .. 2^6 seconds (~1 µs .. 64 s);
# same bucketing discipline as obs/probe.py's norm histogram
HIST_LO = -20            # log2 seconds of the smallest bucket edge
HIST_PER_OCTAVE = 4
HIST_BUCKETS = (6 - HIST_LO) * HIST_PER_OCTAVE  # 104

PHASES = ("producer_wait", "stage", "dispatch", "device_block")


def bucket_index(seconds: float) -> int:
    """Bucket for one duration: ``floor((log2(s) - LO) * 4)``, edge-clamped."""
    if seconds <= 2.0 ** HIST_LO:
        return 0
    i = int(math.floor((math.log2(seconds) - HIST_LO) * HIST_PER_OCTAVE))
    return min(max(i, 0), HIST_BUCKETS - 1)


def bucket_upper_edge(index: int) -> float:
    """Upper duration edge (seconds) of bucket ``index`` — the value a
    bucketed quantile reports (exact to one bucket, ratio ≤ 2^0.25)."""
    return 2.0 ** ((index + 1) / HIST_PER_OCTAVE + HIST_LO)


class _Phase:
    __slots__ = ("count", "total_s", "max_s", "hist")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self.hist: List[int] = [0] * HIST_BUCKETS


def _hist_quantile(hist: List[int], count: int, q: float) -> float:
    """Upper edge of the bucket where the CDF crosses ``q`` of ``count``."""
    if count <= 0:
        return 0.0
    need = max(1, math.ceil(q * count))
    acc = 0
    for i, c in enumerate(hist):
        acc += c
        if acc >= need:
            return bucket_upper_edge(i)
    return bucket_upper_edge(HIST_BUCKETS - 1)


class PhaseAccumulator:
    """Thread-safe per-phase duration histograms for one trainer."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        # RLock: the flight recorder's SIGTERM dump (main thread, any
        # bytecode boundary) snapshots these histograms — a plain Lock held
        # by the interrupted add() would deadlock the handler
        # (obs/blackbox.py has the full rationale)
        self._lock = make_rlock("obs.phases")
        self._phases: Dict[str, _Phase] = {p: _Phase() for p in PHASES}

    def configure(self, enabled: bool) -> None:
        self.enabled = enabled

    def clear(self) -> None:
        with self._lock:
            self._phases = {p: _Phase() for p in PHASES}

    def add(self, phase: str, seconds: float) -> None:
        if not self.enabled:
            return
        ph = self._phases.get(phase)
        if ph is None:
            return
        i = bucket_index(seconds)
        with self._lock:
            ph.count += 1
            ph.total_s += seconds
            if seconds > ph.max_s:
                ph.max_s = seconds
            ph.hist[i] += 1

    # -- snapshots --------------------------------------------------------------

    def raw_snapshot(self) -> Dict[str, tuple]:
        """Cheap copy for later delta(): {phase: (count, total_s, hist[:])}.
        ``max_s`` is deliberately cumulative-only (a per-window max needs
        per-window state the heartbeat path should not pay for)."""
        with self._lock:
            return {name: (ph.count, ph.total_s, list(ph.hist))
                    for name, ph in self._phases.items()}

    @staticmethod
    def _summarize(count: int, total_s: float, hist: List[int],
                   max_s: Optional[float] = None) -> dict:
        out = {
            "count": count,
            "total_s": round(total_s, 6),
            "p50_s": round(_hist_quantile(hist, count, 0.50), 9),
            "p99_s": round(_hist_quantile(hist, count, 0.99), 9),
            # sparse histogram: {bucket_index: count}; upper edge of bucket i
            # is 2^((i+1)/4 - 20) seconds (bucket_upper_edge)
            "hist": {str(i): c for i, c in enumerate(hist) if c},
        }
        if max_s is not None:
            out["max_s"] = round(max_s, 6)
        return out

    def summary(self) -> Dict[str, dict]:
        """Cumulative per-phase rollup (run_end / last_run_stats / statusd);
        phases that never ran are omitted."""
        with self._lock:
            return {
                name: self._summarize(ph.count, ph.total_s, ph.hist, ph.max_s)
                for name, ph in self._phases.items() if ph.count
            }

    def delta(self, prev: Dict[str, tuple]) -> Dict[str, dict]:
        """Per-phase rollup of everything added since ``prev``
        (:meth:`raw_snapshot`) — the heartbeat-window emission."""
        cur = self.raw_snapshot()
        out: Dict[str, dict] = {}
        for name, (count, total_s, hist) in cur.items():
            pc, pt, ph = prev.get(name, (0, 0.0, None))
            dcount = count - pc
            if dcount <= 0:
                continue
            dhist = (hist if ph is None
                     else [a - b for a, b in zip(hist, ph)])
            out[name] = self._summarize(dcount, total_s - pt, dhist)
        return out
