"""Host data-plane A/B harness: producer throughput + checkpoint/export/cold-start
wall clock, serial vs parallel (PERF.md §10).

Every comparison is an INTERLEAVED A/B (the PERF.md §3 methodology): the serial
and parallel variants alternate within one process — [A, B, A, B, ...] for
``--repeats`` rounds — and the reported numbers are per-variant medians, so
allocator drift, page-cache warmth, and co-tenant noise hit both sides alike.
Legacy checkpoint/alias baselines are reconstructed inline (write-then-rehash;
the old round-pairing alias loop) so the single-pass/vectorization wins are
measured against what actually shipped before, not just against workers=1.

Tiers (``--scale``):
    smoke   seconds-scale — wired into tier-1 (tests/test_parallel_host.py) so
            the harness itself cannot rot; numbers are NOT meaningful perf
    small   ~1 minute on a laptop
    medium  the default measurement tier (~100 MB matrices)
    large   the acceptance-criteria tier: >= 1 GB checkpoint matrix

Prints exactly ONE JSON line on stdout; tables go to stderr. bench.py embeds
the ``small`` tier's fields (producer_tokens_per_sec, ckpt_save_s, ckpt_load_s,
export_s, vocab_build_s, alias_build_s) into its round JSON.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCALES = {
    # n_words, vocab, rows, dim, pairs_per_batch, repeats
    "smoke": dict(n_words=120_000, vocab=3_000, rows=4_000, dim=64,
                  pairs_per_batch=4096, repeats=3),
    "small": dict(n_words=2_000_000, vocab=50_000, rows=65_536, dim=128,
                  pairs_per_batch=65_536, repeats=3),
    "medium": dict(n_words=8_000_000, vocab=200_000, rows=262_144, dim=384,
                   pairs_per_batch=65_536, repeats=3),
    "large": dict(n_words=16_000_000, vocab=1_000_000, rows=700_000, dim=384,
                  pairs_per_batch=65_536, repeats=3),  # 700k x 384 f32 ≈ 1.07 GB
}


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def interleaved(variants: dict, repeats: int) -> dict:
    """Run {name: thunk} alternating for ``repeats`` rounds; per-name median
    seconds. The thunks run in a fixed name order within each round."""
    times = {name: [] for name in variants}
    for _ in range(repeats):
        for name, thunk in variants.items():
            t0 = time.perf_counter()
            thunk()
            times[name].append(time.perf_counter() - t0)
    return {name: float(np.median(ts)) for name, ts in times.items()}


def make_corpus(n_words: int, vocab_size: int, sent_len: int = 40):
    rng = np.random.default_rng(0)
    zipf = 1.0 / (np.arange(vocab_size) + 10.0) ** 1.05
    ids = rng.choice(vocab_size, size=n_words, p=zipf / zipf.sum())
    words = np.char.add("w", ids.astype("U8"))
    return [list(words[i:i + sent_len]) for i in range(0, n_words, sent_len)]


def bench_vocab(sents, workers: int, repeats: int) -> dict:
    from glint_word2vec_tpu.data.vocab import build_vocab
    res = interleaved({
        "serial": lambda: build_vocab(sents, min_count=1),
        "parallel": lambda: build_vocab(sents, min_count=1, workers=workers),
    }, repeats)
    log(f"vocab build:   serial {res['serial']:.3f}s  "
        f"workers={workers} {res['parallel']:.3f}s  "
        f"({res['serial'] / max(res['parallel'], 1e-9):.2f}x)")
    return res


def _alias_legacy(counts: np.ndarray, power: float = 0.75):
    """The pre-round-8 alias builder verbatim (one-small-per-large round
    pairing with queue concatenation) — the legacy baseline the vectorized
    cumulative-matching sweep is measured against."""
    counts = np.asarray(counts, dtype=np.float64)
    weights = np.power(np.maximum(counts, 0.0), power)
    V = counts.size
    scaled = weights * (V / weights.sum())
    prob = np.ones(V, dtype=np.float64)
    alias = np.arange(V, dtype=np.int64)
    small = np.flatnonzero(scaled < 1.0)
    large = np.flatnonzero(scaled >= 1.0)
    while small.size and large.size:
        k = min(small.size, large.size)
        s, small = small[:k], small[k:]
        l = large[:k]
        prob[s] = scaled[s]
        alias[s] = l
        scaled[l] -= 1.0 - scaled[s]
        now_small = l[scaled[l] < 1.0]
        large = np.concatenate([l[scaled[l] >= 1.0], large[k:]])
        small = np.concatenate([small, now_small])
    prob[small] = 1.0
    prob[large] = 1.0
    return prob, alias


def bench_alias(vocab_size: int, workers: int, repeats: int) -> dict:
    from glint_word2vec_tpu.ops.sampler import build_alias_table
    counts = np.maximum(1e9 / (np.arange(vocab_size) + 10.0) ** 1.07, 5.0)
    res = interleaved({
        "legacy": lambda: _alias_legacy(counts),
        "serial": lambda: build_alias_table(counts, workers=1),
        "parallel": lambda: build_alias_table(counts, workers=workers),
    }, repeats)
    log(f"alias build (V={vocab_size:,d}): legacy {res['legacy']:.3f}s  "
        f"sweep {res['serial']:.3f}s  sweep+workers={workers} "
        f"{res['parallel']:.3f}s  "
        f"({res['legacy'] / max(res['parallel'], 1e-9):.2f}x vs legacy)")
    return res


def bench_producer(sents, pairs_per_batch: int, workers: int,
                   repeats: int) -> dict:
    """Feed-producer throughput: drain one full epoch_batches iteration and
    count RAW corpus tokens per second (the producer's input rate — the unit
    PERF.md §5's 9.5M pairs/s producer ceiling is about, modulo the pair
    expansion factor). Serial vs producer_workers=N, interleaved."""
    from glint_word2vec_tpu.data.pipeline import encode_sentences, epoch_batches
    from glint_word2vec_tpu.data.vocab import build_vocab
    vocab = build_vocab(sents, min_count=1)
    enc = encode_sentences(sents, vocab, 1000)
    n_tokens = sum(int(s.shape[0]) for s in enc)

    def drain(w: int):
        n = 0
        for b in epoch_batches(enc, vocab, pairs_per_batch=pairs_per_batch,
                               window=5, subsample_ratio=1e-3, seed=1,
                               iteration=1, producer_workers=w,
                               block_words=200_000):
            n += b.num_real_pairs
        return n

    res = interleaved({
        "serial": lambda: drain(1),
        "parallel": lambda: drain(workers),
    }, repeats)
    out = {
        "serial_tokens_per_sec": n_tokens / res["serial"],
        "parallel_tokens_per_sec": n_tokens / res["parallel"],
        "speedup": res["serial"] / max(res["parallel"], 1e-9),
    }
    log(f"producer:      serial {out['serial_tokens_per_sec']:,.0f} tok/s  "
        f"workers={workers} {out['parallel_tokens_per_sec']:,.0f} tok/s  "
        f"({out['speedup']:.2f}x)")
    return out


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def bench_checkpoint(rows: int, dim: int, workers: int, repeats: int,
                     workdir: str) -> dict:
    """Checkpoint save/load/export A/B at a [rows, dim] f32 matrix pair.

    save_legacy reconstructs the pre-round-8 writer cost shape exactly:
    serial np.save of every file followed by a full re-read through sha256
    (the two-pass digest). save_new is the shipped single-pass hashing writer
    at io_workers=N. Load verifies digests both ways; export writes the
    word2vec binary format."""
    import jax.numpy as jnp

    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.data.vocab import Vocabulary
    from glint_word2vec_tpu.models.word2vec import Word2VecModel
    from glint_word2vec_tpu.train import checkpoint as ckpt

    rng = np.random.default_rng(0)
    words = [f"w{i}" for i in range(rows)]
    counts = np.maximum(1e9 / (np.arange(rows) + 10.0) ** 1.07, 5.0).astype(
        np.int64)
    syn0 = rng.standard_normal((rows, dim), dtype=np.float32)
    syn1 = rng.standard_normal((rows, dim), dtype=np.float32)
    gb = 2 * syn0.nbytes / 1e9
    log(f"checkpoint matrices: 2 x [{rows:,d}, {dim}] f32 = {gb:.2f} GB")
    cfg_new = Word2VecConfig(vector_size=dim, io_workers=workers)
    cfg_old = Word2VecConfig(vector_size=dim, io_workers=1)
    p_new = os.path.join(workdir, "ck-new")
    p_old = os.path.join(workdir, "ck-old")

    def save_legacy():
        # the old writer verbatim: serial write, then re-read to hash
        if os.path.exists(p_old):
            shutil.rmtree(p_old)
        os.makedirs(p_old)
        with open(os.path.join(p_old, "words"), "w", encoding="utf-8") as f:
            for w in words:
                f.write(w + "\n")
        np.save(os.path.join(p_old, "counts.npy"), counts)
        np.save(os.path.join(p_old, "syn0.npy"), syn0)
        np.save(os.path.join(p_old, "syn1.npy"), syn1)
        digests = {}
        for name in ("words", "counts.npy", "syn0.npy", "syn1.npy"):
            digests[name] = _sha256_file(os.path.join(p_old, name))
        with open(os.path.join(p_old, "metadata.json"), "w") as f:
            json.dump({"format_version": 1, "vocab_size": rows,
                       "vector_size": dim, "digests": digests,
                       "config": cfg_old.to_dict(auto_markers=False),
                       "train_state": ckpt.TrainState(finished=True).to_dict(),
                       "framework": "glint_word2vec_tpu"}, f)

    def save_new():
        ckpt.save_model(p_new, words, counts, syn0, syn1, cfg_new)

    save = interleaved({"legacy": save_legacy, "new": save_new}, repeats)

    load = interleaved({
        "serial": lambda: ckpt.load_model(p_new, verify=True, io_workers=1),
        "parallel": lambda: ckpt.load_model(p_new, verify=True,
                                            io_workers=workers),
    }, repeats)

    vocab = Vocabulary.from_words_and_counts(words, counts)
    model = Word2VecModel(vocab, jnp.asarray(syn0), config=cfg_new)
    ex = os.path.join(workdir, "export.bin")
    export = interleaved({
        "serial": lambda: model.export_word2vec(ex, binary=True, io_workers=1),
        "parallel": lambda: model.export_word2vec(ex, binary=True,
                                                  io_workers=workers),
    }, repeats)
    model.stop()

    log(f"ckpt save:     legacy(2-pass serial) {save['legacy']:.3f}s  "
        f"new(1-pass, io_workers={workers}) {save['new']:.3f}s  "
        f"({save['legacy'] / max(save['new'], 1e-9):.2f}x)")
    log(f"ckpt load:     serial {load['serial']:.3f}s  "
        f"io_workers={workers} {load['parallel']:.3f}s  "
        f"({load['serial'] / max(load['parallel'], 1e-9):.2f}x)")
    log(f"export (bin):  serial {export['serial']:.3f}s  "
        f"io_workers={workers} {export['parallel']:.3f}s  "
        f"({export['serial'] / max(export['parallel'], 1e-9):.2f}x)")
    return {"save": save, "load": load, "export": export, "matrix_gb": gb}


def run(argv=None) -> dict:
    """Parse args, run the benches, return the result row WITHOUT printing —
    the embeddable entry point (bench.py merges the row into its own single
    stdout JSON line; only the CLI below prints)."""
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scale", choices=sorted(SCALES), default="medium")
    ap.add_argument("--smoke", action="store_true",
                    help="alias for --scale smoke (the tier-1 wiring)")
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--repeats", type=int, default=None,
                    help="interleaved repeats per variant (default: >= 3)")
    ap.add_argument("--workdir", default=None,
                    help="where checkpoint/export bytes land (default: a "
                         "fresh temp dir, deleted afterwards)")
    args = ap.parse_args(argv)
    scale = "smoke" if args.smoke else args.scale
    p = SCALES[scale]
    repeats = max(args.repeats or p["repeats"], 1)
    workers = args.workers
    log(f"hostbench scale={scale} workers={workers} repeats={repeats} "
        f"(host: {os.cpu_count()} cpus)")

    sents = make_corpus(p["n_words"], p["vocab"])
    vocab_res = bench_vocab(sents, workers, repeats)
    alias_res = bench_alias(p["vocab"] if scale == "smoke" else
                            max(p["vocab"], p["rows"]), workers, repeats)
    prod_res = bench_producer(sents, p["pairs_per_batch"], workers, repeats)

    workdir = args.workdir or tempfile.mkdtemp(prefix="glint-hostbench-")
    try:
        ck = bench_checkpoint(p["rows"], p["dim"], workers, repeats, workdir)
    finally:
        if args.workdir is None:
            shutil.rmtree(workdir, ignore_errors=True)

    result = {
        "scale": scale,
        "workers": workers,
        "repeats": repeats,
        "cpus": os.cpu_count(),
        "producer_tokens_per_sec": round(prod_res["parallel_tokens_per_sec"]),
        "producer_tokens_per_sec_serial": round(
            prod_res["serial_tokens_per_sec"]),
        "producer_speedup": round(prod_res["speedup"], 3),
        "ckpt_save_s": round(ck["save"]["new"], 4),
        "ckpt_save_legacy_s": round(ck["save"]["legacy"], 4),
        "ckpt_save_speedup": round(
            ck["save"]["legacy"] / max(ck["save"]["new"], 1e-9), 3),
        "ckpt_load_s": round(ck["load"]["parallel"], 4),
        "ckpt_load_serial_s": round(ck["load"]["serial"], 4),
        "export_s": round(ck["export"]["parallel"], 4),
        "export_serial_s": round(ck["export"]["serial"], 4),
        "ckpt_matrix_gb": round(ck["matrix_gb"], 3),
        "vocab_build_s": round(vocab_res["parallel"], 4),
        "vocab_build_serial_s": round(vocab_res["serial"], 4),
        "alias_build_s": round(alias_res["parallel"], 4),
        "alias_build_serial_s": round(alias_res["serial"], 4),
        "alias_build_legacy_s": round(alias_res["legacy"], 4),
    }
    return result


def main(argv=None) -> dict:
    result = run(argv)
    print(json.dumps(result))
    return result


if __name__ == "__main__":
    main()
