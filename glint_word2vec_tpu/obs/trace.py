"""Cross-process trace propagation: one causal id per fleet query.

PRs 6/9 built per-process observability (the sink, host spans, the flight
recorder); PRs 10-12 made the system a FLEET — a router process, N replica
processes, a trainer/ContinualRunner publishing checkpoints — whose
telemetry lands in N uncorrelated JSONL files. A hedged query's journey
(submit → attempt on r0 → hedge to r2 → r2 wins → r0's reply abandoned)
spans two processes and four spans; nothing tied them together. This module
is the correlation primitive:

- a **trace context** is two short strings, ``trace_id`` (one per client
  query, born at ``FleetRouter._request``) and ``parent_span`` (the span id
  of the enclosing region). It crosses the process boundary as a tiny
  ``"trace": {"tid": ..., "ps": ...}`` object on the JSON-lines replica
  protocol (tools/serve_checkpoint.py echoes request ids the same way);
- a **trace span** is one ``trace_span`` telemetry record in whichever
  process measured it (obs/schema.py): the router emits the per-query root
  span and one child span per retry/hedge attempt (labeled with the replica
  and its ``win``/``abandoned``/``failed`` outcome); the replica's batcher
  emits ``queue_wait`` and ``batch_service`` children; the service emits the
  ANN-probe/exact-scan child. ``tools/obs_collect.py`` merges the N files
  back into one causal timeline using each span's ``mono_ns`` clock and the
  per-process wall anchors (:func:`clock_anchor`).

Zero-cost when off (the ISSUE-13 acceptance bar, A/B'd by
``tools/telemetry_run.py --trace-overhead``): a router/service with no
telemetry sink never calls :func:`new_trace_id` — the hot submit path
allocates no context object, and requests cross the wire byte-identical to
the pre-trace protocol. Ids come from a process-scoped counter folded with
the pid and a boot nonce (no PRNG — the graftlint R2 discipline stays
untouched: tracing must never touch a sample stream's entropy source).
"""

from __future__ import annotations

import itertools
import os
import time
from typing import Dict, Optional

# process-scoped id source: pid + boot-time nonce + a monotone counter.
# Collision story: two processes share a prefix only on a pid reuse within
# the same nanosecond; within a process the counter is unique. itertools
# .count().__next__ is atomic under the GIL — no lock on the hot path.
_BOOT_NS = time.time_ns()
_COUNTER = itertools.count(1)
_PREFIX = f"{os.getpid():x}-{_BOOT_NS & 0xFFFFFFFF:08x}"


def new_trace_id() -> str:
    """One id per client query (the root of the causal tree)."""
    return f"t{_PREFIX}-{next(_COUNTER):x}"


def new_span_id() -> str:
    """One id per measured region; unique process-wide."""
    return f"s{_PREFIX}-{next(_COUNTER):x}"


def wire_context(trace_id: str, parent_span: str) -> Dict[str, str]:
    """The cross-process form: what rides the JSON-lines request as
    ``"trace"`` and what in-process replicas pass straight through."""
    return {"tid": trace_id, "ps": parent_span}


def clock_anchor() -> Dict[str, int]:
    """The per-process clock-alignment pair every ``run_start`` /
    ``serve_start`` / ``fleet_start`` record carries (additive schema
    fields): one simultaneous reading of the wall clock and the monotonic
    clock. Spans record ``mono_ns`` (monotonic — immune to NTP steps
    mid-run); the collector maps a span to fleet wall time as
    ``anchor.wall_ns + (span.mono_ns - anchor.mono_ns)``, which aligns
    processes whose wall clocks agree at anchor time and whose monotonic
    clocks drift independently afterwards (obs/collect.py)."""
    return {"wall_ns": time.time_ns(), "mono_ns": time.monotonic_ns()}


class SpanEmitter:
    """Binds a telemetry sink + process label into a one-call span writer.

    Every layer that measures spans (router, batcher, service) holds one of
    these — or ``None`` when telemetry is off, in which case callers skip
    the whole region-timing block (the zero-cost contract is enforced by
    "no emitter, no clock read", not by a no-op object on the hot path).
    Thread-safe by construction: it only calls ``sink.emit`` (locked) and
    touches no mutable state of its own.
    """

    __slots__ = ("_sink", "process")

    def __init__(self, sink, process: str):
        self._sink = sink
        self.process = process

    def emit(self, trace_id: str, name: str, start_mono_ns: int,
             dur_ns: int, parent: Optional[str] = None,
             span_id: Optional[str] = None, **attrs) -> str:
        """Write one ``trace_span`` record; returns the span id (callers
        pass it as the ``parent`` of child spans, possibly across the
        wire). ``attrs`` are the additive labels — ``replica``, ``outcome``,
        ``op`` — the schema type-checks when present."""
        sid = span_id or new_span_id()
        self._sink.emit(
            "trace_span", trace_id=trace_id, span=sid, name=name,
            mono_ns=int(start_mono_ns), dur_ns=int(dur_ns),
            process=self.process,
            **({"parent": parent} if parent else {}), **attrs)
        return sid


def service_process_name(kind: str = "serve") -> str:
    """Default process label for span/anchor records (overridable by the
    CLI): stable within a process, distinguishable across a fleet."""
    return f"{kind}-{os.getpid()}"


def emit_publish(sink, checkpoint_path: str, step: int,
                 publisher: str = "trainer") -> Optional[str]:
    """The publish-side correlation record: one ``publish`` telemetry
    record carrying the freshly-written checkpoint's ``publish_sig`` (the
    same ``mtime_ns-inode-size`` string the serving tier's watcher and the
    fleet router compare — serve/reload.publish_signature), so the
    collector can link trainer/ContinualRunner save → watcher detect →
    per-replica drain+reload as ONE causal chain keyed by the signature.
    Returns the signature string (None when the path is mid-swap/absent —
    nothing is emitted then; the next save re-anchors)."""
    from glint_word2vec_tpu.serve.reload import (
        publish_signature, publish_signature_str)
    sig_str = publish_signature_str(publish_signature(checkpoint_path))
    if sig_str is None or sink is None:
        return None
    sink.emit("publish", publish_sig=sig_str,
              checkpoint=checkpoint_path, step=int(step),
              publisher=publisher)
    return sig_str
