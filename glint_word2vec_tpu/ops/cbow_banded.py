"""Banded CBOW step: O(B) context gather/scatter via sentence-ordered prefix sums.

The scatter formulation (:func:`glint_word2vec_tpu.ops.sgns.cbow_step_shared_core`)
treats each example's context window as an unordered [B, C] index set: it gathers
``syn0[contexts]`` and scatters ``d_ctx`` as **B·C rows** (~655k at B=64k, C≈10).
PERF.md §2 prices the scatter emitter at ~27–39 ns per update row, so those rows —
not compute — are the measured 33.6 ms CBOW step (BENCH_r05).

But CBOW batches are sliding windows over the *kept-token stream*: when batch
position b holds kept token b (sentence-contiguous feed), both directions of the
context traffic are **banded sums over batch positions**:

- forward: ``hidden_b = (Σ_{j=b-l_b}^{b+r_b} e_j − e_b) / n_b`` — an interval sum,
  i.e. one difference of an inclusive prefix sum ``S`` over the gathered rows:
  ``S[b+r_b] − S[b−l_b−1] − e_b``;
- backward: position j receives ``Σ_{b: j ∈ [b−l_b, b+r_b]} d_hidden_b / n_b`` —
  the classic difference-array trick: add ``g_b = d_hidden_b/n_b`` at interval
  start ``b−l_b``, subtract it at ``b+r_b+1``, prefix-sum, then remove the
  self-term ``g_b`` at b.

Cost: ONE [T]-row ``syn0`` gather + two [T, D] prefix sums (the two-level
triangular-matmul form from ops/pairgen, ~0.5 ms each at 64k×384 on v5e) + the
interval-endpoint accumulation + [T]-row scatters back into syn0/syn1 — ~3–4·B
update rows total instead of ~11·B, which the §2 cost model prices at ≥2× CBOW
examples/s (PERF.md §9 has the full accounting).

Window intervals never cross sentence boundaries (``device_cbow_windows`` clamps
them via the start bits), so prefix-sum *differences* are exact per sentence even
though the prefix runs over the whole block; the same argument makes one flat
prefix correct across the [Sd, T] → [Sd·T] segment concatenation the trainer
feeds (intervals are in-block by construction, so cross-segment prefix mass
cancels in every difference).

Precision: prefix sums accumulate in ``promote_types(param_dtype, float32)`` —
a bf16 prefix over 64k rows would lose the interval in the cancellation; float32
keeps the relative error of an ~10-row interval at ~1e-5, far below SGD noise
(the float64 CPU equivalence suite in tests/test_cbow_banded.py pins the math).

``duplicate_scaling=True`` is NOT supported here — its mean-update bookkeeping
is per-occurrence-count over the materialized context sets; config validation
routes that combination to the scatter path (the selection matrix lives at
trainer._build_step).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from glint_word2vec_tpu.ops.sgns import (
    EmbeddingPair,
    StepMetrics,
    Stabilizers,
    _log_sigmoid,
    _mask_sentinel,
    _sigmoid,
    clip_update_rows,
    stabilize_rows,
)

# above this window the unrolled shifted-add endpoint accumulation (2·window
# fused [T, D] terms) loses to two plain scatter-adds of T rows each
_SHIFT_UNROLL_MAX_WINDOW = 16


def cumsum_rows(x: jax.Array) -> jax.Array:
    """Inclusive prefix sum along axis 0 of a [T, D] array.

    The float twin of ops/pairgen._cumsum_i32: XLA's 1-D cumulative ops cost
    ~0.45 ms at 28k elements on TPU, so the within-chunk prefix runs as a
    [128, 128] triangular matmul on the MXU and only the [T/128, D] chunk
    totals take the (tiny) native cumsum. Unlike the int variant there is no
    exactness window — callers pick an accumulation dtype wide enough for
    their cancellation needs (the banded step uses ≥ float32).
    """
    T, D = x.shape
    chunk = 128
    rows = -(-T // chunk)
    xp = jnp.pad(x, ((0, rows * chunk - T), (0, 0))).reshape(rows, chunk, D)
    tri = jnp.tril(jnp.ones((chunk, chunk), x.dtype))  # [i, j] = 1 iff j <= i
    within = jnp.einsum("ij,rjd->rid", tri, xp)        # inclusive within-chunk
    totals = within[:, -1, :]                          # [rows, D]
    # graftlint: disable=R4 -- accumulation dtype is the CALLER's contract (docstring above); both call sites pass >=f32 and are R4-checked there
    offs = jnp.cumsum(totals, axis=0) - totals         # exclusive chunk offsets
    return (within + offs[:, None, :]).reshape(rows * chunk, D)[:T]


def _band_endpoint_delta(
    g: jax.Array,      # [T, D] per-example spread gradient (masked rows are 0)
    left: jax.Array,   # int32 [T]
    right: jax.Array,  # int32 [T]
    window: int,
) -> jax.Array:
    """The difference array of the banded backward accumulation: +g_b at each
    interval start ``b−left_b``, −g_b at each one-past-end ``b+right_b+1``
    (ends falling at T are dropped — their prefix mass is never read).

    Since ``left ∈ [0, window)`` and ``right+1 ∈ [1, window]``, small windows
    realize both endpoint adds as 2·window statically-unrolled shifted
    masked adds (pure elementwise — XLA fuses them into one pass, no scatter
    rows at all); large windows fall back to one 2T-row scatter-add, still
    ~5× fewer scatter rows than the B·C formulation."""
    T, D = g.shape
    t = jnp.arange(T, dtype=jnp.int32)
    if window > _SHIFT_UNROLL_MAX_WINDOW:
        idx = jnp.concatenate([t - left, t + right + 1])
        upd = jnp.concatenate([g, -g])
        return jnp.zeros((T + 1, D), g.dtype).at[idx].add(
            upd, mode="drop")[:T]
    # start marks: g_b lands at j = b − left_b  ⇔  left[j+d] == d, d ∈ [0, W)
    gs = jnp.pad(g, ((0, window), (0, 0)))
    ls = jnp.pad(left, (0, window), constant_values=-1)
    delta = jnp.zeros((T, D), g.dtype)
    for d in range(window):
        sel = (ls[d:d + T] == d).astype(g.dtype)[:, None]
        delta = delta + gs[d:d + T] * sel
    # end marks: g_b removed at j = b + right_b + 1  ⇔  right[j−d] == d−1,
    # d ∈ [1, W] (legacy right ≤ W−2, symmetric ≤ W−1 — both covered)
    ge = jnp.pad(g, ((window, 0), (0, 0)))
    re = jnp.pad(right, (window, 0), constant_values=-2)
    for d in range(1, window + 1):
        sel = (re[window - d:window - d + T] == d - 1).astype(g.dtype)[:, None]
        delta = delta - ge[window - d:window - d + T] * sel
    return delta


def cbow_step_banded_core(
    params: EmbeddingPair,
    tokens: jax.Array,       # int32 [T] — kept tokens, sentence-contiguous
    left: jax.Array,         # int32 [T] — context extent left (in-sentence)
    right: jax.Array,        # int32 [T] — context extent right
    center_mask: jax.Array,  # float32 [T] — 1.0 for slots trained as centers
    token_mask: jax.Array,   # float32 [T] — 1.0 for valid token slots
    negatives: jax.Array,    # int32 [P] — pre-drawn shared pool
    alpha: jax.Array,
    num_negatives: int,
    window: int,
    sigmoid_mode: str = "exact",
    compute_dtype: jnp.dtype = jnp.float32,
    logits_dtype: jnp.dtype = jnp.float32,
    with_metrics: bool = True,
    stabilizers: Optional[Stabilizers] = None,
) -> Tuple[EmbeddingPair, StepMetrics]:
    """Banded CBOW update — mathematically the shared-pool scatter step
    (:func:`~glint_word2vec_tpu.ops.sgns.cbow_step_shared_core`) on the example
    set {slot b : center_mask_b = 1, left_b + right_b > 0} with contexts
    ``tokens[b−left_b : b+right_b+1] \\ {b}``, identical up to floating-point
    summation order (asserted by tests/test_cbow_banded.py in float64).

    (left, right) come from :func:`~glint_word2vec_tpu.ops.pairgen.device_cbow_windows`
    and are guaranteed in-range (``b−left_b ≥ 0``, ``b+right_b < T``) and
    in-sentence. Halo slots carry ``center_mask 0`` but ``token_mask 1``: they
    train no example this block yet still receive their context gradient from
    this block's core centers (their remaining gradient arrives in the block
    where they are core — each (center, context) link is applied exactly once
    across the overlapping feed).
    """
    syn0, syn1 = params
    T = tokens.shape[0]
    P = negatives.shape[0]
    t = jnp.arange(T, dtype=jnp.int32)
    pf = jnp.promote_types(syn0.dtype, jnp.float32)  # prefix accumulation dtype

    ctx_n_i = left + right
    has_ctx = (ctx_n_i > 0).astype(jnp.float32)
    live = center_mask * has_ctx                                    # [T]

    # -- forward: windowed context mean via one prefix-sum difference ---------
    e = syn0[tokens]                                                # [T, D]
    S = cumsum_rows(e.astype(pf))                                   # [T, D]
    Spad = jnp.concatenate([jnp.zeros((1, S.shape[1]), pf), S])     # S[<i] sums
    ctx_sum = Spad[t + right + 1] - Spad[t - left] - e.astype(pf)
    ctx_n = jnp.maximum(ctx_n_i, 1).astype(pf)
    hidden = (ctx_sum / ctx_n[:, None]).astype(compute_dtype)       # [T, D]

    # -- shared-pool positive/negative chain, unchanged from the scatter step
    tok_i = tokens.astype(jnp.int32)
    e_out = syn1[tokens].astype(compute_dtype)                      # [T, D]
    Z = syn1[negatives].astype(compute_dtype)                       # [P, D]
    f_pos = jnp.sum(hidden * e_out, axis=-1).astype(jnp.float32)
    f_neg = (hidden @ Z.T).astype(logits_dtype)                     # [T, P]
    neg_valid = (negatives[None, :] != tok_i[:, None]).astype(logits_dtype) \
        * center_mask[:, None].astype(logits_dtype)

    g_pos = (1.0 - _sigmoid(f_pos, sigmoid_mode)) * alpha * live
    g_neg = ((0.0 - _sigmoid(f_neg, sigmoid_mode))
             * jnp.asarray(alpha, logits_dtype) * neg_valid
             * has_ctx[:, None].astype(logits_dtype)
             * jnp.asarray(num_negatives / P, logits_dtype))

    gp = g_pos[:, None].astype(compute_dtype)
    gn = g_neg.astype(compute_dtype)
    d_hidden = gp * e_out + gn @ Z                                  # [T, D]
    d_out = gp * hidden
    d_Z = gn.T @ hidden                                             # [P, D]
    if stabilizers is not None and stabilizers.update_clip:
        # clip BEFORE the mean-convention split/spread — the same quantity
        # the scatter formulation clips (ops/sgns.py), so the two CBOW
        # formulations stay equivalent with stabilizers on. d_Z never clips
        # (Stabilizers docstring).
        d_hidden = clip_update_rows(d_hidden, stabilizers.update_clip)
        d_out = clip_update_rows(d_out, stabilizers.update_clip)

    # -- backward: banded spread of d_hidden/n via difference array + prefix --
    g_row = d_hidden.astype(pf) / ctx_n[:, None]                    # [T, D]
    delta = _band_endpoint_delta(g_row, left, right, window)
    d_ctx = (cumsum_rows(delta) - g_row) * token_mask[:, None].astype(pf)

    dtype = syn0.dtype
    new_syn0 = syn0.at[tokens].add(d_ctx.astype(dtype))
    new_syn1 = syn1.at[tokens].add(d_out.astype(dtype))
    new_syn1 = new_syn1.at[negatives].add(d_Z.astype(dtype))
    if stabilizers is not None and stabilizers.post_pass:
        # touched sets of THIS formulation: syn0 at every valid token slot
        # (each is a potential context row of the band — a context-less token
        # sees a zero update but is still in the scatter's index list, so it
        # clamps/decays here where the scatter formulation would skip it: the
        # one documented touched-set difference between the formulations),
        # syn1 at the live centers plus the whole shared pool
        V = syn0.shape[0]
        enable = (token_mask.sum() > 0).astype(jnp.float32)
        new_syn0 = stabilize_rows(
            new_syn0, _mask_sentinel(tokens, token_mask, V), alpha,
            stabilizers, enable)
        idx1 = jnp.concatenate(
            [_mask_sentinel(tokens, live, V), negatives])
        new_syn1 = stabilize_rows(new_syn1, idx1, alpha, stabilizers, enable)

    if with_metrics:
        denom = jnp.maximum(live.sum(), 1.0)
        loss = (-_log_sigmoid(f_pos) * live
                - jnp.sum(_log_sigmoid(-f_neg) * neg_valid
                          * has_ctx[:, None].astype(logits_dtype), axis=-1,
                          dtype=jnp.float32)
                * (num_negatives / P)).sum() / denom
        mean_f_pos = (f_pos * live).sum() / denom
    else:
        loss = mean_f_pos = jnp.float32(0.0)
    metrics = StepMetrics(
        loss=loss,
        mean_f_pos=mean_f_pos,
        pairs=live.sum(),
    )
    return EmbeddingPair(new_syn0, new_syn1), metrics
