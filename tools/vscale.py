"""Vocabulary-scaling probe beyond the bench's V=1M row — BASELINE config 4's
10M-vocab regime on ONE chip.

BASELINE config 4 (Common Crawl, 10M vocab, d=300, v5e-64) sizes the embedding
pair at 10M x 384 x 2 x 2B(bf16) = 15.4 GB — more than one v5e's 16 GB HBM once
step workspace is counted, which is exactly WHY that config names a 64-chip pod
(row-sharding divides rows per chip; parallel/mesh.py). What one chip CAN answer
is how the per-row costs scale to 10M rows, measured here at a width that fits
(d=128 -> pair = 5.1 GB bf16, honestly labeled):

    step                gather/scatter address spread over 10M rows
    alias table build   O(2V) host cost at 10M entries
    find_synonyms       matvec + top-k over 10M rows

Run: python tools/vscale.py [--vocab 10000000] [--dim 128] [--batch 65536]
     [--pool 512]. Results recorded in PERF.md §6.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab", type=int, default=10_000_000)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--batch", type=int, default=65536)
    ap.add_argument("--pool", type=int, default=512)
    ap.add_argument("--skip-step", action="store_true")
    args = ap.parse_args()
    V, D = args.vocab, args.dim

    import bench

    counts = bench.zipf_counts(V)

    t0 = time.perf_counter()
    from glint_word2vec_tpu.ops.sampler import build_alias_table
    build_alias_table(counts)
    print(f"V={V:,} alias table build: {time.perf_counter() - t0:.2f}s "
          "(host, O(2V))", file=sys.stderr)

    if not args.skip_step:
        # bench.bench_step pads dim via PAD_D; override for the reduced width
        old_pad = bench.PAD_D
        bench.PAD_D = D
        try:
            bench.bench_step(counts, b=args.batch, pool=args.pool,
                             dtype="bfloat16", param_dtype="bfloat16",
                             logits_dtype="bfloat16", v=V,
                             label_extra=f" d={D}")
        finally:
            bench.PAD_D = old_pad

    # find_synonyms over 10M rows (embedding created ON device — a host array
    # would time the transfer wire, not the op)
    import jax
    import jax.numpy as jnp

    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.data.vocab import Vocabulary
    from glint_word2vec_tpu.models.word2vec import Word2VecModel
    words = np.char.add("w", np.arange(V).astype("U8"))
    vocab = Vocabulary.from_words_and_counts(list(words), counts.astype(np.int64))
    syn0 = (jax.random.normal(jax.random.key(1), (V, D), jnp.bfloat16) * 0.1
            ).astype(jnp.float32)
    syn0.block_until_ready()
    model = Word2VecModel(vocab, syn0, syn1=None,
                          config=Word2VecConfig(vector_size=D))
    model.find_synonyms("w0", 10)  # compile + warm
    t0 = time.perf_counter()
    for i in range(5):
        model.find_synonyms(f"w{i + 1}", 10)
    ms = (time.perf_counter() - t0) / 5 * 1e3
    print(f"V={V:,} find_synonyms(top-10): {ms:.1f} ms/query", file=sys.stderr)
    model.stop()


if __name__ == "__main__":
    main()
