"""Fleet SLOs: availability + latency objectives with multi-window burn rates.

The fleet-kill chaos drill asserted "zero failed queries" as a test-local
counter; a production tier needs the same statement as a MEASURED service
objective an alert can page on before the error budget is gone. This module
is the standard SRE formulation (multi-window, multi-burn-rate alerting —
the Google SRE workbook's chapter 5 shape) computed over the router's own
per-query samples:

- **availability SLO**: fraction of client queries answered (a query that
  exhausted the retry deadline, or was refused by fleet-level load shedding,
  is BAD — retries that succeeded are invisible here by design: the SLO
  measures what the CALLER saw, the attempt-level churn is the router's
  ``retries`` counter and the per-attempt trace spans);
- **latency SLO**: fraction of answered queries under ``latency_ms``
  (answered-slow is a different failure than not-answered — a saturating
  fleet degrades through the latency SLO first, which is the early warning);
- **burn rate** per window = (bad fraction in the window) / (1 - objective):
  burn 1.0 spends the budget exactly at the objective's rate; burn 14.4 over
  the short window is the classic page-now threshold. Two windows (short ~
  fast detection, long ~ sustained burn) so a transient blip and a steady
  leak are distinguishable — the drill uses seconds-scale windows, the
  defaults are production-scale, both are the same math.

The tracker is a bounded ring of ``(mono_s, ok, within_latency)`` samples
under one lock — O(1) per query, O(ring) per snapshot (snapshots are scrape
/ drill cadence, not query cadence). Window computations walk backwards from
now, so clock steps never corrupt it (monotonic time only).

``FleetRouter`` owns one tracker and exposes its snapshot as
``stats()["slo"]``; ``statusd.fleet_prometheus_text`` renders the
``glint_serve_fleet_slo_*`` gauges; ``tools/obs_collect.py`` recomputes the
same objectives offline over a merged fleet timeline (one math, two
surfaces — :func:`burn_rates_from_samples` is shared).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple
from glint_word2vec_tpu.lockcheck import make_lock


class SloObjectives:
    """The objective set (constructor-level knobs, not config fields: the
    SLO is a property of a DEPLOYMENT's router, not of a trained model —
    unlike the serve_* knobs it does not travel with the checkpoint)."""

    __slots__ = ("availability", "latency_ms", "latency_target",
                 "short_window_s", "long_window_s")

    def __init__(self, availability: float = 0.999,
                 latency_ms: float = 250.0,
                 latency_target: float = 0.99,
                 short_window_s: float = 300.0,
                 long_window_s: float = 3600.0):
        if not 0.0 < availability < 1.0:
            raise ValueError(
                f"availability objective must be in (0, 1) but got "
                f"{availability}")
        if not 0.0 < latency_target < 1.0:
            raise ValueError(
                f"latency target must be in (0, 1) but got {latency_target}")
        if latency_ms <= 0:
            raise ValueError(
                f"latency_ms must be positive but got {latency_ms}")
        if not 0 < short_window_s <= long_window_s:
            raise ValueError(
                f"windows must satisfy 0 < short <= long but got "
                f"{short_window_s}/{long_window_s}")
        self.availability = float(availability)
        self.latency_ms = float(latency_ms)
        self.latency_target = float(latency_target)
        self.short_window_s = float(short_window_s)
        self.long_window_s = float(long_window_s)


def burn_rates_from_samples(
    samples: Sequence[Tuple[float, bool]], now: float, objective: float,
    windows: Sequence[Tuple[str, float]],
) -> Dict[str, Dict[str, Any]]:
    """The shared burn math: ``samples`` is ``(t, good)`` on ANY one clock
    ``now`` belongs to (the live tracker passes monotonic, the collector
    passes anchored wall seconds). Per window: good/bad counts, bad
    fraction, and burn = bad_fraction / (1 - objective). A window with no
    samples reports burn 0.0 (no traffic burns no budget) with
    ``samples: 0`` so consumers can tell silence from health."""
    budget = 1.0 - objective
    out: Dict[str, Dict[str, Any]] = {}
    for name, width in windows:
        lo = now - width
        good = bad = 0
        for t, ok in reversed(samples):
            if t < lo:
                break  # samples arrive in time order; the rest are older
            if ok:
                good += 1
            else:
                bad += 1
        n = good + bad
        bad_frac = (bad / n) if n else 0.0
        out[name] = {
            "window_s": width,
            "samples": n,
            "bad": bad,
            "bad_fraction": round(bad_frac, 6),
            "burn_rate": round(bad_frac / budget, 3) if budget else None,
        }
    return out


class SloTracker:
    """Per-query availability/latency sample ring + burn-rate snapshots."""

    def __init__(self, objectives: Optional[SloObjectives] = None,
                 ring: int = 65536):
        self.objectives = objectives or SloObjectives()
        self._lock = make_lock("obs.slo")
        # (mono_s, answered, within_latency) — bounded: at the ring size a
        # million-QPS tier still holds the full short window at drill scale,
        # and the TOTAL counters below never lose history
        self._samples: deque = deque(maxlen=int(ring))
        self._total = 0
        self._total_bad = 0
        self._total_slow = 0

    def note(self, ok: bool, latency_s: Optional[float] = None) -> None:
        """One client-query outcome: ``ok=False`` is a deadline-exhausted
        failure or a fleet-level refusal (the caller got no answer);
        ``latency_s`` is the end-to-end latency of an ANSWERED query."""
        within = bool(ok and latency_s is not None
                      and latency_s * 1000.0 <= self.objectives.latency_ms)
        with self._lock:
            self._samples.append((time.monotonic(), bool(ok), within))
            self._total += 1
            if not ok:
                self._total_bad += 1
            elif not within:
                self._total_slow += 1

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The SLO gauge set (stats()/statusd/fleet_slo record shape)."""
        obj = self.objectives
        with self._lock:
            samples = list(self._samples)
            total, bad, slow = self._total, self._total_bad, self._total_slow
        now = time.monotonic() if now is None else now
        windows = (("short", obj.short_window_s), ("long", obj.long_window_s))
        avail_burn = burn_rates_from_samples(
            [(t, ok) for t, ok, _ in samples], now, obj.availability, windows)
        # latency SLI is conditioned on ANSWERED queries: an unanswered
        # query already burned the availability budget — double-counting it
        # as "slow" would make the two SLOs redundant instead of layered
        lat_burn = burn_rates_from_samples(
            [(t, within) for t, ok, within in samples if ok], now,
            obj.latency_target, windows)
        answered = total - bad
        return {
            "objective_availability": obj.availability,
            "objective_latency_ms": obj.latency_ms,
            "objective_latency_target": obj.latency_target,
            "samples": total,
            "availability": round(1.0 - bad / total, 6) if total else None,
            "latency_good_fraction": (round(1.0 - slow / answered, 6)
                                      if answered else None),
            "availability_burn": avail_burn,
            "latency_burn": lat_burn,
            # budget remaining over the tracker's whole lifetime: 1.0 =
            # untouched, 0.0 = spent exactly, negative = blown
            "budget_remaining": (
                round(1.0 - (bad / total) / (1.0 - obj.availability), 4)
                if total else None),
        }

    def within_budget(self, snapshot: Optional[Dict[str, Any]] = None
                      ) -> bool:
        """The gate predicate the chaos drills and ``obs_collect --gate``
        assert: every burn window at or under 1.0 (spending faster than the
        objective allows is the alarm, regardless of absolute counts)."""
        snap = snapshot or self.snapshot()
        for burn in (snap["availability_burn"], snap["latency_burn"]):
            for w in burn.values():
                if w["burn_rate"] is not None and w["burn_rate"] > 1.0:
                    return False
        return True


def slo_gauge_lines(gauge, snap: Dict[str, Any]) -> None:
    """Render one SLO snapshot through a ``gauge(name, value, labels)``
    callable — shared by ``statusd.fleet_prometheus_text`` (live) so the
    gauge names have exactly one owner (docs/observability.md §9 table)."""
    if not snap:
        return
    gauge("glint_serve_fleet_slo_availability_objective",
          snap.get("objective_availability"))
    gauge("glint_serve_fleet_slo_availability", snap.get("availability"))
    gauge("glint_serve_fleet_slo_latency_objective_ms",
          snap.get("objective_latency_ms"))
    gauge("glint_serve_fleet_slo_latency_good_fraction",
          snap.get("latency_good_fraction"))
    gauge("glint_serve_fleet_slo_samples_total", snap.get("samples"))
    gauge("glint_serve_fleet_slo_budget_remaining",
          snap.get("budget_remaining"))
    for sli, key in (("availability", "availability_burn"),
                     ("latency", "latency_burn")):
        for window, w in (snap.get(key) or {}).items():
            gauge("glint_serve_fleet_slo_burn_rate", w.get("burn_rate"),
                  f'{{sli="{sli}",window="{window}"}}')


def flatten_burn(snap: Dict[str, Any]) -> Dict[str, Any]:
    """The compact form the ``fleet_slo`` telemetry record carries (full
    nested windows stay in stats()/statusd; the record is for trend lines)."""
    ab = snap.get("availability_burn") or {}
    lb = snap.get("latency_burn") or {}
    return {
        "objective": snap.get("objective_availability"),
        "availability": snap.get("availability"),
        "samples": int(snap.get("samples") or 0),
        "burn_short": (ab.get("short") or {}).get("burn_rate"),
        "burn_long": (ab.get("long") or {}).get("burn_rate"),
        "latency_good_fraction": snap.get("latency_good_fraction"),
        "latency_burn_short": (lb.get("short") or {}).get("burn_rate"),
    }


def slowest_k(items: List[Tuple[float, Any]], k: int) -> List[Any]:
    """Top-k by the float key, descending — the collector's exemplar
    selection (tiny helper here so collect.py and tests share one rule)."""
    return [x for _, x in sorted(items, key=lambda p: -p[0])[:max(0, k)]]
