"""Fault-tolerant serving fleet: N replicas behind one router (ISSUE 12).

The reference's mode-B deployment is a FLEET of standalone parameter
servers (PAPER.md §G1); our serving tier (serve/service.py) was one
process with no failure model above it — a replica that hangs, dies, or
reloads was the whole service. This module is the failure model:

- :class:`ReplicaSet` — spawns N ``tools/serve_checkpoint.py`` replica
  subprocesses watching the same checkpoint publish path (or adopts N
  in-process :class:`~.service.EmbeddingService` instances for tests and
  the bench), restarts dead processes, and gives each a uniform
  submit/wait client (:class:`SubprocessReplica` / :class:`InProcessReplica`).
- :class:`FleetRouter` — the full robustness stack in front of them:

  * **health probes** — a single prober thread sends each replica a cheap
    ``stats`` op every ``probe_s``: liveness AND staleness. A replica whose
    served publish generation (``publish_sig``) is behind the on-disk
    signature is DEGRADED, not dead — it still serves, but the router
    prefers fresh replicas.
  * **circuit breakers** (per replica) — closed → open after
    ``breaker_failures`` consecutive failures/timeouts; after
    ``breaker_reset_s`` the prober sends the half-open trial probe;
    success closes the breaker, failure reopens it. Client traffic is
    only ever routed to CLOSED breakers — the trial is the prober's job,
    so recovery costs zero client queries.
  * **deadline-budgeted retries** — a failed attempt retries on a
    DIFFERENT replica; once every eligible replica has been tried the
    loop backs off with decorrelated jitter (:func:`.reload
    .decorrelated_jitter`) and tries again until the deadline. A
    ``ServerOverloaded`` reply is "retry elsewhere, not here": the
    replica is marked saturated for its ``retry_after_s`` hint and the
    next attempt goes elsewhere immediately, no backoff.
  * **tail-latency hedging** (optional) — after a p99-derived delay with
    no response, the same query goes to a second replica; first response
    wins, the loser is abandoned (its late response is discarded by the
    reader). ``hedge_ms=-1`` derives the delay from the router's own
    measured p99 (re-derived every 64 samples, floored so hedges stay
    rare); ``0`` disables; ``>0`` is a fixed delay. The CIKM'16
    discipline keeps per-request payloads tiny, which is what makes the
    duplicate send cheap enough to be a default policy.
  * **graceful load shedding** — bulk traffic (``synonyms_batch``) sheds
    FIRST: it is refused while any healthy replica is saturated. Single
    queries are refused fast only when EVERY healthy replica is
    saturated (:class:`FleetOverloaded`, carrying the minimum
    ``retry_after_s`` hint across the fleet).
  * **rolling reload** — on a publish, the router drains and reloads
    replicas ONE AT A TIME (replicas are spawned with the watcher off;
    the router owns the reload trigger), so fleet capacity never drops
    below N-1. Each reload is issued only after the replica's in-flight
    count drained to zero (``drained_reloads`` asserts it per replica).

Thread inventory (graftlint R1 documented owners): each
:class:`SubprocessReplica` runs ONE stdout reader thread (it only pairs
responses to tickets by id — read-only on everything), and the router
runs ONE prober/orchestrator thread (probes, breaker trials, restarts,
rolling reloads — read-only on model params; it orders nothing in
training). Hedging is ticket-based and spawns no threads.

Driven end-to-end by ``tools/fleet_run.py --smoke`` and the
``fleet-kill`` chaos phase (``tools/chaos_run.py``); knobs are the
``serve_fleet_*`` rows in docs/configuration.md, resolved from the
checkpoint by :func:`fleet_knobs_from_checkpoint`.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from glint_word2vec_tpu.obs.slo import SloObjectives, SloTracker, flatten_burn
from glint_word2vec_tpu.lockcheck import make_lock
from glint_word2vec_tpu.obs.trace import (
    clock_anchor,
    new_span_id,
    new_trace_id,
    wire_context,
)
from glint_word2vec_tpu.serve.batcher import ServerOverloaded, ServiceClosed
from glint_word2vec_tpu.serve.reload import (
    decorrelated_jitter,
    publish_signature,
    publish_signature_str as _sig_str,
)

logger = logging.getLogger("glint_word2vec_tpu")

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class FleetOverloaded(ServerOverloaded):
    """Every healthy replica is saturated (or bulk traffic is being shed
    under pressure) — the FLEET-level 429. Subclasses
    :class:`ServerOverloaded` so existing single-service callers need no
    new except clause; ``retry_after_s`` is the minimum hint across the
    saturated replicas."""


class NoHealthyReplicas(RuntimeError):
    """The retry deadline expired without any replica answering — every
    breaker open/dead, or every attempt failed. Carries the last
    per-replica error as ``__cause__``."""


class ReplicaError(RuntimeError):
    """One replica failed an attempt (pipe broken, process dead, service
    closing, malformed reply). Router-internal: counted against that
    replica's breaker and retried elsewhere — callers see it only wrapped
    in :class:`NoHealthyReplicas` after the deadline."""


class _Saturated(Exception):
    """Router-internal: a replica answered ServerOverloaded. Not a breaker
    failure — the replica is healthy, just full."""

    def __init__(self, retry_after_s: Optional[float]):
        super().__init__("replica saturated")
        self.retry_after_s = retry_after_s


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


class CircuitBreaker:
    """Per-replica breaker: ``closed`` → ``open`` on ``fail_threshold``
    consecutive failures; after ``reset_s`` the next :meth:`begin_probe`
    moves to ``half-open`` (exactly one trial in flight); trial success
    closes, trial failure reopens and re-arms the cooldown. Transitions
    are recorded (bounded) and surfaced through ``on_transition`` for the
    ``fleet_breaker`` telemetry record."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half-open"

    def __init__(self, fail_threshold: int = 3, reset_s: float = 2.0,
                 on_transition=None):
        if fail_threshold <= 0:
            raise ValueError(
                f"fail_threshold must be positive but got {fail_threshold}")
        if reset_s <= 0:
            raise ValueError(f"reset_s must be positive but got {reset_s}")
        self.fail_threshold = int(fail_threshold)
        self.reset_s = float(reset_s)
        self._on_transition = on_transition
        self._lock = make_lock("fleet.breaker")
        self._state = self.CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        # bounded transition history, newest last: (from, to, reason)
        self.transitions: collections.deque = collections.deque(maxlen=64)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _move(self, to: str, reason: str):
        # under self._lock; returns the (from, to, reason) triple the caller
        # hands to _fire_transition AFTER releasing — the callback emits
        # telemetry (sink I/O), and holding the breaker lock across it made
        # every state change a breaker→sink held-while-blocking window
        # (graftrace: docs/static-analysis.md layer 4)
        frm, self._state = self._state, to
        self.transitions.append((frm, to, reason))
        return (frm, to, reason)

    def _fire_transition(self, t) -> None:
        if t is None:
            return
        cb = self._on_transition
        if cb is not None:
            try:
                cb(*t)
            except Exception:  # noqa: BLE001 — telemetry is best-effort
                logger.warning("breaker transition callback failed",
                               exc_info=True)

    def transitions_snapshot(self) -> list:
        """Transition history copied under the lock — iterating the deque
        while a breaker thread appends raises RuntimeError (the PR 12
        class)."""
        with self._lock:
            return list(self.transitions)

    def allows_traffic(self) -> bool:
        """Client traffic goes only to CLOSED breakers; OPEN/HALF_OPEN
        replicas recover through the prober's trial, costing zero client
        queries."""
        with self._lock:
            return self._state == self.CLOSED

    def probe_due(self) -> bool:
        """True when the breaker is OPEN and the cooldown elapsed — the
        prober should call :meth:`begin_probe` and send the trial."""
        with self._lock:
            return (self._state == self.OPEN
                    and time.monotonic() - self._opened_at >= self.reset_s)

    def begin_probe(self) -> bool:
        """OPEN (cooldown elapsed) → HALF_OPEN; returns False if another
        trial already holds the half-open slot."""
        t = None
        with self._lock:
            if (self._state == self.OPEN
                    and time.monotonic() - self._opened_at >= self.reset_s):
                t = self._move(self.HALF_OPEN, "cooldown elapsed, trial probe")
        self._fire_transition(t)
        return t is not None

    def record_success(self) -> None:
        t = None
        with self._lock:
            self._consecutive = 0
            if self._state == self.HALF_OPEN:
                t = self._move(self.CLOSED, "trial probe succeeded")
        self._fire_transition(t)

    def record_failure(self, reason: str = "") -> None:
        t = None
        with self._lock:
            now = time.monotonic()
            if self._state == self.HALF_OPEN:
                self._opened_at = now
                t = self._move(self.OPEN, f"trial failed: {reason}"[:200])
            elif self._state == self.CLOSED:
                self._consecutive += 1
                if self._consecutive >= self.fail_threshold:
                    self._opened_at = now
                    t = self._move(
                        self.OPEN,
                        f"{self._consecutive} consecutive failures "
                        f"(last: {reason})"[:200])
        self._fire_transition(t)


# ---------------------------------------------------------------------------
# replica clients (uniform submit/wait over two transports)
# ---------------------------------------------------------------------------


class FleetTicket:
    """One in-flight replica request: ``done`` is a ``threading.Event``
    (for the subprocess transport the reader sets it; the in-process
    transport shares the batcher ticket's own event), ``response`` the raw
    wire-shaped dict once resolved. Abandoning a ticket is free: the
    response, when it arrives, is popped and discarded."""

    __slots__ = ("id", "done", "response", "batcher_ticket")

    def __init__(self, tid: int):
        self.id = tid
        self.done = threading.Event()
        self.response: Optional[dict] = None
        self.batcher_ticket = None

    def resolve(self, response: dict) -> None:
        self.response = response
        self.done.set()


class SubprocessReplica:
    """One ``tools/serve_checkpoint.py`` child on the JSON-lines protocol,
    with request ids for out-of-order completion tracking (responses ARE
    in-order; ids let abandoned/hedge-loser responses be discarded instead
    of corrupting FIFO pairing). ``restart()`` relaunches the process in
    place so router bookkeeping keeps its object identity."""

    def __init__(self, name: str, checkpoint: str, ann: bool = False,
                 nprobe: Optional[int] = None,
                 python: str = sys.executable,
                 env: Optional[Dict[str, str]] = None,
                 stderr_path: str = "", telemetry_path: str = ""):
        self.name = name
        self._checkpoint = checkpoint
        self._ann = bool(ann)
        self._nprobe = nprobe
        self._python = python
        self._env = env
        self._stderr_path = stderr_path
        # per-replica sink (ISSUE 13): the replica's serve_*/trace_span
        # records + its .blackbox.json dump — the collector's inputs
        self.telemetry_path = telemetry_path
        self._proc: Optional[subprocess.Popen] = None
        self._reader: Optional[threading.Thread] = None
        self._wlock = make_lock("fleet.replica.write")
        self._plock = make_lock("fleet.replica.pending")
        self._pending: Dict[int, FleetTicket] = {}
        self._next_id = 0
        self.ready = threading.Event()
        self.restarts = 0
        self.leaked_threads = 0

    # -- lifecycle --------------------------------------------------------------------

    def start(self) -> "SubprocessReplica":
        if self._proc is not None and self._proc.poll() is None:
            return self
        cmd = [self._python,
               os.path.join(_REPO, "tools", "serve_checkpoint.py"),
               self._checkpoint]
        if self._ann:
            cmd.append("--ann")
        if self._nprobe:
            cmd += ["--nprobe", str(self._nprobe)]
        if self.telemetry_path:
            cmd += ["--telemetry", self.telemetry_path,
                    "--process-name", self.name]
        env = dict(self._env if self._env is not None else os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        stderr = (open(self._stderr_path, "ab")
                  if self._stderr_path else subprocess.DEVNULL)
        try:
            self._proc = subprocess.Popen(
                cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=stderr, env=env, text=True, bufsize=1)
        finally:
            if self._stderr_path:
                stderr.close()
        self.ready.clear()
        # R1 documented owner: pairs responses to tickets by id; read-only
        self._reader = threading.Thread(
            target=self._read_loop, args=(self._proc,),
            name=f"glint-fleet-reader-{self.name}", daemon=True)
        self._reader.start()
        return self

    def restart(self) -> "SubprocessReplica":
        """Relaunch after a death (the ReplicaSet's respawn path). Pending
        tickets were already failed by the reader's EOF sweep."""
        self.kill()
        self.restarts += 1
        return self.start()

    def wait_ready(self, timeout: float = 120.0) -> bool:
        return self.ready.wait(timeout)

    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    @property
    def pid(self) -> Optional[int]:
        return self._proc.pid if self._proc is not None else None

    def kill(self) -> None:
        """SIGKILL the child (the chaos drill's fault). Idempotent."""
        if self._proc is not None and self._proc.poll() is None:
            try:
                self._proc.kill()
            except OSError:
                pass
            self._proc.wait()

    def terminate(self) -> None:
        """SIGTERM the child — the GRACEFUL half of the kill surface (the
        fleet-kill drill's dump leg): a telemetry-on replica writes its
        ``.blackbox.json`` flight-recorder dump before dying (tools/
        serve_checkpoint.py's handler), which SIGKILL can never exercise.
        Does not wait — the prober's dead-process path owns the respawn."""
        if self._proc is not None and self._proc.poll() is None:
            try:
                self._proc.terminate()
            except OSError:
                pass

    def close(self) -> int:
        """Kill the child and join the stdout reader with a bounded
        timeout; a reader that misses the bound is counted in
        ``leaked_threads`` (surfaced per-replica by the router's stats).
        Idempotent — a second close re-reports the same count."""
        self.kill()
        r, self._reader = self._reader, None
        if r is not None:
            r.join(timeout=10)
            if r.is_alive():
                self.leaked_threads += 1
                logger.warning("%s: reader thread leaked (join timeout)",
                               self.name)
        return self.leaked_threads

    # -- request/response -------------------------------------------------------------

    def submit(self, req: dict) -> FleetTicket:
        proc = self._proc
        if proc is None or proc.poll() is not None:
            raise ReplicaError(f"{self.name}: process not running")
        with self._plock:
            tid = self._next_id
            self._next_id += 1
            t = FleetTicket(tid)
            self._pending[tid] = t
        line = json.dumps({**req, "id": tid})
        try:
            with self._wlock:
                proc.stdin.write(line + "\n")
                proc.stdin.flush()
        except (OSError, ValueError) as e:  # broken pipe / closed stdin
            with self._plock:
                self._pending.pop(tid, None)
            raise ReplicaError(f"{self.name}: write failed ({e})") from e
        return t

    def wait(self, ticket: FleetTicket, timeout: float) -> dict:
        if not ticket.done.wait(timeout):
            raise TimeoutError(
                f"{self.name}: no response within {timeout:.2f}s")
        resp = ticket.response
        if resp is None or resp.get("_dead"):
            raise ReplicaError(f"{self.name}: process exited mid-request")
        return resp

    def abandon(self, ticket: FleetTicket) -> None:
        """Hedge-loser/deadline bookkeeping: nothing to cancel on the wire
        (the replica will answer; the reader discards by id)."""
        with self._plock:
            self._pending.pop(ticket.id, None)

    def _read_loop(self, proc: subprocess.Popen) -> None:
        try:
            for line in proc.stdout:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    logger.warning("%s: unparseable reply %.120r",
                                   self.name, line)
                    continue
                if obj.get("ready"):
                    self.ready.set()
                    continue
                tid = obj.pop("id", None)
                with self._plock:
                    t = self._pending.pop(tid, None)
                if t is not None:
                    t.resolve(obj)
        finally:
            # EOF: the process died — fail everything still in flight so
            # waiting callers turn into breaker failures, not timeouts
            self.ready.clear()
            with self._plock:
                pending, self._pending = list(self._pending.values()), {}
            for t in pending:
                t.resolve({"_dead": True})


class InProcessReplica:
    """An adopted in-process :class:`EmbeddingService` behind the same
    submit/wait surface (tests, and the bench's fleet arm where N
    subprocesses would swamp a small host). Single-query submits ride the
    service's async batcher ticket — its ``done`` event makes in-process
    replicas hedgeable; other ops resolve inline at submit."""

    def __init__(self, name: str, service):
        self.name = name
        self.service = service
        self._next_id = 0
        self.restarts = 0
        self.leaked_threads = 0

    def start(self) -> "InProcessReplica":
        return self

    def wait_ready(self, timeout: float = 0.0) -> bool:
        return True

    def alive(self) -> bool:
        return not self.service._closed

    @property
    def pid(self) -> Optional[int]:
        return None

    def submit(self, req: dict) -> FleetTicket:
        self._next_id += 1
        t = FleetTicket(self._next_id)
        op = req.get("op")
        try:
            if op == "synonyms":
                # the trace context rides through exactly like the wire
                # transport: the adopted service's batcher emits the same
                # queue_wait/batch_service children a subprocess would
                bt = self.service.synonyms_async(req["word"],
                                                 int(req.get("num", 10)),
                                                 trace=req.get("trace"))
                t.batcher_ticket = bt
                t.done = bt.done  # share the batcher event — hedgeable wait
                return t
            if op == "synonyms_batch":
                rows = self.service.synonyms_batch(
                    list(req["words"]), int(req.get("num", 10)),
                    trace=req.get("trace"))
                t.resolve({"synonyms": [[[w, float(s)] for w, s in row]
                                        for row in rows]})
            elif op == "stats":
                t.resolve(self.service.stats())
            elif op == "reload":
                model = self.service.reload_now()
                t.resolve({"reloaded": True, "num_words": model.num_words})
            else:
                t.resolve({"error": f"unknown op {op!r}",
                           "error_type": "ValueError"})
        except Exception as e:  # noqa: BLE001 — wire-shaped error contract
            t.resolve(_error_response(e))
        return t

    def wait(self, ticket: FleetTicket, timeout: float) -> dict:
        if ticket.batcher_ticket is not None and ticket.response is None:
            try:
                res = self.service.wait_result(ticket.batcher_ticket, timeout)
            except TimeoutError:
                raise
            except Exception as e:  # noqa: BLE001 — wire-shaped error contract
                ticket.response = _error_response(e)
            else:
                ticket.response = {
                    "synonyms": [[w, float(s)] for w, s in res]}
        if not ticket.done.wait(timeout):
            raise TimeoutError(
                f"{self.name}: no response within {timeout:.2f}s")
        return ticket.response

    def abandon(self, ticket: FleetTicket) -> None:
        pass

    def kill(self) -> None:
        self.leaked_threads = self.service.close()

    def close(self) -> int:
        self.leaked_threads = self.service.close()
        return self.leaked_threads


def _error_response(e: BaseException) -> dict:
    """The wire-shaped error payload (mirrors tools/serve_checkpoint.py):
    message, type name, and the machine-readable retry hint when the
    exception carries one."""
    resp = {"error": f"{type(e).__name__}: {e}",
            "error_type": type(e).__name__}
    ra = getattr(e, "retry_after_s", None)
    if ra is not None:
        resp["retry_after_s"] = ra
    return resp


# ---------------------------------------------------------------------------
# replica set
# ---------------------------------------------------------------------------


class ReplicaSet:
    """N replicas over one transport. :meth:`spawn` launches subprocess
    replicas concurrently (each is a full JAX interpreter — serial boots
    would multiply the cold start by N); :meth:`adopt` wraps in-process
    services. ``can_respawn`` gates the router's restart path — adopted
    services have no process to relaunch."""

    def __init__(self, replicas: Sequence, can_respawn: bool):
        self.replicas = list(replicas)
        self.can_respawn = bool(can_respawn)

    @classmethod
    def spawn(cls, checkpoint: str, n: int, ann: bool = False,
              nprobe: Optional[int] = None, ready_timeout: float = 180.0,
              stderr_dir: str = "", telemetry_dir: str = "",
              env: Optional[Dict[str, str]] = None) -> "ReplicaSet":
        """``telemetry_dir``: non-empty arms per-replica observability —
        replica ``i`` writes ``replica-i.jsonl`` (serve records + trace
        spans, with the clock anchor the collector aligns on) and, on a
        graceful death, ``replica-i.jsonl.blackbox.json`` there. These are
        exactly the files ``tools/obs_collect.py`` merges with the router's
        own sink into the one fleet timeline."""
        if n <= 0:
            raise ValueError(f"replica count must be positive but got {n}")
        reps = []
        for i in range(n):
            stderr_path = (os.path.join(stderr_dir, f"replica-{i}.log")
                           if stderr_dir else "")
            telemetry_path = (
                os.path.join(telemetry_dir, f"replica-{i}.jsonl")
                if telemetry_dir else "")
            reps.append(SubprocessReplica(
                f"r{i}", checkpoint, ann=ann, nprobe=nprobe, env=env,
                stderr_path=stderr_path,
                telemetry_path=telemetry_path).start())
        deadline = time.monotonic() + ready_timeout
        for r in reps:
            if not r.wait_ready(max(0.0, deadline - time.monotonic())):
                for q in reps:
                    q.close()
                raise TimeoutError(
                    f"replica {r.name} not ready within {ready_timeout}s")
        return cls(reps, can_respawn=True)

    @classmethod
    def adopt(cls, services: Sequence) -> "ReplicaSet":
        return cls([InProcessReplica(f"r{i}", s)
                    for i, s in enumerate(services)], can_respawn=False)

    def close(self) -> int:
        """Close every replica; returns the total leaked-thread count."""
        leaked = 0
        for r in self.replicas:
            try:
                leaked += r.close() or 0
            except Exception:  # noqa: BLE001 — best-effort teardown
                logger.warning("replica %s close failed", r.name,
                               exc_info=True)
        return leaked


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------


class _ReplicaState:
    """Router-side bookkeeping for one replica handle."""

    def __init__(self, handle, breaker: CircuitBreaker):
        self.handle = handle
        self.breaker = breaker
        self.in_flight = 0           # mutated under the router lock
        self.saturated_until = 0.0
        self.draining = False
        self.degraded = False
        self.publish_sig: Optional[str] = None
        self.stats_cache: Optional[dict] = None
        self.retry_after_s: Optional[float] = None
        self.reloads = 0
        self.drained_reloads = 0
        self.last_restart = 0.0

    @property
    def name(self) -> str:
        return self.handle.name


class FleetRouter:
    """The robustness stack over a :class:`ReplicaSet` (module doc)."""

    def __init__(
        self,
        replica_set: ReplicaSet,
        checkpoint: Optional[str] = None,
        breaker_failures: int = 3,
        breaker_reset_s: float = 2.0,
        probe_s: float = 0.5,
        hedge_ms: float = -1.0,
        retry_deadline_s: float = 10.0,
        attempt_timeout_s: float = 5.0,
        rolling_reload: bool = True,
        telemetry_path: str = "",
        status_port: int = 0,
        rng_seed: Optional[int] = None,
        saturation_floor_s: float = 0.25,
        drain_timeout_s: float = 15.0,
        reload_timeout_s: float = 300.0,
        slo: Optional[SloObjectives] = None,
        trace_sample: int = 1,
    ):
        """``slo``: the availability/latency objective set (obs/slo.py;
        default :class:`SloObjectives` — 99.9% availability, p(250ms) ≥
        99%, 5m/1h windows). Always tracked (one deque append per query);
        surfaced as ``stats()["slo"]``, the ``glint_serve_fleet_slo_*``
        gauges, and the periodic ``fleet_slo`` telemetry record. The SLO is
        a deployment property, deliberately NOT a checkpoint-travelling
        config knob.

        ``trace_sample``: trace every Nth query when telemetry is on (1 =
        every query — the drills' setting; production tiers sample because
        a traced query writes ~5 flushed records across the fleet, which
        tools/telemetry_run.py --trace-overhead measures as the dominant
        per-query cost at toy latencies). Untraced queries still feed the
        SLO tracker and cross the wire byte-identical to tracing-off."""
        if probe_s <= 0:
            raise ValueError(f"probe_s must be positive but got {probe_s}")
        if hedge_ms < 0 and hedge_ms != -1.0:
            raise ValueError(
                f"hedge_ms must be -1 (auto), 0 (off), or positive "
                f"but got {hedge_ms}")
        if trace_sample < 1:
            raise ValueError(
                f"trace_sample must be >= 1 but got {trace_sample}")
        self._set = replica_set
        self._checkpoint = checkpoint
        self._probe_s = float(probe_s)
        self._hedge_ms = float(hedge_ms)
        self._retry_deadline_s = float(retry_deadline_s)
        self._attempt_timeout_s = float(attempt_timeout_s)
        self._rolling = bool(rolling_reload) and checkpoint is not None
        self._saturation_floor_s = float(saturation_floor_s)
        self._drain_timeout_s = float(drain_timeout_s)
        self._reload_timeout_s = float(reload_timeout_s)
        self._lock = make_lock("fleet.router")
        self._rr = 0  # round-robin tie-break counter
        # jitter source: seeded (R2); per-router decorrelation is the point
        self._rng = np.random.default_rng(
            rng_seed if rng_seed is not None
            else (os.getpid(), time.monotonic_ns()))
        self._replicas = [
            _ReplicaState(h, CircuitBreaker(
                breaker_failures, breaker_reset_s,
                on_transition=self._make_transition_cb(h.name)))
            for h in replica_set.replicas]
        # counters (under _lock)
        self.queries = 0
        self.failures = 0
        self.retries = 0
        self.hedges = 0
        self.hedge_wins = 0
        self.shed_single = 0
        self.shed_bulk = 0
        self.reload_rounds = 0
        self.min_serving_during_reloads: Optional[int] = None
        # success latency ring + cached p99 (the hedge-delay source)
        self._latencies: collections.deque = collections.deque(maxlen=2048)
        self._lat_count = 0
        self._p99_s: Optional[float] = None
        self._closed = False
        self._leaked_threads = 0
        self._sink = None
        self._statusd = None
        self._slo = SloTracker(slo)
        self._trace_sample = int(trace_sample)
        # trace emitter: exists iff the sink does — `self._span is None` IS
        # the tracing-off predicate on the hot submit path (no context
        # object, no id, no clock read; the acceptance bar tools/
        # telemetry_run.py --trace-overhead A/Bs)
        self._span = None
        self.process_name = f"router-{os.getpid()}"
        if telemetry_path:
            from glint_word2vec_tpu.obs.sink import TelemetrySink
            from glint_word2vec_tpu.obs.trace import SpanEmitter
            self._sink = TelemetrySink(telemetry_path)
            self._span = SpanEmitter(self._sink, self.process_name)
            self._sink.emit("fleet_start",
                            replicas=len(self._replicas),
                            checkpoint=checkpoint or "<in-memory>",
                            process=self.process_name, **clock_anchor())
        if status_port:
            from glint_word2vec_tpu.obs.statusd import (
                StatusServer, fleet_prometheus_text)
            self._statusd = StatusServer(
                status_port, self.status_snapshot,
                metrics_fn=fleet_prometheus_text).start()
        # the publish generation the fleet already serves: the disk
        # signature at boot (every replica just loaded it) — only a LATER
        # publish triggers a rolling round
        self._orchestrated_sig = (
            _sig_str(publish_signature(checkpoint))
            if checkpoint is not None else None)
        self._stop = threading.Event()
        # R1 documented owner: probes + breaker trials + restarts + rolling
        # reloads, all on ONE thread — read-only on model params
        self._prober = threading.Thread(
            target=self._probe_loop, name="glint-fleet-prober", daemon=True)
        self._prober.start()

    def _make_transition_cb(self, name: str):
        def cb(frm: str, to: str, reason: str) -> None:
            logger.info("fleet breaker %s: %s -> %s (%s)",
                        name, frm, to, reason)
            if self._sink is not None:
                self._sink.emit("fleet_breaker", replica=name,
                                from_state=frm, to_state=to, reason=reason)
        return cb

    # -- client surface ----------------------------------------------------------------

    def synonyms(self, word, num: int = 10,
                 deadline_s: Optional[float] = None
                 ) -> List[Tuple[str, float]]:
        return self._request({"op": "synonyms", "word": word,
                              "num": int(num)}, bulk=False,
                             deadline_s=deadline_s)

    def synonyms_batch(self, words: Sequence[str], num: int = 10,
                       deadline_s: Optional[float] = None
                       ) -> List[List[Tuple[str, float]]]:
        return self._request({"op": "synonyms_batch", "words": list(words),
                              "num": int(num)}, bulk=True,
                             deadline_s=deadline_s)

    # -- routing core ------------------------------------------------------------------

    def _eligible(self, exclude=()) -> List[_ReplicaState]:
        """Replicas client traffic may go to right now: breaker CLOSED,
        process alive, not draining for a rolling reload."""
        out = []
        for r in self._replicas:
            if r in exclude or r.draining:
                continue
            if not r.breaker.allows_traffic():
                continue
            if not r.handle.alive():
                continue
            out.append(r)
        return out

    def _pick(self, exclude=()) -> Optional[_ReplicaState]:
        """Least-in-flight among eligible unsaturated replicas, fresh
        (non-degraded) preferred, round-robin tie-break."""
        now = time.monotonic()
        elig = [r for r in self._eligible(exclude)
                if r.saturated_until <= now]
        if not elig:
            return None
        with self._lock:
            self._rr += 1
            rr = self._rr
        # sort key: degraded last, then least in flight, then rotate
        elig.sort(key=lambda r: (r.degraded, r.in_flight,
                                 (self._replicas.index(r) + rr)
                                 % len(self._replicas)))
        return elig[0]

    def _hedge_delay_s(self) -> Optional[float]:
        """The hedging trigger: None = no hedge. AUTO (-1) derives from the
        measured p99 once >= 64 successes exist (re-derived every 64
        samples; floored at 2 ms so the duplicate send can never become
        the common case)."""
        if self._hedge_ms == 0.0:
            return None
        if self._hedge_ms > 0:
            return self._hedge_ms / 1000.0
        p99 = self._p99_s
        if p99 is None:
            return None
        return max(0.002, p99)

    def _note_latency(self, dt: float) -> None:
        # append AND snapshot under the lock: sorting a deque while another
        # thread appends raises RuntimeError("deque mutated during
        # iteration") — which would surface as a FAILED client query on a
        # perfectly successful response
        with self._lock:
            self._latencies.append(dt)
            self._lat_count += 1
            snap = (list(self._latencies)
                    if (self._lat_count % 64 == 0
                        and len(self._latencies) >= 64) else None)
        if snap:
            snap.sort()
            self._p99_s = snap[min(len(snap) - 1, int(0.99 * len(snap)))]

    def _finish_query(self, trace: Optional[tuple], start_s: float,
                      op: str, answered: bool, outcome: str) -> None:
        """Per-query epilogue, EVERY exit path: one SLO sample (answered =
        the caller got a result — a propagating OOV KeyError is the
        caller's error, not unavailability) and, when tracing, the
        ``fleet_query`` root span whose duration is the client-observed
        latency (the collector's slowest-K exemplar key)."""
        self._slo.note(answered,
                       time.monotonic() - start_s if answered else None)
        if trace is not None:
            tid, root, root_ns = trace
            self._span.emit(tid, "fleet_query", root_ns,
                            time.monotonic_ns() - root_ns, span_id=root,
                            outcome=outcome, op=op)

    def _request(self, req: dict, bulk: bool,
                 deadline_s: Optional[float]) -> Any:
        if self._closed:
            raise ServiceClosed("fleet router is closed")
        with self._lock:
            self.queries += 1
            nth_query = self.queries
        start_s = time.monotonic()
        # trace context born HERE (obs/trace.py): one trace per client
        # query, a root span id its attempt children parent to. Off (no
        # sink) = None — no ids, no allocation, requests cross the wire
        # byte-identical (the zero-cost acceptance bar). With a sampled
        # tracer (trace_sample > 1) the unsampled queries take the same
        # None path.
        trace = (None if self._span is None
                 or nth_query % self._trace_sample
                 else (new_trace_id(), new_span_id(), time.monotonic_ns()))
        op = str(req.get("op", "?"))
        deadline = start_s + (deadline_s if deadline_s is not None
                              else self._retry_deadline_s)
        # bulk sheds FIRST: refused while ANY healthy replica is saturated
        if bulk:
            now = time.monotonic()
            pressured = [r for r in self._eligible()
                         if r.saturated_until > now]
            if pressured:
                with self._lock:
                    self.shed_bulk += 1
                self._finish_query(trace, start_s, op, False, "shed")
                raise FleetOverloaded(
                    "bulk traffic shed: fleet under pressure "
                    f"({len(pressured)} saturated replica(s))",
                    retry_after_s=min((r.retry_after_s or
                                       self._saturation_floor_s)
                                      for r in pressured))
        delays = decorrelated_jitter(0.05, 1.0, self._rng)
        tried: set = set()
        last_err: Optional[BaseException] = None
        while True:
            r = self._pick(exclude=tried)
            if r is None:
                # the fleet-level 429, refused FAST: every healthy replica
                # is saturated right now (never block a caller on a fleet
                # that already said it is full — "the fleet refuses fast
                # only when EVERY healthy replica is saturated")
                now = time.monotonic()
                elig_all = self._eligible()
                if elig_all and all(q.saturated_until > now
                                    for q in elig_all):
                    with self._lock:
                        self.shed_single += 1
                    self._finish_query(trace, start_s, op, False, "shed")
                    raise FleetOverloaded(
                        "every healthy replica is saturated",
                        retry_after_s=min(
                            (q.retry_after_s or self._saturation_floor_s)
                            for q in elig_all))
                # every candidate tried (or none healthy): back off with
                # decorrelated jitter and re-open the candidate set, until
                # the deadline — a replica may heal / unsaturate mid-wait
                tried = set()
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                time.sleep(min(next(delays), max(0.0, remaining)))
                continue
            attempt_timeout = min(self._attempt_timeout_s,
                                  max(0.05, deadline - time.monotonic()))
            try:
                value = self._call(r, req, attempt_timeout,
                                   hedge=not bulk, tried=tried, trace=trace)
            except _Saturated as e:
                # "retry elsewhere, not here": healthy-but-full is not a
                # breaker failure; mark and move on with NO backoff. The
                # blamed replica is the one that ANSWERED (a hedged
                # attempt's overloaded reply may come from the hedge
                # target, not the primary — _call attributes it)
                tgt = getattr(e, "replica", r)
                tgt.saturated_until = time.monotonic() + max(
                    self._saturation_floor_s, e.retry_after_s or 0.0)
                tgt.retry_after_s = e.retry_after_s
                tried.add(tgt)
                last_err = e
                continue
            except (ReplicaError, TimeoutError) as e:
                tgt = getattr(e, "replica", r)
                tgt.breaker.record_failure(str(e))
                tried.add(tgt)
                last_err = e
                with self._lock:
                    self.retries += 1
                if time.monotonic() >= deadline:
                    break
                continue
            except Exception:
                # a CLIENT error (OOV KeyError, bad op) propagating from
                # _interpret: the fleet ANSWERED — availability is intact
                self._finish_query(trace, start_s, op, True, "ok")
                raise
            self._finish_query(trace, start_s, op, True, "ok")
            return value
        with self._lock:
            self.failures += 1
        self._finish_query(trace, start_s, op, False, "failed")
        raise NoHealthyReplicas(
            f"no replica answered within the "
            f"{deadline_s if deadline_s is not None else self._retry_deadline_s:g}s "
            f"deadline (last error: {last_err})") from last_err

    def _call(self, r: _ReplicaState, req: dict, timeout: float,
              hedge: bool, tried: set,
              trace: Optional[tuple] = None) -> Any:
        """One attempt, optionally hedged: submit to ``r``; if the
        p99-derived delay passes unresolved, race a second replica —
        first response wins, the loser is abandoned.

        When ``trace`` is set, every replica this attempt touched gets one
        ``attempt`` child span under the query's root, labeled with the
        replica and its outcome: ``ok`` (unhedged success), ``win`` /
        ``abandoned`` (the hedge race — the loser is ABANDONED, never
        "failed": a slow-but-healthy replica must not read as a sick one on
        the timeline), ``failed`` (breaker food), ``saturated`` (healthy
        but full). The wire request carries each attempt's own span id as
        the parent for the replica-side children."""
        deadline = time.monotonic() + timeout
        if trace is None:
            wire1 = req
            s1 = None
            a1_ns = 0
        else:
            tid, root, _ = trace
            s1 = new_span_id()
            wire1 = {**req, "trace": wire_context(tid, s1)}
            a1_ns = time.monotonic_ns()
        try:
            t1 = r.handle.submit(wire1)
        except ReplicaError:
            # dead at submit (the SIGKILL drill's first symptom): the
            # attempt still gets its failed child span — a trace whose
            # failed attempt is invisible can't tell the incident's story
            if trace is not None:
                self._span.emit(tid, "attempt", a1_ns,
                                time.monotonic_ns() - a1_ns, parent=root,
                                span_id=s1, replica=r.name,
                                outcome="failed")
            raise
        with self._lock:
            r.in_flight += 1
        r2: Optional[_ReplicaState] = None
        t2: Optional[FleetTicket] = None
        s2: Optional[str] = None
        a2_ns = 0
        race_died: list = []  # replicas dropped dead mid-hedge-race

        def attempt_spans(err: Optional[BaseException],
                          outcome: str, winner=None) -> None:
            """Emit the attempt children. Success: ``winner`` answered (the
            other side, if any, was abandoned — unless it DIED mid-race:
            its breaker recorded a failure, so the timeline says ``failed``
            too). Failure: the BLAMED replica (the one the error is
            attributed to) carries ``outcome``, the other side was
            abandoned mid-race."""
            if trace is None:
                return
            now = time.monotonic_ns()
            blamed = winner if winner is not None else getattr(
                err, "replica", None)
            for rep, sid, start in ((r, s1, a1_ns), (r2, s2, a2_ns)):
                if rep is None or sid is None:
                    continue
                if rep in race_died:
                    oc = "failed"
                elif blamed is None or rep is blamed:
                    oc = outcome
                else:
                    oc = "abandoned"
                self._span.emit(tid, "attempt", start, now - start,
                                parent=root, span_id=sid, replica=rep.name,
                                outcome=oc)

        try:
            hedge_delay = self._hedge_delay_s() if hedge else None
            if hedge_delay is not None and hedge_delay < timeout:
                if not t1.done.wait(hedge_delay):
                    r2 = self._pick(exclude=tried | {r})
                    if r2 is not None:
                        try:
                            if trace is None:
                                wire2 = req
                            else:
                                s2 = new_span_id()
                                wire2 = {**req,
                                         "trace": wire_context(tid, s2)}
                                a2_ns = time.monotonic_ns()
                            t2 = r2.handle.submit(wire2)
                        except ReplicaError:
                            # dead at submit: the timeline must still show
                            # the hedge touched this replica (the mirror of
                            # the primary's dead-at-submit span above)
                            if trace is not None and s2 is not None:
                                self._span.emit(
                                    tid, "attempt", a2_ns,
                                    time.monotonic_ns() - a2_ns,
                                    parent=root, span_id=s2,
                                    replica=r2.name, outcome="failed")
                            r2, s2 = None, None
                        else:
                            with self._lock:
                                self.hedges += 1
                                r2.in_flight += 1
            if t2 is None:
                src, resp = r, r.handle.wait(
                    t1, max(0.0, deadline - time.monotonic()))
            else:
                src, resp = self._wait_either(
                    (r, t1), (r2, t2), deadline, died=race_died)
                if src is r2:
                    with self._lock:
                        self.hedge_wins += 1
            try:
                value = self._interpret(resp)
            except Exception as e:
                # attribute the failure to the replica that ANSWERED — on a
                # hedged attempt that may be r2, and blaming the primary
                # would open the healthy replica's breaker (or mark it
                # saturated with r2's hint) while the sick one stays routed
                e.replica = src  # read by _request via getattr
                raise
            src.breaker.record_success()
            self._note_latency(timeout - max(0.0,
                                             deadline - time.monotonic()))
            attempt_spans(None, "win" if t2 is not None else "ok",
                          winner=src)
            return value
        except _Saturated as e:
            attempt_spans(e, "saturated")
            raise
        except (ReplicaError, TimeoutError) as e:
            attempt_spans(e, "failed")
            raise
        except Exception as e:
            # client-level error: the blamed replica ANSWERED — its attempt
            # is "ok" on the timeline, the raise is the caller's business
            attempt_spans(e, "ok")
            raise
        finally:
            with self._lock:
                r.in_flight -= 1
                if t2 is not None:
                    r2.in_flight -= 1
            r.handle.abandon(t1)
            if t2 is not None:
                r2.handle.abandon(t2)

    @staticmethod
    def _wait_either(a, b, deadline: float, died: Optional[list] = None):
        """First-wins over two (replica, ticket) pairs. Polls at 1 ms —
        only ever runs inside the hedge window (past p99), so the poll
        granularity is noise relative to the tail it is cutting. A side
        whose ticket resolves as a transport death (ReplicaError) is
        dropped and the OTHER side keeps being waited — a dead hedge
        target must not fail an attempt the primary can still win; the
        raised error carries ``.replica`` for breaker attribution.
        ``died`` (when given) collects the dropped replicas so the
        caller's trace labels them ``failed``, not ``abandoned`` — the
        breaker recorded a failure, the timeline must agree."""
        pairs = [list(a), list(b)]
        while True:
            for pair in list(pairs):
                rx, tx = pair
                if tx.done.is_set():
                    try:
                        return rx, rx.handle.wait(tx, 0.0)
                    except ReplicaError as e:
                        pairs.remove(pair)
                        if not pairs:
                            e.replica = rx  # the outer loop records it
                            raise
                        # dropped side: no exception will propagate for
                        # it, so its breaker is fed here
                        rx.breaker.record_failure(str(e))
                        if died is not None:
                            died.append(rx)
            if time.monotonic() >= deadline:
                raise TimeoutError("hedged attempt timed out on both replicas")
            time.sleep(0.001)

    @staticmethod
    def _interpret(resp: dict) -> Any:
        """Wire response → value, or the typed raise. ServerOverloaded is
        saturation (retry elsewhere); ServiceClosed/timeouts are replica
        failures (breaker food); anything else — an OOV KeyError, a bad
        op — is the CALLER's error and propagates without burning
        retries."""
        if "error" in resp:
            et = resp.get("error_type") or resp["error"].split(":", 1)[0]
            msg = resp["error"]
            if et == "ServerOverloaded":
                raise _Saturated(resp.get("retry_after_s"))
            if et in ("ServiceClosed", "TimeoutError"):
                raise ReplicaError(msg)
            if et == "KeyError":
                raise KeyError(msg.split(":", 1)[-1].strip())
            raise RuntimeError(msg)
        if "synonyms" in resp:
            rows = resp["synonyms"]
            if rows and rows[0] and isinstance(rows[0][0], list):
                return [[(w, s) for w, s in row] for row in rows]
            return [(w, s) for w, s in rows]
        return resp

    # -- prober / orchestrator (one thread) --------------------------------------------

    def _probe_loop(self) -> None:
        while not self._stop.wait(self._probe_s):
            try:
                self._probe_once()
            except Exception:  # noqa: BLE001 — the prober must survive
                logger.warning("fleet probe round failed", exc_info=True)

    def _probe_once(self) -> None:
        disk_sig = (_sig_str(publish_signature(self._checkpoint))
                    if self._checkpoint else None)
        for r in self._replicas:
            if self._stop.is_set():
                return
            self._probe_replica(r, disk_sig)
        # rolling reload: a NEW publish (disk signature moved past the last
        # orchestrated one) drains + reloads replicas one at a time
        if (self._rolling and disk_sig is not None
                and disk_sig != self._orchestrated_sig):
            self._rolling_reload(disk_sig)

    def _probe_replica(self, r: _ReplicaState, disk_sig: Optional[str]
                       ) -> None:
        # dead process: feed the breaker (client traffic may be sparse —
        # liveness must not depend on it) and restart under a cooldown
        if not r.handle.alive():
            r.breaker.record_failure("process dead")
            if (self._set.can_respawn
                    and time.monotonic() - r.last_restart
                    >= r.breaker.reset_s):
                r.last_restart = time.monotonic()
                logger.info("fleet: restarting dead replica %s", r.name)
                try:
                    r.handle.restart()
                except Exception:  # noqa: BLE001 — retried next tick
                    logger.warning("restart of %s failed", r.name,
                                   exc_info=True)
            return
        state = r.breaker.state
        if state == CircuitBreaker.OPEN:
            if not r.breaker.begin_probe():
                return  # cooldown still running
        elif state == CircuitBreaker.HALF_OPEN:
            pass  # a prior trial is resolving this tick
        # the probe: a cheap stats op, bounded by the probe cadence
        try:
            t = r.handle.submit({"op": "stats"})
            resp = r.handle.wait(t, max(1.0, self._probe_s))
            stats = self._interpret(resp)
        except (_Saturated,):
            # a saturated replica is alive — not a breaker failure
            r.breaker.record_success()
            return
        except Exception as e:  # noqa: BLE001 — any probe failure is food
            r.breaker.record_failure(f"probe: {e}")
            return
        r.breaker.record_success()
        if isinstance(stats, dict):
            r.stats_cache = stats
            r.publish_sig = stats.get("publish_sig")
            # staleness: serving an older publish than the disk = DEGRADED
            # (still serves; the router prefers fresh replicas)
            r.degraded = (disk_sig is not None
                          and r.publish_sig is not None
                          and r.publish_sig != disk_sig)

    def _rolling_reload(self, disk_sig: str) -> None:
        """Drain + reload one replica at a time: capacity never drops below
        N-1 (the ``min_serving`` gauge asserts it). Replicas run with the
        watcher OFF — this orchestrator is the only reload trigger."""
        t0 = time.monotonic()
        target = disk_sig
        min_serving = len(self._replicas)
        for r in self._replicas:
            if self._stop.is_set():
                return
            if not (r.handle.alive() and r.breaker.allows_traffic()):
                continue  # a broken replica reloads at restart/boot instead
            r.draining = True
            try:
                drain_deadline = time.monotonic() + self._drain_timeout_s
                while r.in_flight > 0 and time.monotonic() < drain_deadline:
                    time.sleep(0.005)
                drained = r.in_flight == 0
                serving = sum(1 for q in self._replicas
                              if q is not r and not q.draining
                              and q.handle.alive()
                              and q.breaker.allows_traffic())
                min_serving = min(min_serving, serving)
                t = r.handle.submit({"op": "reload"})
                self._interpret(r.handle.wait(t, self._reload_timeout_s))
                r.reloads += 1
                if drained:
                    r.drained_reloads += 1
                r.publish_sig = target
                r.degraded = False
            except Exception as e:  # noqa: BLE001 — one replica's failed
                # reload must not wedge the round; the breaker/probe path
                # owns its recovery and the next publish retries it
                r.breaker.record_failure(f"rolling reload: {e}")
                logger.warning("rolling reload of %s failed", r.name,
                               exc_info=True)
            finally:
                r.draining = False
        self._orchestrated_sig = target
        with self._lock:
            self.reload_rounds += 1
            self.min_serving_during_reloads = (
                min_serving if self.min_serving_during_reloads is None
                else min(self.min_serving_during_reloads, min_serving))
        if self._sink is not None:
            self._sink.emit("fleet_reload",
                            publishes=self.reload_rounds,
                            min_serving=min_serving,
                            replicas=len(self._replicas),
                            # the generation rolled to: joins the
                            # publisher's `publish` record and each
                            # replica's serve_reload on the fleet timeline
                            publish_sig=target,
                            seconds=round(time.monotonic() - t0, 3))
        logger.info("rolling reload round %d: %d replicas, min serving %d, "
                    "%.2fs", self.reload_rounds, len(self._replicas),
                    min_serving, time.monotonic() - t0)

    # -- observability -----------------------------------------------------------------

    def breaker_states(self) -> Dict[str, str]:
        return {r.name: r.breaker.state for r in self._replicas}

    def breaker_transitions(self, name: str) -> List[Tuple[str, str, str]]:
        for r in self._replicas:
            if r.name == name:
                return r.breaker.transitions_snapshot()
        raise KeyError(name)

    def stats(self) -> Dict[str, Any]:
        now = time.monotonic()
        with self._lock:
            snap: Dict[str, Any] = {
                "queries": self.queries,
                "failures": self.failures,
                "retries": self.retries,
                "hedges": self.hedges,
                "hedge_wins": self.hedge_wins,
                "shed_single": self.shed_single,
                "shed_bulk": self.shed_bulk,
                "reload_rounds": self.reload_rounds,
                "min_serving_during_reloads":
                    self.min_serving_during_reloads,
            }
        replicas: Dict[str, Any] = {}
        healthy = degraded = 0
        leaked = self._leaked_threads
        for r in self._replicas:
            alive = r.handle.alive()
            closed = r.breaker.state == CircuitBreaker.CLOSED
            healthy += alive and closed
            degraded += r.degraded
            replicas[r.name] = {
                "state": r.breaker.state,
                "alive": alive,
                "degraded": r.degraded,
                "draining": r.draining,
                "in_flight": r.in_flight,
                "saturated": r.saturated_until > now,
                "reloads": r.reloads,
                "drained_reloads": r.drained_reloads,
                "restarts": r.handle.restarts,
                "leaked_threads": getattr(r.handle, "leaked_threads", 0),
                "publish_sig": r.publish_sig,
                "stats": r.stats_cache,
            }
        for rs in replicas.values():
            leaked += rs["leaked_threads"]
        snap["replicas"] = replicas
        snap["healthy"] = healthy
        snap["degraded"] = degraded
        snap["leaked_threads"] = leaked
        snap["slo"] = self._slo.snapshot()
        with self._lock:  # same mutation-during-sort hazard as _note_latency
            lats = list(self._latencies)
        lats.sort()
        if lats:
            def pct(p: float) -> float:
                return round(
                    lats[min(len(lats) - 1, int(p * len(lats)))] * 1000, 3)
            snap["latency_ms"] = {"p50": pct(0.50), "p95": pct(0.95),
                                  "p99": pct(0.99), "n": len(lats)}
        return snap

    def status_snapshot(self) -> Dict[str, Any]:
        snap = self.stats()
        snap["status"] = "closed" if self._closed else "serving"
        return snap

    def slo_snapshot(self) -> Dict[str, Any]:
        """The live SLO gauge set (obs/slo.py) — what the chaos drills
        assert and ``fleet_prometheus_text`` renders."""
        return self._slo.snapshot()

    def slo_within_budget(self) -> bool:
        return self._slo.within_budget()

    def emit_stats(self) -> None:
        if self._sink is None:
            return
        s = self.stats()
        # the snapshot is always populated (a samples=0 record before any
        # traffic is "no traffic burned no budget", worth the line)
        slo = flatten_burn(s["slo"])
        self._sink.emit(
            "fleet_stats",
            queries=s["queries"], failures=s["failures"],
            retries=s["retries"], hedges=s["hedges"],
            hedge_wins=s["hedge_wins"],
            shed=s["shed_single"] + s["shed_bulk"],
            healthy=s["healthy"], degraded=s["degraded"], slo=slo,
            **({"latency_ms": s["latency_ms"]}
               if s.get("latency_ms") else {}))
        self._sink.emit("fleet_slo", **slo)

    def close(self, close_replicas: bool = True) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._prober.join(timeout=30)
        if self._prober.is_alive():
            self._leaked_threads += 1
            logger.warning("fleet prober thread leaked (join timeout)")
        if self._statusd is not None:
            self._leaked_threads += self._statusd.stop()
        if self._sink is not None:
            with self._lock:
                q, f = self.queries, self.failures
            # the terminal SLO snapshot BEFORE the end bracket: a collector
            # reading only this file still gets the storm's final burn
            self._sink.emit("fleet_slo", **flatten_burn(self._slo.snapshot()))
            self._sink.emit("fleet_end", queries=q, failures=f)
            self._sink.close()
        if close_replicas:
            self._leaked_threads += self._set.close()

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def fleet_knobs_from_checkpoint(checkpoint: str, **overrides) -> dict:
    """Resolve the ``serve_fleet_*`` knobs the same way the single service
    resolves ``serve_*``: explicit override, else the checkpoint config's
    field (the knobs travel with the checkpoint), else the dataclass
    default. Returns the FleetRouter/ReplicaSet keyword dict."""
    from glint_word2vec_tpu.train.checkpoint import load_model_header
    cfg = load_model_header(checkpoint)["config"]

    def knob(name, override_key):
        v = overrides.get(override_key)
        return v if v is not None else getattr(cfg, name)

    return {
        "replicas": int(knob("serve_fleet_replicas", "replicas")),
        "probe_s": float(knob("serve_fleet_probe_s", "probe_s")),
        "breaker_failures": int(knob("serve_fleet_breaker_failures",
                                     "breaker_failures")),
        "breaker_reset_s": float(knob("serve_fleet_breaker_reset_s",
                                      "breaker_reset_s")),
        "hedge_ms": float(knob("serve_fleet_hedge_ms", "hedge_ms")),
        "retry_deadline_s": float(knob("serve_fleet_retry_deadline_s",
                                       "retry_deadline_s")),
    }
