"""R8 good config half: every dispatch refusal has a multi-knob
construction-time twin (range checks ride alongside, as in the real
config)."""
import dataclasses


@dataclasses.dataclass
class Word2VecConfig:
    cbow: bool = False
    device_pairgen: bool = False
    use_pallas: bool = False
    negative_pool: int = -1
    max_row_norm: float = 0.0
    vector_size: int = 100
    step_lowering: str = "gspmd"
    sync_every: int = 1

    def __post_init__(self) -> None:
        if self.vector_size <= 0:
            raise ValueError("vector_size must be positive")
        if self.negative_pool < -1:
            raise ValueError("negative_pool must be >= -1")
        if self.max_row_norm < 0:
            raise ValueError("max_row_norm must be nonnegative")
        if self.sync_every <= 0:
            raise ValueError("sync_every must be positive")
        if self.use_pallas:
            if self.cbow:
                raise ValueError("use_pallas is SGNS-only")
            if self.max_row_norm:
                raise ValueError("stabilizers are XLA-path only")
        if self.device_pairgen and self.cbow:
            raise ValueError("device feed is skip-gram only")
        if self.cbow and self.negative_pool == 0:
            raise ValueError("cbow needs the shared pool here")
        if self.sync_every > 1 and self.step_lowering != "shard_map":
            raise ValueError("sync_every needs the shard_map lowering")
