"""graftlint (layer-1 static analysis, ISSUE 5): every rule fires on its bad
fixture and stays silent on the good twin; the suppression syntax enforces a
written justification; and the CURRENT TREE lints clean with the committed
suppression baseline — so any PR that re-introduces an ad-hoc thread pool, an
unseeded RNG, a host sync inside jit, a bf16 prefix sum, a bare data-plane
read, raw trainer device placement, a stray stdout print in a contract tool,
or a dispatch-only knob refusal fails tier-1, not review."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.graftlint import engine  # noqa: E402
from tools.graftlint.concurrency import (  # noqa: E402
    R1Staleness, R9LockOrder, R10HandlerSafety)
from tools.graftlint.rules import R8RefusalParity  # noqa: E402

FIXTURES = os.path.join(REPO, "tests", "fixtures", "lint")

# rule id -> the virtual repo path the fixture pretends to live at (rules are
# path-scoped: R5 only watches data/, R6 only the trainer, R7 the contract
# tools, the rest all library code)
_VPATH = {
    "R1": "glint_word2vec_tpu/ops/somefile.py",
    "R2": "glint_word2vec_tpu/ops/somefile.py",
    "R3": "glint_word2vec_tpu/ops/somefile.py",
    "R4": "glint_word2vec_tpu/ops/somefile.py",
    "R5": "glint_word2vec_tpu/data/somefile.py",
    "R6": "glint_word2vec_tpu/train/trainer.py",
    "R7": "bench.py",
    "R11": "glint_word2vec_tpu/serve/somefile.py",
}


def _fixture(name: str) -> str:
    with open(os.path.join(FIXTURES, name), "r", encoding="utf-8") as f:
        return f.read()


@pytest.mark.parametrize("rule_id", sorted(_VPATH))
def test_rule_fires_on_bad_and_not_on_good(rule_id):
    vpath = _VPATH[rule_id]
    bad = engine.lint_text(_fixture(f"{rule_id.lower()}_bad.py"), vpath)
    assert any(f.rule == rule_id and not f.suppressed for f in bad), (
        f"{rule_id} did not fire on its bad fixture: {bad}")
    good = engine.lint_text(_fixture(f"{rule_id.lower()}_good.py"), vpath)
    assert not [f for f in good if f.rule == rule_id], (
        f"{rule_id} false-positived on its good fixture: {good}")


def test_r3_flags_every_host_sync_kind():
    bad = engine.lint_text(_fixture("r3_bad.py"), _VPATH["R3"])
    msgs = " ".join(f.message for f in bad if f.rule == "R3")
    assert "float" in msgs and "asarray" in msgs and "clock" in msgs


def test_r3_transitive_helper_coverage():
    """ISSUE 8 satellite: host syncs in a same-module HELPER the jitted
    function calls by name (the obs/probe.py `_matrix_stats` shape) are in
    scope; the good twin (device-pure helper, host flattening outside the
    jit boundary) stays silent."""
    vpath = "glint_word2vec_tpu/obs/somefile.py"
    bad = engine.lint_text(_fixture("r3_trans_bad.py"), vpath)
    msgs = " ".join(f.message for f in bad if f.rule == "R3")
    assert "concretizes" in msgs and "clock" in msgs, bad
    good = engine.lint_text(_fixture("r3_trans_good.py"), vpath)
    assert not [f for f in good if f.rule == "R3"], good


def test_r3_reaches_the_real_probe_helpers():
    """The closure genuinely covers obs/probe.py: poisoning `_matrix_stats`
    (called from the jitted fused probe, not itself a jit target) with a
    float() concretization must fire R3."""
    path = os.path.join(REPO, "glint_word2vec_tpu", "obs", "probe.py")
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    poisoned = src.replace(
        "    return MatrixStats(",
        "    bad = float(norms.sum())\n    return MatrixStats(")
    assert poisoned != src, "probe.py refactored — update the poison anchor"
    found = engine.lint_text(poisoned, "glint_word2vec_tpu/obs/probe.py")
    assert [f for f in found if f.rule == "R3"], found
    # and the committed module itself is clean under the wider scan
    clean = engine.lint_text(src, "glint_word2vec_tpu/obs/probe.py")
    assert not [f for f in clean if f.rule == "R3"], clean
    watch_path = os.path.join(REPO, "glint_word2vec_tpu", "obs", "watch.py")
    with open(watch_path, "r", encoding="utf-8") as f:
        watch_src = f.read()
    assert not [f for f in engine.lint_text(
        watch_src, "glint_word2vec_tpu/obs/watch.py") if f.rule == "R3"]


def test_r7_counts_second_json_line():
    bad = engine.lint_text(_fixture("r7_bad.py"), _VPATH["R7"])
    assert any("exactly ONE JSON line" in f.message for f in bad)


def test_r8_fires_on_bad_pair_and_not_on_good_pair():
    rule = R8RefusalParity()
    bad = rule.check_repo(os.path.join(FIXTURES, "r8_bad"))
    msgs = [f.message for f in bad if f.rule == "R8"]
    # combo with no config twin at all
    assert any("cbow" in m and "use_pallas" in m for m in msgs), bad
    # combo "covered" only by a single-knob RANGE check — not coverage:
    # the rule must not be blinded by config range checks on a member knob
    assert any("cbow" in m and "negative_pool" in m for m in msgs), bad
    # a NEW stabilizer-class knob with a dispatch-only refusal (ISSUE 7):
    # the range check on max_row_norm must not count as combo coverage
    assert any("max_row_norm" in m and "use_pallas" in m for m in msgs), bad
    # a refusal living in Trainer.__init__ path selection, not _build_step —
    # the device_pairgen class graftcheck's first run caught in the real
    # tree (ISSUE 8): __init__ is now a scanned dispatch surface
    assert any("device_pairgen" in m and "cbow" in m for m in msgs), bad
    # a step-cadence knob whose window exists for one lowering only
    # (ISSUE 17): the config-side positivity check on sync_every must not
    # count as coverage for the {sync_every, step_lowering} dispatch combo
    assert any("sync_every" in m and "step_lowering" in m for m in msgs), bad
    good = rule.check_repo(os.path.join(FIXTURES, "r8_good"))
    assert not good, good


def test_r8_cross_references_graftcheck_registry():
    """R8's graftcheck cross-reference: every config field needs a knob
    entry in tools/graftcheck/registry.py. Verified both ways — the real
    tree is clean, and a field invented on a copied config must be flagged
    as missing from the registry."""
    import shutil
    import tempfile

    rule = R8RefusalParity()
    assert not [f for f in rule.check_repo(REPO)
                if "registry" in f.message], "real tree should be in sync"
    with tempfile.TemporaryDirectory() as td:
        for rel in ("glint_word2vec_tpu/config.py",
                    "glint_word2vec_tpu/train/trainer.py",
                    "tools/graftcheck/registry.py"):
            dst = os.path.join(td, rel)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            shutil.copy(os.path.join(REPO, rel), dst)
        cfg_path = os.path.join(td, "glint_word2vec_tpu", "config.py")
        with open(cfg_path, "r", encoding="utf-8") as f:
            src = f.read()
        src = src.replace("    vector_size: int = 100",
                          "    brand_new_knob: int = 0\n"
                          "    vector_size: int = 100")
        with open(cfg_path, "w", encoding="utf-8") as f:
            f.write(src)
        found = rule.check_repo(td)
        assert any("brand_new_knob" in f.message and "registry" in f.message
                   for f in found), found


def test_suppression_requires_justification():
    src = _fixture("r4_bad.py")
    # justified suppression on the line above the finding
    justified = src.replace(
        "    prefix = jnp.cumsum(rows, axis=0)",
        "    # graftlint: disable=R4 -- fixture: exactness argued elsewhere\n"
        "    prefix = jnp.cumsum(rows, axis=0)")
    out = engine.lint_text(justified, _VPATH["R4"])
    assert [f for f in out if f.rule == "R4" and f.suppressed]
    assert not [f for f in out if not f.suppressed]
    # a directive WITHOUT justification suppresses nothing and is itself
    # a finding
    silent = src.replace(
        "    prefix = jnp.cumsum(rows, axis=0)",
        "    prefix = jnp.cumsum(rows, axis=0)  # graftlint: disable=R4")
    out = engine.lint_text(silent, _VPATH["R4"])
    assert [f for f in out if f.rule == "R4" and not f.suppressed]
    assert [f for f in out if f.rule == "SUP"]


def test_trailing_suppression_on_flagged_line():
    src = _fixture("r4_bad.py").replace(
        "    prefix = jnp.cumsum(rows, axis=0)",
        "    prefix = jnp.cumsum(rows, axis=0)"
        "  # graftlint: disable=R4 -- fixture")
    out = engine.lint_text(src, _VPATH["R4"])
    assert [f for f in out if f.rule == "R4" and f.suppressed]
    assert not [f for f in out if not f.suppressed]


def test_tree_lints_clean_with_baseline():
    """THE acceptance gate: zero unsuppressed findings on the tree and the
    suppression inventory matches the committed baseline exactly."""
    report = engine.lint_repo(REPO)
    assert not report.unsuppressed, "\n".join(
        f.key() for f in report.unsuppressed)
    drift = engine.check_baseline(
        report, os.path.join(REPO, "tools", "graftlint", "baseline.json"))
    assert not drift, drift
    # every suppression that IS in the tree carries a justification
    assert all(f.justification for f in report.suppressed)


def test_cli_json_contract():
    """`python -m tools.graftlint --json` exits 0 on the tree and emits one
    parseable JSON report on stdout (the CI wiring)."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--json"],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] and payload["tool"] == "graftlint"
    assert payload["files_scanned"] > 40


def test_missing_baseline_fails_closed():
    """A deleted/typo'd baseline path must FAIL the run, not silently skip
    the suppression-inventory gate (explicit --no-baseline is the only
    opt-out)."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint",
         "--baseline", "tools/graftlint/no-such-baseline.json"],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert proc.returncode == 1
    assert "baseline file not found" in proc.stderr
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--no-baseline"],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert proc.returncode == 0, proc.stderr


def test_ruff_clean_if_available():
    """The generic-lint layer (pyproject [tool.ruff]): pyflakes/E9 clean.
    Skips when the ruff binary is absent (this container does not vendor it);
    CI installs it and fails the lint job on any finding."""
    import shutil

    if shutil.which("ruff") is None:
        pytest.skip("ruff not installed (CI runs it)")
    proc = subprocess.run(["ruff", "check", "."], capture_output=True,
                          text=True, timeout=300, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_fixtures_are_out_of_lint_scope():
    """The bad fixtures must never be swept into the repo lint (they exist to
    fail)."""
    scanned = {os.path.relpath(p, REPO).replace(os.sep, "/")
               for p in engine.iter_source_files(REPO)}
    assert not any(p.startswith("tests/") for p in scanned)
    assert "tools/graftlint/rules.py" not in scanned  # rules discuss patterns


# ---------------------------------------------------------------------------
# graftrace (layer 4, ISSUE 20): R9/R10 repo-rule fixture pairs + the R1
# staleness gate. R11 rides the parametrized per-file pair above.
# ---------------------------------------------------------------------------

def test_r9_fires_on_bad_pair_and_not_on_good_pair():
    rule = R9LockOrder()
    bad = rule.check_repo(os.path.join(FIXTURES, "r9_bad"))
    msgs = [f.message for f in bad if f.rule == "R9"]
    # the inversion: 'outer' (rank 10) taken while holding 'inner' (rank 20)
    assert any("inversion" in m and "'outer'" in m and "'inner'" in m
               for m in msgs), bad
    # the same pair of edges closes a cycle — reported explicitly so a
    # re-ranking "fix" that leaves a loop is still caught
    assert any("cycle" in m for m in msgs), bad
    # registry drift: raw primitive, unregistered factory name, stale entry
    assert any("raw threading.Lock()" in m for m in msgs), bad
    assert any("'unregistered'" in m and "not registered" in m
               for m in msgs), bad
    assert any("stale registry entry 'ghost'" in m for m in msgs), bad
    good = rule.check_repo(os.path.join(FIXTURES, "r9_good"))
    assert not good, good


def test_r10_fires_on_pr9_shape_and_not_on_pr9_fix():
    """Bad twin is the PR 9 handler-deadlock shape (handler closure reaches
    a non-reentrant lock normal paths hold); good twin is the PR 9 FIX
    (literal include_stats=False prunes the locked branch)."""
    rule = R10HandlerSafety()
    bad = rule.check_repo(os.path.join(FIXTURES, "r10_bad"))
    assert any(f.rule == "R10" and "'ring'" in f.message
               and "deadlock" in f.message for f in bad), bad
    good = rule.check_repo(os.path.join(FIXTURES, "r10_good"))
    assert not good, good


def test_r9_registry_site_must_match_construction_site():
    """Moving a construction without updating the registry's site is drift:
    flag it on a copy of the good pair with a wrong site."""
    import shutil
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        shutil.copytree(os.path.join(FIXTURES, "r9_good"), td,
                        dirs_exist_ok=True)
        lc = os.path.join(td, "glint_word2vec_tpu", "lockcheck.py")
        with open(lc, "r", encoding="utf-8") as f:
            src = f.read()
        moved = src.replace(
            '"site": "glint_word2vec_tpu/pipe.py:Pipe.__init__",\n'
            '              "owner": "fixture pipe"},\n    "inner"',
            '"site": "glint_word2vec_tpu/old.py:Old.__init__",\n'
            '              "owner": "fixture pipe"},\n    "inner"')
        assert moved != src, "fixture registry refactored — update anchor"
        with open(lc, "w", encoding="utf-8") as f:
            f.write(moved)
        out = R9LockOrder().check_repo(td)
        assert any("registered at" in f.message and "constructed at"
                   in f.message for f in out), out


def test_repo_rule_findings_honor_suppressions():
    """R9 is a repo rule — the engine only applies suppression directives to
    per-file rules, so the concurrency rules re-apply them per flagged file.
    A justified directive on the raw-construction line must suppress it."""
    import shutil
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        shutil.copytree(os.path.join(FIXTURES, "r9_good"), td,
                        dirs_exist_ok=True)
        pipe = os.path.join(td, "glint_word2vec_tpu", "pipe.py")
        with open(pipe, "a", encoding="utf-8") as f:
            f.write("\nimport threading\n_x = threading.Lock()"
                    "  # graftlint: disable=R9 -- fixture-sanctioned raw\n")
        out = R9LockOrder().check_repo(td)
        raws = [f for f in out if "raw threading.Lock()" in f.message]
        assert raws and all(f.suppressed and f.justification
                            for f in raws), out


def test_r1_staleness_fires_on_dead_entries_and_real_allowlist_is_live():
    """ISSUE 20 satellite: an allowlist entry whose (path, qualname) no
    longer resolves is a finding — on the REAL tree, with the REAL
    allowlist, there must be none (every blessing points at a live def)."""
    assert not R1Staleness().check_repo(REPO)
    stale = R1Staleness(allowlist=[
        ("glint_word2vec_tpu/serve/batcher.py",
         "BatchingScheduler.no_such_method"),
        ("glint_word2vec_tpu/no/such/file.py", "whatever"),
    ])
    out = stale.check_repo(REPO)
    msgs = " ".join(f.message for f in out)
    assert "no_such_method" in msgs and "cannot be parsed/found" in msgs, out


def test_r11_snapshot_escape_requires_name_and_docstring():
    """The documented-snapshot escape is narrow: 'snapshot' in the METHOD
    NAME plus a docstring exempts its accesses; the same unguarded read in
    a method missing either leg stays flagged."""
    tmpl = """
import collections
import threading


class Ring:
    def __init__(self):
        self._lock = threading.Lock()
        self._ring = collections.deque()
        self._t = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        with self._lock:
            self._ring.append(1)

    def {name}(self):
        {doc}return list(self._ring)
"""
    blessed = tmpl.format(
        name="snapshot_ring",
        doc='\"\"\"Callers tolerate a stale copy; GC owns the old one.'
            '\"\"\"\n        ')
    out = engine.lint_text(blessed, _VPATH["R11"])
    assert not [f for f in out if f.rule == "R11"], out
    for name, doc in [("grab", '\"\"\"Some docstring.\"\"\"\n        '),
                      ("snapshot_ring", "")]:
        out = engine.lint_text(tmpl.format(name=name, doc=doc), _VPATH["R11"])
        assert any(f.rule == "R11" for f in out), (name, out)
