"""ctypes binding for the native corpus-ingestion passes (``native/ingest.cpp``).

Same degradation contract as :mod:`.native` (the pair generator): built on
first use with ``g++``, plain C ABI, falls back to the pure-Python path when
the toolchain is unavailable or ``GLINT_DISABLE_NATIVE=1``.

Scope: the HOT LOOPS only — tokenize+count and tokenize+encode over a token
file. The vocabulary filter/sort rules (count desc, stable on first-seen order,
the reference's sortWith contract mllib:266) and the encode metadata stay in
Python, consuming the native passes' output, so both paths share one ordering
implementation. Native applies only to ``lowercase=False`` ASCII-whitespace
corpora (the word2vec norm); anything else takes the Python path, which also
handles unicode whitespace and invalid-UTF-8 replacement.
"""

from __future__ import annotations

import ctypes
import logging
import os
import tempfile
import threading
from typing import Optional

import numpy as np

from glint_word2vec_tpu.train.faults import maybe_fail_ingest, retry_io
from glint_word2vec_tpu.lockcheck import make_lock

logger = logging.getLogger("glint_word2vec_tpu")

_ABI_VERSION = 2
_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "native", "ingest.cpp")
_LIB = os.path.join(os.path.dirname(_SRC), "libingest.so")

_lock = make_lock("data.ingest_native.load")
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if os.environ.get("GLINT_DISABLE_NATIVE"):
            _load_failed = True
            return None
        from glint_word2vec_tpu.data.native import build_or_reload
        lib = build_or_reload(_SRC, _LIB, "glint_ingest_abi_version",
                              _ABI_VERSION, "c++20", "ingest")
        if lib is None:
            _load_failed = True
            return None
        lib.glint_ingest_count.restype = ctypes.c_int64
        lib.glint_ingest_count.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int32]
        lib.glint_ingest_encode.restype = ctypes.c_int64
        lib.glint_ingest_encode.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64)]
        _lib = lib
        return _lib


def ingest_available() -> bool:
    return _load() is not None


def count_words_native(corpus_path: str, n_threads: int):
    """Tokenize+count ``corpus_path``; returns ``(words, counts)`` in FIRST-SEEN
    file order — exactly the iteration order of the Python ``Counter`` the
    fallback builds, so ``Vocabulary.from_counter``'s stable sort gives
    identical vocabularies either way. Returns None on native failure."""
    lib = _load()
    assert lib is not None, "call ingest_available() first"
    with tempfile.TemporaryDirectory(prefix="glint_ingest_") as td:
        wpath = os.path.join(td, "words")
        cpath = os.path.join(td, "counts")

        def attempt() -> int:
            maybe_fail_ingest(f"native ingest count {corpus_path!r}")
            return lib.glint_ingest_count(
                corpus_path.encode(), wpath.encode(), cpath.encode(),
                np.int32(n_threads))

        n = retry_io(attempt, what=f"native ingest count {corpus_path!r}")
        if n == -2:
            logger.info("corpus %r needs Python tokenization semantics "
                        "(unicode whitespace / lone CR / invalid UTF-8); "
                        "using the Python pass", corpus_path)
            return None
        if n < 0:
            logger.warning("native ingest count failed on %r; falling back "
                           "to the Python pass", corpus_path)
            return None
        # idempotent reads of the native pass's finished outputs — safe to
        # retry, unlike the encode passes themselves (graftlint R5)
        with retry_io(lambda: open(wpath, "rb"),
                      what=f"native ingest words {wpath!r}") as f:
            raw = f.read()
        words = raw.decode("utf-8", errors="replace").split("\n")[:-1]
        counts = retry_io(lambda: np.fromfile(cpath, dtype=np.int64),
                          what=f"native ingest counts {cpath!r}")
    if len(words) != n or counts.shape[0] != n:
        logger.warning("native ingest count output inconsistent "
                       "(%d words / %d counts / %d reported); falling back",
                       len(words), counts.shape[0], n)
        return None
    return words, counts


def encode_corpus_native(corpus_path: str, words, max_sentence_length: int,
                         tokens_path: str, offsets_path: str,
                         n_threads: int):
    """Tokenize+encode ``corpus_path`` against the FINAL vocabulary ``words``
    (id == position), writing the tokens.bin/offsets.bin pair EncodedCorpus
    mmaps. Returns ``(total_tokens, n_sentences)``, or None on native
    failure / Python-semantics fallback."""
    lib = _load()
    assert lib is not None, "call ingest_available() first"
    with tempfile.NamedTemporaryFile(prefix="glint_vocab_", suffix=".txt",
                                     delete=False) as tf:
        vocab_path = tf.name
        tf.write("\n".join(words).encode("utf-8") + b"\n")
    try:
        nsents = ctypes.c_int64(0)

        def attempt() -> int:
            # the C pass truncates its output files on open, so a retried
            # attempt restarts clean — same restart-from-scratch contract as
            # the Python pass in corpus.py
            maybe_fail_ingest(f"native ingest encode {corpus_path!r}")
            return lib.glint_ingest_encode(
                corpus_path.encode(), vocab_path.encode(),
                np.int32(max_sentence_length), tokens_path.encode(),
                offsets_path.encode(), np.int32(n_threads),
                ctypes.byref(nsents))

        total = retry_io(attempt, what=f"native ingest encode {corpus_path!r}")
    finally:
        os.unlink(vocab_path)
    if total == -2:
        logger.info("corpus %r needs Python tokenization semantics "
                    "(unicode whitespace / lone CR / invalid UTF-8); "
                    "using the Python pass", corpus_path)
        return None
    if total < 0:
        logger.warning("native ingest encode failed on %r; falling back to "
                       "the Python pass", corpus_path)
        return None
    return int(total), int(nsents.value)
