"""Slope-based microbenchmark harness for the remote-TPU (axon) backend.

``jax.block_until_ready`` through the remote-TPU tunnel can return before device
execution completes, so naive wall-clock loops report fantasy numbers (we measured
"0.007 ms" for a step whose HBM traffic alone needs ~0.1 ms). Two rules make timing
trustworthy:

1. every iteration is data-dependent on the previous one (donated param chain), so the
   device cannot reorder/elide; and
2. the timed region ends with a device→host fetch of a value that depends on the final
   iteration, which genuinely drains the pipeline; and
3. the reported cost is the SLOPE between a short and a long run — constant overheads
   (dispatch, fetch, tunnel RTT) cancel.

Usage: time_chunked(fn, init_carry, args_for_iter, n_lo, n_hi, per_iter_units).
"""

from __future__ import annotations

import time
from typing import Callable

import jax
import jax.numpy as jnp


def _run(fn: Callable, carry, args_for_iter: Callable, n: int, fetch: Callable):
    t0 = time.perf_counter()
    c = carry
    out = None
    for i in range(n):
        c, out = fn(c, *args_for_iter(i))
    # fetch a scalar that depends on the last iteration — this is the real barrier
    _ = float(fetch(c, out))
    return time.perf_counter() - t0


def time_chunked(
    fn: Callable,
    make_carry: Callable[[], object],
    args_for_iter: Callable[[int], tuple],
    n_lo: int = 4,
    n_hi: int = 16,
    fetch: Callable = None,
    warmup: int = 1,
) -> float:
    """Return seconds per iteration of ``fn(carry, *args) -> (carry, out)``,
    overhead-corrected by the two-point slope method."""
    if fetch is None:
        fetch = lambda c, out: jnp.asarray(  # noqa: E731
            jax.tree.leaves(out)[0]).reshape(-1)[0]
    for _ in range(warmup):
        c = make_carry()
        _run(fn, c, args_for_iter, 2, fetch)
    for attempt in range(3):
        t_lo = _run(fn, make_carry(), args_for_iter, n_lo, fetch)
        t_hi = _run(fn, make_carry(), args_for_iter, n_hi, fetch)
        if t_hi > t_lo:
            return (t_hi - t_lo) / (n_hi - n_lo)
    raise RuntimeError(
        f"two-point slope non-positive after 3 attempts "
        f"(t_lo={t_lo:.4f}s @ {n_lo}, t_hi={t_hi:.4f}s @ {n_hi}) — timing too "
        "noisy to report; refusing to publish a fantasy number")
