"""On-device pair generation: subsample + dynamic-window expansion inside jit.

The host pipeline (data/pipeline.py `_block_pairs`, the C4/C5/C6 replacement) ships
4 bytes per training pair (packed uint16 centers+contexts). Through a thin host→device
link — the remote-TPU tunnel here (~9 MB/s honest bandwidth, PERF.md round-4 e2e
analysis), or a DCN-fed multi-host pod — the *feed*, not the host CPU and not the
device step, caps end-to-end throughput. Moving the last two pipeline stages into the
jitted step shrinks the wire format to raw token blocks (~2.1 bytes per token ≈ 1 byte
per pair): the device re-derives every random decision from the same position-keyed
murmur3 lattice as the host (:mod:`glint_word2vec_tpu.data.hashrng`, mirrored by
``native/pairgen.cpp``), so the device stream is **bit-identical** to the host stream
(asserted by tests/test_device_pairgen.py).

Reference parity: this computes the same subsample rule (mllib:371-379, intended float
semantics — see pipeline.py module docstring for the reference's integer-division
no-op) and the same legacy asymmetric window (``b = nextInt(window)``, context span
``[max(0, i-b), min(i+b, len))`` exclusive of ``i``, mllib:381-390), keyed by the raw
token ordinal within (seed, stream, iteration, shard).

Shape discipline: everything is fixed-shape. A step receives T token slots (whole
sentences, zero-padded, ``n_valid`` real) and emits exactly B pair slots; if the drawn
windows yield more than B pairs the tail pairs of the block are dropped (counted and
reported by the trainer), if fewer the tail slots are masked. The host packer targets
~0.85 fill so drops stay rare.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

_GOLDEN = 0x9E3779B9


def _u32(x) -> jax.Array:
    return jnp.asarray(x, jnp.uint32)


def mix32(x: jax.Array) -> jax.Array:
    """murmur3 fmix32 finalizer — jnp twin of data/hashrng.mix32 (bit-identical)."""
    x = _u32(x)
    x = (x ^ (x >> 16)) * _u32(0x85EBCA6B)
    x = (x ^ (x >> 13)) * _u32(0xC2B2AE35)
    return x ^ (x >> 16)


def hash_bits_at(base: jax.Array, ord_lo: jax.Array, ord_hi: jax.Array) -> jax.Array:
    """uint32 bits for 64-bit ordinals given as (lo, hi) uint32 halves — twin of
    data/hashrng.hash_bits_at (which takes uint64; jax runs without x64)."""
    return mix32(ord_lo ^ mix32(ord_hi ^ _u32(0xDEADBEEF)) ^ base)


def hash_u01_at(base, ord_lo, ord_hi) -> jax.Array:
    """float32 uniforms in [0, 1) with 24 mantissa bits — twin of hashrng.hash_u01_at.
    Exact: (bits >> 8) ≤ 2^24 is exactly representable, 2^-24 is a power of two."""
    bits = hash_bits_at(base, ord_lo, ord_hi)
    return (bits >> 8).astype(jnp.float32) * jnp.float32(2.0 ** -24)


def hash_mod_at(base, ord_lo, ord_hi, bound: int) -> jax.Array:
    """draws in [0, bound) — twin of hashrng.hash_mod_at (same modulo bias)."""
    return (hash_bits_at(base, ord_lo, ord_hi) % _u32(bound)).astype(jnp.int32)


def _cumsum_i32(x: jax.Array) -> jax.Array:
    """Inclusive int32 cumsum via a two-level (row-matmul + row-offset) decomposition.

    XLA's 1-D cumulative ops on TPU cost ~0.45 ms at 28k elements (measured);
    reshaping to [rows, 128] and doing the within-row prefix sum as a triangular
    matmul cuts that ~4x. Exactness: every use here sums counts bounded by the
    block size (< 2^24), so the f32 matmul is exact.
    """
    n = x.shape[0]
    rows = -(-n // 128)
    xp = jnp.pad(x, (0, rows * 128 - n)).reshape(rows, 128).astype(jnp.float32)
    tri = jnp.tril(jnp.ones((128, 128), jnp.float32)).T  # [i, j] = 1 iff i <= j
    within = xp @ tri                                    # inclusive row prefix sums
    row_offs = jnp.cumsum(within[:, -1]) - within[:, -1]  # tiny [rows] scan
    return (within + row_offs[:, None]).reshape(-1)[:n].astype(jnp.int32)


class DevicePairs(NamedTuple):
    centers: jax.Array    # int32 [B]
    contexts: jax.Array   # int32 [B]
    mask: jax.Array       # float32 [B] — 1.0 for real pairs
    kept_words: jax.Array  # int32 [] — tokens surviving subsampling this step
    dropped_pairs: jax.Array  # int32 [] — pairs beyond the B slots (lost to overflow)


def device_block_pairs(
    tokens: jax.Array,      # int32/uint16 [T] — raw (NOT subsampled) token ids,
                            # whole sentences, zero-padded past n_valid
    start_bits: jax.Array,  # uint8 [ceil(T/8)] — bit t set ⟺ sentence starts at slot t
    n_valid: jax.Array,     # int32 [] — real token count
    ord_lo: jax.Array,      # uint32 [] — raw-token ordinal of slot 0, low 32 bits
    ord_hi: jax.Array,      # uint32 [] — high 32 bits
    keep_prob: jax.Array,   # float32 [V_pad] — per-word keep probability (C5)
    sub_base: jax.Array,    # uint32 [] — hashrng stream base for STREAM_SUBSAMPLE
    win_base: jax.Array,    # uint32 [] — stream base for STREAM_WINDOW
    window: int,
    num_pairs: int,         # B — output pair slots
    legacy_asymmetric_window: bool = True,
    presubsampled: bool = False,
) -> DevicePairs:
    """One step's (centers, contexts, mask) from a raw token block — C5+C6 on device.

    Mirrors data/pipeline._block_pairs stage for stage; every intermediate is
    fixed-shape [T] or [B]:

      1. subsample: keep ⟺ hash_u01(ordinal) ≤ keep_prob[token]   (mllib:371-379)
      2. compact kept tokens to the front (cumsum + scatter)
      3. segmented positions: pos-in-sentence and distance-to-sentence-end of the
         *subsampled* sentence (windows span the compacted sentence, like the host)
      4. window draw b = hash % window keyed by the raw ordinal    (mllib:384-388)
      5. ragged pair expansion inverted with searchsorted over the cumulative
         per-token pair counts (the jit-able form of numpy's repeat())

    ``presubsampled=True`` is the trainer's production mode: the host packer already
    applied the subsample rule (same hashrng draws on raw ordinals), so the block
    contains only kept tokens — stages 1–2 vanish (no compaction scatter/cumsums),
    the wire carries ~keep_ratio× fewer tokens, and the lr clock is exact. Window
    draws are then keyed by the KEPT-token ordinal (contiguous across blocks);
    statistically identical to raw-ordinal keying, and bit-identical to the host
    ``_block_pairs`` run on the same kept stream with keep ≡ 1.
    """
    T = tokens.shape[0]
    B = num_pairs
    t = jnp.arange(T, dtype=jnp.int32)
    valid = t < n_valid
    tok = tokens.astype(jnp.int32)

    # -- ordinals of each slot as uint32 (lo, hi) with carry ------------------------
    lo = ord_lo + t.astype(jnp.uint32)
    hi = ord_hi + (lo < ord_lo).astype(jnp.uint32)

    # -- sentence ids on the raw stream ---------------------------------------------
    is_start = ((start_bits[t >> 3] >> (t & 7).astype(jnp.uint8)) & 1).astype(
        jnp.bool_) & valid
    sid = _cumsum_i32(is_start.astype(jnp.int32))      # [T] raw sentence id (≥1)

    if presubsampled:
        # host already dropped subsampled tokens — the block IS the kept stream
        n_kept = n_valid
        comp_tok, comp_lo, comp_hi = tok, lo, hi
        ck = valid
        comp_sid = jnp.where(ck, sid, -1)
    else:
        # -- 1. subsample ------------------------------------------------------------
        u = hash_u01_at(sub_base, lo, hi)
        kept = valid & (u <= keep_prob[tok])
        kept_i = kept.astype(jnp.int32)
        n_kept = kept_i.sum()

        # -- 2. compact kept tokens (ONE scatter of the source permutation; scatters
        # are the expensive op on TPU — PERF.md — everything else routes via gathers)
        kpos = _cumsum_i32(kept_i) - 1                 # compact index of kept slots
        dst = jnp.where(kept, kpos, T)                 # OOB → dropped
        comp_src = jnp.zeros(T, jnp.int32).at[dst].set(t, mode="drop")
        comp_tok = tok[comp_src]
        comp_lo = lo[comp_src]
        comp_hi = hi[comp_src]
        # a kept token opens a compacted sentence iff it is the first kept token of
        # its raw sentence: diff the raw sentence ids on the compacted stream
        comp_sid = sid[comp_src]
        ck = t < n_kept                                # valid compacted slots
        comp_sid = jnp.where(ck, comp_sid, -1)
    prev_sid = jnp.concatenate([jnp.full(1, -2, jnp.int32), comp_sid[:-1]])
    new_sent = (comp_sid != prev_sid) & ck

    # -- 3. segmented position / distance-to-end on the compacted stream -------------
    seg_base = jax.lax.cummax(jnp.where(new_sent, t, 0))
    pos = t - seg_base                                 # kept-position in sentence
    # next sentence start at or after t+1 (sentinel n_kept) → distance to sentence end
    ns = jnp.where(new_sent, t, T)
    ns_next = jnp.concatenate([ns[1:], jnp.full(1, T, jnp.int32)])
    seg_end = jnp.flip(jax.lax.cummin(jnp.flip(ns_next)))
    seg_end = jnp.minimum(seg_end, n_kept)             # [T] one-past-last of sentence
    right_avail = seg_end - 1 - t

    # -- 4. window draw (keyed by RAW ordinal, like the host) -------------------------
    b = hash_mod_at(win_base, comp_lo, comp_hi, window)
    left = jnp.minimum(b, pos)
    right_extent = b - 1 if legacy_asymmetric_window else b
    right = jnp.clip(jnp.minimum(right_extent, right_avail), 0, None)
    total = jnp.where(ck, left + right, 0)

    # -- 5. ragged expansion: invert the cumulative pair counts ----------------------
    # The queries are arange(B), so the searchsorted inverse collapses to a scatter
    # of +1 marks at each token's first pair slot followed by a cumsum — one [T]-row
    # scatter (ascending indices) + one [B] cumsum, ~10x cheaper than searchsorted's
    # sequential scan method on TPU (measured; empty groups resolve correctly
    # because their marks stack on the next group's start slot).
    offs = _cumsum_i32(total)                          # [T] inclusive
    total_pairs = offs[-1]
    k = jnp.arange(B, dtype=jnp.int32)
    group_start = offs - total
    marks = jnp.zeros(B, jnp.int32).at[group_start].add(
        1, mode="drop", indices_are_sorted=True)
    src = _cumsum_i32(marks) - 1                       # [B] source token per slot
    src_c = jnp.clip(src, 0, T - 1)
    # one [B, 3] row gather instead of three [B] scalar gathers (group start,
    # window left bound, center token travel together)
    packed = jnp.stack([group_start, left, comp_tok], axis=1)   # [T, 3]
    g = packed[src_c]                                  # [B, 3]
    j = k - g[:, 0]
    left_s = g[:, 1]
    ctx = src_c - left_s + j + (j >= left_s)
    ctx_c = jnp.clip(ctx, 0, T - 1)
    mask = (k < jnp.minimum(total_pairs, B)).astype(jnp.float32)
    centers = jnp.where(mask > 0, g[:, 2], 0)
    contexts = jnp.where(mask > 0, comp_tok[ctx_c], 0)
    return DevicePairs(
        centers=centers, contexts=contexts, mask=mask,
        kept_words=n_kept,
        dropped_pairs=jnp.maximum(total_pairs - B, 0))


class CbowBand(NamedTuple):
    """Per-slot CBOW window geometry over a sentence-contiguous token block —
    the device-side contract of the banded CBOW step (ops/cbow_banded.py)."""

    left: jax.Array    # int32 [T] — context extent to the left of each slot
    right: jax.Array   # int32 [T] — context extent to the right
    center: jax.Array  # float32 [T] — 1.0 where the slot is a CORE center
                       # (trains an example this block; halo slots are 0)
    token: jax.Array   # float32 [T] — 1.0 for valid token slots (slots that may
                       # receive context gradient; zero-padding is 0)


def device_cbow_windows(
    tokens: jax.Array,      # int32/uint16 [T] — KEPT (presubsampled) tokens,
                            # sentence-contiguous, ±halo overlap at block edges
    start_bits: jax.Array,  # uint8 [ceil(T/8)] — bit t set ⟺ sentence starts at t
    n_valid: jax.Array,     # int32 [] — real token slots (prefix)
    ord_lo: jax.Array,      # uint32 [] — kept-token ordinal of slot 0, low 32 bits
    ord_hi: jax.Array,      # uint32 [] — high 32 bits
    win_base: jax.Array,    # uint32 [] — hashrng stream base for STREAM_WINDOW
    window: int,
    halo: int,              # core slots are [halo, T - halo); needs halo >= window
    legacy_asymmetric_window: bool = True,
) -> CbowBand:
    """Per-slot CBOW window extents from the hash lattice — the banded analog of
    :func:`device_block_pairs` stages 3–4, skipping the ragged pair expansion:
    the banded step (:func:`glint_word2vec_tpu.ops.cbow_banded.cbow_step_banded_core`)
    consumes (left, right) intervals directly instead of materialized pairs.

    The block is the kept-token stream cut with a ±``halo`` overlap
    (:func:`glint_word2vec_tpu.data.pipeline.pack_halo_token_blocks`), so window
    clamping is EXACT for every core slot with ``halo >= window``:

    - left: ``l = min(b, pos)`` with pos measured from the last in-block sentence
      start (slot 0 as implicit base). If the sentence started before the block,
      ``pos >= t >= halo > b`` and the clamp never binds — identical to the true
      stream. If it started in-block the start bit makes pos exact.
    - right: ``r`` is clamped by the next in-block start bit or ``n_valid``. A
      sentence end within reach of a core slot (r ≤ window-1 < halo) always has
      its successor's start bit (or the stream end) inside the block, so the
      clamp is exact too.

    Window draws are keyed by the kept-token ordinal (``ord_base + t``), the same
    key :func:`device_block_pairs` uses under ``presubsampled=True`` — a token
    draws the same window in every block that holds it (halo or core).
    """
    T = tokens.shape[0]
    t = jnp.arange(T, dtype=jnp.int32)
    valid = t < n_valid

    lo = ord_lo + t.astype(jnp.uint32)
    hi = ord_hi + (lo < ord_lo).astype(jnp.uint32)

    is_start = ((start_bits[t >> 3] >> (t & 7).astype(jnp.uint8)) & 1).astype(
        jnp.bool_) & valid
    seg_base = jax.lax.cummax(jnp.where(is_start, t, 0))
    pos = t - seg_base
    ns = jnp.where(is_start, t, T)
    ns_next = jnp.concatenate([ns[1:], jnp.full(1, T, jnp.int32)])
    seg_end = jnp.flip(jax.lax.cummin(jnp.flip(ns_next)))
    seg_end = jnp.minimum(seg_end, n_valid)
    right_avail = seg_end - 1 - t

    b = hash_mod_at(win_base, lo, hi, window)
    left = jnp.minimum(b, pos)
    right_extent = b - 1 if legacy_asymmetric_window else b
    right = jnp.clip(jnp.minimum(right_extent, right_avail), 0, None)
    left = jnp.where(valid, left, 0)
    right = jnp.where(valid, right, 0)
    core = (t >= halo) & (t < T - halo) & valid
    return CbowBand(
        left=left, right=right,
        center=core.astype(jnp.float32),
        token=valid.astype(jnp.float32))


def pack_start_bits(lengths: np.ndarray, T: int) -> np.ndarray:
    """Host-side: sentence lengths → the packed start-bit array a step ships.

    uint8 [ceil(T/8)], bit t set iff a sentence begins at token slot t. Padding
    slots carry no bits (they are already masked by n_valid on device).
    """
    bits = np.zeros((T + 7) // 8, np.uint8)
    starts = np.concatenate([[0], np.cumsum(lengths)[:-1]]).astype(np.int64)
    starts = starts[starts < T]
    np.bitwise_or.at(bits, starts >> 3, (1 << (starts & 7)).astype(np.uint8))
    return bits
