"""graftlint (layer-1 static analysis, ISSUE 5): every rule fires on its bad
fixture and stays silent on the good twin; the suppression syntax enforces a
written justification; and the CURRENT TREE lints clean with the committed
suppression baseline — so any PR that re-introduces an ad-hoc thread pool, an
unseeded RNG, a host sync inside jit, a bf16 prefix sum, a bare data-plane
read, raw trainer device placement, a stray stdout print in a contract tool,
or a dispatch-only knob refusal fails tier-1, not review."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.graftlint import engine  # noqa: E402
from tools.graftlint.rules import R8RefusalParity  # noqa: E402

FIXTURES = os.path.join(REPO, "tests", "fixtures", "lint")

# rule id -> the virtual repo path the fixture pretends to live at (rules are
# path-scoped: R5 only watches data/, R6 only the trainer, R7 the contract
# tools, the rest all library code)
_VPATH = {
    "R1": "glint_word2vec_tpu/ops/somefile.py",
    "R2": "glint_word2vec_tpu/ops/somefile.py",
    "R3": "glint_word2vec_tpu/ops/somefile.py",
    "R4": "glint_word2vec_tpu/ops/somefile.py",
    "R5": "glint_word2vec_tpu/data/somefile.py",
    "R6": "glint_word2vec_tpu/train/trainer.py",
    "R7": "bench.py",
}


def _fixture(name: str) -> str:
    with open(os.path.join(FIXTURES, name), "r", encoding="utf-8") as f:
        return f.read()


@pytest.mark.parametrize("rule_id", sorted(_VPATH))
def test_rule_fires_on_bad_and_not_on_good(rule_id):
    vpath = _VPATH[rule_id]
    bad = engine.lint_text(_fixture(f"{rule_id.lower()}_bad.py"), vpath)
    assert any(f.rule == rule_id and not f.suppressed for f in bad), (
        f"{rule_id} did not fire on its bad fixture: {bad}")
    good = engine.lint_text(_fixture(f"{rule_id.lower()}_good.py"), vpath)
    assert not [f for f in good if f.rule == rule_id], (
        f"{rule_id} false-positived on its good fixture: {good}")


def test_r3_flags_every_host_sync_kind():
    bad = engine.lint_text(_fixture("r3_bad.py"), _VPATH["R3"])
    msgs = " ".join(f.message for f in bad if f.rule == "R3")
    assert "float" in msgs and "asarray" in msgs and "clock" in msgs


def test_r3_transitive_helper_coverage():
    """ISSUE 8 satellite: host syncs in a same-module HELPER the jitted
    function calls by name (the obs/probe.py `_matrix_stats` shape) are in
    scope; the good twin (device-pure helper, host flattening outside the
    jit boundary) stays silent."""
    vpath = "glint_word2vec_tpu/obs/somefile.py"
    bad = engine.lint_text(_fixture("r3_trans_bad.py"), vpath)
    msgs = " ".join(f.message for f in bad if f.rule == "R3")
    assert "concretizes" in msgs and "clock" in msgs, bad
    good = engine.lint_text(_fixture("r3_trans_good.py"), vpath)
    assert not [f for f in good if f.rule == "R3"], good


def test_r3_reaches_the_real_probe_helpers():
    """The closure genuinely covers obs/probe.py: poisoning `_matrix_stats`
    (called from the jitted fused probe, not itself a jit target) with a
    float() concretization must fire R3."""
    path = os.path.join(REPO, "glint_word2vec_tpu", "obs", "probe.py")
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    poisoned = src.replace(
        "    return MatrixStats(",
        "    bad = float(norms.sum())\n    return MatrixStats(")
    assert poisoned != src, "probe.py refactored — update the poison anchor"
    found = engine.lint_text(poisoned, "glint_word2vec_tpu/obs/probe.py")
    assert [f for f in found if f.rule == "R3"], found
    # and the committed module itself is clean under the wider scan
    clean = engine.lint_text(src, "glint_word2vec_tpu/obs/probe.py")
    assert not [f for f in clean if f.rule == "R3"], clean
    watch_path = os.path.join(REPO, "glint_word2vec_tpu", "obs", "watch.py")
    with open(watch_path, "r", encoding="utf-8") as f:
        watch_src = f.read()
    assert not [f for f in engine.lint_text(
        watch_src, "glint_word2vec_tpu/obs/watch.py") if f.rule == "R3"]


def test_r7_counts_second_json_line():
    bad = engine.lint_text(_fixture("r7_bad.py"), _VPATH["R7"])
    assert any("exactly ONE JSON line" in f.message for f in bad)


def test_r8_fires_on_bad_pair_and_not_on_good_pair():
    rule = R8RefusalParity()
    bad = rule.check_repo(os.path.join(FIXTURES, "r8_bad"))
    msgs = [f.message for f in bad if f.rule == "R8"]
    # combo with no config twin at all
    assert any("cbow" in m and "use_pallas" in m for m in msgs), bad
    # combo "covered" only by a single-knob RANGE check — not coverage:
    # the rule must not be blinded by config range checks on a member knob
    assert any("cbow" in m and "negative_pool" in m for m in msgs), bad
    # a NEW stabilizer-class knob with a dispatch-only refusal (ISSUE 7):
    # the range check on max_row_norm must not count as combo coverage
    assert any("max_row_norm" in m and "use_pallas" in m for m in msgs), bad
    # a refusal living in Trainer.__init__ path selection, not _build_step —
    # the device_pairgen class graftcheck's first run caught in the real
    # tree (ISSUE 8): __init__ is now a scanned dispatch surface
    assert any("device_pairgen" in m and "cbow" in m for m in msgs), bad
    # a step-cadence knob whose window exists for one lowering only
    # (ISSUE 17): the config-side positivity check on sync_every must not
    # count as coverage for the {sync_every, step_lowering} dispatch combo
    assert any("sync_every" in m and "step_lowering" in m for m in msgs), bad
    good = rule.check_repo(os.path.join(FIXTURES, "r8_good"))
    assert not good, good


def test_r8_cross_references_graftcheck_registry():
    """R8's graftcheck cross-reference: every config field needs a knob
    entry in tools/graftcheck/registry.py. Verified both ways — the real
    tree is clean, and a field invented on a copied config must be flagged
    as missing from the registry."""
    import shutil
    import tempfile

    rule = R8RefusalParity()
    assert not [f for f in rule.check_repo(REPO)
                if "registry" in f.message], "real tree should be in sync"
    with tempfile.TemporaryDirectory() as td:
        for rel in ("glint_word2vec_tpu/config.py",
                    "glint_word2vec_tpu/train/trainer.py",
                    "tools/graftcheck/registry.py"):
            dst = os.path.join(td, rel)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            shutil.copy(os.path.join(REPO, rel), dst)
        cfg_path = os.path.join(td, "glint_word2vec_tpu", "config.py")
        with open(cfg_path, "r", encoding="utf-8") as f:
            src = f.read()
        src = src.replace("    vector_size: int = 100",
                          "    brand_new_knob: int = 0\n"
                          "    vector_size: int = 100")
        with open(cfg_path, "w", encoding="utf-8") as f:
            f.write(src)
        found = rule.check_repo(td)
        assert any("brand_new_knob" in f.message and "registry" in f.message
                   for f in found), found


def test_suppression_requires_justification():
    src = _fixture("r4_bad.py")
    # justified suppression on the line above the finding
    justified = src.replace(
        "    prefix = jnp.cumsum(rows, axis=0)",
        "    # graftlint: disable=R4 -- fixture: exactness argued elsewhere\n"
        "    prefix = jnp.cumsum(rows, axis=0)")
    out = engine.lint_text(justified, _VPATH["R4"])
    assert [f for f in out if f.rule == "R4" and f.suppressed]
    assert not [f for f in out if not f.suppressed]
    # a directive WITHOUT justification suppresses nothing and is itself
    # a finding
    silent = src.replace(
        "    prefix = jnp.cumsum(rows, axis=0)",
        "    prefix = jnp.cumsum(rows, axis=0)  # graftlint: disable=R4")
    out = engine.lint_text(silent, _VPATH["R4"])
    assert [f for f in out if f.rule == "R4" and not f.suppressed]
    assert [f for f in out if f.rule == "SUP"]


def test_trailing_suppression_on_flagged_line():
    src = _fixture("r4_bad.py").replace(
        "    prefix = jnp.cumsum(rows, axis=0)",
        "    prefix = jnp.cumsum(rows, axis=0)"
        "  # graftlint: disable=R4 -- fixture")
    out = engine.lint_text(src, _VPATH["R4"])
    assert [f for f in out if f.rule == "R4" and f.suppressed]
    assert not [f for f in out if not f.suppressed]


def test_tree_lints_clean_with_baseline():
    """THE acceptance gate: zero unsuppressed findings on the tree and the
    suppression inventory matches the committed baseline exactly."""
    report = engine.lint_repo(REPO)
    assert not report.unsuppressed, "\n".join(
        f.key() for f in report.unsuppressed)
    drift = engine.check_baseline(
        report, os.path.join(REPO, "tools", "graftlint", "baseline.json"))
    assert not drift, drift
    # every suppression that IS in the tree carries a justification
    assert all(f.justification for f in report.suppressed)


def test_cli_json_contract():
    """`python -m tools.graftlint --json` exits 0 on the tree and emits one
    parseable JSON report on stdout (the CI wiring)."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--json"],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] and payload["tool"] == "graftlint"
    assert payload["files_scanned"] > 40


def test_missing_baseline_fails_closed():
    """A deleted/typo'd baseline path must FAIL the run, not silently skip
    the suppression-inventory gate (explicit --no-baseline is the only
    opt-out)."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint",
         "--baseline", "tools/graftlint/no-such-baseline.json"],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert proc.returncode == 1
    assert "baseline file not found" in proc.stderr
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--no-baseline"],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert proc.returncode == 0, proc.stderr


def test_ruff_clean_if_available():
    """The generic-lint layer (pyproject [tool.ruff]): pyflakes/E9 clean.
    Skips when the ruff binary is absent (this container does not vendor it);
    CI installs it and fails the lint job on any finding."""
    import shutil

    if shutil.which("ruff") is None:
        pytest.skip("ruff not installed (CI runs it)")
    proc = subprocess.run(["ruff", "check", "."], capture_output=True,
                          text=True, timeout=300, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_fixtures_are_out_of_lint_scope():
    """The bad fixtures must never be swept into the repo lint (they exist to
    fail)."""
    scanned = {os.path.relpath(p, REPO).replace(os.sep, "/")
               for p in engine.iter_source_files(REPO)}
    assert not any(p.startswith("tests/") for p in scanned)
    assert "tools/graftlint/rules.py" not in scanned  # rules discuss patterns
