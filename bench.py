"""Benchmark: fused SGNS training throughput (word-pairs/sec + MFU) on one chip.

Measures the framework's production hot path — the Trainer's scan-chunked jitted step
(glint_word2vec_tpu/train/trainer.py): gather → batched dots → sigmoid → scatter-add,
negatives from the counter-based hash PRNG drawn once per chunk — on a realistic
single-chip config:

    vocab 200k (Zipf counts), d=300 (lane-padded to 384), 5 negatives over a shared
    64-pool, 32k/64k pairs/step (BASELINE configs 2-3 territory; the reference's
    per-minibatch RPC budget capped it at ~65 pairs per round-trip, mllib:83-85)

Batch indices are drawn from the SAME Zipf distribution as the vocab counts (round 3
change): real corpora hit frequent rows constantly, duplicate rows serialize inside the
scatter's read-modify-write, and uniform-index benchmarks hide that cost (~7% at f32,
~13% at bf16 — measured). The numbers below are therefore slightly lower but honest.

Timing methodology (tools/microbench.py): through the remote-TPU tunnel,
``block_until_ready`` can return before device execution finishes, so naive loops
report fantasy numbers. Every number here is a two-point SLOPE over donated,
data-dependent chunk chains ending in a device→host fetch — constant overheads cancel,
elision is impossible.

Reported rows (stderr; e2e runs FIRST — the step benches leave allocator state
behind that throttles the host producer):
    e2e trainer         — Word2Vec-style end-to-end incl. the host pipeline (median
                          of 3 trials; single trials scatter 2x through the tunnel)
    step xla f32/f32    — the default-precision step at B=32k (round-2 continuity) + 64k
    step xla bf16/bf16  — bf16-stored embeddings: rows are 768 B instead of 1536 B, and
                          the step is row-byte-bound, so this is the single biggest
                          lever (measured +30-40%). Both toy-corpus semantic gates pass
                          at bf16 (tests/test_integration_toy.py gates re-run at
                          param_dtype=bfloat16), so it is a supported fast path —
                          f32 stays the default for precision headroom on huge runs.
    step xla pool=1024  — the MFU-frontier row: negative-pool math is MXU matmuls, so
                          growing the pool raises arithmetic intensity (MFU 0.6% → 8%+)
                          at a modest pairs/s cost; quality per pair improves (more
                          negatives). Kept out of the headline because pairs/s is the
                          decision metric.
    step pallas         — the fused-kernel tier, retained as a correctness-proven
                          reference implementation. Measured verdict (round 3 sweeps,
                          tools/sweep.py): per-row async-copy issue overhead on the
                          scalar core (~0.25 µs/DMA × 4 DMAs/pair) dominates; ring
                          depth 8→32 and tile 256→512 change nothing (±5%), so the
                          row-at-a-time design cannot beat XLA's vectorized
                          gather/scatter (~60-90 ns/row). Demoted, not deleted: the
                          analysis is recorded in ops/pallas/sgns_kernel.py.
    cpu-torch           — identical step math on the host CPU (the measured baseline)

MFU ceiling analysis (why the BASELINE ≥50% north star does not apply to SGNS):
at d=300/pool=64 the step moves ~6 row-bytes per matmul FLOP; a perfectly fused
implementation at v5e HBM bandwidth (~819 GB/s) would still spend >95% of its time on
row traffic, bounding MFU below ~2% at pool=64. MFU scales with pool size (see the
pool=1024 row) because only the pool matmuls use the MXU. pairs/s is the decision
metric; MFU is reported because BASELINE names it.

The reference publishes no numbers (BASELINE.md: "none"), so ``vs_baseline`` is measured,
not quoted: the identical step math implemented with torch on the host CPU (gather +
einsum + index_add_), i.e. "what this machine could do without the accelerator". Values
> 1 mean the TPU path wins.

Prints exactly one JSON line on stdout with the headline step metric; the full row table
goes to stderr.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools"))

V, D, NEG = 200_000, 300, 5
POOL = 64
PAD_D = 384        # lane-padded physical dim (config.pad_vector_to_lanes)
K = 16             # steps per dispatch chunk (config.steps_per_dispatch)
E2E_B = 65536      # e2e trainer batch: geometry sweep winner (bigger batches
                   # amortize both scatter row cost and feed transfers)
E2E_K = 32         # e2e steps per dispatch: bigger chunks -> fewer, larger feed
                   # transfers (the tunnel/DCN link rewards both)
E2E_POOL = 256     # scaled with E2E_B: pool-row load B*n/P must stay ~1300 or the run
                   # diverges (EVAL.md finding 2); pool 64 at B=65536 trains to NaN.
                   # subsample 1e-4 in the e2e config for the same reason: without it
                   # the top Zipf word is ~650 duplicate contexts per 64k batch and
                   # their summed scatter updates explode (EVAL.md)
CPU_STEPS = 10
CPU_B = 8192
PEAK_FLOPS = 197e12  # v5e bf16 peak / chip


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def zipf_counts(v: int) -> np.ndarray:
    return np.maximum(1e9 / (np.arange(v) + 10.0) ** 1.07, 5.0)


def step_flops(pool: int, b: int) -> float:
    """Matmul FLOPs per step of the shared-pool path: f_neg (B,D)x(D,P),
    d_in += g_neg@Z (B,P)x(P,D), d_Z = g_negT@e_in (P,B)x(B,D), plus elementwise."""
    return 3 * 2.0 * b * pool * PAD_D + 10.0 * b * PAD_D


_ZIPF_P = None


def _zipf_indices(rng, shape) -> np.ndarray:
    """Batch indices with the corpus's own frequency profile — scatter RMW serializes
    on duplicate rows, so uniform indices understate the real step cost."""
    global _ZIPF_P
    if _ZIPF_P is None:
        c = zipf_counts(V)
        _ZIPF_P = c / c.sum()
    return rng.choice(V, size=shape, p=_ZIPF_P)


def bench_step(counts, b: int, dtype: str = "float32", param_dtype: str = "float32",
               pool: int = POOL, use_pallas: bool = False) -> tuple:
    import jax
    import jax.numpy as jnp
    from microbench import time_chunked

    from glint_word2vec_tpu.ops.sampler import build_alias_table, sample_negatives_hash
    from glint_word2vec_tpu.ops.sgns import (
        EmbeddingPair, init_embeddings, sgns_step_shared_core)

    table = build_alias_table(counts)
    prob, alias = table.prob, table.alias
    pdt = jnp.dtype(param_dtype)
    syn0_0 = init_embeddings(V, PAD_D, jax.random.key(0)).syn0.astype(pdt)
    rng = np.random.default_rng(0)
    syn1_0 = jnp.asarray(rng.normal(0, 0.05, (V, PAD_D)), pdt)

    if use_pallas:
        from glint_word2vec_tpu.ops.pallas.sgns_kernel import make_pallas_sgns_step
        core = make_pallas_sgns_step(NEG, pool, "exact", jnp.float32)
    else:
        cdt = jnp.dtype(dtype)

        def core(p, batch, negs, alpha):
            return sgns_step_shared_core(
                p, batch["centers"], batch["contexts"], batch["mask"],
                negs, alpha, NEG, "exact", cdt)

    def chunk(params, batches, base_step, prob, alias):
        negs = sample_negatives_hash(prob, alias, 1234, base_step, (K, pool))

        def body(p, inp):
            batch, ng = inp
            new_p, m = core(p, batch, ng, jnp.float32(0.025))
            return new_p, m.loss

        return jax.lax.scan(body, params, (batches, negs))

    f = jax.jit(chunk, donate_argnums=(0,))

    all_batches = []
    for i in range(12):
        r = np.random.default_rng(1000 + i)
        all_batches.append({
            "centers": jnp.asarray(_zipf_indices(r, (K, b)), jnp.int32),
            "contexts": jnp.asarray(_zipf_indices(r, (K, b)), jnp.int32),
            "mask": jnp.ones((K, b), jnp.float32),
        })

    def run(p, batches, base):
        return f(p, batches, base, prob, alias)

    spc = time_chunked(
        run,
        make_carry=lambda: EmbeddingPair(syn0_0 + 0, syn1_0 + 0),
        args_for_iter=lambda i: (all_batches[i % 12], np.int32(100 + i)),
        n_lo=4, n_hi=16,
        fetch=lambda c, out: out[-1])
    ms = spc / K * 1e3
    pps = b / (spc / K)
    mfu = step_flops(pool, b) / (spc / K) / PEAK_FLOPS
    short = {"float32": "f32", "bfloat16": "bf16"}
    label = ("pallas" if use_pallas
             else f"xla {short.get(dtype, dtype)}/{short.get(param_dtype, param_dtype)}")
    log(f"step {label:14s} B={b:6d} pool={pool:5d}: {ms:7.3f} ms/step -> "
        f"{pps:13,.0f} pairs/s  mfu={mfu * 100:5.2f}%")
    return pps, mfu


def bench_e2e() -> float:
    """End-to-end Word2Vec.fit on a synthetic Zipf corpus — includes vocab build,
    subsampling, window generation, batch packing, host→device transfer."""
    import jax

    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.data.pipeline import encode_sentences
    from glint_word2vec_tpu.data.vocab import build_vocab
    from glint_word2vec_tpu.train.trainer import Trainer

    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    n_words, sent_len, vocab_sz = 4_000_000, 40, 50_000
    zipf = 1.0 / (np.arange(vocab_sz) + 10.0) ** 1.05
    ids = rng.choice(vocab_sz, size=n_words, p=zipf / zipf.sum())
    words = np.char.add("w", ids.astype("U8"))
    sentences = [list(words[i:i + sent_len])
                 for i in range(0, n_words, sent_len)]
    vocab = build_vocab(sentences, min_count=5)
    cfg = Word2VecConfig(
        vector_size=D, min_count=5, pairs_per_batch=E2E_B, num_iterations=1,
        window=5, negatives=NEG, negative_pool=E2E_POOL, steps_per_dispatch=E2E_K,
        seed=1, subsample_ratio=1e-4)
    encoded = encode_sentences(sentences, vocab, cfg.max_sentence_length)
    trainer = Trainer(cfg, vocab)
    # warm the jit cache on the SAME trainer: one tiny fit would change train state, so
    # drive one dispatch-shaped call through the step fn directly
    trainer.fit(encoded[:400])
    # 3 trials, report the median: through the remote-TPU tunnel the first full pass
    # after a reset is reproducibly 2x slower than steady state (transfer-path warmup),
    # and single-trial numbers scatter 2x (measured 2.0-5.3M on identical configs)
    rates = []
    for trial in range(3):
        trainer.state = type(trainer.state)()  # reset progress; params stay warm
        trainer.pairs_trained = 0.0
        t0 = time.perf_counter()
        trainer.fit(encoded)
        # a dependent device->host fetch, not block_until_ready: through the remote-TPU
        # tunnel the latter can return before execution finishes (see tools/microbench.py)
        float(jnp.sum(trainer.params.syn0[:128]))
        dt = time.perf_counter() - t0
        rates.append(trainer.pairs_trained / dt)
        if not np.isfinite(float(jnp.sum(trainer.params.syn0[:1024]))):
            raise RuntimeError("e2e training diverged (NaN params) — the bench must "
                               "measure a run that actually learns")
        log(f"  e2e trial {trial}: {trainer.pairs_trained:,.0f} pairs in {dt:.1f}s -> "
            f"{rates[-1]:,.0f} pairs/s  [host-wait {trainer.host_wait_time:.2f}s, "
            f"dispatch {trainer.dispatch_time:.2f}s]")
    pps = float(np.median(rates))
    log(f"e2e trainer (host pipeline incl.): median {pps:,.0f} pairs/s over 3 trials")
    return pps


def bench_cpu_torch(counts: np.ndarray) -> float:
    """Same step math on host CPU with torch (gather/einsum/index_add_)."""
    import torch

    B = CPU_B
    torch.manual_seed(0)
    g = torch.Generator().manual_seed(0)
    syn0 = (torch.rand(V, D, generator=g) - 0.5) / D
    syn1 = torch.zeros(V, D)
    probs = torch.tensor(counts ** 0.75, dtype=torch.float64)
    probs /= probs.sum()
    alpha = 0.025
    rng = np.random.default_rng(0)
    centers = torch.tensor(_zipf_indices(rng, B), dtype=torch.long)
    contexts = torch.tensor(_zipf_indices(rng, B), dtype=torch.long)

    def step():
        negatives = torch.multinomial(probs.float(), POOL, replacement=True)
        e_in = syn0[centers]
        e_pos = syn1[contexts]
        Z = syn1[negatives]
        f_pos = (e_in * e_pos).sum(-1)
        f_neg = e_in @ Z.T
        neg_valid = (negatives[None, :] != contexts[:, None]).float()
        g_pos = (1 - torch.sigmoid(f_pos)) * alpha
        g_neg = (0 - torch.sigmoid(f_neg)) * alpha * neg_valid * (NEG / POOL)
        d_in = g_pos[:, None] * e_pos + g_neg @ Z
        syn0.index_add_(0, centers, d_in)
        syn1.index_add_(0, contexts, g_pos[:, None] * e_in)
        syn1.index_add_(0, negatives, g_neg.T @ e_in)

    step()  # warmup
    t0 = time.perf_counter()
    for _ in range(CPU_STEPS):
        step()
    dt = time.perf_counter() - t0
    pps = CPU_STEPS * B / dt
    log(f"cpu-torch baseline: {CPU_STEPS} steps in {dt:.3f}s -> {pps:,.0f} pairs/s")
    return pps


def main() -> None:
    import jax
    dev = jax.devices()[0]
    log(f"device: {dev} ({dev.platform})")
    counts = zipf_counts(V)

    # e2e runs FIRST: the step benches leave multi-GB allocator/page-cache state
    # behind that measurably slows the host producer thread (median e2e dropped
    # ~2x when run last)
    try:
        e2e_pps = bench_e2e()
    except Exception as e:
        log(f"e2e bench failed: {type(e).__name__}: {e}")
        e2e_pps = None
    rows = {}
    rows["f32_32k"] = bench_step(counts, b=32768)
    rows["f32_64k"] = bench_step(counts, b=65536)
    rows["bf16_64k"] = bench_step(counts, b=65536, dtype="bfloat16",
                                  param_dtype="bfloat16")
    try:
        rows["pool1024"] = bench_step(counts, b=32768, pool=1024)
    except Exception as e:
        log(f"pool=1024 row failed: {type(e).__name__}: {e}")
    try:
        bench_step(counts, b=8192, use_pallas=True)
    except Exception as e:
        log(f"pallas step failed: {type(e).__name__}: {e}")

    try:
        cpu_pps = bench_cpu_torch(counts)
    except Exception as e:  # torch missing or OOM: report absolute number only
        log(f"cpu baseline failed: {e}")
        cpu_pps = None
    head_key = max(("f32_32k", "f32_64k", "bf16_64k"), key=lambda k: rows[k][0])
    main_pps, main_mfu = rows[head_key]
    result = {
        "metric": "sgns_word_pairs_per_sec_per_chip",
        "value": round(main_pps),
        "unit": "pairs/s",
        "vs_baseline": round(main_pps / cpu_pps, 2) if cpu_pps else 1.0,
        "mfu": round(main_mfu, 4),
        "config": head_key,
        "step_f32_pairs_per_sec": round(rows["f32_64k"][0]),
        "mfu_pool1024": round(rows["pool1024"][1], 4) if "pool1024" in rows else None,
        "e2e_pairs_per_sec": round(e2e_pps) if e2e_pps else None,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
