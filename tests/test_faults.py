"""Fault-tolerance suite (docs/robustness.md): checkpoint integrity digests,
torn-save recovery via ``load_latest_valid`` (SIGKILL-driven, but the kill is a
deterministic fault point — no timing races), the trainer's non-finite
guardrail policies, and the retrying ingest wrappers.

The crash legs run as subprocesses because SIGKILL is the fault model under
test: no ``finally`` blocks, no atexit — the same surface as a preemption."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.pipeline import encode_sentences
from glint_word2vec_tpu.data.vocab import build_vocab
from glint_word2vec_tpu.parallel.mesh import make_mesh
from glint_word2vec_tpu.train import faults
from glint_word2vec_tpu.train.checkpoint import (
    CheckpointCorruptError,
    TrainState,
    load_latest_valid,
    load_model,
    save_model,
    verify_checkpoint,
)
from glint_word2vec_tpu.train.faults import InjectedFault, NonFiniteParamsError
from glint_word2vec_tpu.train.trainer import Trainer

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _flip_byte(path, offset=130):
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))


def _save(path, step=1, scale=1.0):
    words = ["w0", "w1", "w2"]
    counts = np.array([30, 20, 10])
    syn0 = scale * np.arange(12, dtype=np.float32).reshape(3, 4)
    save_model(path, words, counts, syn0, -syn0, Word2VecConfig(vector_size=4),
               TrainState(iteration=1, words_processed=step * 10,
                          global_step=step))
    return syn0


# -- digests + verification ------------------------------------------------------------


def test_save_records_digests_and_verifies(tmp_path):
    path = str(tmp_path / "ck")
    _save(path)
    meta = verify_checkpoint(path)
    assert set(meta["digests"]) == {"words", "counts.npy", "syn0.npy",
                                    "syn1.npy"}
    load_model(path)  # verify=True default must pass on a clean checkpoint


def test_bitflip_rejected_on_load(tmp_path):
    """One flipped byte in syn0.npy must fail the digest check — silent bit rot
    or a torn write never loads as garbage rows."""
    path = str(tmp_path / "ck")
    _save(path)
    _flip_byte(os.path.join(path, "syn0.npy"))
    with pytest.raises(CheckpointCorruptError, match="syn0.npy"):
        verify_checkpoint(path)
    with pytest.raises(CheckpointCorruptError):
        load_model(path)
    # an explicit opt-out still loads (debugging/forensics)
    assert load_model(path, verify=False)["syn0"].shape == (3, 4)


def test_legacy_checkpoint_without_digests_still_loads(tmp_path):
    path = str(tmp_path / "ck")
    _save(path)
    meta_p = os.path.join(path, "metadata.json")
    with open(meta_p) as f:
        meta = json.load(f)
    del meta["digests"]  # simulate a pre-round-6 writer
    with open(meta_p, "w") as f:
        json.dump(meta, f)
    verify_checkpoint(path)  # vacuous digest pass, structural checks only
    assert load_model(path)["syn0"].shape == (3, 4)


def test_sharded_checkpoint_digests_cover_shards(tmp_path):
    sents = [[f"w{j}" for j in np.random.default_rng(0).integers(0, 40, 10)]
             for _ in range(80)]
    vocab = build_vocab(sents, min_count=1)
    cfg = Word2VecConfig(vector_size=8, pairs_per_batch=128, num_iterations=1,
                         window=2, negatives=2, negative_pool=8,
                         steps_per_dispatch=2, seed=3, sharded_checkpoint=True,
                         subsample_ratio=0.0)
    trainer = Trainer(cfg, vocab, plan=make_mesh(2, 4))
    trainer.fit(encode_sentences(sents, vocab, cfg.max_sentence_length))
    path = str(tmp_path / "ck")
    trainer.save_checkpoint(path)
    meta = verify_checkpoint(path)
    shard_keys = [k for k in meta["digests"] if k.startswith("syn0.shards/")]
    assert len(shard_keys) == trainer.plan.num_model
    _flip_byte(os.path.join(path, shard_keys[0].replace("/", os.sep)))
    with pytest.raises(CheckpointCorruptError, match="syn0.shards"):
        verify_checkpoint(path)


# -- load_latest_valid -----------------------------------------------------------------


def test_load_latest_valid_skips_corrupt_and_reclaims_debris(tmp_path):
    d = str(tmp_path)
    _save(os.path.join(d, "ck-old"), step=5)
    _save(os.path.join(d, "ck-new"), step=9)
    _flip_byte(os.path.join(d, "ck-new", "syn0.npy"))
    os.makedirs(os.path.join(d, ".ck-new.tmp-12345"))  # orphaned staging dir
    got = load_latest_valid(d)
    assert os.path.basename(got) == "ck-old"  # newest VERIFIABLE, not newest
    assert not os.path.exists(os.path.join(d, ".ck-new.tmp-12345"))


def test_load_latest_valid_restores_old_swap_debris(tmp_path):
    """The torn window: the live path vanished mid-swap, leaving only the
    previous checkpoint under its .old-<pid> rename — it must come back."""
    d = str(tmp_path)
    syn0 = _save(os.path.join(d, "ck"), step=4)
    os.rename(os.path.join(d, "ck"), os.path.join(d, "ck.old-999"))
    got = load_latest_valid(d)
    assert os.path.basename(got) == "ck"
    np.testing.assert_array_equal(load_model(got)["syn0"], syn0)


def test_load_latest_valid_nothing_valid(tmp_path):
    d = str(tmp_path)
    _save(os.path.join(d, "ck"))
    _flip_byte(os.path.join(d, "ck", "counts.npy"), offset=80)
    with pytest.raises(FileNotFoundError, match="no verifiable checkpoint"):
        load_latest_valid(d)


def test_sigkill_mid_save_recovers_previous(tmp_path):
    """Acceptance path: a run SIGKILLed inside save_model's swap window (via
    the deterministic crash point, not a timed kill) leaves a torn directory;
    load_latest_valid must hand back the previous checkpoint, digest-verified."""
    d = str(tmp_path)
    script = (
        "import numpy as np\n"
        "from glint_word2vec_tpu.config import Word2VecConfig\n"
        "from glint_word2vec_tpu.train.checkpoint import save_model, TrainState\n"
        "w=['a','b']; c=np.array([2,1])\n"
        "s1=np.ones((2,4),np.float32)\n"
        f"save_model({d + '/ck'!r}, w, c, s1, None, Word2VecConfig(vector_size=4),"
        " TrainState(global_step=2))\n"
        f"save_model({d + '/ck'!r}, w, c, s1*7, None, Word2VecConfig(vector_size=4),"
        " TrainState(global_step=4))\n"
        "raise SystemExit('UNREACHABLE')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               GLINT_FAULT_CRASH_POINT="save:swap@2")
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          cwd=_REPO, capture_output=True, timeout=120)
    assert proc.returncode in (-9, 137), proc.stderr.decode()[-500:]
    assert not os.path.exists(os.path.join(d, "ck"))  # genuinely torn
    got = load_latest_valid(d)
    data = load_model(got)  # digest-verified load
    assert data["train_state"].global_step == 2  # the PREVIOUS checkpoint
    np.testing.assert_array_equal(data["syn0"], np.ones((2, 4), np.float32))
    assert sorted(os.listdir(d)) == ["ck"]  # all debris reclaimed


# -- non-finite guardrails -------------------------------------------------------------


def _toy_trainer(policy, seed=0):
    rng = np.random.default_rng(seed)
    sents = [[f"w{i}" for i in rng.integers(0, 30, 20)] for _ in range(250)]
    vocab = build_vocab(sents, min_count=1)
    cfg = Word2VecConfig(vector_size=8, pairs_per_batch=128, window=3,
                         num_iterations=2, steps_per_dispatch=2,
                         heartbeat_every_steps=2, subsample_ratio=0.0,
                         prefetch_chunks=0, seed=1, nonfinite_policy=policy)
    return Trainer(cfg, vocab), encode_sentences(sents, vocab, 1000)


def test_nan_injection_rollback_recovers():
    faults.configure(nan_at_step=8)
    trainer, enc = _toy_trainer("rollback")
    trainer.fit(enc)
    assert trainer.rollbacks_performed >= 1
    assert np.isfinite(np.asarray(trainer.params.syn0)).all()
    assert np.isfinite(np.asarray(trainer.params.syn1)).all()
    # the re-seed jumped the negative-sample counter lattice
    assert trainer.global_step >= Trainer._ROLLBACK_STEP_JUMP


def test_nan_injection_halt_raises_with_diagnostic():
    faults.configure(nan_at_step=8)
    trainer, enc = _toy_trainer("halt")
    with pytest.raises(NonFiniteParamsError, match="syn0"):
        trainer.fit(enc)


def test_nan_policy_none_keeps_old_behavior():
    faults.configure(nan_at_step=8)
    trainer, enc = _toy_trainer("none")
    trainer.fit(enc)  # must not raise; NaNs train on silently (pre-round-6)
    assert not np.isfinite(np.asarray(trainer.params.syn0)).all()


def test_final_save_is_probed_too(tmp_path):
    """A blowup in the last window — after the final heartbeat/periodic round —
    must still be caught by the guard inside save_checkpoint: the end-of-fit
    finished save must never persist NaNs (code-review r6 finding)."""
    ck = str(tmp_path / "ck")
    # no heartbeat (cadence 10^6) and no periodic save (every_steps unset), so
    # nothing probes between the injection and the end-of-fit finished save
    faults.configure(nan_at_step=8)
    rng = np.random.default_rng(0)
    sents = [[f"w{i}" for i in rng.integers(0, 30, 20)] for _ in range(250)]
    vocab = build_vocab(sents, min_count=1)
    cfg = Word2VecConfig(vector_size=8, pairs_per_batch=128, window=3,
                         num_iterations=1, steps_per_dispatch=2,
                         heartbeat_every_steps=10 ** 6, subsample_ratio=0.0,
                         prefetch_chunks=0, seed=1, nonfinite_policy="halt")
    trainer = Trainer(cfg, vocab)
    enc = encode_sentences(sents, vocab, 1000)
    with pytest.raises(NonFiniteParamsError):
        trainer.fit(enc, checkpoint_path=ck)
    assert not os.path.exists(ck)  # nothing (NaN) was persisted


def test_halt_never_overwrites_good_checkpoint(tmp_path):
    """The probe runs before a periodic save: the on-disk checkpoint must be
    the last GOOD state, never the blown-up one."""
    ck = str(tmp_path / "ck")
    faults.configure(nan_at_step=8)
    trainer, enc = _toy_trainer("halt")
    with pytest.raises(NonFiniteParamsError):
        trainer.fit(enc, checkpoint_path=ck, checkpoint_every_steps=2)
    data = load_model(ck)
    assert np.isfinite(data["syn0"]).all()
    assert data["train_state"].global_step < 8


# -- retrying ingest -------------------------------------------------------------------


def test_encode_corpus_retries_injected_faults(tmp_path):
    from glint_word2vec_tpu.data.corpus import encode_corpus
    sents = [["a", "b", "c"], ["b", "c", "d"]] * 10
    vocab = build_vocab(sents, min_count=1)
    faults.configure(fail_ingest_first_n=2)
    enc = encode_corpus(sents, vocab, str(tmp_path / "enc"))
    assert len(enc) == len(sents)
    np.testing.assert_array_equal(enc[0], enc[2])


def test_encode_corpus_retry_budget_exhausts(tmp_path):
    from glint_word2vec_tpu.data.corpus import encode_corpus
    sents = [["a", "b", "c"]] * 5
    vocab = build_vocab(sents, min_count=1)
    faults.configure(fail_ingest_first_n=50)
    with pytest.raises(InjectedFault):
        encode_corpus(sents, vocab, str(tmp_path / "enc"))


def test_token_file_corpus_open_retries(tmp_path):
    from glint_word2vec_tpu.data.corpus import TokenFileCorpus
    p = tmp_path / "corpus.txt"
    p.write_text("a b c\nd e f\n")
    faults.configure(fail_ingest_first_n=2)
    assert list(TokenFileCorpus(str(p))) == [["a", "b", "c"], ["d", "e", "f"]]


# -- resume of pre-round-5 checkpoints (ADVICE r5 medium) ------------------------------


def test_resume_unstable_checkpoint_config(tmp_path):
    """A checkpoint whose stored (resolved) subsample_ratio is now inside the
    duplicate-overload refusal region must be resumable via the allow_unstable
    pass-through instead of requiring a metadata.json hand-edit."""
    from glint_word2vec_tpu.models.estimator import Word2Vec
    rng = np.random.default_rng(0)
    # tiny vocab + big batch + enough corpus to fill it: expected top-word
    # duplicates per batch land far past the ~300 refusal boundary
    sents = [[f"w{i}" for i in rng.integers(0, 5, 20)] for _ in range(3000)]
    vocab = build_vocab(sents, min_count=1)
    cfg = Word2VecConfig(vector_size=4, pairs_per_batch=8192, window=5,
                         num_iterations=2, subsample_ratio=1e-3, seed=1)
    ck = str(tmp_path / "ck")
    syn0 = rng.normal(size=(vocab.size, 4)).astype(np.float32)
    save_model(ck, vocab.words, vocab.counts, syn0, -syn0, cfg,
               TrainState(iteration=1, words_processed=10, finished=False))
    with pytest.raises(ValueError, match="duplicate"):
        Word2Vec.resume(ck, sents)
    model = Word2Vec.resume(ck, sents, allow_unstable=True,
                            config_overrides={"pairs_per_batch": 256,
                                              "num_iterations": 1})
    assert model.train_state.finished


# -- chaos runner smoke ----------------------------------------------------------------


def test_chaos_runner_smoke(tmp_path):
    """End-to-end: the scripted fault schedule in tools/chaos_run.py passes.
    Covers the full crash → recover → resume → verify loop through the real
    CLI entry point (subprocesses inside)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "chaos_run.py"),
         "--smoke", "--workdir", str(tmp_path / "chaos")],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        cwd=_REPO, capture_output=True, timeout=500, text=True)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    assert "[chaos] OK" in proc.stdout
