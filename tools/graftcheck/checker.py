"""graftcheck orchestration: run the tiers, shrink findings, gate baselines.

The run is pure-functional over the tree: same tree, same mode → the same
report byte-for-byte (all enumeration is deterministic, the probe env is
fixed). Human progress goes to stderr; the CLI (__main__) prints exactly one
JSON line on stdout (graftlint R7)."""

from __future__ import annotations

import json
import os
import re
import sys
from typing import Dict, List, Optional

from tools.graftcheck import lattice, properties, registry
from tools.graftcheck.shrink import shrink

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")

# docs the knob gate scans: every Word2VecConfig field must appear by name in
# at least one of these (docs/configuration.md is the canonical table)
_DOC_FILES = ("docs/configuration.md", "README.md", "docs/static-analysis.md",
              "docs/robustness.md", "docs/observability.md",
              "docs/sharding.md", "docs/serving.md", "docs/continual.md")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def docs_gate(root: str) -> List[str]:
    """Every config field must be documented somewhere in the doc corpus —
    new knobs cannot ship undocumented (ISSUE 8 satellite)."""
    corpus = ""
    for rel in _DOC_FILES:
        path = os.path.join(root, rel)
        if os.path.exists(path):
            with open(path, "r", encoding="utf-8") as f:
                corpus += f.read()
    missing = []
    for name in sorted(registry.config_defaults()):
        if not re.search(rf"\b{re.escape(name)}\b", corpus):
            missing.append(name)
    return missing


def run_sweep(mode: str) -> Dict:
    """Execute the lattice. Returns the full report dict (pre-baseline)."""
    import logging
    # the sweep constructs thousands of candidates; construction-time
    # advisory warnings are the candidates' normal operation, not findings
    logging.getLogger("glint_word2vec_tpu").setLevel(logging.ERROR)

    cands = lattice.candidates(mode)
    probe = properties.DispatchProbe()

    refusal_sigs: Dict[str, Dict] = {}        # key -> {knobs, key, tier}
    violations: List[Dict] = []
    seen_violation_keys = set()
    runtime_refusals: Dict[str, int] = {}
    n_accepted = n_refused = 0

    def record_violation(prop_key: str, message: str, kwargs: Dict,
                         predicate) -> None:
        if prop_key in seen_violation_keys:
            return
        seen_violation_keys.add(prop_key)
        small = shrink(lattice.nondefault(kwargs), predicate, prop_key)
        violations.append({
            "property": prop_key.split(":", 1)[0].split("[", 1)[0],
            "key": prop_key,
            "message": message,
            "counterexample": {k: repr(v) for k, v in sorted(small.items())},
            "knobs_in_counterexample": len(small),
        })

    for i, (tier, kwargs) in enumerate(cands):
        if i and i % 250 == 0:
            log(f"graftcheck: {i}/{len(cands)} candidates "
                f"({probe.probes_run} probes, {len(violations)} violations)")
        cfg, refusal_key = properties.construct(kwargs)

        if tier == "range":
            if refusal_key is None:
                record_violation(
                    "range_check: " + ",".join(sorted(lattice.nondefault(kwargs))),
                    f"out-of-range sample accepted at construction: "
                    f"{lattice.nondefault(kwargs)}",
                    kwargs,
                    lambda kw: None if properties.construction_key(kw) else
                    "range_check: " + ",".join(sorted(lattice.nondefault(kw))))
            elif refusal_key.startswith("crashed"):
                # a non-ValueError out of __post_init__ is a violation in
                # EVERY tier — never a baselineable refusal signature (a
                # --write-baseline run must not be able to accept a crash)
                record_violation(
                    refusal_key,
                    f"construction crashed (non-ValueError) on the range "
                    f"sample {lattice.nondefault(kwargs)}",
                    kwargs, properties.construction_key)
            else:
                n_refused += 1
                _note_refusal(refusal_sigs, refusal_key, kwargs, tier)
            continue

        if refusal_key is not None:
            n_refused += 1
            if refusal_key.startswith("crashed"):
                record_violation(
                    refusal_key,
                    f"construction crashed (non-ValueError) on "
                    f"{lattice.nondefault(kwargs)}",
                    kwargs, properties.construction_key)
            else:
                _note_refusal(refusal_sigs, refusal_key, kwargs, tier)
            continue

        n_accepted += 1
        # (b)/(c)/(d): pure config-level properties on every accepted config
        for check in (properties.check_serialization,
                      properties.check_replace,
                      properties.check_ckpt_normalization):
            finding = check(cfg)
            if finding:
                key, message = finding

                def pred(kw, _check=check):
                    c, rk = properties.construct(kw)
                    if c is None:
                        return rk
                    f = _check(c)
                    return f[0] if f else None

                record_violation(key, message, kwargs, pred)

        # (a): dispatch parity via the cached Trainer probe
        dk = probe.probe_kwargs(kwargs)
        if dk is None:
            continue
        if dk.startswith("runtime_refusal"):
            runtime_refusals[dk] = runtime_refusals.get(dk, 0) + 1
            continue

        def dispatch_pred(kw):
            c, _ = properties.construct(kw)
            if c is None:
                return None  # refused at construction = parity holds there
            return probe.probe_kwargs(kw)

        record_violation(
            dk,
            f"construction accepted but dispatch refused/crashed: "
            f"{lattice.nondefault(kwargs)}",
            kwargs, dispatch_pred)

    # shrink one representative per construction-refusal signature so the
    # baseline stores minimal combos, not raw lattice rows
    signatures = []
    for key in sorted(refusal_sigs):
        entry = refusal_sigs[key]
        small = shrink(lattice.nondefault(entry["kwargs"]),
                       properties.construction_key, key)
        signatures.append({
            "knobs": sorted(small),
            "values": {k: repr(v) for k, v in sorted(small.items())},
            "key": key,
        })

    return {
        "tool": "graftcheck",
        "mode": mode,
        "knobs": len(registry.KNOBS),
        "configs_executed": len(cands),
        "accepted": n_accepted,
        "refused_construction": n_refused,
        "pairwise_pairs": lattice.pair_count(),
        "probes_run": probe.probes_run,
        "probe_cache_size": len(probe.cache),
        "runtime_refusals": dict(sorted(runtime_refusals.items())),
        "refusal_signatures": signatures,
        "violations": violations,
    }


def _note_refusal(sigs: Dict, key: str, kwargs: Dict, tier: str) -> None:
    if key not in sigs:
        sigs[key] = {"kwargs": kwargs, "tier": tier}


def apply_gates(report: Dict, root: str, baseline_path: str = "") -> Dict:
    """Registry drift, docs gate, and the committed-baseline drift gate
    (exact match on the full sweep, subset on smoke — a smoke run executes a
    thinner lattice, so signatures it does NOT see are not drift)."""
    report["registry_drift"] = registry.registry_drift()
    report["docs_missing"] = docs_gate(root)

    baseline_path = baseline_path or BASELINE_PATH
    drift: List[str] = []
    baselined_violations = {}
    if not os.path.exists(baseline_path):
        # fail CLOSED, like graftlint's baseline gate
        drift.append(f"baseline file not found: {baseline_path} "
                     f"(regenerate with --write-baseline after review)")
    else:
        with open(baseline_path, "r", encoding="utf-8") as f:
            baseline = json.load(f)
        want = {s["key"]: s for s in baseline.get("refusal_signatures", [])}
        have = {s["key"]: s for s in report["refusal_signatures"]}
        for key in sorted(set(have) - set(want)):
            drift.append(f"NEW refusal signature not in baseline: "
                         f"{have[key]['knobs']} ({key[:70]}...)")
        if report["mode"] == "full":
            for key in sorted(set(want) - set(have)):
                drift.append(f"baselined refusal signature no longer "
                             f"observed: {want[key]['knobs']} ({key[:70]}...)")
        for key in set(want) & set(have):
            if sorted(want[key].get("knobs", [])) != have[key]["knobs"]:
                drift.append(f"refusal signature changed minimal knob set: "
                             f"{want[key].get('knobs')} -> "
                             f"{have[key]['knobs']} ({key[:70]}...)")
        baselined_violations = {
            v["key"]: v for v in baseline.get("violations", [])
            if v.get("justification")}

    unexplained = [v for v in report["violations"]
                   if v["key"] not in baselined_violations]
    for v in report["violations"]:
        v["baselined"] = v["key"] in baselined_violations

    report["baseline_drift"] = drift
    report["unexplained_violations"] = len(unexplained)
    report["ok"] = (not unexplained and not drift
                    and not report["registry_drift"]
                    and not report["docs_missing"])
    return report


def write_baseline(report: Dict, baseline_path: str = "") -> str:
    """Regenerate the committed baseline from a reviewed FULL run. Keeps any
    justified violations already present (the justification is the reviewed
    part; the tool never invents one)."""
    baseline_path = baseline_path or BASELINE_PATH
    old_violations = []
    if os.path.exists(baseline_path):
        with open(baseline_path, "r", encoding="utf-8") as f:
            old_violations = json.load(f).get("violations", [])
    payload = {
        "_comment": "graftcheck committed baseline — refusal_signatures is "
                    "the reviewed inventory of minimal refused knob combos "
                    "(drift in either direction fails the full sweep); "
                    "violations lists property violations accepted with a "
                    "written justification (should stay empty).",
        "mode": report["mode"],
        "refusal_signatures": [
            {"knobs": s["knobs"], "values": s["values"], "key": s["key"]}
            for s in report["refusal_signatures"]],
        "violations": old_violations,
    }
    with open(baseline_path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")
    return baseline_path
