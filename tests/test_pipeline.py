"""Unit tests for the host data pipeline (C4/C5/C6): indexing, subsampling formula,
dynamic-window generation, fixed-shape batching."""

import numpy as np

from glint_word2vec_tpu.data.pipeline import (
    PairBatcher,
    count_train_words,
    dynamic_window_pairs,
    encode_sentences,
    epoch_batches,
    keep_probabilities,
    subsample_sentence,
)
from glint_word2vec_tpu.data.vocab import build_vocab


def _vocab():
    sents = [["a", "b", "c", "d", "e"] * 4, ["a", "b", "f"] * 3]
    return build_vocab(sents, min_count=1), sents


def test_encode_drops_oov_and_chunks():
    vocab, _ = _vocab()
    enc = encode_sentences([["a", "zzz", "b", "c"]], vocab, max_sentence_length=2)
    # OOV 'zzz' dropped, remaining 3 ids chunked into [2, 1]
    assert [e.shape[0] for e in enc] == [2, 1]
    flat = np.concatenate(enc)
    assert [vocab.words[i] for i in flat] == ["a", "b", "c"]


def test_encode_skips_empty():
    vocab, _ = _vocab()
    assert encode_sentences([["zzz"], []], vocab) == []


def test_keep_probabilities_formula():
    # keep = (sqrt(pct/ratio)+1)*(ratio/pct), pct = cn/total — intended float semantics of
    # mllib:374-377 (the reference's integer division makes it a no-op; see pipeline.py).
    counts = np.array([1000, 10, 1])
    total = 1011
    ratio = 1e-3
    keep = keep_probabilities(counts, total, ratio)
    pct = counts / total
    expected = np.minimum((np.sqrt(pct / ratio) + 1) * (ratio / pct), 1.0)
    np.testing.assert_allclose(keep, expected)
    # frequent words are dropped more
    assert keep[0] < keep[1] <= keep[2] == 1.0


def test_subsample_extremes():
    rng = np.random.default_rng(0)
    sent = np.arange(10, dtype=np.int32)
    keep_all = np.ones(10)
    np.testing.assert_array_equal(subsample_sentence(sent, keep_all, rng), sent)
    keep_none = np.zeros(10)
    # draws <= 0.0 has probability ~0
    assert subsample_sentence(sent, keep_none, rng).size == 0


def test_dynamic_window_legacy_asymmetric():
    # Reference (mllib:384-388): context = [max(0,i-b), min(i+b, len)) \ {i} — the upper
    # bound is exclusive, so right context has b-1 words. Verify against brute force.
    L, window = 23, 5
    sent = np.arange(100, 100 + L, dtype=np.int32)

    # reproduce internal rng: same seed → same b draws
    b = np.random.default_rng(7).integers(0, window, size=L)
    centers, contexts = dynamic_window_pairs(sent, window, np.random.default_rng(7))

    exp_c, exp_x = [], []
    for i in range(L):
        for p in range(max(0, i - int(b[i])), min(i + int(b[i]), L)):
            if p != i:
                exp_c.append(sent[i])
                exp_x.append(sent[p])
    np.testing.assert_array_equal(centers, np.array(exp_c, np.int32))
    np.testing.assert_array_equal(contexts, np.array(exp_x, np.int32))


def test_dynamic_window_symmetric():
    L, window = 17, 4
    sent = np.arange(L, dtype=np.int32)
    b = np.random.default_rng(3).integers(0, window, size=L)
    centers, contexts = dynamic_window_pairs(
        sent, window, np.random.default_rng(3), legacy_asymmetric_window=False)
    exp_c, exp_x = [], []
    for i in range(L):
        for p in range(max(0, i - int(b[i])), min(i + int(b[i]) + 1, L)):
            if p != i:
                exp_c.append(i)
                exp_x.append(p)
    np.testing.assert_array_equal(centers, exp_c)
    np.testing.assert_array_equal(contexts, exp_x)


def test_dynamic_window_empty_and_single():
    rng = np.random.default_rng(0)
    c, x = dynamic_window_pairs(np.empty(0, np.int32), 5, rng)
    assert c.size == 0 and x.size == 0
    c, x = dynamic_window_pairs(np.array([3], np.int32), 5, rng)
    assert c.size == 0


def test_pair_batcher_fixed_shapes():
    batcher = PairBatcher(8)
    batcher.add(np.arange(5, dtype=np.int32), np.arange(5, dtype=np.int32))
    assert list(batcher.drain()) == []
    batcher.add(np.arange(10, dtype=np.int32), np.arange(10, dtype=np.int32))
    full = list(batcher.drain())
    assert len(full) == 1 and full[0][0].shape == (8,) and full[0][2] == 8
    tail = list(batcher.drain(flush=True))
    assert len(tail) == 1
    c, x, n = tail[0]
    assert c.shape == (8,) and n == 7  # 15 total − 8 drained


def test_epoch_batches_end_to_end_shapes_and_determinism():
    vocab, sents = _vocab()
    enc = encode_sentences(sents, vocab)

    def run():
        return list(epoch_batches(
            enc, vocab, pairs_per_batch=16, window=3, subsample_ratio=1.0,
            seed=11, iteration=1, shard=0, num_shards=1))

    b1, b2 = run(), run()
    assert len(b1) >= 1
    for a, b in zip(b1, b2):
        np.testing.assert_array_equal(a.centers, b.centers)
        np.testing.assert_array_equal(a.contexts, b.contexts)
        np.testing.assert_array_equal(a.mask, b.mask)
    for batch in b1:
        assert batch.centers.shape == (16,)
        assert batch.mask.shape == (16,)
        assert batch.num_real_pairs == int(batch.mask.sum())
    # different iteration → different stream
    b3 = list(epoch_batches(
        enc, vocab, pairs_per_batch=16, window=3, subsample_ratio=1.0,
        seed=11, iteration=2, shard=0, num_shards=1))
    assert any(not np.array_equal(a.centers, b.centers) for a, b in zip(b1, b3))


def test_epoch_batches_sharding_partitions_sentences():
    vocab, sents = _vocab()
    enc = encode_sentences(sents * 4, vocab)
    # With subsample_ratio=1.0 every word is kept, so the shards' words_seen clocks must
    # partition the corpus exactly (pair counts differ: window shrink draws are per-shard).
    def words_seen(shard, num_shards):
        last = 0
        for b in epoch_batches(
                enc, vocab, pairs_per_batch=8, window=2, subsample_ratio=1.0,
                seed=5, shard=shard, num_shards=num_shards, shuffle=False):
            last = b.words_seen
        return last

    # The clock credits words up to each shard's last *emitted* pair's center, so each
    # shard may fall short of its exact word count by a few trailing contextless words.
    total = sum(int(s.shape[0]) for s in enc)
    assert total - 8 <= words_seen(0, 1) <= total
    sharded = words_seen(0, 2) + words_seen(1, 2)
    assert total - 16 <= sharded <= total


def test_count_train_words():
    assert count_train_words([np.arange(3), np.arange(4)]) == 7


def test_words_seen_advances_per_batch_not_per_block():
    # Regression: the lr-decay clock must credit words as batches are emitted, not a
    # whole 1M-word block at once (which would run entire small corpora at end-of-run
    # alpha).
    rng = np.random.default_rng(0)
    sents = [rng.integers(0, 50, 30).astype(np.int32) for _ in range(200)]
    vocab_counts = np.bincount(np.concatenate(sents), minlength=50)
    from glint_word2vec_tpu.data.vocab import Vocabulary
    v = Vocabulary.from_words_and_counts([f"w{i}" for i in range(50)], vocab_counts)
    total = v.train_words_count
    batches = list(epoch_batches(sents, v, pairs_per_batch=512, window=4,
                                 subsample_ratio=0.0, seed=3, shuffle=False))
    assert len(batches) > 4
    ws = [b.words_seen for b in batches]
    assert ws == sorted(ws)                  # monotone
    assert ws[0] < total / 2                 # first batch is NOT credited the whole corpus
    assert ws[-1] <= total
    assert ws[-1] >= total - 40              # last center is near the corpus end


def test_block_cbow_matches_block_pairs_grouping():
    """Property: grouping _block_pairs' flat (center, context) stream by center
    ordinal must reproduce _block_cbow's left-packed rows exactly — the two
    generators share one prologue (_subsample_and_window), and this pins the
    expansion halves to each other (boundary clipping, packing order, clock)."""
    from glint_word2vec_tpu.data.pipeline import (
        _block_cbow, _block_pairs, keep_probabilities)

    rng = np.random.default_rng(5)
    V, W = 300, 4
    lengths = rng.integers(1, 25, 80).astype(np.int64)
    tokens = rng.integers(0, V, int(lengths.sum())).astype(np.int32)
    counts = np.maximum(1000 / (np.arange(V) + 2.0), 1.0)
    keep = keep_probabilities(counts, int(counts.sum()), 1e-2)
    args = (tokens, lengths, keep, W, 9, 2, 1, 12345, True)
    pc, px, pclock, pkept = _block_pairs(*args)
    cc, cx, cn, cclock, ckept = _block_cbow(*args)
    assert pkept == ckept
    # group the flat pairs by center ordinal (pclock is center ordinal + 1)
    assert np.array_equal(np.unique(pclock), np.sort(cclock))
    total = 0
    for row in range(cc.shape[0]):
        sel = pclock == cclock[row]
        n = int(sel.sum())
        assert n == cn[row]
        assert np.all(pc[sel] == cc[row])                 # same center token
        np.testing.assert_array_equal(px[sel], cx[row, :n])  # same packed contexts
        assert np.all(cx[row, n:] == 0)                   # masked slots zeroed
        total += n
    assert total == pc.shape[0]
