"""ISSUE-14 step restructurings: fused logit chain (config.fused_logits),
end-to-end bf16 update chain (config.bf16_chain), and cross-step hot-row
accumulation (config.hot_rows / hot_flush_every).

Four layers, mirroring the PR-7 stabilizer discipline:

1. ORACLE — the fused coefficient chain against a plain-NumPy float64 oracle
   (masked slots, duplicate indices, pool-collision entries, pool edge sizes
   P=1 / odd / P=B), plus fused ≡ classic and bf16_chain ≡ classic at f64.
2. HOT-ROW SEMANTICS — read-corrected gathers + split scatters + prefix
   flush reproduce the classic step at f64 (shared-pool and per-pair,
   duplicates spanning the hot/cold boundary, fully-masked padding batches a
   no-op), and multi-step slab accumulation with one flush matches stepwise
   application.
3. OFF-IS-BIT-IDENTICAL — the PR-7 contract: all three knobs off elide the
   new ops entirely (identical lowered module, bit-identical trained
   params vs a default-constructed trainer).
4. DISPATCH — trainer fits with each knob on every supported feed (host,
   device_pairgen), shard_map gets the fused chain (cross-lowering f64
   equivalence), and the config selection matrix refuses every documented
   illegal combination (graftlint R8 parses the parity; graftcheck executes
   it).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.pipeline import encode_sentences
from glint_word2vec_tpu.data.vocab import Vocabulary
from glint_word2vec_tpu.ops.sgns import (
    EmbeddingPair,
    hot_flush,
    sgns_step_core,
    sgns_step_shared_core,
)
from glint_word2vec_tpu.ops.sgns_shard import make_shard_map_sgns_step
from glint_word2vec_tpu.parallel.mesh import make_mesh
from glint_word2vec_tpu.train.trainer import Trainer

NEG = 3


# ---------------------------------------------------------------------------
# 1. NumPy float64 oracle for the fused shared-pool coefficient chain
# ---------------------------------------------------------------------------


def _sig(x):
    out = np.empty_like(x)
    pos = x >= 0
    out[pos] = 1.0 / (1.0 + np.exp(-x[pos]))
    ex = np.exp(x[~pos])
    out[~pos] = ex / (1.0 + ex)
    return out


def _np_shared_step(syn0, syn1, centers, contexts, mask, negs, alpha, n):
    """Plain-NumPy float64 mirror of the (unfused) shared-pool update — the
    same oracle family tests/test_stabilizers.py pins the stabilized step
    against; the fused chain must land on the identical math."""
    e_in, e_pos, Z = syn0[centers], syn1[contexts], syn1[negs]
    P = negs.shape[0]
    f_pos = (e_in * e_pos).sum(-1)
    f_neg = e_in @ Z.T
    neg_valid = (negs[None, :] != contexts[:, None]).astype(np.float64) \
        * mask[:, None]
    g_pos = (1.0 - _sig(f_pos)) * alpha * mask
    g_neg = (0.0 - _sig(f_neg)) * alpha * neg_valid * (n / P)
    d_in = g_pos[:, None] * e_pos + g_neg @ Z
    d_pos = g_pos[:, None] * e_in
    d_Z = g_neg.T @ e_in
    s0, s1 = syn0.copy(), syn1.copy()
    np.add.at(s0, centers, d_in)
    np.add.at(s1, contexts, d_pos)
    np.add.at(s1, negs, d_Z)
    return s0, s1


def _inputs(seed=0, V=60, D=12, B=24, P=8):
    rng = np.random.default_rng(seed)
    syn0 = rng.normal(0, 0.5, (V, D))
    syn1 = rng.normal(0, 0.5, (V, D))
    centers = rng.integers(0, V, B).astype(np.int32)
    contexts = rng.integers(0, V, B).astype(np.int32)
    centers[3] = centers[4] = 2          # duplicates on a (hot-class) row
    contexts[5] = contexts[6] = 1
    mask = (np.arange(B) < B - 4).astype(np.float64)
    # masked tail slots point at real rows: their coefficients must be zero
    centers[B - 1], contexts[B - 1] = 0, 1
    negs = rng.integers(0, V, P).astype(np.int32)
    negs[0] = contexts[0]                # collision -> invalid (pair 0) entry
    if P > 2:
        negs[1] = negs[2]                # duplicate pool entries
    return syn0, syn1, centers, contexts, mask, negs


def _run_shared(params_np, centers, contexts, mask, negs, alpha, **kw):
    from jax.experimental import enable_x64
    with enable_x64():
        got = sgns_step_shared_core(
            EmbeddingPair(jnp.asarray(params_np[0]), jnp.asarray(params_np[1])),
            jnp.asarray(centers), jnp.asarray(contexts),
            jnp.asarray(mask, jnp.float32), jnp.asarray(negs),
            jnp.float64(alpha), NEG, "exact", jnp.float64, False, jnp.float64,
            True, **kw)
    return got


@pytest.mark.parametrize("pool", [1, 3, 8, 24])  # edge sizes incl. P == B
def test_fused_oracle_f64(pool):
    syn0, syn1, centers, contexts, mask, negs = _inputs(P=pool)
    ref0, ref1 = _np_shared_step(
        syn0, syn1, centers, contexts, mask, negs, 0.05, NEG)
    got, _ = _run_shared((syn0, syn1), centers, contexts, mask, negs, 0.05,
                         fused=True)
    np.testing.assert_allclose(np.asarray(got.syn0), ref0, atol=3e-8)
    np.testing.assert_allclose(np.asarray(got.syn1), ref1, atol=3e-8)


@pytest.mark.parametrize("kw", [
    dict(fused=True),
    dict(bf16_chain=True),
    dict(fused=True, bf16_chain=True),
])
def test_fused_and_chain_match_classic_f64(kw):
    """The restructured chains are the SAME math as the classic chain at f64
    (association-only differences, far under 1e-12) — params AND metrics."""
    syn0, syn1, centers, contexts, mask, negs = _inputs()
    base, mb = _run_shared((syn0, syn1), centers, contexts, mask, negs, 0.05)
    got, mg = _run_shared((syn0, syn1), centers, contexts, mask, negs, 0.05,
                          **kw)
    np.testing.assert_allclose(np.asarray(got.syn0), np.asarray(base.syn0),
                               atol=1e-12)
    np.testing.assert_allclose(np.asarray(got.syn1), np.asarray(base.syn1),
                               atol=1e-12)
    assert abs(float(mg.loss) - float(mb.loss)) < 1e-12
    assert float(mg.pairs) == float(mb.pairs)


def test_perpair_fused_and_chain_match_classic_f64():
    from jax.experimental import enable_x64

    syn0, syn1, centers, contexts, mask, _ = _inputs()
    rng = np.random.default_rng(7)
    pn = rng.integers(0, syn0.shape[0], (centers.shape[0], NEG)).astype(
        np.int32)
    pn[0, 0] = contexts[0]               # negative colliding with positive
    with enable_x64():
        params = EmbeddingPair(jnp.asarray(syn0), jnp.asarray(syn1))
        args = (jnp.asarray(centers), jnp.asarray(contexts),
                jnp.asarray(mask, jnp.float32), jnp.asarray(pn),
                jnp.float64(0.05), "exact", jnp.float64, False)
        base, mb = sgns_step_core(params, *args)
        got, mg = sgns_step_core(params, *args, fused=True, bf16_chain=True)
    np.testing.assert_allclose(np.asarray(got.syn0), np.asarray(base.syn0),
                               atol=1e-12)
    np.testing.assert_allclose(np.asarray(got.syn1), np.asarray(base.syn1),
                               atol=1e-12)
    assert abs(float(mg.loss) - float(mb.loss)) < 1e-12


def test_fused_chain_bf16_tracks_f32():
    """The fused bf16 chain stays within the shared-pool coefficient noise
    bound of the f32 chain (the PERF.md §4 tolerance argument, now for the
    fused form)."""
    syn0, syn1, centers, contexts, mask, negs = _inputs(V=40, D=16, B=32, P=8)
    params32 = EmbeddingPair(jnp.asarray(syn0, jnp.float32),
                             jnp.asarray(syn1, jnp.float32))
    args = (jnp.asarray(centers), jnp.asarray(contexts),
            jnp.asarray(mask, jnp.float32), jnp.asarray(negs),
            jnp.float32(0.05), NEG, "exact")
    ref, _ = sgns_step_shared_core(params32, *args, jnp.float32, False,
                                   jnp.float32, True)
    got, _ = sgns_step_shared_core(params32, *args, jnp.bfloat16, False,
                                   jnp.bfloat16, True, fused=True,
                                   bf16_chain=True)
    err = np.abs(np.asarray(got.syn0, np.float32)
                 - np.asarray(ref.syn0, np.float32)).max()
    assert err < 0.02, err


# ---------------------------------------------------------------------------
# 2. Hot-row accumulation semantics
# ---------------------------------------------------------------------------


def _hot_slabs(k, d, dtype=jnp.float64):
    from jax.experimental import enable_x64
    with enable_x64():
        return (jnp.zeros((k, d), dtype), jnp.zeros((k, d), dtype))


def test_hot_single_step_matches_classic_f64():
    """One step + flush == the classic step (reads are delta-corrected, the
    split scatter covers the hot/cold boundary, the flush is exact)."""
    syn0, syn1, centers, contexts, mask, negs = _inputs()
    base, mb = _run_shared((syn0, syn1), centers, contexts, mask, negs, 0.05)
    got, mh, (s0, s1) = _run_shared(
        (syn0, syn1), centers, contexts, mask, negs, 0.05,
        hot_slabs=_hot_slabs(16, syn0.shape[1]))
    from jax.experimental import enable_x64
    with enable_x64():
        got = EmbeddingPair(hot_flush(got.syn0, s0), hot_flush(got.syn1, s1))
    np.testing.assert_allclose(np.asarray(got.syn0), np.asarray(base.syn0),
                               atol=1e-12)
    np.testing.assert_allclose(np.asarray(got.syn1), np.asarray(base.syn1),
                               atol=1e-12)
    # the metrics (loss/f_pos) come from the delta-corrected gathers: exact
    assert abs(float(mh.loss) - float(mb.loss)) < 1e-12


def test_hot_multi_step_accumulation_matches_stepwise_f64():
    """K steps with the slab carried and ONE flush at the end reproduce K
    classic steps applied sequentially — the cross-step contract."""
    from jax.experimental import enable_x64

    syn0, syn1, centers, contexts, mask, negs = _inputs()
    D = syn0.shape[1]
    with enable_x64():
        ref = EmbeddingPair(jnp.asarray(syn0), jnp.asarray(syn1))
        hot = ref
        slabs = _hot_slabs(16, D)
        for step in range(4):
            rng = np.random.default_rng(100 + step)
            c = jnp.asarray(rng.integers(0, 60, 24), jnp.int32)
            x = jnp.asarray(rng.integers(0, 60, 24), jnp.int32)
            ng = jnp.asarray(rng.integers(0, 60, 8), jnp.int32)
            m = jnp.asarray(np.ones(24), jnp.float32)
            args = (c, x, m, ng, jnp.float64(0.05), NEG, "exact",
                    jnp.float64, False, jnp.float64, False)
            ref, _ = sgns_step_shared_core(ref, *args)
            hot, _, slabs = sgns_step_shared_core(hot, *args,
                                                  hot_slabs=slabs)
        hot = EmbeddingPair(hot_flush(hot.syn0, slabs[0]),
                            hot_flush(hot.syn1, slabs[1]))
    np.testing.assert_allclose(np.asarray(hot.syn0), np.asarray(ref.syn0),
                               atol=1e-11)
    np.testing.assert_allclose(np.asarray(hot.syn1), np.asarray(ref.syn1),
                               atol=1e-11)


def test_hot_fully_masked_batch_is_noop():
    """A padding batch (mask all zero, placeholder index 0 = a HOT row) must
    leave params and slabs exactly unchanged through step + flush."""
    from jax.experimental import enable_x64

    syn0, syn1, centers, contexts, _, negs = _inputs()
    with enable_x64():
        params = EmbeddingPair(jnp.asarray(syn0), jnp.asarray(syn1))
        zeros = jnp.zeros(centers.shape[0], jnp.float32)
        got, _, (s0, s1) = sgns_step_shared_core(
            params, jnp.asarray(centers), jnp.asarray(contexts), zeros,
            jnp.asarray(negs), jnp.float64(0.05), NEG, "exact", jnp.float64,
            False, jnp.float64, True, hot_slabs=_hot_slabs(16, syn0.shape[1]))
        got = EmbeddingPair(hot_flush(got.syn0, s0), hot_flush(got.syn1, s1))
    # the pool rows still receive their (zero-coefficient) scatter adds, so
    # compare numerically-exact: nothing may move
    assert np.array_equal(np.asarray(got.syn0), syn0)
    # syn1 pool rows: zero-valued adds may flip -0.0 signs at most; require
    # exact values
    np.testing.assert_array_equal(np.asarray(got.syn1), syn1)


def test_perpair_hot_matches_classic_f64():
    from jax.experimental import enable_x64

    syn0, syn1, centers, contexts, mask, _ = _inputs()
    rng = np.random.default_rng(9)
    pn = rng.integers(0, 60, (centers.shape[0], NEG)).astype(np.int32)
    pn[:, 0] = 1                          # hot negatives with duplicates
    with enable_x64():
        params = EmbeddingPair(jnp.asarray(syn0), jnp.asarray(syn1))
        args = (jnp.asarray(centers), jnp.asarray(contexts),
                jnp.asarray(mask, jnp.float32), jnp.asarray(pn),
                jnp.float64(0.05), "exact", jnp.float64, False)
        base, _ = sgns_step_core(params, *args)
        hot, _, (s0, s1) = sgns_step_core(
            params, *args, hot_slabs=_hot_slabs(16, syn0.shape[1]))
        hot = EmbeddingPair(hot_flush(hot.syn0, s0), hot_flush(hot.syn1, s1))
    np.testing.assert_allclose(np.asarray(hot.syn0), np.asarray(base.syn0),
                               atol=1e-12)
    np.testing.assert_allclose(np.asarray(hot.syn1), np.asarray(base.syn1),
                               atol=1e-12)


# ---------------------------------------------------------------------------
# 3. Off-is-bit-identical (the PR-7 elision contract)
# ---------------------------------------------------------------------------


def _toy():
    rng = np.random.default_rng(0)
    V = 80
    words = [f"w{i}" for i in range(V)]
    vocab = Vocabulary.from_words_and_counts(
        words, np.sort(rng.integers(5, 100, V))[::-1].copy())
    sents = [[f"w{i}" for i in rng.integers(0, V, 12)] for _ in range(80)]
    return vocab, encode_sentences(sents, vocab, 1000)


def _fit(vocab, enc, **kw):
    cfg = Word2VecConfig(vector_size=16, min_count=1, pairs_per_batch=32,
                         num_iterations=1, window=2, steps_per_dispatch=4,
                         prefetch_chunks=0, seed=3, **kw)
    t = Trainer(cfg, vocab, plan=make_mesh(1, 1))
    t.fit(enc)
    return (np.asarray(t.params.syn0.astype(jnp.float32)),
            np.asarray(t.params.syn1.astype(jnp.float32)))


def test_knobs_off_elide_ops_bit_identical():
    """Default config vs explicitly-off knobs: identical LOWERED module (the
    new ops are structurally absent, not just numerically neutral) and
    bit-identical trained params."""
    syn0, syn1, centers, contexts, mask, negs = _inputs()
    params = EmbeddingPair(jnp.asarray(syn0, jnp.float32),
                           jnp.asarray(syn1, jnp.float32))
    args = (jnp.asarray(centers), jnp.asarray(contexts),
            jnp.asarray(mask, jnp.float32), jnp.asarray(negs),
            jnp.float32(0.05), NEG)

    def lower(**kw):
        def step(p, c, x, m, ng):
            return sgns_step_shared_core(p, c, x, m, ng, jnp.float32(0.05),
                                         NEG, **kw)
        return jax.jit(step).lower(params, *args[:4]).as_text()

    assert lower() == lower(fused=False, bf16_chain=False, hot_slabs=None)

    vocab, enc = _toy()
    a = _fit(vocab, enc, negative_pool=16)
    b = _fit(vocab, enc, negative_pool=16, fused_logits=False,
             bf16_chain=False, hot_rows=0, hot_flush_every=0)
    assert np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])


# ---------------------------------------------------------------------------
# 4. Trainer dispatch, shard_map fused, and the refusal matrix
# ---------------------------------------------------------------------------


def test_trainer_hot_rows_close_to_classic_all_feeds():
    vocab, enc = _toy()
    base = _fit(vocab, enc, negative_pool=16)
    hot = _fit(vocab, enc, negative_pool=16, hot_rows=8)
    assert np.allclose(base[0], hot[0], atol=2e-6)
    hot2 = _fit(vocab, enc, negative_pool=16, hot_rows=8, hot_flush_every=2)
    assert np.allclose(base[0], hot2[0], atol=2e-6)
    dev = _fit(vocab, enc, negative_pool=16, device_pairgen=True)
    devh = _fit(vocab, enc, negative_pool=16, device_pairgen=True, hot_rows=8)
    assert np.allclose(dev[0], devh[0], atol=2e-6)
    # per-pair path
    pp = _fit(vocab, enc, negative_pool=0)
    pph = _fit(vocab, enc, negative_pool=0, hot_rows=8)
    assert np.allclose(pp[0], pph[0], atol=2e-6)


def test_trainer_hot_rows_clamped_to_vocab():
    vocab, enc = _toy()
    cfg = Word2VecConfig(vector_size=16, min_count=1, pairs_per_batch=32,
                         negative_pool=16, steps_per_dispatch=4,
                         prefetch_chunks=0, hot_rows=10_000)
    t = Trainer(cfg, vocab, plan=make_mesh(1, 1))
    assert t._hot_rows == vocab.size
    t.fit(enc)
    assert np.isfinite(np.asarray(t.params.syn0, np.float32)).all()


def test_trainer_fused_and_chain_fit_smoke():
    vocab, enc = _toy()
    base = _fit(vocab, enc, negative_pool=16)
    fus = _fit(vocab, enc, negative_pool=16, fused_logits=True)
    assert np.allclose(base[0], fus[0], atol=2e-6)
    bf = _fit(vocab, enc, negative_pool=16, param_dtype="bfloat16",
              compute_dtype="bfloat16", logits_dtype="bfloat16",
              fused_logits=True, bf16_chain=True, hot_rows=8)
    assert np.isfinite(bf[0]).all() and np.abs(bf[0]).sum() > 0


def test_shard_map_fused_matches_gspmd_fused_f64():
    """shard_map runs the SAME fused chain through the shared helper —
    cross-lowering equivalence at f64 on a 2x4 mesh."""
    from jax.experimental import enable_x64

    with enable_x64():
        rng = np.random.default_rng(0)
        v, d, b, pool = 64, 16, 32, 8
        params = EmbeddingPair(
            jnp.asarray(rng.standard_normal((v, d)), jnp.float64),
            jnp.asarray(rng.standard_normal((v, d)) * 0.1, jnp.float64))
        batch = {
            "centers": jnp.asarray(rng.integers(0, v, b), jnp.int32),
            "contexts": jnp.asarray(rng.integers(0, v, b), jnp.int32),
            "mask": jnp.asarray(rng.random(b) < 0.9, jnp.float32),
        }
        negs = jnp.asarray(rng.integers(0, v, pool), jnp.int32)
        alpha = jnp.float64(0.025)
        ref, mref = sgns_step_shared_core(
            params, batch["centers"], batch["contexts"], batch["mask"],
            negs, alpha, NEG, "exact", jnp.float64, False, jnp.float64, True,
            fused=True, bf16_chain=True)
        plan = make_mesh(2, 4)
        sharded = EmbeddingPair(
            jax.device_put(params.syn0, plan.embedding),
            jax.device_put(params.syn1, plan.embedding))
        step = make_shard_map_sgns_step(
            plan.mesh, NEG, "exact", jnp.float64, jnp.float64, True,
            fused=True, bf16_chain=True)
        got, mgot = step(sharded, batch, negs, alpha)
        np.testing.assert_allclose(np.asarray(got.syn0),
                                   np.asarray(ref.syn0), atol=1e-11)
        np.testing.assert_allclose(np.asarray(got.syn1),
                                   np.asarray(ref.syn1), atol=1e-11)
        assert abs(float(mgot.loss) - float(mref.loss)) < 1e-9


@pytest.mark.parametrize("kw", [
    dict(hot_rows=4, cbow=True),
    dict(hot_rows=4, use_pallas=True),
    dict(hot_rows=4, step_lowering="shard_map"),
    dict(hot_rows=4, embedding_partition="cols"),
    dict(hot_rows=4, duplicate_scaling=True),
    dict(hot_rows=4, max_row_norm=10.0),
    dict(hot_rows=4, update_clip=0.5),
    dict(hot_rows=4, row_l2=1e-4),
    dict(hot_rows=4, norm_watch="recover"),
    dict(hot_rows=4, num_model_shards=2),
    dict(hot_rows=4, num_data_shards=2),
    dict(hot_rows=4, mesh_shape=(2, 4)),
    dict(hot_rows=4, hot_flush_every=3, steps_per_dispatch=16),
    dict(hot_rows=4, hot_flush_every=32, steps_per_dispatch=16),
    dict(hot_rows=-1),
    dict(hot_flush_every=-1),
    dict(fused_logits=True, cbow=True),
    dict(fused_logits=True, use_pallas=True),
    dict(fused_logits=True, duplicate_scaling=True),
    dict(bf16_chain=True),                       # compute f32: no chain
    dict(bf16_chain=True, cbow=True, compute_dtype="bfloat16"),
    dict(bf16_chain=True, use_pallas=True, compute_dtype="bfloat16"),
    dict(bf16_chain=True, compute_dtype="bfloat16", negative_pool=512),
])
def test_config_refusal_matrix(kw):
    with pytest.raises(ValueError):
        Word2VecConfig(**kw)


def test_config_legal_combinations_construct():
    Word2VecConfig(hot_rows=4096)
    Word2VecConfig(hot_rows=4096, hot_flush_every=16)
    Word2VecConfig(fused_logits=True)
    Word2VecConfig(fused_logits=True, step_lowering="shard_map",
                   pairs_per_batch=8192)
    Word2VecConfig(bf16_chain=True, compute_dtype="bfloat16",
                   logits_dtype="bfloat16")
    Word2VecConfig(bf16_chain=True, compute_dtype="bfloat16",
                   negative_pool=0)
    # round-trip + replace preserve the knobs
    c = Word2VecConfig(hot_rows=256, hot_flush_every=8, fused_logits=True)
    d = Word2VecConfig.from_dict(c.to_dict())
    assert (d.hot_rows, d.hot_flush_every, d.fused_logits) == (256, 8, True)
    assert c.replace(seed=5).hot_rows == 256


def test_trainer_refuses_hot_rows_on_multi_device_plan():
    vocab, _ = _toy()
    cfg = Word2VecConfig(vector_size=16, min_count=1, pairs_per_batch=32,
                         negative_pool=16, hot_rows=8)
    with pytest.raises(ValueError, match="single-chip"):
        Trainer(cfg, vocab, plan=make_mesh(2, 4))
