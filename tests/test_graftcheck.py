"""graftcheck (layer-3 config-lattice model checker, ISSUE 8): the --smoke
sweep runs clean inside tier-1; the shrinker demonstrably reduces a seeded
violation to a ≤3-knob counterexample; every minimal counterexample from the
FIRST REAL-TREE RUN is pinned beside its fix; and the baseline/registry/docs
drift gates actually detect drift."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from glint_word2vec_tpu.config import Word2VecConfig  # noqa: E402
from tools.graftcheck import checker, lattice, properties, registry  # noqa: E402
from tools.graftcheck.shrink import shrink  # noqa: E402


# ---------------------------------------------------------------------------
# first-run counterexamples, pinned beside their fixes (ISSUE 8 satellite).
# Each of these was ACCEPTED at construction before this PR and refused only
# at Trainer dispatch (or, for the dtype/range rows, crashed past every
# refusal surface) — found by graftcheck's dispatch-parity/range properties,
# fixed in config.__post_init__.
# ---------------------------------------------------------------------------

FIRST_RUN_COUNTEREXAMPLES = [
    (dict(device_pairgen=True, cbow=True), "skip-gram only"),
    (dict(device_pairgen=True, use_pallas=True), "use_pallas"),
    (dict(device_pairgen=True, window=1), "window"),
    (dict(device_pairgen=True, tokens_per_step=200_000, window=100),
     "prefix-sum bound"),
    (dict(embedding_partition="cols", sharded_checkpoint=True), "cols"),
    (dict(param_dtype="float8"), "param_dtype"),
    (dict(compute_dtype="float8"), "compute_dtype"),
    (dict(steps_per_dispatch=0), "steps_per_dispatch"),
    (dict(heartbeat_every_steps=0), "heartbeat_every_steps"),
    (dict(prefetch_chunks=-1), "prefetch_chunks"),
]


@pytest.mark.parametrize(
    "kwargs,match",
    FIRST_RUN_COUNTEREXAMPLES,
    ids=[",".join(sorted(kw)) for kw, _ in FIRST_RUN_COUNTEREXAMPLES])
def test_first_run_counterexample_now_refused_at_construction(kwargs, match):
    with pytest.raises(ValueError, match=match):
        Word2VecConfig(**kwargs)


def test_first_run_counterexample_replace_preserves_pool_autoness():
    """The replace_parity finding: flipping an AUTO-pool config on a
    non-geometry knob (seed) used to freeze the resolved pool, which then
    read as EXPLICIT — to_dict(auto_markers=True) stored it and the
    Trainer's vocab-scaled safety rule silently skipped it."""
    c = Word2VecConfig()
    assert getattr(c, "_auto_pool") is True
    c2 = c.replace(seed=123)
    assert getattr(c2, "_auto_pool") is True
    assert c2.negative_pool == c.negative_pool  # same geometry, same value
    assert c2.to_dict(auto_markers=True)["negative_pool"] == -1
    # and the property itself holds on the flip set
    assert properties.check_replace(c) is None


def test_vocab_scaled_pool_survives_duplicate_channel_lowering():
    """Review finding on the replace() fix itself: the trainer resolves a
    still-AUTO pool UPWARD past 500k vocab (load <= 160), then the duplicate-
    channel auto-lowering calls cfg.replace(subsample_ratio=lo) — whose
    unconditional pool re-derivation would silently revert the enlargement
    to the config-level load <= 600 rule (inside the measured large-vocab
    blowup region). The trainer now re-applies the vocab-scaled rule after
    the lowering."""
    import numpy as np

    from glint_word2vec_tpu.data.vocab import Vocabulary
    from glint_word2vec_tpu.parallel.mesh import make_mesh
    from glint_word2vec_tpu.train.trainer import Trainer

    V = 600_001
    counts = np.full(V, 5, np.int64)
    counts[0] = 5_000_000  # skewed: forces the duplicate-channel lowering
    vocab = Vocabulary.from_words_and_counts(
        [f"w{i}" for i in range(V)], counts)
    cfg = Word2VecConfig(vector_size=8, pad_vector_to_lanes=False,
                         pairs_per_batch=8192, negatives=25,
                         prefetch_chunks=0)
    assert cfg.negative_pool == 384  # config-level load <= 600 resolution
    trainer = Trainer(cfg, vocab, plan=make_mesh(1, 1))
    # the subsample auto-lowering fired...
    assert trainer.config.subsample_ratio < 1e-3
    # ...and the vocab-scaled pool (load <= 160 -> 1280) survived it
    assert trainer.config.negative_pool == 1280, trainer.config.negative_pool
    assert getattr(trainer.config, "_auto_pool") is True


# ---------------------------------------------------------------------------
# shrinker
# ---------------------------------------------------------------------------

def test_shrinker_reduces_seeded_violation_to_three_knobs():
    """Acceptance criterion: seed a synthetic violation into a WIDE config
    (every registry knob set) and the shrinker must come back with exactly
    the ≤3-knob core."""
    wide = dict(next(iter(lattice.pairwise_tier()))[1])
    wide.update(cbow=True, use_pallas=True, window=7)
    nd = lattice.nondefault(wide)
    assert len(nd) > 10  # genuinely wide before shrinking

    def seeded_predicate(kwargs):
        if (kwargs.get("cbow") and kwargs.get("use_pallas")
                and kwargs.get("window") == 7):
            return "seeded-violation"
        return None

    assert seeded_predicate(nd) == "seeded-violation"
    small = shrink(nd, seeded_predicate, "seeded-violation")
    assert set(small) == {"cbow", "use_pallas", "window"}
    assert len(small) <= 3


def test_shrinker_finds_real_minimal_combo():
    """Same machinery against the REAL constructor: a kitchen-sink refused
    config shrinks to the documented 2-knob combo."""
    kwargs = dict(cbow=True, use_pallas=True, vector_size=8, seed=9,
                  negatives=25, shuffle=False, norm_watch="warn")
    key = properties.construction_key(kwargs)
    assert key and key.startswith("refused")
    small = shrink(kwargs, properties.construction_key, key)
    assert set(small) == {"cbow", "use_pallas"}


# ---------------------------------------------------------------------------
# property units on tricky configs
# ---------------------------------------------------------------------------

def test_serialization_fixpoint_on_auto_and_resolved_configs():
    for kwargs in (dict(),                                  # all-AUTO
                   dict(negative_pool=64, subsample_ratio=1e-4),  # explicit
                   dict(cbow=True, duplicate_scaling=True),  # pool -> 0
                   dict(mesh_shape=(1, 1)),                  # tuple via JSON
                   dict(step_lowering="shard_map")):
        cfg = Word2VecConfig(**kwargs)
        assert properties.check_serialization(cfg) is None, kwargs
        assert properties.check_ckpt_normalization(cfg) is None, kwargs


def test_from_dict_is_deliberately_more_lenient_than_replace():
    """The distinction the first smoke run surfaced: from_dict normalizes
    old-checkpoint dicts (stored resolved pool beside cbow+duplicate_scaling
    -> 0), while the constructor and replace() both refuse the same knobs —
    that asymmetry is the documented contract, not a parity violation."""
    d = Word2VecConfig(cbow=True, duplicate_scaling=True).to_dict(
        auto_markers=False)
    loaded = Word2VecConfig.from_dict({**d, "negative_pool": 64})
    assert loaded.negative_pool == 0
    # the normalization is scatter-scoped: a banded dict does not qualify
    # (no old checkpoint can carry it) and falls through to the refusal
    with pytest.raises(ValueError, match="banded"):
        Word2VecConfig.from_dict({**d, "negative_pool": 64,
                                  "cbow_update": "banded"})


def test_dispatch_probe_classifies_and_caches():
    probe = properties.DispatchProbe()
    assert probe.probe_kwargs(dict(vector_size=8)) is None
    n = probe.probes_run
    # dispatch-inert knob flips hit the projection cache, not a new Trainer
    assert probe.probe_kwargs(dict(vector_size=8, seed=5)) is None
    assert probe.probes_run == n


# ---------------------------------------------------------------------------
# gates
# ---------------------------------------------------------------------------

def test_registry_covers_all_fields():
    assert registry.registry_drift() == []


def test_docs_gate_clean_and_detects_missing():
    assert checker.docs_gate(REPO) == []
    # a knob absent from every doc file must be reported — simulate by
    # checking against an empty corpus root
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        missing = checker.docs_gate(td)
        assert "negative_pool" in missing and len(missing) == len(
            registry.KNOBS)


def test_baseline_drift_detected_both_ways(tmp_path):
    report = {"mode": "full", "refusal_signatures": [
        {"knobs": ["a", "b"], "values": {}, "key": "refused: combo-one"}],
        "violations": []}
    base = tmp_path / "baseline.json"
    base.write_text(json.dumps({"refusal_signatures": [
        {"knobs": ["c"], "values": {}, "key": "refused: combo-two"}],
        "violations": []}))
    gated = checker.apply_gates(dict(report), REPO, str(base))
    drift = " ".join(gated["baseline_drift"])
    assert "NEW refusal signature" in drift
    assert "no longer observed" in drift
    assert not gated["ok"]
    # fail-closed on a missing baseline, like graftlint
    gated2 = checker.apply_gates(dict(report), REPO,
                                 str(tmp_path / "nope.json"))
    assert any("not found" in d for d in gated2["baseline_drift"])


def test_unexplained_violation_fails_and_justified_baseline_passes(tmp_path):
    report = {"mode": "full", "refusal_signatures": [], "violations": [
        {"property": "replace_parity", "key": "k1", "message": "m",
         "counterexample": {}, "knobs_in_counterexample": 1}]}
    base = tmp_path / "baseline.json"
    base.write_text(json.dumps(
        {"refusal_signatures": [], "violations": []}))
    gated = checker.apply_gates(dict(report), REPO, str(base))
    assert gated["unexplained_violations"] == 1 and not gated["ok"]
    base.write_text(json.dumps({"refusal_signatures": [], "violations": [
        {"key": "k1", "justification": "accepted: reviewed in PR 8"}]}))
    gated = checker.apply_gates(
        {"mode": "full", "refusal_signatures": [], "violations": [
            dict(report["violations"][0])]}, REPO, str(base))
    assert gated["unexplained_violations"] == 0
    assert gated["violations"][0]["baselined"]


# ---------------------------------------------------------------------------
# the tier-1 wiring: the smoke sweep subprocess (CLI + R7 JSON contract)
# ---------------------------------------------------------------------------

def test_smoke_sweep_runs_clean_cli():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftcheck", "--smoke"],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-3000:]
    # exactly one JSON line on stdout (graftlint R7)
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout
    report = json.loads(lines[0])
    assert report["ok"] and report["tool"] == "graftcheck"
    assert report["knobs"] == 96
    assert report["unexplained_violations"] == 0
    assert report["configs_executed"] >= 200   # the thinned lattice
    assert report["refusal_signatures"], "refusal inventory must be nonempty"
    # runtime-only refusals cannot fire in the hermetic probe env
    assert report["runtime_refusals"] == {}
