"""Unit tests for the vocabulary builder (C1) — tests the reference never had
(its only suite is the Docker integration spec, SURVEY §4)."""

import numpy as np
import pytest

from glint_word2vec_tpu.data.vocab import Vocabulary, build_vocab, count_words, merge_counts


SENTS = [
    "the quick brown fox jumps over the lazy dog".split(),
    "the dog barks at the fox".split(),
    "a quick dog".split(),
]


def test_build_vocab_sorted_desc_and_counts():
    v = build_vocab(SENTS, min_count=1)
    # descending counts
    assert all(v.counts[i] >= v.counts[i + 1] for i in range(len(v) - 1))
    assert v.words[0] == "the" and v.counts[0] == 4
    assert v.train_words_count == sum(len(s) for s in SENTS)
    # index round-trips
    for i, w in enumerate(v.words):
        assert v.index[w] == i


def test_min_count_filters():
    v = build_vocab(SENTS, min_count=2)
    assert "jumps" not in v
    assert "dog" in v and "the" in v
    assert v.train_words_count == int(v.counts.sum())


def test_empty_vocab_raises():
    with pytest.raises(ValueError, match="vocabulary size should be > 0"):
        build_vocab(SENTS, min_count=100)


def test_merge_counts_matches_single_pass():
    c1 = count_words(SENTS[:1])
    c2 = count_words(SENTS[1:])
    merged = merge_counts([c1, c2])
    assert merged == count_words(SENTS)


def test_from_words_and_counts_roundtrip():
    v = build_vocab(SENTS, min_count=1)
    v2 = Vocabulary.from_words_and_counts(v.words, v.counts)
    assert v2.words == v.words
    assert np.array_equal(v2.counts, v.counts)
    assert v2.train_words_count == v.train_words_count
