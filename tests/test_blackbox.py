"""Flight recorder + time attribution suite (docs/observability.md §5–6):
phase histogram bucketing vs exact quantiles, the accumulator's window
deltas and thread safety, the recorder's ring bounds / first-cause-wins
dump, and the trainer integration — a dying fit (guardrail halt, watchdog
halt) leaves a schema-valid ``.blackbox.json`` with the terminal cause
while a clean fit leaves none, and heartbeats carry the mid-run recovery
state (``recoveries``/``lr_scale``) the satellite added."""

import json
import os
import threading

import numpy as np
import pytest

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.pipeline import encode_sentences
from glint_word2vec_tpu.data.vocab import build_vocab
from glint_word2vec_tpu.obs.blackbox import FlightRecorder
from glint_word2vec_tpu.obs.phases import (
    HIST_BUCKETS,
    PhaseAccumulator,
    bucket_index,
    bucket_upper_edge,
)
from glint_word2vec_tpu.obs.schema import (
    validate_blackbox,
    validate_blackbox_file,
)
from glint_word2vec_tpu.train import faults
from glint_word2vec_tpu.train.faults import (
    NonFiniteParamsError,
    NormBlowupError,
)
from glint_word2vec_tpu.train.trainer import Trainer


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _toy_trainer(seed=0, n=250, **cfg_kw):
    rng = np.random.default_rng(seed)
    sents = [[f"w{i}" for i in rng.integers(0, 30, 20)] for _ in range(n)]
    vocab = build_vocab(sents, min_count=1)
    cfg = Word2VecConfig(vector_size=8, pairs_per_batch=128, window=3,
                         num_iterations=2, steps_per_dispatch=2,
                         heartbeat_every_steps=2, subsample_ratio=0.0,
                         prefetch_chunks=0, seed=1, **cfg_kw)
    return Trainer(cfg, vocab), encode_sentences(sents, vocab, 1000)


# -- phase histograms ------------------------------------------------------------------


def test_bucket_edges_bound_quantiles():
    """A bucketed quantile must sit within one quarter-octave (ratio
    <= 2^0.25) above the exact value — same contract as the probe's norm
    histogram."""
    rng = np.random.default_rng(0)
    durations = 10.0 ** rng.uniform(-5, 0, 5000)  # 10 µs .. 1 s
    acc = PhaseAccumulator(enabled=True)
    for d in durations:
        acc.add("dispatch", float(d))
    s = acc.summary()["dispatch"]
    for q, got in ((0.50, s["p50_s"]), (0.99, s["p99_s"])):
        exact = float(np.quantile(durations, q))
        assert exact <= got <= exact * 2 ** 0.25 * 1.001, (q, exact, got)
    assert s["count"] == len(durations)
    assert s["total_s"] == pytest.approx(durations.sum(), rel=1e-4)
    assert s["max_s"] == pytest.approx(durations.max(), rel=1e-4)


def test_bucket_index_clamps_and_orders():
    assert bucket_index(0.0) == 0
    assert bucket_index(1e-9) == 0
    assert bucket_index(1e9) == HIST_BUCKETS - 1
    idx = [bucket_index(x) for x in (1e-6, 1e-3, 0.1, 1.0, 10.0)]
    assert idx == sorted(idx)
    for i in range(HIST_BUCKETS - 1):
        assert bucket_upper_edge(i) < bucket_upper_edge(i + 1)
    # every duration lands at or below its bucket's upper edge
    for d in (1e-5, 0.003, 0.7, 42.0):
        assert d <= bucket_upper_edge(bucket_index(d)) * 1.001


def test_accumulator_window_delta():
    acc = PhaseAccumulator(enabled=True)
    acc.add("stage", 0.01)
    mark = acc.raw_snapshot()
    acc.add("stage", 0.02)
    acc.add("dispatch", 0.5)
    delta = acc.delta(mark)
    assert delta["stage"]["count"] == 1
    assert delta["stage"]["total_s"] == pytest.approx(0.02, rel=1e-6)
    assert delta["dispatch"]["count"] == 1
    # cumulative summary still has both stage adds
    assert acc.summary()["stage"]["count"] == 2
    # an idle window deltas to empty
    assert acc.delta(acc.raw_snapshot()) == {}


def test_accumulator_disabled_and_unknown_phase_noop():
    acc = PhaseAccumulator(enabled=False)
    acc.add("dispatch", 1.0)
    assert acc.summary() == {}
    acc.configure(True)
    acc.add("not_a_phase", 1.0)  # unknown phases are dropped, not KeyError
    assert acc.summary() == {}


def test_accumulator_thread_safe():
    acc = PhaseAccumulator(enabled=True)

    def add_many():
        for _ in range(2000):
            acc.add("producer_wait", 1e-4)

    threads = [threading.Thread(target=add_many) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert acc.summary()["producer_wait"]["count"] == 8000


# -- the recorder itself ---------------------------------------------------------------


def test_recorder_rings_bounded_and_routed(tmp_path):
    rec = FlightRecorder(str(tmp_path / "bb.json"), ring=8)
    rec.begin_run("r1")
    for i in range(50):
        rec.note_dispatch(i, 2, 0.01, 0.001)
        rec.observe("heartbeat", {"step": i, "words": i, "alpha": 0.1,
                                  "loss": 1.0, "mean_f_pos": 0.5,
                                  "pairs_per_sec": 1.0, "host_wait_s": 0.0,
                                  "dispatch_s": 0.0, "recoveries": 0,
                                  "lr_scale": 1.0})
    rec.observe("watchdog", {"step": 9, "policy": "warn", "reason": "x",
                             "channels": {}})
    path = rec.dump({"kind": "none"})
    doc = json.load(open(path))
    assert validate_blackbox(doc) == []
    assert len(doc["dispatches"]) == 8          # ring bound
    assert doc["dispatches"][-1]["step"] == 49  # newest kept
    assert len(doc["heartbeats"]) == 16         # ring // 4 floor is 16
    assert [e["kind"] for e in doc["events"]] == ["watchdog"]
    # atomic: no tmp debris beside the dump
    assert all(".tmp-" not in f for f in os.listdir(tmp_path))


def test_recorder_first_cause_wins(tmp_path):
    """A SIGTERM dump must not be overwritten by the unwind that follows."""
    rec = FlightRecorder(str(tmp_path / "bb.json"))
    rec.begin_run("r1")
    rec.dump(FlightRecorder.signal_cause(15))
    rec.dump(FlightRecorder.exception_cause(RuntimeError("later")))
    doc = json.load(open(tmp_path / "bb.json"))
    assert doc["cause"]["kind"] == "signal"
    # a NEW run re-arms the dump
    rec.begin_run("r2")
    rec.dump(FlightRecorder.exception_cause(RuntimeError("second run")))
    doc = json.load(open(tmp_path / "bb.json"))
    assert doc["cause"] == {
        "kind": "exception", "type": "RuntimeError",
        "message": "second run",
        "traceback": doc["cause"]["traceback"]}


def test_validator_rejects_malformed_dump():
    assert validate_blackbox([])  # not an object
    base = {"schema": 1, "kind": "blackbox", "t": 1.0, "run_id": "r",
            "cause": {"kind": "none"}, "heartbeats": [], "events": [],
            "dispatches": []}
    assert validate_blackbox(base) == []
    assert validate_blackbox({**base, "cause": {"kind": "meteor"}})
    assert validate_blackbox({**base, "dispatches": [{"t": 1.0}]})
    assert validate_blackbox(
        {**base, "heartbeats": [{"schema": 1, "kind": "watchdog", "t": 1.0}]})


# -- trainer integration ---------------------------------------------------------------


def test_nonfinite_halt_leaves_valid_dump(tmp_path):
    """The guardrail's NonFiniteParamsError rides the abort path: the dump
    must exist, validate, and carry the exception cause + the run_end
    terminal record + ring contents."""
    run_log = str(tmp_path / "run.jsonl")
    faults.configure(nan_at_step=8)
    trainer, enc = _toy_trainer(seed=2, telemetry_path=run_log)
    with pytest.raises(NonFiniteParamsError):
        trainer.fit(enc)
    dump = run_log + ".blackbox.json"
    v = validate_blackbox_file(dump)
    assert v["ok"], v["errors"]
    doc = json.load(open(dump))
    assert doc["cause"]["kind"] == "exception"
    assert doc["cause"]["type"] == "NonFiniteParamsError"
    assert doc["run_id"]
    assert len(doc["heartbeats"]) >= 1
    assert len(doc["dispatches"]) >= 1
    kinds = [e["kind"] for e in doc["events"]]
    assert "run_start" in kinds and "run_end" in kinds
    assert doc["status"]["status"] == "idle"  # run_end ran before the dump
    assert "phases" in doc and "spans" in doc


def test_norm_blowup_halt_dump_carries_watchdog_record(tmp_path):
    run_log = str(tmp_path / "run.jsonl")
    faults.configure(scale_params_at_step=8)
    trainer, enc = _toy_trainer(seed=2, telemetry_path=run_log,
                                norm_watch="halt")
    with pytest.raises(NormBlowupError):
        trainer.fit(enc)
    v = validate_blackbox_file(run_log + ".blackbox.json")
    assert v["ok"], v["errors"]
    doc = json.load(open(run_log + ".blackbox.json"))
    assert doc["cause"]["type"] == "NormBlowupError"
    assert "watchdog" in [e["kind"] for e in doc["events"]]


def test_clean_run_leaves_no_dump_and_next_fit_rearms(tmp_path):
    run_log = str(tmp_path / "run.jsonl")
    trainer, enc = _toy_trainer(telemetry_path=run_log)
    trainer.fit(enc)
    assert not os.path.exists(run_log + ".blackbox.json")
    # the same trainer dying on a LATER fit still dumps (per-run re-arm)
    faults.configure(nan_at_step=trainer.global_step + 8)
    trainer.state = type(trainer.state)()
    with pytest.raises(NonFiniteParamsError):
        trainer.fit(enc)
    assert validate_blackbox_file(run_log + ".blackbox.json")["ok"]


def test_telemetry_off_means_no_recorder_no_signal_hook():
    import signal
    before = signal.getsignal(signal.SIGTERM)
    trainer, enc = _toy_trainer(n=60)
    trainer.fit(enc)
    assert trainer._blackbox is None
    assert signal.getsignal(signal.SIGTERM) is before


def test_sigterm_disposition_restored_after_fit(tmp_path):
    import signal
    before = signal.getsignal(signal.SIGTERM)
    trainer, enc = _toy_trainer(n=60, telemetry_path=str(tmp_path / "r.jsonl"))
    trainer.fit(enc)
    assert signal.getsignal(signal.SIGTERM) is before


def test_heartbeat_carries_recovery_state(tmp_path):
    """Satellite: recoveries + the effective lr scale ride EVERY heartbeat
    (pre-round-13 only run_start/run_end had them), so the tail/blackbox can
    show mid-run recovery state without replaying the file."""
    run_log = str(tmp_path / "run.jsonl")
    faults.configure(scale_params_at_step=8)
    trainer, enc = _toy_trainer(seed=2, telemetry_path=run_log,
                                norm_watch="recover")
    trainer.fit(enc)
    assert trainer.recoveries_performed >= 1
    hbs = [json.loads(line) for line in open(run_log)
           if json.loads(line)["kind"] == "heartbeat"]
    assert all("recoveries" in h and "lr_scale" in h for h in hbs)
    assert hbs[0]["recoveries"] == 0 and hbs[0]["lr_scale"] == 1.0
    post = [h for h in hbs if h["recoveries"] >= 1]
    assert post, "no heartbeat after the recovery"
    assert post[-1]["lr_scale"] == pytest.approx(
        trainer._lr_scale, rel=1e-6)
    # the in-memory ring mirrors the sink fields
    assert trainer.heartbeats[-1].recoveries == trainer.recoveries_performed


def test_run_telemetry_carries_phase_attribution(tmp_path):
    """Tentpole layer 2 e2e: heartbeats carry window deltas, run_end the
    cumulative rollup, and Trainer.last_run_stats mirrors it — with the
    producer_wait/dispatch phases populated on a real fit."""
    run_log = str(tmp_path / "run.jsonl")
    trainer, enc = _toy_trainer(telemetry_path=run_log)
    trainer.fit(enc)
    recs = [json.loads(line) for line in open(run_log)]
    hb_phases = [r["phases"] for r in recs
                 if r["kind"] == "heartbeat" and r.get("phases")]
    assert hb_phases, "no heartbeat carried a phases window"
    end = [r for r in recs if r["kind"] == "run_end"][-1]
    for phase in ("producer_wait", "dispatch"):
        assert phase in end["phases"], end["phases"].keys()
        assert end["phases"][phase]["count"] > 0
        assert end["phases"][phase]["hist"]
    # windows sum to (at most) the cumulative counts
    total_hb = sum(w.get("dispatch", {}).get("count", 0) for w in hb_phases)
    assert total_hb <= end["phases"]["dispatch"]["count"]
    stats = trainer.last_run_stats
    assert stats["phases"]["dispatch"]["count"] == \
        end["phases"]["dispatch"]["count"]


def test_phases_zero_cost_when_observability_off():
    trainer, enc = _toy_trainer(n=60)
    trainer.fit(enc)
    assert not trainer._phases.enabled
    assert trainer._phases.summary() == {}
    assert "phases" not in trainer.last_run_stats
