"""Incremental vocabulary extension — grow a checkpoint onto a drifted corpus.

The reference retrains from scratch whenever the vocabulary changes (its runs
are all-or-nothing, SURVEY §5); ``estimator.resume`` refuses a fingerprint
mismatch outright. This module turns that dead end into a *migration*: given a
checkpoint and the word counts of a corpus tail, it

1. computes the **vocab delta** — new words past ``min_count``, merged counts
   for surviving words (:func:`compute_vocab_delta`);
2. builds the **extended vocabulary** with the *identity-prefix* contract
   (:func:`extended_vocabulary`): surviving words keep their EXACT indices
   (rows are never re-sorted by the merged counts — a re-sort would permute
   every embedding row and invalidate every cached encode), new words append
   after them in descending tail-count order. The old→new index remap is
   therefore the identity on ``[0, V_old)`` — recorded explicitly in the
   lineage so readers never have to infer it;
3. grows ``syn0``/``syn1`` by the new rows (:func:`grow_arrays`) — surviving
   rows carried over **bit-identically** (verified against the parent's
   recorded digests / re-read bytes), new ``syn0`` rows seeded with the
   classic word2vec init U(−0.5/D, 0.5/D) from a deterministic
   ``(seed, V_old, V_new)``-keyed stream, new ``syn1`` rows zero (σ=0.5
   starting gradient, exactly like a fresh fit's rows);
4. records the migration in a **fingerprint lineage chain**
   (``metadata.json["vocab_lineage"]``): one entry per extension with the
   parent and child :func:`~glint_word2vec_tpu.data.corpus.vocab_fingerprint`,
   sizes, and the remap kind — ``resume()`` consults the chain to accept
   encode caches written under ANY ancestor vocabulary (their ids are still
   valid under the identity-prefix contract).

Both checkpoint layouts are supported. The **row-shards** path grows
per-shard without ever densifying ``[V, D]`` on one host: shards fully below
``V_old`` are carried verbatim (hash-verified during the copy, parent digest
reused), the boundary shard is sliced at ``V_old`` (padding rows drop), pure
padding shards drop, and one fresh shard ``rows-<V_old>-<V_new>`` carries the
seeded new rows. Peak memory is one shard, not one matrix.

The negative-sampling alias table is NOT stored in checkpoints — the Trainer
rebuilds it from ``vocab.counts`` at construction, so the merged-counts
rebuild happens for free on the next increment. A rebuild is
distribution-exact for the merged counts (tested; see ops/sampler.py), but
the *realized* negative-sample stream differs from the pre-extension one —
the same cross-release caveat as the round-8 vectorized builder (PERF.md
§10): continual increments may legally change the negative stream.

Host-side, single-process by design: extension is a migration step between
fits, not a collective — a multi-host deployment runs it once on the
coordinator and lets every process stream the grown checkpoint back in
through ``load_params_into_plan``.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import shutil
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence

import numpy as np

from glint_word2vec_tpu.data.corpus import vocab_fingerprint
from glint_word2vec_tpu.data.vocab import Vocabulary, count_words
from glint_word2vec_tpu.train.checkpoint import (
    CheckpointCorruptError,
    _HashingWriter,
    _format_version,
    _merge_extra_metadata,
    _save_npy_hashed,
    _save_words_hashed,
    _sha256_file,
    SHARDED_FORMAT_VERSION,
    ShardedMatrixReader,
    TrainState,
    load_model,
    load_model_header,
)

logger = logging.getLogger("glint_word2vec_tpu")

#: The only remap kind this writer emits: surviving words keep their indices,
#: new words append. Readers that meet an unknown kind must refuse, not guess.
REMAP_IDENTITY_PREFIX = "identity-prefix"


@dataclasses.dataclass
class VocabDelta:
    """The difference between a checkpoint's vocabulary and a corpus tail."""

    new_words: List[str]        # promoted words, descending tail count
    new_counts: np.ndarray      # int64 [len(new_words)] — tail counts
    merged_counts: np.ndarray   # int64 [V_old] — old counts + tail counts
    tail_words_total: int       # total tail occurrences seen (incl. dropped)

    @property
    def num_new(self) -> int:
        return len(self.new_words)


def compute_vocab_delta(
    vocab: Vocabulary,
    tail_counts: Mapping[str, int],
    min_count: int,
) -> VocabDelta:
    """Split a tail's word counts into merged-survivor counts and promoted
    new words.

    Promotion uses the TAIL count alone: the checkpoint only persists counts
    for words that made the vocabulary, so a word's sub-``min_count``
    occurrences from earlier eras are gone (O(V) state, the streaming trade —
    the reference re-counts the whole corpus instead; docs/continual.md). New
    words sort by descending tail count, ties on first-seen order (the same
    stable tie-break as :meth:`Vocabulary.from_counter`).
    """
    merged = vocab.counts.copy()
    fresh: List[tuple] = []
    total = 0
    for w, c in tail_counts.items():
        total += int(c)
        i = vocab.get(w)
        if i >= 0:
            merged[i] += int(c)
        elif c >= min_count:
            fresh.append((w, int(c)))
    fresh.sort(key=lambda wc: -wc[1])
    return VocabDelta(
        new_words=[w for w, _ in fresh],
        new_counts=np.asarray([c for _, c in fresh], dtype=np.int64),
        merged_counts=merged,
        tail_words_total=total,
    )


def extended_vocabulary(vocab: Vocabulary, delta: VocabDelta) -> Vocabulary:
    """The identity-prefix extension: old words at their old indices (merged
    counts), new words appended. NOTE the descending-count global invariant
    of fresh vocabularies is deliberately given up — preserving row identity
    is what keeps carried rows, cached encodes, and the serving tier's ids
    valid across increments."""
    if not delta.num_new:
        return Vocabulary.from_words_and_counts(
            vocab.words, delta.merged_counts)
    return Vocabulary.from_words_and_counts(
        list(vocab.words) + list(delta.new_words),
        np.concatenate([delta.merged_counts, delta.new_counts]))


def seed_new_rows(
    n_new: int,
    vector_size: int,
    seed: int,
    old_vocab_size: int,
    dtype=np.float32,
) -> np.ndarray:
    """Deterministic init for the grown ``syn0`` rows: the classic word2vec
    U(−0.5/D, 0.5/D), keyed by ``(seed, V_old, n_new)`` so the same extension
    on the same checkpoint reproduces bit-identically — and a LATER extension
    (different V_old) draws a fresh stream."""
    rng = np.random.default_rng(
        [int(seed) & 0xFFFFFFFF, int(old_vocab_size), int(n_new)])
    lim = 0.5 / float(vector_size)
    return rng.uniform(-lim, lim, size=(n_new, vector_size)).astype(dtype)


def lineage_entry(old_vocab: Vocabulary, new_vocab: Vocabulary,
                  delta: VocabDelta,
                  tail_fingerprint: Optional[str] = None) -> Dict[str, Any]:
    """One vocab_lineage chain link for this migration.

    ``tail_fingerprint`` identifies WHICH corpus tail this migration merged
    (the driver passes a digest of the tail segments' content fingerprints):
    a retry of a crashed increment compares it against the chain's last link
    to recognize an already-applied merge instead of double-weighting the
    tail's counts."""
    entry = {
        "parent_fingerprint": vocab_fingerprint(old_vocab),
        "fingerprint": vocab_fingerprint(new_vocab),
        "old_vocab_size": old_vocab.size,
        "new_vocab_size": new_vocab.size,
        "new_words": delta.num_new,
        "remap": REMAP_IDENTITY_PREFIX,
    }
    if tail_fingerprint is not None:
        entry["tail_fingerprint"] = tail_fingerprint
    return entry


def lineage_fingerprints(lineage: Sequence[Mapping[str, Any]]) -> List[str]:
    """Every ancestor fingerprint a lineage chain names (parents + children;
    the terminal child equals the checkpoint's own current fingerprint).
    Encode caches written under ANY of these are valid under the current
    vocabulary — identity-prefix remaps never move an id."""
    out: List[str] = []
    for entry in lineage:
        if entry.get("remap") != REMAP_IDENTITY_PREFIX:
            # an unknown remap kind could have moved ids; nothing before it
            # in the chain is safe to reuse
            out.clear()
            continue
        for key in ("parent_fingerprint", "fingerprint"):
            fp = entry.get(key)
            if isinstance(fp, str) and fp not in out:
                out.append(fp)
    return out


def grow_arrays(
    syn0: np.ndarray,
    syn1: Optional[np.ndarray],
    delta: VocabDelta,
    vector_size: int,
    seed: int,
) -> tuple:
    """Dense growth: carried rows are the SAME bytes (``np.concatenate``
    copies but never transforms; verified by the caller against the parent),
    new ``syn0`` rows seeded, new ``syn1`` rows zero."""
    n = delta.num_new
    if n == 0:
        return syn0, syn1
    V_old = syn0.shape[0]
    cols = syn0.shape[1]
    new0 = np.zeros((n, cols), dtype=syn0.dtype)
    new0[:, :vector_size] = seed_new_rows(
        n, vector_size, seed, V_old, dtype=syn0.dtype)
    g0 = np.concatenate([np.asarray(syn0), new0])
    g1 = None
    if syn1 is not None:
        g1 = np.concatenate(
            [np.asarray(syn1), np.zeros((n, cols), dtype=syn1.dtype)])
    return g0, g1


def extend_checkpoint(
    checkpoint_path: str,
    tail: "Iterable[Sequence[str]] | Mapping[str, int]",
    out_path: Optional[str] = None,
    min_count: Optional[int] = None,
    min_new_words: int = 1,
    tail_fingerprint: Optional[str] = None,
    verify: bool = True,
) -> Dict[str, Any]:
    """Migrate a checkpoint onto a drifted corpus: grow the vocabulary and
    the embedding matrices, merge counts, append the lineage link.

    ``tail`` is either a word→count mapping (the driver's counted corpus
    tail) or an iterable of token sequences (counted here). ``out_path``
    defaults to IN-PLACE migration — the write is the trainer's atomic
    tmp+rename swap, so it doubles as a publish the serving watcher picks up
    (new words become servable with their seeded vectors immediately;
    the incremental fit then improves them). ``min_count`` defaults to the
    checkpoint config's.

    ``verify=True`` re-reads the carried region of the written checkpoint and
    asserts it is bit-identical to the source rows (dense), or hash-verifies
    every carried shard against the parent's recorded digests during the copy
    (row-shards — the verification IS the copy pass there, no extra read).

    Returns a report dict: sizes, new-word count, the appended lineage entry,
    and the output path. ``min_new_words`` (the ``continual_min_new_words``
    knob) gates GROWTH: below it the promoted words are dropped for this
    migration. Zero-growth migrations still merge counts, still append a
    lineage link (``new_words: 0`` — the fingerprint changes with the merged
    counts, and the chain is what keeps old encode caches acceptable), and
    still publish — frequencies drifted, so the next increment's alias table
    must see the merged counts.
    """
    header = load_model_header(checkpoint_path)
    cfg = header["config"]
    if min_count is None:
        min_count = cfg.min_count
    old_vocab = Vocabulary.from_words_and_counts(
        header["words"], header["counts"])
    if isinstance(tail, Mapping):
        counts = tail
    else:
        counts = count_words(tail)
    prior = list(header.get("vocab_lineage") or [])
    if (tail_fingerprint is not None and prior
            and prior[-1].get("tail_fingerprint") == tail_fingerprint):
        # this exact tail was already merged by a crashed previous attempt
        # (the increment died between its extension publish and its cursor
        # save) — re-applying would double-weight the tail's counts
        logger.info("extension for tail %s already applied to %s; skipping "
                    "the re-merge", tail_fingerprint, checkpoint_path)
        return {
            "old_vocab_size": prior[-1]["old_vocab_size"],
            "new_vocab_size": prior[-1]["new_vocab_size"],
            "new_words": prior[-1]["new_words"],
            "tail_words_total": 0,
            "lineage_entry": prior[-1],
            "lineage_depth": len(prior),
            "path": out_path or checkpoint_path,
            "layout": header["layout"],
            "already_applied": True,
        }
    delta = compute_vocab_delta(old_vocab, counts, min_count)
    if delta.num_new < max(min_new_words, 1):
        delta = VocabDelta(
            new_words=[], new_counts=np.zeros(0, dtype=np.int64),
            merged_counts=delta.merged_counts,
            tail_words_total=delta.tail_words_total)
    new_vocab = extended_vocabulary(old_vocab, delta)
    entry = lineage_entry(old_vocab, new_vocab, delta, tail_fingerprint)
    chain = prior + [entry]
    dst = out_path or checkpoint_path
    state: TrainState = header["train_state"]
    if header["layout"] == "row-shards":
        _extend_row_shards(checkpoint_path, dst, header, new_vocab, delta,
                           chain, state, verify=verify)
    else:
        _extend_dense(checkpoint_path, dst, header, new_vocab, delta,
                      chain, state, verify=verify)
    logger.info(
        "extended checkpoint %s: vocab %d -> %d (+%d new words, "
        "%d tail occurrences) -> %s", checkpoint_path, old_vocab.size,
        new_vocab.size, delta.num_new, delta.tail_words_total, dst)
    return {
        "old_vocab_size": old_vocab.size,
        "new_vocab_size": new_vocab.size,
        "new_words": delta.num_new,
        "tail_words_total": delta.tail_words_total,
        "lineage_entry": entry,
        "lineage_depth": len(chain),
        "path": dst,
        "layout": header["layout"],
    }


def _extend_dense(src: str, dst: str, header: Dict[str, Any],
                  new_vocab: Vocabulary, delta: VocabDelta,
                  chain: List[dict], state: TrainState,
                  verify: bool) -> None:
    from glint_word2vec_tpu.train.checkpoint import save_model

    data = load_model(src, header=header, verify=False)
    syn0, syn1 = grow_arrays(
        data["syn0"], data["syn1"], delta,
        header["vector_size"] or data["syn0"].shape[1],
        header["config"].seed)
    save_model(dst, new_vocab.words, new_vocab.counts,
               syn0, syn1, header["config"], state,
               extra_metadata={"vocab_lineage": chain})
    if verify:
        V_old = delta.merged_counts.shape[0]
        # the writer stores float32 (save_model converts); compare in the
        # written dtype so the check is byte-for-byte what a reader gets —
        # BOTH matrices: syn1 is the training state the next increment
        # resumes from, a silently-corrupted carry there would train every
        # subsequent increment against wrong context vectors
        for name, src_arr in (("syn0", data["syn0"]), ("syn1", data["syn1"])):
            if src_arr is None:
                continue
            carried = np.load(os.path.join(dst, f"{name}.npy"),
                              mmap_mode="r")[:V_old]
            if not np.array_equal(np.asarray(carried),
                                  np.asarray(src_arr, dtype=np.float32)):
                raise CheckpointCorruptError(
                    f"extended checkpoint {dst!r}: carried {name} rows are "
                    f"not bit-identical to the source — migration bug or "
                    f"torn write")


def _copy_shard_verified(src_file: str, dst_file: str,
                         want_digest: Optional[str]) -> str:
    """Copy one shard file, hashing in the same pass; verify against the
    parent's recorded digest when one exists. Returns the digest (reused in
    the child's digest map — the bytes are identical by construction)."""
    with open(src_file, "rb") as fin, open(dst_file, "wb") as fout:
        w = _HashingWriter(fout)
        shutil.copyfileobj(fin, w, length=1 << 20)
    got = w.sha.hexdigest()
    if want_digest is not None and got != want_digest:
        raise CheckpointCorruptError(
            f"shard {src_file!r} digest {got[:12]}… does not match the "
            f"parent checkpoint's recorded {want_digest[:12]}… — refusing "
            f"to carry a corrupt shard into the extended checkpoint")
    return got


def _extend_row_shards(src: str, dst: str, header: Dict[str, Any],
                       new_vocab: Vocabulary, delta: VocabDelta,
                       chain: List[dict], state: TrainState,
                       verify: bool) -> None:
    """Per-shard growth: never materializes [V, D]; peak memory is one
    shard. Carried shards below V_old copy verbatim (digest-verified in the
    copy pass), the boundary shard slices at V_old, padding-only shards
    drop, and one new shard carries the seeded rows [V_old, V_new)."""
    with open(os.path.join(src, "metadata.json"), encoding="utf-8") as f:
        src_meta = json.load(f)
    parent_digests: Dict[str, str] = src_meta.get("digests") or {}
    cfg = header["config"]
    V_old = delta.merged_counts.shape[0]
    V_new = new_vocab.size
    vector_size = header["vector_size"] or cfg.vector_size

    parent = os.path.dirname(os.path.abspath(dst)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = os.path.join(parent, f".{os.path.basename(dst)}.tmp-{os.getpid()}")
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        digests: Dict[str, str] = {}
        padded_dim = None
        for name in ("syn0", "syn1"):
            src_dir = os.path.join(src, f"{name}.shards")
            if not os.path.isdir(src_dir):
                continue
            reader = ShardedMatrixReader(src_dir)
            padded_dim = reader.cols
            dst_dir = os.path.join(tmp, f"{name}.shards")
            os.makedirs(dst_dir)
            for start, stop, fname in reader._spans:
                rel_src = f"{name}.shards/{fname}"
                if stop <= V_old:
                    # wholly real rows: verbatim carry, digest-verified
                    digests[rel_src] = _copy_shard_verified(
                        os.path.join(src_dir, fname),
                        os.path.join(dst_dir, fname),
                        parent_digests.get(rel_src) if verify else None)
                elif start < V_old:
                    # the boundary shard: slice the padding rows off so the
                    # new rows can take coordinates [V_old, V_new)
                    if verify and rel_src in parent_digests:
                        got = _sha256_file(os.path.join(src_dir, fname))
                        if got != parent_digests[rel_src]:
                            raise CheckpointCorruptError(
                                f"shard {rel_src!r} digest mismatch in "
                                f"{src!r} — refusing to slice a corrupt "
                                f"boundary shard")
                    m = reader._undo_void(np.load(
                        os.path.join(src_dir, fname), mmap_mode="r"))
                    out_name = f"rows-{start:010d}-{V_old:010d}.npy"
                    digests[f"{name}.shards/{out_name}"] = _save_npy_hashed(
                        os.path.join(dst_dir, out_name),
                        np.ascontiguousarray(m[:V_old - start]))
                # start >= V_old: pure padding shard, dropped
            if delta.num_new:
                if name == "syn0":
                    block = np.zeros((delta.num_new, reader.cols),
                                     dtype=reader.dtype)
                    block[:, :vector_size] = seed_new_rows(
                        delta.num_new, vector_size, cfg.seed, V_old,
                        dtype=reader.dtype)
                else:
                    block = np.zeros((delta.num_new, reader.cols),
                                     dtype=reader.dtype)
                out_name = f"rows-{V_old:010d}-{V_new:010d}.npy"
                digests[f"{name}.shards/{out_name}"] = _save_npy_hashed(
                    os.path.join(dst_dir, out_name), block)
        digests["words"] = _save_words_hashed(
            os.path.join(tmp, "words"), new_vocab.words)
        digests["counts.npy"] = _save_npy_hashed(
            os.path.join(tmp, "counts.npy"),
            np.asarray(new_vocab.counts, dtype=np.int64))
        meta = {
            "format_version": _format_version(SHARDED_FORMAT_VERSION, state),
            "framework": "glint_word2vec_tpu",
            "layout": "row-shards",
            "vocab_size": V_new,
            "vector_size": int(vector_size),
            # spans now end exactly at V_new: the grown checkpoint carries no
            # padding rows (loaders re-pad onto their own target mesh)
            "padded_vocab": V_new,
            "padded_dim": int(padded_dim if padded_dim is not None
                              else vector_size),
            "config": cfg.to_dict(auto_markers=False),
            "train_state": state.to_dict(),
            "digests": digests,
        }
        _merge_extra_metadata(meta, {"vocab_lineage": chain})
        with open(os.path.join(tmp, "metadata.json"), "w",
                  encoding="utf-8") as f:
            json.dump(meta, f, indent=2)
        old = None
        if os.path.exists(dst):
            old = dst + f".old-{os.getpid()}"
            os.rename(dst, old)
        os.rename(tmp, dst)
        if old is not None:
            shutil.rmtree(old)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
