"""graftlint engine: file discovery, rule running, suppressions, reporting.

Design notes
------------
- **Rules are AST visitors over one module** (``check(ctx) -> [Finding]``),
  except *repo rules* (``repo_rule = True``) which see the repo root and may
  cross-reference files (R8 diffs the config/trainer refusal matrices).
- **Suppression syntax** (enforced, not decorative): a finding is suppressed
  only by a directive **with a written justification** on the flagged line or
  the line directly above::

      x = jnp.cumsum(totals, axis=0)  # graftlint: disable=R4 -- caller picks dtype

  A directive without the ``-- justification`` text is itself a finding
  (rule ``SUP``): silent suppressions are exactly the review rot this tool
  exists to stop.
- **Baseline**: the committed suppression inventory
  (tools/graftlint/baseline.json) pins the multiset of (path, rule) pairs
  that are allowed to be suppressed. ``--baseline`` fails on drift in either
  direction, so adding a suppression is a reviewed diff of the baseline file,
  and removing a stale one cleans it up.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import sys
from typing import Dict, List, Optional, Sequence

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\s]+?)"
    r"(?:\s*--\s*(.*?))?\s*$")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int          # 1-based
    col: int
    message: str
    suppressed: bool = False
    justification: Optional[str] = None

    def key(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class LintReport:
    findings: List[Finding]
    files_scanned: int

    @property
    def unsuppressed(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> List[Finding]:
        return [f for f in self.findings if f.suppressed]

    def to_dict(self) -> dict:
        counts: Dict[str, int] = {}
        for f in self.unsuppressed:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return {
            "tool": "graftlint",
            "files_scanned": self.files_scanned,
            "unsuppressed": [f.to_dict() for f in self.unsuppressed],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "counts_unsuppressed": counts,
            "ok": not self.unsuppressed,
        }


class ModuleContext:
    """Everything a per-file rule sees: path, parsed AST, raw lines."""

    def __init__(self, path: str, text: str):
        self.path = path.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text)
        # parent links + enclosing-qualname map, shared by several rules
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    def qualname(self, node: ast.AST) -> str:
        """Dotted name of the innermost enclosing function/class chain."""
        parts: List[str] = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(parts)) or "<module>"

    def enclosing_function(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return cur
            cur = self.parents.get(cur)
        return None


def _parse_suppressions(lines: Sequence[str]):
    """line (1-based) -> (set of rule ids, justification or None)."""
    out = {}
    for i, line in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        just = (m.group(2) or "").strip() or None
        out[i] = (rules, just)
    return out


def _apply_suppressions(ctx_lines: Sequence[str],
                        findings: List[Finding]) -> List[Finding]:
    sup = _parse_suppressions(ctx_lines)
    extra: List[Finding] = []
    seen_invalid = set()
    for f in findings:
        for line in (f.line, f.line - 1):
            entry = sup.get(line)
            if not entry:
                continue
            rules, just = entry
            if f.rule in rules or "all" in rules:
                if just:
                    f.suppressed = True
                    f.justification = just
                elif line not in seen_invalid:
                    seen_invalid.add(line)
                    extra.append(Finding(
                        rule="SUP", path=f.path, line=line, col=0,
                        message="suppression directive without a "
                                "justification (use `# graftlint: "
                                "disable=Rn -- why`)"))
                break
    return findings + extra


def lint_text(text: str, virtual_path: str, rules=None) -> List[Finding]:
    """Lint one source string as if it lived at ``virtual_path`` (the unit
    the fixture tests drive). Repo rules are skipped (no repo here)."""
    from tools.graftlint.rules import ALL_RULES
    rules = [r for r in (rules or ALL_RULES) if not getattr(r, "repo_rule", False)]
    ctx = ModuleContext(virtual_path, text)
    findings: List[Finding] = []
    for rule in rules:
        if rule.applies(ctx.path):
            findings.extend(rule.check(ctx))
    return _apply_suppressions(ctx.lines, findings)


# Files the per-file rules walk: library code + the JSON-contract tools.
# Tests/fixtures are deliberately out of scope (bad fixtures MUST lint dirty).
_SCAN_GLOBS = ("glint_word2vec_tpu", "tools")
_SCAN_TOP = ("bench.py", "__graft_entry__.py")
_SKIP_PARTS = ("__pycache__", os.path.join("tools", "graftlint"))


def iter_source_files(root: str):
    for top in _SCAN_TOP:
        p = os.path.join(root, top)
        if os.path.exists(p):
            yield p
    for d in _SCAN_GLOBS:
        base = os.path.join(root, d)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(x for x in dirnames if x != "__pycache__")
            if any(part in dirpath for part in _SKIP_PARTS):
                continue
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def lint_repo(root: str, rules=None) -> LintReport:
    from tools.graftlint.rules import ALL_RULES
    rules = list(rules or ALL_RULES)
    file_rules = [r for r in rules if not getattr(r, "repo_rule", False)]
    repo_rules = [r for r in rules if getattr(r, "repo_rule", False)]
    findings: List[Finding] = []
    n = 0
    for path in iter_source_files(root):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
        try:
            ctx = ModuleContext(rel, text)
        except SyntaxError as e:
            findings.append(Finding(
                rule="AST", path=rel, line=e.lineno or 0, col=0,
                message=f"syntax error: {e.msg}"))
            continue
        n += 1
        per_file: List[Finding] = []
        for rule in file_rules:
            if rule.applies(rel):
                per_file.extend(rule.check(ctx))
        findings.extend(_apply_suppressions(ctx.lines, per_file))
    for rule in repo_rules:
        findings.extend(rule.check_repo(root))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintReport(findings=findings, files_scanned=n)


def suppressed_inventory(report: LintReport) -> Dict[str, List[str]]:
    """The baseline shape: rule -> sorted list of paths (one entry per
    suppressed finding — a multiset, so adding a second suppression in the
    same file is still drift)."""
    inv: Dict[str, List[str]] = {}
    for f in report.suppressed:
        inv.setdefault(f.rule, []).append(f.path)
    return {k: sorted(v) for k, v in sorted(inv.items())}


def check_baseline(report: LintReport, baseline_path: str) -> List[str]:
    """Compare the suppression inventory against the committed baseline;
    returns human-readable drift messages (empty = clean)."""
    with open(baseline_path, "r", encoding="utf-8") as f:
        want = json.load(f).get("suppressed", {})
    have = suppressed_inventory(report)
    drift: List[str] = []
    for rule in sorted(set(want) | set(have)):
        w, h = want.get(rule, []), have.get(rule, [])
        if w != h:
            drift.append(
                f"suppression drift for {rule}: baseline {w} vs tree {h} "
                f"(update tools/graftlint/baseline.json in the same PR "
                f"with the justification)")
    return drift


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="graftlint", description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    ap.add_argument("--json", action="store_true",
                    help="emit the full JSON report on stdout "
                         "(default: human-readable)")
    ap.add_argument("--json-out", default="",
                    help="also write the JSON report to this path")
    ap.add_argument("--baseline", default="",
                    help="fail on suppression drift vs this baseline file "
                         "(default: the committed baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="skip the baseline drift check")
    args = ap.parse_args(argv)

    report = lint_repo(args.root)
    drift: List[str] = []
    if not args.no_baseline:
        baseline = args.baseline or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "baseline.json")
        if os.path.exists(baseline):
            drift = check_baseline(report, baseline)
        else:
            # fail CLOSED: a deleted/renamed/typo'd baseline must not
            # silently disable the suppression-inventory gate — skipping is
            # an explicit --no-baseline decision
            drift = [f"baseline file not found: {baseline} "
                     f"(pass --no-baseline to skip the drift check)"]

    payload = report.to_dict()
    payload["baseline_drift"] = drift
    payload["ok"] = payload["ok"] and not drift
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1)
    if args.json:
        print(json.dumps(payload))
    else:
        for f in report.unsuppressed:
            print(f"{f.path}:{f.line}:{f.col}: {f.rule}: {f.message}",
                  file=sys.stderr)
        for msg in drift:
            print(f"baseline: {msg}", file=sys.stderr)
        print(f"graftlint: {report.files_scanned} files, "
              f"{len(report.unsuppressed)} unsuppressed finding(s), "
              f"{len(report.suppressed)} suppressed, "
              f"{len(drift)} baseline drift(s)", file=sys.stderr)
    return 0 if payload["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
