"""Device-side pair generation (ops/pairgen.py) vs the host pipeline: bit-identical.

The device stream must reproduce data/pipeline._block_pairs exactly — same murmur3
position-keyed draws (data/hashrng.py contract), same subsample rule (mllib:371-379
intended semantics), same legacy asymmetric window (mllib:384-388) — so switching the
feed to raw token blocks never changes training results.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from glint_word2vec_tpu.data.hashrng import (
    STREAM_SUBSAMPLE, STREAM_WINDOW, stream_base)
from glint_word2vec_tpu.data.pipeline import _block_pairs, keep_probabilities
from glint_word2vec_tpu.ops.pairgen import device_block_pairs, pack_start_bits

V = 500
WINDOW = 5


def _mk_corpus(rng, n_sent, max_len):
    lengths = rng.integers(1, max_len, n_sent)
    tokens = rng.integers(0, V, int(lengths.sum())).astype(np.int32)
    return tokens, lengths.astype(np.int64)


def _host(tokens, lengths, keep, seed, iteration, shard, token_base,
          legacy=True):
    return _block_pairs(tokens, lengths, keep, WINDOW, seed, iteration, shard,
                        token_base, legacy)


def _device(tokens, lengths, keep, seed, iteration, shard, token_base, T, B,
            legacy=True):
    N = tokens.shape[0]
    padded = np.zeros(T, np.int32)
    padded[:N] = tokens
    bits = pack_start_bits(lengths, T)
    sub = stream_base(seed, STREAM_SUBSAMPLE, iteration, shard)
    win = stream_base(seed, STREAM_WINDOW, iteration, shard)
    fn = jax.jit(device_block_pairs, static_argnames=(
        "window", "num_pairs", "legacy_asymmetric_window"))
    return fn(
        jnp.asarray(padded), jnp.asarray(bits), jnp.int32(N),
        jnp.uint32(token_base & 0xFFFFFFFF), jnp.uint32(token_base >> 32),
        jnp.asarray(keep, jnp.float32), jnp.uint32(sub), jnp.uint32(win),
        window=WINDOW, num_pairs=B, legacy_asymmetric_window=legacy)


@pytest.mark.parametrize("subsample", [0.0, 1e-2])
@pytest.mark.parametrize("legacy", [True, False])
def test_device_stream_bit_identical_to_host(subsample, legacy):
    rng = np.random.default_rng(0)
    counts = np.maximum(1000 / (np.arange(V) + 2.0), 1.0)
    keep = keep_probabilities(counts, int(counts.sum()), subsample)
    tokens, lengths = _mk_corpus(rng, n_sent=60, max_len=30)
    hc, hx, _, hkept = _host(tokens, lengths, keep, seed=7, iteration=2,
                             shard=0, token_base=0, legacy=legacy)
    out = _device(tokens, lengths, keep, seed=7, iteration=2, shard=0,
                  token_base=0, T=1024, B=4096, legacy=legacy)
    n = int(out.mask.sum())
    assert n == hc.shape[0]
    np.testing.assert_array_equal(np.asarray(out.centers[:n]), hc)
    np.testing.assert_array_equal(np.asarray(out.contexts[:n]), hx)
    assert int(out.kept_words) == hkept
    assert int(out.dropped_pairs) == 0
    # masked tail is zeroed
    assert np.all(np.asarray(out.centers[n:]) == 0)


def test_device_stream_nonzero_token_base_matches_host():
    """Ordinal continuity: a later block (token_base > 0, incl. > 2^32 for the carry
    path) draws exactly the host's subsample/window decisions."""
    rng = np.random.default_rng(1)
    counts = np.maximum(1000 / (np.arange(V) + 2.0), 1.0)
    keep = keep_probabilities(counts, int(counts.sum()), 1e-2)
    tokens, lengths = _mk_corpus(rng, n_sent=40, max_len=25)
    for base in (12_345, (1 << 32) - 100):  # the second straddles the carry
        hc, hx, _, hkept = _host(tokens, lengths, keep, seed=3, iteration=1,
                                 shard=2, token_base=base)
        out = _device(tokens, lengths, keep, seed=3, iteration=1, shard=2,
                      token_base=base, T=1024, B=4096)
        n = int(out.mask.sum())
        assert n == hc.shape[0]
        np.testing.assert_array_equal(np.asarray(out.centers[:n]), hc)
        np.testing.assert_array_equal(np.asarray(out.contexts[:n]), hx)
        assert int(out.kept_words) == hkept


def test_device_overflow_drops_tail_pairs():
    """More window pairs than B slots: the first B pairs of the host stream are
    emitted, the remainder is counted in dropped_pairs."""
    rng = np.random.default_rng(2)
    keep = np.ones(V)
    tokens, lengths = _mk_corpus(rng, n_sent=50, max_len=30)
    hc, hx, _, _ = _host(tokens, lengths, keep, seed=1, iteration=1, shard=0,
                         token_base=0)
    B = hc.shape[0] // 2
    out = _device(tokens, lengths, keep, seed=1, iteration=1, shard=0,
                  token_base=0, T=2048, B=B)
    assert int(out.mask.sum()) == B
    np.testing.assert_array_equal(np.asarray(out.centers), hc[:B])
    np.testing.assert_array_equal(np.asarray(out.contexts), hx[:B])
    assert int(out.dropped_pairs) == hc.shape[0] - B


def test_split_blocks_concatenate_to_host_stream():
    """Two consecutive device blocks (whole-sentence packing, ordinal bases carried
    like the trainer does) concatenate to the host stream over the full corpus."""
    rng = np.random.default_rng(3)
    counts = np.maximum(1000 / (np.arange(V) + 2.0), 1.0)
    keep = keep_probabilities(counts, int(counts.sum()), 5e-3)
    tokens, lengths = _mk_corpus(rng, n_sent=50, max_len=30)
    hc, hx, _, _ = _host(tokens, lengths, keep, seed=9, iteration=1, shard=0,
                         token_base=0)
    # split at a sentence boundary near the middle
    s_half = len(lengths) // 2
    n1 = int(lengths[:s_half].sum())
    parts = []
    for toks, lens, base in (
            (tokens[:n1], lengths[:s_half], 0),
            (tokens[n1:], lengths[s_half:], n1)):
        out = _device(toks, lens, keep, seed=9, iteration=1, shard=0,
                      token_base=base, T=1024, B=4096)
        n = int(out.mask.sum())
        parts.append((np.asarray(out.centers[:n]), np.asarray(out.contexts[:n])))
    np.testing.assert_array_equal(np.concatenate([p[0] for p in parts]), hc)
    np.testing.assert_array_equal(np.concatenate([p[1] for p in parts]), hx)


def test_empty_and_all_dropped_blocks():
    keep = np.zeros(V)  # drop everything
    tokens = np.arange(20, dtype=np.int32) % V
    lengths = np.asarray([10, 10], np.int64)
    out = _device(tokens, lengths, keep, seed=0, iteration=1, shard=0,
                  token_base=0, T=64, B=128)
    assert int(out.mask.sum()) == 0
    assert int(out.kept_words) == 0
    # zero valid tokens at all
    out = _device(np.empty(0, np.int32), np.empty(0, np.int64), np.ones(V),
                  seed=0, iteration=1, shard=0, token_base=0, T=64, B=128)
    assert int(out.mask.sum()) == 0
