"""The self-stabilizing runtime (ISSUE 7): in-step stabilizers + the
norm-watchdog recovery ladder.

Four layers, each pinned where it can actually break:

1. ORACLE — the stabilized shared-pool SGNS update (update_clip → scatter →
   per-touched-row decay+clamp) against a plain-NumPy float64 oracle: clamp
   engaged and not engaged, masked batch slots excluded from the touched set,
   never-touched (padding-class) rows bit-untouched, and the all-off state
   bit-identical to the pre-stabilizer step.
2. CROSS-LOWERING — GSPMD single-program ≡ shard_map owner-local at f64
   ~1e-11 with stabilizers ON (every mesh shape), and banded CBOW ≡ scatter
   CBOW with the clamp+clip engaged.
3. ESCALATION LADDER — watchdog `recover` policy units (would_fire purity,
   one recovery per firing probe, budget decrement, exhaustion degrades to
   the halt contract with the telemetry record emitted BEFORE the raise) and
   the snapshot-ring arming fix (the previously-dead norm_watch='recover' +
   nonfinite_policy='halt' combination).
4. VOCAB-SCALED AUTO POOL — the trainer re-resolves a still-AUTO pool into
   the measured large-vocab safe band (load <= 160 past 500k vocab), never
   touches explicit pools, and keeps replace() re-resolution semantics.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.pipeline import encode_sentences
from glint_word2vec_tpu.data.vocab import Vocabulary, build_vocab
from glint_word2vec_tpu.obs.watch import NormWatchdog
from glint_word2vec_tpu.ops.sgns import (
    EmbeddingPair,
    Stabilizers,
    sgns_step_shared_core,
)
from glint_word2vec_tpu.train import faults
from glint_word2vec_tpu.train.faults import NormBlowupError
from glint_word2vec_tpu.train.trainer import Trainer

# ---------------------------------------------------------------------------
# 1. NumPy float64 oracle for the stabilized shared-pool step
# ---------------------------------------------------------------------------


def _np_shared_step(syn0, syn1, centers, contexts, mask, negs, alpha, n,
                    stab: Stabilizers):
    """Plain-NumPy mirror of sgns_step_shared_core + stabilizers (float64)."""
    e_in, e_pos, Z = syn0[centers], syn1[contexts], syn1[negs]
    P = negs.shape[0]

    def sig(x):
        # the numerically-stable two-branch expit, matching jax.nn.sigmoid
        # to the ulp (the naive 1/(1+exp(-x)) loses precision for x < 0,
        # which the blown-row dot products amplify past the tolerance)
        out = np.empty_like(x)
        pos = x >= 0
        out[pos] = 1.0 / (1.0 + np.exp(-x[pos]))
        ex = np.exp(x[~pos])
        out[~pos] = ex / (1.0 + ex)
        return out
    f_pos = (e_in * e_pos).sum(-1)
    f_neg = e_in @ Z.T
    neg_valid = (negs[None, :] != contexts[:, None]).astype(np.float64) \
        * mask[:, None]
    g_pos = (1.0 - sig(f_pos)) * alpha * mask
    g_neg = (0.0 - sig(f_neg)) * alpha * neg_valid * (n / P)
    d_in = g_pos[:, None] * e_pos + g_neg @ Z
    d_pos = g_pos[:, None] * e_in
    d_Z = g_neg.T @ e_in
    if stab.update_clip:
        def clip(d):
            nrm = np.linalg.norm(d, axis=-1, keepdims=True)
            return d * np.minimum(1.0, stab.update_clip / np.maximum(
                nrm, 1e-30))
        d_in, d_pos = clip(d_in), clip(d_pos)
    s0, s1 = syn0.copy(), syn1.copy()
    np.add.at(s0, centers, d_in)
    np.add.at(s1, contexts, d_pos)
    np.add.at(s1, negs, d_Z)
    if (stab.max_row_norm or stab.row_l2) and mask.sum() > 0:
        t0 = np.unique(centers[mask > 0])
        t1 = np.unique(np.concatenate([contexts[mask > 0], negs]))
        for mat, idx in ((s0, t0), (s1, t1)):
            rows = mat[idx]
            scale = np.ones(len(idx))
            if stab.row_l2:
                scale = scale * (1.0 - alpha * stab.row_l2)
            if stab.max_row_norm:
                nrm = np.linalg.norm(rows, axis=-1) * scale
                scale = scale * np.minimum(
                    1.0, stab.max_row_norm / np.maximum(nrm, 1e-30))
            mat[idx] = rows * scale[:, None]
    return s0, s1


def _oracle_inputs(seed=0, V=60, D=12, B=24, P=8):
    rng = np.random.default_rng(seed)
    syn0 = rng.normal(0, 0.5, (V, D))
    syn1 = rng.normal(0, 0.5, (V, D))
    syn0[40] *= 300.0          # a blown row the clamp must catch when touched
    syn1[41] *= 300.0
    syn0[V - 2] *= 500.0       # NEVER touched — must stay bit-identical
    centers = rng.integers(0, 38, B).astype(np.int32)
    contexts = rng.integers(0, 38, B).astype(np.int32)
    centers[0], contexts[1] = 40, 41          # blown rows get touched
    # masked tail slots deliberately point at the blown rows: the sentinel
    # gating must keep them OUT of the clamp/decay pass
    mask = (np.arange(B) < B - 4).astype(np.float64)
    centers[B - 1], contexts[B - 1] = 40, 41
    negs = rng.integers(0, 38, P).astype(np.int32)
    return syn0, syn1, centers, contexts, mask, negs


@pytest.mark.parametrize("stab", [
    Stabilizers(),                                        # all off
    Stabilizers(max_row_norm=5.0),                        # clamp only
    Stabilizers(update_clip=0.05),                        # clip only
    Stabilizers(row_l2=1e-3),                             # decay only
    Stabilizers(max_row_norm=5.0, update_clip=0.05, row_l2=1e-3),
    Stabilizers(max_row_norm=1e6),                        # present, no row hit
])
def test_shared_pool_oracle_f64(stab):
    from jax.experimental import enable_x64

    syn0, syn1, centers, contexts, mask, negs = _oracle_inputs()
    n = 3
    alpha = 0.05
    ref0, ref1 = _np_shared_step(
        syn0, syn1, centers, contexts, mask, negs, alpha, n, stab)
    with enable_x64():
        got, _ = sgns_step_shared_core(
            EmbeddingPair(jnp.asarray(syn0), jnp.asarray(syn1)),
            jnp.asarray(centers), jnp.asarray(contexts),
            jnp.asarray(mask, jnp.float32), jnp.asarray(negs),
            jnp.float64(alpha), n, "exact", jnp.float64, False, jnp.float64,
            True, stabilizers=stab if stab.enabled else None)
    # atol 3e-8, not 1e-11: XLA's exp differs from libm's in the last ulps,
    # and the deliberately 300x-blown rows amplify that through the sigmoid
    # chain; any real semantic error (dropped clamp, double decay, wrong
    # touched set) is orders of magnitude larger
    np.testing.assert_allclose(np.asarray(got.syn0), ref0, atol=3e-8)
    np.testing.assert_allclose(np.asarray(got.syn1), ref1, atol=3e-8)
    # the never-touched blown row is BIT-identical — no dense renorm pass
    assert np.array_equal(np.asarray(got.syn0)[syn0.shape[0] - 2],
                          syn0[syn0.shape[0] - 2])
    if stab.max_row_norm:
        norms0 = np.linalg.norm(np.asarray(got.syn0), axis=1)
        assert norms0[40] <= stab.max_row_norm * (1 + 1e-9)


def test_off_state_bit_identical():
    """stabilizers=None, all-zero Stabilizers, and the pre-stabilizer call
    signature produce the bit-identical compiled step."""
    syn0, syn1, centers, contexts, mask, negs = _oracle_inputs()
    params = EmbeddingPair(jnp.asarray(syn0, jnp.float32),
                           jnp.asarray(syn1, jnp.float32))
    args = (jnp.asarray(centers), jnp.asarray(contexts),
            jnp.asarray(mask, jnp.float32), jnp.asarray(negs),
            jnp.float32(0.05), 3)
    base, _ = sgns_step_shared_core(params, *args)
    none_, _ = sgns_step_shared_core(params, *args, stabilizers=None)
    zero, _ = sgns_step_shared_core(params, *args,
                                    stabilizers=Stabilizers())
    for other in (none_, zero):
        assert np.array_equal(np.asarray(base.syn0), np.asarray(other.syn0))
        assert np.array_equal(np.asarray(base.syn1), np.asarray(other.syn1))


def test_update_clip_bounds_single_pair_delta():
    """With no duplicates, clamp/decay off: ||new_row − old_row|| <= clip."""
    rng = np.random.default_rng(1)
    V, D = 20, 8
    syn0 = rng.normal(0, 5.0, (V, D)).astype(np.float32)
    syn1 = rng.normal(0, 5.0, (V, D)).astype(np.float32)
    params = EmbeddingPair(jnp.asarray(syn0), jnp.asarray(syn1))
    got, _ = sgns_step_shared_core(
        params, jnp.asarray([3], jnp.int32), jnp.asarray([7], jnp.int32),
        jnp.ones(1, jnp.float32), jnp.asarray([11, 12], jnp.int32),
        jnp.float32(5.0),  # absurd lr so the unclipped delta is huge
        3, stabilizers=Stabilizers(update_clip=0.25))
    d_center = np.linalg.norm(np.asarray(got.syn0)[3] - syn0[3])
    d_ctx = np.linalg.norm(np.asarray(got.syn1)[7] - syn1[7])
    assert d_center <= 0.25 * (1 + 1e-5)
    assert d_ctx <= 0.25 * (1 + 1e-5)


# ---------------------------------------------------------------------------
# 2. cross-lowering agreement with stabilizers ON
# ---------------------------------------------------------------------------

MESHES = [(1, 8), (2, 4), (8, 1)]


@pytest.mark.parametrize("shape", MESHES)
def test_shard_map_stabilized_equivalence_f64(shape):
    from jax.experimental import enable_x64

    from glint_word2vec_tpu.ops.sgns_shard import make_shard_map_sgns_step
    from glint_word2vec_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh (conftest)")
    with enable_x64():
        syn0, syn1, centers, contexts, mask, negs = _oracle_inputs(
            seed=2, V=64, D=16, B=16, P=8)
        stab = Stabilizers(max_row_norm=5.0, update_clip=0.1, row_l2=1e-3)
        params = EmbeddingPair(jnp.asarray(syn0), jnp.asarray(syn1))
        batch = {"centers": jnp.asarray(centers),
                 "contexts": jnp.asarray(contexts),
                 "mask": jnp.asarray(mask, jnp.float32)}
        alpha = jnp.float64(0.025)
        plan = make_mesh(*shape)
        sharded = EmbeddingPair(jax.device_put(params.syn0, plan.embedding),
                                jax.device_put(params.syn1, plan.embedding))
        step = make_shard_map_sgns_step(
            plan.mesh, 3, compute_dtype=jnp.float64,
            logits_dtype=jnp.float64, stabilizers=stab)
        ps, _ = step(sharded, batch, jnp.asarray(negs), alpha)
        pr, _ = sgns_step_shared_core(
            params, batch["centers"], batch["contexts"], batch["mask"],
            jnp.asarray(negs), alpha, 3, "exact", jnp.float64, False,
            jnp.float64, True, stabilizers=stab)
        np.testing.assert_allclose(np.asarray(ps.syn0), np.asarray(pr.syn0),
                                   atol=1e-11)
        np.testing.assert_allclose(np.asarray(ps.syn1), np.asarray(pr.syn1),
                                   atol=1e-11)


def test_banded_scatter_stabilized_equivalence_f64():
    """Banded CBOW ≡ scatter CBOW with clamp+clip engaged (row_l2 stays off
    here: the two formulations' touched SETS differ on context-less tokens —
    documented in cbow_step_banded_core — so decay is pinned by the oracle
    and SGNS lowering tests instead)."""
    from jax.experimental import enable_x64

    from test_cbow_banded import _banded_blocks, _host_windows, _kept_stream

    from glint_word2vec_tpu.ops.cbow_banded import cbow_step_banded_core

    with enable_x64():
        rng = np.random.default_rng(3)
        V, D, P, W, NEG = 120, 16, 16, 3, 4
        ktoks, starts = _kept_stream(rng, 40, 15, V)
        left_h, right_h = _host_windows(ktoks, starts, W)
        live = np.flatnonzero(left_h + right_h > 0)
        assert live.size > 20

        syn0 = rng.normal(0, 0.1, (V, D))
        syn1 = rng.normal(0, 0.05, (V, D))
        # blow a row that IS a live context/center and a pool row — the clamp
        # must catch them identically in both formulations
        blown = int(ktoks[live[3]])
        syn0[blown] *= 400.0
        negs = rng.integers(0, V, P).astype(np.int32)
        syn1[negs[0]] *= 400.0
        params0 = EmbeddingPair(jnp.asarray(syn0), jnp.asarray(syn1))
        alpha = jnp.float64(0.05)
        stab = Stabilizers(max_row_norm=2.0, update_clip=0.05)

        T = ktoks.shape[0] + 2 * W + 5
        ((tb, band, nc),) = _banded_blocks(ktoks, starts, T, W)
        p_band, _ = cbow_step_banded_core(
            params0, jnp.asarray(tb), band.left, band.right, band.center,
            band.token, jnp.asarray(negs), alpha, NEG, W, "exact",
            jnp.float64, jnp.float64, True, stabilizers=stab)
        # scatter reference over the same live example set + stabilizers
        from glint_word2vec_tpu.ops.sgns import cbow_step_shared_core
        C = 2 * W
        nb = len(live)
        ctx = np.zeros((nb, C), np.int32)
        ctxm = np.zeros((nb, C), np.float32)
        for i, b in enumerate(live):
            idx = (list(range(b - left_h[b], b))
                   + list(range(b + 1, b + right_h[b] + 1)))
            ctx[i, :len(idx)] = ktoks[idx]
            ctxm[i, :len(idx)] = 1.0
        p_ref, _ = cbow_step_shared_core(
            params0, jnp.asarray(ktoks[live].astype(np.int32)),
            jnp.asarray(ctx), jnp.asarray(ctxm),
            jnp.ones(nb, jnp.float32), jnp.asarray(negs), alpha, NEG,
            "exact", jnp.float64, jnp.float64, True, stabilizers=stab)
        np.testing.assert_allclose(
            np.asarray(p_band.syn0), np.asarray(p_ref.syn0), atol=1e-10)
        np.testing.assert_allclose(
            np.asarray(p_band.syn1), np.asarray(p_ref.syn1), atol=1e-10)
        # the clamp actually engaged
        assert np.linalg.norm(np.asarray(p_band.syn0)[blown]) <= 2.0 + 1e-9


# ---------------------------------------------------------------------------
# 3. escalation ladder
# ---------------------------------------------------------------------------


def _channels(max_norm=1.0, frac=0.0):
    m = {"max_norm": max_norm, "mean_norm": 1.0, "p99_norm": 1.0,
         "frac_over": frac}
    return {"finite": True, "syn0": dict(m), "syn1": dict(m)}


def test_watchdog_would_fire_is_pure():
    wd = NormWatchdog("recover", threshold=100.0, max_norm=1000.0, frac=0.01)
    assert wd.would_fire(_channels()) is None
    assert wd.would_fire(_channels(max_norm=5000.0))
    assert wd.fires == 0 and wd.last_reason is None  # no state touched


def test_watchdog_recover_policy_returns_reason_no_raise():
    wd = NormWatchdog("recover", 100.0, 1000.0, 0.01)
    reason = wd.check(_channels(frac=0.5), step=10)
    assert reason and "exceed norm" in reason
    assert wd.fires == 1


def _toy_sentences(n=200, seed=2):
    rng = np.random.default_rng(seed)
    return [[f"w{i}" for i in rng.integers(0, 30, 20)] for _ in range(n)]


def _toy_cfg(**kw):
    return Word2VecConfig(
        vector_size=8, pairs_per_batch=128, window=3, num_iterations=2,
        steps_per_dispatch=2, heartbeat_every_steps=2, subsample_ratio=0.0,
        prefetch_chunks=0, seed=1, **kw)


def _toy_trainer(**kw):
    sents = _toy_sentences()
    vocab = build_vocab(sents, min_count=1)
    enc = encode_sentences(sents, vocab, 1000)
    return Trainer(_toy_cfg(**kw), vocab), enc


def test_snapshot_ring_arms_for_recover_without_rollback_policy():
    """The arming bugfix: pre-round-12 the ring seeded only under
    nonfinite_policy='rollback', so norm_watch='recover' beside
    nonfinite_policy='halt' found it empty on first firing."""
    trainer, _ = _toy_trainer(norm_watch="recover", nonfinite_policy="halt")
    assert trainer._needs_snapshot_ring
    trainer._start_run_bookkeeping()
    assert len(trainer._snapshot_ring) == 1
    # and the rollback-only arming still works
    trainer2, _ = _toy_trainer(nonfinite_policy="rollback")
    trainer2._start_run_bookkeeping()
    assert len(trainer2._snapshot_ring) == 1
    # while a consumer-less config pays nothing
    trainer3, _ = _toy_trainer(nonfinite_policy="halt")
    assert not trainer3._needs_snapshot_ring
    trainer3._start_run_bookkeeping()
    assert len(trainer3._snapshot_ring) == 0


def test_recover_ladder_end_to_end(tmp_path):
    """One injected finite blowup under the previously-dead combination:
    recover fires ONCE, rolls back, backs lr off, engages the clamp, and the
    fit FINISHES finite — with schema-valid watchdog + recovery records."""
    run_log = str(tmp_path / "run.jsonl")
    faults.configure(scale_params_at_step=8)
    try:
        trainer, enc = _toy_trainer(
            norm_watch="recover", nonfinite_policy="halt",
            telemetry_path=run_log)
        trainer.fit(enc)
    finally:
        faults.reset()
    assert trainer.recoveries_performed == 1   # one recovery per firing probe
    assert trainer.norm_watchdog.fires == 1
    assert trainer._lr_scale == pytest.approx(0.5)
    assert trainer._stabilizers.max_row_norm == pytest.approx(
        trainer.config.norm_watch_threshold)
    assert np.isfinite(np.asarray(trainer.params.syn0)).all()
    norms = np.linalg.norm(np.asarray(trainer.params.syn0, np.float64),
                           axis=1)
    assert norms.max() <= trainer.config.norm_watch_threshold * 1.001

    from glint_word2vec_tpu.obs.schema import validate_file
    summary = validate_file(run_log)
    assert summary["ok"], summary["errors"]
    assert summary["kinds"].get("recovery") == 1
    assert summary["kinds"].get("watchdog", 0) >= 1
    recs = [json.loads(line) for line in open(run_log)
            if '"kind": "recovery"' in line]
    assert recs[0]["action"] == "rollback"
    assert recs[0]["recoveries_performed"] == 1
    assert recs[0]["lr_scale"] == pytest.approx(0.5)
    assert recs[0]["max_row_norm"] == pytest.approx(
        trainer.config.norm_watch_threshold)
    # run_end carries the recovery outcome
    ends = [json.loads(line) for line in open(run_log)
            if '"kind": "run_end"' in line]
    assert ends[-1]["recoveries"] == 1 and ends[-1]["status"] == "ok"


def test_recover_budget_decrements_then_halts(tmp_path):
    """A repeatedly-reblowing run: the budget decrements one recovery per
    firing, and exhaustion degrades to the halt contract — with the halt
    recovery record emitted BEFORE the raise."""
    run_log = str(tmp_path / "run.jsonl")
    faults.configure(scale_params_at_step=8, scale_params_times=99)
    trainer = None
    try:
        trainer, enc = _toy_trainer(
            norm_watch="recover", nonfinite_policy="halt",
            max_recoveries=2, telemetry_path=run_log)
        with pytest.raises(NormBlowupError, match="budget exhausted"):
            trainer.fit(enc)
    finally:
        faults.reset()
    assert trainer.recoveries_performed == 2
    # lr backoff compounds per recovery
    assert trainer._lr_scale == pytest.approx(0.25)
    recs = [json.loads(line) for line in open(run_log)
            if '"kind": "recovery"' in line]
    assert [r["action"] for r in recs] == ["rollback", "rollback", "halt"]
    assert recs[-1]["snapshot_step"] == -1
    ends = [json.loads(line) for line in open(run_log)
            if '"kind": "run_end"' in line]
    assert ends[-1]["status"] == "error"
    from glint_word2vec_tpu.obs.schema import validate_file
    assert validate_file(run_log)["ok"]


def test_recover_lr_backoff_scales_dispatched_alphas():
    trainer, _ = _toy_trainer(norm_watch="recover", nonfinite_policy="halt")
    trainer._lr_scale = 0.25
    meta = np.stack([np.full(4, 0.02, np.float32), np.ones(4, np.float32)])
    meta_dev, _ = trainer._stage_dispatch_meta(meta, 1)
    np.testing.assert_allclose(np.asarray(meta_dev)[0],
                               0.25 * meta[0], rtol=1e-6)
    assert meta[0][0] == np.float32(0.02)  # producer's array not mutated


def test_maybe_snapshot_skips_states_the_watchdog_flags():
    trainer, _ = _toy_trainer(norm_watch="recover", nonfinite_policy="halt")
    trainer._snapshot_ring.clear()
    trainer._maybe_snapshot(_channels(max_norm=5000.0))   # would fire
    assert len(trainer._snapshot_ring) == 0
    trainer._maybe_snapshot(_channels())                  # healthy
    assert len(trainer._snapshot_ring) == 1


# ---------------------------------------------------------------------------
# 4. vocab-scaled AUTO pool
# ---------------------------------------------------------------------------


def _big_vocab(size):
    rng = np.random.default_rng(0)
    counts = rng.integers(5, 50, size).astype(np.int64)
    return Vocabulary.from_words_and_counts(
        [f"w{i}" for i in range(size)], counts)


def _large_vocab_trainer(**kw):
    cfg = Word2VecConfig(
        vector_size=8, pad_vector_to_lanes=False, pairs_per_batch=65536,
        subsample_ratio=1e-4, prefetch_chunks=0, **kw)
    return Trainer(cfg, _big_vocab(600_001))


def test_auto_pool_scales_with_vocab():
    trainer = _large_vocab_trainer()
    cfg = trainer.config
    load = cfg.pairs_per_batch * cfg.negatives / cfg.negative_pool
    assert load <= Trainer._LARGE_VOCAB_SAFE_LOAD
    assert cfg.negative_pool % 128 == 0
    assert getattr(cfg, "_auto_pool", False)   # re-resolution kept AUTO-ness
    # replace() re-derives from -1 (the from_dict/replace semantics intact):
    # a geometry change re-runs the config-time rule, not the frozen value
    derived = cfg.replace(pairs_per_batch=8192)
    assert getattr(derived, "_auto_pool", False)
    assert derived.negative_pool == Word2VecConfig(
        pairs_per_batch=8192).negative_pool


def test_explicit_pool_never_rescaled():
    trainer = _large_vocab_trainer(negative_pool=640)
    assert trainer.config.negative_pool == 640
    assert not getattr(trainer.config, "_auto_pool", True)


def test_to_dict_round_trip_preserves_pool_autoness():
    """The worker-transport round trip (to_dict with auto markers →
    from_dict) must keep an AUTO pool AUTO, or the receiving trainer's
    vocab-scaled safety re-resolution silently never runs."""
    cfg = Word2VecConfig(pairs_per_batch=65536)
    assert getattr(cfg, "_auto_pool", False)
    rt = Word2VecConfig.from_dict(cfg.to_dict())
    assert getattr(rt, "_auto_pool", False)
    assert rt.negative_pool == cfg.negative_pool  # same resolved value
    # checkpoints pin the RESOLVED value instead (trained semantics)
    assert cfg.to_dict(auto_markers=False)["negative_pool"] \
        == cfg.negative_pool
    # an explicit pool stays explicit through the round trip
    ex = Word2VecConfig(pairs_per_batch=65536, negative_pool=640)
    rt2 = Word2VecConfig.from_dict(ex.to_dict())
    assert rt2.negative_pool == 640
    assert not getattr(rt2, "_auto_pool", True)


def test_small_vocab_auto_pool_unchanged():
    """Below the boundary the config-time resolution stands untouched."""
    sents = _toy_sentences()
    vocab = build_vocab(sents, min_count=1)
    cfg = Word2VecConfig(pairs_per_batch=65536, vector_size=8,
                         subsample_ratio=1e-3)
    trainer = Trainer(cfg, vocab)
    assert trainer.config.negative_pool == Word2VecConfig(
        pairs_per_batch=65536).negative_pool


# ---------------------------------------------------------------------------
# trainer-level stabilized smoke: every step path accepts the knobs
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kw", [
    dict(),                                           # shared pool resolves 0
    dict(negative_pool=64),                           # shared pool
    dict(cbow=True),                                  # per-example CBOW
    dict(cbow=True, negative_pool=64),                # shared-pool CBOW
    dict(cbow=True, negative_pool=64, cbow_update="banded"),
    dict(device_pairgen=True),                        # device feed
])
def test_stabilized_fit_smoke_all_paths(kw):
    sents = _toy_sentences(60)
    vocab = build_vocab(sents, min_count=1)
    enc = encode_sentences(sents, vocab, 1000)
    cfg = _toy_cfg(max_row_norm=50.0, update_clip=0.5, row_l2=1e-4, **kw)
    trainer = Trainer(cfg, vocab)
    assert trainer._stabilizers.enabled
    trainer.fit(enc)
    emb = np.asarray(trainer.params.syn0, np.float64)
    assert np.isfinite(emb).all()
    assert np.linalg.norm(emb, axis=1).max() <= 50.0 * 1.001


def test_default_config_stabilizers_off():
    trainer, _ = _toy_trainer()
    assert not trainer._stabilizers.enabled
