"""On-device unigram negative sampler (replaces reference component G7).

The reference materializes a server-resident unigram table of ``unigramTableSize`` entries
(default 10^8 — 400 MB of int32; mllib:81,234-244, built fork-side from broadcast vocab
counts, mllib:317,355-359) and draws negatives by indexing it with a shared seed so every
parameter-server shard samples identical negatives without communicating them (G3 contract,
mllib:419-421).

TPU-native replacement: a **Walker alias table** over the counts^0.75 unigram distribution —
O(2·vocab) memory instead of O(table_size), *exact* (no quantization), sampled fully
on-device with ``jax.random`` in O(1) per draw. The shared-seed trick survives as ordinary
functional PRNG: every device derives the same per-step key, so data-parallel replicas and
model shards agree on negatives for free.

A quantized table-based sampler (:func:`build_unigram_table`) is kept for distribution-parity
tests against the classic word2vec table semantics.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class AliasTable(NamedTuple):
    """Walker alias method tables for a categorical distribution over vocab rows.

    prob[i] ∈ [0,1]: probability of keeping bucket i's own index; alias[i]: the index drawn
    otherwise. Both shape [vocab_size]; small and replicable across the mesh.
    """

    prob: jax.Array   # float32 [V]
    alias: jax.Array  # int32 [V]

    @property
    def vocab_size(self) -> int:
        return self.prob.shape[0]


# Fixed partition fan-out of the parallel alias build. A CONSTANT (never a
# function of the worker count), so the table is deterministic per
# (counts, power) — a worker knob that changed the realized negative-sample
# stream would make throughput settings quality-relevant.
_ALIAS_PARTITIONS = 16
_ALIAS_PARTITION_MIN_V = 1 << 18


def _alias_pair_sweep(scaled: np.ndarray, prob: np.ndarray, alias: np.ndarray,
                      small: np.ndarray, large: np.ndarray):
    """Vose pairing over the given small/large index queues, vectorized by
    CUMULATIVE MATCHING: one round assigns EVERY coverable small bucket to a
    large donor by aligning the cumulative deficit (1 − scaled[small]) against
    the cumulative surplus (scaled[large] − 1) with a searchsorted — O(V log V)
    across a handful of rounds, vs the old one-small-per-large round pairing
    whose 10k+ rounds of queue concatenation dominated the 10M-vocab build
    (PERF.md §10). A donor pushed below residual 1 demotes to the small queue
    (classic Vose), and the fp endgame — total remaining surplus smaller than
    the first deficit — falls back to one literal Vose pairing round, which
    absorbs the rounding imbalance exactly like the old builder. Mutates
    prob/alias/scaled in place; returns the leftover (small, large) queues
    (numerically ≈1 entries, finalized by the caller).

    Exactness: any pairing order yields an exact table — correctness only
    needs each bucket's kept probability plus its inbound alias mass to equal
    ``scaled`` — and both branches maintain that invariant; the construction
    is deterministic (fixed queue orders, no RNG)."""
    while small.size and large.size:
        d = 1.0 - scaled[small]
        j = np.searchsorted(np.cumsum(scaled[large] - 1.0), np.cumsum(d),
                            side="left")
        assigned = j < large.size
        if assigned.any():
            sa, ja = small[assigned], j[assigned]
            prob[sa] = scaled[sa]
            alias[sa] = large[ja]
            take = np.bincount(ja, weights=d[assigned], minlength=large.size)
            scaled[large] -= take
            now_small = scaled[large] < 1.0
            small = np.concatenate([small[~assigned], large[now_small]])
            large = large[~now_small]
        else:
            k = min(small.size, large.size)
            s, small = small[:k], small[k:]
            l = large[:k]
            prob[s] = scaled[s]
            alias[s] = l
            scaled[l] -= 1.0 - scaled[s]
            now_small = scaled[l] < 1.0
            small = np.concatenate([small, l[now_small]])
            large = np.concatenate([l[~now_small], large[k:]])
    return small, large


def build_alias_table(counts: np.ndarray, power: float = 0.75,
                      workers: int = 1) -> AliasTable:
    """Build alias tables for p(w) ∝ counts[w]^power (classic word2vec 3/4 power).

    Host-side Vose construction, vectorized by cumulative matching
    (:func:`_alias_pair_sweep`). Above ``_ALIAS_PARTITION_MIN_V`` rows the
    build is PARTITIONED: a fixed ``_ALIAS_PARTITIONS``-way strided split (the
    stride interleaves the Zipf head so every partition gets a balanced
    small/large mix) is swept per partition — independently, on ``workers``
    threads when ``workers > 1`` (numpy releases the GIL in the hot ops) —
    and the per-partition leftovers merge through one final sweep. The
    partition count is a constant, never the worker count, so the table is
    deterministic per (counts, power) at ANY ``workers``; partitions touch
    disjoint index sets, so concurrent in-place writes never overlap.

    **Rebuild vs incremental (continual training, docs/continual.md):** a
    vocab extension / counts merge REBUILDS the table from the merged
    counts rather than patching the old one — there is no incremental
    update path, by design. The rebuilt table is *distribution-exact* for
    the merged counts (the alias construction is exact for any counts;
    pinned by the implied-distribution equality test at an extended vocab,
    tests/test_continual.py), but the (prob, alias) PAIRING differs from
    the old table's, so the REALIZED negative-sample stream after an
    increment is not a continuation of the pre-increment stream — the same
    cross-release caveat as the round-8 vectorized builder (PERF.md §10,
    config.io_workers note). Continual increments may legally change the
    negative stream; only the sampled distribution is contractual.
    """
    counts = np.asarray(counts, dtype=np.float64)
    if counts.ndim != 1 or counts.size == 0:
        raise ValueError("counts must be a nonempty 1-D array")
    weights = np.power(np.maximum(counts, 0.0), power)
    total = weights.sum()
    if total <= 0:
        raise ValueError("all counts are zero")
    V = counts.size
    scaled = weights * (V / total)  # mean 1.0
    prob = np.ones(V, dtype=np.float64)
    alias = np.arange(V, dtype=np.int64)

    if V >= _ALIAS_PARTITION_MIN_V:
        P = _ALIAS_PARTITIONS

        def sweep_partition(c: int):
            idx = np.arange(c, V, P)
            sc = scaled[idx]
            return _alias_pair_sweep(
                scaled, prob, alias, idx[sc < 1.0], idx[sc >= 1.0])

        # R1 determinism audit (ISSUE 5): this fan-out is ordered-merge safe —
        # partitions mutate disjoint strided index sets and the leftovers are
        # consumed in partition order below — so it routes through the one
        # blessed pool primitive instead of an ad-hoc executor. workers<=1
        # degrades to the same serial loop as before inside ordered_pool_map.
        from glint_word2vec_tpu.data.pipeline import ordered_pool_map
        leftovers = list(ordered_pool_map(
            sweep_partition, range(P), workers=min(workers, P)))
        small = np.concatenate([s for s, _ in leftovers])
        large = np.concatenate([l for _, l in leftovers])
    else:
        small = np.flatnonzero(scaled < 1.0)
        large = np.flatnonzero(scaled >= 1.0)
    small, large = _alias_pair_sweep(scaled, prob, alias, small, large)
    # leftovers are numerically ≈1: keep their own index
    prob[small] = 1.0
    prob[large] = 1.0
    return AliasTable(
        prob=jnp.asarray(prob, dtype=jnp.float32),
        alias=jnp.asarray(alias, dtype=jnp.int32),
    )


def sample_negatives(
    table: AliasTable, key: jax.Array, shape: Tuple[int, ...]
) -> jax.Array:
    """Draw negative word indices with p ∝ counts^power, fully on-device, any shape.

    Two uniforms per draw: bucket u1·V, then keep-vs-alias on u2 < prob[bucket].

    NOTE: uses ``jax.random`` (threefry). Fine for one-off draws, but inside a
    training program threefry ops cost ~2 ms per call on TPU — the hot path must use
    :func:`sample_negatives_hash` instead (see ops/prng.py for the measurements).
    """
    k1, k2 = jax.random.split(key)
    V = table.vocab_size
    buckets = jax.random.randint(k1, shape, 0, V, dtype=jnp.int32)
    u = jax.random.uniform(k2, shape, dtype=jnp.float32)
    keep = u < table.prob[buckets]
    return jnp.where(keep, buckets, table.alias[buckets])


def sample_negatives_hash(
    prob: jax.Array,    # [V] or [V, 1] float32 — pass as a jit ARGUMENT, not a closure
    alias: jax.Array,   # [V] or [V, 1] int32 — same
    seed,
    counter: jax.Array,
    shape: Tuple[int, ...],
) -> jax.Array:
    """Hot-path sampler: same alias-method draw as :func:`sample_negatives`, but from
    the counter-based hash PRNG (ops/prng.py) — deterministic in (seed, counter) and
    ~55x faster inside a jitted training step than the threefry path.

    The tables must be passed into the enclosing jit as arguments: closure-captured
    constants degrade the whole program on TPU (measured 3.4M → 204M pairs/s by this
    change plus the PRNG swap; see bench.py).
    """
    from glint_word2vec_tpu.ops.prng import randint_mod, uniform01

    V = prob.shape[0]
    prob2 = prob.reshape(V, 1)    # free view; (V, 1) row gathers take the fast path
    alias2 = alias.reshape(V, 1)
    buckets = randint_mod(seed, 0, counter, shape, V)
    u = uniform01(seed, 1, counter, shape)
    flat = buckets.reshape(-1)
    keep = u < prob2[flat][:, 0].reshape(shape)
    return jnp.where(keep, buckets, alias2[flat][:, 0].reshape(shape))


def sampled_probabilities(counts: np.ndarray, power: float = 0.75) -> np.ndarray:
    """Exact target distribution, for tests: p(w) = counts^power / Σ counts^power."""
    w = np.power(np.asarray(counts, dtype=np.float64), power)
    return w / w.sum()


def build_unigram_table(counts: np.ndarray, table_size: int, power: float = 0.75) -> np.ndarray:
    """Classic word2vec quantized unigram table (the reference's G7 semantics,
    unigramTableSize entries, mllib:81,234-244): entry j holds the word whose cumulative
    counts^power mass covers j/table_size. Kept for parity testing only — the alias sampler
    is exact and O(vocab)."""
    p = sampled_probabilities(counts, power)
    cdf = np.cumsum(p)
    grid = (np.arange(table_size, dtype=np.float64) + 0.5) / table_size
    return np.searchsorted(cdf, grid).astype(np.int32)
