"""Fused Pallas TPU kernel for the shared-negative SGNS step.

This is the "native kernel" tier of the framework — the replacement for the reference's
server-side Scala compute (G3 ``dotprod`` + G4 ``adjust``, mllib:419-425) that the
BASELINE north star asks to lower to Pallas.

Why a kernel at all: profiling shows the XLA step is row-access bound — the embedding
row gathers and read-modify-write scatters of ~1.5 KB rows dominate, with the MXU nearly
idle. The kernel fuses the whole update into one pass over each row:

    HBM row ──DMA──▶ VMEM ──compute f, g, Δ──▶ updated row ──DMA──▶ same HBM row

so each touched row is read once and written once (the XLA lowering reads rows for the
gather, then reads them again inside the scatter's read-modify-write), with a ring of
``NBUF`` outstanding row DMAs to hide HBM latency, and the negative-pool math
(``f_neg = E_in @ Zᵀ``, ``ΔZ = g_negᵀ @ E_in``) on the MXU from VMEM.

Layout: Mosaic only allows DMA slices aligned to the (8, 128) f32 tiling, so single
embedding rows cannot be sliced out of a 2-D ``[Vp, D]`` array (dim 0 is a tiled
sublane dim). The kernel therefore views both matrices as ``[Vp, S, 128]`` with
``S = D // 128`` — dim 0 becomes an untiled "array" dim that can be indexed at row
granularity, and each row is an (S, 128) block. The 2-D↔3-D reshape is a free layout
no-op on TPU (measured ~0.07 ms for a 1.5 GB matrix — metadata only, not a copy).
Compute runs per 128-lane slab: ``f_neg = Σ_s E[:, s, :] @ Z[:, s, :]ᵀ`` keeps the
contractions on the MXU with K = 128 per pass.

**Measured verdict (round 3) — demoted to a reference tier.** On a v5e chip the kernel
runs ~7.6-8.2 ms/step at B=8192 across the whole tuning grid (tile ∈ {256, 512}, ring
depth ∈ {8, 32}; tile=1024 and ring=128 exceed Mosaic's scoped-memory budget), while the
XLA shared-pool step does the same work in ~1.9 ms (tools/sweep.py). Ring depth and tile
size changing nothing (±5%) means the bound is not DMA *latency* (more outstanding
copies would hide it) but per-copy *issue overhead* on the scalar core: ~0.25 µs per
async copy × 4 row copies per pair ≈ 1 µs/pair, an order of magnitude above XLA's
vectorized gather/scatter row cost (~60-90 ns/row). The read-once/write-once premise is
sound, but a row-at-a-time DMA loop cannot express it profitably on this hardware
generation — beating XLA here would need a bulk gather/scatter DMA primitive Mosaic
does not expose. The kernel stays as a correctness-proven reference (interpret-mode
equivalence tests vs the jnp step) and as the scaffold to revisit if such a primitive
lands; the production fast path is the XLA shared-pool step with bf16-stored embeddings
(see bench.py's frontier rows).

**Round-5 closure — the coalesced-DMA shape is priced out by measurement.** The
round-4 verdict asked for the one kernel shape the demotion had not falsified: a
pool-resident-VMEM, batch-tiled kernel applying sorted/coalesced segment updates
with double-buffered DMA. Every link of that design is now measured and each one
loses to the XLA emitter's ~27 ns/update-row (PERF.md §2):

- per-row HBM↔VMEM DMA issue: ~0.25 µs/row (round 3, this file) — coalescing
  duplicates only shrinks B to ~0.55·B unique rows under the production Zipf,
  nowhere near the 10× needed;
- the ONLY bulk-DMA escape, a contiguous hot-head block resident in VMEM (Zipf
  puts 63% of update rows in the top-2048 ids), dies on the tail: rows dropped
  OOB from the remaining scatter still cost full emitter time until the drop
  fraction is extreme (measured: 63% dropped = 0% faster — PERF.md §3 round-5
  probe, `tools/step_lean.py --probe-only`);
- and even with ALL data movement free, the per-row apply loop itself —
  scalar-core dynamic addressing into VMEM — measures **~95 ns/row** (best
  63 ns; `tools/pallas_vmem_scatter.py`), 2-3.5× the emitter. The emitter's
  27 ns/row is vectorized sorted-run application that Mosaic's exposed
  primitives (per-row dynamic slices, scalar fori_loop) cannot express.

So no Pallas shape beats the XLA scatter for this op on this hardware
generation, with measurements at every exit; BASELINE.md formally re-baselines
the MFU north star against the emitter ceiling (headline at 71% of it).

Concurrency semantics: grid tiles execute sequentially on a TensorCore, so cross-tile
duplicate rows are consistent. *Within* a tile, duplicate rows are gathered before either
update is applied and written back last-wins — i.e. one of the duplicate updates is
dropped. This is strictly tamer than the reference's accepted cross-worker Hogwild races
(README.md:17-19, "Use a small number [of partitions] for accuracy"); the jnp paths
(:func:`..sgns.sgns_step_shared`) remain the exact-accumulation reference implementation
and the default. Padded rows (mask == 0) are skipped at writeback so they cannot alias
row 0 (see the masked-writeback predicate below).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from glint_word2vec_tpu.ops.sgns import MAX_EXP, EmbeddingPair, StepMetrics

NBUF = 8  # outstanding row-DMA ring depth per stream


def _sigmoid(f, mode: str):
    if mode == "clipped":
        return jnp.where(f > MAX_EXP, 1.0,
                         jnp.where(f < -MAX_EXP, 0.0, jax.nn.sigmoid(f)))
    return jax.nn.sigmoid(f)


def _dot(a, b, dims):
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)


def _sgns_tile_kernel(
    # scalar prefetch
    centers_ref,      # SMEM [B] int32
    contexts_ref,     # SMEM [B] int32
    # inputs
    alpha_ref,        # SMEM (1, 1) f32
    ctx_ref,          # VMEM (T, 1) int32 — this tile's context ids (for collision mask)
    mask_ref,         # VMEM (T, 1) f32
    negs_ref,         # VMEM (1, P) int32
    z_ref,            # VMEM (P, S, 128) f32 — gathered negative-pool rows
    syn0_ref,         # ANY  [Vp, S, 128] f32 (aliased with syn0_out)
    syn1_ref,         # ANY  [Vp, S, 128] f32 (aliased with syn1_out)
    # outputs
    syn0_out,         # ANY  [Vp, S, 128]
    syn1_out,         # ANY  [Vp, S, 128]
    dz_out,           # VMEM (P, S, 128) f32 — negative-pool delta, applied by the host
    fpos_out,         # VMEM (T, 1) f32
    nloss_out,        # VMEM (1, 1) f32 — accumulated negative-term loss sum
    # scratch
    ein,              # VMEM (T, S, 128) f32
    epos,             # VMEM (T, S, 128) f32
    gsem0,            # DMA sems (NBUF,)
    gsem1,
    wsem0,
    wsem1,
    *,
    tile: int,
    neg_ratio: float,
    sigmoid_mode: str,
    nbuf: int = NBUF,
):
    t = pl.program_id(0)
    base = t * tile
    S = ein.shape[1]

    def g0(i):
        return pltpu.make_async_copy(
            syn0_ref.at[centers_ref[base + i]], ein.at[i], gsem0.at[i % nbuf])

    def g1(i):
        return pltpu.make_async_copy(
            syn1_ref.at[contexts_ref[base + i]], epos.at[i], gsem1.at[i % nbuf])

    # ---- gather phase: ring of nbuf outstanding row copies per stream ----
    for w in range(nbuf):
        g0(w).start()
        g1(w).start()

    def gather_body(i, _):
        g0(i).wait()
        g1(i).wait()

        @pl.when(i + nbuf < tile)
        def _():
            g0(i + nbuf).start()
            g1(i + nbuf).start()

        return ()

    jax.lax.fori_loop(0, tile, gather_body, (), unroll=False)

    # ---- compute phase (VPU + MXU, all in VMEM, per 128-lane slab) ----
    e = ein[...]                                             # (T, S, 128)
    p = epos[...]
    z = z_ref[...]                                           # (P, S, 128)
    alpha = alpha_ref[0, 0]
    mask = mask_ref[...]                                     # (T, 1)

    f_pos = jnp.zeros((tile, 1), jnp.float32)
    f_neg = jnp.zeros((tile, z.shape[0]), jnp.float32)
    for s in range(S):
        f_pos += jnp.sum(e[:, s, :] * p[:, s, :], axis=1, keepdims=True)
        f_neg += _dot(e[:, s, :], z[:, s, :], ((1,), (1,)))  # (T, P) MXU

    neg_valid = (ctx_ref[...] != negs_ref[...]).astype(jnp.float32) * mask

    g_pos = (1.0 - _sigmoid(f_pos, sigmoid_mode)) * alpha * mask
    g_neg = (0.0 - _sigmoid(f_neg, sigmoid_mode)) * alpha * neg_valid * neg_ratio

    @pl.when(t == 0)
    def _():
        dz_out[...] = jnp.zeros_like(dz_out)
        nloss_out[...] = jnp.zeros_like(nloss_out)

    fpos_out[...] = f_pos
    # −Σ log σ(−f_neg) over valid entries, reweighted like the gradient
    nloss_out[...] += jnp.sum(
        jax.nn.softplus(f_neg) * neg_valid).reshape(1, 1) * neg_ratio

    for s in range(S):
        es, ps, zs = e[:, s, :], p[:, s, :], z[:, s, :]
        ein[:, s, :] = es + g_pos * ps + _dot(g_neg, zs, ((1,), (0,)))   # MXU
        epos[:, s, :] = ps + g_pos * es
        dz_out[:, s, :] += _dot(g_neg, es, ((0,), (0,)))                 # (P, 128) MXU

    # ---- writeback phase: same ring, rows go back to their HBM slots ----
    # Padded rows (mask == 0) are skipped entirely: their centers/contexts are 0, so an
    # unconditional writeback would alias vocab row 0 and could overwrite (last-wins) a
    # real row-0 update made earlier in the same tile. Start and wait share the per-row
    # predicate, so every started DMA is waited exactly once.
    def live(i):
        return mask_ref[i, 0] != 0.0

    def w0(i):
        return pltpu.make_async_copy(
            ein.at[i], syn0_out.at[centers_ref[base + i]], wsem0.at[i % nbuf])

    def w1(i):
        return pltpu.make_async_copy(
            epos.at[i], syn1_out.at[contexts_ref[base + i]], wsem1.at[i % nbuf])

    for w in range(nbuf):
        @pl.when(live(w))
        def _(w=w):
            w0(w).start()
            w1(w).start()

    def write_body(i, _):
        @pl.when(live(i))
        def _():
            w0(i).wait()
            w1(i).wait()

        # clamp the lookahead index so the mask read stays in bounds; the outer
        # predicate makes the clamped duplicate read irrelevant
        nxt = jnp.minimum(i + nbuf, tile - 1)

        @pl.when((i + nbuf < tile) & live(nxt))
        def _():
            w0(nxt).start()
            w1(nxt).start()

        return ()

    # all writes complete before this tile ends: the next tile may read these rows
    jax.lax.fori_loop(0, tile, write_body, (), unroll=False)


def fused_sgns_shared(
    syn0: jax.Array,       # [Vp, D] f32, D a multiple of 128
    syn1: jax.Array,
    centers: jax.Array,    # [B] int32
    contexts: jax.Array,   # [B] int32
    mask: jax.Array,       # [B] f32
    negatives: jax.Array,  # [P] int32
    z: jax.Array,          # [P, D] f32 — syn1 rows of the pool (gathered by caller)
    alpha: jax.Array,      # scalar f32
    num_negatives: int,
    sigmoid_mode: str = "exact",
    tile: int = 512,
    nbuf: int = NBUF,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Run the fused kernel. Returns (syn0', syn1', dZ, f_pos, neg_loss_sum);
    the caller applies ``syn1'.at[negatives].add(dZ)``."""
    B = centers.shape[0]
    Vp, D = syn0.shape
    P = z.shape[0]
    if B % tile:
        raise ValueError(f"batch {B} not divisible by tile {tile}")
    if tile < nbuf:
        raise ValueError(f"tile {tile} smaller than the DMA ring depth {nbuf}")
    if D % 128:
        raise ValueError(
            f"vector dim {D} must be a multiple of 128 for the fused kernel "
            "(enable pad_vector_to_lanes)")
    S = D // 128
    num_tiles = B // tile
    neg_ratio = float(num_negatives) / float(P)

    # free layout view: row r becomes the (S, 128) block at untiled dim-0 index r,
    # which is the granularity Mosaic DMAs can address
    syn0v = syn0.reshape(Vp, S, 128)
    syn1v = syn1.reshape(Vp, S, 128)
    zv = z.reshape(P, S, 128)

    kernel = functools.partial(
        _sgns_tile_kernel, tile=tile, neg_ratio=neg_ratio, sigmoid_mode=sigmoid_mode,
        nbuf=nbuf)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(num_tiles,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i, *_: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((tile, 1), lambda i, *_: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i, *_: (i, 0)),
            pl.BlockSpec((1, P), lambda i, *_: (0, 0)),
            pl.BlockSpec((P, S, 128), lambda i, *_: (0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((P, S, 128), lambda i, *_: (0, 0, 0)),
            pl.BlockSpec((tile, 1), lambda i, *_: (i, 0)),
            pl.BlockSpec((1, 1), lambda i, *_: (0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((tile, S, 128), jnp.float32),
            pltpu.VMEM((tile, S, 128), jnp.float32),
            pltpu.SemaphoreType.DMA((nbuf,)),
            pltpu.SemaphoreType.DMA((nbuf,)),
            pltpu.SemaphoreType.DMA((nbuf,)),
            pltpu.SemaphoreType.DMA((nbuf,)),
        ],
    )

    out_shape = [
        jax.ShapeDtypeStruct((Vp, S, 128), jnp.float32),   # syn0'
        jax.ShapeDtypeStruct((Vp, S, 128), jnp.float32),   # syn1'
        jax.ShapeDtypeStruct((P, S, 128), jnp.float32),    # dZ
        jax.ShapeDtypeStruct((B, 1), jnp.float32),         # f_pos
        jax.ShapeDtypeStruct((1, 1), jnp.float32),         # neg loss sum
    ]

    new_syn0, new_syn1, dz, f_pos, nloss = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        # operand indices include the 2 scalar-prefetch args:
        # 2=alpha 3=ctx 4=mask 5=negs 6=z 7=syn0 8=syn1
        input_output_aliases={7: 0, 8: 1},
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(
        centers, contexts,
        alpha.reshape(1, 1).astype(jnp.float32),
        contexts.reshape(num_tiles * tile, 1),
        mask.reshape(-1, 1),
        negatives.reshape(1, P),
        zv,
        syn0v, syn1v,
    )
    return (new_syn0.reshape(Vp, D), new_syn1.reshape(Vp, D),
            dz.reshape(P, D), f_pos, nloss)


def make_pallas_sgns_step(
    num_negatives: int,
    negative_pool: int,
    sigmoid_mode: str = "exact",
    compute_dtype=jnp.float32,
    tile: int = 512,
    nbuf: int = NBUF,
    interpret: bool = False,
):
    """Trainer-facing factory: returns ``inner(params, batch, negatives, alpha)`` with
    the same contract as the jnp step cores (the Pallas analog of
    :func:`..sgns.sgns_step_shared_core`); the trainer pre-draws the shared pool."""
    del compute_dtype  # kernel is float32; bf16 variant is future work
    del negative_pool  # pool size is read off the pre-drawn negatives

    def inner(params: EmbeddingPair, batch, negatives, alpha):
        syn0, syn1 = params
        centers = batch["centers"]
        contexts = batch["contexts"]
        mask = batch["mask"]
        # shrink the tile to the batch when the batch is smaller (tests, toy
        # corpora); larger batches must divide the tile — one giant tile would
        # blow the VMEM scratch budget
        B = centers.shape[0]
        if B % tile == 0:
            t = tile
        elif B < tile:
            t = B
        else:
            raise ValueError(
                f"pairs_per_batch {B} must be a multiple of the kernel tile "
                f"{tile} (or smaller than it) for use_pallas=True")
        z = syn1[negatives]
        new_syn0, new_syn1, dz, f_pos, nloss = fused_sgns_shared(
            syn0, syn1, centers, contexts, mask, negatives, z, alpha,
            num_negatives, sigmoid_mode, tile=t, nbuf=min(nbuf, t),
            interpret=interpret)
        new_syn1 = new_syn1.at[negatives].add(dz.astype(new_syn1.dtype))

        f_pos = f_pos[:, 0]
        denom = jnp.maximum(mask.sum(), 1.0)
        loss = ((jax.nn.softplus(-f_pos) * mask).sum() + nloss[0, 0]) / denom
        metrics = StepMetrics(
            loss=loss,
            mean_f_pos=(f_pos * mask).sum() / denom,
            pairs=mask.sum(),
        )
        return EmbeddingPair(new_syn0, new_syn1), metrics

    return inner
