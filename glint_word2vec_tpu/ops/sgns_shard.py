"""Explicit shard_map lowering of the shared-pool SGNS step (docs/sharding.md).

The GSPMD path (:func:`.sgns.sgns_step_shared_core` under jit +
``with_sharding_constraint``) leaves the sharded step's collective schedule to
the compiler pass (Xu et al., "GSPMD", 2021); its collective profile at the
production geometry was never inspected — every multi-chip number in PERF.md §7
was a formula estimate. This module is the hand-lowered replacement, the TPU
analog of the reference's CIKM'16 discipline (Ordentlich et al.: ship indices
and scalar coefficients, keep embedding-row traffic off the wire):

Per step, on the (data, model) mesh with rows sharded over ``model``
(each shard owns ``Vs = V/num_model`` contiguous rows) and the batch split
over ``data`` (``Bl = B/num_data`` pairs per shard):

1. **Forward assembly — ONE psum over the model axis.** Each model shard
   gathers the rows it owns (``index − row_offset``, OOB rows masked to zero)
   for this data shard's centers, contexts, and the shared pool, concatenated
   into one ``[2·Bl + P, D]`` block; a single ``psum`` over ``model``
   assembles the full rows (every row has exactly one owner, so the psum adds
   exact zeros). This is the only model-axis collective in the step.
2. **Local logit/coefficient chain.** f_pos/f_neg/g_pos/g_neg and the update
   deltas d_in/d_pos/d_Z run per data shard on the assembled rows — op-for-op
   the shared helpers of :mod:`.sgns`, so the two lowerings cannot drift.
3. **Data-axis payload exchange — ONE all_gather over the data axis.** The
   per-shard update payload (``[2·Bl + P, D]`` deltas, already cast to the
   param dtype, plus the int32 index list) is all-gathered over ``data``:
   bytes scale with the BATCH (2·Bl·D·b per shard), not with V/num_model —
   the dense alternative (scatter into a [Vs, D] zero delta, psum_scatter by
   row ownership, all_gather the applied sub-blocks back) moves
   ~2·Vs·D·b and loses whenever V/num_model > ~2·B/num_data, which includes
   every north-star geometry (V=1M B=64k: 98 MB vs 50 MB per shard at 2×4);
   it is recorded here as considered-and-priced-out, not built.
4. **Owner-local scatters only.** Every shard localizes the gathered index
   list (``index − row_offset``; rows it does not own become an out-of-range
   sentinel and are DROPPED by the scatter), then applies ONE scatter-add per
   matrix. ZERO update bytes cross the model axis — vs the ~4·B·D·b
   round-trip PERF.md §7 priced for the default lowering — and each shard's
   applied update rows are only those targeting its ``Vs`` rows, so the
   per-update-row scatter bound (PERF.md §2, ~27 ns/row) divides by
   ``num_model`` (dropped candidates ride the §3-measured cheap regime:
   at num_model ≥ 8 the drop fraction ≥ 87.5% is past the 81% knee).

Metrics (when not elided) are per-shard scalars psum'd over ``data`` — three
floats, not a collective that shows up in a bytes audit.

The schedule is audited, not asserted: ``tools/collectives.py`` compiles both
lowerings and tabulates every collective in the HLO with its mesh axis and
bytes; ``tools/shard_ab.py`` A/Bs step time and numeric agreement across mesh
shapes. Equivalence: f64 ~1e-12 against both the GSPMD lowering and the
single-device step at every 8-device mesh shape (tests/test_shard_map_step.py).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from glint_word2vec_tpu.ops.sgns import (
    EmbeddingPair, StepMetrics, Stabilizers, clip_update_rows,
    shared_pool_coeffs, shared_pool_loss_terms, stabilize_rows)
from glint_word2vec_tpu.parallel.distributed import local_sgd_delta_merge
from glint_word2vec_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def _owned_rows(mat: jax.Array, idx: jax.Array, row_offset: jax.Array) -> jax.Array:
    """Gather ``mat[idx]`` restricted to this shard's rows: local index =
    ``idx − row_offset``, out-of-range rows exactly zero (so the model-axis
    psum of all shards' partials reconstructs each row bit-exactly — one
    owner contributes the row, the rest contribute 0.0, and x + 0.0 == x)."""
    vs = mat.shape[0]
    loc = idx - row_offset
    inb = (loc >= 0) & (loc < vs)
    rows = mat[jnp.where(inb, loc, 0)]
    return jnp.where(inb[..., None], rows, jnp.zeros((), mat.dtype))


def _owner_local_scatter_add(
    mat: jax.Array, idx: jax.Array, upd: jax.Array, row_offset: jax.Array,
) -> jax.Array:
    """``mat.at[idx].add(upd)`` applying ONLY rows this shard owns: non-owned
    indices map to the out-of-range sentinel ``Vs`` and are dropped by the
    scatter (mode="drop") — zero collective traffic, ~1/num_model of the
    update rows actually applied per shard."""
    vs = mat.shape[0]
    loc = idx - row_offset
    loc = jnp.where((loc >= 0) & (loc < vs), loc, vs)
    return mat.at[loc].add(upd, mode="drop")


def make_shard_map_sgns_step(
    mesh: Mesh,
    num_negatives: int,
    sigmoid_mode: str = "exact",
    compute_dtype: jnp.dtype = jnp.float32,
    logits_dtype: jnp.dtype = jnp.float32,
    with_metrics: bool = True,
    stabilizers: Optional[Stabilizers] = None,
    fused: bool = False,
    bf16_chain: bool = False,
    sync_every: int = 1,
) -> Callable[..., Tuple[EmbeddingPair, StepMetrics]]:
    """Build the explicitly-scheduled sharded step. The returned function has
    the trainer's ``inner`` signature — ``(params, batch, negatives, alpha) ->
    (EmbeddingPair, StepMetrics)`` on GLOBAL arrays — so
    ``trainer._build_step`` swaps it in for :func:`.sgns.sgns_step_shared_core`
    behind ``config.step_lowering`` with no other plumbing.

    Requirements (validated at trace time with real messages): the padded
    vocab divides ``num_model`` (pad_vocab_for_sharding guarantees it) and the
    batch divides ``num_data``. ``duplicate_scaling`` has no shard_map form
    (global in-batch occurrence counts would need a [V]-sized psum) — the
    config selection matrix refuses the combination up front.

    ``fused``/``bf16_chain`` (config.fused_logits / config.bf16_chain —
    ISSUE 14): the coefficient chain lives in the shared
    :func:`..sgns.shared_pool_coeffs` helper, so the fused select chain and
    the f32-accumulating positive dot apply to this lowering by
    construction — the two lowerings cannot drift. The per-data-shard
    [Bl, P] chain shrinks exactly like the single-program [B, P] one; the
    collective schedule is untouched (the fusion is local elementwise
    restructuring, no new cross-shard values). ``hot_rows`` has NO shard_map
    form and is refused at config construction: the hot slab covers the
    global index prefix [0, K), which under the rows layout lives entirely
    on model shard 0 — accumulating it owner-locally would serialize every
    hot update onto one shard, the exact imbalance the owner-local schedule
    exists to avoid (docs/sharding.md records the refusal contract).

    ``sync_every`` (config.sync_every — local-SGD, docs/sharding.md
    §Local-SGD): 1 (default) returns the synchronous step above, byte-for-byte
    the pre-knob program. k > 1 returns a WINDOW function with the same outer
    signature over k-stacked inputs — ``batch`` leaves ``[k, B]``,
    ``negatives [k, nd·P]`` (each data shard consumes its own DISJOINT
    ``[k, P]`` pool slice, so merged runs are deterministic per
    (seed, mesh, k)), ``alpha [k]`` — that runs k OWNER-LOCAL steps per data
    shard (forward assembly psum over ``model`` per step as above, but the
    backward applies ONLY this shard's own payload: zero bytes cross the data
    axis inside the window) and then reconciles the data axis with ONE
    delta-merge collective (:func:`..parallel.distributed.local_sgd_delta_merge`:
    mean of per-shard deltas against the window-start state). Metrics come
    back as ``[k]`` vectors (per-step, data-psum'd once per window). The
    window's k-step loop is PYTHON-UNROLLED, not a lax.scan — deliberately:
    the HLO collective audit (tools/collectives.py) counts ops textually and
    a scan body would hide k−1 of the per-step assembly psums, making the
    priced schedule a lie. In-window stabilizer passes run owner-locally on
    the LOCAL touched mask (no mask all_gather); the merge preserves the
    clamp invariant (a convex combination of rows each with ‖row‖ ≤ c stays
    in the ball).
    """
    nd = mesh.shape[DATA_AXIS]
    nm = mesh.shape[MODEL_AXIS]

    def local_step(syn0, syn1, centers, contexts, mask, negatives, alpha):
        # per-device blocks: syn0/syn1 [Vs, D]; centers/contexts/mask [Bl];
        # negatives [P] and alpha replicated.
        #
        # SERIALIZATION PROPERTY (learned from a live rendezvous-starvation
        # deadlock on the 8-device CPU mesh — trainer._sync_collectives has
        # the full story): every collective in this program should data-
        # depend on the params carry. The index all_gather and the elided
        # twin's `pairs` psum otherwise depend only on the FEED, so a run
        # dispatched behind another collective-bearing program could start
        # those collectives early and race it on XLA:CPU's shared rendezvous
        # pool. The barrier ties the batch inputs to syn0/syn1 so every
        # collective waits for the carry; params are program inputs, so
        # within-program TPU/GPU stream scheduling is untouched.
        centers, contexts, mask, negatives, syn0, syn1 = (
            jax.lax.optimization_barrier(
                (centers, contexts, mask, negatives, syn0, syn1)))
        vs = syn0.shape[0]
        bl = centers.shape[0]
        pool = negatives.shape[0]
        row_offset = (jax.lax.axis_index(MODEL_AXIS) * vs).astype(jnp.int32)

        # (1) forward assembly: owner-local gathers, ONE psum over `model`
        cat = jnp.concatenate([
            _owned_rows(syn0, centers, row_offset),
            _owned_rows(syn1, contexts, row_offset),
            _owned_rows(syn1, negatives, row_offset),
        ], axis=0)                                   # [2·Bl + P, D] param dtype
        if nm > 1:
            cat = jax.lax.psum(cat, MODEL_AXIS)
        e_in = cat[:bl].astype(compute_dtype)
        e_pos = cat[bl:2 * bl].astype(compute_dtype)
        Z = cat[2 * bl:].astype(compute_dtype)

        # (2) the shared coefficient/update math — literally the same helpers
        # the GSPMD step runs (ops/sgns.py), per data shard
        f_pos, f_neg, neg_valid, g_pos, g_neg = shared_pool_coeffs(
            e_in, e_pos, Z, contexts, negatives, mask, alpha,
            num_negatives, sigmoid_mode, logits_dtype,
            fused=fused, bf16_chain=bf16_chain)
        gn = g_neg.astype(compute_dtype)
        d_in = g_pos[:, None].astype(compute_dtype) * e_pos + gn @ Z
        d_pos = g_pos[:, None].astype(compute_dtype) * e_in
        d_Z = gn.T @ e_in                            # [P, D] partial over Bl pairs
        if stabilizers is not None and stabilizers.update_clip:
            # the per-pair rows only, never the (shard-partial) d_Z — the
            # exact scoping the single-program lowering applies (ops/sgns.py
            # Stabilizers docstring), so the lowerings cannot drift
            d_in = clip_update_rows(d_in, stabilizers.update_clip)
            d_pos = clip_update_rows(d_pos, stabilizers.update_clip)

        # (3) data-axis payload exchange: deltas in param dtype + int32 indices,
        # ONE all_gather each (the index list is 4 bytes/row — noise next to
        # the D·b-byte delta rows). nd == 1 skips the collective entirely.
        dtype = syn0.dtype
        payload = jnp.concatenate(
            [d_in, d_pos, d_Z], axis=0).astype(dtype)  # [2·Bl + P, D]
        idx = jnp.concatenate([centers, contexts, negatives])
        if nd > 1:
            payload = jax.lax.all_gather(payload, DATA_AXIS, tiled=True)
            idx = jax.lax.all_gather(idx, DATA_AXIS, tiled=True)
        # split back into per-matrix streams: every data shard's first Bl rows
        # target syn0 (centers), the rest target syn1 (contexts + pool; the
        # nd pool copies are partial d_Z sums — scatter-add accumulates them)
        seg = payload.reshape(nd, 2 * bl + pool, -1)
        seg_idx = idx.reshape(nd, 2 * bl + pool)
        upd0 = seg[:, :bl].reshape(nd * bl, -1)
        idx0 = seg_idx[:, :bl].reshape(-1)
        upd1 = seg[:, bl:].reshape(nd * (bl + pool), -1)
        idx1 = seg_idx[:, bl:].reshape(-1)

        # (4) owner-local scatters — ZERO update bytes cross the model axis
        new_syn0 = _owner_local_scatter_add(syn0, idx0, upd0, row_offset)
        new_syn1 = _owner_local_scatter_add(syn1, idx1, upd1, row_offset)

        # (4b) owner-local touched-row stabilizer pass (config.max_row_norm /
        # row_l2): the rows layout owns FULL rows per shard, so the clamp's
        # norm math runs locally on the just-updated block — the same
        # gathered index lists drive it, with masked batch slots mapped to a
        # global OOB sentinel (their placeholder index 0 must not drag row 0
        # into the pass) and non-owned/sentinel rows dropping at the scatter-
        # set exactly like the update scatter. One extra [B]-float all_gather
        # of the mask funds the gating — only compiled in when a stabilizer
        # is ON, so the stabilizers-off program is untouched.
        if stabilizers is not None and stabilizers.post_pass:
            gmask = mask
            if nd > 1:
                gmask = jax.lax.all_gather(gmask, DATA_AXIS, tiled=True)
            enable = (gmask.sum() > 0).astype(jnp.float32)
            sent = jnp.int32(vs * nm)                # global OOB sentinel
            stab0 = jnp.where(gmask > 0, idx0, sent)  # [nd·bl] centers
            gm = gmask.reshape(nd, bl)
            m1 = jnp.concatenate(
                [gm, jnp.ones((nd, pool), jnp.float32)], axis=1).reshape(-1)
            stab1 = jnp.where(m1 > 0, idx1, sent)

            def loc(i):
                li = i - row_offset
                return jnp.where((li >= 0) & (li < vs), li, vs)

            new_syn0 = stabilize_rows(
                new_syn0, loc(stab0), alpha, stabilizers, enable)
            new_syn1 = stabilize_rows(
                new_syn1, loc(stab1), alpha, stabilizers, enable)

        # metrics: three scalars psum'd over `data` (loss/mean_f_pos follow
        # the GSPMD step's masked-mean: global numerators / global pair count)
        if with_metrics:
            loss_num, fpos_num = shared_pool_loss_terms(
                f_pos, f_neg, neg_valid, mask, num_negatives)
            stats = jnp.stack([loss_num, fpos_num, mask.sum()])
            if nd > 1:
                stats = jax.lax.psum(stats, DATA_AXIS)
            denom = jnp.maximum(stats[2], 1.0)
            loss, mean_f_pos, pairs = stats[0] / denom, stats[1] / denom, stats[2]
        else:
            pairs = mask.sum()
            if nd > 1:
                pairs = jax.lax.psum(pairs, DATA_AXIS)
            loss = mean_f_pos = jnp.float32(0.0)
        return new_syn0, new_syn1, loss, mean_f_pos, pairs

    mapped = shard_map(
        local_step, mesh=mesh,
        in_specs=(P(MODEL_AXIS, None), P(MODEL_AXIS, None),
                  P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(), P()),
        out_specs=(P(MODEL_AXIS, None), P(MODEL_AXIS, None), P(), P(), P()),
        # outputs ARE replicated where the specs say so (every data replica
        # applies the identical all-gathered payload to the identical block;
        # scalars ride a psum) — but the tracer cannot prove it through the
        # scatters, so replication checking is off
        check_rep=False)

    def step(params, batch, negatives, alpha):
        syn0, syn1 = params
        v, b = syn0.shape[0], batch["centers"].shape[0]
        if v % nm:
            raise ValueError(
                f"shard_map step needs the padded vocab ({v}) divisible by "
                f"num_model={nm} (pad_vocab_for_sharding guarantees this in "
                "the trainer)")
        if b % nd:
            raise ValueError(
                f"shard_map step needs the batch ({b}) divisible by "
                f"num_data={nd}")
        s0, s1, loss, mean_f_pos, pairs = mapped(
            syn0, syn1, batch["centers"], batch["contexts"], batch["mask"],
            negatives, alpha)
        return EmbeddingPair(s0, s1), StepMetrics(
            loss=loss, mean_f_pos=mean_f_pos, pairs=pairs)

    if sync_every == 1:
        return step

    # ---- local-SGD window (sync_every = k > 1): k owner-local steps per
    # data shard, then ONE delta-merge collective over the data axis ----
    if sync_every < 1:
        raise ValueError(f"sync_every must be >= 1, got {sync_every}")
    k = int(sync_every)

    def owner_local_step(syn0, syn1, centers, contexts, mask, negatives,
                         alpha, row_offset):
        """One step of the in-window schedule on THIS shard's diverged
        replica: same forward assembly (steps 1–2 of the module schedule, the
        one model-axis psum included) but the backward applies only the
        shard's OWN payload — no data-axis all_gather, so the window crosses
        the data axis zero times until the merge. ``negatives`` is this
        shard's disjoint [P] pool slice; its d_Z rows therefore accumulate
        only this shard's partials (exactly what the per-shard oracle
        replays). Returns the updated blocks + the [3] local stat numerators
        (summed over `data` once per window, not per step)."""
        vs = syn0.shape[0]
        bl = centers.shape[0]
        pool = negatives.shape[0]

        cat = jnp.concatenate([
            _owned_rows(syn0, centers, row_offset),
            _owned_rows(syn1, contexts, row_offset),
            _owned_rows(syn1, negatives, row_offset),
        ], axis=0)                                   # [2·Bl + P, D] param dtype
        if nm > 1:
            cat = jax.lax.psum(cat, MODEL_AXIS)
        e_in = cat[:bl].astype(compute_dtype)
        e_pos = cat[bl:2 * bl].astype(compute_dtype)
        Z = cat[2 * bl:].astype(compute_dtype)

        f_pos, f_neg, neg_valid, g_pos, g_neg = shared_pool_coeffs(
            e_in, e_pos, Z, contexts, negatives, mask, alpha,
            num_negatives, sigmoid_mode, logits_dtype,
            fused=fused, bf16_chain=bf16_chain)
        gn = g_neg.astype(compute_dtype)
        d_in = g_pos[:, None].astype(compute_dtype) * e_pos + gn @ Z
        d_pos = g_pos[:, None].astype(compute_dtype) * e_in
        d_Z = gn.T @ e_in
        if stabilizers is not None and stabilizers.update_clip:
            d_in = clip_update_rows(d_in, stabilizers.update_clip)
            d_pos = clip_update_rows(d_pos, stabilizers.update_clip)

        dtype = syn0.dtype
        idx0 = centers
        upd0 = d_in.astype(dtype)
        idx1 = jnp.concatenate([contexts, negatives])
        upd1 = jnp.concatenate([d_pos, d_Z], axis=0).astype(dtype)
        new_syn0 = _owner_local_scatter_add(syn0, idx0, upd0, row_offset)
        new_syn1 = _owner_local_scatter_add(syn1, idx1, upd1, row_offset)

        if stabilizers is not None and stabilizers.post_pass:
            # owner-local in-window form: the LOCAL touched mask gates the
            # pass (no data-axis mask all_gather — the window's whole point);
            # each shard clamps the rows IT touched, and the merge preserves
            # the clamp ball (convexity — see local_sgd_delta_merge)
            enable = (mask.sum() > 0).astype(jnp.float32)
            sent = jnp.int32(vs * nm)
            stab0 = jnp.where(mask > 0, idx0, sent)
            m1 = jnp.concatenate([mask, jnp.ones((pool,), jnp.float32)])
            stab1 = jnp.where(m1 > 0, idx1, sent)

            def loc(i):
                li = i - row_offset
                return jnp.where((li >= 0) & (li < vs), li, vs)

            new_syn0 = stabilize_rows(
                new_syn0, loc(stab0), alpha, stabilizers, enable)
            new_syn1 = stabilize_rows(
                new_syn1, loc(stab1), alpha, stabilizers, enable)

        if with_metrics:
            loss_num, fpos_num = shared_pool_loss_terms(
                f_pos, f_neg, neg_valid, mask, num_negatives)
            stats = jnp.stack([loss_num, fpos_num, mask.sum()])
        else:
            stats = jnp.stack(
                [jnp.float32(0.0), jnp.float32(0.0), mask.sum()])
        return new_syn0, new_syn1, stats

    def local_window(syn0, syn1, centers, contexts, mask, negatives, alphas):
        # per-device blocks: syn0/syn1 [Vs, D]; centers/contexts/mask
        # [k, Bl]; negatives [k, P] (this shard's disjoint lattice); alphas
        # [k] replicated. Same serialization barrier as the k=1 step: every
        # collective in the window (the per-step assembly psums, the merge
        # psum, the stats psum) must data-depend on the params carry.
        centers, contexts, mask, negatives, syn0, syn1 = (
            jax.lax.optimization_barrier(
                (centers, contexts, mask, negatives, syn0, syn1)))
        vs = syn0.shape[0]
        row_offset = (jax.lax.axis_index(MODEL_AXIS) * vs).astype(jnp.int32)
        start0, start1 = syn0, syn1
        stats_steps = []
        # Python-unrolled on purpose (see make_shard_map_sgns_step docstring):
        # the HLO bytes audit must see all k assembly psums
        for i in range(k):
            syn0, syn1, st = owner_local_step(
                syn0, syn1, centers[i], contexts[i], mask[i], negatives[i],
                alphas[i], row_offset)
            stats_steps.append(st)

        # the ONE data-axis collective of the window
        merged0, merged1 = local_sgd_delta_merge(
            (start0, start1), (syn0, syn1), DATA_AXIS, nd)

        stats = jnp.stack(stats_steps)               # [k, 3]
        if nd > 1:
            stats = jax.lax.psum(stats, DATA_AXIS)
        pairs = stats[:, 2]
        if with_metrics:
            denom = jnp.maximum(pairs, 1.0)
            loss, mean_f_pos = stats[:, 0] / denom, stats[:, 1] / denom
        else:
            loss = mean_f_pos = jnp.zeros((k,), jnp.float32)
        return merged0, merged1, loss, mean_f_pos, pairs

    mapped_window = shard_map(
        local_window, mesh=mesh,
        in_specs=(P(MODEL_AXIS, None), P(MODEL_AXIS, None),
                  P(None, DATA_AXIS), P(None, DATA_AXIS), P(None, DATA_AXIS),
                  P(None, DATA_AXIS), P()),
        out_specs=(P(MODEL_AXIS, None), P(MODEL_AXIS, None), P(), P(), P()),
        # replication holds BY the merge (bitwise-identical psum result +
        # replicated start on every data replica), but the tracer cannot
        # prove it through the scatters — same waiver as the k=1 step
        check_rep=False)

    def window(params, batch, negatives, alphas):
        syn0, syn1 = params
        v, b = syn0.shape[0], batch["centers"].shape[1]
        if v % nm:
            raise ValueError(
                f"shard_map window needs the padded vocab ({v}) divisible "
                f"by num_model={nm} (pad_vocab_for_sharding guarantees this "
                "in the trainer)")
        if b % nd:
            raise ValueError(
                f"shard_map window needs the batch ({b}) divisible by "
                f"num_data={nd}")
        if batch["centers"].shape[0] != k:
            raise ValueError(
                f"sync_every={k} window needs [k, B]-stacked batch leaves, "
                f"got leading dim {batch['centers'].shape[0]}")
        if negatives.shape[1] % nd:
            raise ValueError(
                f"sync_every={k} window needs the pool axis "
                f"({negatives.shape[1]}) divisible by num_data={nd} (each "
                f"data shard consumes a disjoint slice)")
        s0, s1, loss, mean_f_pos, pairs = mapped_window(
            syn0, syn1, batch["centers"], batch["contexts"], batch["mask"],
            negatives, alphas)
        return EmbeddingPair(s0, s1), StepMetrics(
            loss=loss, mean_f_pos=mean_f_pos, pairs=pairs)

    return window
