from glint_word2vec_tpu.parallel.mesh import (
    MeshPlan,
    make_mesh,
    embedding_sharding,
    batch_sharding,
    replicated_sharding,
    shard_params,
    shard_batch,
    pad_vocab_for_sharding,
)

__all__ = [
    "MeshPlan",
    "make_mesh",
    "embedding_sharding",
    "batch_sharding",
    "replicated_sharding",
    "shard_params",
    "shard_batch",
    "pad_vocab_for_sharding",
]
