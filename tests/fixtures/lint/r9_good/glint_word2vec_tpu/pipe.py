"""R9 good twin: registered factory constructions only, and the single
nesting acquires in strictly increasing rank order (outer=10 -> inner=20)."""
from glint_word2vec_tpu.lockcheck import make_lock


class Pipe:
    def __init__(self):
        self._outer = make_lock("outer")
        self._inner = make_lock("inner")

    def forward(self):
        with self._outer:
            with self._inner:
                pass
