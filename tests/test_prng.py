"""Tests for the counter-based hash PRNG (ops/prng.py) and the hot-path negative
sampler built on it (ops/sampler.sample_negatives_hash) — the source of every
production training negative, so its distribution and determinism are load-bearing.
"""

import jax
import jax.numpy as jnp
import numpy as np

from glint_word2vec_tpu.ops.prng import hash_bits, randint_mod, uniform01
from glint_word2vec_tpu.ops.sampler import (
    build_alias_table,
    sample_negatives_hash,
    sampled_probabilities,
)


def test_hash_bits_deterministic_and_stream_separated():
    a = hash_bits(7, 0, jnp.int32(3), (256,))
    b = hash_bits(7, 0, jnp.int32(3), (256,))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # different seed / stream / counter each give a different grid
    for other in (hash_bits(8, 0, jnp.int32(3), (256,)),
                  hash_bits(7, 1, jnp.int32(3), (256,)),
                  hash_bits(7, 0, jnp.int32(4), (256,))):
        assert not np.array_equal(np.asarray(a), np.asarray(other))


def test_uniform01_range_and_mean():
    u = np.asarray(uniform01(1, 0, jnp.int32(0), (100_000,)))
    assert (u >= 0).all() and (u < 1).all()
    # mean/variance of U(0,1): 0.5 / 1/12 — loose 5-sigma bounds
    assert abs(u.mean() - 0.5) < 5 * (1 / np.sqrt(12 * u.size))
    # all 8 leading bits exercised (no stuck-bit degeneracy)
    assert len(np.unique((u * 256).astype(np.int32))) == 256


def test_randint_mod_uniformity_chi2():
    bound = 97  # prime, adversarial to power-of-two structure in the hash
    n = 200_000
    draws = np.asarray(randint_mod(3, 0, jnp.int32(5), (n,), bound))
    freq = np.bincount(draws, minlength=bound)
    expected = n / bound
    chi2 = ((freq - expected) ** 2 / expected).sum()
    # chi2 dof=96: mean 96, sd ~13.9; 5 sigma ≈ 165
    assert chi2 < 165, f"chi2 {chi2:.1f} too high — hash not uniform mod {bound}"


def test_sample_negatives_hash_matches_target_distribution():
    counts = np.array([1000, 400, 150, 60, 25, 10, 4, 1], dtype=np.float64)
    table = build_alias_table(counts, 0.75)
    draws = np.asarray(sample_negatives_hash(
        table.prob, table.alias, 11, jnp.int32(0), (200_000,)))
    freq = np.bincount(draws, minlength=counts.size) / 200_000
    np.testing.assert_allclose(freq, sampled_probabilities(counts, 0.75), atol=0.01)


def test_sample_negatives_hash_counter_advances():
    counts = np.arange(1, 101)
    table = build_alias_table(counts)
    a = sample_negatives_hash(table.prob, table.alias, 5, jnp.int32(1), (64, 5))
    b = sample_negatives_hash(table.prob, table.alias, 5, jnp.int32(1), (64, 5))
    c = sample_negatives_hash(table.prob, table.alias, 5, jnp.int32(2), (64, 5))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))
    assert a.shape == (64, 5)
    assert a.dtype == jnp.int32


def test_sample_negatives_hash_same_under_jit_and_eager():
    counts = np.arange(1, 51)
    table = build_alias_table(counts)
    eager = sample_negatives_hash(table.prob, table.alias, 9, jnp.int32(4), (128,))
    jitted = jax.jit(
        lambda p, a, c: sample_negatives_hash(p, a, 9, c, (128,))
    )(table.prob, table.alias, jnp.int32(4))
    np.testing.assert_array_equal(np.asarray(eager), np.asarray(jitted))
