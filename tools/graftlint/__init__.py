"""graftlint — repo-invariant static analysis for glint-word2vec-tpu.

Layer 1 of the two-layer static-analysis subsystem (docs/static-analysis.md;
layer 2 is the compiled-artifact contract auditor, tools/stepaudit.py). The
engine walks the library/tool sources, runs the repo-specific rules R1–R8
(tools/graftlint/rules.py — each encodes an invariant a prior PR paid to
learn), honors per-line suppressions with written justifications, and exits
nonzero on any unsuppressed finding. Wired into tier-1 via
tests/test_graftlint.py and into CI as its own job.

Run:  python -m tools.graftlint [--json] [--json-out F] [--baseline F]
"""

from tools.graftlint.engine import (  # noqa: F401  (public surface)
    Finding,
    LintReport,
    lint_repo,
    lint_text,
    suppressed_inventory,
)
from tools.graftlint.rules import ALL_RULES  # noqa: F401
