"""Streaming corpus ingestion — train from token files with bounded host RAM.

The reference trains from RDDs of arbitrary size (mllib:310-345); the single-host analog
is a token file too large to hold as Python lists (enwiki ≈ 3B words ≈ tens of GB as
strings). Ingestion is therefore two streaming passes over a re-iterable corpus:

    pass 1: :func:`..data.vocab.build_vocab` — a Counter, O(vocab) RAM
    pass 2: :func:`encode_corpus` — words → int32 ids written straight to disk

after which training reads the encoded shards through ``np.memmap`` (O(1) resident per
access; the OS page cache does the rest). :class:`EncodedCorpus` satisfies the
``Sequence[np.ndarray]`` contract of :func:`..data.pipeline.epoch_batches`, so the
trainer is oblivious to whether sentences live in RAM or on disk.

Layout of an encoded dir (two flat binary files + a small JSON):

    tokens.bin   int32  [total_tokens]     all sentences concatenated
    offsets.bin  int64  [n_sentences + 1]  sentence i = tokens[offsets[i]:offsets[i+1]]
    meta.json    {"n_sentences", "total_tokens", "max_sentence_length"}
"""

from __future__ import annotations

import json
import logging
import os
from typing import Iterable, Iterator, List, Sequence

import numpy as np

from glint_word2vec_tpu.data.vocab import Vocabulary
from glint_word2vec_tpu.train.faults import maybe_fail_ingest, retry_io

logger = logging.getLogger("glint_word2vec_tpu")

_TOKENS = "tokens.bin"
_OFFSETS = "offsets.bin"
_META = "meta.json"


class TokenFileCorpus:
    """Re-iterable sentence stream over a whitespace-tokenized text file
    (one sentence per line — the text8/enwiki-style input). Nothing is held in RAM;
    every ``__iter__`` re-opens the file, so the vocab pass and the encode pass can
    each stream it independently."""

    def __init__(self, path: str, lowercase: bool = False):
        self.path = path
        self.lowercase = lowercase

    def __iter__(self) -> Iterator[List[str]]:
        def _open():
            maybe_fail_ingest(f"corpus open {self.path!r}")
            return open(self.path, "r", encoding="utf-8", errors="replace")

        # the open is the flaky-NFS surface worth retrying; a failure mid-read
        # propagates (the caller restarts the whole streaming pass — replaying
        # from an arbitrary line offset could silently skip sentences)
        with retry_io(_open, what=f"open corpus {self.path!r}") as f:
            for line in f:
                if self.lowercase:
                    line = line.lower()
                toks = line.split()
                if toks:
                    yield toks


class EncodedCorpus(Sequence):
    """Memory-mapped encoded sentences: the disk-backed analog of the
    ``List[np.ndarray]`` that :func:`..data.pipeline.encode_sentences` returns."""

    def __init__(self, directory: str):
        self.directory = directory

        def _open_meta():
            maybe_fail_ingest(f"encoded-corpus meta {directory!r}")
            with open(os.path.join(directory, _META), "r",
                      encoding="utf-8") as f:
                return json.load(f)

        self.meta = retry_io(
            _open_meta, what=f"read encoded-corpus meta under {directory!r}")
        n = self.meta["n_sentences"]
        self._tokens = retry_io(
            lambda: np.memmap(
                os.path.join(directory, _TOKENS), dtype=np.int32, mode="r"),
            what=f"map {_TOKENS} under {directory!r}")
        self._offsets = retry_io(
            lambda: np.memmap(
                os.path.join(directory, _OFFSETS), dtype=np.int64, mode="r",
                shape=(n + 1,)),
            what=f"map {_OFFSETS} under {directory!r}")
        if int(self._offsets[-1]) != self._tokens.shape[0]:
            raise ValueError(
                f"corrupt encoded corpus at {directory}: last offset "
                f"{int(self._offsets[-1])} != token count {self._tokens.shape[0]}")

    def __len__(self) -> int:
        return self.meta["n_sentences"]

    def __getitem__(self, i: int) -> np.ndarray:
        if isinstance(i, slice):
            raise TypeError("EncodedCorpus supports integer indexing only")
        n = len(self)
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        return np.asarray(self._tokens[self._offsets[i]:self._offsets[i + 1]])

    @property
    def total_tokens(self) -> int:
        return self.meta["total_tokens"]


def encode_corpus(
    sentences: Iterable[Sequence[str]],
    vocab: Vocabulary,
    out_dir: str,
    max_sentence_length: int = 1000,
    buffer_sentences: int = 8192,
) -> EncodedCorpus:
    """One streaming pass: words → vocab ids (OOV dropped), chunked to
    ``max_sentence_length`` (the C4 contract, mllib:335-343), appended to disk.

    Peak RAM is O(buffer + offsets): the int64 offset list is the only thing that
    grows with corpus size (8 bytes per sentence — 600 MB even at enwiki's ~75M
    sentences would be the worst case; tokens stream straight through).

    Token-file corpora take the native C++ encode pass when available
    (``native/ingest.cpp``) — identical output files, ~4-5× the throughput."""
    os.makedirs(out_dir, exist_ok=True)
    if isinstance(sentences, TokenFileCorpus) and not sentences.lowercase:
        from glint_word2vec_tpu.data import ingest_native, native
        if ingest_native.ingest_available():
            tok_p = os.path.join(out_dir, _TOKENS)
            off_p = os.path.join(out_dir, _OFFSETS)
            # the native pass retries internally (ingest_native.py); a hard
            # failure — a None sentinel OR an exhausted retry budget — falls
            # through to the Python pass below (which restarts clean)
            try:
                res = ingest_native.encode_corpus_native(
                    sentences.path, vocab.words, max_sentence_length,
                    tok_p, off_p, native.default_threads())
            except OSError as e:
                logger.warning("native corpus encode failed after retries "
                               "(%s); falling back to the Python pass", e)
                res = None
            if res is not None:
                total_n, n_sents = res
                _write_meta(out_dir, n_sents, total_n, max_sentence_length,
                            vocab)
                return EncodedCorpus(out_dir)
    index = vocab.index

    def python_pass() -> tuple:
        """One full encode attempt, restartable from scratch: the tokens file
        is opened "wb" (truncates any partial previous attempt) and all
        position state is local, so the retry wrapper can simply re-run it."""
        maybe_fail_ingest(f"corpus encode into {out_dir!r}")
        offsets: List[int] = [0]
        total = 0
        buf: List[np.ndarray] = []
        buffered = 0

        with open(os.path.join(out_dir, _TOKENS), "wb") as tf:
            def flush():
                nonlocal buf, buffered
                if buf:
                    np.concatenate(buf).tofile(tf)
                    buf, buffered = [], 0

            for sentence in sentences:
                ids = [index[w] for w in sentence if w in index]
                if not ids:
                    continue
                arr = np.asarray(ids, dtype=np.int32)
                for start in range(0, len(arr), max_sentence_length):
                    chunk = arr[start:start + max_sentence_length]
                    if not chunk.size:
                        continue
                    buf.append(chunk)
                    buffered += 1
                    total += int(chunk.size)
                    offsets.append(total)
                    if buffered >= buffer_sentences:
                        flush()
            flush()
        return offsets, total

    if iter(sentences) is sentences:
        # one-shot iterator: a retry would re-iterate the partially consumed
        # generator and silently encode a truncated corpus — propagate instead
        # (same hazard the read path's mid-read policy documents above)
        offsets, total = python_pass()
    else:
        offsets, total = retry_io(
            python_pass, what=f"encode corpus into {out_dir!r}")
    np.asarray(offsets, dtype=np.int64).tofile(os.path.join(out_dir, _OFFSETS))
    _write_meta(out_dir, len(offsets) - 1, total, max_sentence_length, vocab)
    return EncodedCorpus(out_dir)


def _write_meta(out_dir: str, n_sentences: int, total_tokens: int,
                max_sentence_length: int, vocab: Vocabulary) -> None:
    """The encoded-dir metadata — one schema for both the Python and the
    native encode paths."""
    with open(os.path.join(out_dir, _META), "w", encoding="utf-8") as f:
        json.dump({"n_sentences": n_sentences, "total_tokens": total_tokens,
                   "max_sentence_length": max_sentence_length,
                   "vocab_fingerprint": vocab_fingerprint(vocab)}, f)


def vocab_fingerprint(vocab: Vocabulary) -> str:
    """Cheap stable fingerprint of a vocabulary: ids encoded under a different vocab
    are meaningless, so consumers that reuse an encoded dir (resume) verify this."""
    import zlib

    h = zlib.crc32(("\n".join(vocab.words[:1000])).encode("utf-8"))
    h = zlib.crc32(("\n".join(vocab.words[-1000:])).encode("utf-8"), h)
    return f"{vocab.size}-{vocab.train_words_count}-{h:08x}"
