"""R2 good: explicitly seeded generator (position-keyed streams elsewhere)."""
import numpy as np


def draw(n, seed):
    rng = np.random.default_rng(np.random.SeedSequence(entropy=seed))
    return rng.random(n)
