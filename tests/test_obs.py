"""Observability-layer suite (docs/observability.md): telemetry JSONL schema
round-trip + rotation, span nesting/thread-safety + Chrome-trace export, the
fused health probe vs a NumPy oracle, the finite-blowup watchdog under both
policies, the bounded heartbeat ring, and the compiled-step contract proof
that a probing fit adds no implicit transfers and no extra step-twin
recompile (the stepaudit discipline, exercised in-process with the probe
actually firing)."""

import json
import os
import subprocess
import sys
import threading

import jax
import numpy as np
import pytest

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.pipeline import encode_sentences
from glint_word2vec_tpu.data.vocab import build_vocab
from glint_word2vec_tpu.obs.probe import make_health_probe, stats_to_channels
from glint_word2vec_tpu.obs.schema import (
    SCHEMA_VERSION,
    validate_file,
    validate_record,
)
from glint_word2vec_tpu.obs.sink import TelemetrySink
from glint_word2vec_tpu.obs.spans import Tracer
from glint_word2vec_tpu.obs.watch import NormWatchdog
from glint_word2vec_tpu.ops.sgns import EmbeddingPair
from glint_word2vec_tpu.train import faults
from glint_word2vec_tpu.train.faults import NormBlowupError
from glint_word2vec_tpu.train.trainer import Trainer

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _toy_trainer(seed=0, n=250, **cfg_kw):
    rng = np.random.default_rng(seed)
    sents = [[f"w{i}" for i in rng.integers(0, 30, 20)] for _ in range(n)]
    vocab = build_vocab(sents, min_count=1)
    cfg = Word2VecConfig(vector_size=8, pairs_per_batch=128, window=3,
                         num_iterations=2, steps_per_dispatch=2,
                         heartbeat_every_steps=2, subsample_ratio=0.0,
                         prefetch_chunks=0, seed=1, **cfg_kw)
    return Trainer(cfg, vocab), encode_sentences(sents, vocab, 1000)


# -- schema + sink ---------------------------------------------------------------------


def test_sink_roundtrip_schema_valid(tmp_path):
    """Every record kind the trainer emits must validate against the
    catalogue, version field included, after a disk round-trip."""
    p = str(tmp_path / "run.jsonl")
    with TelemetrySink(p) as sink:
        sink.emit("run_start", run_id="r1", vocab_size=30, mesh=[1, 1],
                  config={"learning_rate": 0.02})
        sink.emit("heartbeat", step=4, words=100, alpha=0.02, loss=1.5,
                  mean_f_pos=0.4, pairs_per_sec=1e5, host_wait_s=0.1,
                  dispatch_s=0.2, recoveries=0, lr_scale=1.0,
                  norms={"finite": True})
        sink.emit("watchdog", step=4, policy="warn", reason="x",
                  channels={"syn0": {"max_norm": 1e4}})
        sink.emit("run_end", run_id="r1", status="ok", steps=4,
                  pairs_trained=512.0, host_wait_s_total=0.1,
                  dispatch_s_total=0.2, watchdog_fires=1)
    summary = validate_file(p)
    assert summary["ok"], summary["errors"]
    assert summary["kinds"] == {"run_start": 1, "heartbeat": 1,
                                "watchdog": 1, "run_end": 1}
    with open(p) as f:
        recs = [json.loads(line) for line in f]
    assert all(r["schema"] == SCHEMA_VERSION for r in recs)
    assert all("t" in r for r in recs)


def test_schema_rejects_drift():
    ok = {"schema": SCHEMA_VERSION, "kind": "heartbeat", "t": 1.0, "step": 1,
          "words": 10, "alpha": 0.1, "loss": 1.0, "mean_f_pos": 0.5,
          "pairs_per_sec": 1.0, "host_wait_s": 0.0, "dispatch_s": 0.0,
          "recoveries": 0, "lr_scale": 1.0}
    assert validate_record(ok) == []
    assert validate_record({**ok, "schema": SCHEMA_VERSION + 1})  # version drift
    bad = dict(ok)
    del bad["loss"]
    assert any("loss" in e for e in validate_record(bad))  # field removal
    assert validate_record({**ok, "step": "four"})         # type change
    assert validate_record({**ok, "kind": "mystery"})      # unknown kind
    # additive evolution stays legal
    assert validate_record({**ok, "new_field": 123}) == []
    # a pre-round-13 heartbeat (no recoveries/lr_scale/phases) still
    # validates — new fields are OPTIONAL under the unchanged version, so
    # archived run logs don't retroactively fail the drift gate
    old = dict(ok)
    del old["recoveries"], old["lr_scale"]
    assert validate_record(old) == []
    # ...but a present optional field is still type-checked
    assert any("lr_scale" in e
               for e in validate_record({**ok, "lr_scale": "half"}))


def test_sink_rotation_bounded(tmp_path):
    p = str(tmp_path / "run.jsonl")
    sink = TelemetrySink(p, rotate_bytes=2000, keep=2)
    for i in range(200):
        sink.emit("watchdog", step=i, policy="warn", reason="r" * 50,
                  channels={})
    sink.close()
    files = sorted(os.listdir(tmp_path))
    assert "run.jsonl" in files
    assert "run.jsonl.1" in files
    assert "run.jsonl.2" in files
    assert "run.jsonl.3" not in files  # keep=2 bounds the rotated segments
    for f in files:
        assert os.path.getsize(tmp_path / f) <= 2000 + 200
        assert validate_file(str(tmp_path / f))["ok"]


def test_sink_thread_safety(tmp_path):
    """Concurrent emitters must never interleave mid-line (each record is one
    write under the lock)."""
    p = str(tmp_path / "run.jsonl")
    sink = TelemetrySink(p)

    def emit_many(tid):
        for i in range(100):
            sink.emit("watchdog", step=i, policy="warn",
                      reason=f"t{tid}" * 20, channels={"tid": tid})

    threads = [threading.Thread(target=emit_many, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    sink.close()
    summary = validate_file(p)
    assert summary["ok"], summary["errors"][:3]
    assert summary["records"] == 400


def test_sink_sanitizes_nonfinite(tmp_path):
    """Non-finite measured values (a diverging run's NaN loss) must land as
    null, never as RFC-8259-invalid bare NaN/Infinity tokens — strict
    consumers (jq) read the run log of exactly those runs."""
    p = str(tmp_path / "run.jsonl")
    with TelemetrySink(p) as sink:
        sink.emit("heartbeat", step=1, words=1, alpha=0.1, loss=float("nan"),
                  mean_f_pos=float("inf"), pairs_per_sec=1.0,
                  host_wait_s=0.0, dispatch_s=0.0, recoveries=0, lr_scale=1.0,
                  norms={"syn0": {"max_norm": float("-inf")}})
    line = open(p).read()
    assert "NaN" not in line and "Infinity" not in line
    rec = json.loads(line)
    assert rec["loss"] is None and rec["mean_f_pos"] is None
    assert rec["norms"]["syn0"]["max_norm"] is None
    assert validate_record(rec) == []


# -- spans -----------------------------------------------------------------------------


def test_span_nesting_and_export(tmp_path):
    tr = Tracer(enabled=True)
    with tr.span("outer"):
        with tr.span("inner"):
            pass
        with tr.span("inner"):
            pass
    evs = tr.events()
    assert [e["name"] for e in evs] == ["inner", "inner", "outer"]
    outer = evs[2]
    for inner in evs[:2]:
        # containment: inner spans sit inside the outer's [ts, ts+dur] window
        assert inner["ts_s"] >= outer["ts_s"] - 1e-9
        assert (inner["ts_s"] + inner["dur_s"]
                <= outer["ts_s"] + outer["dur_s"] + 1e-9)
    p = str(tmp_path / "trace.json")
    assert tr.export_chrome_trace(p) == 3
    with open(p) as f:
        doc = json.load(f)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"outer", "inner"}
    assert all(set(e) >= {"ph", "name", "pid", "tid", "ts", "dur"} for e in xs)
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert metas and metas[0]["name"] == "thread_name"


def test_span_thread_safety_and_tids():
    tr = Tracer(enabled=True)
    barrier = threading.Barrier(4)  # all 4 alive at once: thread idents are
                                    # only unique among LIVE threads

    def work(i):
        barrier.wait()
        for _ in range(50):
            with tr.span(f"thread{i}"):
                pass

    threads = [threading.Thread(target=work, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    evs = tr.events()
    assert len(evs) == 200
    by_name = {}
    for e in evs:
        by_name.setdefault(e["name"], set()).add(e["tid"])
    # each span name was recorded on exactly its own thread
    assert all(len(tids) == 1 for tids in by_name.values())
    assert len({next(iter(t)) for t in by_name.values()}) == 4
    summary = tr.span_summary()
    assert all(summary[f"thread{i}"]["count"] == 50 for i in range(4))


def test_span_disabled_is_noop_and_bounded():
    tr = Tracer(enabled=False)
    with tr.span("x"):
        pass
    assert tr.events() == []
    tr2 = Tracer(enabled=True, max_events=10)
    for i in range(25):
        with tr2.span(f"s{i}"):
            pass
    evs = tr2.events()
    assert len(evs) == 10
    assert evs[0]["name"] == "s15"  # oldest dropped, tail kept


# -- fused health probe vs NumPy oracle ------------------------------------------------


def test_probe_matches_numpy_oracle():
    rng = np.random.default_rng(0)
    V, Vpad, D = 500, 512, 16
    threshold = 10.0
    syn0 = rng.normal(size=(Vpad, D)).astype(np.float32)
    syn1 = rng.normal(size=(Vpad, D)).astype(np.float32)
    syn0[50] *= 1e4    # a runaway row
    syn0[60:80] *= 40  # a hot subset past the threshold
    syn0[V:] = 0.0     # padding must not contaminate any channel
    syn1[V:] = 0.0
    params = EmbeddingPair(jax.numpy.asarray(syn0), jax.numpy.asarray(syn1))
    probe = make_health_probe(V, threshold)
    ch = stats_to_channels(jax.device_get(probe(params)))
    assert ch["finite"] is True
    for name, mat in (("syn0", syn0), ("syn1", syn1)):
        norms = np.linalg.norm(mat[:V].astype(np.float64), axis=1)
        got = ch[name]
        assert got["max_norm"] == pytest.approx(norms.max(), rel=1e-5)
        assert got["mean_norm"] == pytest.approx(norms.mean(), rel=1e-5)
        assert got["frac_over"] == pytest.approx(
            float((norms > threshold).mean()), abs=1e-7)
        # histogram p99 is exact to one quarter-octave bucket: the true p99
        # lies in (p99/2^0.25, p99]
        true_p99 = np.quantile(norms, 0.99, method="inverted_cdf")
        assert got["p99_norm"] >= true_p99 * (1 - 1e-6)
        assert got["p99_norm"] <= true_p99 * 2 ** 0.25 * (1 + 1e-6)


def test_probe_finite_bit_matches_old_semantics():
    rng = np.random.default_rng(1)
    a = rng.normal(size=(64, 8)).astype(np.float32)
    params = EmbeddingPair(jax.numpy.asarray(a), jax.numpy.asarray(a))
    probe = make_health_probe(60, 100.0)
    assert stats_to_channels(jax.device_get(probe(params)))["finite"] is True
    b = a.copy()
    b[63, 7] = np.nan  # in the PADDING rows — finiteness covers the whole carry
    params = EmbeddingPair(jax.numpy.asarray(a), jax.numpy.asarray(b))
    assert stats_to_channels(jax.device_get(probe(params)))["finite"] is False


# -- watchdog --------------------------------------------------------------------------


def _channels(max_norm=1.0, frac=0.0):
    m = {"max_norm": max_norm, "mean_norm": 1.0, "p99_norm": 1.0,
         "frac_over": frac}
    return {"finite": True, "syn0": dict(m), "syn1": dict(m)}


def test_watchdog_unit_thresholds():
    wd = NormWatchdog("warn", threshold=100.0, max_norm=1000.0, frac=0.01)
    assert wd.check(_channels(), step=1) is None
    assert wd.check(_channels(max_norm=999.0, frac=0.0099), step=2) is None
    assert wd.fires == 0
    assert wd.check(_channels(frac=0.02), step=3)
    assert wd.check(_channels(max_norm=2000.0), step=4)
    assert wd.fires == 2
    wd_halt = NormWatchdog("halt", 100.0, 1000.0, 0.01)
    with pytest.raises(NormBlowupError, match="finite norm blowup"):
        wd_halt.check(_channels(max_norm=5000.0), step=5)
    wd_off = NormWatchdog("off", 100.0, 1000.0, 0.01)
    assert wd_off.check(_channels(max_norm=1e9), step=6) is None


def test_injected_blowup_warn_fires_nonfinite_silent(tmp_path):
    """The acceptance scenario: a scripted FINITE blowup
    (faults.scale_params_at_step). norm_watch='warn' fires and finishes;
    nonfinite_policy='halt' alone must never notice (no NaN exists)."""
    run_log = str(tmp_path / "run.jsonl")
    faults.configure(scale_params_at_step=8)
    trainer, enc = _toy_trainer(norm_watch="warn", nonfinite_policy="halt",
                                telemetry_path=run_log)
    trainer.fit(enc)  # no raise: the guardrail stays silent, warn continues
    assert trainer.norm_watchdog.fires >= 1
    assert np.isfinite(np.asarray(trainer.params.syn0)).all()
    summary = validate_file(run_log)
    assert summary["ok"], summary["errors"][:3]
    assert summary["kinds"].get("watchdog", 0) >= 1
    with open(run_log) as f:
        wd = [json.loads(line) for line in f
              if '"kind": "watchdog"' in line]
    assert wd[0]["policy"] == "warn"
    assert wd[0]["channels"]["syn0"]["max_norm"] > 1000.0


def test_injected_blowup_halt_raises(tmp_path):
    run_log = str(tmp_path / "run.jsonl")
    faults.configure(scale_params_at_step=8)
    trainer, enc = _toy_trainer(norm_watch="halt", telemetry_path=run_log)
    with pytest.raises(NormBlowupError, match="finite norm blowup"):
        trainer.fit(enc)
    # the halt record was emitted BEFORE the raise, and run_end carries error
    with open(run_log) as f:
        recs = [json.loads(line) for line in f]
    kinds = [r["kind"] for r in recs]
    assert "watchdog" in kinds
    assert recs[-1]["kind"] == "run_end" and recs[-1]["status"] == "error"


def test_norm_watch_off_default_and_validation():
    assert Word2VecConfig().norm_watch == "off"
    with pytest.raises(ValueError, match="norm_watch"):
        Word2VecConfig(norm_watch="panic")
    with pytest.raises(ValueError, match="norm_watch_frac"):
        Word2VecConfig(norm_watch_frac=0.0)
    with pytest.raises(ValueError, match="heartbeat_ring"):
        Word2VecConfig(heartbeat_ring=0)


# -- bounded heartbeat ring ------------------------------------------------------------


def test_heartbeat_ring_bounded(tmp_path):
    run_log = str(tmp_path / "run.jsonl")
    trainer, enc = _toy_trainer(heartbeat_ring=4, telemetry_path=run_log)
    trainer.fit(enc)
    assert trainer.heartbeats.maxlen == 4
    assert len(trainer.heartbeats) == 4
    # the ring keeps the newest records; the sink file keeps the full history
    summary = validate_file(run_log)
    assert summary["kinds"]["heartbeat"] > 4
    steps = [r.global_step for r in trainer.heartbeats]
    assert steps == sorted(steps)
    # the ring holds the NEWEST records (the final round may not reach the
    # next heartbeat cadence, so exact equality is not guaranteed)
    assert (trainer.global_step - trainer.heartbeats[-1].global_step
            < trainer.config.heartbeat_every_steps
            + trainer.config.steps_per_dispatch)
    # extended fields ride every record
    hb = trainer.heartbeats[-1]
    assert hb.norms is not None and "syn0" in hb.norms
    assert hb.host_wait_s >= 0.0 and hb.dispatch_s >= 0.0


def test_tracer_disarmed_by_telemetry_off_trainer(tmp_path):
    """The process-wide tracer must be DISARMED by a telemetry-off trainer
    constructed after a telemetry-on one — otherwise the overhead A/B's off
    arm silently records spans into the shared ring (biasing the very metric
    the acceptance bar reads) and long-lived processes accumulate events."""
    from glint_word2vec_tpu.obs.spans import default_tracer
    _toy_trainer(telemetry_path=str(tmp_path / "a.jsonl"))
    assert default_tracer().enabled
    _toy_trainer()
    assert not default_tracer().enabled


def test_run_end_ok_when_fit_called_inside_except_block(tmp_path):
    """A successful fit launched from inside an except handler (the
    crash-recovery resume pattern) must emit run_end status='ok' — a
    sys.exc_info()-based abort check in the fit finally would see the OUTER
    handled exception and mislabel it."""
    run_log = str(tmp_path / "run.jsonl")
    try:
        raise RuntimeError("outer handled failure")
    except RuntimeError:
        trainer, enc = _toy_trainer(telemetry_path=run_log)
        trainer.fit(enc, checkpoint_path=str(tmp_path / "ck"),
                    checkpoint_every_steps=8)
    with open(run_log) as f:
        recs = [json.loads(line) for line in f]
    assert recs[-1]["kind"] == "run_end"
    assert recs[-1]["status"] == "ok"


# -- compiled-step contracts with the probe firing -------------------------------------


def test_probe_no_implicit_transfers_no_extra_recompile(tmp_path):
    """The stepaudit discipline with telemetry ON and the probe actually
    firing (the audit's scripted fits never reach a heartbeat, so this is the
    coverage for the probing path): the whole fit runs under
    jax.transfer_guard('disallow') — the probe's device fetch is explicit
    (jax.device_get) and its inputs are the already-staged params carry — and
    the two step twins still compile exactly once (the probe is its own tiny
    program, never a step-twin signature change)."""
    trainer, enc = _toy_trainer(
        telemetry_path=str(tmp_path / "run.jsonl"), norm_watch="warn")
    with jax.transfer_guard("disallow"):
        trainer.fit(enc)
    assert len(trainer.heartbeats) > 0  # the probe really ran under the guard
    compiles = trainer._step_fn._cache_size()
    if trainer._step_fn_fast is not trainer._step_fn:
        compiles += trainer._step_fn_fast._cache_size()
    assert compiles == 1


# -- scripted telemetry fit through the CLI driver -------------------------------------


def test_telemetry_run_smoke(tmp_path):
    """End-to-end acceptance: tools/telemetry_run.py --smoke produces a
    schema-valid JSONL run log + a Chrome trace with the required spans, and
    prints exactly one JSON line (R7)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "telemetry_run.py"),
         "--smoke", "--out", str(tmp_path / "art")],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        cwd=_REPO, capture_output=True, timeout=500, text=True)
    assert proc.returncode == 0, proc.stdout[-1000:] + proc.stderr[-1000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1
    res = json.loads(lines[0])
    assert res["ok"] and res["schema_valid"]
    assert res["missing_spans"] == []
    assert os.path.exists(res["run_log"])
    assert os.path.exists(res["trace"])
