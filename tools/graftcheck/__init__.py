"""graftcheck — layer 3 of the static-analysis subsystem: an EXECUTING model
checker over the ``Word2VecConfig`` knob lattice.

Where graftlint R8 diffs the config/trainer refusal matrices as AST (what the
source *promises*) and stepaudit checks the compiled artifact, graftcheck
enumerates the 69-knob lattice from a declarative registry and actually RUNS
each candidate through the contracts the five historical serialization bugs
violated (docs/static-analysis.md has the catalogue):

(a) construction/dispatch refusal parity — construct the config, then build a
    real ``Trainer`` against a fixed probe vocabulary/mesh and assert no combo
    is refused at dispatch that construction accepted (runtime-only refusals —
    device counts, process divisibility, corpus-dependent channels — are
    classified and exempt, exactly R8's exemption, but checked empirically);
(b) serialization fixpoints — ``from_dict(to_dict(c))`` reaches a fixpoint
    under both ``auto_markers`` modes, through a JSON round trip, and AUTO-ness
    (pool ``-1``, subsample marker) survives;
(c) ``replace()`` re-resolution parity — a knob flip via ``replace()`` is
    equivalent (same acceptance, same serialized form, same AUTO flags) to
    fresh construction from the auto-marker dict with the flip applied;
(d) checkpoint-normalization monotonicity — every documented old-dict
    normalization (stored resolved pool beside cbow+duplicate_scaling,
    unknown-key filtering, mesh_shape list→tuple) produces a config that
    constructs cleanly.

Violations shrink to minimal (≤3-knob) counterexamples; the expected refusal
signatures live in the committed ``baseline.json`` with a drift gate in both
directions. ``python -m tools.graftcheck`` prints exactly one JSON line on
stdout (the R7 contract); ``--smoke`` is the tier-1 wiring, the full sweep
(all 69 knobs pairwise + exhaustive refusal-relevant subsets, ≥1,000 executed
configs) runs in CI.
"""
