"""Parallel host data plane (PERF.md §10): bit-identity of every parallel path
against its serial twin, plus the hostbench harness smoke tier.

The contract under test: ``producer_workers`` / ``io_workers`` change WALL
CLOCK only — streams, trained parameters, checkpoint bytes, digests, and
exports are identical at any worker count, because every parallel unit is a
pure function of position-keyed inputs consumed in a fixed order.
"""

import filecmp
import json
import os
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from glint_word2vec_tpu.config import Word2VecConfig  # noqa: E402
from glint_word2vec_tpu.data.pipeline import (  # noqa: E402
    encode_sentences, epoch_batches, epoch_batches_cbow, ordered_pool_map)
from glint_word2vec_tpu.data.vocab import (  # noqa: E402
    build_vocab, count_words, count_words_parallel)
from glint_word2vec_tpu.train import checkpoint as ckpt  # noqa: E402
from glint_word2vec_tpu.train.trainer import (  # noqa: E402
    Trainer, _one_ahead_iter)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _corpus(n_words=60_000, vocab_size=300, sent_len=30, seed=0):
    rng = np.random.default_rng(seed)
    zipf = 1.0 / (np.arange(vocab_size) + 10.0) ** 1.05
    ids = rng.choice(vocab_size, size=n_words, p=zipf / zipf.sum())
    words = np.char.add("w", ids.astype("U8"))
    return [list(words[i:i + sent_len]) for i in range(0, n_words, sent_len)]


@pytest.fixture(scope="module")
def corpus():
    sents = _corpus()
    vocab = build_vocab(sents, min_count=1)
    return sents, vocab, encode_sentences(sents, vocab, 1000)


# -- ordered_pool_map ---------------------------------------------------------------


def test_ordered_pool_map_order_and_serial_equivalence():
    jobs = list(range(57))
    fn = lambda x: x * x  # noqa: E731
    assert list(ordered_pool_map(fn, jobs, 1)) == [x * x for x in jobs]
    assert list(ordered_pool_map(fn, jobs, 4)) == [x * x for x in jobs]


def test_ordered_pool_map_propagates_exceptions():
    def fn(x):
        if x == 3:
            raise ValueError("job 3")
        return x

    out = []
    with pytest.raises(ValueError, match="job 3"):
        for r in ordered_pool_map(fn, range(10), 4):
            out.append(r)
    assert out == [0, 1, 2]  # everything before the failing job, in order


def test_ordered_pool_map_consumer_abandon():
    # closing the generator mid-stream must not hang on in-flight futures
    gen = ordered_pool_map(lambda x: x, range(1000), 4)
    assert next(gen) == 0
    gen.close()


# -- producer bit-identity ----------------------------------------------------------


def _batches_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        for f in x.__dataclass_fields__:
            xa, ya = getattr(x, f), getattr(y, f)
            if isinstance(xa, np.ndarray):
                assert np.array_equal(xa, ya), f
            else:
                assert xa == ya, f


@pytest.mark.parametrize("fn", [epoch_batches, epoch_batches_cbow])
def test_epoch_batches_parallel_bit_identity(corpus, fn):
    _, vocab, enc = corpus
    kw = dict(pairs_per_batch=512, window=4, subsample_ratio=1e-3, seed=3,
              iteration=1, block_words=5000)  # small blocks => many slab jobs
    serial = list(fn(enc, vocab, producer_workers=1, **kw))
    parallel = list(fn(enc, vocab, producer_workers=4, **kw))
    _batches_equal(serial, parallel)


def test_epoch_batches_native_parallel_bit_identity(corpus):
    # the native backend divides its C++ thread budget across the slab pool
    # (pipeline.epoch_batches) — the stream must stay bit-identical to the
    # serial full-budget native run at any worker count
    from glint_word2vec_tpu.data.native import native_available
    if not native_available():
        pytest.skip("native generator not built")
    _, vocab, enc = corpus
    kw = dict(pairs_per_batch=512, window=4, subsample_ratio=1e-3, seed=3,
              iteration=1, block_words=5000, backend="native")
    serial = list(epoch_batches(enc, vocab, producer_workers=1, **kw))
    parallel = list(epoch_batches(enc, vocab, producer_workers=4, **kw))
    _batches_equal(serial, parallel)


def _seg_blocks(vocab, enc, workers, **cfg_kw):
    cfg = Word2VecConfig(
        vector_size=16, pairs_per_batch=512, window=3, num_iterations=1,
        seed=7, subsample_ratio=1e-3, negative_pool=128, steps_per_dispatch=2,
        producer_workers=workers, **cfg_kw)
    tr = Trainer(cfg, vocab)
    return list(tr._device_seg_blocks(enc, 1, 0))


@pytest.mark.parametrize("cfg_kw", [
    dict(device_pairgen=True),                    # plain T-boundary cut
    dict(cbow=True, cbow_update="banded"),        # ±window halo cut
], ids=["plain-cut", "halo-cut"])
def test_device_seg_blocks_parallel_bit_identity(corpus, cfg_kw):
    _, vocab, enc = corpus
    serial = _seg_blocks(vocab, enc, 1, **cfg_kw)
    parallel = _seg_blocks(vocab, enc, 4, **cfg_kw)
    assert len(serial) == len(parallel) and len(serial) > 1
    for s, p in zip(serial, parallel):
        for xa, ya in zip(s, p):
            assert np.array_equal(xa, ya)


def test_trained_params_bit_identity_across_workers(corpus):
    _, vocab, enc = corpus

    def fit(workers, device_pairgen):
        cfg = Word2VecConfig(
            vector_size=16, pairs_per_batch=512, window=3, num_iterations=1,
            seed=7, subsample_ratio=1e-3, negative_pool=128,
            steps_per_dispatch=2, prefetch_chunks=2, producer_workers=workers,
            device_pairgen=device_pairgen)
        tr = Trainer(cfg, vocab)
        tr.fit(enc)
        return np.asarray(tr.params.syn0), np.asarray(tr.params.syn1)

    for dp in (False, True):
        s0, s1 = fit(1, dp)
        p0, p1 = fit(4, dp)
        assert np.array_equal(s0, p0) and np.array_equal(s1, p1)


# -- vocab counting -----------------------------------------------------------------


def test_count_words_parallel_bit_identity(corpus):
    sents, _, _ = corpus
    serial = count_words(sents)
    parallel = count_words_parallel(sents, workers=4, slab_sentences=137)
    assert serial == parallel
    # iteration order too: the descending-count TIE-BREAK ranks equal-count
    # words by first appearance, so key order is vocabulary-identical
    assert list(serial.keys()) == list(parallel.keys())
    v1 = build_vocab(sents, min_count=2)
    v4 = build_vocab(sents, min_count=2, workers=4)
    assert v1.words == v4.words
    assert np.array_equal(v1.counts, v4.counts)


# -- alias table --------------------------------------------------------------------


def test_alias_table_exact_and_worker_independent():
    from glint_word2vec_tpu.ops.sampler import (
        build_alias_table, sampled_probabilities)
    # the last size crosses _ALIAS_PARTITION_MIN_V, so the strided-partition
    # sweep + leftover-merge path is exercised, not just the single sweep
    for V in (7, 1000, 40_000, (1 << 18) + 7):
        counts = np.maximum(1e8 / (np.arange(V) + 10.0) ** 1.07, 3.0)
        t1 = build_alias_table(counts, workers=1)
        t4 = build_alias_table(counts, workers=4)
        # deterministic per (counts, power): the worker knob must never change
        # the realized negative-sample stream
        assert np.array_equal(np.asarray(t1.prob), np.asarray(t4.prob))
        assert np.array_equal(np.asarray(t1.alias), np.asarray(t4.alias))
        # exactness: represented distribution == counts^0.75, to f32 prob res
        # (the tables store prob as float32, so the absolute error scales with
        # the largest scaled head weight)
        prob = np.asarray(t1.prob, np.float64)
        dist = prob.copy()
        np.add.at(dist, np.asarray(t1.alias), 1.0 - prob)
        target = sampled_probabilities(counts) * V
        tol = max(1e-6, 3e-7 * float(target.max()))
        assert np.abs(dist - target).max() < tol
        assert (prob >= 0).all() and (prob <= 1).all()


# -- checkpoint I/O -----------------------------------------------------------------


def _tree_files(path):
    out = {}
    for root, _, files in os.walk(path):
        for f in files:
            p = os.path.join(root, f)
            out[os.path.relpath(p, path)] = p
    return out


def _assert_same_checkpoint_bytes(a, b):
    fa, fb = _tree_files(a), _tree_files(b)
    assert set(fa) == set(fb)
    for rel in fa:
        if rel == "metadata.json":
            ma = json.load(open(fa[rel]))
            mb = json.load(open(fb[rel]))
            # the stored config legitimately records its own io_workers
            ma["config"].pop("io_workers"), mb["config"].pop("io_workers")
            assert ma == mb
        else:
            assert filecmp.cmp(fa[rel], fb[rel], shallow=False), rel


def _ckpt_fixtures(rows=500, dim=24, seed=0):
    rng = np.random.default_rng(seed)
    words = [f"w{i}" for i in range(rows)]
    counts = rng.integers(1, 100, rows).astype(np.int64)
    syn0 = rng.standard_normal((rows, dim)).astype(np.float32)
    syn1 = rng.standard_normal((rows, dim)).astype(np.float32)
    return words, counts, syn0, syn1


def test_dense_save_parallel_bit_identity(tmp_path):
    words, counts, syn0, syn1 = _ckpt_fixtures()
    for w in (1, 4):
        ckpt.save_model(str(tmp_path / f"m{w}"), words, counts, syn0, syn1,
                        Word2VecConfig(vector_size=24, io_workers=w))
    _assert_same_checkpoint_bytes(str(tmp_path / "m1"), str(tmp_path / "m4"))
    # single-pass digests verify against a fresh re-hash
    ckpt.verify_checkpoint(str(tmp_path / "m4"), io_workers=4)
    d = ckpt.load_model(str(tmp_path / "m4"), io_workers=4)
    assert np.array_equal(d["syn0"], syn0)
    assert np.array_equal(d["syn1"], syn1)


def test_sharded_save_parallel_bit_identity(tmp_path):
    from glint_word2vec_tpu.parallel.mesh import make_mesh
    words, counts, syn0, syn1 = _ckpt_fixtures(rows=512)
    plan = make_mesh(1, 1)
    s0 = jax.device_put(jnp.asarray(syn0), plan.embedding)
    s1 = jax.device_put(jnp.asarray(syn1), plan.embedding)
    for w in (1, 4):
        ckpt.save_model_sharded(
            str(tmp_path / f"s{w}"), words, counts, s0, s1,
            Word2VecConfig(vector_size=24, io_workers=w),
            vocab_size=512, vector_size=24)
    _assert_same_checkpoint_bytes(str(tmp_path / "s1"), str(tmp_path / "s4"))
    d1 = ckpt.load_model(str(tmp_path / "s1"), io_workers=1)
    d4 = ckpt.load_model(str(tmp_path / "s4"), io_workers=4)
    assert np.array_equal(d1["syn0"], d4["syn0"])
    assert np.array_equal(d1["syn1"], d4["syn1"])


def test_hashing_writer_digest_matches_rehash(tmp_path):
    # the single-pass digest must equal a from-scratch file hash
    arr = np.random.default_rng(0).standard_normal((100, 7))
    p = str(tmp_path / "a.npy")
    got = ckpt._save_npy_hashed(p, arr)
    assert got == ckpt._sha256_file(p)
    loaded = np.load(p)
    assert np.array_equal(loaded, arr)


def test_corrupt_checkpoint_still_detected_with_workers(tmp_path):
    words, counts, syn0, syn1 = _ckpt_fixtures()
    path = str(tmp_path / "m")
    ckpt.save_model(path, words, counts, syn0, syn1,
                    Word2VecConfig(vector_size=24, io_workers=4))
    with open(os.path.join(path, "syn0.npy"), "r+b") as f:
        f.seek(256)
        f.write(b"\xff\xff\xff\xff")
    with pytest.raises(ckpt.CheckpointCorruptError):
        ckpt.verify_checkpoint(path, io_workers=4)


@pytest.mark.slow
def test_large_matrix_save_load_identity(tmp_path):
    # the large-matrix variant of the round-trip (ISSUE-3 test satellite):
    # ~200 MB of matrices through the parallel writer, byte-compared
    words, counts, syn0, syn1 = _ckpt_fixtures(rows=70_000, dim=384)
    for w in (1, 4):
        ckpt.save_model(str(tmp_path / f"m{w}"), words, counts, syn0, syn1,
                        Word2VecConfig(vector_size=384, io_workers=w))
    _assert_same_checkpoint_bytes(str(tmp_path / "m1"), str(tmp_path / "m4"))
    d = ckpt.load_model(str(tmp_path / "m4"), io_workers=4)
    assert np.array_equal(d["syn0"], syn0)


# -- export -------------------------------------------------------------------------


@pytest.mark.parametrize("binary", [False, True], ids=["text", "binary"])
def test_export_parallel_byte_identity(tmp_path, binary):
    from glint_word2vec_tpu.data.vocab import Vocabulary
    from glint_word2vec_tpu.models.word2vec import Word2VecModel
    words, counts, syn0, _ = _ckpt_fixtures()
    vocab = Vocabulary.from_words_and_counts(words, counts)
    paths = []
    for w in (1, 3):
        m = Word2VecModel(vocab, jnp.asarray(syn0),
                          config=Word2VecConfig(vector_size=24, io_workers=w))
        p = str(tmp_path / f"e{w}")
        m.export_word2vec(p, binary=binary, batch_size=64)
        paths.append(p)
        m.stop()
    assert filecmp.cmp(paths[0], paths[1], shallow=False)


# -- CPU top-k routing --------------------------------------------------------------


def test_cpu_topk_matches_lax_topk(monkeypatch):
    from glint_word2vec_tpu.data.vocab import Vocabulary
    from glint_word2vec_tpu.models import word2vec as w2v
    if jax.default_backend() != "cpu":
        pytest.skip("exercises the CPU argpartition route")
    monkeypatch.setenv("GLINT_CPU_TOPK", "argpartition")
    words, counts, syn0, _ = _ckpt_fixtures(rows=800, dim=16)
    vocab = Vocabulary.from_words_and_counts(words, counts)
    model = w2v.Word2VecModel(vocab, jnp.asarray(syn0))
    queries = jnp.asarray(syn0[:5])
    s_ref, i_ref = w2v._cosine_topk_batch(
        model._full0, model.norms, queries, 12, 800)
    s_cpu, i_cpu = w2v._topk_dispatch(
        model._full0, model.norms, queries, 12, 800)
    assert np.array_equal(np.asarray(i_ref), i_cpu)
    assert np.allclose(np.asarray(s_ref), s_cpu, atol=1e-6)
    # and through the public API
    out = model.find_synonyms_batch(["w0", syn0[3]], 5)
    assert len(out) == 2 and len(out[0]) == 5
    model.stop()


def test_cpu_topk_tie_order_matches_lax_topk():
    # tied scores are real in this domain (duplicate rows, zero-norm rows all
    # scoring 0.0); lax.top_k breaks ties toward the LOWER index and the host
    # route must match exactly — a plain argpartition boundary does not
    from glint_word2vec_tpu.models.word2vec import _cpu_topk_row
    cases = [
        (np.asarray([1.0, 1.0, 0.5, 1.0], np.float32), 2),
        (np.asarray([0.0] * 10, np.float32), 3),
        (np.asarray([0.5, -np.inf, 0.5, 0.5, -np.inf], np.float32), 4),
        (np.asarray([2.0, 1.0, 2.0, 1.0, 1.0, 1.0], np.float32), 4),
    ]
    for row, k in cases:
        s_ref, i_ref = jax.lax.top_k(jnp.asarray(row), k)
        s, i = _cpu_topk_row(row, k)
        assert np.array_equal(np.asarray(i_ref), i), (row, k, i, i_ref)
        assert np.array_equal(np.asarray(s_ref), s)
    # randomized ties: coarse-quantized scores collide constantly
    rng = np.random.default_rng(0)
    for _ in range(20):
        row = (rng.integers(0, 4, 200) / 4.0).astype(np.float32)
        k = int(rng.integers(1, 20))
        s_ref, i_ref = jax.lax.top_k(jnp.asarray(row), k)
        s, i = _cpu_topk_row(row, k)
        assert np.array_equal(np.asarray(i_ref), i)


# -- staging primitives -------------------------------------------------------------


def test_one_ahead_iter_handshake_order():
    events = []

    def gen():
        for i in range(4):
            events.append(("produce", i))
            yield i

    it = _one_ahead_iter(gen())
    for x in it:
        events.append(("consume", x))
        it.ack()
    idx = {e: i for i, e in enumerate(events)}
    for r in range(1, 4):
        # the launch-order invariant: stage r+1 strictly after round r's
        # consumption was acked
        assert idx[("produce", r)] > idx[("consume", r - 1)], events


def test_one_ahead_iter_exception_and_close():
    def boom():
        yield 1
        raise RuntimeError("boom")

    it = _one_ahead_iter(boom())
    assert next(it) == 1
    it.ack()
    with pytest.raises(RuntimeError, match="boom"):
        next(it)

    def infinite():
        i = 0
        while True:
            yield i
            i += 1

    it = _one_ahead_iter(infinite())
    assert next(it) == 0
    it.close()  # must not hang


def test_allgather_split_phase_single_process():
    from glint_word2vec_tpu.parallel.distributed import (
        allgather_fetch, allgather_start)
    tree = {"a": np.arange(6).reshape(2, 3), "b": np.float32(3.5)}
    g = allgather_fetch(allgather_start(tree))
    # process_allgather layout: leading [process_count] axis
    assert g["a"].shape == (1, 2, 3)
    assert np.array_equal(g["a"][0], tree["a"])
    assert g["b"].shape == (1,) and g["b"][0] == np.float32(3.5)


# -- hostbench smoke (the harness cannot rot) ---------------------------------------


def test_hostbench_smoke_tier():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "hostbench.py"),
         "--smoke", "--workers", "2", "--repeats", "1"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    for field in ("producer_tokens_per_sec", "ckpt_save_s", "ckpt_load_s",
                  "export_s", "vocab_build_s", "alias_build_s"):
        assert field in row and row[field] > 0, field
