"""Model persistence (reference components G9/C13) with mid-training checkpointing.

On-disk layout keeps the reference's composite-model contract (mllib:493-498,714-715,
ml:504-507) while replacing HDFS matrix shards with array files:

    path/
      words          one word per line, line order == embedding row order (exact parity
                     with the reference's sidecar, mllib:495-496)
      counts.npy     per-word corpus counts (needed to rebuild the negative-sampling
                     table on resume; the reference re-broadcasts vocabCns instead)
      syn0.npy       input embeddings [V, D] float32
      syn1.npy       output embeddings [V, D] float32 (present iff trainable state saved;
                     the reference's save keeps both matrices alive on the PS too)
      metadata.json  config + format version + train_state — the analog of the ML layer's
                     DefaultParamsWriter metadata (ml:504-507)

Improvement over the reference: ``train_state`` records (iteration, words_processed), so a
``numIterations`` run is resumable mid-way — the reference is all-or-nothing (SURVEY §5).

Arrays are gathered to host before writing; a tensorstore/orbax sharded writer can slot in
behind the same layout for >HBM models.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
from typing import Any, Dict, List, Optional

import numpy as np

from glint_word2vec_tpu.config import Word2VecConfig

FORMAT_VERSION = 1


@dataclasses.dataclass
class TrainState:
    """Mid-training progress: which iteration we are in and how many (subsampled) words
    the lr-decay clock has consumed (mllib:405-413 semantics)."""

    iteration: int = 1
    words_processed: int = 0
    finished: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TrainState":
        return cls(**{k: d[k] for k in ("iteration", "words_processed", "finished")
                      if k in d})


def save_model(
    path: str,
    words: List[str],
    counts: np.ndarray,
    syn0: np.ndarray,
    syn1: Optional[np.ndarray],
    config: Word2VecConfig,
    train_state: Optional[TrainState] = None,
) -> None:
    """Atomic save: everything is written to a sibling temp directory first and swapped
    into place, so a crash mid-save never corrupts an existing checkpoint (the whole point
    of ``checkpoint_every_steps``-style periodic saves)."""
    bad = [w for w in words if (not w) or ("\n" in w)]
    if bad:
        raise ValueError(
            f"cannot save vocabulary: {len(bad)} token(s) are empty or contain newlines "
            f"(first: {bad[0]!r}); the words sidecar is newline-delimited")
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = os.path.join(parent, f".{os.path.basename(path)}.tmp-{os.getpid()}")
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        with open(os.path.join(tmp, "words"), "w", encoding="utf-8") as f:
            for w in words:
                f.write(w + "\n")
        np.save(os.path.join(tmp, "counts.npy"), np.asarray(counts, dtype=np.int64))
        syn0 = np.asarray(syn0, dtype=np.float32)
        np.save(os.path.join(tmp, "syn0.npy"), syn0)
        if syn1 is not None:
            np.save(os.path.join(tmp, "syn1.npy"), np.asarray(syn1, dtype=np.float32))
        meta = {
            "format_version": FORMAT_VERSION,
            "framework": "glint_word2vec_tpu",
            "vocab_size": int(syn0.shape[0]),
            "vector_size": int(syn0.shape[1]),
            "config": config.to_dict(),
            "train_state": (train_state or TrainState(finished=True)).to_dict(),
        }
        with open(os.path.join(tmp, "metadata.json"), "w", encoding="utf-8") as f:
            json.dump(meta, f, indent=2)
        old = None
        if os.path.exists(path):
            old = path + f".old-{os.getpid()}"
            os.rename(path, old)
        os.rename(tmp, path)
        if old is not None:
            shutil.rmtree(old)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def load_model(path: str) -> Dict[str, Any]:
    """Read a saved model directory. Returns dict with words, counts, syn0, syn1 (may be
    None), config, train_state. Mirrors the reference's load contract (mllib:710-725:
    read /words in row order, load matrix shards, rebuild model)."""
    meta_path = os.path.join(path, "metadata.json")
    if not os.path.exists(meta_path):
        raise FileNotFoundError(f"no metadata.json under {path!r}")
    with open(meta_path, "r", encoding="utf-8") as f:
        meta = json.load(f)
    version = meta.get("format_version")
    if version != FORMAT_VERSION:
        raise ValueError(f"unsupported checkpoint format_version {version}")
    with open(os.path.join(path, "words"), "r", encoding="utf-8") as f:
        words = [line.rstrip("\n") for line in f if line.rstrip("\n")]
    counts = np.load(os.path.join(path, "counts.npy"))
    syn0 = np.load(os.path.join(path, "syn0.npy"))
    syn1_path = os.path.join(path, "syn1.npy")
    syn1 = np.load(syn1_path) if os.path.exists(syn1_path) else None
    if syn0.shape[0] != len(words):
        raise ValueError(
            f"words sidecar has {len(words)} entries but syn0 has {syn0.shape[0]} rows")
    return {
        "words": words,
        "counts": counts,
        "syn0": syn0,
        "syn1": syn1,
        "config": Word2VecConfig.from_dict(meta["config"]),
        "train_state": TrainState.from_dict(meta.get("train_state", {})),
    }
