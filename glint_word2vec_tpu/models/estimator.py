"""Estimator API — fit sentences to a Word2VecModel.

The pythonic primary surface (in the reference, Python was a Py4J shim over the Spark ML
Estimator, C11/C14; here Python is the framework's first language). One call chain:

    model = Word2Vec(vector_size=100, window=5).fit(sentences)

covers what the reference spreads over mllib fit (vocab → broadcasts → doFit,
mllib:310-326), the ML Estimator (ml:284-305) and the PySpark wrapper
(ml_glintword2vec.py:143-151).
"""

from __future__ import annotations

import logging
from typing import Iterable, Optional, Sequence

import numpy as np

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.pipeline import encode_sentences
from glint_word2vec_tpu.data.vocab import Vocabulary, build_vocab
from glint_word2vec_tpu.models.word2vec import Word2VecModel
from glint_word2vec_tpu.parallel.mesh import MeshPlan
from glint_word2vec_tpu.train.trainer import Trainer

logger = logging.getLogger("glint_word2vec_tpu")


class Word2Vec:
    """Trains skip-gram (default) or CBOW word2vec with negative sampling."""

    def __init__(self, config: Optional[Word2VecConfig] = None, **overrides):
        if config is None:
            config = Word2VecConfig(**overrides)
        elif overrides:
            config = config.replace(**overrides)
        self.config = config

    def fit(
        self,
        sentences: Iterable[Sequence[str]],
        plan: Optional[MeshPlan] = None,
        vocab: Optional[Vocabulary] = None,
        checkpoint_path: Optional[str] = None,
        checkpoint_every_steps: Optional[int] = None,
        encode_cache_dir: Optional[str] = None,
    ) -> Word2VecModel:
        """sentences: iterable of token sequences (the RDD[Iterable[String]] analog,
        mllib:310). Re-iterables (lists, :class:`..data.corpus.TokenFileCorpus`) are
        streamed twice (vocab pass + encode pass) without materialization; one-shot
        generators are materialized to a list first.

        ``encode_cache_dir``: write the encoded corpus there and train from
        memory-mapped shards — bounded host RAM for corpora that don't fit as
        Python lists (see data/corpus.py). Without it, encoding is in-RAM.
        """
        cfg = self.config
        if iter(sentences) is sentences:  # one-shot generator: must materialize
            sentences = list(sentences)
        if vocab is None:
            vocab = build_vocab(sentences, cfg.min_count,
                                workers=cfg.io_workers)
        logger.info("vocabSize = %d, trainWordsCount = %d",
                    vocab.size, vocab.train_words_count)
        if encode_cache_dir is not None:
            from glint_word2vec_tpu.data.corpus import encode_corpus
            encoded = encode_corpus(
                sentences, vocab, encode_cache_dir, cfg.max_sentence_length)
        else:
            encoded = encode_sentences(sentences, vocab, cfg.max_sentence_length)
        trainer = Trainer(cfg, vocab, plan=plan)
        trainer.fit(encoded, checkpoint_path=checkpoint_path,
                    checkpoint_every_steps=checkpoint_every_steps)
        params = trainer.unpadded_params()
        # runtime outcome of the fit (docs/robustness.md ladder +
        # docs/observability.md attribution): the EVAL harness emits this
        # into its rows so a stabilizer A/B reports the ENGAGED mitigation
        # state, and a telemetry-on run additionally carries the per-phase
        # time rollup. One owner: Trainer.last_run_stats.
        self.last_run_stats = trainer.last_run_stats
        return Word2VecModel(
            vocab=vocab, syn0=params.syn0, syn1=params.syn1,
            config=cfg, plan=trainer.plan, train_state=trainer.state)

    @staticmethod
    def resume(
        checkpoint_path: str,
        sentences: Iterable[Sequence[str]],
        plan: Optional[MeshPlan] = None,
        checkpoint_every_steps: Optional[int] = None,
        encode_cache_dir: Optional[str] = None,
        allow_unstable: Optional[bool] = None,
        config_overrides: Optional[dict] = None,
    ) -> Word2VecModel:
        """Resume an interrupted run from a mid-training checkpoint (capability the
        reference lacks — its runs are all-or-nothing, SURVEY §5). Resume is
        exact-step: the checkpoint records the deterministic batch-stream position
        (``TrainState.batches_done``), so already-trained batches of the interrupted
        iteration are skipped, not replayed.

        ``sentences`` may be raw token sequences or an already-encoded
        :class:`..data.corpus.EncodedCorpus`. If ``encode_cache_dir`` already holds an
        encoded corpus whose vocab fingerprint matches the checkpoint's vocabulary, it
        is reused as-is (the common resume case — no re-encoding pass, unlike
        :meth:`fit` which always re-encodes); otherwise the sentences are streamed
        into it. Either way training reads memory-mapped shards.

        ``config_overrides``/``allow_unstable``: the rebuilt Trainer otherwise
        takes the checkpoint's config verbatim, and checkpoints pin the
        RESOLVED subsample_ratio (to_dict(auto_markers=False)) — so a
        pre-round-5 checkpoint saved with the old default 1e-3 at a geometry
        now inside the measured duplicate-overload refusal region would be
        unresumable (ADVICE r5). ``allow_unstable=True`` overrides that
        refusal for the resumed run (warn-only); ``config_overrides`` replaces
        arbitrary config fields (e.g. ``{"subsample_ratio": 1e-4}``) — note
        non-feed knobs that change the batch stream will shift the recorded
        resume position's meaning."""
        import os

        from glint_word2vec_tpu.data.corpus import (
            EncodedCorpus, encode_corpus, vocab_fingerprint)
        from glint_word2vec_tpu.ops.sgns import EmbeddingPair
        from glint_word2vec_tpu.train.checkpoint import (
            load_model, load_model_header, load_params_into_plan)

        header = load_model_header(checkpoint_path)
        cfg: Word2VecConfig = header["config"]
        if config_overrides:
            cfg = cfg.replace(**config_overrides)
        if allow_unstable is not None:
            cfg = cfg.replace(allow_unstable=allow_unstable)
        state = header["train_state"]
        vocab = Vocabulary.from_words_and_counts(header["words"], header["counts"])
        streamed = None
        if plan is not None and header["layout"] == "row-shards":
            # stream the shards straight onto the target mesh — resume at the 10M-row
            # north star must not materialize [V, D] on one host (same path as
            # Word2VecModel.load(plan=...))
            from glint_word2vec_tpu.parallel.mesh import (
                pad_dim_to_lanes, pad_vocab_for_sharding)
            pv = pad_vocab_for_sharding(vocab.size, plan.num_model)
            pd = pad_dim_to_lanes(cfg.vector_size, cfg.pad_vector_to_lanes)
            syn0, syn1 = load_params_into_plan(
                checkpoint_path, plan, pv, pd, dtype=np.dtype(cfg.param_dtype),
                io_workers=cfg.io_workers)
            if syn1 is None:
                raise ValueError("checkpoint has no syn1; cannot resume training")
            streamed = EmbeddingPair(syn0, syn1)
            data = None
        else:
            # io_workers from the LIVE (override-applied) config — the saved
            # value reflects the writing host, not this one
            data = load_model(checkpoint_path, header=header,
                              io_workers=cfg.io_workers)
        if isinstance(sentences, EncodedCorpus):
            encoded = sentences
        elif encode_cache_dir is not None:
            if os.path.exists(os.path.join(encode_cache_dir, "meta.json")):
                encoded = EncodedCorpus(encode_cache_dir)
                want = vocab_fingerprint(vocab)
                got = encoded.meta.get("vocab_fingerprint")
                # the continual case (docs/continual.md): a checkpoint grown
                # by continual.extend carries a vocab_lineage chain whose
                # identity-prefix contract keeps every ANCESTOR vocabulary's
                # ids valid — a cache encoded under any of them is reused
                # as-is, not re-encoded
                from glint_word2vec_tpu.continual.extend import (
                    lineage_fingerprints)
                allowed = set(
                    lineage_fingerprints(header.get("vocab_lineage") or []))
                allowed.add(want)
                if got not in allowed:
                    raise ValueError(
                        f"encode_cache_dir {encode_cache_dir!r} was encoded under a "
                        f"different vocabulary (fingerprint {got} != checkpoint's "
                        f"{want}, and it is not an ancestor in the checkpoint's "
                        "lineage chain); ids would map to the wrong words. Point "
                        "resume at the cache dir of the interrupted run, or a "
                        "fresh directory — or, if the CORPUS drifted (new words, "
                        "shifted frequencies), migrate the checkpoint first with "
                        "glint_word2vec_tpu.continual.extend.extend_checkpoint "
                        "(vocab growth on resume, docs/continual.md) instead of "
                        "retraining from scratch.")
            else:
                encoded = encode_corpus(
                    sentences, vocab, encode_cache_dir, cfg.max_sentence_length)
        else:
            if iter(sentences) is sentences:
                sentences = list(sentences)
            encoded = encode_sentences(sentences, vocab, cfg.max_sentence_length)
        if streamed is not None:
            params = streamed
        else:
            if data["syn1"] is None:
                raise ValueError("checkpoint has no syn1; cannot resume training")
            import jax.numpy as jnp
            params = EmbeddingPair(
                jnp.asarray(data["syn0"]), jnp.asarray(data["syn1"]))
        trainer = Trainer(cfg, vocab, plan=plan, params=params, train_state=state)
        if not state.finished:
            # pass checkpoint_every_steps explicitly to keep periodic checkpointing
            # alive across the resumed run — the cadence is a fit() argument, not
            # persisted in the checkpoint, so it cannot be inherited
            trainer.fit(encoded, checkpoint_path=checkpoint_path,
                        checkpoint_every_steps=checkpoint_every_steps)
        out = trainer.unpadded_params()
        return Word2VecModel(
            vocab=vocab, syn0=out.syn0, syn1=out.syn1, config=cfg,
            plan=trainer.plan, train_state=trainer.state)
