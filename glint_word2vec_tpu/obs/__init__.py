"""Run-telemetry subsystem (docs/observability.md).

Four layers, each usable alone, all off by default and zero-cost when off:

- :mod:`.probe` — the fused on-device health reduction over the params carry
  (finiteness + per-matrix row-norm channels), the instrumentation ROADMAP
  item 2 names as the first step against the measured finite norm blowup.
- :mod:`.watch` — the finite-blowup watchdog (``config.norm_watch``) that
  fires on the probe channels where the non-finite guardrail stays silent.
- :mod:`.sink` + :mod:`.schema` — the schema-versioned JSONL run log
  (rotating file, never stdout — graftlint R7).
- :mod:`.spans` — thread-safe host trace spans exported as Chrome-trace JSON
  (Perfetto-loadable).
"""

from glint_word2vec_tpu.obs.probe import HealthStats, make_health_probe
from glint_word2vec_tpu.obs.schema import SCHEMA_VERSION, validate_file, validate_record
from glint_word2vec_tpu.obs.sink import TelemetrySink
from glint_word2vec_tpu.obs.spans import Tracer, default_tracer
from glint_word2vec_tpu.obs.watch import NormWatchdog

__all__ = [
    "HealthStats", "make_health_probe",
    "SCHEMA_VERSION", "validate_file", "validate_record",
    "TelemetrySink", "Tracer", "default_tracer", "NormWatchdog",
]
