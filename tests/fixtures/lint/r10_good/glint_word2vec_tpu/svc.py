"""R10 good twin: the PR 9 FIX shape. dump() takes include_stats and only
touches the lock inside `if include_stats:`; the handler passes the literal
include_stats=False, so the rule's one-deep constant propagation prunes the
locked branch and the handler closure is lock-free."""
import signal

from glint_word2vec_tpu.lockcheck import make_lock


class Recorder:
    def __init__(self):
        self._lock = make_lock("ring")
        self._events = []

    def record(self, e):
        with self._lock:
            self._events.append(e)

    def dump(self, include_stats=True):
        out = {"n": -1}
        if include_stats:
            with self._lock:
                out["n"] = len(self._events)
        return out


class Daemon:
    def __init__(self):
        self._rec = Recorder()

    def install(self):
        signal.signal(signal.SIGTERM, self._on_sigterm)

    def _on_sigterm(self, signum, frame):
        self._rec.dump(include_stats=False)
