"""Profile the e2e host pipeline: where do the pairs/s go between step and trainer?

Stages measured on the bench corpus (4M words, 50k vocab, Zipf):
    producer-only  — drain the Trainer's chunk_stream with no device work at all:
                     the host-side ceiling for any amount of pipelining
    pairgen-only   — raw epoch_batches drain (no K-stacking/packing/alpha)
    e2e fit        — the real thing (3 trials, median), with host-wait/dispatch split

Since round 13 the e2e leg RIDES THE TELEMETRY LAYER (docs/observability.md)
instead of private timers: each trial runs with a sink + spans + the
per-phase log2 histograms armed, and the report is the same per-phase
attribution (producer-wait / stage / dispatch / device-block, p50/p99/total)
every telemetry-on production run gets — one owner of e2e profiling, so this
tool can never drift from what the run log says. The run artifacts
(`run.jsonl`, `.trace.json`) are left under --out (default: a temp dir) for
Perfetto/run_report.py; the CLI contract (flags, human output on stderr) is
unchanged.

Run on TPU: python tools/e2e_profile.py [--batch 65536] [--pool 512] [--k 32]
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=65536)
    ap.add_argument("--pool", type=int, default=512)
    ap.add_argument("--k", type=int, default=32)
    ap.add_argument("--prefetch", type=int, default=8)
    ap.add_argument("--logits", default="float32")
    ap.add_argument("--param-dtype", default="float32")
    ap.add_argument("--device-pairgen", action="store_true")
    ap.add_argument("--skip-host-stages", action="store_true")
    ap.add_argument("--skip-fit", action="store_true")
    ap.add_argument("--out", default="",
                    help="where the telemetry artifacts (run.jsonl + "
                         ".trace.json) land; default: a fresh temp dir")
    args = ap.parse_args()

    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.data.pipeline import encode_sentences, epoch_batches
    from glint_word2vec_tpu.data.vocab import build_vocab
    from glint_word2vec_tpu.train.trainer import Trainer

    out_dir = args.out or tempfile.mkdtemp(prefix="glint_e2e_profile_")
    os.makedirs(out_dir, exist_ok=True)
    run_log = os.path.join(out_dir, "run.jsonl")

    rng = np.random.default_rng(0)
    n_words, sent_len, vocab_sz = 4_000_000, 40, 50_000
    zipf = 1.0 / (np.arange(vocab_sz) + 10.0) ** 1.05
    ids = rng.choice(vocab_sz, size=n_words, p=zipf / zipf.sum())
    words = np.char.add("w", ids.astype("U8"))
    sentences = [list(words[i:i + sent_len]) for i in range(0, n_words, sent_len)]
    vocab = build_vocab(sentences, min_count=5)
    cfg = Word2VecConfig(
        vector_size=300, min_count=5, pairs_per_batch=args.batch,
        num_iterations=1, window=5, negatives=5, negative_pool=args.pool,
        steps_per_dispatch=args.k, seed=1, subsample_ratio=1e-4,
        prefetch_chunks=args.prefetch, logits_dtype=args.logits,
        param_dtype=args.param_dtype, device_pairgen=args.device_pairgen,
        telemetry_path=run_log)
    encoded = encode_sentences(sentences, vocab, cfg.max_sentence_length)

    trainer = Trainer(cfg, vocab)
    from glint_word2vec_tpu.data.native import native_available
    print(f"native pairgen: {native_available()}  device_pairgen: "
          f"{cfg.device_pairgen}  telemetry -> {run_log}", file=sys.stderr)
    if cfg.device_pairgen:
        print(f"tokens_per_step: {trainer._tokens_per_step}", file=sys.stderr)

    if not args.skip_host_stages:
        # --- pairgen-only ----------------------------------------------------
        t0 = time.perf_counter()
        pairs = 0
        for b in epoch_batches(encoded, vocab, pairs_per_batch=args.batch,
                               window=5, subsample_ratio=1e-4, seed=1,
                               iteration=1):
            pairs += b.num_real_pairs
        dt = time.perf_counter() - t0
        print(f"pairgen-only : {pairs:,} pairs in {dt:.2f}s -> "
              f"{pairs / dt:,.0f} pairs/s", file=sys.stderr)

        # --- producer-only (batch stream + packing, no device) ---------------
        t0 = time.perf_counter()
        pairs = 0
        K = cfg.steps_per_dispatch
        pending = 0
        pack = np.empty((K, 2, args.batch), trainer._pair_dtype)
        for b in trainer._batch_stream(encoded, 1):
            pack[pending % K, 0] = b["centers"]
            pack[pending % K, 1] = b["contexts"]
            pairs += b["real"]
            pending += 1
        dt = time.perf_counter() - t0
        print(f"producer-only: {pairs:,} pairs in {dt:.2f}s -> "
              f"{pairs / dt:,.0f} pairs/s (batch stream + packing)",
              file=sys.stderr)

    if args.skip_fit:
        return

    # --- full e2e, attributed through the telemetry layer --------------------
    import jax.numpy as jnp
    trainer.fit(encoded[:400])  # warm jit
    rates = []
    for trial in range(3):
        trainer.state = type(trainer.state)()
        trainer.pairs_trained = 0.0
        t0 = time.perf_counter()
        trainer.fit(encoded)
        float(jnp.sum(trainer.params.syn0[:128]))
        dt = time.perf_counter() - t0
        rates.append(trainer.pairs_trained / dt)
        print(f"  e2e trial {trial}: {trainer.pairs_trained:,.0f} pairs in {dt:.1f}s "
              f"-> {rates[-1]:,.0f} pairs/s [host-wait {trainer.host_wait_time:.2f}s "
              f"dispatch {trainer.dispatch_time:.2f}s]", file=sys.stderr)
        if not np.isfinite(float(jnp.sum(trainer.params.syn0[:1024]))):
            raise RuntimeError("diverged")
    print(f"e2e median: {float(np.median(rates)):,.0f} pairs/s", file=sys.stderr)
    # per-phase attribution of the LAST trial (obs/phases.py — the same
    # rollup the run log's run_end record carries)
    phases = trainer.last_run_stats.get("phases", {})
    for name in ("producer_wait", "stage", "dispatch", "device_block"):
        ph = phases.get(name)
        if not ph:
            continue
        print(f"  phase {name:14s} count {ph['count']:>6}  "
              f"total {ph['total_s']:8.2f}s  p50 {ph['p50_s']:.2e}s  "
              f"p99 {ph['p99_s']:.2e}s  max {ph['max_s']:.3f}s",
              file=sys.stderr)
    print(f"artifacts: {run_log} (+ .trace.json) — summarize with "
          f"tools/run_report.py, tail with tools/telemetry_tail.py",
          file=sys.stderr)


if __name__ == "__main__":
    main()
