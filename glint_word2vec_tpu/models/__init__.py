from glint_word2vec_tpu.models.word2vec import Word2VecModel
from glint_word2vec_tpu.models.estimator import Word2Vec
from glint_word2vec_tpu.models.compat import (
    ServerSideGlintWord2Vec,
    ServerSideGlintWord2VecModel,
)

__all__ = [
    "Word2VecModel",
    "Word2Vec",
    "ServerSideGlintWord2Vec",
    "ServerSideGlintWord2VecModel",
]
