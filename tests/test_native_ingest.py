"""Parity tests for the native C++ ingestion passes (native/ingest.cpp):
vocabulary counting and corpus encoding must be BIT-IDENTICAL to the Python
path on ASCII-whitespace token files — including tie ordering (count desc,
stable on first-seen), OOV dropping, empty lines, and max-sentence chunking."""

import os

import numpy as np
import pytest

from glint_word2vec_tpu.data import ingest_native
from glint_word2vec_tpu.data.corpus import TokenFileCorpus, encode_corpus
from glint_word2vec_tpu.data.vocab import Vocabulary, build_vocab, count_words

pytestmark = pytest.mark.skipif(
    not ingest_native.ingest_available(),
    reason="native ingest unavailable (no toolchain)")

CORPUS = """the quick brown fox jumps over the lazy dog
the the the
\tpad   spaced\ttokens here

rare1 rare2 rare1
a b c d e f g h i j k l m n o p q r s t u v w x y z
zz zz zz yy yy xx
"""


@pytest.fixture()
def corpus_file(tmp_path):
    p = tmp_path / "corpus.txt"
    p.write_text(CORPUS, encoding="utf-8")
    return str(p)


def _python_vocab(path, min_count):
    return Vocabulary.from_counter(
        count_words(TokenFileCorpus(path)), min_count)


def test_count_parity_and_tie_order(corpus_file):
    c = TokenFileCorpus(corpus_file)
    for mc in (1, 2, 3):
        got = build_vocab(c, min_count=mc)        # native path
        want = _python_vocab(corpus_file, mc)     # python Counter path
        assert got.words == want.words, mc
        np.testing.assert_array_equal(got.counts, want.counts)
        assert got.train_words_count == want.train_words_count


def test_encode_parity_including_chunking(corpus_file, tmp_path):
    c = TokenFileCorpus(corpus_file)
    vocab = build_vocab(c, min_count=2)           # drops the 26 rare singletons
    for msl in (1000, 4, 1):                      # incl. aggressive chunking
        nat_dir = str(tmp_path / f"nat{msl}")
        enc_nat = encode_corpus(c, vocab, nat_dir, msl)     # native path
        # python path: feed the parsed sentences (not a TokenFileCorpus) so the
        # native gate does not trigger
        py_dir = str(tmp_path / f"py{msl}")
        enc_py = encode_corpus(list(c), vocab, py_dir, msl)
        tn = np.memmap(os.path.join(nat_dir, "tokens.bin"), np.int32, "r")
        tp = np.memmap(os.path.join(py_dir, "tokens.bin"), np.int32, "r")
        on = np.memmap(os.path.join(nat_dir, "offsets.bin"), np.int64, "r")
        op = np.memmap(os.path.join(py_dir, "offsets.bin"), np.int64, "r")
        np.testing.assert_array_equal(np.asarray(tn), np.asarray(tp))
        np.testing.assert_array_equal(np.asarray(on), np.asarray(op))
        assert len(enc_nat) == len(enc_py)
        assert enc_nat.total_tokens == enc_py.total_tokens


def test_valid_utf8_multibyte_takes_native_path(tmp_path):
    """Accented words are plain multi-byte UTF-8 — byte-level tokens match
    Python's str tokens, so the native path applies and agrees."""
    p = tmp_path / "c.txt"
    p.write_text("österreich wien österreich\nüber über\n", encoding="utf-8")
    got = build_vocab(TokenFileCorpus(str(p)), min_count=1)
    want = _python_vocab(str(p), 1)
    assert got.words == want.words
    np.testing.assert_array_equal(got.counts, want.counts)
    assert "österreich" in got.index


def test_python_semantics_detector_falls_back(tmp_path, caplog):
    """Corpora whose tokenization differs between Python and the ASCII
    tokenizer — unicode whitespace, lone \\r line breaks, C0 separators —
    must be detected and produce the PYTHON path's result."""
    import logging
    cases = [
        "foo\u00a0bar baz\n",       # NBSP: Python splits it, ASCII would not
        "foo\rbar\n",               # lone \r: a Python line break
        "foo\u2028bar\n",           # LINE SEPARATOR
        "a\x1cb\n",                 # C0 file separator (Python-split space)
        "foo\u202fbar baz\n",       # NARROW NBSP (the easy one to miss)
    ]
    for text in cases:
        p = tmp_path / "c.txt"
        p.write_text(text, encoding="utf-8", newline="")
        with caplog.at_level(logging.INFO, logger="glint_word2vec_tpu"):
            got = build_vocab(TokenFileCorpus(str(p)), min_count=1)
        want = _python_vocab(str(p), 1)
        assert got.words == want.words, text.encode()
        np.testing.assert_array_equal(got.counts, want.counts)


def test_lowercase_corpora_stay_on_python_path(tmp_path, monkeypatch):
    """The native tokenizer is ASCII-only; lowercase=True needs Python's
    unicode lower(), so the gate must keep such corpora off the native path."""
    p = tmp_path / "c.txt"
    p.write_text("The QUICK fox\nThe fox\n", encoding="utf-8")
    c = TokenFileCorpus(str(p), lowercase=True)
    vocab = build_vocab(c, min_count=1)
    assert "the" in vocab.index and "The" not in vocab.index


def test_disable_native_env_falls_back(corpus_file, monkeypatch):
    monkeypatch.setenv("GLINT_DISABLE_NATIVE", "1")
    monkeypatch.setattr(ingest_native, "_lib", None)
    monkeypatch.setattr(ingest_native, "_load_failed", False)
    try:
        assert not ingest_native.ingest_available()
        got = build_vocab(TokenFileCorpus(corpus_file), min_count=1)
        want = _python_vocab(corpus_file, 1)
        assert got.words == want.words
    finally:
        monkeypatch.setattr(ingest_native, "_lib", None)
        monkeypatch.setattr(ingest_native, "_load_failed", False)
