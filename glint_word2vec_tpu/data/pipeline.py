"""Host-side data pipeline: index → subsample → dynamic window → fixed-shape pair batches.

Replaces the reference's three per-iteration RDD stages (components C4/C5/C6):

- sentence indexing + chunking to maxSentenceLength (mllib:335-343),
- frequency subsampling (mllib:371-379),
- dynamic context-window generation (mllib:381-390),

with vectorized NumPy producing **fixed-shape padded (center, context, mask) batches** — the
shape discipline jit/pjit needs, replacing the reference's ragged Scala arrays.

Behavioral notes vs. the reference (intentional divergences, each covered by a unit test):

- Subsampling: the reference computes ``percentageCn = vocabCns(word) / trainWordsCount`` in
  *integer* division (mllib:374-376, Int/Long → Long), which truncates to 0 and makes the
  keep-probability +Inf — i.e. subsampling in the reference is a silent no-op. We implement
  the evidently intended float formula ``keep = (sqrt(pct/ratio) + 1) * (ratio/pct)`` with
  ``pct = count/train_words_count`` (the classic word2vec rule the code was transcribing).
- Window: the reference draws ``b = nextInt(window)`` (uniform 0..window-1) and takes context
  positions ``[max(0, i-b), min(i+b, len))`` excluding ``i`` (mllib:384-388) — note the upper
  bound is *exclusive*, so the right context is one short (b-1 words). We reproduce this
  exactly by default for parity (``legacy_asymmetric_window=True``); the symmetric variant is
  available for quality.
- RNG: the reference's per-partition XORShift seeding (``seed ^ ((idx+1)<<16) ^ ((-k-1)<<8)``,
  mllib:372) is reproduced in spirit: each (iteration, shard) gets an independent
  ``numpy.random.Generator`` derived from (seed, iteration, shard) so runs are deterministic.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Iterator, List, Sequence, Tuple

import numpy as np

from glint_word2vec_tpu.data.vocab import Vocabulary


def ordered_pool_map(fn, jobs: Iterable, workers: int, ahead: int = 2):
    """Map ``fn`` over ``jobs`` on a thread pool, yielding results in job order.

    The host feed's parallelism primitive (PERF.md §10): every job is a pure
    function of its inputs (the streams are position-keyed — hashrng — not
    sequential-RNG), so running them concurrently and consuming in submission
    order yields the bit-identical stream at ANY worker count. ``workers <= 1``
    degrades to a plain serial loop (no pool, no thread — exactly the
    pre-round-8 producer). At most ``workers + ahead`` jobs are in flight, so
    a slow consumer bounds memory.
    """
    if workers <= 1:
        for job in jobs:
            yield fn(job)
        return
    import collections
    from concurrent.futures import ThreadPoolExecutor

    pool = ThreadPoolExecutor(max_workers=workers,
                              thread_name_prefix="glint-feed-worker")
    pending: "collections.deque" = collections.deque()
    try:
        cap = workers + ahead
        for job in jobs:
            pending.append(pool.submit(fn, job))
            if len(pending) >= cap:
                yield pending.popleft().result()
        while pending:
            yield pending.popleft().result()
    finally:
        while pending:
            pending.pop().cancel()
        pool.shutdown(wait=False, cancel_futures=True)


def stream_rng(seed: int, iteration: int, shard: int) -> np.random.Generator:
    """The batch stream's RNG: deterministic per (seed, iteration, shard) — the analog
    of the reference's XORShift reseed ``seed ^ ((idx+1)<<16) ^ ((-k-1)<<8)``
    (mllib:372,382). The uint64 mask is the single place the host pipeline normalizes
    user seeds (compat setSeed accepts the reference's full Long surface, and
    SeedSequence rejects negative entropy); the device-side negative sampler applies
    its own uint32 mask in the trainer — the two streams are independent by design."""
    return np.random.default_rng(
        np.random.SeedSequence(entropy=seed & 0xFFFFFFFFFFFFFFFF,
                               spawn_key=(iteration, shard)))


def encode_sentences(
    sentences: Iterable[Sequence[str]],
    vocab: Vocabulary,
    max_sentence_length: int = 1000,
) -> List[np.ndarray]:
    """Words → vocab indices, OOV dropped, chunked to max_sentence_length (mllib:335-343)."""
    index = vocab.index
    out: List[np.ndarray] = []
    for sentence in sentences:
        ids = [index[w] for w in sentence if w in index]
        if not ids:
            continue
        arr = np.asarray(ids, dtype=np.int32)
        for start in range(0, len(arr), max_sentence_length):
            chunk = arr[start:start + max_sentence_length]
            if chunk.size:
                out.append(chunk)
    return out


def keep_probabilities(
    counts: np.ndarray, train_words_count: int, subsample_ratio: float
) -> np.ndarray:
    """Per-word keep probability ``(sqrt(pct/ratio)+1)*(ratio/pct)`` (intended semantics of
    mllib:374-377; see module docstring for the reference's integer-division bug)."""
    if subsample_ratio <= 0:
        return np.ones(counts.shape[0], dtype=np.float64)  # disabled (the reference's
        # observed behavior at any setting, due to its integer-division bug)
    pct = counts.astype(np.float64) / float(train_words_count)
    ratio = float(subsample_ratio)
    keep = (np.sqrt(pct / ratio) + 1.0) * (ratio / pct)
    return np.minimum(keep, 1.0)


def expected_kept_words(
    counts: np.ndarray, train_words_count: int, subsample_ratio: float
) -> int:
    """Expected number of words surviving subsampling per iteration — the lr-decay clock
    total. The reference uses the raw trainWordsCount (mllib:363) because its subsampling
    keeps everything (no-op bug); with real subsampling the clock must count what the
    stream actually yields or alpha never reaches its floor."""
    keep = keep_probabilities(counts, train_words_count, subsample_ratio)
    return int(np.round((counts * keep).sum()))


def subsample_sentence(
    sentence: np.ndarray, keep_prob: np.ndarray, rng: np.random.Generator
) -> np.ndarray:
    """Drop frequent words: keep word w with probability keep_prob[w] (mllib:371-379)."""
    draws = rng.random(sentence.shape[0])
    return sentence[draws <= keep_prob[sentence]]


def dynamic_window_pairs(
    sentence: np.ndarray,
    window: int,
    rng: np.random.Generator,
    legacy_asymmetric_window: bool = True,
) -> Tuple[np.ndarray, np.ndarray]:
    """(center, context) index pairs with per-position random window shrink.

    Reference behavior (mllib:384-388): ``b = nextInt(window)`` ∈ [0, window), context
    positions ``p ∈ [max(0, i-b), min(i+b, len))``, ``p != i`` — i.e. b words of left
    context, b-1 of right. With ``legacy_asymmetric_window=False`` the right bound becomes
    inclusive (b both sides), the classic word2vec shape.

    Vectorized: per-position left/right context lengths → ragged arange, no Python loop.
    Returns (centers, contexts), both int32 [num_pairs].
    """
    L = sentence.shape[0]
    if L == 0:
        return (np.empty(0, np.int32), np.empty(0, np.int32))
    positions = np.arange(L, dtype=np.int64)
    b = rng.integers(0, window, size=L)  # nextInt(window): 0..window-1
    left = np.minimum(b, positions)
    right_extent = b if not legacy_asymmetric_window else b - 1
    right = np.clip(np.minimum(right_extent, L - 1 - positions), 0, None)
    total = left + right
    num_pairs = int(total.sum())
    if num_pairs == 0:
        return (np.empty(0, np.int32), np.empty(0, np.int32))
    centers_pos = np.repeat(positions, total)
    # Ragged per-group offset 0..total_i-1
    group_starts = np.cumsum(total) - total
    offsets = np.arange(num_pairs, dtype=np.int64) - np.repeat(group_starts, total)
    left_rep = np.repeat(left, total)
    # offsets < left → left context (i-left+k); offsets >= left → right context, skip center
    ctx_pos = centers_pos - left_rep + offsets + (offsets >= left_rep)
    return (sentence[centers_pos].astype(np.int32), sentence[ctx_pos].astype(np.int32))


@dataclass
class PairBatch:
    """One fixed-shape device batch of training pairs.

    mask is 1.0 for real pairs, 0.0 for padding; padded center/context indices are 0 but
    contribute zero gradient because the step multiplies through by mask.
    ``words_seen`` is the cumulative count of (subsampled) training words up to and including
    this batch within the current shard — the reference's ``wordCount`` lr-decay clock
    (mllib:405-413).
    """

    centers: np.ndarray    # int32 [B]
    contexts: np.ndarray   # int32 [B]
    mask: np.ndarray       # float32 [B]
    words_seen: int
    num_real_pairs: int


class PairBatcher:
    """Accumulates N parallel ragged streams into fixed-size batches along axis 0.

    Used with 2 streams (centers, contexts) for skip-gram and 3 (centers, contexts [B,C],
    ctx_mask [B,C]) for CBOW — one implementation of the accumulate / slice-full-batches /
    carry-remainder / pad-last invariants.
    """

    def __init__(self, pairs_per_batch: int, num_streams: int = 2):
        self.B = int(pairs_per_batch)
        self.num_streams = num_streams
        self._bufs: List[List[np.ndarray]] = [[] for _ in range(num_streams)]
        self._buffered = 0

    def add(self, *arrays: np.ndarray) -> None:
        assert len(arrays) == self.num_streams
        if arrays[0].shape[0] == 0:
            return
        for buf, arr in zip(self._bufs, arrays):
            buf.append(arr)
        self._buffered += arrays[0].shape[0]

    def _pop_full(self) -> Iterator[Tuple]:
        if self._buffered < self.B:
            return
        cats = [np.concatenate(buf) for buf in self._bufs]
        n_full = cats[0].shape[0] // self.B
        for i in range(n_full):
            sl = slice(i * self.B, (i + 1) * self.B)
            yield (*(c[sl] for c in cats), self.B)
        rest = [c[n_full * self.B:] for c in cats]
        self._buffered = rest[0].shape[0]
        self._bufs = [[r] if self._buffered else [] for r in rest]

    def drain(self, flush: bool = False) -> Iterator[Tuple]:
        """Yields ``(*stream_slices, num_real)`` tuples of exactly B rows each. With
        ``flush``, the remainder is zero-padded to B and ``num_real < B`` marks it."""
        yield from self._pop_full()
        if flush and self._buffered:
            cats = [np.concatenate(buf) for buf in self._bufs]
            n = cats[0].shape[0]
            pad = self.B - n
            padded = [
                np.concatenate([c, np.zeros((pad, *c.shape[1:]), c.dtype)])
                for c in cats
            ]
            self._bufs = [[] for _ in range(self.num_streams)]
            self._buffered = 0
            yield (*padded, n)


def _block_pairs(
    tokens: np.ndarray,          # int32 [N] concatenated sentence tokens
    lengths: np.ndarray,         # int64 [S] sentence lengths (sum == N)
    keep: np.ndarray,            # float32 [V] per-word keep probability
    window: int,
    seed: int,
    iteration: int,
    shard: int,
    token_base: int,             # raw-token ordinal of this block's first token
    legacy_asymmetric_window: bool,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Subsample + dynamic-window pair generation for a whole block of sentences in a
    handful of vectorized ops (no per-sentence Python loop — the hot host path; a
    per-sentence equivalent exists as :func:`subsample_sentence` +
    :func:`dynamic_window_pairs` for unit-testing the formulas). All randomness is
    position-keyed (:mod:`.hashrng`), so the native C++ generator
    (``native/pairgen.cpp``) produces this exact stream in parallel.

    Returns (centers, contexts, center_word_index, words_kept) where
    ``center_word_index[p]`` is the kept-word ordinal (within this block) of pair p's
    center — the per-pair lr-decay clock, so downstream batches can credit exactly the
    words consumed *up to each batch* rather than the whole block at once."""
    prologue = _subsample_and_window(
        tokens, lengths, keep, window, seed, iteration, shard, token_base,
        legacy_asymmetric_window)
    if prologue is None:
        return (np.empty(0, np.int32), np.empty(0, np.int32),
                np.empty(0, np.int64), 0)
    toks, left, total, Nk = prologue
    num_pairs = int(total.sum())
    if num_pairs == 0:
        return (np.empty(0, np.int32), np.empty(0, np.int32),
                np.empty(0, np.int64), int(Nk))
    center_flat = np.repeat(np.arange(Nk, dtype=np.int64), total)
    group_starts = np.cumsum(total) - total
    offsets = np.arange(num_pairs, dtype=np.int64) - np.repeat(group_starts, total)
    left_rep = np.repeat(left, total)
    ctx_flat = center_flat - left_rep + offsets + (offsets >= left_rep)
    return (toks[center_flat].astype(np.int32), toks[ctx_flat].astype(np.int32),
            center_flat + 1, int(Nk))


def _subsample_and_window(
    tokens: np.ndarray,
    lengths: np.ndarray,
    keep: np.ndarray,
    window: int,
    seed: int,
    iteration: int,
    shard: int,
    token_base: int,
    legacy_asymmetric_window: bool,
):
    """Shared prologue of :func:`_block_pairs` and :func:`_block_cbow` — one place
    owns the subsample/window stream contract (mirrored bit-identically by
    native/pairgen.cpp and ops/pairgen.py).

    Returns (kept_tokens, left, total, Nk) where ``left[i]``/``total[i]`` are pair
    counts to the left / in total of kept position i under the per-position window
    draw, or None for an empty block."""
    from glint_word2vec_tpu.data.hashrng import (
        STREAM_SUBSAMPLE, STREAM_WINDOW, hash_mod_at, hash_u01_at, stream_base)

    N = tokens.shape[0]
    if N == 0:
        return None
    ordinals = np.arange(token_base, token_base + N, dtype=np.uint64)
    sent_ids = np.repeat(np.arange(lengths.shape[0]), lengths)
    # subsample the whole block at once (mllib:371-379 semantics)
    sub_base = stream_base(seed, STREAM_SUBSAMPLE, iteration, shard)
    kept_mask = hash_u01_at(sub_base, ordinals) <= keep.astype(np.float32)[tokens]
    toks = tokens[kept_mask]
    sids = sent_ids[kept_mask]
    Nk = toks.shape[0]
    if Nk == 0:
        return None
    # per-sentence positions after subsampling
    new_lengths = np.bincount(sids, minlength=lengths.shape[0])
    new_starts = np.concatenate([[0], np.cumsum(new_lengths)])[:-1]
    pos = np.arange(Nk, dtype=np.int64) - new_starts[sids]
    slen = new_lengths[sids]
    # dynamic window draw (mllib:384-388), keyed by the RAW token ordinal so draws
    # are independent of the subsample outcome of other positions
    win_base = stream_base(seed, STREAM_WINDOW, iteration, shard)
    b = hash_mod_at(win_base, ordinals[kept_mask], window)
    left = np.minimum(b, pos)
    right_extent = b if not legacy_asymmetric_window else b - 1
    right = np.clip(np.minimum(right_extent, slen - 1 - pos), 0, None)
    total = (left + right).astype(np.int64)
    return toks, left, total, int(Nk)


def epoch_batches(
    sentences: Sequence[np.ndarray],
    vocab: Vocabulary,
    *,
    pairs_per_batch: int,
    window: int,
    subsample_ratio: float = 0.0,
    seed: int = 0,
    iteration: int = 1,
    shard: int = 0,
    num_shards: int = 1,
    shuffle: bool = True,
    legacy_asymmetric_window: bool = True,
    flush_last: bool = True,
    block_words: int = 1_000_000,
    backend: str = "auto",   # "auto" | "numpy" | "native" (C++ generator if built)
    producer_workers: int = 1,
) -> Iterator[PairBatch]:
    """One iteration's stream of fixed-shape pair batches for one data shard.

    Mirrors the reference's per-iteration pipeline (mllib:367-390): fresh subsample + fresh
    window draw each iteration, deterministic per (seed, iteration, shard) — the analog of
    the XORShift reseed ``seed ^ ((idx+1)<<16) ^ ((-k-1)<<8)`` at mllib:372,382.

    Sentences are round-robin assigned to shards (the analog of repartition, mllib:345)
    and processed in ~``block_words``-word blocks, each block fully vectorized
    (:func:`_block_pairs`) or handed to the multithreaded native generator
    (``native/pairgen.cpp``, bit-identical stream) — the host must outrun a TPU
    consuming millions of pairs/s.

    ``producer_workers > 1`` fans the per-slab generation across a thread pool
    (:func:`ordered_pool_map`): every slab's output is a pure function of
    (tokens, lengths, token_base) under the position-keyed hashrng draws, so
    the merged stream is bit-identical to the serial one at any worker count —
    only the batching/clock accumulation below stays serial. The NATIVE
    backend already fans each call over ``default_threads()`` C++ threads, so
    the pooled path DIVIDES that budget across the concurrent calls
    (``n_threads = default_threads() // workers``) — pools compose instead of
    multiplying into N×M oversubscription; the native stream is deterministic
    at any thread count.
    """
    if backend == "auto":
        from glint_word2vec_tpu.data.native import native_available
        use_native = native_available()
    else:
        use_native = backend == "native"
    if use_native:
        from glint_word2vec_tpu.data.native import block_pairs_native
    rng = stream_rng(seed, iteration, shard)
    keep = keep_probabilities(
        vocab.counts, vocab.train_words_count, subsample_ratio).astype(np.float32)
    order = np.arange(shard, len(sentences), num_shards)
    if shuffle:
        rng.shuffle(order)
    batcher = PairBatcher(pairs_per_batch, num_streams=3)
    words_base = 0   # kept words fully consumed in prior blocks
    words_seen = 0
    native_threads = 0
    if use_native and producer_workers > 1:
        from glint_word2vec_tpu.data.native import default_threads
        native_threads = max(1, default_threads() // producer_workers)

    def slab_jobs():
        token_base = 0  # raw tokens consumed in prior blocks (position-key base)
        for block in iter_sentence_slabs(sentences, order, block_words):
            yield block, token_base
            token_base += sum(int(s.shape[0]) for s in block)

    def run_slab(job):
        block, token_base = job
        tokens = np.concatenate(block) if len(block) > 1 else block[0]
        lengths = np.fromiter((s.shape[0] for s in block), np.int64, len(block))
        if use_native:
            return block_pairs_native(
                tokens, lengths, keep, window, seed, iteration, shard,
                token_base, legacy_asymmetric_window,
                n_threads=native_threads)
        return _block_pairs(tokens, lengths, keep, window, seed, iteration,
                            shard, token_base, legacy_asymmetric_window)

    for c, x, clock, kept in ordered_pool_map(
            run_slab, slab_jobs(), producer_workers):
        # The reference counts *subsampled* words into its decay clock (mllib:414); the
        # per-pair clock credits words as their pairs are actually emitted, so alpha
        # advances per batch, not per block.
        batcher.add(c, x, words_base + clock)
        words_base += kept
        for bc, bx, bclock, n in batcher.drain():
            mask = np.ones(pairs_per_batch, np.float32)
            words_seen = int(bclock[n - 1])
            yield PairBatch(bc, bx, mask, words_seen, n)
    for bc, bx, bclock, n in batcher.drain(flush=flush_last):
        mask = (np.arange(pairs_per_batch) < n).astype(np.float32)
        words_seen = int(bclock[n - 1]) if n else words_seen
        yield PairBatch(bc, bx, mask, words_seen, n)
    # trailing subsampled words with no emitted pairs still count toward the clock for
    # the *next* iteration's prev_words baseline — callers use iteration boundaries, so
    # nothing further to emit here


def iter_sentence_slabs(
    sentences: Sequence[np.ndarray],
    order: np.ndarray,
    block_words: int = 1_000_000,
) -> Iterator[List[np.ndarray]]:
    """Whole-sentence slabs of ~``block_words`` raw tokens in the given order — the
    vectorization granule shared by the host pair pipeline (:func:`epoch_batches`)
    and the device-feed packer (train/trainer._fit_device_feed), so their stream
    contracts stay aligned on one slab rule."""
    slab: List[np.ndarray] = []
    nwords = 0
    for si in order:
        s = sentences[si]
        slab.append(s)
        nwords += s.shape[0]
        if nwords >= block_words:
            yield slab
            slab, nwords = [], 0
    if slab:
        yield slab


def count_train_words(sentences: Sequence[np.ndarray]) -> int:
    return int(sum(int(s.shape[0]) for s in sentences))


# ---------------------------------------------------------------------------------------
# CBOW variant (BASELINE config 5): grouped context windows instead of flat pairs.
# ---------------------------------------------------------------------------------------


def dynamic_window_cbow(
    sentence: np.ndarray,
    window: int,
    rng: np.random.Generator,
    legacy_asymmetric_window: bool = True,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-position padded context windows for CBOW.

    Same window draw as :func:`dynamic_window_pairs` (so skip-gram and CBOW see identical
    context structure), but grouped per center: returns (centers [L], contexts [L, C],
    ctx_mask [L, C]) with C = 2·window. Positions with zero context are dropped.
    """
    L = sentence.shape[0]
    C = 2 * window
    if L == 0:
        return (np.empty(0, np.int32), np.empty((0, C), np.int32),
                np.empty((0, C), np.float32))
    positions = np.arange(L, dtype=np.int64)
    b = rng.integers(0, window, size=L)
    left = np.minimum(b, positions)
    right_extent = b if not legacy_asymmetric_window else b - 1
    right = np.clip(np.minimum(right_extent, L - 1 - positions), 0, None)
    total = left + right
    num_pairs = int(total.sum())
    contexts = np.zeros((L, C), dtype=np.int32)
    ctx_mask = np.zeros((L, C), dtype=np.float32)
    if num_pairs:
        group_starts = np.cumsum(total) - total
        offsets = np.arange(num_pairs, dtype=np.int64) - np.repeat(group_starts, total)
        rows = np.repeat(positions, total)
        left_rep = np.repeat(left, total)
        ctx_pos = rows - left_rep + offsets + (offsets >= left_rep)
        contexts[rows, offsets] = sentence[ctx_pos]
        ctx_mask[rows, offsets] = 1.0
    keep = total > 0
    return (sentence[keep].astype(np.int32), contexts[keep], ctx_mask[keep])


def _block_cbow(
    tokens: np.ndarray,          # int32 [N] concatenated sentence tokens
    lengths: np.ndarray,         # int64 [S] sentence lengths (sum == N)
    keep: np.ndarray,            # float32 [V] per-word keep probability
    window: int,
    seed: int,
    iteration: int,
    shard: int,
    token_base: int,
    legacy_asymmetric_window: bool,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, int]:
    """CBOW analog of :func:`_block_pairs`: whole-slab vectorized subsample + grouped
    context windows — no per-sentence Python loop (which starved a >5M-example/s
    device consumer ~5x), and the same position-keyed hashrng draws, so the stream
    is deterministic per (seed, iteration, shard) and block-size independent.

    Returns (centers [Nk], contexts [Nk, 2*window] left-packed, n_ctx [Nk],
    center_word_index [Nk], words_kept). Positions with zero context are dropped
    (the per-sentence generator does the same)."""
    C = 2 * window
    empty = (np.empty(0, np.int32), np.empty((0, C), np.int32),
             np.empty(0, np.int32), np.empty(0, np.int64), 0)
    prologue = _subsample_and_window(
        tokens, lengths, keep, window, seed, iteration, shard, token_base,
        legacy_asymmetric_window)
    if prologue is None:
        return empty
    toks, left, total, Nk = prologue
    j = np.arange(C, dtype=np.int64)[None, :]
    ctx_pos = np.where(j < left[:, None],
                       np.arange(Nk, dtype=np.int64)[:, None] - left[:, None] + j,
                       np.arange(Nk, dtype=np.int64)[:, None] + j - left[:, None] + 1)
    valid = j < total[:, None]
    contexts = np.where(valid, toks[np.clip(ctx_pos, 0, Nk - 1)], 0).astype(np.int32)
    has_ctx = total > 0
    return (toks[has_ctx].astype(np.int32), contexts[has_ctx],
            total[has_ctx].astype(np.int32),
            np.flatnonzero(has_ctx) + 1, int(Nk))


def pack_halo_token_blocks(
    slabs: Iterable[Tuple[np.ndarray, np.ndarray]],
    T: int,
    halo: int,
    tok_dtype=np.int32,
) -> Iterator[Tuple[np.ndarray, np.ndarray, int, int, int]]:
    """Sentence-contiguous [T]-slot token blocks with a ±``halo`` overlap — the
    feed granule of the banded CBOW step (ops/cbow_banded.py).

    ``slabs`` yields (kept_tokens, start_flags) chunks of the kept-token stream
    (already subsampled; ``start_flags[i]`` True iff a sentence begins at that
    token — the stream's first token must carry a flag). Blocks advance by the
    CORE width ``Tc = T − 2·halo``: block k holds kept positions
    ``[k·Tc − halo, k·Tc − halo + T)``, so every kept token is a **core** slot
    (``[halo, T−halo)``) of exactly one block and a halo slot of its neighbors.
    With ``halo ≥ window`` the overlap makes chunk-edge windows EXACT — both the
    forward context mean and the backward context gradient of a center near a
    cut see/reach their cross-cut neighbors (contrast the non-overlapping
    skip-gram device feed, which loses ~0.02% of windows at the cuts).

    Pre-stream slots of block 0 (positions < 0) are zero tokens with no start
    bits; they are never centers (core slots begin at slot ``halo`` = stream
    position 0) and never contexts (the stream-start start bit clamps every
    real window at position 0), so they ride as inert padding inside the valid
    prefix.

    Yields ``(tokens[T], start_bits, n_valid, ordinal_base, n_core)`` per
    block: ``n_valid`` counts the valid slot prefix, ``ordinal_base`` is the
    kept-token ordinal of slot 0 (wrapped to uint64 — block 0's is −halo), and
    ``n_core`` the NEW core tokens this block trains (the lr-clock increment;
    overlap slots are not re-counted).
    """
    if halo <= 0:
        raise ValueError(f"halo must be positive, got {halo}")
    Tc = T - 2 * halo
    if Tc <= 0:
        raise ValueError(f"T={T} leaves no core slots at halo={halo}")
    buf_tok = np.zeros(halo, tok_dtype)   # virtual pre-stream slots of block 0
    buf_start = np.zeros(halo, bool)
    bpos = -halo                          # stream position of buf[0]

    def emit(n_core: int):
        n = min(buf_tok.shape[0], T)
        tokens = np.zeros(T, tok_dtype)
        tokens[:n] = buf_tok[:n]
        bits = np.packbits(np.pad(buf_start[:n], (0, T - n)),
                           bitorder="little")
        return (tokens, bits, n, bpos & 0xFFFFFFFFFFFFFFFF, n_core)

    for ktoks, kstart in slabs:
        if ktoks.shape[0] == 0:
            continue
        buf_tok = np.concatenate([buf_tok, ktoks.astype(tok_dtype)])
        buf_start = np.concatenate([buf_start, kstart])
        while buf_tok.shape[0] >= T:
            yield emit(Tc)
            buf_tok = buf_tok[Tc:]
            buf_start = buf_start[Tc:].copy()
            bpos += Tc
    # flush: emit while un-centered core positions remain (len > halo ⟺ some
    # stream token at position ≥ bpos + halo has not been a core slot yet)
    while buf_tok.shape[0] > halo:
        yield emit(min(buf_tok.shape[0] - halo, Tc))
        buf_tok = buf_tok[Tc:]
        buf_start = buf_start[Tc:].copy()
        bpos += Tc


@dataclass
class CbowBatch:
    centers: np.ndarray    # int32 [B]
    contexts: np.ndarray   # int32 [B, C] — LEFT-PACKED: real slots first
    n_ctx: np.ndarray      # int32 [B] — real context count; ctx_mask = iota < n_ctx
                           # (shipping the count instead of a [B, C] float mask cuts
                           # the CBOW feed bytes ~40x; the device rebuilds the mask)
    mask: np.ndarray       # float32 [B]
    words_seen: int
    num_real: int

    @property
    def ctx_mask(self) -> np.ndarray:
        C = self.contexts.shape[1]
        return (np.arange(C)[None, :] < self.n_ctx[:, None]).astype(np.float32)


def epoch_batches_cbow(
    sentences: Sequence[np.ndarray],
    vocab: Vocabulary,
    *,
    pairs_per_batch: int,
    window: int,
    subsample_ratio: float = 0.0,
    seed: int = 0,
    iteration: int = 1,
    shard: int = 0,
    num_shards: int = 1,
    shuffle: bool = True,
    legacy_asymmetric_window: bool = True,
    block_words: int = 1_000_000,
    producer_workers: int = 1,
) -> Iterator[CbowBatch]:
    """CBOW analog of :func:`epoch_batches`: fixed-shape [B, 2·window] context
    batches, block-vectorized (:func:`_block_cbow`) with the same position-keyed
    hashrng stream — deterministic per (seed, iteration, shard), no per-sentence
    Python loop, and sharded exactly like the skip-gram feed (the multi-process
    allgather protocol consumes either). ``producer_workers``: same per-slab
    thread-pool fan-out (and bit-identity contract) as :func:`epoch_batches`."""
    B = int(pairs_per_batch)
    rng = stream_rng(seed, iteration, shard)
    keep = keep_probabilities(
        vocab.counts, vocab.train_words_count, subsample_ratio).astype(np.float32)
    order = np.arange(shard, len(sentences), num_shards)
    if shuffle:
        rng.shuffle(order)
    batcher = PairBatcher(B, num_streams=4)
    words_base = 0
    words_seen = 0

    def slab_jobs():
        token_base = 0
        for block in iter_sentence_slabs(sentences, order, block_words):
            yield block, token_base
            token_base += sum(int(s.shape[0]) for s in block)

    def run_slab(job):
        block, token_base = job
        tokens = np.concatenate(block) if len(block) > 1 else block[0]
        lengths = np.fromiter((s.shape[0] for s in block), np.int64, len(block))
        return _block_cbow(
            tokens, lengths, keep, window, seed, iteration, shard, token_base,
            legacy_asymmetric_window)

    for c, x, nc, clock, kept in ordered_pool_map(
            run_slab, slab_jobs(), producer_workers):
        batcher.add(c, x, nc, words_base + clock)
        words_base += kept
        for bc, bx, bn, bclock, n in batcher.drain():
            words_seen = int(bclock[n - 1])
            yield CbowBatch(bc, bx, bn, np.ones(B, np.float32), words_seen, n)
    for bc, bx, bn, bclock, n in batcher.drain(flush=True):
        words_seen = int(bclock[n - 1]) if n else words_seen
        yield CbowBatch(bc, bx, bn, (np.arange(B) < n).astype(np.float32),
                        words_seen, n)
