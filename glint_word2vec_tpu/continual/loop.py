"""The continual driver: watch corpus → extend vocab → incremental fit →
atomic publish — the loop that turns one-shot fits into a system that never
stops (ROADMAP item 5; docs/continual.md).

One :class:`ContinualRunner` owns a (checkpoint path, corpus stream, work
dir) triple. Each :meth:`run_once` cycle:

1. polls the append-only corpus stream for unconsumed segments
   (continual/stream.py) — nothing new → idle, no work;
2. counts the tail's words and computes the vocab delta against the
   checkpoint's vocabulary; at ``continual_min_new_words`` or more promoted
   words, migrates the checkpoint through
   :func:`~glint_word2vec_tpu.continual.extend.extend_checkpoint` — an
   ATOMIC in-place publish, so a watching ``EmbeddingService`` hot-reloads
   the grown model (new words servable with seeded vectors) before the
   incremental fit even starts; below the threshold, counts still merge
   (frequencies drifted — the next alias table must see them);
3. delta-encodes only the new tail under the (possibly grown) vocabulary —
   cached encodes of consumed segments stay valid through the lineage chain
   and are reused untouched, optionally replayed
   (``continual_replay_segments``);
4. runs the incremental fit: the checkpoint's params stream back in, the
   learning rate re-warms to ``learning_rate * continual_lr_rewarm`` and
   decays over the increment's own word clock, the PRNG lattice continues
   from the checkpoint's ``global_step`` (no negative-sample replay), and
   every save — periodic and final — carries the lineage chain and lands
   through the same atomic-swap publish signal PR 10's serving tier polls;
5. marks the tail consumed ONLY after the fit finished, so a SIGTERM
   mid-increment leaves a resumable published checkpoint and an unconsumed
   cursor — the next cycle simply retries the increment from the last
   published params (the extension re-run is a no-op: zero new words).

The runner is deliberately thread-free (one blocking loop, graftlint R1 has
nothing to sanction): run it as its own process
(``tools/continual_run.py``) beside the serving replicas, exactly the
trainer/server process split the deployment story already assumes.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Dict, Optional

import numpy as np

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.continual import extend as _extend
from glint_word2vec_tpu.continual.stream import (
    ConcatCorpus,
    CorpusStream,
    StreamCursor,
    encode_delta,
    encode_segment,
    segment_fingerprint,
)
from glint_word2vec_tpu.data.corpus import vocab_fingerprint
from glint_word2vec_tpu.data.vocab import (
    Vocabulary,
    count_words,
    merge_counts,
)

logger = logging.getLogger("glint_word2vec_tpu")


class ContinualRunner:
    """Drives continual train→publish cycles over an append-only corpus.

    ``checkpoint_path`` is the publish path serving replicas watch;
    ``corpus_dir`` the append-only segment directory; ``work_dir`` holds the
    cursor and the per-segment encode caches. ``config_overrides`` replace
    checkpoint-config fields for every increment (e.g. a different
    ``continual_lr_rewarm``); ``plan`` routes row-shards checkpoints
    straight onto a mesh. ``telemetry_path`` opens a runner-owned sink for
    the additive ``continual_*`` record kinds (obs/schema.py).
    """

    def __init__(
        self,
        checkpoint_path: str,
        corpus_dir: str,
        work_dir: str,
        plan=None,
        config_overrides: Optional[Dict[str, Any]] = None,
        checkpoint_every_steps: Optional[int] = None,
        telemetry_path: str = "",
    ):
        self.checkpoint_path = checkpoint_path
        self.stream = CorpusStream(corpus_dir)
        self.work_dir = work_dir
        self.plan = plan
        self.config_overrides = dict(config_overrides or {})
        self.checkpoint_every_steps = checkpoint_every_steps
        self.increments = 0
        self._sink = None
        if telemetry_path:
            from glint_word2vec_tpu.obs.sink import TelemetrySink
            self._sink = TelemetrySink(telemetry_path)
        os.makedirs(work_dir, exist_ok=True)
        self.cursor = StreamCursor(work_dir)

    # -- helpers -----------------------------------------------------------------------

    def _emit(self, kind: str, **fields) -> None:
        if self._sink is not None:
            self._sink.emit(kind, **fields)

    def _emit_publish(self, trainer) -> None:
        """The publish-side correlation record for the increment's final
        save (obs/trace.emit_publish): keyed by the on-disk publish_sig the
        serving watcher and fleet router compare, so tools/obs_collect.py
        joins this increment's publish to every replica's drain+reload."""
        if self._sink is not None:
            from glint_word2vec_tpu.obs.trace import emit_publish
            emit_publish(self._sink, self.checkpoint_path,
                         int(trainer.global_step), publisher="continual")

    def _cache_dir(self) -> str:
        return os.path.join(self.work_dir, "encode-cache")

    def _recovered_checkpoint(self) -> str:
        """The publish path, healed if the last save died mid-swap: the
        atomic protocol guarantees either the old or the new checkpoint
        verifies; torn-swap debris is reclaimed (the writer — us — is not
        running concurrently with this call by construction)."""
        from glint_word2vec_tpu.train.checkpoint import (
            load_latest_valid, verify_checkpoint)
        try:
            verify_checkpoint(self.checkpoint_path)
            return self.checkpoint_path
        except (FileNotFoundError, ValueError):
            recovered = load_latest_valid(
                os.path.dirname(os.path.abspath(self.checkpoint_path))
                or ".", reclaim=True)
            if recovered != self.checkpoint_path:
                logger.warning("recovered checkpoint at %s (expected %s)",
                               recovered, self.checkpoint_path)
            return recovered

    def _load_config(self, header: Dict[str, Any]) -> Word2VecConfig:
        cfg: Word2VecConfig = header["config"]
        if self.config_overrides:
            cfg = cfg.replace(**self.config_overrides)
        return cfg

    def _load_params(self, path: str, header: Dict[str, Any], cfg):
        """Checkpoint params as an EmbeddingPair ready for the Trainer —
        streamed onto the mesh for row-shards + plan (never a full host
        copy), host-loaded otherwise. Mirrors estimator.resume's split."""
        from glint_word2vec_tpu.ops.sgns import EmbeddingPair
        from glint_word2vec_tpu.train.checkpoint import (
            load_model, load_params_into_plan)
        if self.plan is not None and header["layout"] == "row-shards":
            from glint_word2vec_tpu.parallel.mesh import (
                pad_dim_to_lanes, pad_vocab_for_sharding)
            pv = pad_vocab_for_sharding(header["vocab_size"],
                                        self.plan.num_model)
            pd = pad_dim_to_lanes(cfg.vector_size, cfg.pad_vector_to_lanes)
            syn0, syn1 = load_params_into_plan(
                path, self.plan, pv, pd, dtype=np.dtype(cfg.param_dtype),
                io_workers=cfg.io_workers)
            if syn1 is None:
                raise ValueError(
                    "checkpoint has no syn1; cannot train an increment")
            return EmbeddingPair(syn0, syn1)
        data = load_model(path, header=header, io_workers=cfg.io_workers)
        if data["syn1"] is None:
            raise ValueError(
                "checkpoint has no syn1; cannot train an increment")
        return EmbeddingPair(data["syn0"], data["syn1"])

    # -- bootstrap ---------------------------------------------------------------------

    def ensure_base(self) -> Dict[str, Any]:
        """First-run bootstrap: when no checkpoint exists yet, fit a base
        model over every segment currently in the stream and publish it.
        Idempotent — with an existing checkpoint this is a no-op."""
        if os.path.exists(
                os.path.join(self.checkpoint_path, "metadata.json")):
            return {"action": "none"}
        from glint_word2vec_tpu.train.trainer import Trainer
        names = self.cursor.new_segments(self.stream)
        if not names:
            raise FileNotFoundError(
                f"no checkpoint at {self.checkpoint_path!r} and no corpus "
                f"segments under {self.stream.directory!r} to bootstrap "
                f"from")
        cfg = Word2VecConfig(**self.config_overrides)
        counter = merge_counts(
            count_words(self.stream.corpus(n)) for n in names)
        vocab = Vocabulary.from_counter(counter, cfg.min_count)
        parts = [encode_segment(self.stream, n, vocab, self._cache_dir(),
                                cfg.max_sentence_length) for n in names]
        t0 = time.perf_counter()
        trainer = Trainer(cfg, vocab, plan=self.plan)
        trainer.fit(ConcatCorpus(parts),
                    checkpoint_path=self.checkpoint_path,
                    checkpoint_every_steps=self.checkpoint_every_steps)
        vfp = vocab_fingerprint(vocab)
        for name, enc in zip(names, parts):
            self.cursor.mark_consumed(
                name, segment_fingerprint(self.stream.path(name)),
                vfp, enc.meta)
        self.cursor.save()
        report = {"action": "base", "segments": len(names),
                  "vocab_size": vocab.size,
                  "train_seconds": round(time.perf_counter() - t0, 3)}
        self._emit("continual_increment", increment=0,
                   segments=len(names), vocab_size=vocab.size,
                   new_words=vocab.size, words=int(vocab.train_words_count),
                   train_seconds=report["train_seconds"])
        self._emit_publish(trainer)
        return report

    # -- one cycle ---------------------------------------------------------------------

    def run_once(self) -> Dict[str, Any]:
        """One poll→extend→fit→publish cycle; returns a report dict
        (``action`` = "idle" | "increment")."""
        new_names = self.cursor.new_segments(self.stream)
        if not new_names:
            return {"action": "idle", "segments": 0}
        ck = self._recovered_checkpoint()
        from glint_word2vec_tpu.train.checkpoint import (
            TrainState, load_model_header)
        header = load_model_header(ck)
        cfg = self._load_config(header)

        # 1. count the tail (pass 1 of the two-pass streaming contract) —
        # only segments whose counts have NOT already been merged: a crashed
        # increment retries the fit without double-weighting the tail
        # (cursor.counted, the stage marker saved right after the extension
        # publish below)
        count_names = self.cursor.uncounted(new_names)
        grew = False
        report = {"new_words": 0}
        if count_names:
            tail_counts = merge_counts(
                count_words(self.stream.corpus(n)) for n in count_names)

            # 2. migrate — EVERY increment with fresh counts: growth when
            # >= continual_min_new_words promoted words, a counts-merge
            # otherwise (either way the vocab fingerprint changes with the
            # merged counts, so the lineage link the migration appends is
            # what keeps old encode caches — and resume()'s cache
            # acceptance — valid). This write is atomic publish #1: a
            # watching EmbeddingService hot-reloads the grown model before
            # the incremental fit even starts. The tail_fingerprint rides
            # the lineage link so a retry whose previous attempt died
            # BETWEEN this publish and the cursor save below recognizes the
            # already-applied merge instead of double-weighting the tail.
            tail_fp = "+".join(
                f"{n}={segment_fingerprint(self.stream.path(n))}"
                for n in count_names)
            report = _extend.extend_checkpoint(
                ck, tail_counts, out_path=self.checkpoint_path,
                min_count=cfg.min_count,
                min_new_words=cfg.continual_min_new_words,
                tail_fingerprint=tail_fp)
            ck = report["path"]
            grew = report["new_words"] > 0
            header = load_model_header(ck)
            cfg = self._load_config(header)
            for name in count_names:
                self.cursor.mark_counted(
                    name, segment_fingerprint(self.stream.path(name)))
            self.cursor.save()
            if grew:
                self._emit("continual_extend",
                           old_vocab_size=report["old_vocab_size"],
                           new_vocab_size=report["new_vocab_size"],
                           new_words=report["new_words"])
        vocab = Vocabulary.from_words_and_counts(
            header["words"], header["counts"])

        lineage = list(header.get("vocab_lineage") or [])
        allowed = _extend.lineage_fingerprints(lineage)

        # 3. delta encode: only the tail is new work
        enc = encode_delta(
            self.stream, self.cursor, vocab, self._cache_dir(),
            max_sentence_length=cfg.max_sentence_length,
            lineage=allowed,
            replay_segments=cfg.continual_replay_segments)

        # 4. incremental fit — lr re-warmed, PRNG lattice continued. The
        # re-warm rides the trainer's dispatch-time lr scale (the same
        # staging point the recovery ladder backs lr off through), NOT a
        # config.learning_rate rewrite: the Trainer persists its config
        # into every publish, and a rewritten lr would COMPOUND — after k
        # increments at rewarm 0.8 the deployment's base lr would silently
        # read as 0.8^k of itself. The published checkpoint keeps the base
        # learning_rate; only the increment's dispatched alphas scale.
        from glint_word2vec_tpu.train.trainer import Trainer
        params = self._load_params(ck, header, cfg)
        inc_cfg = cfg.replace(num_iterations=cfg.continual_iterations)
        state = TrainState(global_step=header["train_state"].global_step)
        t0 = time.perf_counter()
        trainer = Trainer(inc_cfg, vocab, plan=self.plan, params=params,
                          train_state=state)
        if cfg.continual_lr_rewarm != 1.0:
            trainer._lr_scale = cfg.continual_lr_rewarm
        trainer.extra_checkpoint_meta = {"vocab_lineage": lineage}
        # corpus_words: the lr-decay clock must anneal over the INCREMENT's
        # corpus, not the full merged history the vocab counts imply
        trainer.fit(enc["corpus"], checkpoint_path=self.checkpoint_path,
                    checkpoint_every_steps=self.checkpoint_every_steps,
                    corpus_words=enc["corpus"].total_tokens)
        train_seconds = round(time.perf_counter() - t0, 3)

        # 5. consume the tail — only now, so a crash above retries cleanly
        vfp = vocab_fingerprint(vocab)
        for name in enc["new"]:
            self.cursor.mark_consumed(
                name, segment_fingerprint(self.stream.path(name)),
                vfp, enc["encoded"][name].meta)
        self.cursor.save()
        self.increments += 1
        words = sum(int(enc["encoded"][n].total_tokens) for n in enc["new"])
        self._emit("continual_increment", increment=self.increments,
                   segments=len(enc["new"]), vocab_size=vocab.size,
                   new_words=report["new_words"], words=words,
                   train_seconds=train_seconds)
        self._emit_publish(trainer)
        return {
            "action": "increment",
            "increment": self.increments,
            "segments": len(enc["new"]),
            "replayed": len(enc["replayed"]),
            "grew": grew,
            "new_words": report["new_words"],
            "vocab_size": vocab.size,
            "words": words,
            "lineage_depth": len(lineage),
            "train_seconds": train_seconds,
        }

    # -- the loop ----------------------------------------------------------------------

    def run_forever(
        self,
        max_increments: Optional[int] = None,
        max_idle_polls: Optional[int] = None,
        poll_s: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Poll→increment until a bound trips: ``max_increments`` completed
        increments, or ``max_idle_polls`` CONSECUTIVE empty polls (both None
        = run until killed — SIGTERM lands between or inside increments and
        either way leaves a resumable checkpoint + consistent cursor).
        ``poll_s`` defaults to the config knob of the current checkpoint (or
        the dataclass default before a checkpoint exists)."""
        if poll_s is None:
            # the knobs travel with the checkpoint: a deployment that
            # pinned continual_poll_s there must be honored; overrides win,
            # the dataclass default is the pre-checkpoint fallback
            try:
                from glint_word2vec_tpu.train.checkpoint import (
                    load_model_header)
                poll_s = self._load_config(
                    load_model_header(self.checkpoint_path)).continual_poll_s
            except (FileNotFoundError, ValueError):
                poll_s = Word2VecConfig(
                    **self.config_overrides).continual_poll_s
        done, idle = 0, 0
        while True:
            report = self.run_once()
            if report["action"] == "increment":
                done += 1
                idle = 0
                logger.info("continual increment %d: %s",
                            report["increment"], report)
                if max_increments is not None and done >= max_increments:
                    return {"increments": done, "stopped": "max_increments"}
            else:
                idle += 1
                if max_idle_polls is not None and idle >= max_idle_polls:
                    return {"increments": done, "stopped": "idle"}
                time.sleep(poll_s)

    def close(self) -> None:
        if self._sink is not None:
            self._sink.close()
            self._sink = None

    def __enter__(self) -> "ContinualRunner":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
