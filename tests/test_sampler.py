"""Unit tests for the on-device unigram sampler (G7 replacement): distribution ∝ counts^0.75."""

import jax
import numpy as np

from glint_word2vec_tpu.ops.sampler import (
    build_alias_table,
    build_unigram_table,
    sample_negatives,
    sampled_probabilities,
)


def test_alias_table_shapes_and_validity():
    counts = np.array([100, 50, 10, 1, 1])
    t = build_alias_table(counts)
    assert t.prob.shape == (5,) and t.alias.shape == (5,)
    assert np.all(np.asarray(t.prob) >= 0) and np.all(np.asarray(t.prob) <= 1)
    assert np.all(np.asarray(t.alias) >= 0) and np.all(np.asarray(t.alias) < 5)


def test_alias_table_exactly_encodes_power_distribution():
    # Reconstruct p from (prob, alias): p[i] = (prob[i] + Σ_j (1−prob[j])[alias_j == i]) / V
    counts = np.array([1000, 300, 50, 7, 3, 1, 1, 1])
    t = build_alias_table(counts, power=0.75)
    prob = np.asarray(t.prob, dtype=np.float64)
    alias = np.asarray(t.alias)
    V = counts.size
    p = prob.copy()
    np.add.at(p, alias, 1.0 - prob)
    p /= V
    np.testing.assert_allclose(p, sampled_probabilities(counts, 0.75), atol=1e-6)


def test_sample_negatives_distribution():
    counts = np.array([500, 200, 100, 10, 5])
    t = build_alias_table(counts, power=0.75)
    draws = sample_negatives(t, jax.random.key(0), (200_000,))
    freq = np.bincount(np.asarray(draws), minlength=5) / 200_000
    np.testing.assert_allclose(freq, sampled_probabilities(counts, 0.75), atol=0.01)


def test_sample_negatives_deterministic_per_key():
    counts = np.arange(1, 101)
    t = build_alias_table(counts)
    a = sample_negatives(t, jax.random.key(7), (64, 5))
    b = sample_negatives(t, jax.random.key(7), (64, 5))
    c = sample_negatives(t, jax.random.key(8), (64, 5))
    assert a.shape == (64, 5)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_quantized_table_matches_alias_distribution():
    # The reference's G7 table (unigramTableSize entries) and the alias sampler encode the
    # same counts^0.75 distribution, up to table quantization.
    counts = np.array([900, 400, 100, 30, 9, 2])
    table = build_unigram_table(counts, table_size=100_000)
    table_freq = np.bincount(table, minlength=6) / table.size
    np.testing.assert_allclose(table_freq, sampled_probabilities(counts, 0.75), atol=1e-3)


def test_single_word_vocab():
    t = build_alias_table(np.array([42]))
    draws = sample_negatives(t, jax.random.key(0), (16,))
    assert np.all(np.asarray(draws) == 0)
