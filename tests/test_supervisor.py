"""Preemption-safe training supervisor suite (docs/robustness.md
§supervisor): SIGTERM landing INSIDE the checkpoint-save window (both
orderings — the atomic protocol must leave old-or-new verified, never
torn), the restart/quarantine state machine over scripted subprocess
children, the peer-liveness beacon board, the injected in-step stall
fault, and the "preempted" run-report status.

The save-window crashes run as subprocesses because the default SIGTERM
disposition is the fault model under test: no handler installed, the
process dies mid-save exactly where the signal lands. The supervisor
state-machine tests use trivial ``python -c`` children — the
classification/ladder logic needs exit codes and silence, not a real
fit (the real-fit proof is tools/train_run.py's drills, wired into
chaos --smoke)."""

import json
import os
import subprocess
import sys
import time

import pytest

from glint_word2vec_tpu.train import faults
from glint_word2vec_tpu.train.checkpoint import (
    load_latest_valid,
    verify_checkpoint,
)
from glint_word2vec_tpu.train.supervisor import (
    MITIGATE_ENV,
    PEER_ABORT_EXIT,
    BeaconBoard,
    PeerDeathError,
    TrainingSupervisor,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# -- SIGTERM inside the checkpoint-save window -----------------------------


@pytest.mark.parametrize("point", ["save:staged@2", "save:swap@2"])
def test_sigterm_during_save_window(tmp_path, point):
    """A preemption SIGTERM landing mid-save — before the staged tmp is
    blessed ("staged") or inside the swap's torn window ("swap") — must
    leave a recoverable directory either way: ``load_latest_valid``
    reclaims the debris and returns a checkpoint that VERIFIES (the old
    one or the new one, never a torn hybrid)."""
    workdir = str(tmp_path / "w")
    os.makedirs(workdir)
    rc = subprocess.call(
        [sys.executable, os.path.join(_REPO, "tools", "chaos_run.py"),
         "--worker", "crash", "--workdir", workdir, "--sentences", "120"],
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 GLINT_FAULT_CRASH_POINT=point,
                 GLINT_FAULT_CRASH_SIGNAL="TERM"),
        cwd=_REPO, timeout=300,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    assert rc in (-15, 143), f"worker exited {rc}, expected SIGTERM"
    # the interrupted save left debris; recovery must step around it
    ck = load_latest_valid(workdir)
    meta = verify_checkpoint(ck)
    step = meta["train_state"]["global_step"]
    assert step > 0 and not meta["train_state"]["finished"], meta
    # reclaim happened: a fresh scan sees no staging/old debris
    entries = os.listdir(workdir)
    assert not any(".tmp-" in e for e in entries), entries


# -- the supervisor state machine (scripted children) ----------------------


def _child(script: str) -> list:
    return [sys.executable, "-c", script]


def _supervisor(tmp_path, commands, **kw):
    kw.setdefault("poll_s", 0.02)
    kw.setdefault("term_grace_s", 0.3)
    kw.setdefault("backoff_base_s", 0.01)
    kw.setdefault("backoff_cap_s", 0.05)
    workdir = str(tmp_path)
    logs = kw.pop("child_logs",
                  [os.path.join(workdir, f"c{i}.jsonl")
                   for i in range(len(commands))])
    return TrainingSupervisor(commands, workdir, child_logs=logs, **kw)


def test_clean_child_is_ok(tmp_path):
    sup = _supervisor(tmp_path, [_child("raise SystemExit(0)")],
                      max_restarts=3, stall_s=30.0)
    v = sup.run()
    assert v.status == "ok" and v.attempts == 1
    assert not os.path.exists(os.path.join(str(tmp_path), "verdict.json"))


def test_deterministic_crash_loop_quarantines(tmp_path):
    """The same exit code at the same (step-bucketed) position on every
    attempt: after ``loop_window`` identical signatures the ladder
    engages mitigations and clears the window; after a second full
    window it halts with a machine-readable quarantine verdict — never
    an unbounded restart loop."""
    sup = _supervisor(tmp_path, [_child("raise SystemExit(7)")],
                      max_restarts=6, stall_s=30.0, loop_window=2)
    v = sup.run()
    assert v.status == "quarantined"
    assert v.classification == "deterministic-crash-loop"
    assert v.attempts == 4 <= 6  # 2 per ladder stage, well under budget
    assert [l["stage"] for l in v.ladder] == [1, 2]
    assert "rc7" in v.signature
    # stage 1 engaged the mitigation env for every later attempt
    assert sup.env.get(MITIGATE_ENV) == "1"
    with open(os.path.join(str(tmp_path), "verdict.json")) as f:
        doc = json.load(f)
    assert doc["status"] == "quarantined" and doc["signature"] == v.signature


def test_nondeterministic_crashes_exhaust_budget(tmp_path):
    """DIFFERENT failure signatures never match the loop window — the
    supervisor keeps restarting until the budget runs out and reports
    gave-up (restarting might have helped; it just didn't)."""
    script = "import os; raise SystemExit(int(os.environ['RC']))"
    sup = _supervisor(tmp_path, [_child(script)], max_restarts=2,
                      stall_s=30.0, loop_window=2,
                      env_for_attempt=lambda a: {"RC": str(40 + a)})
    v = sup.run()
    assert v.status == "gave-up"
    assert v.classification == "restart-budget-exhausted"
    assert v.attempts == 3  # initial + max_restarts


def test_stall_detected_killed_and_resumed(tmp_path):
    """A child that goes silent past ``stall_s`` is killed (counted as a
    stall, not a crash) and the run is retried; the retry succeeding
    ends the whole supervised run ok."""
    script = ("import os, time\n"
              "if os.environ.get('STALL') == '1':\n"
              "    time.sleep(60)\n")
    sup = _supervisor(tmp_path, [_child(script)], max_restarts=3,
                      stall_s=0.4,
                      env_for_attempt=lambda a:
                      {"STALL": "1" if a == 0 else "0"})
    t0 = time.monotonic()
    v = sup.run()
    took = time.monotonic() - t0
    assert v.status == "ok" and v.attempts == 2
    assert v.history[0]["cls"] == "stall"
    assert sup.stalls == 1
    assert took < 10.0, f"stall kill path took {took:.1f}s"


def test_peer_death_restarts_whole_gang(tmp_path):
    """In a gang, one member exiting with the peer-abort code (a survivor
    fleeing a dead peer's collective) is NOT the root cause: the attempt
    classifies as peer-death and the WHOLE gang restarts together."""
    script = ("import os\n"
              "raise SystemExit(int(os.environ['MY_RC']))\n")
    calls = []

    def env_for(attempt):
        calls.append(attempt)
        return {"MY_RC": str(PEER_ABORT_EXIT) if attempt == 0 else "0"}

    sup = _supervisor(tmp_path, [_child(script), _child(script)],
                      max_restarts=3, stall_s=30.0, env_for_attempt=env_for)
    v = sup.run()
    assert v.status == "ok" and v.attempts == 2
    assert v.history[0]["cls"] == "peer-death"


def test_gang_partial_death_kills_survivors(tmp_path):
    """One gang member crashing while the other would run on forever: the
    supervisor must reap the survivor itself (it would otherwise hang in
    a collective that can never complete) and classify by the member
    that died on its own."""
    crasher = _child("raise SystemExit(9)")
    sleeper = _child("import time; time.sleep(60)")
    sup = _supervisor(tmp_path, [crasher, sleeper], max_restarts=0,
                      stall_s=30.0)
    t0 = time.monotonic()
    v = sup.run()
    took = time.monotonic() - t0
    assert v.status == "gave-up" and v.attempts == 1
    assert v.history[0]["cls"] == "crash"
    assert "rc9" in v.history[0]["signature"]
    assert took < 10.0, f"survivor reap took {took:.1f}s"


# -- beacon board ----------------------------------------------------------


def test_beacons_fresh_and_not_yet_joined(tmp_path):
    b0 = BeaconBoard(str(tmp_path), 0, 3, interval_s=10.0)
    b0._touch()
    # peer 1 joined and is fresh; peer 2 never joined (slow start) — only
    # a beacon that was SEEN and then went quiet may count as dead
    BeaconBoard(str(tmp_path), 1, 3, interval_s=10.0)._touch()
    assert b0.stale_peers(60.0) == []
    b0.check_or_raise()


def test_beacon_stale_mtime_raises(tmp_path):
    b0 = BeaconBoard(str(tmp_path), 0, 2, interval_s=0.1)
    b0._touch()
    b1 = BeaconBoard(str(tmp_path), 1, 2, interval_s=0.1)
    b1._touch()
    old = time.time() - 3600
    os.utime(b1.path_for(1), (old, old))
    assert b0.stale_peers(b0.stale_after) == [1]
    with pytest.raises(PeerDeathError):
        b0.check_or_raise()


def test_beacon_seen_then_vanished_is_dead(tmp_path):
    b0 = BeaconBoard(str(tmp_path), 0, 2, interval_s=10.0)
    b0._touch()
    b1 = BeaconBoard(str(tmp_path), 1, 2, interval_s=10.0)
    b1._touch()
    assert b0.stale_peers(60.0) == []          # observes peer 1
    os.remove(b1.path_for(1))                  # clean file, dead process
    assert b0.stale_peers(60.0) == [1]


def test_beacon_stop_removes_own_file(tmp_path):
    b0 = BeaconBoard(str(tmp_path), 0, 1, interval_s=0.05).start()
    assert os.path.exists(b0.path_for(0))
    b0.stop()
    assert not os.path.exists(b0.path_for(0))


# -- the injected stall fault ----------------------------------------------


def test_maybe_stall_fires_once_at_step(tmp_path):
    faults.configure(stall_at_step=3, stall_s=0.3)
    assert faults.maybe_stall(2) == 0.0
    t0 = time.monotonic()
    assert faults.maybe_stall(3) == pytest.approx(0.3)
    assert time.monotonic() - t0 >= 0.3
    assert faults.maybe_stall(3) == 0.0  # once-semantics: resume must run


# -- run_report: the "preempted" status ------------------------------------


def test_run_report_preempted_status(tmp_path):
    """A deadline-checkpointed preemption reports status "preempted"
    (distinct from "truncated"), carries steps-saved vs steps-lost, and
    still exits nonzero — resuming is the supervisor's job."""
    from glint_word2vec_tpu.obs.sink import TelemetrySink
    log = str(tmp_path / "run.jsonl")
    sink = TelemetrySink(log)
    sink.emit("run_start", run_id="r1", vocab_size=10, mesh=[1, 1],
              config={})
    sink.emit("heartbeat", step=6, words=60, alpha=0.02, loss=0.1,
              mean_f_pos=0.5, pairs_per_sec=100.0, host_wait_s=0.0,
              dispatch_s=0.1)
    sink.emit("preempt", step=6, saved=True, checkpoint="ck",
              deadline_s=30.0, steps_since_save=0)
    sink.emit("run_end", run_id="r1", status="preempted", steps=6,
              pairs_trained=600, host_wait_s_total=0.0,
              dispatch_s_total=0.1, watchdog_fires=0)
    sink.close()
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "run_report.py"), log],
        cwd=_REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    rep = json.loads(proc.stdout)
    assert rep["schema_valid"], rep["schema_errors"]
    assert rep["status"] == "preempted" and not rep["ok"]
    assert rep["preempt"] == {"saved": True, "step": 6, "steps_saved": 6,
                              "steps_lost": 0, "checkpoint": "ck"}


def test_run_report_preempted_deadline_missed(tmp_path):
    from glint_word2vec_tpu.obs.sink import TelemetrySink
    log = str(tmp_path / "run.jsonl")
    sink = TelemetrySink(log)
    sink.emit("run_start", run_id="r1", vocab_size=10, mesh=[1, 1],
              config={})
    sink.emit("preempt", step=10, saved=False, checkpoint="ck",
              deadline_s=5.0, steps_since_save=3)
    sink.emit("run_end", run_id="r1", status="preempted", steps=10,
              pairs_trained=0, host_wait_s_total=0.0, dispatch_s_total=0.0,
              watchdog_fires=0)
    sink.close()
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "run_report.py"), log],
        cwd=_REPO, capture_output=True, text=True, timeout=120)
    rep = json.loads(proc.stdout)
    assert rep["preempt"]["steps_lost"] == 3
    assert rep["preempt"]["steps_saved"] == 7


# -- chaos CLI surface -----------------------------------------------------


def test_chaos_list_and_unknown_only():
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "chaos_run.py"),
         "--list"], cwd=_REPO, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0
    names = out.stdout.split()
    for want in ("train-preempt", "train-stall", "train-crashloop",
                 "crash-resume"):
        assert want in names, names
    bad = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "chaos_run.py"),
         "--only", "no-such-phase"],
        cwd=_REPO, capture_output=True, text=True, timeout=120)
    assert bad.returncode == 2
    assert "no-such-phase" in bad.stdout and "available:" in bad.stdout


# -- supervisor gauges -----------------------------------------------------


def test_supervisor_prometheus_text(tmp_path):
    from glint_word2vec_tpu.obs.statusd import supervisor_prometheus_text
    sup = _supervisor(tmp_path, [_child("raise SystemExit(0)")],
                      max_restarts=0, stall_s=30.0)
    sup.run()
    text = supervisor_prometheus_text(sup.status_snapshot())
    assert "glint_supervisor_up 1" in text
    assert "glint_supervisor_attempts_total 1" in text
    assert "glint_supervisor_quarantined 0" in text
