"""stepaudit (layer-2 compiled-step contract auditor, ISSUE 5): the four step
variants (rows-GSPMD, shard_map, cols, banded CBOW) plus the bf16 dtype twin
pass all four compiled-artifact contracts — donation present, zero implicit
transfers under jax.transfer_guard("disallow"), no f64 / no dense f32 [V, D]
in bf16 mode, exactly one jit compilation — and the auditor demonstrably
CATCHES each regression class (dropped donate_argnums; dropped explicit
staging)."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools import stepaudit  # noqa: E402


def test_stepaudit_smoke_all_variants():
    """Subprocess run of the tier-1/CI wiring: all variants pass all four
    contracts and the structural fields match the committed STEPAUDIT.json
    baseline (drift = a contract changed — review it, then regenerate with
    `python tools/stepaudit.py --smoke --json-out STEPAUDIT.json`)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "stepaudit.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["ok"]
    assert set(result["variants"]) == set(
        stepaudit.VARIANTS) | {stepaudit.BF16_VARIANT}
    for name, r in result["variants"].items():
        assert r["donation"]["ok"] and r["donation"]["aliased_params"] >= 2, (
            name, r)
        assert r["transfers"]["ok"] and r["transfers"]["dispatches"] >= 2, (
            name, r)
        assert r["dtype"]["f64_free"], (name, r)
        assert r["recompile"]["compiles"] == 1, (name, r)
    bf16 = result["variants"][stepaudit.BF16_VARIANT]
    assert bf16["dtype"]["dense_f32_vd_free"] is True
    # the ISSUE-14 end-to-end bf16 chain: no dense f32 [B, D] intermediate
    # survives in the lowered module (the classic chain's f_pos convert)
    chain = result["variants"]["rows_gspmd_bf16_chain"]
    assert chain["dtype"]["dense_f32_bd_free"] is True
    assert chain["dtype"]["dense_f32_vd_free"] is True
    # the hot-row slab scan holds donation/one-compile on its 1x1 mesh
    assert result["variants"]["rows_gspmd_hot"]["mesh"] == [1, 1]
    # the recover-rebuild contract (ISSUE 8): one recovery, twins rebuilt
    # once, exactly one extra compile — 2 total for the whole
    # blowup-and-recover fit
    rr = result["recover_rebuild"]
    assert rr["ok"] and rr["recoveries"] == 1 and rr["rebuilt"], rr
    assert rr["total_compiles"] == rr["expected_total_compiles"] == 2, rr

    with open(os.path.join(REPO, "STEPAUDIT.json"), "r") as f:
        baseline = json.load(f)
    assert set(baseline["variants"]) == set(result["variants"])
    for name in result["variants"]:
        for field in ("donation", "dtype", "recompile"):
            assert result["variants"][name][field] == \
                baseline["variants"][name][field], (name, field)
    assert result["recover_rebuild"] == baseline["recover_rebuild"]


def test_auditor_catches_dropped_donation():
    """The ISSUE's regression test: a toy step compiled WITHOUT
    donate_argnums must be flagged by the donation parser; the same step
    WITH donation passes."""
    def step(params, batch):
        syn0, syn1 = params
        return (syn0 + batch.sum(), syn1 * 2), batch

    params = (jnp.ones((16, 8)), jnp.ones((16, 8)))
    batch = jnp.ones((4,))

    donated = jax.jit(step, donate_argnums=(0,)).lower(
        params, batch).compile().as_text()
    ok = stepaudit.donation_summary(donated)
    assert ok["ok"] and ok["aliased_params"] >= 2, ok

    dropped = jax.jit(step).lower(params, batch).compile().as_text()
    bad = stepaudit.donation_summary(dropped)
    assert not bad["ok"] and bad["aliased_params"] == 0, bad


def test_auditor_catches_dropped_staging(monkeypatch):
    """Re-introducing an implicit host→device transfer at dispatch (the exact
    regression the explicit _stage_dispatch_meta discipline prevents) must
    fail the transfer-guard contract — while donation and dtype still report,
    so one broken contract does not mask the others."""
    from glint_word2vec_tpu.train.trainer import Trainer

    monkeypatch.setattr(
        Trainer, "_stage_dispatch_meta",
        lambda self, meta, base_step, *bases: (
            np.asarray(meta, np.float32), np.int32(base_step), *bases))
    res = stepaudit.audit_variant(
        "rows_gspmd", (2, 4), stepaudit.smoke_geometry())
    assert not res["transfers"]["ok"]
    assert "transfer" in (res["transfers"]["error"] or "").lower()
    assert not res["ok"]


def test_auditor_catches_recovery_without_rebuild(monkeypatch):
    """The recover-rebuild audit's own regression coverage: a recovery that
    rolls back and backs lr off but never rebuilds the step twins (so the
    engaged clamp would silently not exist in the compiled step) must fail
    the contract."""
    from glint_word2vec_tpu.train.trainer import Trainer

    def no_rebuild(self, reason, channels):
        self._restore_snapshot()
        self.recoveries_performed += 1
        self._lr_scale *= self.config.recover_lr_backoff

    monkeypatch.setattr(Trainer, "_perform_recovery", no_rebuild)
    res = stepaudit.audit_recover_rebuild(stepaudit.smoke_geometry())
    assert res["recoveries"] == 1
    assert not res["rebuilt"] and res["compiles_after"] == 0
    assert not res["ok"], res


def test_audit_variant_in_process_shard_map():
    """One in-process audit (shard_map — the lowering whose schedule the
    collective auditor guards) so contract failures debug without subprocess
    indirection."""
    res = stepaudit.audit_variant(
        "shard_map", (2, 4), stepaudit.smoke_geometry())
    assert res["ok"], res
    assert res["recompile"] == {"compiles": 1, "expected": 1, "ok": True}
