"""Preemption-safe training supervision: the die→diagnose→resume loop.

The reference survives worker churn for free — Hogwild-async Spark
partitions just reschedule — but our synchronous trainer dies wholesale.
This module is the layer that brings a long fit back (docs/robustness.md):

- :class:`TrainingSupervisor` runs a fit (or a multi-process gang of
  them) as subprocesses, watches step progress through each child's
  telemetry sink, and owns the restart policy: resume from
  ``load_latest_valid`` with decorrelated-jitter backoff
  (``reload.decorrelated_jitter`` — the same curve the serving watcher
  retries with), classify every death (clean exit / preemption with an
  emergency checkpoint / hang / crash / peer-death), and escalate a
  DETERMINISTIC crash loop — the same signature ``loop_window`` times in
  a row — up a documented ladder instead of restarting forever:
  stage 1 engages the trainer's existing stabilizer/lr-backoff recover
  knobs (via the ``GLINT_SUPERVISOR_MITIGATE`` env contract the worker
  honors), stage 2 halts with a machine-readable ``verdict.json``.

- :class:`BeaconBoard` is the peer-death protocol for sharded fits: each
  process heartbeats a tiny file under ``<ckpt dir>/beacons/``, and the
  trainer checks the board before every allgather — a dead peer's
  collective never comes, so without the check survivors hang in the
  rendezvous forever. A stale beacon raises :class:`PeerDeathError`
  (clean abort, supervisor restarts the whole gang from the last
  verified checkpoint); if the survivor is already WEDGED inside the
  collective when its peer dies, the board's writer thread hard-exits
  the process with :data:`PEER_ABORT_EXIT` instead.

Driven by ``tools/train_run.py`` and proven by the ``train-preempt`` /
``train-stall`` / ``train-crashloop`` chaos phases (tools/chaos_run.py).
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

logger = logging.getLogger("glint_word2vec_tpu")

# rc a gang member exits with when it aborts BECAUSE a peer died (either
# the clean PeerDeathError path wrapped by the worker, or the BeaconBoard
# watcher's hard exit). Distinct from any Python/signal rc so the
# supervisor can tell "this process was the victim, not the cause".
PEER_ABORT_EXIT = 43

# env var the supervisor sets (ladder stage >= 1) and tools/train_run.py's
# worker honors by engaging the trainer's existing recover knobs
MITIGATE_ENV = "GLINT_SUPERVISOR_MITIGATE"


class PeerDeathError(RuntimeError):
    """A peer process of a sharded fit stopped heartbeating its beacon —
    raised by the main-thread board check so the fit aborts cleanly
    instead of hanging in the next collective."""


class BeaconBoard:
    """Per-process liveness beacons beside the checkpoint directory.

    Each process owns ``p<index>.beacon`` and touches it every
    ``interval_s`` from a daemon writer thread. Staleness is mtime-based
    (the files sit on the shared checkpoint filesystem, the one surface
    every gang member can already reach):

    - main-thread ``check_or_raise`` (the trainer calls it before every
      allgather) raises :class:`PeerDeathError` once a peer's beacon is
      older than ``stale_after`` = 6 × interval — wide enough that a GC
      pause or a slow NFS flush never false-positives, narrow enough
      that survivors abort long before any collective timeout;
    - the writer thread doubles as a watchdog: at 2 × ``stale_after`` it
      assumes the main thread is already wedged inside the dead peer's
      collective (a healthy one would have hit the check above first)
      and hard-exits with :data:`PEER_ABORT_EXIT` — ``os._exit``,
      because no Python exception can unwind a thread blocked in a
      native collective.

    A beacon file that has NEVER been observed is "not yet joined", not
    dead — gang members start at slightly different times. One that was
    seen and then vanished counts as dead (clean shutdown removes the
    file only after the fit left its collective loop)."""

    def __init__(self, directory: str, process_index: int,
                 num_processes: int, interval_s: float,
                 stale_factor: float = 6.0, hard_factor: float = 2.0):
        if interval_s <= 0:
            raise ValueError(
                f"interval_s must be > 0 but got {interval_s}")
        self.directory = directory
        self.index = int(process_index)
        self.num = int(num_processes)
        self.interval_s = float(interval_s)
        self.stale_after = stale_factor * self.interval_s
        self.hard_after = hard_factor * self.stale_after
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seen: set = set()  # peer indices observed at least once

    def path_for(self, index: int) -> str:
        return os.path.join(self.directory, f"p{index}.beacon")

    def start(self) -> "BeaconBoard":
        os.makedirs(self.directory, exist_ok=True)
        self._touch()
        self._thread = threading.Thread(
            target=self._run, name=f"beacon-p{self.index}", daemon=True)
        self._thread.start()
        return self

    def _touch(self) -> None:
        # atomic replace so a reader never stats a half-created file; the
        # payload is for humans (staleness reads only the mtime)
        path = self.path_for(self.index)
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(f"{os.getpid()} {time.time():.3f}\n")
            os.replace(tmp, path)
        except OSError as e:  # beacon I/O must never kill the fit itself
            logger.warning("beacon touch failed: %s", e)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._touch()
            dead = self.stale_peers(self.hard_after)
            if dead:
                logger.critical(
                    "peer beacon(s) %s stale past the hard limit (%.1fs) "
                    "with the main thread unresponsive — assuming it is "
                    "wedged in the dead peer's collective; hard-exiting "
                    "rc=%d for the supervisor to restart the gang",
                    dead, self.hard_after, PEER_ABORT_EXIT)
                os._exit(PEER_ABORT_EXIT)

    def stale_peers(self, horizon_s: float) -> List[int]:
        """Peer indices whose beacon is older than ``horizon_s`` (or was
        seen once and has since vanished). Never includes self."""
        now = time.time()
        out: List[int] = []
        for i in range(self.num):
            if i == self.index:
                continue
            try:
                mtime = os.stat(self.path_for(i)).st_mtime
            except OSError:
                if i in self._seen:
                    out.append(i)  # seen, then vanished: dead
                continue           # never seen: not yet joined
            self._seen.add(i)
            if now - mtime > horizon_s:
                out.append(i)
        return out

    def check_or_raise(self) -> None:
        dead = self.stale_peers(self.stale_after)
        if dead:
            raise PeerDeathError(
                f"peer process(es) {dead} stopped heartbeating their "
                f"liveness beacon (> {self.stale_after:.1f}s stale) — "
                "aborting before the next collective would hang forever")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(2.0, 2 * self.interval_s))
            self._thread = None
        try:
            os.remove(self.path_for(self.index))
        except OSError:
            pass


class _SinkTail:
    """Incremental reader of one child's telemetry JSONL sink: tracks the
    last observed step, the current attempt's run_end bracket, and any
    ``preempt`` record — the supervisor's only window into a child it
    must never block on. Byte-offset based, so it keeps reading the same
    file across attempts (each attempt appends a fresh run bracket)."""

    def __init__(self, path: str):
        self.path = path
        self._pos = 0
        self._buf = b""
        self.records = 0          # total parsed (progress heartbeat)
        self.last_step = 0
        self.run_end_status: Optional[str] = None
        self.preempt: Optional[dict] = None

    def begin_attempt(self) -> None:
        self.run_end_status = None
        self.preempt = None

    def poll(self) -> int:
        """Parse any newly appended complete lines; returns how many."""
        try:
            with open(self.path, "rb") as f:
                f.seek(self._pos)
                data = f.read()
        except OSError:
            return 0
        if not data:
            return 0
        self._pos += len(data)
        self._buf += data
        lines = self._buf.split(b"\n")
        self._buf = lines.pop()  # torn tail: a write still in flight
        n = 0
        for raw in lines:
            if not raw.strip():
                continue
            try:
                r = json.loads(raw)
            except (ValueError, UnicodeDecodeError):
                continue
            n += 1
            kind = r.get("kind")
            if kind == "heartbeat":
                self.last_step = max(self.last_step, int(r.get("step") or 0))
            elif kind == "preempt":
                self.preempt = r
                self.last_step = max(self.last_step, int(r.get("step") or 0))
            elif kind == "run_end":
                self.run_end_status = r.get("status")
                self.last_step = max(self.last_step,
                                     int(r.get("steps") or 0))
        self.records += n
        return n


@dataclass
class AttemptResult:
    """One child-fit attempt's post-mortem, as the supervisor saw it."""
    attempt: int
    rc: int                  # gang: the root-cause member's rc
    cls: str                 # ok | preempt | stall | crash | peer-death
    step: int                # last telemetry step observed across the gang
    signature: str = ""      # crash-loop matching key ("" for ok/preempt)
    stalled_s: float = 0.0
    preempt: Optional[dict] = None   # the trainer's preempt record, if any


@dataclass
class SupervisorVerdict:
    """What ``TrainingSupervisor.run`` returns — and, for the halt
    outcomes, what lands in ``<workdir>/verdict.json`` for a driver to
    gate on."""
    status: str              # ok | quarantined | gave-up
    attempts: int
    final_step: int
    classification: str = ""         # e.g. "deterministic-crash-loop"
    signature: str = ""
    ladder: List[dict] = field(default_factory=list)
    history: List[dict] = field(default_factory=list)
    progress_lost_steps: int = 0     # across all observed preemptions

    def to_dict(self) -> dict:
        return {
            "status": self.status, "attempts": self.attempts,
            "final_step": self.final_step,
            "classification": self.classification,
            "signature": self.signature, "ladder": self.ladder,
            "history": self.history,
            "progress_lost_steps": self.progress_lost_steps,
        }


class TrainingSupervisor:
    """Run a fit (gang of fits) under restart supervision.

    ``commands``: one argv per gang member — typically ONE for a
    single-process fit; the worker command must itself resume from the
    newest checkpoint under ``checkpoint_dir`` when one exists (the
    ``load_latest_valid`` contract; tools/train_run.py ``--worker fit``
    is the canonical shape).

    ``child_logs``: the telemetry sink path each member writes — the
    supervisor's progress window (hang detection) and classification
    evidence (``preempt`` records, run_end brackets; each log's
    ``<log>.blackbox.json`` dump names the crash cause).

    Failure classification, in priority order:

    - killed by our own stall watchdog (no telemetry progress for
      ``stall_s``; SIGTERM first so the flight recorder dumps, SIGKILL
      after ``term_grace_s``)                       → ``stall``
    - rc ``-SIGTERM`` with a ``preempted`` run_end  → ``preempt``
    - every non-zero member exited PEER_ABORT_EXIT  → ``peer-death``
      (root cause unknown: the offending member died without a story)
    - anything else → ``crash``, with a signature built from the
      blackbox cause (exception type / signal) + the last observed step
      bucketed to ± ``step_slop``.

    The same ``crash``/``stall`` signature ``loop_window`` times in a
    row is a DETERMINISTIC loop — restarting cannot help. The ladder:
    stage 1 sets ``GLINT_SUPERVISOR_MITIGATE=1`` for every later attempt
    (the worker engages norm_watch="recover" + lr backoff) and clears
    the window; a loop that survives mitigation reaches stage 2: halt
    with a quarantine verdict. ``max_restarts`` bounds total restarts
    regardless, so no path restarts forever."""

    def __init__(self, commands: Sequence[Sequence[str]], workdir: str,
                 child_logs: Sequence[str],
                 checkpoint_dir: str = "",
                 telemetry=None,
                 max_restarts: int = 8, stall_s: float = 300.0,
                 loop_window: int = 3,
                 backoff_base_s: float = 0.05, backoff_cap_s: float = 2.0,
                 seed: int = 0, env: Optional[Dict[str, str]] = None,
                 env_for_attempt: Optional[
                     Callable[[int], Dict[str, str]]] = None,
                 poll_s: float = 0.25, term_grace_s: float = 5.0,
                 step_slop: int = 4):
        if len(commands) != len(child_logs):
            raise ValueError(
                f"{len(commands)} commands but {len(child_logs)} child "
                "logs — the supervisor needs one sink path per gang member")
        self.commands = [list(c) for c in commands]
        self.workdir = workdir
        self.child_logs = list(child_logs)
        self.checkpoint_dir = checkpoint_dir
        self._telemetry = telemetry
        self.max_restarts = int(max_restarts)
        self.stall_s = float(stall_s)
        self.loop_window = int(loop_window)
        self.poll_s = float(poll_s)
        self.term_grace_s = float(term_grace_s)
        self.step_slop = max(1, int(step_slop))
        self.env = dict(env or {})
        self.env_for_attempt = env_for_attempt
        self._backoff = None
        self._backoff_base = float(backoff_base_s)
        self._backoff_cap = float(backoff_cap_s)
        self._seed = int(seed)
        # live counters (status_snapshot / supervisor_prometheus_text)
        self.attempts = 0
        self.restarts = 0
        self.stalls = 0
        self.preempts = 0
        self.ladder_stage = 0
        self.quarantined = False
        self.last_step = 0
        self.child_up = 0

    # -- telemetry ---------------------------------------------------------

    def _emit(self, kind: str, **fields) -> None:
        if self._telemetry is not None:
            self._telemetry.emit(kind, **fields)

    def status_snapshot(self) -> dict:
        """Live gauges for a StatusServer (obs/statusd.py) riding beside
        the supervisor — the fleet-run observability idiom one tier up."""
        return {
            "up": 1, "attempts": self.attempts, "restarts": self.restarts,
            "stalls": self.stalls, "preempts": self.preempts,
            "ladder_stage": self.ladder_stage,
            "quarantined": self.quarantined,
            "last_step": self.last_step, "child_up": self.child_up,
        }

    # -- the loop ----------------------------------------------------------

    def run(self) -> SupervisorVerdict:
        self._emit("supervisor_start", commands=len(self.commands),
                   max_restarts=self.max_restarts, stall_s=self.stall_s)
        rng = np.random.default_rng(self._seed)
        from glint_word2vec_tpu.serve.reload import decorrelated_jitter
        self._backoff = decorrelated_jitter(
            self._backoff_base, self._backoff_cap, rng)
        tails = [_SinkTail(p) for p in self.child_logs]
        window: List[str] = []       # trailing failure signatures
        history: List[dict] = []
        ladder: List[dict] = []
        lost = 0
        attempt = 0
        while True:
            if attempt > 0:
                self.restarts += 1
                backoff = float(next(self._backoff))
                self._emit("supervisor_restart", attempt=attempt,
                           backoff_s=round(backoff, 4),
                           resume_step=self._resume_step())
                time.sleep(backoff)
            res = self._run_attempt(attempt, tails)
            self.attempts = attempt + 1
            self.last_step = max(self.last_step, res.step)
            history.append({"attempt": res.attempt, "rc": res.rc,
                            "cls": res.cls, "step": res.step,
                            "signature": res.signature})
            self._emit("supervisor_exit", attempt=res.attempt, rc=res.rc,
                       cls=res.cls, step=res.step)
            if res.cls == "ok":
                verdict = SupervisorVerdict(
                    status="ok", attempts=self.attempts,
                    final_step=self.last_step, history=history,
                    progress_lost_steps=lost)
                self._finish(verdict)
                return verdict
            if res.cls == "preempt":
                self.preempts += 1
                if res.preempt is not None and not res.preempt.get("saved"):
                    lost += int(res.preempt.get("steps_since_save") or 0)
                # an eviction is external, not a bug — it never feeds the
                # deterministic-loop window
            elif res.cls == "peer-death":
                # the whole gang restarts from the last verified
                # checkpoint; the root cause died story-less, so it can't
                # be signature-matched either
                pass
            else:
                if res.cls == "stall":
                    self.stalls += 1
                window.append(res.signature)
                window = window[-self.loop_window:]
                if (len(window) == self.loop_window
                        and len(set(window)) == 1):
                    # deterministic loop: same failure, same place,
                    # loop_window times running — restarting is futile
                    self.ladder_stage += 1
                    ladder.append({"stage": self.ladder_stage,
                                   "attempt": attempt,
                                   "signature": res.signature})
                    self._emit("supervisor_quarantine",
                               signature=res.signature,
                               attempts=self.attempts,
                               ladder_stage=self.ladder_stage)
                    if self.ladder_stage == 1:
                        logger.warning(
                            "deterministic failure loop %r — engaging "
                            "mitigations (%s=1) and retrying",
                            res.signature, MITIGATE_ENV)
                        self.env[MITIGATE_ENV] = "1"
                        window.clear()
                    else:
                        self.quarantined = True
                        verdict = SupervisorVerdict(
                            status="quarantined", attempts=self.attempts,
                            final_step=self.last_step,
                            classification="deterministic-crash-loop",
                            signature=res.signature, ladder=ladder,
                            history=history, progress_lost_steps=lost)
                        self._finish(verdict)
                        return verdict
            if attempt >= self.max_restarts:
                verdict = SupervisorVerdict(
                    status="gave-up", attempts=self.attempts,
                    final_step=self.last_step,
                    classification="restart-budget-exhausted",
                    signature=res.signature, ladder=ladder,
                    history=history, progress_lost_steps=lost)
                self._finish(verdict)
                return verdict
            attempt += 1

    def _finish(self, verdict: SupervisorVerdict) -> None:
        self._emit("supervisor_end", status=verdict.status,
                   attempts=verdict.attempts,
                   final_step=verdict.final_step)
        if verdict.status != "ok":
            # the machine-readable halt verdict a driver/CI gates on
            path = os.path.join(self.workdir, "verdict.json")
            os.makedirs(self.workdir, exist_ok=True)
            with open(path, "w", encoding="utf-8") as f:
                json.dump(verdict.to_dict(), f, indent=2, sort_keys=True)
            logger.warning("supervisor verdict %r written to %s",
                           verdict.status, path)

    def _resume_step(self) -> int:
        """Step of the checkpoint the next attempt will resume from (0 =
        cold start) — also verifies the publish, so a preemption's
        emergency save is audited before anything trusts it."""
        if not self.checkpoint_dir:
            return 0
        from glint_word2vec_tpu.train.checkpoint import load_latest_valid, \
            verify_checkpoint
        try:
            path = load_latest_valid(self.checkpoint_dir)
            meta = verify_checkpoint(path)
        except Exception as e:  # any verification failure means cold
            # start, never a crash here
            logger.info("no resumable checkpoint yet (%s)", e)
            return 0
        return int((meta.get("train_state") or {}).get("global_step") or 0)

    # -- one attempt -------------------------------------------------------

    def _attempt_env(self, attempt: int) -> Dict[str, str]:
        env = dict(os.environ)
        env.update(self.env)
        if self.env_for_attempt is not None:
            env.update(self.env_for_attempt(attempt))
        return env

    def _run_attempt(self, attempt: int,
                     tails: List[_SinkTail]) -> AttemptResult:
        env = self._attempt_env(attempt)
        for t in tails:
            t.poll()           # drain pre-attempt leftovers
            t.begin_attempt()
        procs = [subprocess.Popen(cmd, env=env) for cmd in self.commands]
        self.child_up = len(procs)
        killed_by_us = [False] * len(procs)
        stall_fired = False
        stalled_s = 0.0
        last_activity = time.monotonic()
        while True:
            alive = [p.poll() is None for p in procs]
            self.child_up = sum(alive)
            moved = sum(t.poll() for t in tails)
            if moved:
                last_activity = time.monotonic()
                self.last_step = max(self.last_step,
                                     max(t.last_step for t in tails))
            if not any(alive):
                break
            if len(procs) > 1 and not all(alive):
                # gang rule: one death fails the whole attempt — survivors
                # are TERMed (emergency-checkpoint-eligible) rather than
                # left to discover the stale beacon one collective later
                self._kill(procs, killed_by_us, alive_only=True)
                break
            silence = time.monotonic() - last_activity
            if silence > self.stall_s:
                stall_fired = True
                stalled_s = silence
                last = max((t.last_step for t in tails), default=0)
                self._emit("supervisor_stall", attempt=attempt,
                           last_step=last, stalled_s=round(silence, 3))
                logger.warning(
                    "no telemetry progress for %.1fs (> stall_s=%.1fs) at "
                    "step %d — requesting a flight-recorder dump (SIGTERM)"
                    " then killing", silence, self.stall_s, last)
                self._kill(procs, killed_by_us)
                break
            time.sleep(self.poll_s)
        rcs = [p.wait() for p in procs]
        self.child_up = 0
        for t in tails:
            t.poll()
        step = max((t.last_step for t in tails), default=0)
        return self._classify(attempt, rcs, killed_by_us, stall_fired,
                              stalled_s, tails, step)

    def _kill(self, procs, killed_by_us, alive_only: bool = False) -> None:
        """SIGTERM (the diagnostic request: the fit's handler dumps its
        flight recorder, and with checkpoint_on_preempt even drains an
        emergency save), then SIGKILL whatever outlives the grace — a
        stalled process is by definition wedged and may never honor the
        TERM (faults.maybe_stall's sliced sleep does, a real native hang
        would not)."""
        for i, p in enumerate(procs):
            if p.poll() is None:
                killed_by_us[i] = True
                try:
                    p.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + self.term_grace_s
        for p in procs:
            left = deadline - time.monotonic()
            try:
                p.wait(timeout=max(left, 0.05))
            except subprocess.TimeoutExpired:
                try:
                    p.kill()
                except OSError:
                    pass
        _ = alive_only  # semantics identical: only live members signaled

    def _classify(self, attempt: int, rcs: List[int],
                  killed_by_us: List[bool], stall_fired: bool,
                  stalled_s: float, tails: List[_SinkTail],
                  step: int) -> AttemptResult:
        bucket = step - step % self.step_slop
        if stall_fired:
            return AttemptResult(
                attempt=attempt, rc=min(rcs), cls="stall", step=step,
                signature=f"stall@s{bucket}", stalled_s=stalled_s)
        if all(rc == 0 for rc in rcs):
            return AttemptResult(attempt=attempt, rc=0, cls="ok", step=step)
        # root cause: the first member that failed on its OWN (not TERMed
        # by the gang rule above, not a peer-death victim)
        own = [(i, rc) for i, rc in enumerate(rcs)
               if rc != 0 and not killed_by_us[i] and rc != PEER_ABORT_EXIT]
        if not own:
            if any(rc == PEER_ABORT_EXIT for rc in rcs):
                return AttemptResult(attempt=attempt, rc=PEER_ABORT_EXIT,
                                     cls="peer-death", step=step)
            # only our own TERMs failed it (gang rule after a rc-0 exit
            # race) — treat as crash with the kill rc
            own = [(i, rc) for i, rc in enumerate(rcs) if rc != 0]
        idx, rc = own[0]
        tail = tails[idx]
        if (rc == -signal.SIGTERM and not killed_by_us[idx]
                and tail.run_end_status == "preempted"):
            return AttemptResult(attempt=attempt, rc=rc, cls="preempt",
                                 step=step, preempt=tail.preempt)
        cause = self._blackbox_cause(self.child_logs[idx]) or f"rc{rc}"
        return AttemptResult(attempt=attempt, rc=rc, cls="crash", step=step,
                             signature=f"crash:{cause}@s{bucket}")

    @staticmethod
    def _blackbox_cause(log_path: str) -> str:
        """The crash-loop signature's exception-type half, from the dump
        the dying fit left beside its sink (obs/blackbox.py naming —
        the same ``<log>.blackbox.json`` run_report folds in)."""
        path = log_path + ".blackbox.json"
        try:
            with open(path, "r", encoding="utf-8") as f:
                cause = (json.load(f).get("cause") or {})
        except (OSError, ValueError):
            return ""
        kind = cause.get("kind") or ""
        detail = cause.get("type") or cause.get("signal") or ""
        return f"{kind}:{detail}" if detail else str(kind)
