"""Word2Vec model — transform, sentence averaging, synonym/analogy search, persistence.

The TPU-native model API with the capabilities of both reference model layers:

- MLlib model (C8, mllib:460-669): ``transform`` (word → vector; batched iterator),
  ``find_synonyms`` (word and vector overloads), ``get_vectors``, ``to_local``, ``save``,
  ``stop``.
- ML model (C12, ml:322-497): sentence ``transform`` = **average of in-vocab word
  vectors** (ml:428-460, server-side pullAverage), ``find_synonyms_array``,
  ``get_vectors`` as a streaming iterator.

Where the reference pays an RPC per op (pull/pullAverage/norms/multiply with 1-5 min
Await timeouts, mllib:486-652), every op here is a jitted gather/reduction on the sharded
embedding array; ``find_synonyms``'s full-vocab matvec + top-k (mllib:583-630: client-side
O(V) scan over a PS matvec) runs as one sharded ``cosine = (syn0 @ q) / ‖rows‖`` + top-k
on device.
"""

from __future__ import annotations

import logging
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.vocab import Vocabulary
from glint_word2vec_tpu.parallel.mesh import MeshPlan, pad_vocab_for_sharding
from glint_word2vec_tpu.train import checkpoint as ckpt

logger = logging.getLogger("glint_word2vec_tpu")


class Word2VecModel:
    """Trained word embeddings with the full reference model-op surface."""

    def __init__(
        self,
        vocab: Vocabulary,
        syn0: jax.Array,
        syn1: Optional[jax.Array] = None,
        config: Optional[Word2VecConfig] = None,
        plan: Optional[MeshPlan] = None,
        train_state: Optional["ckpt.TrainState"] = None,
    ):
        Vp = (pad_vocab_for_sharding(vocab.size, plan.num_model)
              if plan is not None else vocab.size)
        if syn0.shape[0] not in (vocab.size, Vp):
            raise ValueError(
                f"syn0 has {syn0.shape[0]} rows but vocabulary has {vocab.size} words")
        self.vocab = vocab
        self.config = config or Word2VecConfig(vector_size=int(syn0.shape[1]))
        self.plan = plan
        self.train_state = train_state
        if plan is not None:
            # Row-sharding needs rows % num_model == 0: pad with zero rows (zero norm →
            # cosine 0 and explicitly masked out of top-k), the model-ops analog of the
            # trainer's pad_vocab_for_sharding. Arrays that arrive already padded AND
            # placed (the streaming load_params_into_plan path) are used as-is — no
            # host round-trip.
            placed = (isinstance(syn0, jax.Array) and syn0.shape[0] == Vp
                      and syn0.sharding.is_equivalent_to(plan.embedding, 2)
                      and (syn1 is None or (
                          isinstance(syn1, jax.Array)
                          and syn1.shape[0] == Vp
                          and syn1.sharding.is_equivalent_to(plan.embedding, 2))))
            if not placed:
                syn0 = jnp.asarray(syn0)
                syn1 = jnp.asarray(syn1) if syn1 is not None else None
                pad = Vp - syn0.shape[0]
                if pad:
                    zeros = jnp.zeros((pad, syn0.shape[1]), syn0.dtype)
                    syn0 = jnp.concatenate([syn0, zeros])
                    if syn1 is not None:
                        syn1 = jnp.concatenate([syn1, zeros])
                syn0 = jax.device_put(syn0, plan.embedding)
                if syn1 is not None:
                    syn1 = jax.device_put(syn1, plan.embedding)
        else:
            syn0 = jnp.asarray(syn0)
            syn1 = jnp.asarray(syn1) if syn1 is not None else None
        self._full0 = syn0
        self._full1 = syn1
        self._norms: Optional[jax.Array] = None
        self._ann = None
        self._stopped = False

    @property
    def syn0(self) -> jax.Array:
        """Input embeddings, unpadded view [vocab_size, D]."""
        self._check_alive()
        return self._full0[: self.vocab.size]

    @property
    def syn1(self) -> Optional[jax.Array]:
        if self._full1 is None:
            return None
        self._check_alive()
        return self._full1[: self.vocab.size]

    # -- basic properties --------------------------------------------------------------

    @property
    def vector_size(self) -> int:
        return int(self._full0.shape[1])

    @property
    def num_words(self) -> int:
        return self.vocab.size

    def _check_alive(self) -> None:
        if self._stopped:
            raise RuntimeError("model has been stopped; its buffers were released")

    # -- transform (C8 mllib:511-546; C12 ml:432-460) ----------------------------------

    def transform(self, word: str) -> np.ndarray:
        """Vector of a single word. Raises on OOV like the reference (mllib:516-518)."""
        self._check_alive()
        idx = self.vocab.get(word)
        if idx < 0:
            raise KeyError(f"{word} not in vocabulary")
        return np.asarray(self.syn0[idx])

    def transform_words(self, words: Iterable[str], batch_size: int = 10_000
                        ) -> Iterator[np.ndarray]:
        """Batched word → vector stream (the reference's 10k-word batched iterator path,
        mllib:529-546, noted there as the efficient variant)."""
        self._check_alive()
        buf: List[str] = []

        def emit(buf: List[str]) -> Iterator[np.ndarray]:
            idxs = []
            for w in buf:
                i = self.vocab.get(w)
                if i < 0:
                    raise KeyError(f"{w} not in vocabulary")
                idxs.append(i)
            rows = np.asarray(self.syn0[jnp.asarray(idxs, jnp.int32)])
            yield from rows

        for w in words:
            buf.append(w)
            if len(buf) >= batch_size:
                yield from emit(buf)
                buf = []
        if buf:
            yield from emit(buf)

    def transform_sentences(
        self, sentences: Sequence[Sequence[str]], batch_size: int = 10_000
    ) -> np.ndarray:
        """Sentence → mean of in-vocab word vectors (the ML transform semantics,
        ml:428-460). OOV words are silently dropped (ml:451-452); a sentence with no
        in-vocab words maps to the zero vector. Processed in fixed-size row batches like
        the reference's 10k-row mapPartitions slides (ml:449-450)."""
        self._check_alive()
        out = np.zeros((len(sentences), self.vector_size), dtype=np.float32)
        flat: List[int] = []
        seg: List[int] = []
        row = 0
        rows_in_batch: List[int] = []

        def flush():
            nonlocal flat, seg, rows_in_batch
            if not rows_in_batch:
                return
            if flat:
                idx = jnp.asarray(flat, jnp.int32)
                seg_ids = jnp.asarray(seg, jnp.int32)
                sums = jax.ops.segment_sum(
                    self.syn0[idx].astype(jnp.float32), seg_ids,
                    num_segments=len(rows_in_batch))
                counts = jax.ops.segment_sum(
                    jnp.ones(len(flat), jnp.float32), seg_ids,
                    num_segments=len(rows_in_batch))
                means = np.asarray(sums / jnp.maximum(counts, 1.0)[:, None])
                for local, global_row in enumerate(rows_in_batch):
                    out[global_row] = means[local]
            flat, seg, rows_in_batch = [], [], []

        for sent in sentences:
            local = len(rows_in_batch)
            rows_in_batch.append(row)
            for w in sent:
                i = self.vocab.get(w)
                if i >= 0:
                    flat.append(i)
                    seg.append(local)
            row += 1
            if len(rows_in_batch) >= batch_size:
                flush()
        flush()
        return out

    # -- pull / norms / multiply (G5, mllib:486,514,598) -------------------------------

    def pull(self, indices: Sequence[int]) -> np.ndarray:
        """Row gather — the PS ``pull`` (mllib:514,539)."""
        self._check_alive()
        return np.asarray(self.syn0[jnp.asarray(indices, jnp.int32)])

    @property
    def norms(self) -> jax.Array:
        """Per-row Euclidean norms, computed once and cached (mllib:486,600-609)."""
        self._check_alive()
        if self._norms is None:
            self._norms = jnp.linalg.norm(self._full0, axis=1)
        return self._norms[: self.vocab.size]

    def multiply(self, vector: np.ndarray) -> np.ndarray:
        """Full matrix–vector product syn0 @ v (the PS ``multiply`` powering cosine
        search, mllib:598). One sharded matvec on device."""
        self._check_alive()
        v = jnp.asarray(vector, jnp.float32)
        return np.asarray(self.syn0 @ v)

    # -- ANN index attach (serving tier, serve/ann.py) ---------------------------------

    def attach_ann(self, index) -> None:
        """Attach a built :class:`~glint_word2vec_tpu.serve.ann.IvfIndex`
        so :meth:`find_synonyms_batch` can serve the approximate arm
        (``ann=True``). The exact path stays the ground-truth oracle; the
        index is a serving-time accessory, never persisted with the model
        (it rebuilds from the matrix at load/publish time).

        Refuses an index whose row count differs from the vocabulary — with
        continual publishes the vocabulary GROWS across reloads, and a stale
        index carried over from the previous generation would silently
        mis-rank (new rows unreachable, row-id → word lookups shifted only
        by luck of the identity-prefix contract). A vocab-size change forces
        a full rebuild by construction."""
        self._check_alive()
        if index is not None:
            rows = getattr(index, "num_rows", None)
            if rows is not None and int(rows) != self.vocab.size:
                raise ValueError(
                    f"ANN index covers {rows} rows but the vocabulary has "
                    f"{self.vocab.size} words — a stale index from a "
                    f"previous publish (the vocabulary grew?); rebuild with "
                    f"serve.ann.build_ivf(np.asarray(model.syn0))")
        self._ann = index

    @property
    def ann(self):
        """The attached ANN index, or None."""
        return self._ann

    # -- synonym / analogy search (C8 mllib:554-630, C12 ml:375-420) -------------------

    def find_synonyms(
        self, query: Union[str, np.ndarray], num: int
    ) -> List[Tuple[str, float]]:
        """Top-``num`` cosine-similar words. String query excludes the query word itself
        (mllib:621-629); vector queries (for analogies) do not."""
        return self.find_synonyms_batch([query], num)[0]

    find_synonyms_array = find_synonyms  # ml:405-420 naming alias

    def find_synonyms_batch(
        self,
        queries: Sequence[Union[str, np.ndarray]],
        num: int,
        chunk: int = 128,
        ann: bool = False,
        nprobe: Optional[int] = None,
    ) -> List[List[Tuple[str, float]]]:
        """Batched :meth:`find_synonyms`: one device dispatch per ``chunk``
        queries instead of one per query. Through a thin host→device link the
        per-query round trip dominates (PERF.md §6: ~300 ms/query at V=1M rows);
        batching amortizes it — the [chunk, V] cosine matrix rides one matmul.
        Word queries exclude themselves (mllib:621-629); vector queries do not.
        ``chunk`` bounds device memory at chunk·V·4 bytes of scores.

        ``ann=True`` routes the batch through the attached IVF index
        (:meth:`attach_ann`) instead of the exact full-vocab scan — the
        serving tier's fast arm (docs/serving.md): approximate top-k over
        the ``nprobe`` nearest coarse cells, same result shape and the same
        self-exclusion semantics; scores remain true cosines (candidates
        are ranked exactly, only the candidate SET is approximate)."""
        self._check_alive()
        if ann:
            if self._ann is None:
                raise RuntimeError(
                    "ann=True but no index attached — build one with "
                    "serve.ann.build_ivf(np.asarray(model.syn0)) and "
                    "model.attach_ann(index)")
            return self._find_synonyms_batch_ann(queries, num, nprobe)
        self.norms  # materialize the cached full-row norms
        out: List[List[Tuple[str, float]]] = []
        k = min(num + 1, self.num_words)
        for lo in range(0, len(queries), chunk):
            part = queries[lo:lo + chunk]
            words: List[Optional[str]] = []
            rows = []
            for q in part:
                if isinstance(q, str):
                    idx = self.vocab.get(q)
                    if idx < 0:
                        raise KeyError(f"{q} not in vocabulary")
                    words.append(q)
                    rows.append(self._full0[idx])
                else:
                    words.append(None)
                    rows.append(jnp.asarray(q, jnp.float32))
            scores, idxs = _topk_dispatch(
                self._full0, self._norms, jnp.stack(rows), k, self.num_words)
            for word, srow, irow in zip(words, np.asarray(scores),
                                        np.asarray(idxs)):
                res: List[Tuple[str, float]] = []
                for i, s in zip(irow, srow):
                    w = self.vocab.words[int(i)]
                    if w == word:
                        continue
                    res.append((w, float(s)))
                out.append(res[:num])
        return out

    def _find_synonyms_batch_ann(
        self, queries: Sequence[Union[str, np.ndarray]], num: int,
        nprobe: Optional[int] = None) -> List[List[Tuple[str, float]]]:
        """The ANN arm of :meth:`find_synonyms_batch`: host-side probe over
        the attached index. Word queries read their vector from the index's
        own normalized copy (no device gather); vector queries are
        normalized by the index (cosine is scale-invariant)."""
        index = self._ann
        words: List[Optional[str]] = []
        rows: List[np.ndarray] = []
        for q in queries:
            if isinstance(q, str):
                idx = self.vocab.get(q)
                if idx < 0:
                    raise KeyError(f"{q} not in vocabulary")
                words.append(q)
                rows.append(index.vector(idx))
            else:
                words.append(None)
                rows.append(np.asarray(q, np.float32))
        k = min(num + 1, self.num_words)
        scores, idxs = index.search(np.stack(rows), k, nprobe)
        out: List[List[Tuple[str, float]]] = []
        for word, srow, irow in zip(words, scores, idxs):
            res: List[Tuple[str, float]] = []
            for i, s in zip(irow, srow):
                if i < 0:
                    break  # fewer candidates than k in the probed cells
                w = self.vocab.words[int(i)]
                if w == word:
                    continue
                res.append((w, float(s)))
            out.append(res[:num])
        return out

    def analogy(self, a: str, b: str, c: str, num: int = 10) -> List[Tuple[str, float]]:
        """b − a + c vector arithmetic, excluding the three query words — the analogy
        pattern from the reference's integration gates (it spec:327-352)."""
        va, vb, vc = self.transform(a), self.transform(b), self.transform(c)
        res = self.find_synonyms(vb - va + vc, num + 3)
        return [(w, s) for w, s in res if w not in (a, b, c)][:num]

    # -- exports (C8 mllib:638-662) ----------------------------------------------------

    def get_vectors(self) -> Dict[str, np.ndarray]:
        """word → vector for the whole vocabulary (mllib:638-649; mind the reference's
        caveat that this pulls everything to the client, mllib:635-637)."""
        self._check_alive()
        mat = np.asarray(self.syn0)
        return {w: mat[i] for i, w in enumerate(self.vocab.words)}

    def iter_vectors(self, batch_size: int = 10_000
                     ) -> Iterator[Tuple[str, np.ndarray]]:
        """Streaming variant of get_vectors — the analog of the ML layer's distributed
        per-partition pulls (ml:342-364) for vocabularies too large for one dict."""
        self._check_alive()
        for start in range(0, self.num_words, batch_size):
            stop = min(start + batch_size, self.num_words)
            block = np.asarray(self.syn0[start:stop])
            for i in range(stop - start):
                yield self.vocab.words[start + i], block[i]

    def to_local(self) -> Tuple[List[str], np.ndarray]:
        """Dense host-side export (words, matrix) — the ``toLocal`` analog
        (mllib:651-662) without the Spark model wrapper. For the ecosystem
        hand-off the reference's Spark ``Word2VecModel`` provided (usable by
        downstream tooling), see :meth:`export_word2vec`."""
        self._check_alive()
        return list(self.vocab.words), np.asarray(self.syn0)

    def export_word2vec(self, path: str, binary: bool = False,
                        batch_size: int = 65536,
                        io_workers: Optional[int] = None) -> None:
        """Write the classic word2vec vectors file — the ecosystem interop the
        reference's ``toLocal`` delivers by producing a stock Spark model
        (mllib:651-662): gensim ``KeyedVectors.load_word2vec_format``, fastText
        tooling, and the original word2vec.c distance tools all read this.

        Format (word2vec.c's writer): header line ``"<vocab> <dim>\\n"``; then per
        word, ``word`` + ``' '`` + (text: space-joined decimals + ``'\\n'``;
        binary: dim little-endian float32s followed by ``'\\n'``). Streams in row
        blocks — no full-matrix host copy beyond the in-flight blocks.

        ``io_workers`` (default ``config.io_workers``) runs the byte
        formatting of ~4k-row sub-chunks on a thread pool overlapped with the
        serial in-order file write (pipeline.ordered_pool_map) — small jobs
        keep the in-flight memory bounded (large whole-block jobs measurably
        REGRESSED under allocator churn, hostbench). Device fetches stay on
        the calling thread, and the bytes written are identical at any worker
        count."""
        self._check_alive()
        import io

        from glint_word2vec_tpu.data.pipeline import ordered_pool_map
        if io_workers is None:
            io_workers = getattr(self.config, "io_workers", 1)
        D = int(self.syn0.shape[1])
        sub = max(1, min(batch_size, 4096))

        def jobs():
            for start in range(0, self.num_words, batch_size):
                stop = min(start + batch_size, self.num_words)
                block = np.asarray(self.syn0[start:stop], np.float32)
                for lo in range(start, stop, sub):
                    hi = min(lo + sub, stop)
                    yield lo, block[lo - start:hi - start]

        words = self.vocab.words

        def format_chunk(job) -> bytes:
            lo, rows = job
            buf = io.BytesIO()
            if binary:
                raw = rows.astype("<f4")
                for i in range(rows.shape[0]):
                    buf.write(words[lo + i].encode())
                    buf.write(b" ")
                    buf.write(raw[i].tobytes())
                    buf.write(b"\n")
            else:
                for i in range(rows.shape[0]):
                    vec = " ".join(repr(float(x)) for x in rows[i])
                    buf.write(f"{words[lo + i]} {vec}\n".encode())
            return buf.getvalue()

        with open(path, "wb") as f:
            f.write(f"{self.num_words} {D}\n".encode())
            for data in ordered_pool_map(format_chunk, jobs(), io_workers):
                f.write(data)

    # -- persistence (G9/C13) ----------------------------------------------------------

    def save(self, path: str) -> None:
        self._check_alive()
        ckpt.save_model(
            path, self.vocab.words, self.vocab.counts,
            np.asarray(self.syn0),
            np.asarray(self.syn1) if self.syn1 is not None else None,
            self.config, self.train_state)

    @classmethod
    def load(cls, path: str, plan: Optional[MeshPlan] = None,
             verify: bool = True,
             io_workers: Optional[int] = None) -> "Word2VecModel":
        """Load a saved model; ``plan`` retargets the arrays onto a different mesh — the
        analog of the reference's load-onto-different-PS-topology overloads
        (mllib:696-725, ml:584-599).

        With a ``plan``, a row-shards checkpoint streams each device's row block
        straight from the mmap'd shard files onto the target mesh
        (:func:`..train.checkpoint.load_params_into_plan`) — the full [V, D] matrices
        never materialize on any single host, so model ops (transform/find_synonyms)
        work at vocabularies that exceed one host's memory.

        ``verify=False`` skips the digest (re-)hash on both layouts — for
        callers that just verified (e.g. :meth:`load_latest`), or for skipping
        the extra sequential shard read on a trusted very large row-shards
        checkpoint.

        ``io_workers``: thread fan-out for digest hashing and shard reads on
        THIS host (default: the worker count recorded in the checkpoint's
        config — pass your own on hosts that differ from the writer's)."""
        header = None
        if plan is not None:
            header = ckpt.load_model_header(path)
            if header["layout"] == "row-shards":
                vocab = Vocabulary.from_words_and_counts(
                    header["words"], header["counts"])
                Vp = pad_vocab_for_sharding(vocab.size, plan.num_model)
                syn0, syn1 = ckpt.load_params_into_plan(
                    path, plan, Vp, header["vector_size"], verify=verify,
                    io_workers=io_workers)
                return cls(vocab=vocab, syn0=syn0, syn1=syn1,
                           config=header["config"], plan=plan,
                           train_state=header["train_state"])
        data = ckpt.load_model(path, header=header, verify=verify,
                               io_workers=io_workers)
        vocab = Vocabulary.from_words_and_counts(data["words"], data["counts"])
        return cls(
            vocab=vocab,
            syn0=jnp.asarray(data["syn0"]),
            syn1=jnp.asarray(data["syn1"]) if data["syn1"] is not None else None,
            config=data["config"],
            plan=plan,
            train_state=data["train_state"],
        )

    @classmethod
    def load_latest(cls, directory: str, plan: Optional[MeshPlan] = None,
                    reclaim: bool = False) -> "Word2VecModel":
        """Serving-side recovery load: scan ``directory`` and load the newest
        checkpoint whose content passes digest verification
        (:func:`..train.checkpoint.load_latest_valid`). Non-destructive by
        default (``reclaim=False``): safe to call while a trainer may still be
        saving into the directory — debris is left alone, and a torn-swap
        predecessor is loaded from its ``*.old-*`` path without renaming.
        Pass ``reclaim=True`` only when the writer is known dead (true crash
        recovery) to also clean the directory up. The scan already verified
        the winner's digests, so the load itself skips the re-hash."""
        return cls.load(ckpt.load_latest_valid(directory, reclaim=reclaim),
                        plan=plan, verify=False)

    def stop(self) -> None:
        """Release device buffers — the analog of the reference's PS teardown
        (client.terminateOnSpark + matrix.destroy, mllib:655-667). Idempotent."""
        if self._stopped:
            return
        for arr in (self._full0, self._full1, self._norms):
            if arr is not None:
                try:
                    arr.delete()
                except Exception:
                    pass
        self._full0 = None  # type: ignore[assignment]
        self._full1 = None
        self._norms = None
        self._ann = None
        self._stopped = True


from functools import partial


@partial(jax.jit, static_argnames=("valid_rows",))
def _cosine_batch(syn0: jax.Array, norms: jax.Array, queries: jax.Array,
                  valid_rows: int) -> jax.Array:
    """The [Q, V] masked cosine matrix of :func:`_cosine_topk_batch` without
    the top-k — the shared front half of the device and CPU top-k routes."""
    qn = jnp.linalg.norm(queries, axis=1, keepdims=True)
    q = queries / jnp.maximum(qn, 1e-12)
    dots = q @ syn0.T                                          # [Q, V]
    cos = jnp.where(norms[None, :] > 0,
                    dots / jnp.maximum(norms[None, :], 1e-12), 0.0)
    return jnp.where(jnp.arange(cos.shape[1])[None, :] < valid_rows,
                     cos, -jnp.inf)


@partial(jax.jit, static_argnames=("k", "valid_rows"))
def _cosine_topk_batch(syn0: jax.Array, norms: jax.Array, queries: jax.Array,
                       k: int, valid_rows: int) -> Tuple[jax.Array, jax.Array]:
    """cosine(rows, q) top-k over a [Q, D] query matrix in ONE dispatch:
    normalize queries (snrm2/sscal analog, mllib:589-596), the [Q, V] cosine
    matrix as a single MXU matmul (mllib:598's matvec, batched), divide by row
    norms with zero-norm → 0 (mllib:601-609), batched device top-k instead of
    the client-side BoundedPriorityQueue scan (mllib:611-619). Rows past
    valid_rows are sharding padding, excluded outright."""
    return jax.lax.top_k(
        _cosine_batch(syn0, norms, queries, valid_rows), k)


# CPU route tiling: queries are sub-chunked so the fetched [q, V] score
# block stays under ~512 MB of host RAM
_CPU_TOPK_SCORE_BYTES = 512 << 20


def _cpu_topk_row(row: np.ndarray, k: int) -> Tuple[np.ndarray, np.ndarray]:
    """Top-k of one score row: O(V) selection + a k-element sort, scratch
    bounded to one float copy of the row (``np.partition``). Tie handling is
    EXACT to ``lax.top_k``: everything strictly above the k-th value is in,
    and entries EQUAL to it fill the remaining slots in ascending index order
    (a plain ``argpartition`` leaves that boundary choice arbitrary — it
    returned different neighbors than the device route on tied scores)."""
    V = row.shape[0]
    if k >= V:
        cand = np.arange(V)
    else:
        kth = np.partition(row, V - k)[V - k]        # the k-th largest value
        above = np.flatnonzero(row > kth)
        need = k - above.shape[0]
        ties = np.flatnonzero(row == kth)[:need]     # lowest tied indices win
        cand = np.concatenate([above, ties])
    sc = row[cand]
    order = np.lexsort((cand, -sc))
    return sc[order], cand[order]


def _topk_dispatch(syn0: jax.Array, norms: jax.Array, queries: jax.Array,
                   k: int, valid_rows: int) -> Tuple[np.ndarray, np.ndarray]:
    """Route the cosine top-k (PERF.md §10). Default everywhere:
    ``lax.top_k`` in the same dispatch as the matmul. The host route —
    fetch scores in ~512 MB sub-chunks, rank with chunked ``np.argpartition``
    (:func:`_cpu_topk_row`), bit-identical results tie-order included
    (tested) — exists for CPU backends whose XLA top-k lowers to a per-row
    SORT (round 5 measured >30 min for 64 queries at V=10M, PERF.md §6, which
    bricked CPU serving at scale). That pathology did NOT reproduce under the
    current jaxlib — re-measured at 6.4 s for the same shape, beating the
    host route 2-3x at every shape tried (§10) — so the host route is opt-in:
    set ``GLINT_CPU_TOPK=argpartition`` on toolchains that still exhibit the
    sort lowering."""
    import os
    if (jax.default_backend() != "cpu"
            or os.environ.get("GLINT_CPU_TOPK") != "argpartition"):
        s, i = _cosine_topk_batch(syn0, norms, queries, k, valid_rows)
        return np.asarray(s), np.asarray(i)
    Q, V = queries.shape[0], syn0.shape[0]
    qsub = max(1, min(Q, _CPU_TOPK_SCORE_BYTES // max(V * 4, 1)))
    scores = np.empty((Q, k), np.float32)
    idxs = np.empty((Q, k), np.int64)
    for lo in range(0, Q, qsub):
        cos = np.asarray(_cosine_batch(
            syn0, norms, queries[lo:lo + qsub], valid_rows))
        for r in range(cos.shape[0]):
            scores[lo + r], idxs[lo + r] = _cpu_topk_row(cos[r], k)
    return scores, idxs
