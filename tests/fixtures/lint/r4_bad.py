"""R4 bad: prefix accumulation with no static dtype evidence — fed bf16
params this cancels the interval it computes."""
import jax.numpy as jnp


def context_sums(rows):
    prefix = jnp.cumsum(rows, axis=0)
    return prefix[4:] - prefix[:-4]
