"""Performance frontier sweeps on the live chip (round-3 workstream).

Usage: python tools/sweep.py pallas|xla|all

Sweeps, with the same slope harness AND the same Zipf-distributed batch indices as
bench.py (the harness is imported from it, so the two cannot drift):
- pallas: kernel tile x nbuf grid at B=8192 (tile was fixed at 512 / nbuf at 8 so far)
- xla: batch curve x compute/param dtype x negative-pool size for the shared-pool step

Round-3 measured conclusions (recorded in bench.py's docstring and
ops/pallas/sgns_kernel.py): pallas flat across the whole grid (issue-overhead bound,
demoted); bf16-stored params +30-40%; batch curve peaks at B=65536; pool=1024 trades
~15% pairs/s for 10x MFU.
"""

import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
_root = os.path.dirname(_here)
sys.path.insert(0, _here)                      # tools/ (microbench)
sys.path.insert(0, _root)                      # repo root (glint_word2vec_tpu, bench)

from bench import bench_step, log, zipf_counts  # noqa: E402


def main():
    import jax
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    log(f"device: {jax.devices()[0]}")
    counts = zipf_counts(200_000)
    if which in ("pallas", "all"):
        from functools import partial

        from glint_word2vec_tpu.ops.pallas import sgns_kernel
        for tile in (256, 512):
            for nbuf in (8, 32):
                if nbuf > tile:
                    continue
                orig = sgns_kernel.make_pallas_sgns_step
                sgns_kernel.make_pallas_sgns_step = partial(
                    orig, tile=tile, nbuf=nbuf)
                try:
                    log(f"[tile={tile} nbuf={nbuf}]")
                    bench_step(counts, 8192, use_pallas=True)
                except Exception as e:
                    log(f"pallas tile={tile} nbuf={nbuf} FAILED: "
                        f"{type(e).__name__}: {e}")
                finally:
                    sgns_kernel.make_pallas_sgns_step = orig
    if which in ("xla", "all"):
        for b in (32768, 65536, 131072):
            for pdt in ("float32", "bfloat16"):
                for cdt in ("float32", "bfloat16"):
                    bench_step(counts, b, dtype=cdt, param_dtype=pdt)
        for pool in (256, 1024):
            bench_step(counts, 32768, pool=pool)


if __name__ == "__main__":
    main()
