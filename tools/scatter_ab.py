"""Focused A/B: plain scatter-add vs sorted+flagged scatter-add, with repeats.

tools/rowbench.py showed up to 7x run-to-run variance on single slope measurements
through the remote-TPU tunnel. This tool interleaves R slope repeats of each variant
and prints per-variant median [min..max], which is the only defensible basis for a
design decision. Variants:

    plain          — mat.at[zipf_idx].add(upd)
    sorted         — same indices pre-sorted, no XLA flag
    sorted+flag    — pre-sorted + indices_are_sorted=True
    sorted+permute — pre-sorted + flag, plus the [B,D] update-row permute the real
                     step needs for its second scatter (upd[order])

Run: python tools/scatter_ab.py [--dtype f32|bf16] [--repeats 5]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

V, D, B, K = 200_000, 384, 65_536, 16


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dtype", default="f32", choices=["f32", "bf16"])
    ap.add_argument("--repeats", type=int, default=5)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from microbench import time_chunked

    dt = jnp.float32 if args.dtype == "f32" else jnp.bfloat16
    itemsize = 4 if args.dtype == "f32" else 2
    print(f"device: {jax.devices()[0]}  dtype={args.dtype}", file=sys.stderr)

    rng = np.random.default_rng(0)
    c = np.maximum(1e9 / (np.arange(V) + 10.0) ** 1.07, 5.0)
    p = c / c.sum()
    mat0 = jnp.asarray(rng.normal(0, 0.05, (V, D)), dt)
    upd0 = jnp.asarray(rng.normal(0, 1e-4, (B, D)), dt)

    zipf = np.stack([np.random.default_rng(100 + j).choice(V, size=B, p=p)
                     for j in range(K)])
    order = np.argsort(zipf, axis=-1)
    zipf_sorted = np.take_along_axis(zipf, order, axis=-1)
    idx_plain = jnp.asarray(zipf, jnp.int32)
    idx_sorted = jnp.asarray(zipf_sorted, jnp.int32)
    idx_order = jnp.asarray(order, jnp.int32)

    def make(fn):
        f = jax.jit(fn, donate_argnums=(0,))

        def run():
            return time_chunked(
                f, lambda: mat0 + 0, lambda i: (upd0, idx_plain, idx_sorted,
                                                idx_order),
                n_lo=2, n_hi=8, fetch=lambda cc, o: o)
        return run

    def plain(m, u, ip, isrt, iord):
        def body(cc, ix):
            return cc.at[ix].add(u), ()
        out, _ = jax.lax.scan(body, m, ip)
        return out, out[0, 0]

    def sorted_noflag(m, u, ip, isrt, iord):
        def body(cc, ix):
            return cc.at[ix].add(u), ()
        out, _ = jax.lax.scan(body, m, isrt)
        return out, out[0, 0]

    def sorted_flag(m, u, ip, isrt, iord):
        def body(cc, ix):
            return cc.at[ix].add(u, indices_are_sorted=True), ()
        out, _ = jax.lax.scan(body, m, isrt)
        return out, out[0, 0]

    def sorted_flag_permute(m, u, ip, isrt, iord):
        def body(cc, inp):
            ix, od = inp
            return cc.at[ix].add(u[od], indices_are_sorted=True), ()
        out, _ = jax.lax.scan(body, m, (isrt, iord))
        return out, out[0, 0]

    variants = {
        "plain": make(plain),
        "sorted": make(sorted_noflag),
        "sorted+flag": make(sorted_flag),
        "sorted+flag+permute": make(sorted_flag_permute),
    }
    times = {k: [] for k in variants}
    for r in range(args.repeats):
        for name, run in variants.items():
            spc = run()
            times[name].append(spc / K * 1e3)
    print(f"\nB={B} rows x D={D} {args.dtype} into V={V} "
          f"({args.repeats} interleaved slope repeats):", file=sys.stderr)
    for name, ts in times.items():
        med = float(np.median(ts))
        gbs = 2 * B * D * itemsize / (med / 1e3) / 1e9
        print(f"  {name:22s} median {med:7.3f} ms  [{min(ts):7.3f} .. "
              f"{max(ts):7.3f}]  ~{gbs:6.1f} GB/s", file=sys.stderr)


if __name__ == "__main__":
    main()
