"""Interleaved GSPMD-vs-shard_map A/B across mesh shapes (the scale-out step).

For each (data, model) mesh shape over the forced 8-device CPU mesh —
1x8, 2x4, 4x2, 8x1 — this builds TWO production Trainers that differ ONLY in
``config.step_lowering`` ("gspmd" = compiler-scheduled collectives,
"shard_map" = the explicit owner-local schedule of ops/sgns_shard.py), feeds
both the identical packed-pair chunk, and reports:

- step time per lowering (interleaved A/B medians, the PERF.md §3
  methodology: variants alternate within one process so allocator drift and
  co-tenant noise hit both alike; two-point-slope timing via
  tools/microbench.py);
- numeric agreement: max |Δ| between the two lowerings' params after one
  identical chunk from identical initial params (they are NOT bit-identical —
  different FP reduction orders — but must agree to f32 reassociation noise;
  the f64 ~1e-12 equivalence lives in tests/test_shard_map_step.py).

On this CPU mesh the TIME column is indicative only (CPU collective/scatter
economics are nothing like ICI + the TPU scatter emitter); the collective-
bytes evidence is tools/collectives.py, and the first hardware session should
re-run this tool on a real pod slice — the harness is the deliverable. The
agreement column is meaningful everywhere.

Run:  python tools/shard_ab.py [--smoke] [--b 16384] [--v 100000] [--d 384]
      [--pool 512] [--k 4] [--repeats 3]
Prints a table on stderr and exactly ONE JSON line on stdout.
``--smoke`` (tiny geometry, 1 repeat) is wired into tier-1
(tests/test_shard_map_step.py) so the harness cannot rot.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# self-provision the virtual multi-device CPU mesh BEFORE jax initializes
if __name__ == "__main__":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MESHES = [(1, 8), (2, 4), (4, 2), (8, 1)]


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_trainer(lowering: str, shape, vocab, args, sync_every: int = 1,
                 steps_per_dispatch: int = 0):
    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.parallel.mesh import make_mesh
    from glint_word2vec_tpu.train.trainer import Trainer

    cfg = Word2VecConfig(
        vector_size=args.d, min_count=1, pairs_per_batch=args.b,
        negatives=5, negative_pool=args.pool,
        steps_per_dispatch=steps_per_dispatch or args.k,
        window=5, seed=7, step_lowering=lowering, sync_every=sync_every)
    return Trainer(cfg, vocab, plan=make_mesh(*shape))


def ab_one_mesh(shape, vocab, args) -> dict:
    import jax
    import jax.numpy as jnp
    from microbench import time_chunked

    from glint_word2vec_tpu.ops.sgns import EmbeddingPair

    K, B = args.k, args.b
    res = {"mesh": list(shape)}
    trainers = {low: make_trainer(low, shape, vocab, args)
                for low in ("gspmd", "shard_map")}
    t0 = trainers["gspmd"]
    # identical initial params on both (same seed/geometry -> same init);
    # host copies survive donation so every timing run re-places fresh params
    syn0_h = np.asarray(t0.params.syn0)
    syn1_h = np.asarray(t0.params.syn1)
    assert np.array_equal(syn0_h, np.asarray(trainers["shard_map"].params.syn0))

    n_sets = 4
    feeds = []
    for i in range(n_sets):
        r = np.random.default_rng(500 + i)
        feeds.append(jax.device_put(
            r.integers(0, vocab.size, (K, 2, B)).astype(t0._pair_dtype),
            t0.plan.pairs_stacked))
    meta = np.stack([np.full((K,), 0.025, np.float32),
                     np.full((K,), B, np.float32)])

    # numeric agreement: one identical chunk from identical params
    outs = {}
    for low, tr in trainers.items():
        p = EmbeddingPair(jax.device_put(syn0_h, tr.plan.embedding),
                          jax.device_put(syn1_h, tr.plan.embedding))
        new_p, _ = tr._step_fn(p, {"pairs": feeds[0]}, meta, np.int32(1),
                               tr._table_prob, tr._table_alias)
        outs[low] = jax.tree.map(np.asarray, new_p)
    diff = max(
        float(np.max(np.abs(outs["gspmd"].syn0.astype(np.float64)
                            - outs["shard_map"].syn0.astype(np.float64)))),
        float(np.max(np.abs(outs["gspmd"].syn1.astype(np.float64)
                            - outs["shard_map"].syn1.astype(np.float64)))))
    res["max_abs_diff"] = diff
    # scale reference so the smoke assertion is relative, not absolute
    res["param_abs_max"] = float(np.max(np.abs(outs["gspmd"].syn0)))

    times = {"gspmd": [], "shard_map": []}
    for rep in range(args.repeats):
        for low in ("gspmd", "shard_map"):      # interleaved
            tr = trainers[low]

            def run(p, feed, base, tr=tr):
                return tr._step_fn(p, {"pairs": feed}, meta, base,
                                   tr._table_prob, tr._table_alias)

            make_carry = lambda tr=tr: EmbeddingPair(       # noqa: E731
                jax.device_put(syn0_h, tr.plan.embedding),
                jax.device_put(syn1_h, tr.plan.embedding))
            args_for_iter = lambda i: (feeds[i % n_sets],   # noqa: E731
                                       np.int32(100 + i))
            fetch = lambda c, out: c.syn0[0, 0].astype(jnp.float32)  # noqa: E731
            try:
                spc = time_chunked(run, make_carry=make_carry,
                                   args_for_iter=args_for_iter,
                                   n_lo=2, n_hi=6, fetch=fetch)
            except RuntimeError:
                # loaded/noisy host: the two-point slope can go non-positive
                # on sub-100ms chunks. Fall back to direct chained timing —
                # honest on CPU (synchronous dispatch; no tunnel to lie
                # through), which is the only backend this tool times anyway
                import time as _time
                c = make_carry()
                c, out = run(c, *args_for_iter(0))          # warm
                float(fetch(c, out))
                t0 = _time.perf_counter()
                n = 4
                for i in range(n):
                    c, out = run(c, *args_for_iter(i))
                float(fetch(c, out))
                spc = (_time.perf_counter() - t0) / n
            times[low].append(spc / K * 1e3)
    for low in ("gspmd", "shard_map"):
        res[f"{low}_ms"] = float(np.median(times[low]))
    res["speedup_shard_map"] = res["gspmd_ms"] / res["shard_map_ms"]
    log(f"mesh {shape[0]}x{shape[1]}: gspmd {res['gspmd_ms']:8.2f} ms/step  "
        f"shard_map {res['shard_map_ms']:8.2f} ms/step  "
        f"(x{res['speedup_shard_map']:.2f})  max|dparam| {diff:.2e}")
    return res


def localsgd_ab_one_mesh(shape, vocab, args) -> dict:
    """sync_every interleaved arm (docs/sharding.md §Local-SGD): same mesh,
    same packed-pair chunk, shard_map lowering throughout; arms differ ONLY in
    ``config.sync_every`` ∈ args.sync_set. Every arm runs with
    steps_per_dispatch = max(sync_set) so chunk geometry (and therefore the
    feed, the metrics shape, and the per-step normalization) is identical —
    only the merge cadence moves. Reports per-arm ms/step plus the one-chunk
    params divergence of each local arm vs the sync_every=1 arm (the staleness
    column; quality impact is gated by tools/eval_quality.py --localsgd-ab)."""
    import jax
    import jax.numpy as jnp
    from microbench import time_chunked

    from glint_word2vec_tpu.ops.sgns import EmbeddingPair

    ks = sorted(set(args.sync_set))
    K, B = max(ks), args.b
    res = {"mesh": list(shape), "steps_per_dispatch": K, "arms": {}}
    trainers = {k: make_trainer("shard_map", shape, vocab, args,
                                sync_every=k, steps_per_dispatch=K)
                for k in ks}
    t0 = trainers[ks[0]]
    syn0_h = np.asarray(t0.params.syn0)
    syn1_h = np.asarray(t0.params.syn1)

    n_sets = 4
    feeds = []
    for i in range(n_sets):
        r = np.random.default_rng(700 + i)
        feeds.append(jax.device_put(
            r.integers(0, vocab.size, (K, 2, B)).astype(t0._pair_dtype),
            t0.plan.pairs_stacked))
    meta = np.stack([np.full((K,), 0.025, np.float32),
                     np.full((K,), B, np.float32)])

    # one-chunk divergence of each local arm vs the synchronous arm — the
    # cheap staleness indicator (at nd=1 this is exactly 0 by construction)
    outs = {}
    for k, tr in trainers.items():
        p = EmbeddingPair(jax.device_put(syn0_h, tr.plan.embedding),
                          jax.device_put(syn1_h, tr.plan.embedding))
        new_p, _ = tr._step_fn(p, {"pairs": feeds[0]}, meta, np.int32(1),
                               tr._table_prob, tr._table_alias)
        outs[k] = jax.tree.map(np.asarray, new_p)

    times = {k: [] for k in ks}
    for rep in range(args.repeats):
        for k in ks:                                # interleaved
            tr = trainers[k]

            def run_step(p, feed, base, tr=tr):
                return tr._step_fn(p, {"pairs": feed}, meta, base,
                                   tr._table_prob, tr._table_alias)

            make_carry = lambda tr=tr: EmbeddingPair(       # noqa: E731
                jax.device_put(syn0_h, tr.plan.embedding),
                jax.device_put(syn1_h, tr.plan.embedding))
            args_for_iter = lambda i: (feeds[i % n_sets],   # noqa: E731
                                       np.int32(100 + i))
            fetch = lambda c, out: c.syn0[0, 0].astype(jnp.float32)  # noqa: E731
            try:
                spc = time_chunked(run_step, make_carry=make_carry,
                                   args_for_iter=args_for_iter,
                                   n_lo=2, n_hi=6, fetch=fetch)
            except RuntimeError:
                import time as _time
                c = make_carry()
                c, out = run_step(c, *args_for_iter(0))     # warm
                float(fetch(c, out))
                t1 = _time.perf_counter()
                n = 4
                for i in range(n):
                    c, out = run_step(c, *args_for_iter(i))
                float(fetch(c, out))
                spc = (_time.perf_counter() - t1) / n
            times[k].append(spc / K * 1e3)
    base_ms = float(np.median(times[ks[0]]))
    for k in ks:
        ms = float(np.median(times[k]))
        diff = max(
            float(np.max(np.abs(outs[ks[0]].syn0.astype(np.float64)
                                - outs[k].syn0.astype(np.float64)))),
            float(np.max(np.abs(outs[ks[0]].syn1.astype(np.float64)
                                - outs[k].syn1.astype(np.float64)))))
        res["arms"][str(k)] = {"sync_every": k, "ms_per_step": ms,
                               "speedup_vs_sync": base_ms / ms,
                               "max_abs_diff_vs_sync": diff}
        log(f"mesh {shape[0]}x{shape[1]} localsgd k={k:<3d} {ms:8.2f} ms/step"
            f"  (x{base_ms / ms:.2f} vs sync)  max|dparam vs sync| {diff:.2e}")
    return res


def run(argv=None) -> dict:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny geometry, 1 repeat (the tier-1 wiring)")
    ap.add_argument("--b", type=int, default=16384)
    ap.add_argument("--v", type=int, default=100_000)
    ap.add_argument("--d", type=int, default=384)
    ap.add_argument("--pool", type=int, default=512)
    ap.add_argument("--k", type=int, default=4)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--sync-set", type=str, default="1,4,16",
                    help="comma list of sync_every arms for the local-SGD A/B")
    args = ap.parse_args(argv)
    if args.smoke:
        args.b, args.v, args.d, args.pool = 1024, 8192, 64, 128
        args.k, args.repeats = 2, 1
        args.sync_set = "1,2"
    args.sync_set = [int(s) for s in args.sync_set.split(",") if s.strip()]

    import jax
    if len(jax.devices()) < 8:
        raise SystemExit(
            f"need 8 devices (have {len(jax.devices())}); run as a script so "
            "the CPU mesh self-provisions")
    if (os.cpu_count() or 1) < len(jax.devices()):
        log(f"WARNING: host has {os.cpu_count()} cores for a "
            f"{len(jax.devices())}-device virtual mesh — device steps are "
            "contended; treat ms/step as relative, not absolute")
    log(f"device: {jax.devices()[0]}  B={args.b} V={args.v} D={args.d} "
        f"pool={args.pool} K={args.k} repeats={args.repeats}")

    from glint_word2vec_tpu.data.vocab import Vocabulary
    counts = np.maximum(1e9 / (np.arange(args.v) + 10.0) ** 1.07, 5.0)
    vocab = Vocabulary.from_words_and_counts(
        [f"w{i}" for i in range(args.v)], counts.astype(np.int64))

    result = {
        "geometry": {"b": args.b, "v": args.v, "d": args.d,
                     "pool": args.pool, "k": args.k},
        "backend": jax.devices()[0].platform,
        "meshes": [ab_one_mesh(shape, vocab, args) for shape in MESHES],
    }
    # local-SGD arm: only meshes with >1 data shard carry a real merge (at
    # nd=1 every sync_every is bit-identical to synchronous); smoke keeps one
    # mesh so the tier-1 wiring stays cheap
    ls_meshes = [(2, 4)] if args.smoke else [m for m in MESHES if m[0] > 1]
    result["localsgd_sync_set"] = args.sync_set
    result["localsgd_meshes"] = [
        localsgd_ab_one_mesh(shape, vocab, args) for shape in ls_meshes]
    return result


def main(argv=None) -> None:
    print(json.dumps(run(argv)))


if __name__ == "__main__":
    main()
